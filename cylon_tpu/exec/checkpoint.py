"""Durable checkpoint/resume — the recovery ladder's persistence rung.

PRs 3–4 made the pipeline survive *in-process* faults: the consensus
retry ladder re-plans at degraded configurations and the HBM ledger
spills resident state to host RAM.  What neither can cure is a fault
that poisons the PROCESS — a real XLA ``RESOURCE_EXHAUSTED`` on an
HBM-poisoning rig, a libtpu compiler crash that exhausted its pad
ladder — where the only honest remedy is a fresh process, and before
this module that meant recomputing every completed piece from zero.
Following the lineage/checkpoint recovery tradition of the
MapReduce/Spark line (PAPERS.md), this module adds the missing
*durability* rung:

1. **Per-rank checkpoint directories** (``CYLON_TPU_CKPT_DIR``): each
   pipelined stage (one ``pipelined_join`` invocation — deterministic
   stage ids replay identically in a fresh process) owns
   ``<dir>/rank<r>/stage<k>-<label>/``.  Completed-piece state — the
   range loop's per-piece outputs, or the GroupBySink's per-piece
   partial aggregates — is serialized through the SAME host-page
   transport the PR 4 spill tier uses (``utils.host.host_shard_blocks``
   out, :func:`cylon_tpu.exec.memory.put_blocks` back in), so a
   restored piece is byte-identical to the resident array it was
   pulled from and multi-controller checkpoints stay collective-free
   (each process writes/reads only its addressable shards).  Every
   page carries a content hash (sha256); the piece meta sidecar is
   hashed into the manifest entry.

2. **Two-phase rank-coherent manifest commit**: after a piece's pages
   land, the updated manifest is STAGED (atomic rank-local write), then
   every rank votes :class:`~cylon_tpu.status.Code.CkptCommit` with its
   staged epoch over the PR 3 pmax wire
   (:func:`cylon_tpu.exec.recovery.ckpt_commit_consensus`) and only
   then renames staged → ``MANIFEST.json`` — so a manifest is committed
   on every rank at the IDENTICAL epoch or on none, and a crash between
   stage and commit leaves only staged files, which resume ignores.

3. **Resume** (``CYLON_TPU_RESUME=1``): a fresh process replaying the
   same workload reaches each stage with the same plan token (a hash of
   the stage's static plan — operator, key names, chunk count, piece
   capacities, per-range row counts); committed pieces whose token
   matches are loaded bit-identically and the range loop fast-forwards
   past them (``resume_fast_forwarded_pieces`` in the bench detail).  A
   corrupt or hash-mismatched page raises a typed
   :class:`~cylon_tpu.status.CheckpointCorruptError` and the stage
   falls back to recomputing its remaining pieces — corruption degrades
   resume to recompute, never to a wrong answer.

4. **The FINAL ladder rung** (:mod:`cylon_tpu.exec.recovery`): an
   unrecoverable ``DeviceOOMError`` or exhausted compiler-crash ladder
   flushes the session (:func:`flush_for_abort`) and raises a typed
   :class:`~cylon_tpu.status.ResumableAbort` carrying the resume token
   instead of a bare abort.

5. **Elastic resume** (docs/robustness.md "Elastic resume & preemption
   grace"): stages carry a world-invariant BASE token next to the full
   layout token; a resume whose checkpoint was committed at a DIFFERENT
   topology (world size or process layout) re-shards complete stages —
   foreign rank dirs' pages sha-verified, shard prefixes stitched into
   global row order, re-blocked through ``relational/repart``'s
   order-preserving split, re-voted and re-committed over the NEW mesh
   (:meth:`Stage.load_foreign_pieces` / :meth:`Stage.begin_rewrite`) —
   and counts what it could not adopt (``resume_world_mismatch``)
   instead of silently recomputing.  **Preemption grace**
   (:mod:`cylon_tpu.exec.preempt`): SIGTERM with
   ``CYLON_TPU_PREEMPT_GRACE_S`` armed drains at the next checkpoint
   boundary (:func:`drain_requested` → :func:`drain_abort`, the drain
   vote rank-coherent) so a spot scale-down is a planned
   ``ResumableAbort``, not a mid-piece crash.

Happy path contract: with ``CYLON_TPU_CKPT_DIR`` unset this module's
entry points are a couple of env reads — ZERO filesystem writes, zero
extra collectives, no measurable cost on the pipelined hot path.  In a
single-controller session even an armed checkpoint adds no collectives
(the commit consensus short-circuits locally).

Fault injection (``scripts/chaos_soak.py``, docs/robustness.md): sites
``ckpt.write``/``ckpt.load``; kind ``corrupt`` flips page bytes after
hashing (write) or simulates a failed hash check (load); ``kill``
SIGKILLs the process mid-write — the chaos-soak harness's hard-crash
primitive.

Lint rule TS107: this module is the ONE sanctioned place that writes
checkpoint artifacts — a direct ``open``/``np.save``/pickle of
``CYLON_TPU_CKPT_DIR`` paths in ``relational/`` or ``exec/pipeline.py``
bypasses the hash/manifest protocol and is a finding.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
import re
import time

import numpy as np

from ..status import (CheckpointCorruptError, DataIntegrityError,
                      InvalidError, ResumableAbort)
from ..utils import timing


# ---------------------------------------------------------------------------
# switches (read dynamically: tests and the chaos harness flip env vars)
# ---------------------------------------------------------------------------

def ckpt_dir() -> str | None:
    """The checkpoint root (``CYLON_TPU_CKPT_DIR``), or None = disabled."""
    return os.environ.get("CYLON_TPU_CKPT_DIR") or None


def enabled() -> bool:
    return ckpt_dir() is not None


def resume_requested() -> bool:
    """``CYLON_TPU_RESUME=1``: committed pieces of matching stages are
    restored instead of recomputed.  A serving session the scheduler
    preempted and REQUEUED resumes in-process the same way: its
    ``_resume_pending`` flag arms the resume for the re-granted fn run
    only (per-session stage namespaces keep the tokens collision-free),
    without flipping the process-wide env knob for co-tenants."""
    if os.environ.get("CYLON_TPU_RESUME") == "1":
        return True
    from .scheduler import current_session
    sess = current_session()
    return bool(sess is not None
                and getattr(sess, "_resume_pending", False))


# ---------------------------------------------------------------------------
# stats (bench JSON detail, alongside recovery_events / spill counters)
# ---------------------------------------------------------------------------

# counters live in the metrics registry (cylon_tpu.obs.metrics — the
# TS112 facade); this dict-like view keeps every `_STATS[k] += 1` call
# site (and tests poking the table directly) working verbatim
from ..obs import metrics as _metrics  # noqa: E402

_STATS = _metrics.group("ckpt", (
    "checkpoint_events", "bytes_checkpointed",
    "resume_fast_forwarded_pieces", "corrupt_pages",
    "resume_resharded_pieces", "resume_world_mismatch"))


def stats() -> dict:
    """Checkpoint counters for the bench JSON detail:
    ``checkpoint_events`` (committed piece checkpoints),
    ``bytes_checkpointed`` (page bytes written),
    ``resume_fast_forwarded_pieces`` (pieces restored instead of
    recomputed), ``corrupt_pages`` (hash-mismatch fallbacks),
    ``resume_resharded_pieces`` (pieces adopted across a topology
    change — always also counted as fast-forwarded) and
    ``resume_world_mismatch`` (stages whose checkpoint came from a
    DIFFERENT topology: together with ``resume_resharded_pieces`` an
    operator can tell "resharded and fast-forwarded" apart from "threw
    the checkpoint away and recomputed")."""
    return dict(_STATS)


def reset_stats() -> None:
    for k in _STATS:
        _STATS[k] = 0


def unrestore(k: int) -> None:
    """Back out ``k`` discarded restores from the fast-forward counter:
    a multiprocess resume adopts the MINIMUM restorable prefix across
    ranks (:func:`cylon_tpu.exec.recovery.ckpt_resume_consensus`), so
    pieces a rank restored beyond the agreed prefix are recomputed and
    must not count as fast-forwarded.  Backing out more than was ever
    counted is a consensus bug, not a bookkeeping nuance: the counter
    clamps at zero (a later bench read can never report a negative
    fast-forward) and a typed :class:`InvalidError` surfaces the
    over-unrestore loudly."""
    k = int(k)
    if k < 0:
        raise InvalidError(f"unrestore({k}): negative back-out")
    have = _STATS["resume_fast_forwarded_pieces"]
    if k > have:
        _STATS["resume_fast_forwarded_pieces"] = 0
        raise InvalidError(
            f"unrestore({k}) exceeds the {have} restores counted — the "
            "resume consensus agreed on more discards than this rank "
            "ever restored (counter clamped at zero)")
    _STATS["resume_fast_forwarded_pieces"] = have - k


def note_reshard(k: int) -> None:
    """Count ``k`` pieces adopted across a topology change: they fast-
    forwarded (the resumed loop skips their work) AND they resharded
    (their host pages were stitched and re-blocked onto the new mesh) —
    both counters move so the bench detail distinguishes an elastic
    adoption from a plain same-world fast-forward."""
    k = int(k)
    _STATS["resume_fast_forwarded_pieces"] += k
    _STATS["resume_resharded_pieces"] += k
    for _ in range(k):
        timing.bump("ckpt.piece_resharded")


# ---------------------------------------------------------------------------
# stage identity
# ---------------------------------------------------------------------------

#: per-(serving-session) stage sequences, key None = outside a
#: scheduler: checkpoint-enabled stages replay in the same PER-SESSION
#: order in a fresh process (each session's workload is deterministic,
#: and the serving scheduler re-creates sessions under the same names),
#: so (session, counter) IS the cross-process stage identity even when
#: concurrent sessions interleave their stage openings in a different
#: order — the plan token guards against the workload having actually
#: changed
_STAGE_SEQ: dict = {}

#: stage directories opened this process (for the resume-token file)
_OPEN_DIRS: list[str] = []


def reset_stages() -> None:
    """Restart the stage sequences (tests replaying a workload in-process
    to exercise the resume path without a fresh interpreter)."""
    _STAGE_SEQ.clear()
    _OPEN_DIRS.clear()


def reset_session_stages(sid: str) -> None:
    """Restart ONE serving session's stage sequence — the scheduler's
    preemptive-requeue path: the re-granted session replays its
    workload from the top, so its stage identities must restart at
    seq 0 for the resume to match the committed directories."""
    _STAGE_SEQ.pop(sid, None)


def plan_token(*parts) -> str:
    """Deterministic token over a stage's static plan (pass plain python
    ints/strs/tuples).  Stages carry TWO tokens (docs/robustness.md
    "Elastic resume & preemption grace"): a world-invariant BASE token
    over the workload identity (operator, keys, chunk count, consumption
    mode — nothing layout-derived), and the full LAYOUT token folding
    the base together with world size, piece capacities and per-range
    row counts.  A full-token match fast-forwards bit-identically; a
    base-only match with a different recorded topology takes the
    re-shard path (committed host pages stitched into global row order
    and re-blocked onto the live mesh); no match at all starts the
    stage over — foreign state is never spliced in."""
    return hashlib.sha1(repr(parts).encode()).hexdigest()[:16]


def _rank() -> int:
    import jax
    return jax.process_index()


def _procs() -> int:
    import jax
    return jax.process_count()


_RANK_DIR_RE = re.compile(r"rank(\d+)$")


def _rank_dirs() -> list[str]:
    """``rank<r>`` directory names under the checkpoint root, sorted by
    rank.  The elastic re-shard scan reads ALL of them (this module is
    the one sanctioned reader of foreign rank directories — lint rule
    TS111): with a shared checkpoint root (the GKE PVC drill,
    deploy/gke/README.md) every live rank sees every old rank's pages;
    with rank-local disks a world change degrades to recompute because
    the foreign shards simply are not visible."""
    root = ckpt_dir()
    try:
        names = os.listdir(root)
    except OSError:
        return []
    ranked = [(int(m.group(1)), n) for n in names
              if (m := _RANK_DIR_RE.fullmatch(n))]
    return [n for _, n in sorted(ranked)]


# ---------------------------------------------------------------------------
# page serialization — the spill tier's host-page transport, persisted
# ---------------------------------------------------------------------------

def _sha(raw: bytes) -> str:
    return hashlib.sha256(raw).hexdigest()


def _page_bytes(blocks: list) -> bytes:
    """One array's per-shard host blocks → one page (npz).  Remote
    shards' entries are None (another process owns them) and are simply
    absent — each rank's page holds exactly its addressable shards."""
    buf = io.BytesIO()
    arrs = {f"b{k}": b for k, b in enumerate(blocks) if b is not None}
    np.savez(buf, w=np.asarray(len(blocks), np.int64), **arrs)
    return buf.getvalue()


def _page_blocks(raw: bytes) -> list:
    with np.load(io.BytesIO(raw)) as z:
        blocks: list = [None] * int(z["w"])
        for key in z.files:
            if key != "w":
                blocks[int(key[1:])] = z[key]
    return blocks


class Stage:
    """One pipelined stage's durable checkpoint state: piece pages +
    hashed meta sidecars under the per-rank stage directory, committed
    under the two-phase manifest.  Obtain via :func:`open_stage`."""

    def __init__(self, env, label: str, token: str, seq: int,
                 base_token: str | None = None):
        self.env = env
        self.label = label
        self.token = token
        self.base = base_token
        self._dirname = f"stage{seq:03d}-{label}"
        self.dir = os.path.join(ckpt_dir(), f"rank{_rank()}", self._dirname)
        os.makedirs(self.dir, exist_ok=True)
        self.epoch = 0
        #: manifest generation — monotonic across sessions sharing this
        #: checkpoint root: seeded above anything already on disk, and
        #: bumped again by a re-shard rewrite (scan keeps the max)
        self.gen = 0
        self.complete_flag = False
        self.committed: dict[int, dict] = {}
        self.resuming = False
        #: world-mismatch resume state: {"world", "procs", "gen",
        #: "complete", "pieces", "manifests": {rank_dirname: manifest}} —
        #: set when the current manifest generation for this stage was
        #: written by a DIFFERENT topology (see _resolve_resume)
        self.foreign: dict | None = None
        if resume_requested():
            self._resolve_resume()
        else:
            # FRESH run over a non-empty stage dir landscape: supersede
            # whatever previous sessions parked here.  Generations must
            # be monotonic ACROSS sessions — a fresh run re-starting at
            # gen 0 would leave an earlier reshard rewrite's gen-1
            # manifests outranking ITS commits at the next resume,
            # silently fast-forwarding a previous run's data
            mans = self._scan_manifests()
            if mans:
                self.gen = max(int(m.get("gen", 0))
                               for m in mans.values()) + 1

    # -- manifest ----------------------------------------------------------
    @property
    def _manifest_path(self) -> str:
        return os.path.join(self.dir, "MANIFEST.json")

    def _read_manifest(self, rank_dirname: str | None = None) -> dict | None:
        path = self._manifest_path if rank_dirname is None else os.path.join(
            ckpt_dir(), rank_dirname, self._dirname, "MANIFEST.json")
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _scan_manifests(self) -> dict:
        """Every rank dir's manifest for THIS stage (rank dirname →
        manifest).  One small JSON read per rank dir, once per stage
        handle — negligible next to the page traffic it arbitrates,
        and the price of generation monotonicity: an own-manifest-only
        shortcut would let a rank resume from a manifest a later
        reshard rewrite (possibly covering fewer ranks) already
        superseded."""
        mans: dict = {}
        for rd in _rank_dirs():
            man = self._read_manifest(rd)
            if man is not None:
                mans[rd] = man
        return mans

    def _resolve_resume(self) -> None:
        """Decide what this stage can restore.  The scan reads every
        ``rank<r>`` dir's manifest for this stage and keeps the highest
        GENERATION whose manifests agree (same plan, world, gen) — a
        re-shard rewrite bumps ``gen``, so rank dirs the rewrite did not
        cover (the old world had more ranks) are recognized as stale
        instead of masquerading as restorable state.  Three outcomes:

        * current generation matches this stage's full layout token AND
          live topology → plain fast-forward (``resuming``);
        * current generation matches only the BASE token, from a
          different world/process layout → the re-shard path
          (``foreign``; counted in ``resume_world_mismatch`` with a
          structured recovery event, so "resharded" vs "thrown away" is
          auditable — before this rung the mismatch was a SILENT
          recompute);
        * anything else → stale, stage starts over (logged)."""
        from ..utils.logging import log
        mans = self._scan_manifests()
        if not mans:
            return
        top_gen = max(int(m.get("gen", 0)) for m in mans.values())
        cur = {rd: m for rd, m in mans.items()
               if int(m.get("gen", 0)) == top_gen}
        plans = {m.get("plan") for m in cur.values()}
        worlds = {int(m.get("world", 0)) for m in cur.values()}
        procs = {int(m.get("procs", 1)) for m in cur.values()}
        if len(plans) != 1 or len(worlds) != 1 or len(procs) != 1:
            log.warning("checkpoint stage %s: rank manifests disagree at "
                        "generation %d (plans %s, worlds %s, procs %s) — "
                        "torn checkpoint ignored, stage starts over",
                        self._dirname, top_gen, plans, worlds, procs)
            self.gen = top_gen + 1   # the recompute supersedes the mess
            return
        plan, world = plans.pop(), worlds.pop()
        same_topo = (world == int(self.env.world_size)
                     and procs == {_procs()})
        if plan == self.token and same_topo:
            # adopt the current generation even when THIS rank's own
            # manifest is missing/unreadable (it recomputes, voted down
            # to 0 by the resume consensus): committing below the
            # on-disk generation would hand the NEXT resume's max-gen
            # scan stale data over this run's fresh commits
            self.gen = top_gen
            own = cur.get(f"rank{_rank()}")
            if own is None:
                return
            self.committed = {int(k): v
                              for k, v in own.get("pieces", {}).items()}
            self.epoch = int(own.get("epoch", 0))
            self.gen = int(own.get("gen", 0))
            self.complete_flag = bool(own.get("complete", False))
            self.resuming = bool(self.committed)
            return
        base = {m.get("base") for m in cur.values()}
        if (self.base is not None and base == {self.base}
                and not same_topo):
            # every rank dir must hold a piece for it to be adoptable
            # (each dir contributes that rank's shard blocks); the
            # contiguous common prefix is the restorable unit
            common = set.intersection(*[
                {int(k) for k in m.get("pieces", {})} for m in cur.values()])
            n = 0
            while n in common:
                n += 1
            # "complete" for WHOLE-stage adoption means the contiguous
            # common prefix covers the piece count recorded at
            # completion time on every rank — the complete flag alone
            # would let a truncated (torn/tampered) piece table adopt a
            # prefix as if it were the whole stage, a wrong answer
            want = {int(m.get("n_pieces", -1)) for m in cur.values()}
            complete = (all(bool(m.get("complete", False))
                            for m in cur.values())
                        and len(want) == 1 and n == want.pop())
            info = {"world": world, "procs": procs.pop(), "gen": top_gen,
                    "complete": complete, "pieces": n, "manifests": cur}
            self.foreign = info
            # whatever this run commits — a re-shard rewrite OR a fresh
            # recompute of an unadoptable stage — supersedes the foreign
            # generation, so old-world rank dirs the new (possibly
            # smaller) process set never rewrites read as stale forever
            self.gen = top_gen + 1
            _STATS["resume_world_mismatch"] += 1
            from . import recovery
            recovery._record("ckpt.reshard", "world_mismatch", "detected")
            log.warning(
                "checkpoint stage %s: committed at world=%d (%d rank "
                "dirs), resuming at world=%d — %s", self._dirname,
                world, len(cur), int(self.env.world_size),
                "re-shard path engaged (complete stage, %d pieces)" % n
                if info["complete"] else
                "stage incomplete at the old topology: whole-stage "
                "consumers (pipelined joins) recompute — old-layout "
                "pieces cannot splice into a new-layout loop — while "
                "mergeable consumers (stream views) adopt the %d-piece "
                "committed prefix (counted as resume_world_mismatch "
                "either way)" % n)
            return
        log.warning(
            "checkpoint stage %s: plan token mismatch (manifest %s, "
            "workload %s) — stale checkpoint ignored, stage starts "
            "over", self.dir, plan, self.token)
        self.gen = top_gen + 1       # the fresh commits supersede it

    def _commit(self) -> None:
        """Two-phase manifest commit: stage (atomic rank-local write +
        fsync), consensus (every rank of the LIVE mesh votes
        Code.CkptCommit with its staged epoch over the pmax wire — after
        an elastic re-shard that is the NEW mesh; stale old-world rank
        dirs are not voters), then rename staged → MANIFEST.json.
        Single-controller sessions skip the collective entirely."""
        from . import recovery
        self.epoch += 1
        man = {"plan": self.token, "base": self.base, "label": self.label,
               "epoch": self.epoch, "gen": self.gen,
               "complete": self.complete_flag,
               "n_pieces": len(self.committed),
               "world": int(self.env.world_size), "procs": _procs(),
               "pieces": {str(k): v for k, v in self.committed.items()}}
        staged = self._manifest_path + ".staged"

        def stage_write():
            with open(staged, "w", encoding="utf-8") as f:
                json.dump(man, f)
                f.flush()
                os.fsync(f.fileno())

        # bounded IO retry (exec/recovery.retry_io): a transient OSError
        # on shared storage — an NFS blip during a GKE drain — used to
        # abort a drain a 3-attempt backoff saves
        recovery.retry_io(stage_write, "ckpt.write")
        # stage -> vote -> publish: the commit vote must precede the
        # os.replace on every path (reordering fails the CX403 gate)
        recovery.ckpt_commit_consensus(getattr(self.env, "mesh", None),
                                       self.epoch)
        recovery.retry_io(lambda: os.replace(staged, self._manifest_path),
                          "ckpt.write")

    def has_piece(self, i: int) -> bool:
        return int(i) in self.committed

    @property
    def foreign_complete(self) -> bool:
        """True when the world-mismatched checkpoint covers the WHOLE
        stage — the precondition for adopting a non-mergeable (sinkless
        piece-output) stage across a topology change: a partial prefix
        of old-layout pieces has no expressible complement in the new
        layout, so only a complete stage re-shards; anything less
        recomputes (never a wrong answer)."""
        return (self.foreign is not None and self.foreign["complete"]
                and self.foreign["pieces"] > 0)

    def mark_complete(self) -> None:
        """Record that the stage finished all its pieces — the flag a
        LATER world-mismatched resume needs to know the committed set is
        the whole stage (adoptable) rather than a crash prefix
        (recompute).  One extra manifest commit per stage on the armed
        happy path; no-op when already marked."""
        if self.complete_flag:
            return
        self.complete_flag = True
        self._commit()

    def begin_rewrite(self) -> None:
        """Start the post-reshard rewrite: the adopted (re-blocked)
        state re-commits under THIS topology's layout token at the next
        manifest generation, so a second resume at this world is a plain
        fast-forward and the old world's surviving rank dirs — which the
        rewrite may not cover — read as stale (lower gen) forever."""
        self.gen = int(self.foreign["gen"]) + 1
        self.committed = {}
        self.epoch = 0
        self.resuming = False
        self.complete_flag = False

    # -- save --------------------------------------------------------------
    def save_piece(self, i: int, table) -> None:
        """Checkpoint one completed piece's Table: per-array host pages
        (spill-tier transport) + hashed meta sidecar, committed under
        the two-phase manifest.  The piece is durable only after
        :meth:`_commit` returns — a kill mid-write leaves staged files
        that resume ignores."""
        from . import recovery
        from . import integrity as _integrity
        corrupt = recovery.maybe_inject(
            "ckpt.write", intercept=("corrupt",)) == "corrupt"
        i = int(i)
        # armed audit (CYLON_TPU_AUDIT=1, exec/integrity): the piece's
        # order-invariant content fingerprint rides the manifest entry so
        # a resume can audit restored — and topology-mismatched adopted —
        # pieces beyond the page shas (the shas only prove the bytes on
        # disk match what was written; the fingerprint proves what was
        # written matches what the piece held).  None when unarmed: zero
        # cost, and old manifests without the key stay readable.
        fp = _integrity.manifest_fingerprint(table)
        with timing.region("ckpt.write"):
            nbytes, meta_sha, meta_file = self._write_pages(i, table,
                                                            corrupt)
            self.committed[i] = {"meta": meta_file, "sha": meta_sha,
                                 "nbytes": nbytes, "fp": fp}
            self._commit()
        _STATS["checkpoint_events"] += 1
        _STATS["bytes_checkpointed"] += nbytes
        timing.add_bytes("ckpt.write", nbytes)
        timing.bump("ckpt.piece_committed")
        # per-tenant durable-progress accounting: the scheduler's
        # no-progress guard keys off pieces committed since the last
        # preemption (docs/serving.md)
        from .scheduler import current_session
        sess = current_session()
        if sess is not None:
            sess.pieces_committed += 1

    def _write_pages(self, i: int, table, corrupt: bool):
        from ..utils.host import host_shard_blocks
        w = int(self.env.world_size)
        cols, flats = [], []
        for name, c in table.columns.items():
            cols.append({"name": name, "type": c.type,
                         "dictionary": c.dictionary, "bounds": c.bounds,
                         "has_validity": c.validity is not None})
            flats.append(c.data)
            if c.validity is not None:
                flats.append(c.validity)
        pages, total = [], 0
        for j, arr in enumerate(flats):
            raw = _page_bytes(host_shard_blocks(arr, w))
            fname = f"piece_{i}.p{j}"
            # each page carries a content hash computed over the GOOD
            # bytes; an injected corruption flips a byte AFTER hashing so
            # the resume path's verification catches it (the acceptance
            # path for CheckpointCorruptError)
            pages.append({"file": fname, "sha": _sha(raw), "nbytes": len(raw)})
            if corrupt and j == 0:
                raw = bytes([raw[0] ^ 0xFF]) + raw[1:]
            self._atomic_write(fname, raw)
            total += len(raw)
        meta = pickle.dumps({
            "cols": cols,
            "valid_counts": np.asarray(table.valid_counts, np.int64),
            "grouped_by": table.grouped_by,
            "pages": pages,
        })
        meta_file = f"piece_{i}.meta"
        self._atomic_write(meta_file, meta)
        return total + len(meta), _sha(meta), meta_file

    def _atomic_write(self, fname: str, raw: bytes) -> None:
        path = os.path.join(self.dir, fname)
        tmp = path + ".tmp"

        def write():
            with open(tmp, "wb") as f:
                f.write(raw)
            os.replace(tmp, path)

        # page writes share the checkpoint tier's bounded transient-
        # OSError backoff (exec/recovery.retry_io) with the disk tier
        from . import recovery
        recovery.retry_io(write, "ckpt.write")

    # -- load (resume fast-forward) ----------------------------------------
    def load_piece(self, i: int):
        """Restore one committed piece bit-identically: verify the meta
        sidecar against the manifest hash, every page against its meta
        hash, and re-enter the device through the spill tier's sanctioned
        upload boundary (:func:`cylon_tpu.exec.memory.put_blocks`).  Any
        mismatch (or an injected ``corrupt``) raises a typed
        :class:`CheckpointCorruptError` — the caller recomputes the
        stage's remaining pieces."""
        from . import memory, recovery
        from ..core.column import Column
        from ..core.table import Table
        if recovery.maybe_inject("ckpt.load", intercept=("corrupt",)):
            _STATS["corrupt_pages"] += 1
            raise CheckpointCorruptError(
                "injected checkpoint corruption on load", site="ckpt.load")
        entry = self.committed[int(i)]
        with timing.region("ckpt.load"):
            meta_raw = self._read_verified(entry["meta"], entry["sha"])
            meta = pickle.loads(meta_raw)
            sharding = self.env.sharding()
            flats = []
            for page in meta["pages"]:
                raw = self._read_verified(page["file"], page["sha"])
                flats.append(memory.put_blocks(_page_blocks(raw), sharding))
        flats = iter(flats)
        cols = {}
        for cm in meta["cols"]:
            data = next(flats)
            validity = next(flats) if cm["has_validity"] else None
            cols[cm["name"]] = Column(data, cm["type"], validity,
                                      cm["dictionary"], bounds=cm["bounds"])
        out = Table(cols, self.env, meta["valid_counts"])
        out.grouped_by = meta["grouped_by"]
        # armed resume audit (exec/integrity): recompute the restored
        # piece's order-invariant fingerprint against the manifest-
        # recorded one — catches what the shas cannot (a rewrite with
        # self-consistent hashes); a mismatch raises a typed
        # DataIntegrityError and the caller recomputes, never adopts
        from . import integrity
        integrity.audit_restored_table(out, entry.get("fp"))
        _STATS["resume_fast_forwarded_pieces"] += 1
        timing.bump("ckpt.piece_restored")
        return out

    # -- elastic re-shard (world-mismatch resume) --------------------------
    def load_foreign_pieces(self, limit: int | None = None,
                            prefix_ok: bool = False) -> list:
        """Adopt a world-mismatched checkpoint's committed pieces onto
        the LIVE mesh — the elastic resume path (docs/robustness.md
        "Elastic resume & preemption grace").  For each piece, every old
        ``rank<r>`` directory's pages are read and sha-verified (this is
        the one sanctioned foreign-rank read, lint rule TS111), the
        per-shard blocks merged across directories (each old rank held
        only its addressable shards), the shards' live prefixes stitched
        into GLOBAL row order, and the rows re-blocked onto the live
        mesh through :func:`cylon_tpu.relational.repart.
        even_partition_counts` — the same order-preserving split a
        fresh ``repartition`` would produce — before re-entering the
        device through the sanctioned upload boundary
        (:func:`cylon_tpu.exec.memory.put_blocks`).

        Any missing block, unreadable file or hash mismatch (or an
        injected ``corrupt`` at site ``ckpt.reshard``) raises a typed
        :class:`CheckpointCorruptError`: the caller degrades the stage
        to recompute — corruption never produces a wrong answer.

        Returns the adopted Tables in piece order, re-distributed but
        NOT yet counted (the caller counts via :func:`note_reshard`
        after the all-or-nothing resume vote) and NOT yet re-committed
        (the caller rewrites via :meth:`begin_rewrite` + save_piece so
        a second resume at this topology is a plain fast-forward).
        ``limit`` caps the adopted prefix.  ``prefix_ok`` is the
        mergeable-consumer mode (stream views — piece identity is the
        world-invariant batch ordinal): a corruption at piece k > 0
        returns the VERIFIED prefix ``0..k-1`` instead of raising, so
        one flipped byte in batch 199 of 200 costs one batch, not the
        stream's whole committed history; join stages keep the raising
        all-or-nothing contract (:attr:`foreign_complete`)."""
        from . import recovery
        if recovery.maybe_inject("ckpt.reshard",
                                 intercept=("corrupt",)) == "corrupt":
            _STATS["corrupt_pages"] += 1
            raise CheckpointCorruptError(
                "injected checkpoint corruption during re-shard",
                site="ckpt.reshard")
        n = self.foreign["pieces"] if limit is None \
            else min(int(limit), self.foreign["pieces"])
        out: list = []
        with timing.region("ckpt.reshard"):
            for i in range(n):
                try:
                    out.append(self._load_one_foreign(i))
                except (CheckpointCorruptError, DataIntegrityError) as e:
                    if not (prefix_ok and out):
                        raise
                    recovery._record("ckpt.reshard", "corrupt",
                                     "prefix_trim")
                    from ..utils.logging import log
                    log.warning(
                        "re-shard of stage %s: piece %d failed "
                        "verification (%s); adopting the verified "
                        "%d-piece prefix (mergeable consumer)",
                        self._dirname, i, e, len(out))
                    break
        return out

    def _load_one_foreign(self, i: int):
        from ..core.column import Column
        from ..core.table import Table
        from . import memory
        meta = None
        fp_rec = None
        merged: list[list] = []
        for rd, man in self.foreign["manifests"].items():
            entry = man["pieces"][str(i)]
            stage_dir = os.path.join(ckpt_dir(), rd, self._dirname)
            meta_d = pickle.loads(
                self._read_verified(entry["meta"], entry["sha"],
                                    dir=stage_dir))
            if meta is None:
                meta = meta_d
                fp_rec = entry.get("fp")
                merged = [[] for _ in meta["pages"]]
            for j, page in enumerate(meta_d["pages"]):
                raw = self._read_verified(page["file"], page["sha"],
                                          dir=stage_dir)
                blocks = _page_blocks(raw)
                if len(merged[j]) < len(blocks):
                    merged[j].extend([None] * (len(blocks) - len(merged[j])))
                for b, blk in enumerate(blocks):
                    if blk is not None:
                        merged[j][b] = blk
        vc_old = np.asarray(meta["valid_counts"], np.int64)
        for j, blocks in enumerate(merged):
            if any(b is None for b in blocks):
                _STATS["corrupt_pages"] += 1
                raise CheckpointCorruptError(
                    f"re-shard of stage {self._dirname} piece {i}: page "
                    f"{j} is missing shard blocks — an old rank "
                    "directory is absent or unreadable (is the "
                    "checkpoint root shared storage?)",
                    site="ckpt.reshard")
        from .. import config
        from ..relational.repart import even_partition_counts
        total = int(vc_old.sum())
        w_new = int(self.env.world_size)
        dest = even_partition_counts(total, w_new)
        new_cap = config.pow2ceil(max(int(dest.max(initial=0)), 1))
        dof = np.concatenate([[0], np.cumsum(dest)[:-1]]).astype(np.int64)
        sharding = self.env.sharding()
        flats = []
        for blocks in merged:
            rows = np.concatenate(
                [blocks[s][:int(vc_old[s])] for s in range(len(blocks))]) \
                if blocks else np.zeros(0)
            new_blocks = []
            for s in range(w_new):
                part = rows[int(dof[s]):int(dof[s]) + int(dest[s])]
                pad = np.zeros((new_cap - part.shape[0],) + part.shape[1:],
                               part.dtype)
                new_blocks.append(np.concatenate([part, pad]))
            flats.append(memory.put_blocks(new_blocks, sharding))
        flats = iter(flats)
        cols = {}
        for cm in meta["cols"]:
            data = next(flats)
            validity = next(flats) if cm["has_validity"] else None
            # the re-block pads with zeros (the old padding is dropped
            # with the old layout), so bounds must admit 0
            b = cm["bounds"]
            nb = (min(b[0], 0), max(b[1], 0)) if b is not None else None
            cols[cm["name"]] = Column(data, cm["type"], validity,
                                      cm["dictionary"], bounds=nb)
        # per-shard key contiguity does not survive re-blocking: the
        # grouped contract is deliberately dropped, consumers re-derive
        out = Table(cols, self.env, dest)
        # armed adoption audit (exec/integrity): the order-invariant
        # fingerprint is topology-independent — the XOR over per-row
        # hashes survives the stitch + re-block — so the OLD world's
        # recorded fp audits the table as adopted onto the NEW mesh
        from . import integrity
        integrity.audit_restored_table(out, fp_rec, site="ckpt.reshard")
        return out

    def _read_verified(self, fname: str, want_sha: str,
                       dir: str | None = None) -> bytes:
        path = os.path.join(self.dir if dir is None else dir, fname)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            _STATS["corrupt_pages"] += 1
            raise CheckpointCorruptError(
                f"checkpoint page {path} unreadable: {e}",
                site="ckpt.load") from e
        if _sha(raw) != want_sha:
            _STATS["corrupt_pages"] += 1
            raise CheckpointCorruptError(
                f"checkpoint page {path} failed its content-hash check "
                "(torn write or on-disk corruption)", site="ckpt.load")
        return raw


def open_stage(env, label: str, token: str,
               base_token: str | None = None) -> Stage:
    """The next pipelined stage's checkpoint handle (advances the
    deterministic PER-SESSION stage sequence; under the serving
    scheduler the stage directory is additionally namespaced by the
    session name, so concurrent tenants' checkpoints never collide and a
    resumed process matches each tenant's stages regardless of how the
    original interleave ordered them).  ``base_token`` is the
    world-invariant workload identity (:func:`plan_token`) — passing it
    makes the stage eligible for the elastic re-shard path when a
    resume finds its checkpoint committed at a different topology.
    Call only when :func:`enabled`."""
    from . import recovery
    sid = recovery.current_session()
    seq = _STAGE_SEQ.get(sid, 0)
    _STAGE_SEQ[sid] = seq + 1
    if sid is not None:
        label = f"{sid}.{label}"
    stage = Stage(env, label, token, seq, base_token=base_token)
    _OPEN_DIRS.append(stage.dir)
    return stage


def corrupt_fallback(stage: Stage, piece: int, err: Exception) -> None:
    """Log + count a corruption-triggered recompute fallback (the range
    loop calls this, then recomputes the stage's remaining pieces)."""
    from . import recovery
    from ..utils.logging import log
    recovery._record("ckpt.load", "corrupt", "recompute")
    log.warning("checkpoint stage %s piece %d failed verification (%s); "
                "recomputing this stage's remaining pieces instead of "
                "restoring", stage.label, piece, err)


def drain_requested(env) -> bool:
    """Preemption-grace drain poll — called by the pipelined range loop
    and the streaming absorb path at their checkpoint boundaries (the
    points where completed-piece state is already durably committed).
    True only when ALL of: a grace budget is declared
    (``CYLON_TPU_PREEMPT_GRACE_S``), durable checkpointing is armed,
    and the rank-coherent drain vote
    (:func:`cylon_tpu.exec.recovery.drain_consensus`) agrees a
    preemption notice arrived somewhere.  With checkpointing unarmed
    the SIGTERM flag changes nothing — no drain, no writes, no
    collectives (the happy-path contract, asserted in
    tests/test_checkpoint.py).

    A serving session the scheduler flagged for a PREEMPTIVE or FLEET
    drain (docs/serving.md) exits through the same poll: the flag is
    one thread-local read (zero cost for unflagged tenants), the vote
    rides the identical session-namespaced wire, and the
    ``sched.preempt`` injector site fires here so a SIGKILL *during* a
    preemption drain is a constructible chaos schedule."""
    from .scheduler import current_session
    sess = current_session()
    if (sess is not None and sess._drain_mode is not None and enabled()):
        from . import recovery
        kind = recovery.maybe_inject("sched.preempt",
                                     intercept=("stall",))
        if kind == "stall":
            # widen the drain window for kill/term races in chaos
            # schedules — the stall is injected, never organic
            time.sleep(0.25)
        return recovery.drain_consensus(getattr(env, "mesh", None), True)
    from . import preempt
    if not (preempt.armed() and enabled()):
        return False
    from . import recovery
    return recovery.drain_consensus(getattr(env, "mesh", None),
                                    preempt.requested())


def drain_abort(label: str) -> None:
    """Raise the preemption-grace drain: committed state is already
    durable (the caller sits at a checkpoint boundary and has flushed
    any pending sink state), so this records the resume token and exits
    via typed :class:`ResumableAbort` — a planned scale-down, not a
    fault.  The supervisor's relaunch (same or DIFFERENT topology)
    fast-forwards past everything committed inside the grace window."""
    from . import preempt, recovery
    token = flush_for_abort(label)
    recovery._record(label, "preempt", "drain")
    timing.bump("ckpt.preempt_drain")
    g = preempt.grace_seconds()
    if g is not None:
        left = preempt.remaining_s()
        why = (f"preemption notice received (grace {g:g}s"
               f"{'' if left is None else f', {left:.1f}s left'})")
    else:
        # scheduler-initiated drain (preemptive requeue / fleet
        # resize): no OS grace budget is armed
        why = "scheduler drain requested"
    raise ResumableAbort(
        f"{label}: {why} "
        "— current stage flushed and committed; rerun with "
        f"CYLON_TPU_RESUME=1 to fast-forward (resume token: {token}); a "
        "different world size re-shards committed state automatically",
        token=token)


def flush_for_abort(label: str) -> str:
    """The FINAL ladder rung's flush: committed state is already durable
    (every piece commits at its own stage boundary), so this records the
    resume token — a ``RESUME_TOKEN.json`` breadcrumb naming the stages
    this process committed — and returns the token (the checkpoint
    root's absolute path)."""
    root = ckpt_dir()
    token = os.path.abspath(root)
    try:
        os.makedirs(root, exist_ok=True)
        path = os.path.join(root, "RESUME_TOKEN.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"label": label, "pid": os.getpid(),
                       "stages": list(_OPEN_DIRS),
                       "resume": "rerun with CYLON_TPU_RESUME=1"}, f)
    except OSError:
        pass  # the committed manifests are the durable state; the
        # breadcrumb is best-effort
    # flight-recorder postmortem (obs/trace, armed runs only): the
    # last-N timeline events land alongside the manifests — the
    # multi-event successor of the single last_region() breadcrumb
    from ..obs import trace
    trace.postmortem(f"abort flush: {label}", dir_path=root)
    return token
