"""Compile-lifecycle facade — the one gate between the engine and XLA.

ROADMAP item 2's COMPILE axis: before this module, compile cost was
O(tenants) (every distinct ingest row count compiled its own program
family at world 1) and compile state accumulated unboundedly in-process
— this rig's deterministic XLA:CPU ``backend_compile`` SIGSEGV under
accumulation (the reason tier-1 runs one pytest process per file) is
direct evidence that unbounded accumulation is a production outage.
The facade makes compilation **bounded, persistent and typed-failing**:

* **shape families** (:func:`family_cap`) — single-controller ingest
  buckets row capacity onto the same pow2 families the multi-rank
  distributor always used (``config.pow2ceil`` + masked validity
  tails), so N tenants with near-miss plans share ONE executable;
  bit- and order-equal because padding rides the existing pad/validity
  lanes.  Pure function of the row count → rank-uniform with no vote.
  Escape hatch ``CYLON_TPU_SHAPE_FAMILIES=0``.
* **bounded compile ledger** — a registry over live compiled programs
  per mesh fed by ``utils/cache.program_cache`` (:func:`on_insert` /
  :func:`on_hit` / :func:`on_builder_evict` / :func:`on_table_evict`),
  with an LRU eviction budget (``CYLON_TPU_COMPILE_BUDGET``): past it
  the oldest non-pinned programs are retired BEFORE the accumulation
  crash point (re-use recompiles, warm from the persistent cache where
  armed).  In multiprocess sessions the eviction count rides the
  existing count-consensus wire so every rank drops the same programs.
* **persistent layer** (``CYLON_TPU_COMPILE_CACHE_DIR``) — arms jax's
  on-disk compilation cache under ``<dir>/xla`` (accelerator platforms
  only: XLA:CPU executable (de)serialization segfaults, see config.py)
  and keeps three facade-owned files beside it with the checkpoint
  tier's atomic-write (+ bounded ``retry_io``) discipline: a
  warm **manifest** of successfully compiled signatures (content-hashed
  — a corrupted entry fails its hash and is DROPPED: clean miss →
  recompile, never wrong code), a **quarantine** ledger, and a per-rank
  compile-**intent** journal.
* **watchdog + crash quarantine** — the intent record is written
  BEFORE each guarded ``.lower()``/``.compile()``/first-trace and
  cleared after, so a relaunched process finds the intent its dead
  predecessor left, quarantines that signature, and raises typed
  :class:`~cylon_tpu.status.CompileQuarantinedError` instead of
  re-crashing — which subclasses the capacity fault, so the recovery
  ladder's cap-halving rung re-plans at a DIFFERENT shape.  Hung
  compiles surface as :class:`~cylon_tpu.status.CompileTimeoutError`
  via the exchange-watchdog worker-thread pattern
  (``CYLON_TPU_COMPILE_TIMEOUT_S``).

Every compile in the package rides this facade: modules import
:func:`jit` from here instead of calling ``jax.jit`` (lint rule TS117
fences raw ``jax.jit`` / ``.lower().compile()`` outside this module and
``utils/cache.py``), and AOT prewarms go through :func:`aot_compile`.

Overhead contract (the chaos soak's unarmed leg asserts it): with no
cache dir, no watchdog budget and no ``compile.build`` injector spec,
:func:`jit` programs call straight through — one list load + one
``is None``/bool check per call, ZERO filesystem writes, zero
collectives, zero host syncs.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
import threading
import time
import weakref
from collections import OrderedDict

import jax

from .. import config
from ..obs import metrics
from ..status import CompileQuarantinedError, CompileTimeoutError

#: the injector site guarding every facade-routed compile
SITE = "compile.build"

#: ledger entries whose builder name starts with one of these are never
#: evicted: the consensus-wire programs (exec/recovery) are themselves
#: program_cache builders — evicting the wire would make the NEXT
#: eviction vote recompile it mid-agreement (re-entrancy), and a
#: retired wire desyncs the very mechanism that coordinates retirement
_PINNED_PREFIXES = ("cylon_tpu.exec.recovery",)

_HIT = metrics.counter(
    "compile_cache_hit_total",
    help="program_cache lookups served from a live compiled program")
_MISS = metrics.counter(
    "compile_cache_miss_total",
    help="program_cache lookups that built (compiled) a new program")
_EVICT = metrics.counter(
    "compile_cache_evict_total",
    help="live compiled programs retired (ledger budget, per-builder "
         "LRU bound, or mesh-table LRU)")
_MESH_EVICT = metrics.counter(
    "compile_mesh_table_evict_total",
    help="whole per-mesh program tables cleared by the MESH_TABLE_LIMIT "
         "LRU (previously silent in utils/cache.py)")
_SECONDS = metrics.counter(
    "compile_seconds_total",
    help="cumulative XLA backend_compile seconds (jax.monitoring)")
_EVENTS = metrics.counter(
    "compile_events_total",
    help="XLA backend_compile invocations observed (jax.monitoring) — "
         "the per-file `# COMPILE_COUNT` line tests/run_all.py greps")
_QUARANTINED = metrics.counter(
    "compile_quarantine_total",
    help="compile signatures quarantined from a predecessor's orphaned "
         "compile-intent journal")
_TIMEOUTS = metrics.counter(
    "compile_timeout_total",
    help="guarded compiles aborted typed by the compile watchdog")
_MANIFEST_DROPS = metrics.counter(
    "compile_manifest_drop_total",
    help="persistent warm-manifest entries dropped on a failed content "
         "hash (clean miss; never loads wrong code)")

_lock = threading.RLock()
_tls = threading.local()

#: (mesh_key, builder_name, static_key) -> (weakref(per-builder LRU),
#: static_key) in insertion (≈ LRU) order; the bounded compile ledger
_LEDGER: "OrderedDict[tuple, tuple]" = OrderedDict()

#: armed tri-state: None = recompute on next probe (rearm())
_ARMED: list = [None]

#: persistent-layer state for the currently scanned dir ("" = none)
_DIR_STATE: dict = {"path": None, "quarantine": set(), "manifest": {},
                    "adopted": []}

#: signatures already guarded-compiled in THIS process (armed mode only)
_SEEN: set = set()

_LISTENER: list = [False]


def _on_compile_event(event: str, duration: float, **kw) -> None:
    if event.startswith("/jax/core/compile/backend_compile"):
        _SECONDS.inc(duration)
        _EVENTS.inc()


def install_listener() -> None:
    """Idempotently hook jax's compile-event monitoring into the facade
    counters.  The facade's own :func:`jit` installs it on first use;
    harnesses that want compile counts before any facade program exists
    (tests/conftest.py's per-file ``# COMPILE_COUNT`` line) call it
    directly."""
    if not _LISTENER[0]:
        _LISTENER[0] = True
        jax.monitoring.register_event_duration_secs_listener(
            _on_compile_event)


_install_listener = install_listener


# ---------------------------------------------------------------------------
# shape families
# ---------------------------------------------------------------------------

def family_cap(n: int) -> int:
    """The canonical row capacity for a single-controller ingest of
    ``n`` rows: the pow2-family bucket (``config.pow2ceil`` — exactly
    the buckets the multi-rank distributor and every operator output
    capacity already use) while ``CYLON_TPU_SHAPE_FAMILIES`` is armed
    (the default), else ``n`` (exact-shape placement).  Pure function
    of the row count — rank-uniform by construction, no vote needed."""
    n = int(n)
    if n <= 0 or not config.SHAPE_FAMILIES:
        return max(n, 0)
    return config.pow2ceil(n)


# ---------------------------------------------------------------------------
# armed-state plumbing
# ---------------------------------------------------------------------------

def cache_dir() -> str:
    """The facade's persistent directory (``CYLON_TPU_COMPILE_CACHE_DIR``),
    or ``""`` when the durable layer is disarmed."""
    return str(getattr(config, "COMPILE_CACHE_DIR", "") or "")


def _compute_armed() -> bool:
    if float(getattr(config, "COMPILE_TIMEOUT_S", 0) or 0) > 0:
        return True
    if cache_dir():
        return True
    try:
        from . import recovery
        return recovery.faults_declare(SITE)
    except Exception:  # noqa: BLE001 — a broken spec disarms, not crashes
        return False


def armed() -> bool:
    """True while any lifecycle feature (persistent dir, watchdog
    budget, ``compile.build`` injector spec) needs the guarded path.
    Cached; :func:`rearm` invalidates (tests / chaos reprogramming)."""
    a = _ARMED[0]
    if a is None:
        a = _ARMED[0] = _compute_armed()
    return a


def rearm() -> None:
    """Recompute the armed state and re-scan the persistent dir on next
    use — call after changing ``config.COMPILE_*`` knobs or
    ``recovery.install_faults`` specs mid-process (tests, chaos)."""
    _ARMED[0] = None
    _DIR_STATE["path"] = None


# ---------------------------------------------------------------------------
# persistent layer: manifest / quarantine / intent journal
# ---------------------------------------------------------------------------

def _atomic_json(path: str, payload) -> None:
    """Checkpoint-tier write discipline: tmp + ``os.replace`` under the
    bounded transient-OSError retry (exec/recovery.retry_io)."""
    from . import recovery

    def write():
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, sort_keys=True)
        os.replace(tmp, path)

    recovery.retry_io(write, SITE)


def _read_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _entry_sha(sig: str, builder: str) -> str:
    return hashlib.sha1(f"{sig}|{builder}".encode()).hexdigest()[:16]


def _intent_path(d: str) -> str:
    return os.path.join(d, f"intent.rank{jax.process_index()}.json")


def _ensure_dir() -> dict | None:
    """Arm the persistent layer for the configured dir (idempotent per
    dir).  Loads the quarantine ledger, hash-validates the warm
    manifest (corrupt entries DROP — clean miss, never wrong code), and
    adopts orphaned compile intents: an intent file present at arm time
    was left by a predecessor that died mid-compile (the happy path
    always clears it), so its signature is quarantined."""
    d = cache_dir()
    if not d:
        return None
    with _lock:
        if _DIR_STATE["path"] == d:
            return _DIR_STATE
        from . import recovery
        recovery.retry_io(lambda: os.makedirs(d, exist_ok=True), SITE)
        if not config._cpu_only():
            # the facade dir wins over config.py's fingerprint default;
            # CPU-only processes stay uncached (XLA:CPU executable
            # (de)serialization segfaults — config.py's documented stance)
            try:
                jax.config.update("jax_compilation_cache_dir",
                                  os.path.join(d, "xla"))
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 1.0)
            except Exception:  # noqa: BLE001 — stale jax: journal-only
                pass
        q = _read_json(os.path.join(d, "quarantine.json")) or {}
        quarantine = set(q.get("signatures", ()))
        man = _read_json(os.path.join(d, "manifest.json")) or {}
        manifest, dropped = {}, 0
        for sig, ent in man.items() if isinstance(man, dict) else ():
            try:
                ok = ent.get("sha") == _entry_sha(sig, ent.get("builder", ""))
            except AttributeError:
                ok = False
            if ok:
                manifest[sig] = ent
            else:
                dropped += 1
        if dropped:
            _MANIFEST_DROPS.inc(dropped)
            _record("corrupt", f"manifest_drop:{dropped}")
        # adopt orphaned intents from ANY rank of the dead predecessor
        adopted = []
        try:
            names = [f for f in os.listdir(d)
                     if f.startswith("intent.rank") and f.endswith(".json")]
        except OSError:
            names = []
        for name in sorted(names):
            p = os.path.join(d, name)
            intent = _read_json(p)
            sig = (intent or {}).get("sig")
            if sig and sig not in quarantine:
                quarantine.add(sig)
                adopted.append({"sig": sig,
                                "builder": (intent or {}).get("builder", "")})
                _QUARANTINED.inc()
                _record("quarantined",
                        f"orphan_intent:{(intent or {}).get('builder', '?')}")
            try:
                os.remove(p)
            except OSError:
                pass
        if adopted:
            _atomic_json(os.path.join(d, "quarantine.json"),
                         {"signatures": sorted(quarantine)})
        _DIR_STATE.update(path=d, quarantine=quarantine, manifest=manifest,
                          adopted=adopted)
        return _DIR_STATE


def quarantine(sig: str, builder: str = "") -> None:
    """Persist ``sig`` into the quarantine ledger (tests / operators)."""
    st = _ensure_dir()
    with _lock:
        if st is None:
            _DIR_STATE["quarantine"].add(sig)
            return
        st["quarantine"].add(sig)
        _atomic_json(os.path.join(st["path"], "quarantine.json"),
                     {"signatures": sorted(st["quarantine"])})


def quarantined_signatures() -> tuple:
    with _lock:
        return tuple(sorted(_DIR_STATE["quarantine"]))


def _write_intent(label: str, sig: str) -> None:
    d = cache_dir()
    if d:
        _atomic_json(_intent_path(d),
                     {"builder": label, "sig": sig, "pid": os.getpid()})


def _clear_intent() -> None:
    d = cache_dir()
    if not d:
        return
    try:
        os.remove(_intent_path(d))
    except OSError:
        pass


def _manifest_add(label: str, sig: str, poison: bool = False) -> None:
    st = _ensure_dir()
    if st is None:
        return
    with _lock:
        ent = {"builder": label, "sha": _entry_sha(sig, label)}
        if poison:
            # the injector's ``corrupt`` kind: persist a WRONG content
            # hash — the next process's arm-time validation must drop
            # the entry (clean miss → recompile), never trust it
            ent["sha"] = "0" * 16
            _record("corrupt", "poisoned_manifest")
        st["manifest"][sig] = ent
        _atomic_json(os.path.join(st["path"], "manifest.json"),
                     st["manifest"])


def expected_warm() -> int:
    """Hash-valid warm-manifest entries adopted at arm time — the
    relaunch path's rewarm population (docs/serving.md cold/warm)."""
    st = _ensure_dir()
    return 0 if st is None else len(st["manifest"])


# ---------------------------------------------------------------------------
# the guarded compile path
# ---------------------------------------------------------------------------

def _record(kind: str, action: str) -> None:
    from . import recovery
    recovery._record(SITE, kind, action)


def _sig_hash(label: str, args, kwargs) -> str:
    """Deterministic cross-process signature of a guarded compile:
    builder label + the (shape, dtype) leaf walk the retrace sentinel
    uses — rank-uniform (shapes are SPMD-uniform) and stable across
    relaunches, so a predecessor's intent/quarantine entries match."""
    from ..analysis.runtime import _signature
    return hashlib.sha1(
        repr((label, _signature(args, kwargs))).encode()).hexdigest()[:16]


def _watchdog(label: str, sig: str, thunk, stalled: bool):
    """Run a compile thunk under the compile watchdog: the exchange
    watchdog's worker-thread + bounded-join pattern, surfacing typed
    :class:`CompileTimeoutError` instead of RankDesyncError."""
    t = float(getattr(config, "COMPILE_TIMEOUT_S", 0) or 0)
    if stalled and t <= 0:
        t = 2.0   # injected stall must surface typed even unconfigured
    if t <= 0:
        return thunk()
    box: dict = {}

    def run():
        if stalled:
            time.sleep(4 * max(t, 0.5))   # simulated hung compiler
            return
        try:
            box["value"] = thunk()
        except BaseException as e:  # noqa: BLE001 — re-raised on caller
            box["error"] = e

    th = threading.Thread(target=run, daemon=True,
                          name=f"cylon-compile-watchdog-{label}")
    th.start()
    th.join(t)
    if "error" in box:
        raise box["error"]
    if "value" not in box:
        _TIMEOUTS.inc()
        _record("stall", "watchdog")
        raise CompileTimeoutError(
            f"compile watchdog: {label} did not finish lowering/compiling "
            f"within {t:g}s — the compiler is hung", site=SITE,
            signature=sig)
    return box["value"]


def _lifecycle(label: str, thunk, args, kwargs):
    """One guarded compile: quarantine check → intent journal →
    injector probe → watchdog-bounded build → clear intent → manifest.
    Only reached for the FIRST call of each signature while armed."""
    from . import recovery
    sig = _sig_hash(label, args, kwargs)
    with _lock:
        fresh = sig not in _SEEN
    if not fresh:
        return thunk()
    st = _ensure_dir()
    with _lock:
        bad = sig in _DIR_STATE["quarantine"]
    if bad:
        _record("quarantined", "raised")
        raise CompileQuarantinedError(
            f"compile signature {sig} of {label} is quarantined: a "
            "predecessor process died mid-compile on this exact shape "
            "(orphaned compile intent) — re-plan at a different capacity "
            "instead of re-crashing", site=SITE, signature=sig)
    kind = None
    if st is not None:
        _write_intent(label, sig)
    try:
        # kill fires HERE — after the intent hit disk, the honest
        # mid-compile crash the quarantine exists for
        kind = recovery.maybe_inject(SITE, intercept=("corrupt", "stall"))
        out = _watchdog(label, sig, thunk, stalled=(kind == "stall"))
    finally:
        if st is not None:
            _clear_intent()
    with _lock:
        _SEEN.add(sig)
    if st is not None:
        _manifest_add(label, sig, poison=(kind == "corrupt"))
    return out


def _label(fun) -> str:
    mod = getattr(fun, "__module__", "") or ""
    name = getattr(fun, "__qualname__", None) \
        or getattr(fun, "__name__", None) or "jit"
    return f"{mod}.{name}" if mod else str(name)


class _Program:
    """Facade-wrapped jitted program: transparent passthrough while the
    lifecycle is unarmed (one bool check per call); armed, the first
    call of each shape signature runs the guarded compile path.
    Attribute access (``lower`` etc.) forwards to the jax program."""

    # __weakref__: jax weakrefs callables it is handed during tracing —
    # a slotted wrapper without the slot dies with "cannot create weak
    # reference" the first time a program nests inside another trace
    __slots__ = ("_fn", "_facade_label", "_pinned", "__weakref__")

    def __init__(self, fn, label: str, pinned: bool = False):
        self._fn = fn
        self._facade_label = label
        self._pinned = pinned

    def __call__(self, *args, **kwargs):
        if self._pinned or not armed():
            return self._fn(*args, **kwargs)
        return _lifecycle(self._facade_label,
                          lambda: self._fn(*args, **kwargs), args, kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)


def jit(fun=None, pinned: bool = False, **kw):
    """The facade's ``jax.jit``: identical signature/semantics, but the
    returned program's compiles ride the lifecycle (ledger, journal,
    watchdog, quarantine).  ``pinned=True`` marks consensus-wire
    programs (exec/recovery): they bypass the guarded path entirely —
    injecting a fault into (or evicting) the wire would break the very
    mechanism that coordinates recovery.  Usable as ``jit(fn, ...)`` or
    ``@partial``-style ``jit(static_argnums=...)`` decorator."""
    if fun is None:
        return functools.partial(jit, pinned=pinned, **kw)
    _install_listener()
    return _Program(jax.jit(fun, **kw), _label(fun), pinned=pinned)


def _unwrap_program(fn):
    """Peel the retrace sentinel's ``tagged[...]`` wrapper and the cache
    layer's lazy proxy down to the facade program (or a raw jitted
    callable).  Bounded — never walks ``jax.jit``'s own ``__wrapped__``
    (that is the plain Python function, which cannot ``.lower``)."""
    from ..utils.cache import _LazyJit
    for _ in range(8):
        if isinstance(fn, _LazyJit):
            fn = fn._resolve()
        elif isinstance(fn, _Program):
            return fn
        elif (getattr(fn, "__name__", "").startswith("tagged[")
                and hasattr(fn, "__wrapped__")):
            fn = fn.__wrapped__
        else:
            break
    return fn


def aot_compile(fn, *args, **kwargs):
    """AOT ``fn.lower(*args).compile()`` under the lifecycle guard —
    the sanctioned prewarm path (TS117).  Accepts a facade
    :class:`_Program`, the cache layer's lazy proxy, a sentinel-tagged
    program, or a raw jitted callable."""
    fn = _unwrap_program(fn)
    target = fn._fn if isinstance(fn, _Program) else fn
    label = (fn._facade_label if isinstance(fn, _Program)
             else _label(target))

    def thunk():
        return target.lower(*args, **kwargs).compile()

    if not armed():
        return thunk()
    return _lifecycle(label + ".aot", thunk, args, kwargs)


# ---------------------------------------------------------------------------
# the bounded compile ledger (fed by utils/cache.program_cache)
# ---------------------------------------------------------------------------

def _prune_locked() -> None:
    dead = [k for k, (ref, key) in _LEDGER.items()
            if ref() is None or key not in (ref() or {})]
    for k in dead:
        del _LEDGER[k]


def live_programs() -> int:
    """Live compiled programs across every mesh's program tables — the
    ``compile_programs_live`` gauge read callback."""
    with _lock:
        _prune_locked()
        return len(_LEDGER)


metrics.gauge("compile_programs_live",
              help="live compiled programs across all program_cache "
                   "tables (facade ledger)", fn=live_programs)


def on_hit(mesh, name: str, key) -> None:
    """program_cache hit hook (utils/cache wrapper, outside its lock)."""
    _HIT.inc()
    ekey = (id(mesh), name, key)
    with _lock:
        if ekey in _LEDGER:
            _LEDGER.move_to_end(ekey, last=True)


def on_insert(mesh, name: str, key, lru) -> None:
    """program_cache miss/insert hook: append to the ledger and enforce
    the ``CYLON_TPU_COMPILE_BUDGET`` per-mesh bound.  Called OUTSIDE the
    cache lock (lock order: cache._lock before compiler._lock); the
    consensus vote for multiprocess eviction counts runs here too —
    never under either lock's critical build path (the wire programs
    are pinned and the TLS guard breaks re-entrancy)."""
    _MISS.inc()
    mk = id(mesh)
    with _lock:
        _LEDGER[(mk, name, key)] = (weakref.ref(lru), key)
        _LEDGER.move_to_end((mk, name, key), last=True)
    budget = int(getattr(config, "COMPILE_BUDGET", 0) or 0)
    if budget <= 0 or getattr(_tls, "in_evict", False):
        return
    with _lock:
        _prune_locked()
        over = sum(1 for k in _LEDGER if k[0] == mk) - budget
    if over <= 0:
        return
    if jax.process_count() > 1:
        from . import recovery
        _tls.in_evict = True
        try:
            # every rank inserts at the same program point (SPMD
            # builders), so the vote is symmetric; max-agree the count
            # so a straggling GC on one rank can't desync the drops
            over = recovery.count_consensus(mesh, over)
        finally:
            _tls.in_evict = False
    if over > 0:
        _evict(mk, over)


def _evict(mesh_key: int, n: int) -> None:
    """Retire the ``n`` least-recently-used non-pinned programs of one
    mesh: pop them from their per-builder LRUs (re-use recompiles).
    Lock order: cache._lock first, compiler._lock second — the same
    order the program_cache wrapper's table hook uses."""
    from ..utils import cache as _cache
    removed = 0
    with _cache._lock:
        with _lock:
            for ekey in list(_LEDGER):
                if removed >= n:
                    break
                mk, name, key = ekey
                if mk != mesh_key or \
                        name.startswith(_PINNED_PREFIXES):
                    continue
                ref, _k = _LEDGER.pop(ekey)
                lru = ref()
                if lru is not None:
                    lru.pop(key, None)
                removed += 1
    if removed:
        _EVICT.inc(removed)
        from ..utils import timing
        timing.bump("compile.ledger_evict")


def on_builder_evict(mesh, name: str, keys) -> None:
    """Per-builder LRU overflow hook: the wrapper popped ``keys`` past
    ``config.PROGRAM_CACHE_SIZE`` — keep the ledger exact and count."""
    mk = id(mesh)
    with _lock:
        for key in keys:
            _LEDGER.pop((mk, name, key), None)
    _EVICT.inc(len(keys))


def on_table_evict(mesh_key: int, n_programs: int) -> None:
    """MESH_TABLE_LIMIT hook: a whole mesh's program table was cleared
    by utils/cache (previously silent).  Called UNDER cache._lock —
    taking compiler._lock second matches the global lock order."""
    _MESH_EVICT.inc()
    if n_programs:
        _EVICT.inc(n_programs)
    with _lock:
        for ekey in [k for k in _LEDGER if k[0] == mesh_key]:
            del _LEDGER[ekey]


# ---------------------------------------------------------------------------
# stats
# ---------------------------------------------------------------------------

def stats() -> dict:
    """The facade's counter block — surfaced in the serving summary
    (exec/scheduler.stats) and obs.bench_detail."""
    return {
        "programs_live": live_programs(),
        "cache_hits": _HIT.value,
        "cache_misses": _MISS.value,
        "cache_evictions": _EVICT.value,
        "mesh_table_evictions": _MESH_EVICT.value,
        "compile_seconds": round(float(_SECONDS.value), 6),
        "compile_events": _EVENTS.value,
        "quarantined": len(_DIR_STATE["quarantine"]),
        "quarantine_adoptions": _QUARANTINED.value,
        "watchdog_timeouts": _TIMEOUTS.value,
        "manifest_drops": _MANIFEST_DROPS.value,
        "expected_warm": (len(_DIR_STATE["manifest"])
                          if _DIR_STATE["path"] else 0),
    }


def reset_stats() -> None:
    """Zero the facade counters and the in-process seen-set (bench
    iterations; the persistent dir state is untouched)."""
    for c in (_HIT, _MISS, _EVICT, _MESH_EVICT, _SECONDS, _EVENTS,
              _QUARANTINED, _TIMEOUTS, _MANIFEST_DROPS):
        c.reset()
    with _lock:
        _SEEN.clear()
