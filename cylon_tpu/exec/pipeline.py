"""Pipelined (chunked) operator execution — the C9 slot, TPU-first.

The reference ships an experimental push-based operator DAG (ops/api/
parallel_op.hpp:32 ``Op`` with per-tag input queues, execution/execution.hpp
:43-110 RoundRobin/ForkJoin/Priority executors, dis_join_op.hpp:44) whose
point is overlapping the shuffle of one batch with the compute of another.
On TPU the executor half of that machinery already exists in the runtime:
XLA dispatch is asynchronous, so a host loop that ENQUEUES chunk k+1's
partition/exchange while chunk k's join still occupies the device gets
comm/compute overlap for free — the design reduces to *streaming chunked
operators*:

  build side: promote + hash-shuffle ONCE (amortized across all chunks);
  probe side: split into C row chunks; each chunk flows
      partition -> exchange -> local join
  and successive chunks' device work interleaves in the dispatch queue.

Chunking also bounds peak memory: each materialization sizes to one
chunk's output instead of the whole join's — the way to run a join whose
output (or sort scratch) exceeds HBM.

Degenerate case C=1 equals the monolithic operator exactly.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .. import config
from ..core.column import Column
from ..core.table import Table
from ..relational.common import REP, ROW, check_same_env, promote_key_pair
from ..relational.join import join_tables
from ..relational.repart import concat_tables, shuffle_table
from ..status import InvalidError

shard_map = jax.shard_map


@lru_cache(maxsize=config.PROGRAM_CACHE_SIZE)
def _chunk_fn(mesh: Mesh, cap: int, step: int):
    """Per-shard dynamic slice [start, start+step) of every column."""

    def per_shard(start, datas, valids):
        def sl(a):
            return jax.lax.dynamic_slice(a, (start,), (step,))

        out_d = tuple(sl(d) for d in datas)
        out_v = tuple(sl(v) if v is not None else None for v in valids)
        return out_d, out_v

    return jax.jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, ROW, ROW), out_specs=(ROW, ROW)))


def chunk_table(table: Table, n_chunks: int) -> list[Table]:
    """Split each shard's valid prefix into ``n_chunks`` contiguous row
    ranges; chunk i is a Table holding every shard's i-th range (so the
    concatenation of chunks in order re-covers the table, per shard)."""
    if n_chunks <= 1:
        return [table]
    from ..relational.repart import repad_table
    cap = max(table.capacity, 1)
    step = -(-cap // n_chunks)
    if step * n_chunks != cap:      # make every window in-bounds
        table = repad_table(table, step * n_chunks)
        cap = step * n_chunks
    items = list(table.columns.items())
    datas = tuple(c.data for _, c in items)
    valids = tuple(c.validity for _, c in items)
    fn = _chunk_fn(table.env.mesh, cap, step)
    out = []
    for i in range(n_chunks):
        start = i * step
        # chunk validity = how much of each shard's live prefix falls
        # inside [start, start+step)
        vc = np.clip(table.valid_counts - start, 0, step)
        out_d, out_v = fn(np.int32(start), datas, valids)
        cols = {}
        for (n, c), d, v in zip(items, out_d, out_v):
            cols[n] = Column(d, c.type, v, c.dictionary, bounds=c.bounds)
        out.append(Table(cols, table.env, vc.astype(np.int64)))
    return out


def pipelined_set_op(a: Table, b: Table, op: str, n_chunks: int = 4):
    """Streaming chunked set operation — the reference's ``DisSetOp``
    pipeline stage (cpp/src/cylon/ops/dis_set_op.hpp) re-thought: the
    resident side ``b`` shuffles ONCE, ``a`` streams through in row
    chunks (each chunk shuffled in the loop, interleaving exchange with
    compute — ``a`` is never held shuffled in full), and per-chunk
    partials combine under one final distinct pass:

    union:      distinct(a ∪ b) = unique(concat(unique(chunk_i)…, unique(b)))
    subtract:   rows of a not in b — per-chunk subtract vs resident b,
                then distinct across chunks (a row can recur in chunks)
    intersect:  symmetric to subtract.

    No sink form: set semantics need the cross-chunk distinct pass, so
    partials are not independently consumable.  Peak extra memory is the
    partials (each ≤ one chunk) plus the final distinct input.
    """
    from ..relational.setops import _align_schemas, _set_operation_impl, \
        unique_table
    if op not in ("union", "intersect", "subtract"):
        raise InvalidError(f"unknown set op {op!r}")
    env = check_same_env(a, b)
    a, b = _align_schemas(a, b)
    names = a.column_names
    if env.world_size > 1 and op != "union":
        b = shuffle_table(b, names)     # resident side: ONCE
    parts = []
    for chunk in chunk_table(a, n_chunks):
        if op == "union":
            # unique_table shuffles internally; a pre-shuffle of `a`
            # would be a redundant third pass over its rows
            parts.append(unique_table(chunk))
        else:
            if env.world_size > 1:
                chunk = shuffle_table(chunk, names)
            parts.append(_set_operation_impl(chunk, b, op,
                                             assume_colocated=True))
    if op == "union":
        parts.append(unique_table(b))
    combined = concat_tables(parts) if len(parts) > 1 else parts[0]
    return unique_table(combined)


class GroupBySink:
    """Streaming groupby consumer for :func:`pipelined_join` — the
    downstream ``Op`` of the reference's dis-join DAG (dis_join_op.hpp:44
    feeding a groupby op through its queue).

    Each joined chunk is partially aggregated (and released); ``finalize``
    combines the partials.  Ops must decompose through PUBLIC aggregations
    of their partials: sum/count/min/max/mean (mean = sum & count).
    var/std need a sum-of-squares intermediate the public surface does not
    expose — use ``groupby_aggregate`` on a materialized table for those.

    Usage::

        sink = GroupBySink("k", [("a", "sum"), ("b", "mean")])
        pipelined_join(lt, rt, "k", "k", n_chunks=8, sink=sink)
        out = sink.finalize()          # Table, same schema as the
                                       # monolithic groupby_aggregate
    """

    _DECOMP = {"sum": ("sum",), "count": ("count",), "min": ("min",),
               "max": ("max",), "mean": ("sum", "count")}
    _COMBINE = {"sum": "sum", "count": "sum", "min": "min", "max": "max"}

    def __init__(self, by, aggs):
        self.by = [by] if isinstance(by, str) else list(by)
        self.aggs = list(aggs)
        for col, op, *_ in self.aggs:
            if op not in self._DECOMP:
                raise InvalidError(
                    f"GroupBySink does not support {op!r}; supported: "
                    f"{sorted(self._DECOMP)}")
        # one partial agg per distinct (col, intermediate-op)
        self._chunk_aggs = sorted({(c, i) for c, op, *_ in self.aggs
                                   for i in self._DECOMP[op]})
        self._parts: list[Table] = []

    def __call__(self, chunk: Table) -> None:
        from ..relational.groupby import groupby_aggregate
        self._parts.append(
            groupby_aggregate(chunk, self.by, list(self._chunk_aggs)))
        return None

    def finalize(self) -> Table:
        from ..relational.groupby import groupby_aggregate
        if not self._parts:
            raise InvalidError("GroupBySink saw no chunks")
        partial = concat_tables(self._parts) if len(self._parts) > 1 \
            else self._parts[0]
        self._parts = []
        combine = [(f"{c}_{i}", self._COMBINE[i]) for c, i in
                   self._chunk_aggs]
        comb = groupby_aggregate(partial, self.by, combine)
        # final columns in requested order, renamed to the public contract
        from ..frame import DataFrame
        df = DataFrame(_table=comb)
        out_cols = list(self.by)
        # means first: they READ sum/count intermediates that a sibling
        # sum/count agg over the same column will rename away below
        for col, op, *_ in self.aggs:
            if op == "mean":
                df[f"{col}_mean"] = (df[f"{col}_sum_sum"]
                                     / df[f"{col}_count_sum"])
        for col, op, *_ in self.aggs:
            name = f"{col}_{op}"
            if op != "mean":
                i = self._DECOMP[op][0]
                df = df.rename({f"{col}_{i}_{self._COMBINE[i]}": name})
            out_cols.append(name)
        out = df[out_cols]._table
        out.grouped_by = None  # combine order is chunk-partial order
        return out


def pipelined_join(left: Table, right: Table, left_on, right_on,
                   how: str = "inner", n_chunks: int = 4,
                   suffixes=("_x", "_y"), sink=None):
    """Streaming chunked distributed join (reference DisJoinOP re-thought).

    The (smaller) build side shuffles once; the probe side streams through
    in ``n_chunks`` row chunks whose partition/exchange/join dispatches
    interleave on the device.  Semantics match
    :func:`~cylon_tpu.relational.join.join_tables` for inner/left joins
    (each probe row appears in exactly one chunk).  right/outer need
    cross-chunk unmatched-row bookkeeping and are not supported here.

    Note: chunks shuffle with plain hashing — the monolithic join's
    heavy-key skew split is not applied here, so an extreme single-key
    distribution still concentrates on one shard (use join_tables for
    skewed keys).

    ``sink``: the downstream operator of the pipeline (the reference's next
    ``Op`` in the DAG).  When given, each output chunk is passed to
    ``sink(chunk_table)`` and immediately released — peak memory is ONE
    chunk's output — and the list of sink results is returned.  Without a
    sink the chunks are concatenated into one Table (which necessarily
    holds the full output twice during assembly; use a sink for outputs
    near HBM capacity).
    """
    if how not in ("inner", "left"):
        raise InvalidError("pipelined_join supports how in ('inner','left')")
    env = check_same_env(left, right)
    left_on = [left_on] if isinstance(left_on, str) else list(left_on)
    right_on = [right_on] if isinstance(right_on, str) else list(right_on)

    # promote once so every chunk shares dictionaries/dtypes with the build
    lkey, rkey = [], []
    for ln, rn in zip(left_on, right_on):
        a, b = promote_key_pair(left.column(ln), right.column(rn))
        lkey.append(a)
        rkey.append(b)
    lwork = left.with_columns(dict(zip(left_on, lkey)))
    rwork = right.with_columns(dict(zip(right_on, rkey)))

    if env.world_size > 1:
        rwork = shuffle_table(rwork, right_on)   # build side: ONCE

    outs = []
    for chunk in chunk_table(lwork, n_chunks):
        if env.world_size > 1:
            chunk = shuffle_table(chunk, left_on)
        # chunk and rwork are now co-located: plain local join, EAGER
        # (allow_defer=False).  Measured at the out-of-HBM scale this
        # pipeline targets (96M rows/side, v5e 16GB): deferring chunk
        # joins so the sink's groupby consumes the fused pre-expansion
        # state OOMs — the fused kernel's temporaries span the full
        # (chunk + resident build) concat rows and dwarf the expanded
        # chunk output the eager path holds instead; eager chunks
        # complete (40.1 s at 96M/side, results/tpu_v5e_pipelined.jsonl).
        res = join_tables(chunk, rwork, left_on, right_on, how=how,
                          suffixes=suffixes, assume_colocated=True,
                          allow_defer=False)
        outs.append(sink(res) if sink is not None else res)
    if sink is not None:
        return outs
    return concat_tables(outs) if len(outs) > 1 else outs[0]
