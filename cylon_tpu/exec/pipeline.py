"""Pipelined (chunked) operator execution — the C9 slot, TPU-first.

The reference ships an experimental push-based operator DAG (ops/api/
parallel_op.hpp:32 ``Op`` with per-tag input queues, execution/execution.hpp
:43-110 RoundRobin/ForkJoin/Priority executors, dis_join_op.hpp:44) whose
point is overlapping the shuffle of one batch with the compute of another.
On TPU the executor half of that machinery already exists in the runtime:
XLA dispatch is asynchronous, so a host loop that ENQUEUES piece k+1's
work while piece k still occupies the device gets comm/compute overlap for
free — the design reduces to *streaming tiled operators*, with the tiling
dimension chosen per op:

  set ops tile over ROW chunks (a row's set membership is position-free);
  joins tile over KEY RANGES of the once-sorted build side
  (``pipelined_join``): re-joining row chunks against the full resident
  build would re-sort it per chunk — the measured 7.5x cliff vs the
  monolith — while range pieces sort every row once and make all four
  join types complete per piece (a key's matches cannot leave its range).

Tiling also bounds peak memory: each materialization sizes to one piece's
output instead of the whole op's — the way to run a join whose output (or
sort scratch) exceeds HBM.

Degenerate case C=1 equals the monolithic operator exactly.
"""

from __future__ import annotations

import time as _time
from collections.abc import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .. import config
from ..obs import plan as _plan
from ..obs import trace as _trace
from ..utils.cache import jit, program_cache
from ..core.column import Column
from ..core.table import Table
from ..ctx.context import ROW_AXIS
from ..relational.common import (PAD_L, REP, ROW, check_same_env,
                                 promote_key_pair)
from ..relational.join import join_tables
from ..relational.piece import PackedPiece, PieceSource  # noqa: F401
from ..relational.repart import concat_tables, shuffle_table
from ..status import CylonError, InvalidError

shard_map = jax.shard_map


def _interleave() -> None:
    """Serving-tier interleave point (docs/serving.md): at piece-loop
    boundaries a session scheduled by :mod:`cylon_tpu.exec.scheduler`
    hands the baton to the next tenant — its already-dispatched async
    device work keeps executing underneath, so the PR 6 overlap
    scheduler keeps the device busy ACROSS tenants.  A no-op (one
    module-global load) outside a scheduler.  Piece boundaries are
    also the periodic metrics-snapshot poll for entrypoints that never
    run the scheduler loop (bench.py; CYLON_TPU_METRICS_JSON) — one
    list load when unarmed."""
    from ..obs import metrics
    metrics.maybe_write_snapshot()
    from . import scheduler
    scheduler.maybe_yield()


def _norep_kwargs() -> dict:
    """shard_map kwargs disabling replication checking — required when a
    pallas_call is in the program (no replication rule on jax < 0.5; the
    vma shim in ops/pallas_probe covers jax >= 0.5, whose flag is named
    check_vma).  The program stays pure-local; the jaxpr gate still
    asserts it contains no collective."""
    import inspect
    params = inspect.signature(shard_map).parameters
    if "check_rep" in params:
        return {"check_rep": False}
    if "check_vma" in params:
        return {"check_vma": False}
    return {}


@program_cache()
def _chunk_fn(mesh: Mesh, cap: int, step: int):
    """Per-shard dynamic slice [start, start+step) of every column."""

    def per_shard(start, datas, valids):
        def sl(a):
            return jax.lax.dynamic_slice(a, (start,), (step,))

        out_d = tuple(sl(d) for d in datas)
        out_v = tuple(sl(v) if v is not None else None for v in valids)
        return out_d, out_v

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, ROW, ROW), out_specs=(ROW, ROW)))


class _LazyChunks(Sequence):
    """Dispatch-on-demand chunk views of one table: ``chunks[i]`` slices
    chunk i when (and each time) it is accessed, so a streaming consumer
    holds ONE chunk's arrays live at a time — the seed dispatched every
    chunk before any consumer ran, pinning all slices at once (the peak
    the pipelined ops' docstrings promise to avoid).  Re-indexing
    re-dispatches: slices are cheap and deterministic."""

    def __init__(self, table: Table, n_chunks: int, step: int):
        self._table = table
        self._n = int(n_chunks)
        self._step = int(step)

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._n))]
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        t = self._table
        items = list(t.columns.items())
        fn = _chunk_fn(t.env.mesh, t.capacity, self._step)
        start = i * self._step
        # chunk validity = how much of each shard's live prefix falls
        # inside [start, start+step)
        vc = np.clip(t.valid_counts - start, 0, self._step)
        out_d, out_v = fn(np.int32(start),
                          tuple(c.data for _, c in items),
                          tuple(c.validity for _, c in items))
        cols = {}
        for (n, c), d, v in zip(items, out_d, out_v):
            cols[n] = Column(d, c.type, v, c.dictionary, bounds=c.bounds)
        return Table(cols, t.env, vc.astype(np.int64))


def chunk_table(table: Table, n_chunks: int) -> Sequence:
    """Split each shard's valid prefix into ``n_chunks`` contiguous row
    ranges; chunk i is a Table holding every shard's i-th range (so the
    concatenation of chunks in order re-covers the table, per shard).
    Returns a lazy sequence: each chunk's device slice dispatches on
    access, not up front."""
    if n_chunks <= 1:
        return [table]
    from ..relational.repart import repad_table
    cap = max(table.capacity, 1)
    step = -(-cap // n_chunks)
    if step * n_chunks != cap:      # make every window in-bounds
        table = repad_table(table, step * n_chunks)
    return _LazyChunks(table, n_chunks, step)


def pipelined_set_op(a: Table, b: Table, op: str, n_chunks: int = 4):
    """Streaming chunked set operation — the reference's ``DisSetOp``
    pipeline stage (cpp/src/cylon/ops/dis_set_op.hpp) re-thought: the
    resident side ``b`` shuffles ONCE, ``a`` streams through in row
    chunks (each chunk shuffled in the loop, interleaving exchange with
    compute — ``a`` is never held shuffled in full), and per-chunk
    partials combine under one final distinct pass:

    union:      distinct(a ∪ b) = unique(concat(unique(chunk_i)…, unique(b)))
    subtract:   rows of a not in b — per-chunk subtract vs resident b,
                then distinct across chunks (a row can recur in chunks)
    intersect:  symmetric to subtract.

    No sink form: set semantics need the cross-chunk distinct pass, so
    partials are not independently consumable.  Peak extra memory is the
    partials (each ≤ one chunk) plus the final distinct input.
    """
    from ..relational.setops import _align_schemas, _set_operation_impl, \
        unique_table
    if op not in ("union", "intersect", "subtract"):
        raise InvalidError(f"unknown set op {op!r}")
    env = check_same_env(a, b)
    with _plan.node("pipelined_set_op", kind=op,
                    n_chunks=int(n_chunks)) as pn:
        if pn:
            pn.set(rows_in=a.row_count + b.row_count)
        a, b = _align_schemas(a, b)
        names = a.column_names
        if env.world_size > 1 and op != "union":
            b = shuffle_table(b, names)     # resident side: ONCE
        parts = []
        for chunk in chunk_table(a, n_chunks):
            _interleave()   # chunk boundary = serving interleave point
            if op == "union":
                # unique_table shuffles internally; a pre-shuffle of `a`
                # would be a redundant third pass over its rows
                parts.append(unique_table(chunk))
            else:
                if env.world_size > 1:
                    chunk = shuffle_table(chunk, names)
                parts.append(_set_operation_impl(chunk, b, op,
                                                 assume_colocated=True))
        if op == "union":
            parts.append(unique_table(b))
        combined = concat_tables(parts) if len(parts) > 1 else parts[0]
        res = unique_table(combined)
        if pn:
            pn.set(rows_out=res.row_count)
        return res


class GroupBySink:
    """Streaming groupby consumer for :func:`pipelined_join` — the
    downstream ``Op`` of the reference's dis-join DAG (dis_join_op.hpp:44
    feeding a groupby op through its queue).

    Each joined chunk is partially aggregated (and released); ``finalize``
    combines the partials.  Ops must decompose through PUBLIC aggregations
    of their partials: sum/count/min/max/mean/var/std (mean = sum & count;
    var/std = sum & count & sumsq — the public ``sumsq`` aggregation is the
    reference's VAR intermediate, compute/aggregate_kernels.hpp:43, exposed
    so the streaming decomposition closes).

    Usage::

        sink = GroupBySink("k", [("a", "sum"), ("b", "mean")])
        pipelined_join(lt, rt, "k", "k", n_chunks=8, sink=sink)
        out = sink.finalize()          # Table, same schema as the
                                       # monolithic groupby_aggregate
    """

    _DECOMP = {"sum": ("sum",), "count": ("count",), "min": ("min",),
               "max": ("max",), "mean": ("sum", "count"),
               "var": ("sum", "count", "sumsq"),
               "std": ("sum", "count", "sumsq")}
    _COMBINE = {"sum": "sum", "count": "sum", "min": "min", "max": "max",
                "sumsq": "sum"}

    def __init__(self, by, aggs, ddof: int = 1):
        self.by = [by] if isinstance(by, str) else list(by)
        self.aggs = list(aggs)
        self.ddof = int(ddof)
        for col, op, *_ in self.aggs:
            if op not in self._DECOMP:
                raise InvalidError(
                    f"GroupBySink does not support {op!r}; supported: "
                    f"{sorted(self._DECOMP)}")
        # one partial agg per distinct (col, intermediate-op)
        self._chunk_aggs = sorted({(c, i) for c, op, *_ in self.aggs
                                   for i in self._DECOMP[op]})
        self._parts: list[Table] = []
        self._regs: list = []  # HBM-ledger registrations of the partials
        self._pending = []   # in-flight fused dispatches (see __call__)
        self._disjoint = False
        self._ckpt = None    # durable-checkpoint Stage (exec/checkpoint)
        self._adopted = 0    # pieces adopted so far = checkpoint index

    def attach_checkpoint(self, stage) -> None:
        """Arm durable checkpointing (exec/checkpoint): each adopted
        partial aggregate — the sink's completed-piece state — is saved
        and committed at its stage boundary.  Adoption order equals
        consumption order (the pending queue is FIFO), so the adoption
        counter IS the piece index."""
        self._ckpt = stage

    def restore_partial(self, part: Table) -> None:
        """Adopt a checkpoint-restored partial (resume fast-forward)
        without re-saving it — bit-identical to the partial the crashed
        process computed, so finalize() is bit-equal to an uninterrupted
        run."""
        from . import memory
        self._parts.append(part)
        self._regs.append(memory.register_table("sink_part", part))
        self._adopted += 1

    def _adopt(self, part: Table) -> None:
        """Keep one chunk's partial aggregate, accounted in the HBM
        ledger (exec/memory): sink state is resident across the whole
        piece loop, so budget decisions must see it.  Released (and the
        balance drained) at finalize."""
        from . import memory
        self._parts.append(part)
        self._regs.append(memory.register_table("sink_part", part))
        if self._ckpt is not None:
            self._ckpt.save_piece(self._adopted, part)
        _trace.async_end("sink.chunk_inflight", self._adopted)
        self._adopted += 1

    def mark_key_disjoint(self) -> None:
        """Caller guarantee: no group key occurs in more than one consumed
        chunk (range-partitioned pipelines keyed on the join keys).
        ``finalize`` then skips the cross-chunk combine groupby — the
        per-chunk partials ARE the final groups and just concatenate."""
        self._disjoint = True

    def __call__(self, chunk: Table) -> None:
        """Consume one chunk.  Deferred inner-join chunks take the fused
        pushdown via begin/resolve: the NEXT chunk's program is enqueued
        before the previous chunk's meta is pulled, so the device never
        idles on the host round trip (one-deep software pipeline; the
        reference's ops-DAG keeps pieces in flight the same way,
        execution.hpp:43)."""
        from ..relational.fused import try_begin_join_groupby
        from ..relational.groupby import _normalize_aggs, groupby_aggregate
        # async trace span per chunk (obs/trace, armed runs only):
        # begins at absorb, ends when the chunk's partial is ADOPTED —
        # for deferred chunks that is one piece later, which is exactly
        # the dispatch/consume overlap the timeline exists to show
        _trace.async_begin("sink.chunk_inflight",
                           self._adopted + len(self._pending))
        specs = _normalize_aggs(list(self._chunk_aggs))
        h = try_begin_join_groupby(chunk, self.by, specs, 1)
        if h is not None:
            self._pending.append((h, chunk))
            # one-deep: the next piece's program is enqueued before this
            # pull blocks.  Two-deep was measured SLOWER at the 125M
            # bench (12.91 vs 12.73 s/iter): the extra piece's pinned
            # join state (~1 GB) costs more than the pull overlap gains.
            while len(self._pending) > 1:
                self._settle(self._pending.pop(0))
        else:
            self.flush_pending()
        if h is None:
            # a crash-exhausted begin must not let groupby_aggregate
            # re-run the identical (uncached) compile ladder — force the
            # materialize path first, exactly like _settle
            chunk.columns  # noqa: B018 — triggers DeferredTable thunk
            self._adopt(
                groupby_aggregate(chunk, self.by, list(self._chunk_aggs)))
        return None

    def _settle(self, pending) -> None:
        from ..relational.groupby import groupby_aggregate
        from ..utils import timing
        h, chunk = pending
        with timing.sync_region("pipe.consume"):
            # the per-piece host sync of the sink pipeline: its ".block"
            # twin is where the dispatch/block split (bench.py,
            # CYLON_TPU_TIMING=async) charges the device work that every
            # dispatch-only pipe.* marker above it enqueued
            out = h.resolve()
        if out is None:   # compile ladder exhausted mid-resolve
            # materialize FIRST: groupby_aggregate would otherwise retry
            # the identical (crash-exhausted, uncached) pushdown ladder
            chunk.columns  # noqa: B018 — triggers DeferredTable thunk
            out = groupby_aggregate(chunk, self.by, list(self._chunk_aggs))
        self._adopt(out)

    #: public alias of the consume path — the streaming view's verb
    #: (cylon_tpu/stream.view absorbs one micro-batch per call)
    def absorb(self, chunk: Table) -> None:
        self(chunk)

    def flush_pending(self) -> None:
        """Settle every in-flight deferred chunk NOW — the partials
        commit at their stage boundaries as a side effect.  Called
        before a stage is marked complete and before a preemption-grace
        drain raises: both need the durable state to cover every chunk
        the sink has consumed, not just the settled ones."""
        while self._pending:
            self._settle(self._pending.pop(0))

    def compact(self) -> None:
        """Fold the adopted partials into ONE combined partial — bounded
        sink state for unbounded streams.  The combine groupby's summed
        intermediates, renamed back to the partial schema, ARE a valid
        partial (re-summing an already-summed intermediate is the same
        associative fold), so under the streaming exactness contract
        (integer-exact partial sums — docs/streaming.md) a compacted
        sink's snapshot stays bit-equal to the uncompacted one.  Without
        compaction every ``snapshot()`` re-combines one partial per
        absorbed chunk: O(batches) state and per-read cost, quadratic
        over a stream's lifetime.  No-op for 0/1 partials and for
        key-disjoint sinks (their partials are already final groups)."""
        from ..relational.groupby import groupby_aggregate
        self.flush_pending()
        if len(self._parts) <= 1 or self._disjoint:
            return
        partial = concat_tables(self._parts)
        combine = [(f"{c}_{i}", self._COMBINE[i])
                   for c, i in self._chunk_aggs]
        comb = groupby_aggregate(partial, self.by, combine)
        from ..frame import DataFrame
        df = DataFrame(_table=comb).rename(
            {f"{c}_{i}_{self._COMBINE[i]}": f"{c}_{i}"
             for c, i in self._chunk_aggs})
        folded = df[self.by
                    + [f"{c}_{i}" for c, i in self._chunk_aggs]]._table
        from . import memory
        for reg in self._regs:
            memory.release(reg)
        self._parts = [folded]
        self._regs = [memory.register_table("sink_part", folded)]

    def snapshot(self) -> Table:
        """A consistent finalized aggregate over every chunk absorbed SO
        FAR, without disturbing the partials: pending deferred chunks
        are settled (they were already absorbed — settling is part of
        consumption, not a mutation), then the partials combine through
        the shared sink-combine path
        (:func:`cylon_tpu.relational.groupby.combine_sink_partials`)
        while staying adopted — the sink keeps absorbing afterwards.
        This is the streaming ``read()`` primitive
        (:mod:`cylon_tpu.stream.view`): snapshot(k batches) is bit-equal
        to finalize() of a fresh sink fed the same k batches."""
        return self._combine(drain=False)

    def finalize(self) -> Table:
        return self._combine(drain=True)

    def _combine(self, drain: bool) -> Table:
        from ..relational.groupby import combine_sink_partials
        self.flush_pending()
        if not self._parts:
            raise InvalidError("GroupBySink saw no chunks")
        partial = concat_tables(self._parts) if len(self._parts) > 1 \
            else self._parts[0]
        if drain:
            self._parts = []
            from . import memory
            for reg in self._regs:
                memory.release(reg)
            self._regs = []
        return combine_sink_partials(partial, self.by, self.aggs,
                                     self._chunk_aggs, self._COMBINE,
                                     ddof=self.ddof,
                                     disjoint=self._disjoint)


# ---------------------------------------------------------------------------
# scan-pushdown join: stream an out-of-core input straight into the loop
# ---------------------------------------------------------------------------

def pipelined_scan_join(scan, build: Table, scan_on, build_on,
                        how: str = "inner", suffixes=("_x", "_y"),
                        sink=None):
    """Feed a streaming scan (``io.scan_parquet_dist`` — row-group
    batches) DIRECTLY into the pipelined join/groupby loop: the build
    side shuffles ONCE and stays resident; each scan batch is admitted
    against the ledger, shuffled, joined against the resident build and
    consumed (``sink`` absorbs and the batch is released) — so the scan
    side never materializes at full size and the input of an
    out-of-core query never enters the ledger beyond one batch
    (asserted via ``memory.ledger().peak`` in tests/test_io.py).  This
    is the reference's read→partition→operate streaming stack (SURVEY
    §3.5, distributed_io.py:146) on the TPU pipeline.

    Completeness argument: batches partition the scan's ROWS, and every
    scan row's matches live entirely in the resident build — so
    ``inner`` and ``left`` (left = scan side) are complete per batch
    and their union over batches is the full join.  ``right``/``outer``
    would need cross-batch unmatched-build bookkeeping and are typed
    errors here (use :func:`pipelined_join` on a materialized read).
    Dictionary-encoded KEY columns are typed errors too: their codes
    are per-batch, so hash colocation against the once-shuffled build
    would silently diverge — numeric keys (the fact-table case) promote
    batch-independently and are supported."""
    from ..status import CylonIOError
    if how not in ("inner", "left"):
        raise InvalidError(
            "pipelined_scan_join supports how in ('inner','left'): "
            "right/outer need cross-batch unmatched-build bookkeeping — "
            "materialize the read and use pipelined_join instead")
    scan_on = [scan_on] if isinstance(scan_on, str) else list(scan_on)
    build_on = [build_on] if isinstance(build_on, str) else list(build_on)
    env = build.env
    from ..utils import timing
    from . import memory, scheduler
    with _plan.node("pipelined_scan_join", how=how,
                    sink=(type(sink).__name__ if sink is not None
                          else None)) as pn:
        bwork = None
        outs: list = []
        rows_in = 0
        n_batches = 0
        for batch in scan:
            _interleave()   # batch boundary = serving interleave point
            rows_in += batch.row_count
            n_batches += 1
            # per-batch key promotion against the (already promoted,
            # already shuffled) build columns: numeric promotion is
            # batch-independent, so the build side promotes exactly once
            bk = [batch.column(n) for n in scan_on]
            rk = [(build if bwork is None else bwork).column(n)
                  for n in build_on]
            pairs = [promote_key_pair(a, b) for a, b in zip(bk, rk)]
            if any(p.dictionary is not None for pair in pairs
                   for p in pair):
                raise InvalidError(
                    "pipelined_scan_join: dictionary-encoded join keys "
                    "are per-batch-coded and cannot hash-colocate "
                    "against a once-shuffled build — materialize the "
                    "read and use pipelined_join")
            batch = batch.with_columns(
                {n: p for n, (p, _) in zip(scan_on, pairs)})
            if bwork is None:
                bwork = build.with_columns(
                    {n: p for n, (_, p) in zip(build_on, pairs)})
                if env.world_size > 1:
                    bwork = shuffle_table(bwork, build_on)  # ONCE
                memory.register_table("scan_build", bwork)
            # ledger admission per batch (scheduler-mediated, TS109):
            # cold spillable owners evict — and, under a host budget,
            # demote — BEFORE the batch's rows land
            need = sum(int(c.data.nbytes)
                       + (int(c.validity.nbytes)
                          if c.validity is not None else 0)
                       for c in batch.columns.values())
            scheduler.admit_allocation(env, need)
            reg = memory.register_table("scan_batch", batch)
            if env.world_size > 1:
                batch = shuffle_table(batch, scan_on)
            with timing.region("pipe.scan_join"):
                res = join_tables(batch, bwork, scan_on, build_on,
                                  how=how, suffixes=suffixes,
                                  assume_colocated=True,
                                  allow_defer=(sink is not None))
            with timing.region("pipe.consume"):
                outs.append(sink(res) if sink is not None else res)
            memory.release(reg)
        if n_batches == 0:
            raise CylonIOError("pipelined_scan_join: the scan yielded "
                               "no batches")
        if pn:
            pn.set(rows_in=rows_in + build.row_count)
            pn.annotate(route="scan_pushdown", n_batches=n_batches)
        if sink is not None:
            return outs
        out = concat_tables(outs) if len(outs) > 1 else outs[0]
        if pn:
            pn.set(rows_out=out.row_count)
        return out


# ---------------------------------------------------------------------------
# range-partitioned pipelined join
# ---------------------------------------------------------------------------

def _key_op_kinds(dtypes: tuple, need_nf: tuple, narrow: tuple) -> tuple:
    """Static operand KIND tuple of pack.key_operands for this key
    structure — derived next to the packing rules it mirrors
    (ops/pack.key_operand_kinds, the single source of truth); the
    Pallas probe's eligibility gate reads it."""
    from ..ops.pack import key_operand_kinds
    return key_operand_kinds(dtypes, need_nf, narrow)


def _n_key_ops(dtypes: tuple, need_nf: tuple, narrow: tuple) -> int:
    """Static operand count of pack.key_operands for this key structure
    (liveness flag + per-column null flag + 1 or 2 value lanes)."""
    return len(_key_op_kinds(dtypes, need_nf, narrow))


@program_cache()
def _range_bounds_fn(mesh: Mesh, n_ranges: int, narrow: tuple,
                     need_nf: tuple, n_ops: int):
    """Per-shard range boundaries over the LOCALLY SORTED build side:
    candidate positions r*n/R snapped forward to the next key-group start
    (a key's whole run stays in one range), plus the splitter key operands
    at those positions.  A boundary at the live-prefix end (b == n) must
    read as "+infinity" so probe rows never route into the empty trailing
    ranges — each operand is extended by ONE explicit sentinel slot whose
    liveness flag is the pad key (a padding row would serve when n < cap,
    but at exact capacity, n == cap, there is none — gathering the last
    LIVE row there would silently strand that key's probe matches)."""
    from ..ops import pack

    def per_shard(vc, by_datas, by_valids):
        cap = by_datas[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        n = vc[my]
        mask = jnp.arange(cap) < n
        ko = pack.key_operands(list(by_datas), list(by_valids), row_mask=mask,
                               pad_key=PAD_L, need_null_flags=need_nf,
                               narrow32=narrow)
        bnd = pack.neighbor_flags(ko.ops, ko.kinds)
        pos = jnp.arange(cap, dtype=jnp.int32)
        first = (bnd != 0) | (pos == 0)
        imax = jnp.int32(2**31 - 1)
        nxt = jax.lax.cummin(jnp.where(first, pos, imax), reverse=True)
        cand = (jnp.arange(1, n_ranges, dtype=jnp.int32) * n) // n_ranges
        cand = jnp.clip(cand, 0, cap - 1)
        b = jnp.minimum(nxt[cand], n).astype(jnp.int32)
        sops = []
        for j, op in enumerate(ko.ops):
            sent = jnp.full((1,), PAD_L if j == 0 else 0, op.dtype)
            sops.append(jnp.concatenate([op, sent])[jnp.clip(b, 0, cap)])
        return (b,) + tuple(sops)

    return jit(shard_map(per_shard, mesh=mesh, in_specs=(REP, ROW, ROW),
                             out_specs=(ROW,) * (1 + n_ops)))


@program_cache()
def _probe_targets_fn(mesh: Mesh, n_ranges: int, narrow: tuple,
                      need_nf: tuple, n_ops: int, donate: bool = False,
                      use_pallas: bool = False):
    """Per-row range id for the probe side: count of splitters <= row key
    (>= because splitters are group STARTS of the sorted build).  Dead rows
    get id R so a stable sort by id puts them last.  Also returns per-shard
    per-range live counts.

    ``use_pallas`` routes the splitter probe through the Pallas kernel
    (ops/pallas_probe — splitters resident in SMEM, rows streamed in
    tiles; no (rows, splitters) comparison matrix in HBM); bit-equal to
    the XLA path by construction.  ``donate`` donates the splitter
    operand args (positions 3..3+n_ops) — their only consumer is this
    program, so the steady-state loop reuses their buffers."""
    from ..ops import pack

    def per_shard(vc, by_datas, by_valids, *sops):
        cap = by_datas[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        n = vc[my]
        mask = jnp.arange(cap) < n
        ko = pack.key_operands(list(by_datas), list(by_valids), row_mask=mask,
                               pad_key=PAD_L, need_null_flags=need_nf,
                               narrow32=narrow)
        if use_pallas:
            from ..ops import pallas_probe
            tgt = pallas_probe.count_ge_splitters(ko.ops, tuple(sops))
        else:
            ge = pack.rows_ge_splitters(ko, tuple(sops))
            # pinned accumulator: jnp.sum(bool) defaults to int64 under
            # x64 — a row-scale widening the jaxpr pass (JX203) flags
            tgt = jnp.sum(ge, axis=1, dtype=jnp.int32)
        tgt = jnp.where(mask, tgt, jnp.int32(n_ranges))
        counts = jnp.zeros(n_ranges + 1, jnp.int32).at[tgt].add(1)
        return tgt, counts[:n_ranges]

    in_specs = (REP, ROW, ROW) + (ROW,) * n_ops
    sm_kwargs = _norep_kwargs() if use_pallas else {}
    jit_kwargs = {"donate_argnums": tuple(range(3, 3 + n_ops))} \
        if donate else {}
    return jit(shard_map(per_shard, mesh=mesh, in_specs=in_specs,
                             out_specs=(ROW, ROW), **sm_kwargs),
                   **jit_kwargs)


def _pull_phase_outputs(devs: list):
    """ONE batched pull of the deferred setup-phase outputs (range
    boundaries + per-range probe counts) — the overlap scheduler's
    designated pre-loop sync point.  Every rank reaches it at the same
    program position (right after the probe-sort dispatch), so a fault
    raised by any deferred phase surfaces HERE, classified onto the
    typed taxonomy, never inside an arbitrary later sync.  The
    ``pipe.phase_sync`` injector site makes that contract testable on
    the CPU rig (tests/test_recovery.py)."""
    from ..utils.host import host_arrays
    from .recovery import maybe_inject
    maybe_inject("pipe.phase_sync")
    try:
        return host_arrays(devs)
    except Exception as e:  # noqa: BLE001 — re-raise typed when classifiable
        from .recovery import classify
        fault = classify(e)
        if fault is None:
            raise
        raise fault from e


class _PieceFuture:
    """One range piece's phase work (packed window descriptors; the
    seed's materialized windows; for spilled sources the async window
    uploads) dispatched AHEAD of its consumption.  A typed fault raised
    while dispatching ahead (piece-cap overflow, injected spill
    pressure) is HELD and re-raised when the piece is CONSUMED — the
    identical consensus-coherent point the non-overlapped schedule
    raises at, so the recovery ladder takes the same rung at the same
    piece with overlap on or off.  Foreign (non-taxonomy) exceptions
    raise immediately: deferring an unclassified error would detach it
    from its dispatch context."""

    __slots__ = ("_pieces", "_fault")

    def __init__(self, thunk, defer_faults: bool = True):
        self._pieces = self._fault = None
        if not defer_faults:
            self._pieces = thunk()
            return
        try:
            self._pieces = thunk()
        except CylonError as e:
            self._fault = e

    def get(self):
        if self._fault is not None:
            raise self._fault
        return self._pieces


def pipelined_join(left: Table, right: Table, left_on, right_on,
                   how: str = "inner", n_chunks: int = 4,
                   suffixes=("_x", "_y"), sink=None):
    """Range-partitioned streaming join (reference DisJoinOP, re-thought
    twice).  The naive streaming form — probe chunks against the full
    resident build — re-sorts the build side per chunk (measured 7.5x below
    the monolith at 96M rows/side).  Instead both sides shuffle once and
    the work tiles over KEY RANGES:

      1. sort the build side ONCE per shard (keys are hash-colocated, so
         ranges are per-shard state — no cross-shard splitter agreement);
      2. snap R-1 evenly spaced positions forward to key-group starts:
         a key's entire build run lives in exactly one range;
      3. assign each probe row its range (vectorized >=-splitters pass) and
         stable-sort the probe side by range id ONCE (columns ride as u32
         lanes);
      4. join range piece pairs — contiguous windows of the two resident
         sorted tables — with the standard two-phase local kernel.

    Total sort work is ~2x the monolith (vs C-times for the naive form)
    while each piece's sort scratch and output stay 1/R-sized.  Because
    ranges partition the KEY space, every join type is complete per piece:
    inner/left/right/outer all stream (an unmatched build row's probe
    matches could only be in its own range — no cross-chunk bookkeeping).

    Note: pieces shuffle with plain hashing — the adaptive skew-split
    plan (relational/skew.py, docs/skew.md) is not applied to the range
    loop's pre-shuffle: range boundaries snap to key-group starts, so a
    salted heavy key would straddle a range's rank group and break the
    per-piece completeness contract every join type stands on (and the
    key-disjoint sink fast path with it).  An extreme single-key
    distribution therefore still concentrates one RANGE's piece on one
    shard — use the monolithic ``join_tables`` for skewed keys, where
    the split + stitch route engages; under EXPLAIN ANALYZE the probe
    side's heavy-hitter profile (``est_rows_per_rank``) is attached to
    this node so the exposure is visible in plan diffs.

    ``sink``: the downstream operator of the pipeline (the reference's next
    ``Op`` in the DAG).  When given, each output piece is passed to
    ``sink(piece_table)`` and immediately released — peak memory is ONE
    piece's output — and the list of sink results is returned.  Piece joins
    then also DEFER (relational/join.py), so a groupby sink on the join
    keys consumes each piece's pre-expansion fused state.  Without a sink
    the pieces are concatenated into one Table (which necessarily holds
    the full output twice during assembly; use a sink for outputs near
    HBM capacity).
    """
    if how not in ("inner", "left", "right", "outer"):
        raise InvalidError(
            "pipelined_join supports how in ('inner','left','right','outer')")
    with _plan.node("pipelined_join", how=how, n_chunks=int(n_chunks),
                    sink=(type(sink).__name__ if sink is not None
                          else None)) as pn:
        if pn:
            pn.set(rows_in=left.row_count + right.row_count)
            # heavy-hitter exposure of the PROBE side (analyze mode
            # only) — the right table for how='right', matching the
            # skew route's probe choice: the pipelined route has no
            # skew split, so the profile's est_rows_per_rank is the
            # "why not this plan" evidence in explain.py diffs
            # (docs/skew.md)
            probe, probe_on = (right, right_on) if how == "right" \
                else (left, left_on)
            po = [probe_on] if isinstance(probe_on, str) else list(probe_on)
            _plan.profile_keys(pn, probe, po)
        res = _pipelined_join_impl(left, right, left_on, right_on, how,
                                   n_chunks, suffixes, sink, pn)
        if pn and type(res) is Table:
            pn.set(rows_out=res.row_count)
        return res


def _pipelined_join_impl(left: Table, right: Table, left_on, right_on,
                         how: str, n_chunks: int, suffixes, sink, pn):
    env = check_same_env(left, right)
    left_on = [left_on] if isinstance(left_on, str) else list(left_on)
    right_on = [right_on] if isinstance(right_on, str) else list(right_on)

    # promote once so every piece shares dictionaries/dtypes with the build
    lkey, rkey = [], []
    for ln, rn in zip(left_on, right_on):
        a, b = promote_key_pair(left.column(ln), right.column(rn))
        lkey.append(a)
        rkey.append(b)
    lwork = left.with_columns(dict(zip(left_on, lkey)))
    rwork = right.with_columns(dict(zip(right_on, rkey)))

    if (sink is not None and isinstance(sink, GroupBySink)
            and left_on == right_on and list(sink.by) == list(left_on)):
        # ranges partition the join-key space, so a groupby sink keyed on
        # the join keys sees each group in exactly one piece
        sink.mark_key_disjoint()

    if env.world_size > 1:
        rwork = shuffle_table(rwork, right_on)   # build side: ONCE
        lwork = shuffle_table(lwork, left_on)    # probe side: ONCE

    n_ranges = max(int(n_chunks), 1)
    if n_ranges == 1 or rwork.row_count == 0 or lwork.row_count == 0:
        if pn:
            pn.annotate(route="monolithic")
        res = join_tables(lwork, rwork, left_on, right_on, how=how,
                          suffixes=suffixes, assume_colocated=True,
                          allow_defer=False)
        return [sink(res)] if sink is not None else res

    from ..relational.sort import local_sort_table
    from ..utils import timing
    # Phase-overlapped scheduling (CYLON_TPU_PACKED_OVERLAP, docs/
    # pipeline.md): the setup phases below — build sort, range bounds,
    # probe targets, probe sort — chain purely on device arrays; nothing
    # between them needs a host value.  With overlap on, each phase is a
    # plain async dispatch and the two host-side sidecars (range
    # boundaries, per-range probe counts) stay ON DEVICE until the one
    # designated sync point after the probe-sort dispatch, where a single
    # batched pull resolves both — the DeferredTable counts-on-device
    # trick (PR 2's join count phase) generalized to every setup phase.
    # Off restores the prior pull-per-phase dispatch behavior.
    overlap = config.PACKED_OVERLAP
    donate = config.DONATE_BUFFERS
    # The phase-1 sorts may donate their input buffers ONLY when those
    # buffers are fresh shuffle outputs this function exclusively owns
    # (world > 1).  At world == 1 lwork/rwork are with_columns views
    # SHARING buffers with the caller's tables — donating them would
    # invalidate user data (use-after-donate, lint rule TS108).
    donate_sort = donate and env.world_size > 1
    with timing.region("pipe.build_sort"):
        rsorted = local_sort_table(rwork, right_on, donate=donate_sort)
        # hash shuffle above co-located equal keys; the per-shard sort
        # makes them contiguous — together that is grouped_by's contract
        rsorted.grouped_by = tuple(right_on)
        timing.maybe_block(next(iter(rsorted.columns.values())).data)
    del rwork
    w = env.world_size

    l_keys = [lwork.column(n) for n in left_on]
    r_keys = [rsorted.column(n) for n in right_on]
    need_nf = tuple((a.validity is not None) or (b.validity is not None)
                    for a, b in zip(l_keys, r_keys))
    from ..relational.common import narrow32_flags
    narrow = narrow32_flags(l_keys, r_keys)
    key_dtypes = tuple(str(c.data.dtype) for c in r_keys)
    op_kinds = _key_op_kinds(key_dtypes, need_nf, narrow)
    n_ops = len(op_kinds)

    from ..relational.common import col_arrays
    from ..utils.host import host_array
    r_datas, r_valids = col_arrays(r_keys)
    vcr = np.asarray(rsorted.valid_counts, np.int32)
    with timing.region("pipe.bounds"):
        res = _range_bounds_fn(env.mesh, n_ranges, narrow, need_nf, n_ops)(
            vcr, r_datas, r_valids)
        b_dev = res[0]
        if not overlap:
            b_host = host_array(b_dev)
    sops = res[1:]

    l_datas, l_valids = col_arrays(l_keys)
    vcl = np.asarray(lwork.valid_counts, np.int32)
    use_pallas = False
    if config.PALLAS_PROBE:
        from ..ops import pallas_probe
        use_pallas = pallas_probe.supported(lwork.capacity, n_ranges - 1,
                                            op_kinds)
    with timing.region("pipe.targets"):
        # sops' only consumer — donated so the loop's steady state reuses
        # their buffers instead of re-allocating per query
        tgt, pc_flat = _probe_targets_fn(env.mesh, n_ranges, narrow, need_nf,
                                         n_ops, donate=donate,
                                         use_pallas=use_pallas)(
            vcl, l_datas, l_valids, *sops)
        if not overlap:
            pc_host = host_array(pc_flat)
    del sops

    from ..core.dtypes import LogicalType
    tmp = "__range__"
    while tmp in lwork:
        tmp += "_"
    ltab = lwork.with_columns(
        {tmp: Column(tgt, LogicalType.INT32, None, bounds=(0, n_ranges))})
    del lwork, tgt
    with timing.region("pipe.probe_sort"):
        # ltab's buffers (fresh shuffle outputs + the fresh range column)
        # are last read here — donated, the sorted output reuses them
        del l_datas, l_valids, l_keys
        lsorted = local_sort_table(ltab, [tmp], donate=donate_sort)
        timing.maybe_block(next(iter(lsorted.columns.values())).data)
    del ltab

    if overlap:
        # THE pre-loop host sync: every setup phase above was dispatched
        # with no intervening pull, so the device executes them as one
        # uninterrupted stream while the host raced ahead to here.
        with timing.sync_region("pipe.phase_sync"):
            b_host, pc_host = _pull_phase_outputs([b_dev, pc_flat])
    b = np.asarray(b_host).reshape(w, n_ranges - 1).astype(np.int64)
    pcounts = np.asarray(pc_host).reshape(w, n_ranges).astype(np.int64)
    n_r = vcr.astype(np.int64)
    bb = np.concatenate([np.zeros((w, 1), np.int64), b, n_r[:, None]], axis=1)
    r_starts = bb[:, :-1]
    r_lens = np.diff(bb, axis=1)
    l_starts = np.concatenate([np.zeros((w, 1), np.int64),
                               np.cumsum(pcounts, axis=1)], axis=1)[:, :-1]

    # all per-range pow2 piece capacities are host-known UP FRONT — the
    # static shape family of every slice/join program the loop will need
    caps_l = [config.pow2ceil(max(int(pcounts[:, r].max()), 1))
              for r in range(n_ranges)]
    caps_r = [config.pow2ceil(max(int(r_lens[:, r].max()), 1))
              for r in range(n_ranges)]
    if pn:
        # the plan-facing piece geometry: route + chunking + dispatch
        # rungs — the static attrs EXPLAIN prints for this node
        pn.annotate(route="range_pipeline", n_ranges=n_ranges,
                    max_cap_l=max(caps_l), max_cap_r=max(caps_r),
                    packed=bool(config.PACKED_PIECES),
                    overlap=bool(overlap), donate=bool(donate))

    # piece-cap-sizing consult of the HBM ledger (exec/memory): admission
    # of the packed sources accounts for the transient sort-operand set
    # the largest piece pair will materialize on top of the resident
    # matrices; under budget pressure, COLD spillable owners evict first
    # (collectively — docs/robustness.md) before the pack allocates
    from ..ops.pack import sort_operand_nbytes
    scratch = sort_operand_nbytes(
        key_dtypes, need_nf, narrow, (max(caps_l) + max(caps_r)) * w)
    with timing.region("pipe.pack"):
        # the sorted tables are exclusively owned here (fresh sort
        # outputs, deleted right below) — donate their columns into the
        # pack programs so the lane matrices reuse those buffers, with
        # the ledger crediting the reuse (exec/memory, docs/pipeline.md)
        src_l = PieceSource(lsorted, max(caps_l), drop=(tmp,),
                            scratch_bytes=scratch, donate=donate)
        src_r = PieceSource(rsorted, max(caps_r), scratch_bytes=scratch,
                            donate=donate)
        timing.maybe_block(src_r.arrs)
    del lsorted, rsorted
    if pn:
        pn.annotate(spilled=bool(src_l.spilled or src_r.spilled))

    packed = config.PACKED_PIECES

    def make_pieces(r):
        """Pieces for range r: packed window descriptors (free — the
        slice+unpack runs inside the join program) or, with the packed
        path disabled, the seed's materialized window tables."""
        if packed:
            return (src_l.packed(l_starts[:, r], pcounts[:, r], caps_l[r]),
                    src_r.packed(r_starts[:, r], r_lens[:, r], caps_r[r]))
        with timing.region("pipe.piece_slice"):
            piece_l = src_l.piece(l_starts[:, r], pcounts[:, r])
            piece_r = src_r.piece(r_starts[:, r], r_lens[:, r])
            timing.maybe_block(next(iter(piece_r.columns.values())).data)
        return piece_l, piece_r

    def qualifies(r):
        any_l = pcounts[:, r].sum() > 0
        any_r = r_lens[:, r].sum() > 0
        return {"inner": any_l and any_r, "left": any_l,
                "right": any_r, "outer": any_l or any_r}[how]

    live_ranges = [r for r in range(n_ranges) if qualifies(r)]

    # ---- durable checkpoint stage (exec/checkpoint) ---------------------
    # Armed only when CYLON_TPU_CKPT_DIR is set — otherwise `stage` stays
    # None and this path adds zero filesystem writes and zero extra
    # collectives.  The plan token pins the stage's static plan; a resume
    # restores committed pieces bit-identically and fast-forwards the
    # loop past them (a corrupt page degrades to recomputing the stage's
    # remaining pieces, never to a wrong answer).
    from . import checkpoint as ckpt
    stage = None
    if (ckpt.enabled() and live_ranges
            and (sink is None or isinstance(sink, GroupBySink))):
        # the consumption MODE is part of the plan: a sink stage
        # checkpoints partial aggregates, a sinkless one piece outputs —
        # restoring one as the other would splice wrong-shaped state in.
        # The token is SPLIT (docs/robustness.md "Elastic resume"): the
        # base names the workload (world-invariant — nothing derived
        # from the shard layout), the full token folds in world size,
        # piece capacities and per-range counts.  A resume matching only
        # the base at a different topology takes the re-shard path.
        mode = ("nosink", tuple(suffixes)) if sink is None else \
            ("sink", tuple(sink.by), tuple(sink._chunk_aggs), sink.ddof)
        # the base carries a world-INVARIANT data fingerprint too — the
        # global live row totals of both sides (per-range counts are
        # layout-derived, their sums are not): without it an elastic
        # resume over CHANGED inputs would base-match a stale
        # checkpoint and adopt another dataset's answers, the guard the
        # same-world full token already provides
        base = ckpt.plan_token("pipelined_join", how, tuple(left_on),
                               tuple(right_on), n_ranges, mode,
                               int(pcounts.sum()), int(r_lens.sum()))
        token = ckpt.plan_token(
            base, w, tuple(caps_l), tuple(caps_r),
            tuple(int(x) for x in pcounts.sum(axis=0)),
            tuple(int(x) for x in r_lens.sum(axis=0)))
        stage = ckpt.open_stage(env, "pipelined_join", token,
                                base_token=base)
        if pn:
            pn.annotate(ckpt=True)
        if isinstance(sink, GroupBySink):
            sink.attach_checkpoint(stage)

    start = 0
    outs = []
    adopted_whole = False
    if stage is not None and ckpt.resume_requested():
        from ..status import CheckpointCorruptError, DataIntegrityError
        from . import recovery
        restored: list = []
        foreign = stage.foreign is not None
        if stage.resuming:
            while (len(restored) < len(live_ranges)
                   and stage.has_piece(len(restored))):
                try:
                    restored.append(stage.load_piece(len(restored)))
                except (CheckpointCorruptError, DataIntegrityError) as e:
                    # an armed manifest-fingerprint miss degrades exactly
                    # like page corruption: recompute, never adopt
                    ckpt.corrupt_fallback(stage, len(restored), e)
                    break
        elif foreign and stage.foreign_complete:
            # world-mismatch re-shard: the WHOLE stage (and only a whole
            # stage — old-layout pieces have no expressible complement
            # in the new layout) is adopted, stitched and re-blocked
            # onto this mesh; any corruption degrades to recompute
            try:
                restored = stage.load_foreign_pieces()
            except (CheckpointCorruptError, DataIntegrityError) as e:
                ckpt.corrupt_fallback(stage, len(restored), e)
                restored = []
        # rank-coherent fast-forward: every rank adopts the MINIMUM
        # restorable prefix across ranks (one vote per stage; entered by
        # every rank whenever resume is requested, even with nothing
        # restorable locally — including ranks that have no own rank dir
        # because the world GREW) — a rank-local fallback would leave
        # the recomputing rank alone in the per-piece commit collectives
        # below
        start = recovery.ckpt_resume_consensus(getattr(env, "mesh", None),
                                               len(restored))
        if foreign:
            # all-or-nothing: a rank that verified fewer foreign pieces
            # degrades EVERY rank's adoption to recompute (foreign
            # restores were not yet counted, so nothing to unrestore)
            if start != len(restored) or not restored:
                start = 0
                restored = []
            else:
                ckpt.note_reshard(start)
                adopted_whole = True
                # first post-reshard commit: rewrite the adopted state
                # under THIS topology's layout token at the next
                # manifest generation — the second resume at this world
                # is then a plain fast-forward, and the old world's
                # leftover rank dirs read as stale forever
                stage.begin_rewrite()
                for i, tbl in enumerate(restored):
                    stage.save_piece(i, tbl)
                stage.mark_complete()
                start = len(live_ranges)   # the whole piece loop is done
        elif len(restored) > start:
            ckpt.unrestore(len(restored) - start)
        for tbl in restored[:(len(restored) if adopted_whole else start)]:
            if sink is not None:
                sink.restore_partial(tbl)
                outs.append(None)   # a GroupBySink call returns None too
            else:
                outs.append(tbl)

    if packed and live_ranges[start:]:
        # pre-warm: with the capacities known, every distinct join
        # program can AOT-compile BEFORE the range loop (while the probe
        # sort still occupies the device) instead of stalling dispatch
        # mid-stream.  No-op where the persistent compile cache is off.
        from ..relational.join import prewarm_packed_join
        warmed = set()
        for r in live_ranges[start:]:
            # the program's static key includes the all-live class (lens
            # exactly at capacity drops the liveness operand), not just
            # the capacity pair — dedupe on the same signature
            key = (caps_l[r], caps_r[r],
                   bool((pcounts[:, r] == caps_l[r]).all()
                        and (r_lens[:, r] == caps_r[r]).all()))
            if key in warmed:
                continue
            warmed.add(key)
            pl0, pr0 = make_pieces(r)
            prewarm_packed_join(pl0, pr0, left_on, right_on, how, suffixes,
                                allow_defer=(sink is not None))

    def _prefetch_ok(r) -> bool:
        """Double-buffer the NEXT piece's host→device uploads against
        this piece's compute when a source is host-resident (spilled):
        upload of piece r+1's window overlaps compute of piece r.  The
        prefetch depth consults the ledger — a budget too tight for two
        window pairs falls back to single-buffering (exec/memory).
        Resident sources skip the prefetch: descriptors are free, and
        creating them early would only reorder CapacityOverflow checks."""
        if not (packed and (src_l.spilled or src_r.spilled)):
            return False
        from . import memory
        pair = w * (caps_l[r] * memory.spec_row_bytes(src_l.spec)
                    + caps_r[r] * memory.spec_row_bytes(src_r.spec))
        return memory.prefetch_depth(pair) > 1

    def piece_future(r):
        # with overlap on, a typed fault raised while dispatching piece
        # r's phases ahead of time is held and re-raised at r's consume
        # point (_PieceFuture) — the recovery ladder sees the identical
        # escalation order as the non-overlapped schedule
        return _PieceFuture(lambda: make_pieces(r), defer_faults=overlap)

    nxt = piece_future(live_ranges[start]) if live_ranges[start:] else None
    for i in range(start, len(live_ranges)):
        # flight-recorder lifecycle (obs/trace, armed runs only): a
        # dispatch span per piece — paired with the sink's async
        # in-flight span, the Perfetto timeline shows piece r+1's
        # dispatch overlapping piece r's consume
        trace_armed = _trace.armed()   # process-uniform (env-armed)
        t_disp = _time.perf_counter() if trace_armed else 0.0
        piece_l, piece_r = nxt.get()
        nxt = None
        if i + 1 < len(live_ranges) and _prefetch_ok(live_ranges[i + 1]):
            # async upload dispatch for piece r+1 (spilled sources) —
            # overlaps the join compute of piece r below
            nxt = piece_future(live_ranges[i + 1])
        with timing.region("pipe.piece_join"):
            # packed pieces: slice + key unpack are fused into this
            # dispatch; with a sink the counts stay on device, so piece
            # r+1's programs enqueue before piece r's host sync (the
            # one-deep software pipeline now spans the WHOLE piece chain)
            res_r = join_tables(piece_l, piece_r, left_on, right_on,
                                how=how, suffixes=suffixes,
                                assume_colocated=True,
                                allow_defer=(sink is not None))
        if trace_armed:
            _trace.complete("pipe.piece_dispatch", t_disp,
                            piece=int(live_ranges[i]))
        with timing.region("pipe.consume"):
            out_r = sink(res_r) if sink is not None else res_r
        if stage is not None and sink is None:
            # sinkless stage boundary: the piece output IS the
            # completed-piece state (a GroupBySink checkpoints its own
            # partials at adoption instead)
            stage.save_piece(i, res_r)
        outs.append(out_r)
        if stage is not None and ckpt.drain_requested(env):
            # preemption grace (exec/preempt): a SIGTERM arrived and the
            # drain vote agreed — the vote must guard the abort on every
            # path (reordering fails the CX403 gate); this piece
            # boundary is the planned
            # exit.  Pending sink chunks settle first (their partials
            # commit), then the typed ResumableAbort carries the resume
            # token out; the relaunch fast-forwards everything committed
            # inside the grace window, re-sharding if the world changed.
            if isinstance(sink, GroupBySink):
                sink.flush_pending()
            ckpt.drain_abort("pipelined_join")
        if nxt is None and i + 1 < len(live_ranges):
            # piece r+1's phase dispatch overlaps piece r's in-flight
            # consumption (the sink's pending pull / deferred counts)
            nxt = piece_future(live_ranges[i + 1])
        # piece boundary = the serving tier's interleave point: piece
        # r's consume (and r+1's dispatch-ahead) are in flight on the
        # device while another tenant's piece enqueues
        _interleave()
    if not outs:
        # no range qualified (e.g. inner join, no overlapping keys at all):
        # one empty piece pair keeps the output schema path uniform
        zeros = np.zeros(w, np.int64)
        if packed:
            piece_l = src_l.packed(zeros, zeros, 1)
            piece_r = src_r.packed(zeros, zeros, 1)
        else:
            piece_l = src_l.piece(zeros, zeros)
            piece_r = src_r.piece(zeros, zeros)
        res_r = join_tables(piece_l, piece_r, left_on, right_on, how=how,
                            suffixes=suffixes, assume_colocated=True,
                            allow_defer=False)
        outs.append(sink(res_r) if sink is not None else res_r)
    if stage is not None:
        # mark the stage COMPLETE (one manifest commit): a later resume
        # at a DIFFERENT topology may only adopt whole stages, and this
        # flag is how it tells a finished stage from a crash prefix.
        # Pending sink chunks settle first so the durable set covers
        # every consumed chunk.
        if isinstance(sink, GroupBySink):
            sink.flush_pending()
        stage.mark_complete()
    if sink is not None:
        return outs
    out = concat_tables(outs) if len(outs) > 1 else outs[0]
    from . import integrity as _integrity
    if _integrity.armed():
        # armed audit (exec/integrity): vote the assembled pipeline
        # output's order-invariant fingerprint rank-coherently at the
        # stage boundary — a rank that stitched different bytes (a
        # corrupted piece that slipped past the per-exchange checks)
        # surfaces typed here instead of as a silently diverged answer
        _integrity.audit_table(out, site="pipe.stitch",
                               phase="post_pipeline")
    if left_on == right_on and not adopted_whole:
        # pieces are key-grouped (sorted merge order) in key-range order and
        # hash-colocated: the concatenation keeps the grouped contract —
        # EXCEPT for state adopted across a topology change, whose rows
        # were re-blocked in global order (per-shard key contiguity and
        # hash colocation are both gone; consumers re-derive)
        out.grouped_by = tuple(left_on)
    return out

# ---------------------------------------------------------------------------
# trace-safety declarations (cylon_tpu.analysis.registry): the pipeline's
# own programs are pure-local shard programs — slicing, key-operand
# packing and prefix scans; the exchanges happen upstream in
# parallel/shuffle.py.  docs/trace_safety.md.
# ---------------------------------------------------------------------------

def _trace_chunk(mesh):
    w = int(mesh.devices.size)
    S = jax.ShapeDtypeStruct
    fn = _unwrap(_chunk_fn(mesh, 1024, 256))
    datas = (S((w * 1024,), np.int64), S((w * 1024,), np.float64))
    valids = (S((w * 1024,), np.bool_), None)
    return jax.make_jaxpr(fn)(S((), np.int32), datas, valids)


def _trace_range_bounds(mesh):
    w = int(mesh.devices.size)
    S = jax.ShapeDtypeStruct
    n_ops = _n_key_ops(("int32",), (False,), (False,))
    fn = _unwrap(_range_bounds_fn(mesh, 4, (False,), (False,), n_ops))
    vc = S((w,), np.int32)
    return jax.make_jaxpr(fn)(vc, (S((w * 1024,), np.int32),), (None,))


def _trace_probe_targets(mesh):
    w = int(mesh.devices.size)
    S = jax.ShapeDtypeStruct
    n_ranges = 4
    n_ops = _n_key_ops(("int32",), (False,), (False,))
    fn = _unwrap(_probe_targets_fn(mesh, n_ranges, (False,), (False,),
                                   n_ops))
    vc = S((w,), np.int32)
    sops = tuple(S((w * (n_ranges - 1),), np.int32) for _ in range(n_ops))
    return jax.make_jaxpr(fn)(vc, (S((w * 1024,), np.int32),), (None,),
                              *sops)


def _trace_probe_targets_pallas(mesh):
    """The ``CYLON_TPU_PALLAS_PROBE`` dispatch variant: identical
    contract, the splitter probe routed through the Pallas kernel
    (ops/pallas_probe).  Still a pure-local program — the jaxpr walk
    recurses into the pallas_call body, so a collective smuggled into
    the kernel would be a JX205 finding like anywhere else."""
    w = int(mesh.devices.size)
    S = jax.ShapeDtypeStruct
    n_ranges = 4
    n_ops = _n_key_ops(("int32",), (False,), (False,))
    fn = _unwrap(_probe_targets_fn(mesh, n_ranges, (False,), (False,),
                                   n_ops, use_pallas=True))
    vc = S((w,), np.int32)
    sops = tuple(S((w * (n_ranges - 1),), np.int32) for _ in range(n_ops))
    return jax.make_jaxpr(fn)(vc, (S((w * 1024,), np.int32),), (None,),
                              *sops)


from ..analysis.registry import declare_builder, unwrap as _unwrap  # noqa: E402

declare_builder(f"{__name__}._chunk_fn", _trace_chunk, tags=("pipeline",))
declare_builder(f"{__name__}._range_bounds_fn", _trace_range_bounds,
                tags=("pipeline",))
declare_builder(f"{__name__}._probe_targets_fn", _trace_probe_targets,
                tags=("pipeline",))
declare_builder(f"{__name__}._probe_targets_fn[pallas]",
                _trace_probe_targets_pallas, tags=("pipeline",))
