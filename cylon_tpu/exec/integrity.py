"""End-to-end data-integrity audit tier — THE facade (lint rule TS118).

Every at-rest byte in the engine is sha256-verified (spill pages, disk
tier, checkpoint pages, the compile cache), but data IN FLIGHT — through
:func:`cylon_tpu.parallel.shuffle.exchange`, the two-hop topo route,
skew-split stitches and piece-loop partials — historically had no
runtime integrity story: a wrong-route bug, a miscounted sidecar or a
corrupted buffer produced a silently wrong answer, which the "never a
wrong answer" contract forbids.  This module is that story, in three
layers, each inert until armed:

1. **Conservation laws (always on).**  Every exchange already pulls the
   (W, W) count sidecar to the host; :func:`conserve_exchange` asserts
   rows-sent == rows-received per (src, dst) — non-negative counts,
   column sums equal to the returned per-destination vector, the grand
   total equal to the logical row total — and reconciles the running
   totals against the ``exchange_rows_total``/``exchange_bytes_total``
   registry counters.  Pure host arithmetic on an already-pulled array:
   zero extra device work, zero syncs, zero collectives.  The two-hop
   route adds :func:`conserve_hops` over its hop count matrices.

2. **Order-invariant fingerprints (``CYLON_TPU_AUDIT=1``).**  A
   registered jaxpr-gated builder (:func:`_fingerprint_fn`) computes a
   64-bit content fingerprint per mesh: a commutative XOR mix of
   per-row hashes over every key+payload lane (validity bits included,
   padding rows masked to the XOR identity), reduced within each shard
   and folded across the mesh with one ``all_gather`` — so the
   fingerprint is REPLICATED and invariant to row order and row
   placement.  Verified at stage boundaries: post-exchange
   (:func:`verify_exchange` — fingerprint conservation, inputs XOR ==
   outputs XOR), post-stitch for skew-split plans, per absorbed stream
   batch (:func:`audit_table`), and recorded into checkpoint manifests
   (:func:`table_fingerprint`) so a resume audits adopted foreign
   pieces beyond their page shas.  In multiprocess sessions every
   fingerprint rides the double-polarity consensus wire
   (:func:`cylon_tpu.exec.recovery.fingerprint_consensus`) before any
   raise/proceed decision — the rank-coherence invariant.

3. **Recovery.**  A violation raises typed :class:`DataIntegrityError`
   (``site=``, ``phase=``) through the classify path; the ladder's
   ``Code.IntegrityFault`` rung recomputes the affected stage ONCE
   (mirroring the disk-corruption rung) and escalates to a typed abort
   on repeat — corruption degrades to recompute, never a wrong answer.

Overhead contract: the unarmed happy path is the always-on host math
plus one cached env read — zero extra collectives, zero host syncs,
zero writes (asserted by ``scripts/chaos_soak.py --audit``); the armed
path is one extra compiled program + one host pull + one 4-round vote
per audited boundary (≤10 % on the default pipelined CPU config,
``bench_detail``'s ``audit`` block carries the counts).

TS118: fingerprint computation and ``DataIntegrityError`` raises are
THIS module's exclusive business — call sites in ``relational/``,
``parallel/`` and ``topo/`` invoke the verb-named wrappers here
(``conserve_*``, ``verify_*``, ``audit_*``, ``flip_one``) and never
hash, vote or raise themselves (docs/trace_safety.md).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ctx.context import ROW_AXIS
from ..obs import metrics as _metrics
from ..ops import hashing
from ..status import DataIntegrityError
from ..utils.cache import jit, program_cache

shard_map = jax.shard_map

_STATS = _metrics.group("audit", (
    "conservation_checks", "fingerprint_checks", "fingerprint_votes",
    "violations", "rows_reconciled", "bytes_reconciled",
    "reconcile_resyncs", "manifest_fps", "manifest_audits",
    "corruptions_injected"))


def stats() -> dict:
    return dict(_STATS)


def reset_stats() -> None:
    for k in _STATS:
        _STATS[k] = 0
    # an audit-stats reset is NOT a registry reset: re-seed the
    # reconcile mirror from the live exchange counters, else the next
    # conservation check would see them "running ahead" and raise
    _STATS["rows_reconciled"] = _metrics.counter(
        "exchange_rows_total").value
    _STATS["bytes_reconciled"] = _metrics.counter(
        "exchange_bytes_total").value


# ---------------------------------------------------------------------------
# arming
# ---------------------------------------------------------------------------

#: [None = env unread, else the cached bool] — one list load on the
#: unarmed happy path (the same contract as metrics' snapshot poll)
_ARMED: list = [None]


def armed() -> bool:
    """True while ``CYLON_TPU_AUDIT=1`` arms the fingerprint layer.
    Cached after the first read; :func:`rearm` re-reads (tests, and the
    multihost driver arming legs mid-process)."""
    a = _ARMED[0]
    if a is None:
        a = _ARMED[0] = os.environ.get("CYLON_TPU_AUDIT", "") not in ("", "0")
    return a


def rearm() -> None:
    _ARMED[0] = None


# ---------------------------------------------------------------------------
# layer 1: conservation laws — pure host math on the count sidecar
# ---------------------------------------------------------------------------

def conserve_exchange(counts, per_dest, total: int, row_bytes: int, *,
                      site: str = "shuffle.recv",
                      phase: str = "post_exchange") -> None:
    """Always-on conservation check over one exchange's (W, W) count
    sidecar: every row some source rank sent must be received by exactly
    the destination the sidecar names.  Raises typed
    :class:`DataIntegrityError` on violation (classified: the ladder
    recomputes the stage once).  Also reconciles the running logical
    totals against the ``exchange_rows_total``/``exchange_bytes_total``
    registry counters — a route that moves rows without accounting them
    (or accounts rows it never moved) surfaces here instead of silently
    skewing the comm model.  A registry reset between exchanges (bench
    iterations) re-syncs instead of raising: only the counters running
    AHEAD of the audited exchanges is a drift."""
    _STATS["conservation_checks"] += 1
    c = np.asarray(counts)
    pd = np.asarray(per_dest)
    bad = None
    if c.ndim != 2 or c.shape[0] != c.shape[1]:
        bad = f"count sidecar shape {c.shape} is not (W, W)"
    elif (c < 0).any():
        s, d = np.argwhere(c < 0)[0]
        bad = f"negative count {int(c[s, d])} at (src={s}, dst={d})"
    elif not np.array_equal(c.sum(axis=0), pd):
        col = c.sum(axis=0)
        d = int(np.argwhere(col != pd)[0][0])
        bad = (f"rows-received mismatch at dst={d}: sidecar column sum "
               f"{int(col[d])} != delivered {int(pd[d])}")
    elif int(c.sum()) != int(total):
        bad = (f"rows-sent total {int(c.sum())} != logical row total "
               f"{int(total)}")
    if bad is not None:
        _STATS["violations"] += 1
        raise DataIntegrityError(
            f"exchange conservation law violated at {site}: {bad}",
            site=site, phase=phase)
    _STATS["rows_reconciled"] += int(total)
    _STATS["bytes_reconciled"] += int(total) * int(row_bytes)
    rows_seen = _metrics.counter("exchange_rows_total").value
    bytes_seen = _metrics.counter("exchange_bytes_total").value
    if (_STATS["rows_reconciled"] == rows_seen
            and _STATS["bytes_reconciled"] == bytes_seen):
        return
    if (rows_seen < _STATS["rows_reconciled"]
            or bytes_seen < _STATS["bytes_reconciled"]):
        # the exchange counters went backwards relative to the audit
        # mirror: a registry reset happened between exchanges — re-sync
        _STATS["rows_reconciled"] = rows_seen
        _STATS["bytes_reconciled"] = bytes_seen
        _STATS["reconcile_resyncs"] += 1
        return
    _STATS["violations"] += 1
    raise DataIntegrityError(
        f"exchange counter reconciliation failed at {site}: "
        f"exchange_rows_total={rows_seen} / exchange_bytes_total="
        f"{bytes_seen} ran ahead of the audited sidecar totals "
        f"({_STATS['rows_reconciled']} rows / "
        f"{_STATS['bytes_reconciled']} B) — a route moved or counted "
        "rows outside the audited exchange path",
        site=site, phase=phase)


def conserve_hops(counts, c1, c2, *, site: str = "topo.exchange",
                  phase: str = "post_exchange") -> None:
    """The two-hop route's conservation identities over its derived hop
    count matrices (docs/topology.md): hop 1 sends exactly what each
    source holds, hop 2 delivers exactly what each destination is owed,
    and every row hop 1 parks at a gateway leaves on hop 2."""
    _STATS["conservation_checks"] += 1
    c = np.asarray(counts)
    a = np.asarray(c1)
    b = np.asarray(c2)
    bad = None
    if (a < 0).any() or (b < 0).any():
        bad = "negative hop count"
    elif not np.array_equal(a.sum(axis=1), c.sum(axis=1)):
        bad = "hop-1 row sums != sidecar row sums (rows lost before ICI)"
    elif not np.array_equal(b.sum(axis=0), c.sum(axis=0)):
        bad = "hop-2 column sums != sidecar column sums (rows lost on DCN)"
    elif not np.array_equal(a.sum(axis=0), b.sum(axis=1)):
        bad = "gateway imbalance: hop-1 arrivals != hop-2 departures"
    if bad is not None:
        _STATS["violations"] += 1
        raise DataIntegrityError(
            f"two-hop conservation law violated at {site}: {bad}",
            site=site, phase=phase)


# ---------------------------------------------------------------------------
# layer 2: order-invariant content fingerprints (armed)
# ---------------------------------------------------------------------------

#: per-row hash seed and the two finalization tweaks that split the one
#: u32 chain into independent lo/hi output lanes (64 fingerprint bits)
_FP_SEED = 0x243F6A88
_FP_LO = 0xA5A5A5A5
_FP_HI = 0x3C3C3C3C


def _audit_lanes(a):
    """Bit-exact u32 lanes for the fingerprint: unlike the routing hash
    (:func:`cylon_tpu.ops.hashing._u32_lanes`) nothing is canonicalized
    or downcast — a flipped sign bit on -0.0 or a low-mantissa f64 flip
    must change the fingerprint."""
    dt = a.dtype
    if dt == jnp.bool_:
        return [a.astype(jnp.uint32)]
    if jnp.issubdtype(dt, jnp.floating):
        if dt.itemsize == 8:
            pair = jax.lax.bitcast_convert_type(a, jnp.uint32)
            return [pair[..., 0], pair[..., 1]]
        if dt.itemsize < 4:
            a = a.astype(jnp.float32)
        return [jax.lax.bitcast_convert_type(a, jnp.uint32)]
    return hashing._u32_lanes(a)


def _xor_fold(x):
    """XOR-reduce over axis 0 — the commutative mix that makes the
    fingerprint order- and placement-invariant."""
    return jax.lax.reduce(x, np.uint32(0),
                          lambda p, q: jax.lax.bitwise_xor(p, q), (0,))


@program_cache()
def _fingerprint_fn(mesh: Mesh, w: int, n_arrs: int, mask_kind: str):
    """Order-invariant 64-bit mesh fingerprint over ``n_arrs`` row-major
    arrays: one u32 avalanche chain per row across every lane of every
    array (2-D lane matrices contribute each column), finalized twice
    (lo/hi tweaks) for 64 output bits, masked to the XOR identity on
    invalid rows, XOR-folded per shard, all_gathered and folded across
    the mesh — the (2,) uint32 result is REPLICATED, so every process
    of a multihost session holds the identical fingerprint.

    ``mask_kind``: ``"prefix"`` — the first operand is the replicated
    (W,) valid-count vector, valid rows are each shard's dense prefix
    (tables, exchange outputs); ``"targets"`` — the first operand is the
    sharded target-rank array, valid rows are those with a real
    destination (``tgt < W`` — padding carries the trash target W)."""

    def per_shard(sel, *arrs):
        cap = arrs[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        if mask_kind == "prefix":
            mask = jnp.arange(cap) < sel[my]
        else:
            mask = sel < w
        h = jnp.full((cap,), jnp.uint32(_FP_SEED))
        gold = jnp.uint32(hashing._GOLD)
        for a in arrs:
            if a.ndim == 2:
                slices = [a[:, j] for j in range(a.shape[1])]
            else:
                slices = [a]
            for s in slices:
                for lane in _audit_lanes(s):
                    h = hashing._mix32(
                        h ^ (lane + gold + (h << jnp.uint32(6))
                             + (h >> jnp.uint32(2))))
        lo = jnp.where(mask, hashing._mix32(h ^ jnp.uint32(_FP_LO)),
                       jnp.uint32(0))
        hi = jnp.where(mask, hashing._mix32(h ^ jnp.uint32(_FP_HI)),
                       jnp.uint32(0))
        part = jnp.stack([_xor_fold(lo), _xor_fold(hi)]).reshape(1, 2)
        return _xor_fold(jax.lax.all_gather(part, ROW_AXIS).reshape(-1, 2))

    sel_spec = P() if mask_kind == "prefix" else P(ROW_AXIS)
    specs = (sel_spec,) + (P(ROW_AXIS),) * n_arrs
    # replication checking can't infer the post-gather XOR fold is
    # replicated (lax.reduce has no rep rule); the value IS — every
    # shard folds the identical gathered matrix — so disable the check
    # (the jaxpr gate still asserts the program's collective set)
    import inspect
    params = inspect.signature(shard_map).parameters
    norep = {"check_rep": False} if "check_rep" in params else (
        {"check_vma": False} if "check_vma" in params else {})
    return jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                         out_specs=P(), **norep))


def _pull_fp(pair_dev) -> int:
    """Host pull of the replicated (2,) fingerprint — the audit's one
    sync point, run under the exchange watchdog so an injected (or real)
    peer hang at ``audit.verify`` surfaces typed instead of blocking."""
    from . import recovery
    from ..utils.host import host_array
    stalled = recovery.injected("audit.verify") == "stall"
    pair = recovery.exchange_watchdog("audit.verify",
                                      lambda: host_array(pair_dev),
                                      stalled=stalled)
    return (int(pair[1]) << 32) | int(pair[0])


def partition_fingerprint(mesh: Mesh, arrays, *, prefix_counts=None,
                          targets=None) -> int:
    """64-bit order-invariant fingerprint of the valid rows of
    ``arrays`` (data and validity arrays alike — pass both so a flipped
    validity bit changes the fingerprint).  Exactly one of
    ``prefix_counts`` (host (W,) valid counts) / ``targets`` (sharded
    target-rank array, pre-exchange inputs) selects the row mask."""
    arrs = tuple(arrays)
    if targets is not None:
        sel, mask_kind = targets, "targets"
    else:
        sel = np.asarray(prefix_counts, np.int32)
        mask_kind = "prefix"
    w = int(mesh.devices.size)
    out = _fingerprint_fn(mesh, w, len(arrs), mask_kind)(sel, *arrs)
    return _pull_fp(out)


def table_fingerprint(table) -> int | None:
    """Fingerprint of a Table's content — every column's data and
    validity lanes in sorted column-name order, masked to each shard's
    valid prefix.  Order- and placement-invariant, so the fingerprint
    survives resharding: a foreign checkpoint piece re-blocked onto a
    different world fingerprints identically (the resume-audit
    property).  Returns None in serial (mesh-less) sessions."""
    mesh = getattr(table.env, "mesh", None)
    if mesh is None:
        return None
    arrs = []
    for name in sorted(table.columns):
        col = table.columns[name]
        arrs.append(col.data)
        if col.validity is not None:
            arrs.append(col.validity)
    return partition_fingerprint(mesh, arrs,
                                 prefix_counts=table.valid_counts)


def verify_exchange(mesh: Mesh, tgt, cols, outs, per_dest, *,
                    site: str = "shuffle.recv",
                    phase: str = "post_exchange") -> None:
    """Armed post-exchange fingerprint conservation: the XOR fingerprint
    of the valid INPUT rows (those with a real destination) must equal
    the fingerprint of the delivered OUTPUT rows — the exchange moves
    rows verbatim and preserves the multiset, whatever route carried
    them (flat, multi-round, two-hop).  The output fingerprint is voted
    over the consensus wire first (multiprocess), so the raise/proceed
    decision below is rank-uniform by construction."""
    fp_in = partition_fingerprint(mesh, cols, targets=tgt)
    fp_out = partition_fingerprint(mesh, outs, prefix_counts=per_dest)
    _STATS["fingerprint_checks"] += 1
    from . import recovery
    recovery.fingerprint_consensus(mesh, fp_out)
    _STATS["fingerprint_votes"] += 1
    if fp_in != fp_out:
        _STATS["violations"] += 1
        raise DataIntegrityError(
            f"fingerprint conservation violated at {site}: inputs "
            f"{fp_in:#018x} != outputs {fp_out:#018x} — a received "
            "buffer was mutated in flight",
            site=site, phase=phase)


def audit_table(table, *, site: str, phase: str) -> int | None:
    """Armed stage-boundary audit of a whole table (post-stitch output,
    absorbed stream batch, completed piece): compute the replicated
    fingerprint and vote it rank-coherently.  Returns the fingerprint
    (None in serial sessions) so callers can record it (checkpoint
    manifests)."""
    fp = table_fingerprint(table)
    if fp is None:
        return None
    _STATS["fingerprint_checks"] += 1
    from . import recovery
    recovery.fingerprint_consensus(getattr(table.env, "mesh", None), fp)
    _STATS["fingerprint_votes"] += 1
    return fp


def audit_restored_table(table, recorded_fp, *, site: str = "ckpt.audit",
                         phase: str = "resume") -> None:
    """Resume audit: recompute a restored checkpoint piece's content
    fingerprint and compare against the manifest-recorded one — catches
    corruption that page shas cannot (a piece whose pages were rewritten
    sha-consistently, or a stitch/re-block bug in foreign adoption).
    Mismatch raises typed :class:`DataIntegrityError`; the checkpoint
    layer degrades it exactly like a sha miss — recompute, never
    adopt."""
    if recorded_fp is None or not armed():
        return
    fp = table_fingerprint(table)
    if fp is None:
        return
    _STATS["manifest_audits"] += 1
    if int(fp) != int(recorded_fp):
        _STATS["violations"] += 1
        raise DataIntegrityError(
            f"checkpoint piece content fingerprint mismatch at {site}: "
            f"manifest recorded {int(recorded_fp):#018x}, restored "
            f"content fingerprints to {fp:#018x} — refusing to adopt",
            site=site, phase=phase)


def manifest_fingerprint(table) -> int | None:
    """The fingerprint recorded into a checkpoint manifest entry at
    save time (armed sessions only — unarmed saves record nothing and
    unarmed resumes skip the audit, keeping the happy path write-free)."""
    if not armed():
        return None
    fp = table_fingerprint(table)
    if fp is not None:
        _STATS["manifest_fps"] += 1
    return fp


# ---------------------------------------------------------------------------
# the exchange.corrupt drill: flip ONE element of a delivered buffer
# ---------------------------------------------------------------------------

@program_cache()
def _flip_fn(mesh: Mesh, ndim: int, kind: str):
    """Flip element (0, …) of ONE shard's received buffer — the
    ``exchange.corrupt`` injector's device-side single-element
    corruption (``xor``: bit 0 of an integer/bool lane; ``add``: +1 on a
    float lane).  Non-selected shards pass through bit-identically."""

    def per_shard(s_star, a):
        my = jax.lax.axis_index(ROW_AXIS)
        hit = my == s_star[0]
        idx = (0,) * ndim
        if kind == "xor":
            one = (jnp.asarray(True) if a.dtype == jnp.bool_
                   else jnp.ones((), a.dtype))
            flipped = a[idx] ^ one
        else:
            flipped = a[idx] + jnp.ones((), a.dtype)
        return a.at[idx].set(jnp.where(hit, flipped, a[idx]))

    return jit(shard_map(per_shard, mesh=mesh, in_specs=(P(), P(ROW_AXIS)),
                         out_specs=P(ROW_AXIS)))


def flip_one(mesh: Mesh, arrays, per_dest):
    """Corrupt exactly one element of one delivered column, on the shard
    holding the most rows (guaranteed a VALID row, so the flip is never
    masked out of the fingerprint).  Returns the new array tuple; a
    zero-row exchange is returned untouched."""
    pd = np.asarray(per_dest)
    if pd.size == 0 or int(pd.max()) <= 0:
        return tuple(arrays)
    s_star = np.asarray([int(pd.argmax())], np.int32)
    arrays = list(arrays)
    i = next((j for j, a in enumerate(arrays)
              if np.dtype(a.dtype) == np.bool_
              or np.issubdtype(np.dtype(a.dtype), np.integer)), 0)
    a = arrays[i]
    kind = ("add" if np.issubdtype(np.dtype(a.dtype), np.floating)
            else "xor")
    arrays[i] = _flip_fn(mesh, int(np.ndim(a)), kind)(s_star, a)
    _STATS["corruptions_injected"] += 1
    return tuple(arrays)


# ---------------------------------------------------------------------------
# trace-safety declarations (cylon_tpu.analysis.registry) — the jaxpr
# pass verifies the fingerprint builder's SPMD invariants: exactly one
# all_gather (the replication fold), no other collective; the flip
# builder is pure-local.
# ---------------------------------------------------------------------------

def _trace_fingerprint(mesh):
    w, cap, S = _decl_shapes(mesh)
    prefix = _unwrap(_fingerprint_fn(mesh, w, 3, "prefix"))
    targets = _unwrap(_fingerprint_fn(mesh, w, 1, "targets"))

    def both(vc, a, m, v, tgt, b):
        # prefix-masked table walk (i64 + 2-D u32 lane matrix + validity)
        # and target-masked exchange-input walk in one jaxpr
        return prefix(vc, a, m, v), targets(tgt, b)

    return jax.make_jaxpr(both)(
        S((w,), np.int32), S((w * cap,), np.int64),
        S((w * cap, 2), np.uint32), S((w * cap,), np.bool_),
        S((w * cap,), np.int32), S((w * cap,), np.float64))


def _trace_flip(mesh):
    w, cap, S = _decl_shapes(mesh)
    f1 = _unwrap(_flip_fn(mesh, 1, "xor"))
    f2 = _unwrap(_flip_fn(mesh, 2, "add"))

    def both(s, a, b):
        return f1(s, a), f2(s, b)

    return jax.make_jaxpr(both)(S((1,), np.int32), S((w * cap,), np.int64),
                                S((w * cap, 2), np.float64))


from ..analysis.registry import (declare_builder, decl_shapes as _decl_shapes,  # noqa: E402
                                 unwrap as _unwrap)

declare_builder(f"{__name__}._fingerprint_fn", _trace_fingerprint,
                collectives={"all_gather"}, tags=("integrity",),
                retrace_budget=64)
declare_builder(f"{__name__}._flip_fn", _trace_flip, tags=("integrity",))
