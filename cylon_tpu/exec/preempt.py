"""Preemption grace: SIGTERM becomes a planned drain, not a fault.

Spot/preemptible capacity — GCE spot VMs, GKE node drains — announces a
preemption by delivering SIGTERM with a grace budget (typically 30 s on
GCE; ``terminationGracePeriodSeconds`` on GKE) before the hard SIGKILL.
Python's default disposition kills the interpreter mid-piece, which
turns every planned scale-down into the crash path the chaos harness
exists to survive.  With ``CYLON_TPU_PREEMPT_GRACE_S=<seconds>`` set
(the operator's declaration of the platform's grace budget), this
module installs a SIGTERM handler that only SETS A FLAG; the pipelined
range loop and the streaming absorb path poll the flag at their
existing checkpoint boundaries (``exec/checkpoint.drain_requested``) —
where completed-piece state is already durably committed — flush, and
raise a typed :class:`~cylon_tpu.status.ResumableAbort` carrying the
resume token.  The supervisor's relaunch (possibly on a DIFFERENT
topology — the elastic re-shard path, docs/robustness.md "Elastic
resume & preemption grace") fast-forwards past everything that
committed inside the grace window.

Contract:

* ``CYLON_TPU_PREEMPT_GRACE_S`` unset ⇒ nothing is installed and every
  probe is one env read — SIGTERM keeps its default disposition.
* Grace armed but ``CYLON_TPU_CKPT_DIR`` unset ⇒ the handler still only
  sets the flag, and NO drain fires (there is nothing durable to resume
  from): zero filesystem writes, zero behavior change — asserted in
  tests/test_checkpoint.py.
* In a multiprocess session the drain decision is CONSENSUS'D
  (:func:`cylon_tpu.exec.recovery.drain_consensus`, the
  ``Code.PreemptDrain`` vote on the pmax wire): SIGTERM landing on one
  rank drains every rank at the same checkpoint boundary, because a
  rank that drains alone leaves its peers hanging in the next
  collective — the exact desync docs/robustness.md exists to prevent.

Signal handlers are main-thread-only in CPython; :func:`install` is
called at env creation (``ctx/context.CylonEnv``) and silently declines
off the main thread (the default disposition then applies — honest
spot semantics, no partial arming).
"""

from __future__ import annotations

import os
import signal
import time

_STATE: dict = {"installed": False, "requested": False,
                "received_at": None, "prev": None}


def grace_seconds() -> float | None:
    """The declared grace budget (``CYLON_TPU_PREEMPT_GRACE_S``), or
    None = preemption grace disarmed (the default)."""
    v = os.environ.get("CYLON_TPU_PREEMPT_GRACE_S")
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        from ..status import InvalidError
        raise InvalidError(
            f"CYLON_TPU_PREEMPT_GRACE_S={v!r} is not a number") from None


def armed() -> bool:
    """True when a grace budget is declared — the gate every drain poll
    checks FIRST, so unarmed sessions pay one env read and nothing
    else (no handler state, no consensus poll)."""
    return grace_seconds() is not None


def install() -> bool:
    """Install the SIGTERM flag-setting handler (idempotent; called at
    env creation).  Returns True when the handler is active.  Declines
    when grace is disarmed or when called off the main thread (CPython
    restricts ``signal.signal`` to the main thread — the default
    disposition then applies, which is exactly what an unarmed process
    would see)."""
    if grace_seconds() is None:
        return False
    if _STATE["installed"]:
        return True
    try:
        prev = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:      # not the main thread
        return False
    _STATE["prev"] = prev
    _STATE["installed"] = True
    return True


def _on_sigterm(signum, frame) -> None:
    # flag only: logging/IO inside a signal handler is re-entrancy
    # roulette — the drain site (exec/checkpoint.drain_requested's
    # caller) does the logging with full context
    _STATE["requested"] = True
    if _STATE["received_at"] is None:
        _STATE["received_at"] = time.monotonic()
    # chain to an embedding application's own SIGTERM handler so its
    # shutdown semantics survive the grace arming (SIG_DFL/SIG_IGN are
    # ints, not callable — never chained)
    prev = _STATE["prev"]
    if callable(prev):
        prev(signum, frame)


def request() -> None:
    """Programmatic preemption notice (tests; the ``term`` injector kind
    delivers a real SIGTERM instead, exercising the handler too)."""
    _on_sigterm(signal.SIGTERM, None)


def requested() -> bool:
    """True once a preemption notice (SIGTERM or :func:`request`) has
    arrived on this process."""
    return bool(_STATE["requested"])


def remaining_s() -> float | None:
    """Seconds left of the grace budget, or None when no notice has
    arrived.  Informational: the drain fires at the next checkpoint
    boundary regardless — there is no useful work to schedule against
    the remainder."""
    if _STATE["received_at"] is None:
        return None
    g = grace_seconds() or 0.0
    return g - (time.monotonic() - _STATE["received_at"])


def reset(uninstall: bool = False) -> None:
    """Clear the preemption flag (tests / soak iterations).  With
    ``uninstall=True`` also restore the previous SIGTERM disposition."""
    _STATE["requested"] = False
    _STATE["received_at"] = None
    if uninstall and _STATE["installed"]:
        try:
            signal.signal(signal.SIGTERM, _STATE["prev"] or signal.SIG_DFL)
        except ValueError:
            pass
        _STATE["installed"] = False
        _STATE["prev"] = None
