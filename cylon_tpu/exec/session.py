"""One tenant's query session — the unit the serving scheduler admits,
interleaves and audits (:mod:`cylon_tpu.exec.scheduler`).

A :class:`QuerySession` wraps a query thunk (any callable running
against the shared mesh — a TPC-H query over the DataFrame API, a
pipelined join + sink, an arbitrary plan) together with everything the
serving tier needs to multiplex it safely against its neighbors:

* **admission inputs** — the pack-time HBM ``footprint_bytes`` estimate
  the scheduler checks against the mesh-wide ledger budget before the
  session may start, plus the ``priority``/``weight`` knobs the
  scheduling policies read;
* **isolation state** — the session's own
  :class:`~cylon_tpu.utils.timing.AttributionScope` (per-tenant phase
  table, no cross-tenant bleed) and its recovery identity
  (:func:`cylon_tpu.exec.recovery.set_session` on the session thread:
  tagged events, ``@session``-selective fault injection, namespaced
  consensus wires, per-session checkpoint stage sequences);
* **serving metrics** — admission wait count/seconds, granted slices,
  accumulated service seconds, end-to-end latency.

Sessions execute on their own daemon thread, but only ONE session runs
between interleave points at a time (the scheduler's baton — see
scheduler module docstring for why), so the session sees exactly the
single-threaded engine semantics every operator was built under.
"""

from __future__ import annotations

import threading
import time

#: session lifecycle states
PENDING = "pending"      # submitted, not yet admitted
RUNNING = "running"      # admitted; thread live (may be waiting for turn)
DONE = "done"            # fn returned; result holds the return value
FAILED = "failed"        # fn raised; error holds the exception


class QuerySession:
    """One submitted query's handle.  Created by
    :meth:`cylon_tpu.exec.scheduler.QueryScheduler.submit`; read-only
    for callers (the scheduler owns the state transitions)."""

    #: session kinds: a ``query`` runs to completion and returns its
    #: result; a ``stream`` session is a LONG-LIVED ingest loop
    #: (cylon_tpu/stream) that yields at its own interleave points —
    #: per micro-batch append, per watermark vote, per window close —
    #: so continuous ingestion coexists with the query tenant mix on
    #: one mesh (docs/streaming.md, docs/serving.md)
    KINDS = ("query", "stream")

    def __init__(self, name: str, fn, ordinal: int, *,
                 footprint_bytes: int = 0, priority: int = 0,
                 weight: float = 1.0, tenant: str | None = None,
                 kind: str = "query", preempt_budget: int = 2,
                 shape_family: str | None = None):
        if "/" in name or name != name.strip() or not name:
            raise ValueError(
                f"session name {name!r} must be a non-empty path-safe "
                "token (it namespaces checkpoint stage directories)")
        if kind not in self.KINDS:
            raise ValueError(
                f"session kind {kind!r} must be one of {self.KINDS}")
        self.name = name
        self.kind = kind
        self.fn = fn
        self.ordinal = int(ordinal)
        self.footprint_bytes = int(footprint_bytes)
        self.priority = int(priority)
        self.weight = float(weight)
        if self.weight <= 0:
            raise ValueError("session weight must be > 0")
        self.tenant = tenant or name
        #: max times this session may be preemptively drained; beyond
        #: the budget it becomes unpreemptable (storm bound)
        self.preempt_budget = int(preempt_budget)
        #: admission shape family: when ANALYZE history has recorded a
        #: peak-ledger observation for this family, admission uses
        #: min(declared, observed_peak x safety_factor) instead of the
        #: declared maximum (docs/serving.md, "Admission contract")
        self.shape_family = shape_family
        self.state = PENDING
        self.result = None
        self.error: BaseException | None = None
        #: per-tenant phase table (utils.timing.AttributionScope); set
        #: when the session thread starts
        self.timing = None
        # serving metrics
        self.admission_waits = 0
        self.admission_wait_s = 0.0
        self.bytes_admitted = 0    # allocation bytes routed through
        #                            scheduler.admit_allocation (TS109)
        self.slices = 0
        self.service_s = 0.0       # granted-slice wall time, accumulated
        self.submitted_s = time.perf_counter()
        self.started_s: float | None = None
        self.finished_s: float | None = None
        # preemption / requeue accounting (scheduler-owned)
        self.preemptions = 0       # completed preempt-drain cycles
        self.requeues = 0          # times requeued after a drain
        self.pieces_committed = 0  # checkpoint pieces durably committed
        # baton machinery (scheduler-owned)
        self._thread: threading.Thread | None = None
        self._grant = threading.Event()
        self._slice_t0 = 0.0
        self._wait_mark: float | None = None  # admission-wait start
        #: None, "preempt" (drain + requeue) or "fleet" (drain, stay
        #: failed-resumable for a cross-process relaunch) — set by the
        #: scheduler, polled by checkpoint.drain_requested at boundaries
        self._drain_mode: str | None = None
        #: pieces_committed snapshot at the last preemption — the
        #: no-progress guard compares against it before re-preempting
        self._progress_mark = 0
        #: requeued session: next fn run resumes in-process (read by
        #: checkpoint.resume_requested on the session thread)
        self._resume_pending = False
        self._outcome_counted = False

    # -- derived metrics ---------------------------------------------------
    @property
    def latency_s(self) -> float | None:
        """Submit-to-finish wall seconds (None while unfinished)."""
        if self.finished_s is None:
            return None
        return self.finished_s - self.submitted_s

    def attributed_s(self) -> float:
        """Accumulated device-dispatch seconds from the session's timing
        scope — the weighted-fair-share policy's ordering key.  Falls
        back to granted-slice wall time before the scope exists."""
        if self.timing is not None:
            return self.timing.total_seconds()
        return self.service_s

    def outcome(self) -> str:
        """Per-tenant outcome bucket (docs/serving.md): ``completed`` /
        ``preempted_requeued`` (finished, but only after >= 1 preempt
        cycle) / ``drained_resumable`` (failed with a ResumableAbort —
        committed work survives, a relaunch resumes it) /
        ``failed_typed`` / ``failed_untyped``; unfinished sessions
        report their lifecycle state."""
        from ..status import CylonError, ResumableAbort
        if self.state == DONE:
            return "preempted_requeued" if self.preemptions else "completed"
        if self.state == FAILED:
            if isinstance(self.error, ResumableAbort):
                return "drained_resumable"
            if isinstance(self.error, CylonError):
                return "failed_typed"
            return "failed_untyped"
        return self.state

    # -- isolation audits --------------------------------------------------
    def recovery_events(self) -> list[dict]:
        """Recovery events recorded under THIS session's tag — the
        per-tenant isolation audit (empty for a clean run; another
        tenant's ladder never appears here)."""
        from . import recovery
        return recovery.events_for_session(self.name)

    def phase_snapshot(self) -> dict:
        """The session's private phase table (same shape as
        ``utils.timing.snapshot``), or {} before the session started."""
        return self.timing.snapshot() if self.timing is not None else {}

    def summary(self) -> dict:
        """Serving metrics for bench JSON detail."""
        return {
            "name": self.name, "tenant": self.tenant, "state": self.state,
            "kind": self.kind,
            "priority": self.priority, "weight": self.weight,
            "footprint_bytes": self.footprint_bytes,
            "admission_waits": self.admission_waits,
            "admission_wait_s": round(self.admission_wait_s, 4),
            "bytes_admitted": self.bytes_admitted,
            "slices": self.slices,
            "preemptions": self.preemptions,
            "requeues": self.requeues,
            "pieces_committed": self.pieces_committed,
            "outcome": self.outcome(),
            "service_s": round(self.service_s, 4),
            "latency_s": (round(self.latency_s, 4)
                          if self.latency_s is not None else None),
            "recovery_events": self.recovery_events(),
        }

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"QuerySession({self.name!r}, state={self.state}, "
                f"slices={self.slices})")
