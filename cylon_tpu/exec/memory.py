"""HBM budget ledger + host spill tier — graceful degradation under
memory pressure.

The paper's answer to a distributed operator outgrowing device memory is
abort-and-rerun; PR 3's consensus retry ladder improved that to
*recompute at higher chunk counts* or *halve piece caps* — both throw
away completed device work, and neither knows how much HBM is actually
held by resident state.  This module closes that gap with the same
mechanism a training stack uses for activation offload:

1. **HBM budget ledger** (:class:`Ledger`): every long-lived resident
   allocation — packed lane matrices and f64 side arrays
   (:class:`~cylon_tpu.relational.piece.PieceSource`), GroupBySink
   partials, exchange receive buffers — registers its byte count under a
   deterministic owner name.  The ledger is consulted by the exchange
   receive-budget guard (:mod:`cylon_tpu.parallel.shuffle`) and by the
   pipelined join's piece working-set sizing, against a budget from
   ``CYLON_TPU_HBM_BUDGET`` (total bytes across the mesh) with a
   platform-detected default (per-chip ``bytes_limit`` × device count on
   accelerators; unlimited on CPU).

2. **Host spill tier**: cold spillable registrations evict to host RAM
   — LRU by last piece-loop access (:func:`touch`), per-shard pulls
   through the sanctioned :mod:`cylon_tpu.utils.host` funnel
   (``host_shard_blocks``: each process reads only its addressable
   shards, so the transport is collective-free) — and re-enter the
   device *per window*
   (:func:`upload_window`): a host-resident
   :class:`~cylon_tpu.relational.piece.PieceSource` uploads only the
   current range piece's rows, and the pipelined join's range loop
   double-buffers so piece r+1's upload overlaps piece r's compute.
   Spill round-trips are bit-exact (u32/f64 arrays move unchanged).

3. **Collective coherence**: eviction is a COLLECTIVE decision.  A
   rank-local eviction would change that rank's guard predicates and
   retry branches while its peers proceed — the same desync a
   rank-local retry causes — and the eviction's own host pulls are
   collectives in a multiprocess session.  Registrations and LRU order
   advance at uniform program points, but a raw balance READ is uniform
   only up to GC release timing, so no multiprocess decision gates on
   it: admission polls whenever a budget is configured, agrees on the
   eviction COUNT (max of each rank's deterministic
   :meth:`Ledger.evict_count_for`) over the PR 3 consensus wire
   (:func:`cylon_tpu.exec.recovery.count_consensus`), and every rank
   then evicts that many oldest owners — same owners, same order
   (asserted cross-rank by ``tests/multihost_driver.py``).  The
   ladder's spill rung agrees its take-the-rung decision the same way
   (:func:`~cylon_tpu.exec.recovery.spill_consensus`), and rank-local
   shortcuts (:func:`try_free`) are single-controller only.

4. **Ladder integration**: ``run_with_recovery`` gains a new FIRST rung
   — *spill-then-retry at the same chunk count*
   (:func:`spill_for_retry`) — so a
   :class:`~cylon_tpu.status.PredictedResourceExhausted` first tries to
   free resident bytes without discarding any completed work; chunk
   escalation remains the backstop (docs/robustness.md).

5. **Disk tier** (the residency ladder's FINAL rung — docs/robustness.md
   "Disk tier & scan pushdown"): a second, HOST-side budget
   (``CYLON_TPU_HOST_BUDGET``) bounds the host-resident spill pages.
   When device→host evictions push the host balance past it, cold host
   pages DEMOTE to per-rank spill files under ``CYLON_TPU_SPILL_DIR``
   (one ``.spill.npy`` page per array per addressable shard, sha256 over
   the page content — the same bit-exact round-trip contract as
   checkpoints, except spill pages are PROCESS-transient: hashes live in
   memory and a fresh process never reads a predecessor's files).
   Promotion is ON-TOUCH: a piece access of a disk-resident source
   verifies the owner's pages once (full sequential read, streamed —
   never the whole working set in RAM) and then windows read straight
   off memory-mapped pages through the same :func:`upload_window`
   double-buffering the host tier uses, so piece r+1's disk reads
   overlap piece r's compute.  Demote decisions ride the SAME
   rank-coherent count-consensus wire as evictions (same owners, same
   order on every rank).  Robustness: page writes/reads take the bounded
   IO retry (:func:`cylon_tpu.exec.recovery.retry_io`); a failed or
   ENOSPC'd demotion degrades to keeping the page host-resident (typed
   recovery event, never a crash); a corrupt page on promote surfaces as
   a typed :class:`~cylon_tpu.status.CheckpointCorruptError` at site
   ``disk.read`` and the ladder recomputes that owner's stage (never a
   wrong answer); a stalled page transfer surfaces via the exchange
   watchdog as a typed RankDesyncError.  Injector sites ``disk.write``
   (kinds ``corrupt``/``stall``/``enospc``/``kill``) and ``disk.read``
   (``corrupt``/``stall``) make every path testable on the CPU rig.

Escape hatches: ``CYLON_TPU_SPILL=0`` disables eviction entirely (the
ledger keeps accounting); ``CYLON_TPU_HBM_BUDGET`` overrides the
detected budget.  With spill disabled and no faults armed, the happy
path through :func:`ensure_headroom` is a couple of dict lookups — no
collectives, no host syncs; with ``CYLON_TPU_HOST_BUDGET`` unset the
disk tier adds ZERO filesystem writes (asserted in tests/test_memory.py
and the chaos ``--oocore`` happy-path leg).

Trace-safety notes: this module is the ONE sanctioned place that
changes residency of lane-sized arrays (TS106) — a bare
``jax.device_put``/``jax.device_get`` in ``relational/`` or
``parallel/`` bypasses the ledger and is a lint finding — AND the one
sanctioned place that constructs spill-file paths or does raw spill
page IO (TS114): a direct ``open``/``np.save`` of a spill page
elsewhere would skip the sha contract, the bounded IO retry and the
demote/promote accounting.
"""

from __future__ import annotations

import errno
import hashlib
import os
import re
import threading
import weakref

import numpy as np

from .. import config
from ..status import CheckpointCorruptError
from ..utils import timing

#: injector kinds at the spill sites that RAISE as typed faults (the
#: rest — ``predicted`` = simulated pressure, ``spill_stall``/``stall``
#: = simulated transfer hang — steer the spill machinery instead)
_RAISE_KINDS = ("device_oom", "capacity", "desync")


def _spill_enabled() -> bool:
    return config.SPILL_ENABLED


def _session_tag() -> str | None:
    """The serving session tagged on this thread (exec/recovery holds the
    thread-local identity the scheduler sets), or None outside one."""
    from . import recovery
    return recovery.current_session()


# ---------------------------------------------------------------------------
# budget
# ---------------------------------------------------------------------------

_BUDGET_CACHE: list = []  # [int] once detected; empty = not yet probed


def budget_bytes() -> int:
    """The ledger's budget in TOTAL bytes across the mesh: the
    ``CYLON_TPU_HBM_BUDGET`` override when set, else per-chip
    ``bytes_limit`` × device count on accelerators, else 0 (unlimited —
    CPU rigs where host RAM, not HBM, is the ceiling).  Detected lazily
    (the backend must already be initialized) and cached."""
    if config.HBM_BUDGET_BYTES > 0:
        return config.HBM_BUDGET_BYTES
    if _BUDGET_CACHE:
        return _BUDGET_CACHE[0]
    import jax
    total = 0
    try:
        devs = jax.devices()
        if devs and devs[0].platform != "cpu":
            per = 0
            try:
                per = int((devs[0].memory_stats() or {}).get(
                    "bytes_limit", 0))
            except Exception:  # noqa: BLE001 — backend without stats
                per = 0
            total = (per or 16 * 1024**3) * len(devs)
    except Exception:  # noqa: BLE001 — no backend yet: stay unlimited
        return 0
    _BUDGET_CACHE.append(total)
    return total


# ---------------------------------------------------------------------------
# registrations + ledger
# ---------------------------------------------------------------------------

def _nbytes(arrays) -> int:
    return sum(int(np.prod(a.shape, dtype=np.int64))
               * int(np.dtype(a.dtype).itemsize) for a in arrays
               if a is not None)


class Registration:
    """One resident allocation's ledger entry — also the owner's HANDLE
    to its arrays: spillable owners read their device arrays through
    :attr:`arrays` (None while spilled) so eviction can actually drop
    the device references.  ``host`` (while spilled) is a tuple of
    PER-SHARD host block lists (``utils.host.host_shard_blocks``): each
    process holds only its addressable shards, which keeps both the
    eviction pull and the re-upload collective-free."""

    __slots__ = ("owner", "nbytes", "spillable", "seq", "arrays", "host",
                 "disk", "disk_ok", "disk_views", "sharding", "world",
                 "live", "session", "__weakref__")

    def __init__(self, owner: str, arrays, spillable: bool, sharding,
                 seq: int):
        self.owner = owner
        self.nbytes = _nbytes(arrays)
        self.spillable = bool(spillable)
        # the serving session whose turn allocated this (None outside a
        # scheduler): eviction under another tenant's admission pressure
        # is a CROSS-tenant eviction, counted separately in stats()
        self.session = _session_tag()
        # only a SPILLABLE entry holds its arrays (it must be able to
        # drop the device references on eviction); a bookkeeping-only
        # entry keeping them would pin its own anchor and never drain
        self.arrays = tuple(arrays) if spillable else ()
        self.sharding = sharding
        self.world = (int(sharding.mesh.devices.size)
                      if sharding is not None else 1)
        self.seq = seq
        self.host: tuple | None = None
        #: disk-tier page table while demoted (per-array tuples of
        #: per-shard ``{"path", "sha", "nbytes"}`` entries, None for
        #: remote shards); ``disk_ok`` records the one on-touch sha
        #: verification per demote cycle (windows mmap after it), and
        #: ``disk_views`` caches the post-verification mmap views so a
        #: P-piece loop opens each page once, not P times
        self.disk: tuple | None = None
        self.disk_ok = False
        self.disk_views: tuple | None = None
        self.live = True

    @property
    def spilled(self) -> bool:
        """Off-device: host-resident (spill tier) OR disk-resident."""
        return self.host is not None or self.disk is not None

    @property
    def on_disk(self) -> bool:
        return self.disk is not None


class Ledger:
    """Owner-named byte accounting for resident device allocations, with
    LRU host eviction of spillable entries.  All state transitions are
    deterministic functions of the (rank-uniform) registration and
    access sequence, so a multiprocess session's ledgers stay identical
    across ranks by construction."""

    def __init__(self):
        self._live: dict[str, Registration] = {}
        self._lock = threading.RLock()
        self._seq = 0
        self._names = 0
        self.peak = 0

    # -- accounting --------------------------------------------------------
    def balance(self) -> int:
        with self._lock:
            return sum(r.nbytes for r in self._live.values()
                       if not r.spilled)

    def spillable_bytes(self) -> int:
        with self._lock:
            return sum(r.nbytes for r in self._live.values()
                       if r.spillable and not r.spilled)

    def host_balance(self) -> int:
        """Bytes of live registrations currently HOST-resident (spilled
        to RAM, not yet demoted to disk) — the disk tier's budget
        predicate (``CYLON_TPU_HOST_BUDGET``)."""
        with self._lock:
            return sum(r.nbytes for r in self._live.values()
                       if r.host is not None)

    def owners(self) -> list[str]:
        with self._lock:
            return sorted(self._live, key=lambda o: self._live[o].seq)

    # -- registration lifecycle --------------------------------------------
    def register(self, base: str, arrays, spillable: bool = False,
                 sharding=None, anchor=None) -> Registration:
        """Register a resident allocation under a deterministic owner
        name ``base#<n>`` (the counter advances identically on every
        rank).  ``anchor``: auto-release when this object is collected
        (the registration must not outlive — or leak past — its owner)."""
        with self._lock:
            self._names += 1
            self._seq += 1
            reg = Registration(f"{base}#{self._names}", arrays, spillable,
                               sharding, self._seq)
            self._live[reg.owner] = reg
            self.peak = max(self.peak, self.balance())
        if anchor is not None:
            try:
                weakref.finalize(anchor, self.release, reg)
            except TypeError:
                pass  # not weakrefable: caller releases explicitly
        return reg

    def touch(self, reg: Registration | None) -> None:
        """LRU bump: record a piece-loop access of this registration."""
        if reg is None or not reg.live:
            return
        with self._lock:
            self._seq += 1
            reg.seq = self._seq

    def release(self, reg: Registration | None) -> None:
        """Drop a registration (idempotent): device, host and disk
        copies are unpinned (spill page files deleted best-effort) and
        the balance drains — never below zero."""
        if reg is None or not reg.live:
            return
        with self._lock:
            reg.live = False
            self._live.pop(reg.owner, None)
            reg.arrays = ()
            reg.host = None
            disk, reg.disk = reg.disk, None
            reg.disk_ok = False
            reg.disk_views = None
        if disk is not None:
            _remove_disk_pages(disk)

    # -- spill tier --------------------------------------------------------
    def evict(self, reg: Registration, stall: bool = False) -> int:
        """Move one spillable registration's arrays to host RAM — a
        PER-SHARD, collective-free pull (each process reads only its
        addressable shards; ``utils.host.host_shard_blocks``) under the
        exchange watchdog — and drop the device references.  Returns the
        bytes freed (0 if not evictable).  Bit-exact: the arrays are raw
        u32 lane matrices / f64 side channels."""
        if not (reg.live and reg.spillable and not reg.spilled
                and reg.arrays):
            return 0
        from . import recovery
        from ..utils.host import host_shard_blocks
        devs, w = list(reg.arrays), reg.world
        with timing.region("spill.evict"):
            # stalled is passed explicitly (never probed): a spill-site
            # eviction must not consume `exchange.stall` injections meant
            # for the exchange path
            host = recovery.exchange_watchdog(
                "spill.evict",
                lambda: tuple(host_shard_blocks(a, w) for a in devs),
                timeout_s=_stall_timeout(stall), stalled=stall)
        with self._lock:
            reg.host = host
            reg.arrays = ()
        _note_spill("spill.evict", reg)
        return reg.nbytes

    def readmit(self, reg: Registration, stall: bool = False) -> tuple:
        """Re-upload a spilled registration's FULL arrays to the device
        (the whole-matrix complement of the per-window
        :func:`upload_window` path) and return them.  A DISK-resident
        registration first promotes its pages back to host (sha-verified
        full read, :meth:`promote_host`).  Not on the overlap-critical
        path, so with ``CYLON_TPU_WATCHDOG_S`` armed the readiness check
        blocks under the watchdog — a hung transfer surfaces typed at
        ``spill.upload``."""
        if not (reg.live and reg.spilled):
            return reg.arrays
        if reg.host is None:
            self.promote_host(reg, stall=stall)
        arrs = _upload(list(reg.host), reg.sharding, stall=stall)
        if config.EXCHANGE_WATCHDOG_S > 0 and not stall:
            import jax
            from . import recovery
            recovery.exchange_watchdog(
                "spill.upload", lambda: jax.block_until_ready(list(arrs)),
                stalled=False)
        with self._lock:
            reg.arrays = tuple(arrs)
            reg.host = None
            self._seq += 1
            reg.seq = self._seq
            self.peak = max(self.peak, self.balance())
        _STATS["readmit_events"] += 1
        _STATS["bytes_readmitted"] += reg.nbytes
        timing.add_bytes("spill.upload", reg.nbytes)
        return reg.arrays

    # -- disk tier (host → spill files → back) -----------------------------
    def demote(self, reg: Registration, stall: bool = False) -> int:
        """Move one HOST-resident registration's pages to per-rank spill
        files — the residency ladder's final rung.  One ``.spill.npy``
        page per array per addressable shard, sha256 over the page
        content recorded in the (in-memory) page table; writes take the
        bounded IO retry.  Returns the bytes moved off host RAM.

        Degrades, never crashes: a write that still fails after the
        retry budget (ENOSPC, quota, a dead disk) abandons the demotion
        — partial pages are deleted, the registration STAYS
        host-resident, and a typed ``disk.write`` recovery event records
        the degrade.  An injected ``stall`` (or a real hang surfaced the
        same way) raises typed through the exchange watchdog; ``corrupt``
        flips a byte of the first page AFTER hashing so the promote-side
        verification catches it; ``kill`` is the chaos harness's
        mid-demote crash."""
        if not (reg.live and reg.host is not None):
            return 0
        from . import recovery
        kind = recovery.maybe_inject(
            "disk.write", intercept=("corrupt", "stall", "enospc"))
        root = _rank_spill_dir()
        safe = _safe_owner(reg.owner)
        written: list[str] = []
        first = [True]

        def write_all():
            out = []
            for j, blocks in enumerate(reg.host):
                per = []
                for k, blk in enumerate(blocks):
                    if blk is None:
                        per.append(None)
                        continue
                    path = os.path.join(root, f"{safe}.a{j}.s{k}.spill.npy")
                    if kind == "enospc" and first[0]:
                        raise OSError(errno.ENOSPC,
                                      "injected ENOSPC mid-demote")
                    sha = _sha_arr(blk)
                    recovery.retry_io(lambda p=path, b=blk: np.save(p, b),
                                      "disk.write", on_retry=_note_retry)
                    written.append(path)
                    if kind == "corrupt" and first[0]:
                        # flip a DATA byte after hashing: the promote
                        # verification must catch it (the acceptance
                        # path for corrupt-on-promote → recompute)
                        _flip_last_byte(path)
                    first[0] = False
                    per.append({"path": path, "sha": sha,
                                "nbytes": int(blk.nbytes)})
                out.append(tuple(per))
            return tuple(out)

        try:
            with timing.region("disk.write"):
                if stall or kind == "stall":
                    meta = recovery.exchange_watchdog(
                        "disk.write", write_all,
                        timeout_s=_stall_timeout(True), stalled=True)
                else:
                    meta = write_all()
        except OSError as e:
            _remove_paths(written)
            is_enospc = e.errno == errno.ENOSPC
            _DSTATS["write_degrades"] += 1
            recovery._record("disk.write",
                             "enospc" if is_enospc else "os_error",
                             "degrade_in_memory")
            from ..utils.logging import log
            log.warning("memory: demotion of %s to disk failed (%s); page "
                        "stays host-resident — degraded, not crashed",
                        reg.owner, e)
            return 0
        except BaseException:
            # typed stall/desync (or anything else) propagates — but the
            # pages already written must not strand on disk (best-effort:
            # a watchdogged writer thread may still be mid-write; the
            # first-use purge above is the backstop)
            _remove_paths(list(written))
            raise
        with self._lock:
            reg.disk = meta
            reg.host = None
            reg.disk_ok = False
            reg.disk_views = None
        moved = sum(e["nbytes"] for per in meta for e in per
                    if e is not None)
        _DSTATS["events"] += 1
        _DSTATS["bytes_demoted"] += moved
        # counted only on SUCCESS: a degraded demotion wrote no durable
        # pages the accounting should claim
        _DSTATS["pages_demoted"] += sum(1 for per in meta for e in per
                                        if e is not None)
        _DEMOTION_LOG.append(reg.owner)
        timing.add_bytes("disk.write", moved)
        timing.bump("memory.disk.demote")
        from ..utils.logging import log
        log.info("memory: %s -> disk (%d B, %s)", reg.owner, moved, root)
        return moved

    def verify_disk(self, reg: Registration, stall: bool = False) -> None:
        """The on-touch promotion gate: sha-verify EVERY page of a
        disk-resident registration once per demote cycle (streamed —
        one page in RAM at a time), after which window reads mmap the
        pages directly.  A mismatch (or an injected ``corrupt`` at site
        ``disk.read``) retires the poisoned owner (released, files
        deleted) and raises a typed :class:`CheckpointCorruptError` —
        the recovery ladder recomputes that owner's stage; corruption
        degrades to recompute, never to a wrong answer."""
        if reg.disk is None or reg.disk_ok:
            return
        from . import recovery
        kind = recovery.maybe_inject("disk.read",
                                     intercept=("corrupt", "stall"))

        def check():
            if kind == "corrupt":
                raise CheckpointCorruptError(
                    "injected spill-page corruption on promote",
                    site="disk.read")
            for per in reg.disk:
                for ent in per:
                    if ent is None:
                        continue
                    arr = _read_page(ent["path"])
                    if _sha_arr(arr) != ent["sha"]:
                        raise CheckpointCorruptError(
                            f"spill page {ent['path']} failed its "
                            "content-hash check (torn write or on-disk "
                            "corruption)", site="disk.read")

        try:
            with timing.region("disk.read"):
                if stall or kind == "stall":
                    recovery.exchange_watchdog(
                        "disk.read", check,
                        timeout_s=_stall_timeout(True), stalled=True)
                else:
                    check()
        except CheckpointCorruptError:
            _DSTATS["corrupt_degrades"] += 1
            recovery._record("disk.read", "corrupt", "recompute_owner")
            self.release(reg)
            raise
        reg.disk_ok = True

    def promote_host(self, reg: Registration, stall: bool = False) -> None:
        """Full disk → host promotion (sha-verified): read every page
        back into host block lists and delete the spill files — the
        whole-owner complement of the per-window mmap reads."""
        if reg.disk is None:
            return
        self.verify_disk(reg, stall=stall)
        moved = 0
        with timing.region("disk.read"):
            hosts = []
            for per in reg.disk:
                blocks: list = []
                for ent in per:
                    if ent is None:
                        blocks.append(None)
                        continue
                    arr = _read_page(ent["path"])
                    blocks.append(arr)
                    moved += int(arr.nbytes)
                    _DSTATS["pages_promoted"] += 1
                hosts.append(blocks)
        with self._lock:
            disk, reg.disk = reg.disk, None
            reg.host = tuple(hosts)
            reg.disk_ok = False
            reg.disk_views = None
        _remove_disk_pages(disk)
        _DSTATS["events"] += 1
        _DSTATS["bytes_promoted"] += moved
        timing.add_bytes("disk.read", moved)
        timing.bump("memory.disk.promote")

    def _demote_cands(self) -> list[Registration]:
        """Host-resident entries, oldest ``seq`` first — the
        deterministic LRU demotion order (mirrors :meth:`_spill_cands`
        one rung down)."""
        with self._lock:
            return sorted((r for r in self._live.values()
                           if r.host is not None), key=lambda r: r.seq)

    def demote_count_for(self, budget: int) -> int:
        """How many LRU demotions bring the host balance under the host
        budget — the number, not the balance, is what multiprocess
        sessions agree on (max across ranks), exactly like
        :meth:`evict_count_for` one rung up."""
        if budget <= 0:
            return 0
        bal = self.host_balance()
        if bal <= budget:
            return 0
        n = 0
        for r in self._demote_cands():
            n += 1
            bal -= r.nbytes
            if bal <= budget:
                break
        return n

    def demote_n(self, n: int) -> list[str]:
        """Demote the ``n`` oldest host-resident entries (fewer if the
        ledger has fewer candidates).  Returns the demoted owner names
        in demotion order — identical on every rank by construction."""
        out: list[str] = []
        for reg in self._demote_cands()[:max(int(n), 0)]:
            if self.demote(reg):
                out.append(reg.owner)
        return out

    def _spill_cands(self) -> list[Registration]:
        """Spillable, still-resident entries, oldest ``seq`` first — the
        deterministic LRU eviction order."""
        with self._lock:
            return sorted((r for r in self._live.values()
                           if r.spillable and not r.spilled),
                          key=lambda r: r.seq)

    def evict_count_for(self, need: int, budget: int) -> int:
        """How many LRU evictions bring ``balance + need`` under the
        budget (0 when already under or no budget; all candidates when
        even that is insufficient).  A pure function of the ledger — the
        number, not the balance, is what multiprocess sessions agree on
        (max across ranks) before anyone evicts."""
        if budget <= 0:
            return 0
        bal = self.balance()
        if bal + need <= budget:
            return 0
        n = 0
        for r in self._spill_cands():
            n += 1
            bal -= r.nbytes
            if bal + need <= budget:
                break
        return n

    def evict_n(self, n: int, stall: bool = False) -> list[str]:
        """Evict the ``n`` oldest spillable entries (fewer if the ledger
        has fewer candidates).  Returns the evicted owner names in
        eviction order — identical on every rank by construction."""
        evicted: list[str] = []
        for reg in self._spill_cands()[:max(int(n), 0)]:
            if self.evict(reg, stall=stall):
                evicted.append(reg.owner)
        return evicted

    def evict_until(self, need: int, budget: int,
                    stall: bool = False) -> list[str]:
        """Deterministic LRU eviction until ``balance + need`` fits the
        budget (single-controller convenience for
        :func:`evict_count_for` + :func:`evict_n`)."""
        return self.evict_n(self.evict_count_for(need, budget),
                            stall=stall)


_LEDGER = Ledger()


def ledger() -> Ledger:
    return _LEDGER


# ---------------------------------------------------------------------------
# module-level conveniences (the public surface operators use)
# ---------------------------------------------------------------------------

def register(base: str, arrays, spillable: bool = False, sharding=None,
             anchor=None) -> Registration:
    return _LEDGER.register(base, arrays, spillable=spillable,
                            sharding=sharding, anchor=anchor)


def register_table(base: str, table, anchor=None) -> Registration | None:
    """Account a materialized Table's columns (data + validity) under one
    owner; ``anchor`` defaults to the table itself so GC drains the
    ledger (tests assert balance returns to zero after release).
    Unmaterialized DeferredTables are skipped — forcing their thunk here
    would defeat the fused pushdown they exist for."""
    from ..core.table import DeferredTable
    if isinstance(table, DeferredTable) and not table.materialized:
        return None
    arrays = []
    for c in table.columns.values():
        arrays.append(c.data)
        if c.validity is not None:
            arrays.append(c.validity)
    return _LEDGER.register(base, arrays,
                            anchor=table if anchor is None else anchor)


def release(reg) -> None:
    _LEDGER.release(reg)


def touch(reg) -> None:
    _LEDGER.touch(reg)


def device_arrays(reg: Registration) -> tuple | None:
    """The registration's device arrays, or None while spilled."""
    return reg.arrays if not reg.spilled else None


def evict(reg) -> int:
    return _LEDGER.evict(reg)


def readmit(reg) -> tuple:
    return _LEDGER.readmit(reg)


def balance() -> int:
    return _LEDGER.balance()


def over_budget(need: int) -> bool:
    """Would admitting ``need`` more resident bytes exceed the budget?
    Rank-uniform: balance, need and budget are identical across ranks."""
    b = budget_bytes()
    return b > 0 and _LEDGER.balance() + int(need) > b


def try_free(need: int) -> int:
    """Best-effort eviction of ``need`` bytes of headroom at a guard
    call site.  SINGLE-CONTROLLER only: a multiprocess session returns 0
    and defers all eviction to the consensus'd admission path
    (:func:`ensure_headroom`) — the local balance read that would gate a
    rank-local eviction here is only uniform up to GC timing, and the
    eviction's host pulls are themselves collectives, so a rank evicting
    alone would hang its peers.  Returns bytes freed."""
    if not _spill_enabled():
        return 0
    import jax
    if jax.process_count() > 1:
        return 0
    before = _LEDGER.balance()
    _LEDGER.evict_until(int(need), budget_bytes())
    return before - _LEDGER.balance()


def spillable_bytes() -> int:
    return _LEDGER.spillable_bytes()


def host_balance() -> int:
    return _LEDGER.host_balance()


def demote(reg) -> int:
    return _LEDGER.demote(reg)


def promote_host(reg) -> None:
    _LEDGER.promote_host(reg)


# ---------------------------------------------------------------------------
# disk tier plumbing (TS114: the ONE sanctioned spill-file IO site)
# ---------------------------------------------------------------------------

def _disk_armed() -> bool:
    """The disk tier engages only when a host budget is configured (and
    spilling is on) — rank-uniform by construction (config, not a
    balance read), so consensus-poll gating may key on it."""
    return config.SPILL_ENABLED and config.HOST_BUDGET_BYTES > 0


_SPILL_ROOT: list[str] = []  # [path] once resolved; empty = not yet


def spill_root() -> str:
    """The spill-file root: ``CYLON_TPU_SPILL_DIR``, else a private temp
    directory created lazily on the first demote (so an unarmed run
    never touches the filesystem)."""
    if config.SPILL_DIR:
        return config.SPILL_DIR
    if not _SPILL_ROOT:
        import tempfile
        _SPILL_ROOT.append(tempfile.mkdtemp(prefix="cylon_tpu_spill_"))
    return _SPILL_ROOT[0]


_PURGED_DIRS: set = set()


def _rank_spill_dir() -> str:
    """This process's per-rank spill directory (created on demand).  On
    FIRST use of a given directory this process purges any ``.spill.npy``
    orphans a crashed/killed predecessor left behind: spill pages are
    process-transient by contract (hashes live in memory — a fresh
    process never reads a predecessor's files), so without the purge a
    fixed ``CYLON_TPU_SPILL_DIR`` volume would accumulate orphans run
    over run until a real ENOSPC degrades every future demotion.
    (Concurrent processes of the SAME rank must use distinct spill
    roots — the default private temp dir does — since owner names
    repeat across processes.)"""
    import glob as _glob
    import jax
    d = os.path.join(spill_root(), f"rank{jax.process_index()}")
    os.makedirs(d, exist_ok=True)
    if d not in _PURGED_DIRS:
        _PURGED_DIRS.add(d)
        _remove_paths(_glob.glob(os.path.join(d, "*.spill.npy")))
    return d


_SAFE_OWNER_RE = re.compile(r"[^A-Za-z0-9_.-]")


def _safe_owner(owner: str) -> str:
    return _SAFE_OWNER_RE.sub("_", owner)


def _sha_arr(a) -> str:
    """sha256 over an array's raw content bytes — the spill pages' half
    of the checkpoint tier's bit-exact round-trip contract."""
    return hashlib.sha256(np.ascontiguousarray(a).tobytes()).hexdigest()


def _flip_last_byte(path: str) -> None:
    """Corrupt a written page in place (injection support): XOR the LAST
    file byte — data, not the npy header — after the content hash was
    computed over the good bytes."""
    with open(path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))


def _read_page(path: str):
    """One page file → array, under the bounded IO retry; a page that is
    still unreadable after the budget surfaces as the same typed
    corruption the hash check raises (an absent page IS corruption of
    the owner's disk state).  ValueError/EOFError cover the TORN-page
    shapes np.load raises itself (truncated data → reshape mismatch,
    truncated npy header) — a torn write must end typed → recompute,
    never an unhandled crash."""
    from . import recovery
    try:
        return recovery.retry_io(lambda: np.load(path), "disk.read",
                                 on_retry=_note_retry)
    except (OSError, ValueError, EOFError) as e:
        raise CheckpointCorruptError(
            f"spill page {path} unreadable or torn: {e}",
            site="disk.read") from e


def _mmap_page(path: str):
    """Memory-mapped page view for window reads (post-verification):
    row slices touch only the pages the window covers — the disk tier's
    out-of-core read path.  Same torn-page conversion as
    :func:`_read_page` (a too-short file fails the mmap length check
    with ValueError)."""
    from . import recovery
    try:
        return recovery.retry_io(lambda: np.load(path, mmap_mode="r"),
                                 "disk.read", on_retry=_note_retry)
    except (OSError, ValueError, EOFError) as e:
        raise CheckpointCorruptError(
            f"spill page {path} unreadable or torn: {e}",
            site="disk.read") from e


def _remove_paths(paths) -> None:
    for p in paths:
        try:
            os.remove(p)
        except OSError:
            pass  # best-effort cleanup; a leftover file is never re-read


def _remove_disk_pages(disk) -> None:
    _remove_paths(e["path"] for per in disk for e in per if e is not None)


def _note_retry() -> None:
    _DSTATS["retries"] += 1


def _maybe_demote(env, multi: bool) -> None:
    """Host-budget admission (the disk tier's analog of the eviction
    poll above): when the host-resident spill balance exceeds
    ``CYLON_TPU_HOST_BUDGET``, demote the agreed COUNT of LRU host pages
    to spill files.  The count rides the same one-int32 consensus wire
    as evictions in multiprocess sessions (the poll gate —
    :func:`_disk_armed` — is config, rank-uniform by construction), so
    every rank demotes the same owners in the same order.  Unarmed: one
    attribute read, no filesystem, no collectives."""
    if not _disk_armed():
        return
    want = _LEDGER.demote_count_for(config.HOST_BUDGET_BYTES)
    if multi:
        from . import recovery
        mesh = getattr(env, "mesh", env)
        want = recovery.count_consensus(mesh, want)
    if want <= 0:
        return
    demoted = _LEDGER.demote_n(want)
    if demoted:
        from ..utils.logging import log
        log.warning("memory: demoted %s to disk under host pressure "
                    "(host %d B, host budget %d B)", demoted,
                    _LEDGER.host_balance(), config.HOST_BUDGET_BYTES)


def ensure_headroom(env, need: int, scratch: int = 0,
                    site: str = "spill.evict", reuse: int = 0) -> None:
    """Admission control for a new resident allocation of ``need`` bytes
    (plus ``scratch`` transient working-set bytes — e.g. the piece
    join's sort-operand footprint, :func:`cylon_tpu.ops.pack.
    sort_operand_nbytes`): when the ledger would exceed the budget, cold
    spillable owners evict (LRU) first.

    ``reuse``: bytes of caller-owned buffers DONATED into the allocating
    program (``donate_argnums`` — docs/pipeline.md donation rules): XLA
    frees/aliases them during the allocation, so peak demand is ``need -
    reuse``, not ``need`` — counting both would double-charge donated
    bytes and evict spillable owners that still fit.  Rank-uniform: the
    donation decision is a config flag plus static shapes, identical on
    every rank.

    Coherence protocol (docs/robustness.md "why eviction is
    collective"): what multiprocess ranks agree on is the eviction
    COUNT — the max over each rank's deterministic
    :meth:`Ledger.evict_count_for` — through the one-int32 consensus
    wire, and every rank then evicts that many oldest candidates.  The
    poll's gating inputs are rank-uniform BY CONSTRUCTION (the armed
    flag and the configured budget; never a raw balance read, whose
    release timing is only uniform up to GC), so in a multiprocess
    session the poll runs whenever a budget is configured at all —
    admissions are rare (per packed source), and a 1-int pmax is noise
    next to the pack it guards.  Single-controller sessions (and any
    session with no budget and no armed injector) skip consensus
    entirely: no collective, no host sync."""
    from . import recovery
    kind, armed = recovery.probe(site)
    if kind in _RAISE_KINDS:
        raise recovery.make_fault(kind, site)
    if reuse:
        _STATS["donated_bytes_reused"] += int(reuse)
    if not _spill_enabled():
        return
    need = max(int(need) + int(scratch) - int(reuse), 0)
    b = budget_bytes()
    import jax
    multi = jax.process_count() > 1
    # rank-uniform poll gate: armed / budget-configured only
    if not (armed or b > 0):
        return
    want = _LEDGER.evict_count_for(need, b)
    if kind is not None and want == 0:
        want = 1  # injected pressure with no real deficit: probe one LRU
    if multi:
        mesh = getattr(env, "mesh", env)
        want = recovery.count_consensus(mesh, want)
    if want > 0:
        stall = kind in ("stall", "spill_stall")
        evicted = _LEDGER.evict_n(want, stall=stall)
        if evicted:
            from ..utils.logging import log
            log.warning("memory: evicted %s to host under pressure "
                        "(balance %d B, budget %d B)", evicted,
                        _LEDGER.balance(), b)
    # disk-tier rung: evictions above may have pushed the HOST balance
    # past CYLON_TPU_HOST_BUDGET — demote cold host pages to spill files
    # (count-consensus'd like the evictions; no-op unarmed)
    _maybe_demote(env, multi)


def spill_for_retry() -> int:
    """The retry ladder's spill rung (docs/robustness.md): evict EVERY
    spillable resident registration to host, freeing the maximum bytes
    without discarding completed work, and report the total freed.  The
    caller (``run_with_recovery``) takes the rung only after BOTH the
    fault type and the spill decision itself have been agreed across
    ranks (``spill_consensus``), so every rank spills the same owners in
    the same order — up to entries a straggling GC already released on
    one rank, which is harmless: the spill transport is collective-free
    (per-shard pulls), so a missing candidate shortens that rank's loop
    without desyncing any collective."""
    if not _spill_enabled():
        return 0
    freed = 0
    with _LEDGER._lock:
        cands = sorted((r for r in _LEDGER._live.values()
                        if r.spillable and not r.spilled),
                       key=lambda r: r.seq)
    for reg in cands:
        freed += _LEDGER.evict(reg)
    # the rung's evictions can overrun the HOST budget too: demote the
    # deterministic LRU overflow to disk.  No extra consensus — the
    # take-the-rung decision was already agreed (spill_consensus) and
    # the demote set is a pure function of the rank-uniform ledger (a
    # straggling-GC shortfall only shortens a rank-local file write,
    # never a collective).
    if _disk_armed():
        _LEDGER.demote_n(_LEDGER.demote_count_for(config.HOST_BUDGET_BYTES))
    return freed


# ---------------------------------------------------------------------------
# window-lifetime residency (cylon_tpu/stream): buffered event-time window
# state lives exactly from first append to watermark close
# ---------------------------------------------------------------------------

def register_window(base: str, arrays, sharding=None,
                    anchor=None) -> Registration:
    """Register one event-time window buffer's arrays as a SPILLABLE
    resident allocation — the streaming tier's window-lifetime eviction
    class: a cold (not-yet-closable) window is a first-class LRU spill
    candidate exactly like a cold tenant's packed source, and the
    watermark close retires it through :func:`evict_release`.  Only the
    stream package (and this module) may call this — lint rule TS110
    (docs/trace_safety.md): window state mutated elsewhere would bypass
    the close lifecycle's accounting."""
    return _LEDGER.register(base, arrays, spillable=True,
                            sharding=sharding, anchor=anchor)


def evict_release(reg: Registration | None) -> int:
    """The window-close lifecycle: device → host → released.  A closed
    window's buffered state is first EVICTED through the spill tier — a
    bit-exact per-shard host pull through the same sanctioned,
    watchdogged transport as any other eviction — then the registration
    is RELEASED and the host copy freed with it; the ledger balance
    drains by the window's full byte count (asserted via
    ``memory.stats()`` deltas in tests/test_stream.py).  The host hop is
    the DELIBERATE cost of the lifecycle contract (docs/streaming.md): a
    closed window's final state takes the identical audited exit path as
    every other residency transition — one ``spill_events`` +
    ``window_evictions`` record with the watchdog covering the pull —
    rather than a silent drop (``release`` alone would also free the
    device references, without the audit record).  A window that ledger
    pressure already spilled skips straight to release.  Returns the
    bytes retired.  TS110-guarded like :func:`register_window`."""
    if reg is None or not reg.live:
        return 0
    nbytes = reg.nbytes
    if not reg.spilled:
        _LEDGER.evict(reg)
    _LEDGER.release(reg)
    _STATS["window_evictions"] += 1
    timing.bump("stream.window_evicted")
    return nbytes


def prefetch_depth(window_pair_bytes: int) -> int:
    """Double-buffer depth for the pipelined join's spilled-window
    uploads: 2 (upload piece r+1 while piece r computes) when the
    budget has headroom for a second window pair, else 1.  Deterministic
    from rank-uniform inputs."""
    b = budget_bytes()
    if b <= 0 or _LEDGER.balance() + 2 * int(window_pair_bytes) <= b:
        return 2
    return 1


def spec_row_bytes(spec) -> int:
    """Resident bytes per row of a packed source: 4 per u32 lane plus 8
    per laneless f64 side column (ops/lanes layout)."""
    n_f64 = sum(1 for c in spec.cols if not c.lanes)
    return 4 * int(spec.n_lanes) + 8 * n_f64


# ---------------------------------------------------------------------------
# host <-> device movement (the TS106-sanctioned residency boundary)
# ---------------------------------------------------------------------------

def _stall_timeout(stall: bool) -> float | None:
    """Watchdog deadline for a spill transfer: the configured exchange
    watchdog, or a short synthetic one when a stall is injected with the
    watchdog off (so the injected hang still surfaces typed)."""
    if stall:
        return config.EXCHANGE_WATCHDOG_S or 0.2
    return None  # exchange_watchdog falls back to the config value


def _put_blocks(blocks: list, sharding):
    """Per-shard host blocks -> one row-sharded device array, the
    TS106-sanctioned upload boundary of the spill tier.  Collective-free
    in multiprocess sessions: ``make_array_from_callback`` asks each
    process only for its ADDRESSABLE shards, which are exactly the
    blocks this process holds (remote entries are None and never
    touched).  Unsharded (test) registrations device_put directly."""
    import jax
    have = [b for b in blocks if b is not None]
    n = have[0].shape[0]
    if sharding is None:
        return jax.device_put(np.concatenate(have))
    if jax.process_count() > 1:
        shape = (len(blocks) * n,) + have[0].shape[1:]

        def cb(idx):
            start = idx[0].start or 0
            i = start // n
            stop = shape[0] if idx[0].stop is None else idx[0].stop
            return blocks[i][start - i * n: stop - i * n]

        return jax.make_array_from_callback(shape, sharding, cb)
    return jax.device_put(np.concatenate(blocks), sharding)


def put_blocks(blocks: list, sharding):
    """Public name for the sanctioned per-shard-blocks upload boundary —
    the durable-checkpoint restore path (exec/checkpoint) re-enters its
    host pages through the SAME transport the spill tier uses, so a
    resumed piece is byte-identical to the resident array it was pulled
    from (and multi-controller restores stay collective-free: each
    process uploads only its addressable blocks)."""
    return _put_blocks(blocks, sharding)


def _upload(hosts, sharding, stall: bool = False):
    """Per-array host shard-block lists -> device (:func:`_put_blocks`).
    The dispatch stays ASYNC — blocking every upload would serialize
    exactly the double-buffered overlap the pipelined loop exists for —
    except under an injected ``spill_stall``, where the readiness check
    runs inside the exchange watchdog so the simulated hang surfaces as
    a typed RankDesyncError at site ``spill.upload``.  (A real upload
    hang surfaces at the consumer's next watchdogged host sync;
    :func:`Ledger.readmit` — the whole-matrix, non-overlapped path —
    additionally blocks under the watchdog when
    ``CYLON_TPU_WATCHDOG_S`` is armed.)"""
    from . import recovery
    kind = recovery.injected("spill.upload")
    if kind in _RAISE_KINDS:
        raise recovery.make_fault(kind, "spill.upload")
    stall = stall or kind in ("stall", "spill_stall")
    devs = tuple(_put_blocks(blocks, sharding) for blocks in hosts)
    if stall:
        import jax
        recovery.exchange_watchdog(
            "spill.upload", lambda: jax.block_until_ready(list(devs)),
            timeout_s=_stall_timeout(True), stalled=True)
    return devs


def upload_window(reg: Registration, starts, window: int):
    """Upload ONE per-shard window ``[starts[i], starts[i]+window)`` of a
    spilled registration's host arrays back to the device (row-sharded)
    — the host-resident PieceSource's piece materialization.  Window
    content is byte-identical to the resident path's dynamic slice, so
    packed joins over uploaded windows are bit-equal to unspilled runs.
    Uploads are async dispatches: the pipelined range loop prefetches
    piece r+1's windows so this overlaps piece r's compute.

    DISK-resident registrations promote ON TOUCH: the first window
    access after a demote sha-verifies the owner's pages once
    (:meth:`Ledger.verify_disk` — a mismatch degrades that owner to
    recompute, typed, never a wrong answer), and every window then
    reads its rows straight off MEMORY-MAPPED pages — only the touched
    rows come off disk, so the working set never rematerializes in host
    RAM, and the same prefetch double-buffering overlaps the disk reads
    with piece compute."""
    if not reg.spilled:
        raise ValueError(f"{reg.owner} is device-resident; slice in-program")
    _LEDGER.touch(reg)
    starts = np.asarray(starts, np.int64)
    window = int(window)
    from_disk = reg.host is None
    if from_disk:
        _LEDGER.verify_disk(reg)
        sources = reg.disk_views
        if sources is None:
            # one mmap open per page per demote CYCLE (not per window):
            # the views stay valid until promote/release/re-demote,
            # which clear the cache
            with timing.region("disk.read"):
                sources = tuple(
                    [None if ent is None else _mmap_page(ent["path"])
                     for ent in per] for per in reg.disk)
            reg.disk_views = sources
    else:
        sources = reg.host
    outs = []
    with timing.region("spill.upload"):
        for blocks in sources:
            wins: list = [None] * len(blocks)
            for i, blk in enumerate(blocks):
                if blk is None:     # remote shard: another process's block
                    continue
                s = int(starts[i])
                win = np.zeros((window,) + blk.shape[1:], blk.dtype)
                m = min(window, blk.shape[0] - s)
                if m > 0:
                    win[:m] = blk[s:s + m]
                wins[i] = win
            outs.append(wins)
        devs = _upload(outs, reg.sharding)
    moved = _nbytes(devs)
    _STATS["readmit_events"] += 1
    _STATS["bytes_readmitted"] += moved
    if from_disk:
        _DSTATS["events"] += 1
        _DSTATS["bytes_promoted"] += moved
        timing.add_bytes("disk.read", moved)
    timing.add_bytes("spill.upload", moved)
    return devs


# ---------------------------------------------------------------------------
# stats + eviction log (bench detail; cross-rank coherence assertions)
# ---------------------------------------------------------------------------

# counters live in the metrics registry (cylon_tpu.obs.metrics — the
# TS112 facade); this dict-like view keeps every `_STATS[k] += 1` call
# site and the public stats() shim working verbatim
from ..obs import metrics as _metrics  # noqa: E402

_STATS = _metrics.group("memory", (
    "spill_events", "bytes_spilled",
    "readmit_events", "bytes_readmitted",
    "donated_bytes_reused", "cross_session_evictions",
    "window_evictions"))

#: disk-tier counters (registry names ``memory_disk_*``): demote/promote
#: events and page/byte traffic, bounded-IO retries taken at the disk
#: sites, corrupt-page degrades (owner recomputed) and write degrades
#: (ENOSPC/exhausted-retry demotions that stayed in memory)
_DSTATS = _metrics.group("memory_disk", (
    "events", "pages_demoted", "pages_promoted",
    "bytes_demoted", "bytes_promoted",
    "retries", "corrupt_degrades", "write_degrades"))

_metrics.gauge("memory_ledger_bytes",
               help="current resident-ledger balance (bytes)",
               fn=lambda: _LEDGER.balance())
_metrics.gauge("memory_peak_ledger_bytes",
               help="resident-ledger high-water mark (bytes)",
               fn=lambda: _LEDGER.peak)
_metrics.gauge("memory_host_ledger_bytes",
               help="host-resident spill-page balance (bytes) — the "
                    "disk tier's CYLON_TPU_HOST_BUDGET predicate",
               fn=lambda: _LEDGER.host_balance())

#: owners in eviction order since the last reset — the multihost driver
#: asserts this sequence is IDENTICAL across ranks
_EVICTION_LOG: list[str] = []

#: owners in DEMOTION (host→disk) order since the last reset — the disk
#: tier's rank-coherence audit, mirroring the eviction log one rung down
_DEMOTION_LOG: list[str] = []


def _note_spill(site: str, reg: Registration) -> None:
    _STATS["spill_events"] += 1
    _STATS["bytes_spilled"] += reg.nbytes
    if reg.session is not None and reg.session != _session_tag():
        # another tenant's resident state evicted under THIS context's
        # pressure (or the scheduler's admission pass, tag None): the
        # serving tier's "evict cold tenants first" event
        _STATS["cross_session_evictions"] += 1
    _EVICTION_LOG.append(reg.owner)
    timing.add_bytes(site, reg.nbytes)
    timing.bump(f"memory.{site}")
    from ..utils.logging import log
    log.info("memory: %s -> host (%d B)", reg.owner, reg.nbytes)


def stats() -> dict:
    """Spill counters for bench JSON detail (alongside recovery_events):
    ``spill_events``/``bytes_spilled`` (device→host evictions),
    ``readmit_events``/``bytes_readmitted`` (host→device re-entries),
    ``donated_bytes_reused`` (admission credit for buffers donated into
    the allocating program — bytes the ledger did NOT double-count),
    ``cross_session_evictions`` (one tenant's registrations evicted under
    another tenant's — or the scheduler's — admission pressure),
    ``window_evictions`` (closed event-time windows retired through the
    device→host→released lifecycle, :func:`evict_release`),
    ``peak_ledger_bytes`` (high-water resident balance) — plus the DISK
    tier block: ``disk_events`` (demote/promote operations),
    ``bytes_to_disk``/``bytes_from_disk``, per-page
    ``disk_pages_demoted``/``disk_pages_promoted``, ``disk_retries``
    (bounded-IO retries at the disk sites), ``disk_corrupt_degrades``
    (owners retired to recompute after a failed page hash) and
    ``disk_write_degrades`` (demotions that stayed in memory after an
    ENOSPC or exhausted retry budget)."""
    return dict(_STATS, peak_ledger_bytes=_LEDGER.peak,
                ledger_bytes=_LEDGER.balance(),
                host_ledger_bytes=_LEDGER.host_balance(),
                disk_events=_DSTATS["events"],
                bytes_to_disk=_DSTATS["bytes_demoted"],
                bytes_from_disk=_DSTATS["bytes_promoted"],
                disk_pages_demoted=_DSTATS["pages_demoted"],
                disk_pages_promoted=_DSTATS["pages_promoted"],
                disk_retries=_DSTATS["retries"],
                disk_corrupt_degrades=_DSTATS["corrupt_degrades"],
                disk_write_degrades=_DSTATS["write_degrades"])


def eviction_log() -> list[str]:
    return list(_EVICTION_LOG)


def demotion_log() -> list[str]:
    return list(_DEMOTION_LOG)


def reset_stats() -> None:
    """Zero the counters, the eviction/demotion logs and the peak
    high-water mark (live registrations are untouched — their handles
    stay valid)."""
    for k in _STATS:
        _STATS[k] = 0
    for k in _DSTATS:
        _DSTATS[k] = 0
    _EVICTION_LOG.clear()
    _DEMOTION_LOG.clear()
    _LEDGER.peak = _LEDGER.balance()
