"""HBM budget ledger + host spill tier — graceful degradation under
memory pressure.

The paper's answer to a distributed operator outgrowing device memory is
abort-and-rerun; PR 3's consensus retry ladder improved that to
*recompute at higher chunk counts* or *halve piece caps* — both throw
away completed device work, and neither knows how much HBM is actually
held by resident state.  This module closes that gap with the same
mechanism a training stack uses for activation offload:

1. **HBM budget ledger** (:class:`Ledger`): every long-lived resident
   allocation — packed lane matrices and f64 side arrays
   (:class:`~cylon_tpu.relational.piece.PieceSource`), GroupBySink
   partials, exchange receive buffers — registers its byte count under a
   deterministic owner name.  The ledger is consulted by the exchange
   receive-budget guard (:mod:`cylon_tpu.parallel.shuffle`) and by the
   pipelined join's piece working-set sizing, against a budget from
   ``CYLON_TPU_HBM_BUDGET`` (total bytes across the mesh) with a
   platform-detected default (per-chip ``bytes_limit`` × device count on
   accelerators; unlimited on CPU).

2. **Host spill tier**: cold spillable registrations evict to host RAM
   — LRU by last piece-loop access (:func:`touch`), per-shard pulls
   through the sanctioned :mod:`cylon_tpu.utils.host` funnel
   (``host_shard_blocks``: each process reads only its addressable
   shards, so the transport is collective-free) — and re-enter the
   device *per window*
   (:func:`upload_window`): a host-resident
   :class:`~cylon_tpu.relational.piece.PieceSource` uploads only the
   current range piece's rows, and the pipelined join's range loop
   double-buffers so piece r+1's upload overlaps piece r's compute.
   Spill round-trips are bit-exact (u32/f64 arrays move unchanged).

3. **Collective coherence**: eviction is a COLLECTIVE decision.  A
   rank-local eviction would change that rank's guard predicates and
   retry branches while its peers proceed — the same desync a
   rank-local retry causes — and the eviction's own host pulls are
   collectives in a multiprocess session.  Registrations and LRU order
   advance at uniform program points, but a raw balance READ is uniform
   only up to GC release timing, so no multiprocess decision gates on
   it: admission polls whenever a budget is configured, agrees on the
   eviction COUNT (max of each rank's deterministic
   :meth:`Ledger.evict_count_for`) over the PR 3 consensus wire
   (:func:`cylon_tpu.exec.recovery.count_consensus`), and every rank
   then evicts that many oldest owners — same owners, same order
   (asserted cross-rank by ``tests/multihost_driver.py``).  The
   ladder's spill rung agrees its take-the-rung decision the same way
   (:func:`~cylon_tpu.exec.recovery.spill_consensus`), and rank-local
   shortcuts (:func:`try_free`) are single-controller only.

4. **Ladder integration**: ``run_with_recovery`` gains a new FIRST rung
   — *spill-then-retry at the same chunk count*
   (:func:`spill_for_retry`) — so a
   :class:`~cylon_tpu.status.PredictedResourceExhausted` first tries to
   free resident bytes without discarding any completed work; chunk
   escalation remains the backstop (docs/robustness.md).

Escape hatches: ``CYLON_TPU_SPILL=0`` disables eviction entirely (the
ledger keeps accounting); ``CYLON_TPU_HBM_BUDGET`` overrides the
detected budget.  With spill disabled and no faults armed, the happy
path through :func:`ensure_headroom` is a couple of dict lookups — no
collectives, no host syncs.

Trace-safety note (TS106): this module is the ONE sanctioned place that
changes residency of lane-sized arrays — a bare
``jax.device_put``/``jax.device_get`` in ``relational/`` or
``parallel/`` bypasses the ledger and is a lint finding.
"""

from __future__ import annotations

import threading
import weakref

import numpy as np

from .. import config
from ..utils import timing

#: injector kinds at the spill sites that RAISE as typed faults (the
#: rest — ``predicted`` = simulated pressure, ``spill_stall``/``stall``
#: = simulated transfer hang — steer the spill machinery instead)
_RAISE_KINDS = ("device_oom", "capacity", "desync")


def _spill_enabled() -> bool:
    return config.SPILL_ENABLED


def _session_tag() -> str | None:
    """The serving session tagged on this thread (exec/recovery holds the
    thread-local identity the scheduler sets), or None outside one."""
    from . import recovery
    return recovery.current_session()


# ---------------------------------------------------------------------------
# budget
# ---------------------------------------------------------------------------

_BUDGET_CACHE: list = []  # [int] once detected; empty = not yet probed


def budget_bytes() -> int:
    """The ledger's budget in TOTAL bytes across the mesh: the
    ``CYLON_TPU_HBM_BUDGET`` override when set, else per-chip
    ``bytes_limit`` × device count on accelerators, else 0 (unlimited —
    CPU rigs where host RAM, not HBM, is the ceiling).  Detected lazily
    (the backend must already be initialized) and cached."""
    if config.HBM_BUDGET_BYTES > 0:
        return config.HBM_BUDGET_BYTES
    if _BUDGET_CACHE:
        return _BUDGET_CACHE[0]
    import jax
    total = 0
    try:
        devs = jax.devices()
        if devs and devs[0].platform != "cpu":
            per = 0
            try:
                per = int((devs[0].memory_stats() or {}).get(
                    "bytes_limit", 0))
            except Exception:  # noqa: BLE001 — backend without stats
                per = 0
            total = (per or 16 * 1024**3) * len(devs)
    except Exception:  # noqa: BLE001 — no backend yet: stay unlimited
        return 0
    _BUDGET_CACHE.append(total)
    return total


# ---------------------------------------------------------------------------
# registrations + ledger
# ---------------------------------------------------------------------------

def _nbytes(arrays) -> int:
    return sum(int(np.prod(a.shape, dtype=np.int64))
               * int(np.dtype(a.dtype).itemsize) for a in arrays
               if a is not None)


class Registration:
    """One resident allocation's ledger entry — also the owner's HANDLE
    to its arrays: spillable owners read their device arrays through
    :attr:`arrays` (None while spilled) so eviction can actually drop
    the device references.  ``host`` (while spilled) is a tuple of
    PER-SHARD host block lists (``utils.host.host_shard_blocks``): each
    process holds only its addressable shards, which keeps both the
    eviction pull and the re-upload collective-free."""

    __slots__ = ("owner", "nbytes", "spillable", "seq", "arrays", "host",
                 "sharding", "world", "live", "session", "__weakref__")

    def __init__(self, owner: str, arrays, spillable: bool, sharding,
                 seq: int):
        self.owner = owner
        self.nbytes = _nbytes(arrays)
        self.spillable = bool(spillable)
        # the serving session whose turn allocated this (None outside a
        # scheduler): eviction under another tenant's admission pressure
        # is a CROSS-tenant eviction, counted separately in stats()
        self.session = _session_tag()
        # only a SPILLABLE entry holds its arrays (it must be able to
        # drop the device references on eviction); a bookkeeping-only
        # entry keeping them would pin its own anchor and never drain
        self.arrays = tuple(arrays) if spillable else ()
        self.sharding = sharding
        self.world = (int(sharding.mesh.devices.size)
                      if sharding is not None else 1)
        self.seq = seq
        self.host: tuple | None = None
        self.live = True

    @property
    def spilled(self) -> bool:
        return self.host is not None


class Ledger:
    """Owner-named byte accounting for resident device allocations, with
    LRU host eviction of spillable entries.  All state transitions are
    deterministic functions of the (rank-uniform) registration and
    access sequence, so a multiprocess session's ledgers stay identical
    across ranks by construction."""

    def __init__(self):
        self._live: dict[str, Registration] = {}
        self._lock = threading.RLock()
        self._seq = 0
        self._names = 0
        self.peak = 0

    # -- accounting --------------------------------------------------------
    def balance(self) -> int:
        with self._lock:
            return sum(r.nbytes for r in self._live.values()
                       if not r.spilled)

    def spillable_bytes(self) -> int:
        with self._lock:
            return sum(r.nbytes for r in self._live.values()
                       if r.spillable and not r.spilled)

    def owners(self) -> list[str]:
        with self._lock:
            return sorted(self._live, key=lambda o: self._live[o].seq)

    # -- registration lifecycle --------------------------------------------
    def register(self, base: str, arrays, spillable: bool = False,
                 sharding=None, anchor=None) -> Registration:
        """Register a resident allocation under a deterministic owner
        name ``base#<n>`` (the counter advances identically on every
        rank).  ``anchor``: auto-release when this object is collected
        (the registration must not outlive — or leak past — its owner)."""
        with self._lock:
            self._names += 1
            self._seq += 1
            reg = Registration(f"{base}#{self._names}", arrays, spillable,
                               sharding, self._seq)
            self._live[reg.owner] = reg
            self.peak = max(self.peak, self.balance())
        if anchor is not None:
            try:
                weakref.finalize(anchor, self.release, reg)
            except TypeError:
                pass  # not weakrefable: caller releases explicitly
        return reg

    def touch(self, reg: Registration | None) -> None:
        """LRU bump: record a piece-loop access of this registration."""
        if reg is None or not reg.live:
            return
        with self._lock:
            self._seq += 1
            reg.seq = self._seq

    def release(self, reg: Registration | None) -> None:
        """Drop a registration (idempotent): device and host copies are
        unpinned and the balance drains — never below zero."""
        if reg is None or not reg.live:
            return
        with self._lock:
            reg.live = False
            self._live.pop(reg.owner, None)
            reg.arrays = ()
            reg.host = None

    # -- spill tier --------------------------------------------------------
    def evict(self, reg: Registration, stall: bool = False) -> int:
        """Move one spillable registration's arrays to host RAM — a
        PER-SHARD, collective-free pull (each process reads only its
        addressable shards; ``utils.host.host_shard_blocks``) under the
        exchange watchdog — and drop the device references.  Returns the
        bytes freed (0 if not evictable).  Bit-exact: the arrays are raw
        u32 lane matrices / f64 side channels."""
        if not (reg.live and reg.spillable and not reg.spilled
                and reg.arrays):
            return 0
        from . import recovery
        from ..utils.host import host_shard_blocks
        devs, w = list(reg.arrays), reg.world
        with timing.region("spill.evict"):
            # stalled is passed explicitly (never probed): a spill-site
            # eviction must not consume `exchange.stall` injections meant
            # for the exchange path
            host = recovery.exchange_watchdog(
                "spill.evict",
                lambda: tuple(host_shard_blocks(a, w) for a in devs),
                timeout_s=_stall_timeout(stall), stalled=stall)
        with self._lock:
            reg.host = host
            reg.arrays = ()
        _note_spill("spill.evict", reg)
        return reg.nbytes

    def readmit(self, reg: Registration, stall: bool = False) -> tuple:
        """Re-upload a spilled registration's FULL arrays to the device
        (the whole-matrix complement of the per-window
        :func:`upload_window` path) and return them.  Not on the
        overlap-critical path, so with ``CYLON_TPU_WATCHDOG_S`` armed
        the readiness check blocks under the watchdog — a hung transfer
        surfaces typed at ``spill.upload``."""
        if not (reg.live and reg.spilled):
            return reg.arrays
        arrs = _upload(list(reg.host), reg.sharding, stall=stall)
        if config.EXCHANGE_WATCHDOG_S > 0 and not stall:
            import jax
            from . import recovery
            recovery.exchange_watchdog(
                "spill.upload", lambda: jax.block_until_ready(list(arrs)),
                stalled=False)
        with self._lock:
            reg.arrays = tuple(arrs)
            reg.host = None
            self._seq += 1
            reg.seq = self._seq
            self.peak = max(self.peak, self.balance())
        _STATS["readmit_events"] += 1
        _STATS["bytes_readmitted"] += reg.nbytes
        timing.add_bytes("spill.upload", reg.nbytes)
        return reg.arrays

    def _spill_cands(self) -> list[Registration]:
        """Spillable, still-resident entries, oldest ``seq`` first — the
        deterministic LRU eviction order."""
        with self._lock:
            return sorted((r for r in self._live.values()
                           if r.spillable and not r.spilled),
                          key=lambda r: r.seq)

    def evict_count_for(self, need: int, budget: int) -> int:
        """How many LRU evictions bring ``balance + need`` under the
        budget (0 when already under or no budget; all candidates when
        even that is insufficient).  A pure function of the ledger — the
        number, not the balance, is what multiprocess sessions agree on
        (max across ranks) before anyone evicts."""
        if budget <= 0:
            return 0
        bal = self.balance()
        if bal + need <= budget:
            return 0
        n = 0
        for r in self._spill_cands():
            n += 1
            bal -= r.nbytes
            if bal + need <= budget:
                break
        return n

    def evict_n(self, n: int, stall: bool = False) -> list[str]:
        """Evict the ``n`` oldest spillable entries (fewer if the ledger
        has fewer candidates).  Returns the evicted owner names in
        eviction order — identical on every rank by construction."""
        evicted: list[str] = []
        for reg in self._spill_cands()[:max(int(n), 0)]:
            if self.evict(reg, stall=stall):
                evicted.append(reg.owner)
        return evicted

    def evict_until(self, need: int, budget: int,
                    stall: bool = False) -> list[str]:
        """Deterministic LRU eviction until ``balance + need`` fits the
        budget (single-controller convenience for
        :func:`evict_count_for` + :func:`evict_n`)."""
        return self.evict_n(self.evict_count_for(need, budget),
                            stall=stall)


_LEDGER = Ledger()


def ledger() -> Ledger:
    return _LEDGER


# ---------------------------------------------------------------------------
# module-level conveniences (the public surface operators use)
# ---------------------------------------------------------------------------

def register(base: str, arrays, spillable: bool = False, sharding=None,
             anchor=None) -> Registration:
    return _LEDGER.register(base, arrays, spillable=spillable,
                            sharding=sharding, anchor=anchor)


def register_table(base: str, table, anchor=None) -> Registration | None:
    """Account a materialized Table's columns (data + validity) under one
    owner; ``anchor`` defaults to the table itself so GC drains the
    ledger (tests assert balance returns to zero after release).
    Unmaterialized DeferredTables are skipped — forcing their thunk here
    would defeat the fused pushdown they exist for."""
    from ..core.table import DeferredTable
    if isinstance(table, DeferredTable) and not table.materialized:
        return None
    arrays = []
    for c in table.columns.values():
        arrays.append(c.data)
        if c.validity is not None:
            arrays.append(c.validity)
    return _LEDGER.register(base, arrays,
                            anchor=table if anchor is None else anchor)


def release(reg) -> None:
    _LEDGER.release(reg)


def touch(reg) -> None:
    _LEDGER.touch(reg)


def device_arrays(reg: Registration) -> tuple | None:
    """The registration's device arrays, or None while spilled."""
    return reg.arrays if not reg.spilled else None


def evict(reg) -> int:
    return _LEDGER.evict(reg)


def readmit(reg) -> tuple:
    return _LEDGER.readmit(reg)


def balance() -> int:
    return _LEDGER.balance()


def over_budget(need: int) -> bool:
    """Would admitting ``need`` more resident bytes exceed the budget?
    Rank-uniform: balance, need and budget are identical across ranks."""
    b = budget_bytes()
    return b > 0 and _LEDGER.balance() + int(need) > b


def try_free(need: int) -> int:
    """Best-effort eviction of ``need`` bytes of headroom at a guard
    call site.  SINGLE-CONTROLLER only: a multiprocess session returns 0
    and defers all eviction to the consensus'd admission path
    (:func:`ensure_headroom`) — the local balance read that would gate a
    rank-local eviction here is only uniform up to GC timing, and the
    eviction's host pulls are themselves collectives, so a rank evicting
    alone would hang its peers.  Returns bytes freed."""
    if not _spill_enabled():
        return 0
    import jax
    if jax.process_count() > 1:
        return 0
    before = _LEDGER.balance()
    _LEDGER.evict_until(int(need), budget_bytes())
    return before - _LEDGER.balance()


def spillable_bytes() -> int:
    return _LEDGER.spillable_bytes()


def ensure_headroom(env, need: int, scratch: int = 0,
                    site: str = "spill.evict", reuse: int = 0) -> None:
    """Admission control for a new resident allocation of ``need`` bytes
    (plus ``scratch`` transient working-set bytes — e.g. the piece
    join's sort-operand footprint, :func:`cylon_tpu.ops.pack.
    sort_operand_nbytes`): when the ledger would exceed the budget, cold
    spillable owners evict (LRU) first.

    ``reuse``: bytes of caller-owned buffers DONATED into the allocating
    program (``donate_argnums`` — docs/pipeline.md donation rules): XLA
    frees/aliases them during the allocation, so peak demand is ``need -
    reuse``, not ``need`` — counting both would double-charge donated
    bytes and evict spillable owners that still fit.  Rank-uniform: the
    donation decision is a config flag plus static shapes, identical on
    every rank.

    Coherence protocol (docs/robustness.md "why eviction is
    collective"): what multiprocess ranks agree on is the eviction
    COUNT — the max over each rank's deterministic
    :meth:`Ledger.evict_count_for` — through the one-int32 consensus
    wire, and every rank then evicts that many oldest candidates.  The
    poll's gating inputs are rank-uniform BY CONSTRUCTION (the armed
    flag and the configured budget; never a raw balance read, whose
    release timing is only uniform up to GC), so in a multiprocess
    session the poll runs whenever a budget is configured at all —
    admissions are rare (per packed source), and a 1-int pmax is noise
    next to the pack it guards.  Single-controller sessions (and any
    session with no budget and no armed injector) skip consensus
    entirely: no collective, no host sync."""
    from . import recovery
    kind, armed = recovery.probe(site)
    if kind in _RAISE_KINDS:
        raise recovery.make_fault(kind, site)
    if reuse:
        _STATS["donated_bytes_reused"] += int(reuse)
    if not _spill_enabled():
        return
    need = max(int(need) + int(scratch) - int(reuse), 0)
    b = budget_bytes()
    import jax
    multi = jax.process_count() > 1
    # rank-uniform poll gate: armed / budget-configured only
    if not (armed or b > 0):
        return
    want = _LEDGER.evict_count_for(need, b)
    if kind is not None and want == 0:
        want = 1  # injected pressure with no real deficit: probe one LRU
    if multi:
        mesh = getattr(env, "mesh", env)
        want = recovery.count_consensus(mesh, want)
    if want <= 0:
        return
    stall = kind in ("stall", "spill_stall")
    evicted = _LEDGER.evict_n(want, stall=stall)
    if evicted:
        from ..utils.logging import log
        log.warning("memory: evicted %s to host under pressure "
                    "(balance %d B, budget %d B)", evicted,
                    _LEDGER.balance(), b)


def spill_for_retry() -> int:
    """The retry ladder's spill rung (docs/robustness.md): evict EVERY
    spillable resident registration to host, freeing the maximum bytes
    without discarding completed work, and report the total freed.  The
    caller (``run_with_recovery``) takes the rung only after BOTH the
    fault type and the spill decision itself have been agreed across
    ranks (``spill_consensus``), so every rank spills the same owners in
    the same order — up to entries a straggling GC already released on
    one rank, which is harmless: the spill transport is collective-free
    (per-shard pulls), so a missing candidate shortens that rank's loop
    without desyncing any collective."""
    if not _spill_enabled():
        return 0
    freed = 0
    with _LEDGER._lock:
        cands = sorted((r for r in _LEDGER._live.values()
                        if r.spillable and not r.spilled),
                       key=lambda r: r.seq)
    for reg in cands:
        freed += _LEDGER.evict(reg)
    return freed


# ---------------------------------------------------------------------------
# window-lifetime residency (cylon_tpu/stream): buffered event-time window
# state lives exactly from first append to watermark close
# ---------------------------------------------------------------------------

def register_window(base: str, arrays, sharding=None,
                    anchor=None) -> Registration:
    """Register one event-time window buffer's arrays as a SPILLABLE
    resident allocation — the streaming tier's window-lifetime eviction
    class: a cold (not-yet-closable) window is a first-class LRU spill
    candidate exactly like a cold tenant's packed source, and the
    watermark close retires it through :func:`evict_release`.  Only the
    stream package (and this module) may call this — lint rule TS110
    (docs/trace_safety.md): window state mutated elsewhere would bypass
    the close lifecycle's accounting."""
    return _LEDGER.register(base, arrays, spillable=True,
                            sharding=sharding, anchor=anchor)


def evict_release(reg: Registration | None) -> int:
    """The window-close lifecycle: device → host → released.  A closed
    window's buffered state is first EVICTED through the spill tier — a
    bit-exact per-shard host pull through the same sanctioned,
    watchdogged transport as any other eviction — then the registration
    is RELEASED and the host copy freed with it; the ledger balance
    drains by the window's full byte count (asserted via
    ``memory.stats()`` deltas in tests/test_stream.py).  The host hop is
    the DELIBERATE cost of the lifecycle contract (docs/streaming.md): a
    closed window's final state takes the identical audited exit path as
    every other residency transition — one ``spill_events`` +
    ``window_evictions`` record with the watchdog covering the pull —
    rather than a silent drop (``release`` alone would also free the
    device references, without the audit record).  A window that ledger
    pressure already spilled skips straight to release.  Returns the
    bytes retired.  TS110-guarded like :func:`register_window`."""
    if reg is None or not reg.live:
        return 0
    nbytes = reg.nbytes
    if not reg.spilled:
        _LEDGER.evict(reg)
    _LEDGER.release(reg)
    _STATS["window_evictions"] += 1
    timing.bump("stream.window_evicted")
    return nbytes


def prefetch_depth(window_pair_bytes: int) -> int:
    """Double-buffer depth for the pipelined join's spilled-window
    uploads: 2 (upload piece r+1 while piece r computes) when the
    budget has headroom for a second window pair, else 1.  Deterministic
    from rank-uniform inputs."""
    b = budget_bytes()
    if b <= 0 or _LEDGER.balance() + 2 * int(window_pair_bytes) <= b:
        return 2
    return 1


def spec_row_bytes(spec) -> int:
    """Resident bytes per row of a packed source: 4 per u32 lane plus 8
    per laneless f64 side column (ops/lanes layout)."""
    n_f64 = sum(1 for c in spec.cols if not c.lanes)
    return 4 * int(spec.n_lanes) + 8 * n_f64


# ---------------------------------------------------------------------------
# host <-> device movement (the TS106-sanctioned residency boundary)
# ---------------------------------------------------------------------------

def _stall_timeout(stall: bool) -> float | None:
    """Watchdog deadline for a spill transfer: the configured exchange
    watchdog, or a short synthetic one when a stall is injected with the
    watchdog off (so the injected hang still surfaces typed)."""
    if stall:
        return config.EXCHANGE_WATCHDOG_S or 0.2
    return None  # exchange_watchdog falls back to the config value


def _put_blocks(blocks: list, sharding):
    """Per-shard host blocks -> one row-sharded device array, the
    TS106-sanctioned upload boundary of the spill tier.  Collective-free
    in multiprocess sessions: ``make_array_from_callback`` asks each
    process only for its ADDRESSABLE shards, which are exactly the
    blocks this process holds (remote entries are None and never
    touched).  Unsharded (test) registrations device_put directly."""
    import jax
    have = [b for b in blocks if b is not None]
    n = have[0].shape[0]
    if sharding is None:
        return jax.device_put(np.concatenate(have))
    if jax.process_count() > 1:
        shape = (len(blocks) * n,) + have[0].shape[1:]

        def cb(idx):
            start = idx[0].start or 0
            i = start // n
            stop = shape[0] if idx[0].stop is None else idx[0].stop
            return blocks[i][start - i * n: stop - i * n]

        return jax.make_array_from_callback(shape, sharding, cb)
    return jax.device_put(np.concatenate(blocks), sharding)


def put_blocks(blocks: list, sharding):
    """Public name for the sanctioned per-shard-blocks upload boundary —
    the durable-checkpoint restore path (exec/checkpoint) re-enters its
    host pages through the SAME transport the spill tier uses, so a
    resumed piece is byte-identical to the resident array it was pulled
    from (and multi-controller restores stay collective-free: each
    process uploads only its addressable blocks)."""
    return _put_blocks(blocks, sharding)


def _upload(hosts, sharding, stall: bool = False):
    """Per-array host shard-block lists -> device (:func:`_put_blocks`).
    The dispatch stays ASYNC — blocking every upload would serialize
    exactly the double-buffered overlap the pipelined loop exists for —
    except under an injected ``spill_stall``, where the readiness check
    runs inside the exchange watchdog so the simulated hang surfaces as
    a typed RankDesyncError at site ``spill.upload``.  (A real upload
    hang surfaces at the consumer's next watchdogged host sync;
    :func:`Ledger.readmit` — the whole-matrix, non-overlapped path —
    additionally blocks under the watchdog when
    ``CYLON_TPU_WATCHDOG_S`` is armed.)"""
    from . import recovery
    kind = recovery.injected("spill.upload")
    if kind in _RAISE_KINDS:
        raise recovery.make_fault(kind, "spill.upload")
    stall = stall or kind in ("stall", "spill_stall")
    devs = tuple(_put_blocks(blocks, sharding) for blocks in hosts)
    if stall:
        import jax
        recovery.exchange_watchdog(
            "spill.upload", lambda: jax.block_until_ready(list(devs)),
            timeout_s=_stall_timeout(True), stalled=True)
    return devs


def upload_window(reg: Registration, starts, window: int):
    """Upload ONE per-shard window ``[starts[i], starts[i]+window)`` of a
    spilled registration's host arrays back to the device (row-sharded)
    — the host-resident PieceSource's piece materialization.  Window
    content is byte-identical to the resident path's dynamic slice, so
    packed joins over uploaded windows are bit-equal to unspilled runs.
    Uploads are async dispatches: the pipelined range loop prefetches
    piece r+1's windows so this overlaps piece r's compute."""
    if not reg.spilled:
        raise ValueError(f"{reg.owner} is device-resident; slice in-program")
    _LEDGER.touch(reg)
    starts = np.asarray(starts, np.int64)
    window = int(window)
    outs = []
    with timing.region("spill.upload"):
        for blocks in reg.host:
            wins: list = [None] * len(blocks)
            for i, blk in enumerate(blocks):
                if blk is None:     # remote shard: another process's block
                    continue
                s = int(starts[i])
                win = np.zeros((window,) + blk.shape[1:], blk.dtype)
                m = min(window, blk.shape[0] - s)
                if m > 0:
                    win[:m] = blk[s:s + m]
                wins[i] = win
            outs.append(wins)
        devs = _upload(outs, reg.sharding)
    moved = _nbytes(devs)
    _STATS["readmit_events"] += 1
    _STATS["bytes_readmitted"] += moved
    timing.add_bytes("spill.upload", moved)
    return devs


# ---------------------------------------------------------------------------
# stats + eviction log (bench detail; cross-rank coherence assertions)
# ---------------------------------------------------------------------------

# counters live in the metrics registry (cylon_tpu.obs.metrics — the
# TS112 facade); this dict-like view keeps every `_STATS[k] += 1` call
# site and the public stats() shim working verbatim
from ..obs import metrics as _metrics  # noqa: E402

_STATS = _metrics.group("memory", (
    "spill_events", "bytes_spilled",
    "readmit_events", "bytes_readmitted",
    "donated_bytes_reused", "cross_session_evictions",
    "window_evictions"))

_metrics.gauge("memory_ledger_bytes",
               help="current resident-ledger balance (bytes)",
               fn=lambda: _LEDGER.balance())
_metrics.gauge("memory_peak_ledger_bytes",
               help="resident-ledger high-water mark (bytes)",
               fn=lambda: _LEDGER.peak)

#: owners in eviction order since the last reset — the multihost driver
#: asserts this sequence is IDENTICAL across ranks
_EVICTION_LOG: list[str] = []


def _note_spill(site: str, reg: Registration) -> None:
    _STATS["spill_events"] += 1
    _STATS["bytes_spilled"] += reg.nbytes
    if reg.session is not None and reg.session != _session_tag():
        # another tenant's resident state evicted under THIS context's
        # pressure (or the scheduler's admission pass, tag None): the
        # serving tier's "evict cold tenants first" event
        _STATS["cross_session_evictions"] += 1
    _EVICTION_LOG.append(reg.owner)
    timing.add_bytes(site, reg.nbytes)
    timing.bump(f"memory.{site}")
    from ..utils.logging import log
    log.info("memory: %s -> host (%d B)", reg.owner, reg.nbytes)


def stats() -> dict:
    """Spill counters for bench JSON detail (alongside recovery_events):
    ``spill_events``/``bytes_spilled`` (device→host evictions),
    ``readmit_events``/``bytes_readmitted`` (host→device re-entries),
    ``donated_bytes_reused`` (admission credit for buffers donated into
    the allocating program — bytes the ledger did NOT double-count),
    ``cross_session_evictions`` (one tenant's registrations evicted under
    another tenant's — or the scheduler's — admission pressure),
    ``window_evictions`` (closed event-time windows retired through the
    device→host→released lifecycle, :func:`evict_release`) and
    ``peak_ledger_bytes`` (high-water resident balance)."""
    return dict(_STATS, peak_ledger_bytes=_LEDGER.peak,
                ledger_bytes=_LEDGER.balance())


def eviction_log() -> list[str]:
    return list(_EVICTION_LOG)


def reset_stats() -> None:
    """Zero the counters, the eviction log and the peak high-water mark
    (live registrations are untouched — their handles stay valid)."""
    for k in _STATS:
        _STATS[k] = 0
    _EVICTION_LOG.clear()
    _LEDGER.peak = _LEDGER.balance()
