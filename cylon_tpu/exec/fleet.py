"""Elastic serving fleet control: chaos-proven mesh resize under live
traffic (docs/serving.md, "Preemption & elastic serving").

The serving scheduler multiplexes many tenants over ONE mesh whose
world size is fixed at process launch; resizing the fleet therefore
means draining the whole box and relaunching at the new world — the
same planned-scale-down protocol the preemption grace path uses
(exec/preempt → exec/checkpoint), but DRIVEN BY LOAD instead of a
SIGTERM.  :class:`ResizeController` is that driver: the scheduler
polls :meth:`maybe_resize` once per baton turn, and when the local
pressure signals (admission queue depth, realized ledger pressure)
say the current world is wrong AND at least a minimum amount of work
has been durably committed, the controller engages the scheduler's
all-or-nothing fleet drain:

* every RUNNING tenant is flagged; each drains at its own next
  checkpoint boundary (commits, raises typed ``ResumableAbort`` —
  rank-coherent over the session-namespaced drain wire);
* PENDING tenants fail typed-resumable (nothing committed, a resume
  simply recomputes them);
* the caller observes ``scheduler.resize_target`` set, writes nothing
  else, and exits ``RESUMABLE_EXIT``; the supervisor relaunches at the
  new world with ``CYLON_TPU_RESUME=1`` and every tenant resumes —
  same-topology stages fast-forward bit-identically, different-world
  stages take the PR 9 base-token re-shard path.

**All-or-nothing, voted.**  Realized ledger pressure is rank-LOCAL, so
in multiprocess sessions the engage decision is agreed over the count
wire (max target wins — if ANY rank wants the resize, every rank
drains): a rank draining its tenants while its peers keep granting
them is exactly the desync the consensus module exists to prevent.
The vote is entered every poll while a controller is attached
(armed-only: attaching a controller requires durable checkpointing),
so the vote structure is rank-uniform by construction; schedulers
without a controller — the happy path — add zero collectives.

A ``FLEET_RESIZE.json`` breadcrumb with the agreed target world lands
in the checkpoint root next to ``RESUME_TOKEN.json`` so the relauncher
(`scripts/chaos_soak.py --fleet`, the deploy/gke scale drill) can read
the decision back without parsing logs.
"""

from __future__ import annotations

import json
import os

from ..status import InvalidError


class ResizeController:
    """Queue-depth / ledger-pressure resize driver for the serving
    scheduler.  Pass as ``QueryScheduler(env, fleet=...)``.

    ``target_world`` is the world size to relaunch at.  The drain
    engages when EITHER trigger fires: admission queue depth (pending
    sessions) reaches ``queue_depth_high``, or the realized resident
    ledger balance exceeds ``ledger_frac_high`` of the budget — and at
    least ``min_committed_pieces`` checkpoint pieces are durable across
    the session set (resizing a fleet that has committed nothing would
    just be a restart).  Either trigger may be None (disabled)."""

    def __init__(self, env, *, target_world: int,
                 queue_depth_high: int | None = 2,
                 ledger_frac_high: float | None = None,
                 min_committed_pieces: int = 1):
        if int(target_world) < 1:
            raise InvalidError(
                f"resize target world {target_world!r} must be >= 1")
        self.env = env
        self.target_world = int(target_world)
        self.queue_depth_high = queue_depth_high
        self.ledger_frac_high = ledger_frac_high
        self.min_committed_pieces = int(min_committed_pieces)
        self.engaged = False

    # -- local pressure signals --------------------------------------------
    def pressure(self, sched) -> dict:
        """The rank-local observation the decision is made from (also
        exported into the breadcrumb for postmortems)."""
        from . import memory
        from .session import PENDING
        queue_depth = sum(1 for s in sched.sessions
                          if s.state == PENDING)
        committed = sum(s.pieces_committed for s in sched.sessions)
        mem = memory.stats()
        budget = memory.budget_bytes()
        frac = (mem["ledger_bytes"] / budget) if budget > 0 else 0.0
        return {"queue_depth": queue_depth,
                "pieces_committed": committed,
                "ledger_bytes": mem["ledger_bytes"],
                "ledger_frac": round(frac, 4)}

    def should_resize(self, sched) -> bool:
        """Rank-local decision (consensus reconciles divergence)."""
        p = self.pressure(sched)
        if p["pieces_committed"] < self.min_committed_pieces:
            return False
        if (self.queue_depth_high is not None
                and p["queue_depth"] >= self.queue_depth_high):
            return True
        if (self.ledger_frac_high is not None
                and p["ledger_frac"] >= self.ledger_frac_high):
            return True
        return False

    # -- the scheduler hook ------------------------------------------------
    def maybe_resize(self, sched) -> bool:
        """Polled by the scheduler loop once per baton turn.  Votes the
        local decision over the count wire (max target wins) and, on
        agreement, engages the all-or-nothing fleet drain.  Returns
        True when the drain engaged this call."""
        if self.engaged or sched._fleet_drain:
            return False
        from . import checkpoint
        if not checkpoint.enabled():
            # nothing durable to resume from: a drain now would lose
            # work, which is the one thing this tier must never do
            return False
        want = self.target_world if self.should_resize(sched) else 0
        if sched._multi():
            from . import recovery
            want = recovery.count_consensus(self.env.mesh, want)
        if not want:
            return False
        self.engaged = True
        info = self.pressure(sched)
        self._write_breadcrumb(want, info)
        sched._begin_fleet_drain(
            want, f"queue_depth={info['queue_depth']} "
                  f"ledger_frac={info['ledger_frac']}")
        return True

    def _write_breadcrumb(self, target_world: int, info: dict) -> None:
        from . import checkpoint
        root = checkpoint.ckpt_dir()
        try:
            os.makedirs(root, exist_ok=True)
            path = os.path.join(root, "FLEET_RESIZE.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump({"target_world": int(target_world),
                           "from_world": int(self.env.world_size),
                           "pid": os.getpid(), **info}, f)
        except OSError:
            pass  # the committed manifests are the durable state; the
            # breadcrumb is best-effort, like RESUME_TOKEN.json
