"""Rank-coherent failure recovery: classification, consensus, injection.

Cylon's distributed operators are ``local partition → all-to-all shuffle →
local op`` (SURVEY §0), which on TPU makes every failure-recovery decision
a COLLECTIVE decision: if one rank's receive-budget guard fires and it
retries at a different chunk count while its peers proceed, the next
collective deadlocks the whole mesh.  This module is the one place those
decisions are made, built on four pillars (docs/robustness.md):

1. **Typed fault taxonomy** (classes live in :mod:`cylon_tpu.status`):
   :class:`~cylon_tpu.status.PredictedResourceExhausted` (guard fired
   pre-allocation, HBM not poisoned — safe in-process retry),
   :class:`~cylon_tpu.status.DeviceOOMError` (real XLA
   RESOURCE_EXHAUSTED), :class:`~cylon_tpu.status.CapacityOverflowError`
   (pow2 piece/output cap exceeded) and
   :class:`~cylon_tpu.status.RankDesyncError` (peer hang / structural
   divergence).  :func:`classify` is the ONLY sanctioned place that
   string-matches runtime OOM text (lint rule TS105 enforces this).

2. **Rank-coherent retry ladder** (:func:`run_with_recovery`): in a
   multiprocess (``jax.distributed``) session, ranks all-reduce a small
   status code — max over :class:`~cylon_tpu.status.Code` values via a
   one-element ``pmax`` shard_map program — after every guarded attempt,
   so every rank takes the IDENTICAL branch: same fallback chunk count,
   same cap-halving step, or same typed abort.  Escalation is bounded and
   deterministic (predicted OOM: spill-then-retry at the SAME chunk
   count first — the host spill tier, :mod:`cylon_tpu.exec.memory`,
   frees resident bytes without discarding completed work — then chunks
   4 → 16; capacity overflow: one cap-halving step at 8 chunks), nested
   ladders never re-escalate (the outer ladder owns the rungs), and
   every recovery event is logged and counted in
   :mod:`cylon_tpu.utils.timing` phase stats.

3. **Fault injection** (``CYLON_TPU_FAULTS="site[:rank][:nth]=kind"``):
   each typed fault is constructible at its named site on the CPU rig, so
   the whole ladder is testable without a real device OOM.  Sites:
   ``shuffle.recv_guard``, ``join.piece_cap``, ``groupby.device_oom``,
   ``exchange.stall``, ``spill.evict``, ``spill.upload``.  Kinds:
   ``predicted``, ``device_oom``, ``capacity``, ``desync``, ``stall``
   (fires inside the watchdog) and ``spill_stall`` (hangs a spill-tier
   host↔device transfer; at ``spill.evict`` the ``predicted`` kind
   simulates rank-local memory PRESSURE — consensus'd, then evicted —
   rather than raising).  ``rank`` defaults to every rank (``*``);
   ``nth`` is the 1-based occurrence to fire on (default 1; ``*`` =
   every occurrence).

4. **Exchange watchdog** (:func:`exchange_watchdog`): an optional timeout
   (``CYLON_TPU_WATCHDOG_S``) around multihost exchange host-syncs that
   converts a peer hang into a typed
   :class:`~cylon_tpu.status.RankDesyncError` carrying the site and the
   last-known timing phase, instead of an infinite block.

The rank-coherence invariant underlying all of this: **no rank-local
control flow after a collective has been entered** — any guard that can
abort an exchange must take its raise/proceed decision through
:func:`guard_consensus` BEFORE the first collective of that exchange is
dispatched.

**Serving-session isolation** (:mod:`cylon_tpu.exec.scheduler`): when
the multi-tenant scheduler interleaves concurrent queries, each session
runs on its own thread tagged via :func:`set_session`.  Three things
follow from the tag: (1) recovery EVENTS carry the session name, so one
tenant's retry ladder is auditable in isolation
(:func:`events_for_session`) and never pollutes another's log; (2) the
injection grammar grows an optional ``@session`` selector
(``site[:rank][:nth]=kind@tenant``, with ``nth`` counted against the
TARGET session's own probes) so chaos schedules can fault one tenant
while its neighbors run clean; (3) the guard/spill/ladder consensus
wires carry a small session NAMESPACE field above the payload — in a
multiprocess session a rank that enters a consensus poll while a peer is
voting from a different session raises a typed
:class:`RankDesyncError` instead of silently adopting a foreign
tenant's fault code.  The ladder's nesting depth (``_tls.depth``) is
already thread-local, so concurrent ladders never see each other's
escalation state.
"""

from __future__ import annotations

import errno
import os
import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import config
from ..ctx.context import ROW_AXIS
from ..obs import trace as _trace
from ..status import (CapacityOverflowError, CheckpointCorruptError, Code,
                      CylonError, DataIntegrityError, DeviceOOMError,
                      FAULT_TYPES, PredictedResourceExhausted,
                      RankDesyncError, ResumableAbort)
from ..utils.cache import program_cache

shard_map = jax.shard_map

#: injection site names (docs/robustness.md spec grammar).  The spill
#: sites (exec/memory): ``spill.evict`` is probed by the ledger's
#: admission path — kind ``predicted`` there simulates rank-local
#: memory PRESSURE (consensus'd, then evicted) rather than raising —
#: and ``spill.upload`` guards the host→device re-entry of spilled
#: windows.  The checkpoint sites (exec/checkpoint): ``ckpt.write``
#: wraps the page write + manifest commit of one piece, ``ckpt.load``
#: the resume-path restore — kind ``corrupt`` there corrupts (or
#: simulates detecting a corrupted) page instead of raising.
#: ``pipe.phase_sync`` is the overlap scheduler's designated pre-loop
#: batched pull (exec/pipeline._pull_phase_outputs) — injecting there
#: proves deferred-phase faults surface typed at the consensus-coherent
#: sync point, not inside an arbitrary later pull.  The stream sites
#: (cylon_tpu/stream): ``stream.append`` wraps one micro-batch's ingest
#: (shuffle + ledger admission + sink absorb) — ``kill`` there is the
#: chaos harness's mid-ingest crash — and ``stream.watermark`` wraps the
#: watermark min-vote that closes event-time windows.  ``ckpt.reshard``
#: wraps the elastic resume's foreign-rank page read + re-shard
#: (exec/checkpoint.load_foreign_pieces): ``corrupt`` there simulates a
#: failed foreign-page hash check (the stage degrades to recompute,
#: never a wrong answer) and ``kill`` crashes mid-reshard — the resumed
#: rerun must converge anyway.
#: ``obs.export`` wraps the flight recorder's Chrome-trace write
#: (cylon_tpu/obs/trace.export): injecting there proves a hung or
#: corrupt trace write surfaces TYPED instead of silently losing the
#: timeline the operator armed.  The disk-tier sites (exec/memory):
#: ``disk.write`` wraps one registration's host→disk demotion (kinds
#: ``corrupt`` = flip a page byte after hashing so the promote-side
#: verification catches it, ``stall`` = hang the page write inside the
#: watchdog, ``enospc`` = the write fails with a non-transient
#: ``OSError(ENOSPC)`` and the demotion degrades to keeping the page
#: host-resident — never a crash) and ``disk.read`` wraps the
#: disk→host/device promotion's verify pass (``corrupt`` simulates a
#: failed sha check — the owner degrades to recompute, never a wrong
#: answer; ``stall`` hangs the verify read inside the watchdog).
#: ``sched.preempt`` fires at a serving session's preemptive/fleet
#: drain boundary (exec/checkpoint.drain_requested, on the VICTIM's
#: thread — so ``@session`` targets the drained tenant and ``nth``
#: counts its own drain boundaries): ``stall`` widens the drain window,
#: ``kill``/``term`` deliver the signal mid-drain — the chaos-soak
#: schedule proving a crash DURING a preemption drain still resumes
#: every tenant bit-identically (docs/serving.md, docs/robustness.md).
#: ``compile.build`` guards every facade-routed compile
#: (exec/compiler._lifecycle): ``stall`` hangs the build inside the
#: compile watchdog (typed CompileTimeoutError), ``kill`` SIGKILLs
#: mid-compile AFTER the intent journal hit disk (the quarantine
#: drill), and ``corrupt`` poisons the persistent warm-manifest entry
#: the facade just wrote — the next process must drop it on the hash
#: check (clean miss), never load wrong code.
#: The integrity-audit sites (exec/integrity, docs/robustness.md
#: "Integrity audit tier"): ``exchange.corrupt`` fires just AFTER an
#: exchange delivered its buffers — kind ``corrupt`` is INTERCEPTED
#: there and flips one element of one received column in place (rank/
#: nth/``@session``-selectable), the silent-corruption drill the armed
#: fingerprint layer must catch; and ``audit.verify`` wraps the armed
#: fingerprint verification's consensus pull — ``stall`` there hangs
#: the audit vote inside the exchange watchdog (typed RankDesyncError,
#: never a hang).
SITES = ("shuffle.recv_guard", "join.piece_cap", "groupby.device_oom",
         "exchange.stall", "spill.evict", "spill.upload",
         "disk.write", "disk.read",
         "ckpt.write", "ckpt.load", "ckpt.reshard", "pipe.phase_sync",
         "stream.append", "stream.watermark", "obs.export",
         "sched.preempt", "compile.build",
         "exchange.corrupt", "audit.verify")

#: fault kinds accepted by the injection grammar; ``spill_stall`` hangs
#: a spill-tier host↔device transfer inside the watchdog (the spill
#: analog of ``stall``); ``corrupt`` flips checkpoint page bytes (write)
#: or simulates a failed hash check (load/reshard); ``enospc`` makes a
#: disk-tier page write fail with a NON-transient ``OSError(ENOSPC)``
#: (the bounded IO retry gives up immediately — a full disk does not
#: heal in milliseconds — and the demotion degrades in-memory);
#: ``kill`` SIGKILLs the PROCESS at the site — the chaos-soak harness's
#: hard-crash primitive (the parent reruns the workload with
#: ``CYLON_TPU_RESUME=1``) — and ``term`` delivers SIGTERM to the
#: process at the site: the spot-VM preemption notice (exec/preempt) —
#: with the grace handler armed the process keeps running and DRAINS at
#: its next checkpoint boundary; unarmed, default disposition applies,
#: exactly like a real preemption
KINDS = ("predicted", "device_oom", "capacity", "desync", "stall",
         "spill_stall", "corrupt", "enospc", "kill", "term")


# ---------------------------------------------------------------------------
# classification — the sanctioned string-matching boundary (TS105)
# ---------------------------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


def is_oom(e: Exception) -> bool:
    """Device out-of-memory, as surfaced by XLA/PJRT (either a typed
    taxonomy OOM or a foreign runtime error carrying the XLA text)."""
    if isinstance(e, (PredictedResourceExhausted, DeviceOOMError)):
        return True
    s = str(e)
    return any(m in s for m in _OOM_MARKERS)


def classify(e: Exception) -> CylonError | None:
    """Map an exception onto the typed fault taxonomy.

    Typed faults pass through unchanged.  Foreign exceptions carrying XLA
    OOM text become :class:`PredictedResourceExhausted` (when the message
    says ``(predicted)`` — the pre-allocation guard shape) or
    :class:`DeviceOOMError`, with the original on ``__cause__``.  A
    :class:`CheckpointCorruptError` from a DISK-TIER site (``disk.*``,
    exec/memory) is a fault too: a corrupt spill page's owner has no
    other copy of its data, so the ladder's remedy is ONE recompute of
    the stage at the same streaming configuration (never a wrong
    answer).  Checkpoint-site corruption keeps its existing non-fault
    classification — the pipeline handles it locally (restore degrades
    to recompute of remaining pieces).  Returns ``None`` for everything
    else (not a recovery fault: re-raise it)."""
    if isinstance(e, FAULT_TYPES):
        return e
    if isinstance(e, CheckpointCorruptError) \
            and str(getattr(e, "site", "") or "").startswith("disk."):
        return e
    if isinstance(e, CylonError):
        return None  # typed engine errors (Invalid/Type/...) are not faults
    s = str(e)
    if any(m in s for m in _OOM_MARKERS):
        cls = (PredictedResourceExhausted if "(predicted)" in s
               else DeviceOOMError)
        fault = cls(s)
        fault.__cause__ = e
        return fault
    return None


# ---------------------------------------------------------------------------
# compiler-crash classification — probe-compiled per process (VERDICT 8)
# ---------------------------------------------------------------------------

#: [tuple] once probed; empty = not yet.  The base set is the
#: platform-independent shape of a compiler-process death (signal names,
#: Mosaic's own marker); the probe refines it per backend.
_CRASH_SIG_CACHE: list = []

_BASE_CRASH_SIGS = ("tpu_compile_helper", "SIGSEGV",
                    "Mosaic failed to compile")


def compiler_crash_signatures() -> tuple:
    """The platform's compiler-crash message signatures, classified ONCE
    per process by a probe compile (primed at first env creation,
    ``ctx/context.CylonEnv``) instead of a hard-coded substring list at
    every call site: the probe compiles a trivial program on the active
    backend, confirming which surfacing path a compiler death would take
    — a directly-attached TPU VM dies in the ``tpu_compile_helper``
    subprocess, the axon remote-compile tunnel surfaces the same death
    through its ``remote_compile`` HTTP shim — and pins the signature
    set for the process.  ``CYLON_TPU_CRASH_SIGS`` (``|``-separated)
    overrides the set entirely, which is how tests prove the pad ladder
    still engages under a synthetic signature change."""
    env_sigs = os.environ.get("CYLON_TPU_CRASH_SIGS")
    if env_sigs is not None:
        return tuple(s for s in env_sigs.split("|") if s)
    if _CRASH_SIG_CACHE:
        return _CRASH_SIG_CACHE[0]
    sigs = list(_BASE_CRASH_SIGS)
    try:
        import jax.numpy as jnp
        platform = jax.devices()[0].platform
        # probe compile: a working toolchain proves the backend is live
        # and tells us HOW its compiles run (in-process on CPU, helper
        # subprocess / remote tunnel on TPU); rides the facade pinned —
        # the probe must run even while the lifecycle is quarantining
        from .compiler import jit as _jit
        _jit(lambda x: x + 1, pinned=True)(jnp.zeros((), jnp.int32))
        if platform == "tpu":
            sigs.append("remote_compile")
    except Exception:  # noqa: BLE001 — no backend yet: defaults stand,
        return tuple(sigs)  # re-probe on the next call
    _CRASH_SIG_CACHE.append(tuple(sigs))
    return _CRASH_SIG_CACHE[0]


def is_compiler_crash(e: Exception) -> bool:
    """True when the XLA compiler process died (SIGSEGV landmines: f64
    sort payloads and specific gather lane widths, v5e libtpu 2026-07)
    rather than the program being invalid — matched against the
    per-process probed signature set, so the pad ladder
    (``relational/groupby._pad_ladder``) engages on whatever surfacing
    shape THIS platform produces."""
    s = str(e)
    return any(sig in s for sig in compiler_crash_signatures())


def prime_compiler_probe() -> None:
    """Run (and cache) the compiler-crash signature probe — called at
    first env creation so the classification is settled before any
    operator's compile ladder can need it."""
    compiler_crash_signatures()


# ---------------------------------------------------------------------------
# serving-session identity (exec/scheduler tags each session's thread)
# ---------------------------------------------------------------------------

def set_session(name: str | None, ordinal: int | None = None) -> None:
    """Tag recovery state on THIS thread with a serving-session identity
    (the scheduler calls this on each session's thread): recorded events
    carry the session name, ``@session``-selective injector specs match
    against it, and the consensus wires ride its namespace.  ``None``
    clears the tag (the default, and the whole-process single-query
    behavior — nothing changes outside a scheduler)."""
    _tls.session = name
    _tls.session_ord = ordinal


def current_session() -> str | None:
    """The serving-session name tagged on this thread, or None."""
    return getattr(_tls, "session", None)


def _session_ns() -> int:
    """Small per-session consensus-wire namespace: 0 with no session
    tagged, else 1 + (ordinal mod 30) — enough to catch ranks voting
    from different sessions without outgrowing the int32 wire."""
    o = getattr(_tls, "session_ord", None)
    return 0 if o is None else 1 + (int(o) % 30)


def events_for_session(name: str) -> list[dict]:
    """Recorded recovery events tagged with serving session ``name`` —
    the per-tenant isolation audit (tests/test_scheduler.py asserts one
    tenant's ladder leaves its neighbors' logs empty)."""
    return [e for e in _EVENTS if e.get("session") == name]


# ---------------------------------------------------------------------------
# fault injection harness
# ---------------------------------------------------------------------------

class _FaultSpec:
    __slots__ = ("site", "rank", "nth", "kind", "session", "fired")

    def __init__(self, site: str, rank, nth, kind: str, session=None):
        self.site = site
        self.rank = rank      # int or None (= every rank)
        self.nth = nth        # int (1-based) or None (= every occurrence)
        self.kind = kind
        self.session = session  # str or None (= any serving session)
        self.fired = False


_FAULTS: list[_FaultSpec] | None = None   # None = parse env on first probe
_HITS: dict = {}    # occurrence counters: site -> n, (site, session) -> n


def _parse_faults(spec: str) -> list[_FaultSpec]:
    out = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        lhs, _, kind = entry.partition("=")
        # optional trailing @session selector: the spec fires only on a
        # thread tagged with that serving session (exec/scheduler), and
        # its `nth` counts against THAT session's own probe sequence
        kind, _, session = kind.strip().partition("@")
        session = session.strip() or None
        kind = kind.strip()
        if kind not in KINDS:
            raise ValueError(
                f"CYLON_TPU_FAULTS: unknown kind {kind!r} in {entry!r}; "
                f"kinds: {KINDS}")
        parts = lhs.strip().split(":")
        site = parts[0]
        if site not in SITES:
            raise ValueError(
                f"CYLON_TPU_FAULTS: unknown site {site!r} in {entry!r}; "
                f"sites: {SITES}")
        rank = None
        nth: int | None = 1
        if len(parts) > 1 and parts[1] not in ("", "*"):
            rank = int(parts[1])
        if len(parts) > 2:
            nth = None if parts[2] == "*" else int(parts[2])
        if len(parts) > 3:
            raise ValueError(f"CYLON_TPU_FAULTS: bad entry {entry!r} "
                             "(grammar: site[:rank][:nth]=kind[@session])")
        out.append(_FaultSpec(site, rank, nth, kind, session))
    return out


def install_faults(spec: str | None) -> None:
    """(Re)program the injector: ``spec`` in the env-var grammar, ``""``
    to disarm, ``None`` to re-read ``CYLON_TPU_FAULTS`` from the
    environment.  FULLY resets injector state either way: armed ``nth``
    occurrence counters, one-shot ``fired`` flags AND the recorded
    recovery-event log — so back-to-back chaos-soak iterations (and
    tests) start from a clean slate instead of inheriting the previous
    schedule's hit counts (which would silently shift every ``nth``
    spec by the prior iteration's probe count)."""
    global _FAULTS
    _HITS.clear()
    _EVENTS.clear()
    if spec is None:
        spec = os.environ.get("CYLON_TPU_FAULTS", "")
    _FAULTS = _parse_faults(spec)


def probe(site: str) -> tuple[str | None, bool]:
    """Probe the injector at a named site → ``(kind, armed)``.

    ``kind`` is the fault kind firing on THIS rank at this occurrence
    (consuming one-shot specs), or None.  ``armed`` is True while ANY
    spec could still fire at this site on ANY rank — computed from the
    spec list and the per-site hit counter only, both of which advance
    identically on every rank of an SPMD session (same env var / same
    ``install_faults`` call, probes at the same program points), so
    ``armed`` is rank-UNIFORM even when ``kind`` is rank-selective.
    Guards use it to decide — coherently — whether a consensus poll is
    needed at all.

    ``@session``-selective specs match only on a thread tagged with that
    serving session (:func:`set_session`), and their ``nth`` counts
    against the TARGET session's own probe sequence at the site — a
    co-tenant's interleaved probes never shift the firing point."""
    global _FAULTS
    if _FAULTS is None:
        install_faults(None)
    if not _FAULTS:
        return None, False
    _HITS[site] = hit = _HITS.get(site, 0) + 1
    sess = current_session()
    sess_hit = hit
    if sess is not None:
        skey = (site, sess)
        _HITS[skey] = sess_hit = _HITS.get(skey, 0) + 1
    rank = jax.process_index()

    def _could_fire(f) -> bool:
        """Could this spec still fire at this site on ANY rank?  Must be
        computed from rank-UNIFORM state only — the per-site and
        per-(site, session) hit counters, which advance identically on
        every rank (same program points; scheduled sessions are
        pick-consensus-aligned) — never from the rank-local ``fired``
        flag: a rank+session-selective one-shot flips ``fired`` only on
        the firing rank, and an armed flag keyed on it would diverge
        the guards' consensus-poll gating across ranks."""
        if f.site != site:
            return False
        if f.nth is None:
            return True                      # every-occurrence: always
        if f.session is None:
            return f.nth >= hit              # pre-session semantics
        if f.session == sess:
            return f.nth >= sess_hit         # this probe included
        # another session's spec: its NEXT probe is occurrence +1
        return f.nth >= _HITS.get((site, f.session), 0) + 1

    # armed BEFORE consuming one-shots (the firing probe itself reads
    # as armed, exactly like the pre-session semantics)
    armed = any(_could_fire(f) for f in _FAULTS)
    kind = None
    for f in _FAULTS:
        if f.site != site or f.fired:
            continue
        if f.rank is not None and f.rank != rank:
            continue
        if f.session is not None and f.session != sess:
            continue
        if f.nth is not None and f.nth != (sess_hit if f.session is not None
                                           else hit):
            continue
        f.fired = f.nth is not None
        kind = f.kind
        break
    return kind, armed


def injected(site: str) -> str | None:
    """Probe the injector at a named site: counts the occurrence and
    returns the armed fault kind (consuming one-shot specs), or None."""
    return probe(site)[0]


def faults_declare(site: str) -> bool:
    """True when any installed (or env-declared) spec names ``site`` —
    a STATIC query that consumes no occurrence counter, for facades that
    arm a guarded slow path only while their site could ever fire
    (exec/compiler.armed)."""
    global _FAULTS
    if _FAULTS is None:
        install_faults(None)
    return any(f.site == site for f in _FAULTS)


def make_fault(kind: str, site: str) -> Exception:
    """The typed (or deliberately foreign) exception for an injected
    fault.  ``device_oom`` returns a FOREIGN RuntimeError carrying the
    XLA message shape so the injection also exercises :func:`classify`."""
    if kind == "predicted":
        return PredictedResourceExhausted(
            f"RESOURCE_EXHAUSTED (predicted): injected fault at {site}",
            site=site)
    if kind == "device_oom":
        return RuntimeError(
            f"RESOURCE_EXHAUSTED: injected device OOM at {site}")
    if kind == "capacity":
        return CapacityOverflowError(f"injected capacity overflow at {site}",
                                     site=site)
    if kind == "corrupt":
        return CheckpointCorruptError(
            f"injected checkpoint corruption at {site}", site=site)
    if kind == "enospc":
        return OSError(errno.ENOSPC, f"injected ENOSPC at {site}")
    return RankDesyncError(f"injected rank desync at {site}", site=site,
                           phase=_last_phase())


def hard_kill(site: str) -> None:
    """The ``kill`` fault kind: SIGKILL this process at ``site`` — the
    chaos-soak harness's hard-crash primitive (a libtpu/compiler crash
    takes the process down with no Python unwind; SIGKILL is the honest
    simulation).  The parent harness restarts the workload with
    ``CYLON_TPU_RESUME=1`` against the surviving committed checkpoints."""
    import signal
    from ..utils.logging import log
    log.warning("recovery: injected kill at %s — SIGKILL self", site)
    try:
        # flight-recorder breadcrumb: SIGKILL allows no Python unwind,
        # so the postmortem dump (obs/trace, armed runs only) is written
        # HERE — the one place the process still runs — landing next to
        # the checkpoint manifests like the drain-path dump does
        from ..obs import trace
        trace.postmortem(f"injected kill at {site}")
    except Exception:  # noqa: BLE001 — the kill must proceed regardless
        pass
    os.kill(os.getpid(), signal.SIGKILL)


def soft_term(site: str) -> None:
    """The ``term`` fault kind: deliver SIGTERM to THIS process at
    ``site`` — the spot-VM preemption notice (exec/preempt,
    docs/robustness.md "Elastic resume & preemption grace").  With the
    grace handler armed (``CYLON_TPU_PREEMPT_GRACE_S``) the handler
    only sets a flag and the process drains at its next checkpoint
    boundary; unarmed, the default disposition terminates the process —
    both are exactly what a real preemption does."""
    import signal
    from ..utils.logging import log
    log.warning("recovery: injected preemption notice at %s — SIGTERM self",
                site)
    os.kill(os.getpid(), signal.SIGTERM)


def maybe_inject(site: str, intercept: tuple = ()) -> str | None:
    """Raise the armed fault for ``site`` (no-op when nothing is armed).
    Call at each named injection point.  The ``kill`` kind never raises:
    it SIGKILLs the process.  The ``term`` kind never raises either: it
    delivers SIGTERM (the preemption notice) and execution continues to
    the next checkpoint boundary's drain poll.  Kinds named in
    ``intercept`` are RETURNED for site-specific handling instead of
    recorded-and-raised (the checkpoint sites intercept ``corrupt``: on
    write it flips page bytes after hashing rather than raising)."""
    kind = injected(site)
    if kind is None:
        return None
    if kind == "kill":
        hard_kill(site)
    if kind == "term":
        _record(site, kind, "sigterm")
        soft_term(site)
        return None
    if kind in intercept:
        return kind
    _record(site, kind, "injected")
    raise make_fault(kind, site)


# ---------------------------------------------------------------------------
# recovery-event log
# ---------------------------------------------------------------------------

_EVENTS: list[dict] = []


def _last_phase() -> str:
    from ..utils import timing
    return timing.last_region()


def _record(site: str, kind: str, action: str) -> None:
    from ..utils import timing
    from ..utils.logging import log
    ev = {"site": site, "kind": kind, "action": action}
    sess = current_session()
    if sess is not None:
        # serving sessions get per-tenant audit trails; the key is
        # absent outside a scheduler so single-query logs are unchanged
        ev["session"] = sess
    _EVENTS.append(ev)
    timing.bump(f"recovery.{site}.{kind}.{action}")
    log.warning("recovery: %s fault at %s -> %s", kind, site, action)


def recovery_events() -> list[dict]:
    """Events recorded since the last :func:`reset_events`/:func:`drain_events`
    (each ``{"site", "kind", "action"}``), oldest first."""
    return list(_EVENTS)


def drain_events() -> list[dict]:
    out = list(_EVENTS)
    _EVENTS.clear()
    return out


def reset_events() -> None:
    _EVENTS.clear()


# ---------------------------------------------------------------------------
# SPMD consensus: all-reduce (max) one status code across ranks
# ---------------------------------------------------------------------------

@program_cache()
def _consensus_fn(mesh: Mesh, w: int):
    """One int32 status code per shard → the elementwise pmax, replicated.
    The whole program is one unconditional collective — the minimal
    rank-coherence primitive (docs/robustness.md)."""

    def per_shard(code):
        return jax.lax.pmax(code, ROW_AXIS)

    # pinned: the consensus wire must never be evicted, journaled or
    # fault-injected — it IS the mechanism coordinating those
    from .compiler import jit as _jit
    return _jit(shard_map(per_shard, mesh=mesh, in_specs=(P(ROW_AXIS),),
                          out_specs=P()), pinned=True)


def _consensus_wire(mesh: Mesh | None, wire: int) -> int:
    """Max-reduce one raw int32 across ranks — the transport for both
    :func:`consensus_code` (plain Code) and the ladder's type-carrying
    wire encoding (:func:`_wire_code`).  Single-controller sessions have
    no rank-divergent control flow by construction, so the local value
    IS the consensus; multiprocess sessions run the one-element pmax
    program — every rank must call this at the same point (it is a
    collective), and the result pull runs under the exchange watchdog."""
    if mesh is None or jax.process_count() == 1:
        return int(wire)
    w = int(mesh.devices.size)
    sharding = NamedSharding(mesh, P(ROW_AXIS))
    arr = jax.make_array_from_callback(
        (w,), sharding, lambda idx: np.full((1,), int(wire), np.int32))
    res = _consensus_fn(mesh, w)(arr)
    return exchange_watchdog("exchange.consensus",
                             lambda: int(np.asarray(res)[0]))


def _ns_consensus(mesh: Mesh | None, payload: int, base: int,
                  what: str) -> int:
    """Max-reduce ``payload`` (< ``base``) with the serving-session
    namespace riding ABOVE it: ``wire = ns * base + payload``.  With no
    session tagged (ns = 0, the single-query default) this is exactly
    the plain wire.  In a multiprocess session, an agreed wire whose
    namespace differs from this rank's means a peer entered the poll
    from a DIFFERENT serving session — a scheduler interleave divergence
    — and adopting its payload would hand one tenant another tenant's
    fault, so it raises typed instead (docs/serving.md, recovery
    isolation).

    Detection is deliberately ONE-SIDED: the max-reduce surfaces the
    collision on every rank whose namespace is BELOW the agreed one;
    the highest-namespace rank sees its own ns win and proceeds — until
    its now-aborted peers leave it alone in its next collective, where
    the exchange watchdog converts the hang into the same typed desync.
    A ckpt-commit-style complemented second round would make detection
    symmetric, but would double the consensus cost of EVERY guarded
    operator in multiprocess sessions to harden a divergence the
    scheduler's pick consensus (exec/scheduler._pick) already prevents
    upstream; this layer is defense-in-depth, not the primary fence."""
    ns = _session_ns()
    agreed = _consensus_wire(mesh, ns * base + int(payload))
    _trace.instant("consensus." + what, wire=int(agreed))
    if agreed // base != ns:
        raise RankDesyncError(
            f"cross-session consensus collision at {what}: this rank "
            f"voted in session namespace {ns}, the agreed wire is from "
            f"namespace {agreed // base} — ranks are interleaving "
            "different serving sessions", site=what, phase=_last_phase())
    return agreed % base


def consensus_code(mesh: Mesh | None, code: Code | int) -> Code:
    """The agreed (max) status code across every rank of the session.
    Session-namespaced: concurrent serving sessions' polls can never
    silently satisfy each other (:func:`_ns_consensus`)."""
    return Code(_ns_consensus(mesh, int(Code(int(code))), 64,
                              "exchange.consensus"))


def _wire_code(fault: CylonError | None) -> int:
    """Ladder consensus encoding: ``Code*4 + sub`` where the predicted
    OOM shape sorts BELOW a real device OOM within the same Code.  The
    max then agrees not just on the retry rung but on the fault TYPE
    every rank must raise on abort — callers above the ladder (e.g.
    ``bench_tpch``) dispatch on the class, and a rank aborting with
    `predicted` while a peer aborts with `device_oom` would take
    divergent abort-vs-retry branches."""
    if fault is None:
        return 0
    sub = 0 if isinstance(fault, PredictedResourceExhausted) else 1
    return int(fault.code) * 4 + sub


def _unwire(wire: int) -> Code:
    return Code(int(wire) // 4)


def _fault_from_wire(wire: int, msg: str) -> CylonError:
    """The typed taxonomy fault every rank must raise for an agreed wire
    value — identical class on every rank by construction."""
    code = _unwire(wire)
    if code == Code.OutOfMemory:
        return (PredictedResourceExhausted(msg) if wire % 4 == 0
                else DeviceOOMError(msg))
    if code == Code.CapacityError:
        return CapacityOverflowError(msg)
    if code == Code.SerializationError:
        # a peer's disk-tier spill page failed verification: every rank
        # takes the identical recompute rung (the corrupt owner's data
        # exists nowhere else — recompute, never a wrong answer)
        return CheckpointCorruptError(msg, site="disk.read")
    if code == Code.IntegrityFault:
        # a peer's conservation law or armed fingerprint failed: every
        # rank takes the identical one-recompute rung (silent corruption
        # degrades to recompute, never to a wrong answer)
        return DataIntegrityError(msg, site="audit.verify",
                                  phase=_last_phase())
    return RankDesyncError(msg, phase=_last_phase())


def guard_consensus(mesh: Mesh | None, local_fault: bool) -> bool:
    """Pre-collective raise/proceed agreement for capacity guards: True
    when ANY rank's guard fired — then every rank raises the identical
    typed fault BEFORE the exchange's first collective is dispatched (the
    rank-coherence invariant).  Runs unconditionally on every rank in a
    multiprocess session (it is itself a tiny collective)."""
    local = Code.OutOfMemory if local_fault else Code.OK
    return consensus_code(mesh, local) != Code.OK


def spill_consensus(mesh: Mesh | None, local_need: bool) -> bool:
    """Evict/re-admit agreement for the spill tier (exec/memory): True
    when ANY rank is under memory pressure — then every rank runs the
    identical deterministic LRU eviction, because a rank-local eviction
    would desync the next collective exactly like a rank-local retry
    (docs/robustness.md).  Rides the same one-int32 pmax wire as the
    fault codes, with the dedicated :class:`Code.SpillRequired` vote.
    Callers poll only when the pressure predicate or an armed injector
    can be non-OK somewhere — the under-budget happy path stays
    collective-free."""
    local = Code.SpillRequired if local_need else Code.OK
    return consensus_code(mesh, local) == Code.SpillRequired


def drain_consensus(mesh: Mesh | None, local_flag: bool) -> bool:
    """Preemption-grace drain agreement (exec/preempt → exec/checkpoint
    ``drain_requested``): True when ANY rank has received a SIGTERM
    preemption notice — then every rank flushes, commits and raises the
    identical typed ``ResumableAbort`` at the SAME checkpoint boundary.
    A rank draining alone would leave its peers hanging in the next
    piece's commit collective, which is the desync this module exists
    to prevent.  Rides the same one-int32 pmax wire as the fault codes
    with the dedicated :class:`Code.PreemptDrain` vote,
    session-namespaced like every other wire.  Polled ONLY at the
    checkpoint boundaries of sessions with BOTH the grace budget and
    durable checkpointing armed — unarmed sessions stay collective-free
    (one env read per boundary)."""
    local = Code.PreemptDrain if local_flag else Code.OK
    return consensus_code(mesh, local) == Code.PreemptDrain


def preempt_consensus(mesh: Mesh | None, victim_plus1: int) -> int:
    """Preempt-DECISION agreement (exec/scheduler._maybe_preempt): every
    rank votes its locally chosen victim as ``ordinal + 1`` (0 = no
    eligible victim) and the max wins, so either every rank flags the
    SAME running tenant for a boundary drain or none does.  Policy
    inputs like fair-share clocks are wall time and not rank-uniform —
    without the vote one rank could drain tenant A while its peers keep
    granting it, leaving them alone in A's next collective.  Rides the
    count transport (one-int32 pmax, session-namespaced) under its own
    site label; entered only when the preemptive preconditions (policy,
    checkpointing armed, candidate blocked) hold — all rank-uniform —
    so the happy path stays collective-free."""
    return int(_ns_consensus(
        mesh, min(max(int(victim_plus1), 0), (1 << 20) - 1),
        1 << 20, "sched.preempt"))


def count_consensus(mesh: Mesh | None, n: int) -> int:
    """Max-agree a small non-negative count across ranks — the spill
    tier's eviction-COUNT wire (exec/memory.ensure_headroom) and the
    scheduler's pick-agreement wire: every rank then takes the identical
    action, so the eviction sequence is identical even when a straggling
    GC leaves one rank's balance momentarily higher.  Same transport as
    the ladder's code wire, session-namespaced like it."""
    return int(_ns_consensus(mesh, min(max(int(n), 0), (1 << 20) - 1),
                             1 << 20, "exchange.count"))


#: epoch field width of the checkpoint-commit wire (epochs are per-stage
#: piece counters, far below this; the vote code rides above it)
_CKPT_EPOCH_BASE = 1 << 20

#: session-namespace base for the checkpoint wires: the payload
#: (CkptCommit * 2^20 + epoch ≈ 50.3M max) fits under 2^26, and the
#: namespace (≤ 30) on top stays inside the int32 pmax transport
#: (30 * 2^26 + 50.3M ≈ 2.064e9 < 2^31)
_CKPT_NS_BASE = 1 << 26


def ckpt_commit_consensus(mesh: Mesh | None, epoch: int) -> int:
    """Phase 2 of the durable checkpoint's two-phase manifest commit
    (exec/checkpoint): every rank has already STAGED its manifest (phase
    1, a rank-local atomic write) and now votes :class:`Code.CkptCommit`
    with its staged epoch riding the same one-int32 pmax wire as the
    fault codes.  Only after the votes agree does any rank rename
    staged → committed, so a manifest is either committed on EVERY rank
    at the identical epoch or on none — a crash between stage and commit
    leaves only staged files, which resume ignores.  A diverging epoch
    is a structural desync (ranks checkpointing different pieces) and
    raises typed rather than committing torn state.  The wires are
    session-namespaced like every other consensus (:func:`_ns_consensus`
    at :data:`_CKPT_NS_BASE`): two serving tenants' stages commonly sit
    at EQUAL epoch numbers, so without the namespace a rank-schedule
    divergence could durably commit one tenant's manifest against
    another tenant's vote.

    Like the resume vote, this runs over the LIVE mesh only.  After an
    elastic re-shard the first post-reshard commit re-votes the epoch
    over the NEW mesh — stale rank dirs from the old world never
    participate (they are directories, not voters) and are superseded
    by the rewrite's higher manifest generation (exec/checkpoint)."""
    epoch = int(epoch)
    if not 0 <= epoch < _CKPT_EPOCH_BASE:
        raise ValueError(f"checkpoint epoch {epoch} out of wire range")
    if mesh is None or jax.process_count() == 1:
        return epoch
    # two rounds: a max-reduce alone cannot surface divergence to the
    # rank HOLDING the max (its own vote IS the max), so the epoch also
    # rides the wire complemented — max of the complement is the
    # complement of the MIN — and every rank compares both extremes
    # against its own stage before renaming anything
    wire = int(Code.CkptCommit) * _CKPT_EPOCH_BASE + epoch
    agreed = _ns_consensus(mesh, wire, _CKPT_NS_BASE, "ckpt.commit")
    inv = _ns_consensus(mesh, int(Code.CkptCommit) * _CKPT_EPOCH_BASE
                        + (_CKPT_EPOCH_BASE - 1 - epoch),
                        _CKPT_NS_BASE, "ckpt.commit")
    lo = _CKPT_EPOCH_BASE - 1 - (inv % _CKPT_EPOCH_BASE)
    if agreed != wire or lo != epoch:
        raise RankDesyncError(
            f"checkpoint commit diverged: this rank staged epoch {epoch}, "
            f"consensus saw [{lo}, {agreed % _CKPT_EPOCH_BASE}] — ranks "
            "are checkpointing different pieces", site="ckpt.commit",
            phase=_last_phase())
    return epoch


def watermark_consensus(mesh: Mesh | None, n: int) -> int:
    """Min-agree the streaming watermark across ranks (the event-time
    window-close vote, :mod:`cylon_tpu.stream.window`).  ``n`` is this
    rank's CLOSABLE-WINDOW count — the number of tumbling windows whose
    end its local (monotone, per-rank) watermark has passed; window
    ordinals stay far below the wire width, unlike raw int64 event-time
    nanoseconds.  Every rank then closes exactly the agreed MINIMUM — a
    rank that has not yet seen events past a window's end holds the
    whole session's close back, because closing rank-locally would emit
    (and evict) different window state per rank, the desync this module
    exists to prevent.  Rides the pmax transport complemented (max of
    the complement = complement of the min — the ckpt-resume trick) and
    is session-namespaced like every other wire, so a streaming tenant's
    vote can never satisfy another tenant's poll."""
    n = int(n)
    if not 0 <= n < _CKPT_EPOCH_BASE:
        raise ValueError(f"watermark window count {n} out of wire range")
    if mesh is None or jax.process_count() == 1:
        return n
    wire = _CKPT_EPOCH_BASE - 1 - n
    return _CKPT_EPOCH_BASE - 1 - (
        _ns_consensus(mesh, wire, 1 << 20, "stream.watermark")
        % _CKPT_EPOCH_BASE)


def _plan_hash_consensus(mesh: Mesh | None, code: Code, plan_hash: int,
                         site: str, what: str) -> None:
    """Adopt-one-plan agreement shared by the skew-split and topology
    routes: every rank votes ``code`` with two 20-bit slices of the
    canonical plan hash riding the pmax wire — EACH slice in both
    polarities (plain, then complemented), four rounds total, so a rank
    passes a slice's pair only when its value equals both the max AND
    the min across the mesh: any divergence in either slice raises on
    EVERY rank, exactly like the checkpoint-commit vote.  A diverging
    hash is a structural desync — ranks about to enter DIFFERENT
    exchange plans (different collective sequences) — and raises typed
    BEFORE the plan's first collective is dispatched, the
    rank-coherence invariant this module exists for."""
    lo20 = int(plan_hash) & ((1 << 20) - 1)
    hi20 = (int(plan_hash) >> 20) & ((1 << 20) - 1)
    if mesh is None or jax.process_count() == 1:
        return
    base = int(code) * _CKPT_EPOCH_BASE
    for label, slice20 in (("lo", lo20), ("hi", hi20)):
        for complemented in (False, True):
            v = (_CKPT_EPOCH_BASE - 1 - slice20) if complemented \
                else slice20
            wire = base + v
            agreed = _ns_consensus(mesh, wire, _CKPT_NS_BASE, site)
            if agreed != wire:
                peer = agreed % _CKPT_EPOCH_BASE
                if complemented:
                    peer = _CKPT_EPOCH_BASE - 1 - peer
                raise RankDesyncError(
                    f"{what} vote diverged: this rank computed plan "
                    f"hash slice {label}={slice20:#x}, consensus saw "
                    f"{peer:#x} — ranks are about to enter different "
                    f"exchange plans", site=site, phase=_last_phase())


def skew_plan_consensus(mesh: Mesh | None, plan_hash: int) -> None:
    """Adopt-one-plan agreement for the adaptive skew-split route
    (relational/skew.py, docs/skew.md): every rank computes the plan —
    heavy-key set, contiguous rank groups, salted fan-out chunk bounds —
    from the SAME allgathered sample + count sidecars, then votes
    :class:`Code.SkewPlan` over the four-round double-polarity hash
    wire (:func:`_plan_hash_consensus`).  The recovery ladder's retries
    re-detect and re-vote: determinism of the detection inputs makes
    the re-voted hash identical, which chaos_soak's ``--skew``
    schedules assert.

    Polled ONLY when a non-empty plan was decided (plan-armed joins) —
    the plan decision itself is rank-uniform by construction
    (``host_array`` allgathers the sample), so the unarmed / no-heavy-key
    path stays collective-free (the bench's zero-extra-collectives
    contract at skew 0)."""
    _plan_hash_consensus(mesh, Code.SkewPlan, plan_hash, "skew.plan",
                         "skew-plan")


def topo_plan_consensus(mesh: Mesh | None, plan_hash: int) -> None:
    """Adopt-one-plan agreement for the multi-slice topology route
    (cylon_tpu/topo — the TS116 facade is the only sanctioned caller;
    docs/topology.md): every rank derives the topology plan — slice
    map, flat/hierarchical route, gateway scheme — from the SAME device
    attributes / ``CYLON_TPU_SLICES`` declaration, then votes
    :class:`Code.TopoPlan` over the four-round double-polarity hash
    wire (:func:`_plan_hash_consensus`) BEFORE the first hierarchical
    collective, so recovery ladders, checkpoints and elastic resume
    (slice loss → re-shard onto the surviving world, which re-votes the
    NEW topology) all adopt one plan.  Voted once per (mesh, plan) —
    single-slice sessions never reach it (zero collectives on the flat
    route, the chaos ``--multislice`` unarmed-leg contract)."""
    _plan_hash_consensus(mesh, Code.TopoPlan, plan_hash, "topo.plan",
                         "topology-plan")


def fingerprint_consensus(mesh: Mesh | None, fp: int) -> None:
    """Rank-coherent verification of an order-invariant content
    fingerprint (exec/integrity — the TS118 facade is the only
    sanctioned caller; docs/robustness.md "Integrity audit tier"):
    every rank computes the REPLICATED 64-bit mesh fingerprint for the
    same stage boundary and votes :class:`Code.IntegrityFault` with two
    20-bit slices of it over the four-round double-polarity hash wire
    (:func:`_plan_hash_consensus`), so a rank whose device delivered
    different bytes raises typed BEFORE anyone commits the stage —
    identically on every rank, exactly like a plan vote.  Polled only
    under ``CYLON_TPU_AUDIT=1`` in multiprocess sessions: the unarmed
    path (and any single-controller session, where the replicated
    fingerprint is trivially coherent) stays collective-free."""
    _plan_hash_consensus(mesh, Code.IntegrityFault, fp, "audit.verify",
                         "fingerprint-audit")


def ckpt_resume_consensus(mesh: Mesh | None, n: int) -> int:
    """Min-agree the resume fast-forward count (exec/pipeline): each
    rank votes how many committed pieces IT restored and verified, and
    every rank fast-forwards exactly the MINIMUM — a rank whose page
    failed its content-hash check (rank-local disk corruption) degrades
    the whole session's fast-forward coherently, because a rank-local
    fallback would leave the recomputing rank alone in the per-piece
    commit collectives.  The count rides the wire complemented so the
    pmax transport yields the min; adopting the min needs no divergence
    check (divergence IS the input here, and min is the agreement) —
    but the wire IS session-namespaced, so a vote arriving from another
    serving tenant's resume surfaces typed instead of silently clamping
    this tenant's fast-forward.

    The vote is over the LIVE mesh, never over checkpoint rank
    directories: an elastic resume (docs/robustness.md "Elastic resume
    & preemption grace") commonly has rank dirs OUTNUMBERING live ranks
    (world shrank — every live rank reads all N foreign dirs and votes
    the count it could verify) or UNDERNUMBERING them (world grew — a
    live rank with no own-rank dir simply votes what the foreign scan
    yielded, 0 if the checkpoint root is not shared).  Either way the
    min over live ranks is well-defined, and for an all-or-nothing
    re-shard adoption the caller compares the agreed min against its
    own count and discards EVERYTHING on any shortfall (old-layout
    pieces cannot partially splice into a new-layout loop)."""
    n = int(n)
    if not 0 <= n < _CKPT_EPOCH_BASE:
        raise ValueError(f"resume fast-forward count {n} out of wire range")
    if mesh is None or jax.process_count() == 1:
        return n
    wire = (int(Code.CkptCommit) * _CKPT_EPOCH_BASE
            + (_CKPT_EPOCH_BASE - 1 - n))
    return _CKPT_EPOCH_BASE - 1 - (
        _ns_consensus(mesh, wire, _CKPT_NS_BASE, "ckpt.resume")
        % _CKPT_EPOCH_BASE)


# ---------------------------------------------------------------------------
# exchange watchdog
# ---------------------------------------------------------------------------

def exchange_watchdog(site: str, thunk, timeout_s: float | None = None,
                      stalled: bool | None = None):
    """Run a blocking exchange host-sync under an optional deadline.

    With ``CYLON_TPU_WATCHDOG_S`` unset/0 this is a plain call.  With a
    deadline, the sync runs in a worker thread; if it does not complete in
    time the hang is converted into a typed :class:`RankDesyncError`
    carrying the site and the last-known timing phase.  The injector kind
    ``stall`` (site ``exchange.stall``) simulates the peer hang;
    ``stalled=True`` forces the simulated hang directly (the spill tier
    routes its site-local ``spill_stall`` injections through this — a
    hung host↔device transfer then surfaces typed at ``spill.evict`` /
    ``spill.upload`` instead of silently blocking)."""
    t = config.EXCHANGE_WATCHDOG_S if timeout_s is None else float(timeout_s)
    if t <= 0:
        return thunk()
    if stalled is None:
        stalled = injected("exchange.stall")
    box: dict = {}

    def run():
        if stalled:
            # simulated peer hang: the data never arrives
            import time
            time.sleep(4 * t)
            return
        try:
            box["value"] = thunk()
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            box["error"] = e

    th = threading.Thread(target=run, daemon=True,
                          name=f"cylon-watchdog-{site}")
    th.start()
    th.join(t)
    if "error" in box:
        raise box["error"]
    if "value" not in box:
        _record(site, "desync", "watchdog")
        raise RankDesyncError(
            f"exchange watchdog: no progress at {site} within {t:g}s — a "
            "peer rank hung in (or never entered) the exchange",
            site=site, phase=_last_phase())
    return box["value"]


# ---------------------------------------------------------------------------
# bounded IO retry — the shared transient-OSError backoff helper
# ---------------------------------------------------------------------------

#: registry counter: transient-OSError retries taken by retry_io across
#: every adopter (checkpoint page/manifest writes, disk-tier spill pages)
from ..obs import metrics as _obs_metrics  # noqa: E402

_IO_RETRIES = _obs_metrics.counter(
    "recovery_io_retries",
    help="transient-OSError retries taken by the bounded IO backoff")

#: errno values retry_io treats as NON-transient: a full disk (or quota)
#: does not heal on a millisecond backoff — the caller's typed degrade
#: path owns those, not the retry loop
_NON_TRANSIENT_ERRNOS = frozenset(
    getattr(errno, name)
    for name in ("ENOSPC", "EDQUOT", "EROFS", "ENOENT", "EISDIR")
    if hasattr(errno, name))


def retry_io(fn, site: str, attempts: int = 3, base_delay_s: float = 0.05,
             on_retry=None):
    """Run a filesystem thunk with a SMALL bounded exponential-backoff
    retry on transient ``OSError`` — the shared-storage-blip helper
    (docs/robustness.md "Disk tier & scan pushdown"): a single NFS hiccup
    during a GKE drain used to abort a checkpoint commit that a
    3-attempt backoff saves.  Bounded by construction: at most
    ``attempts`` calls, delays ``base * 2^i`` (≈0.15 s total at the
    defaults) — never an unbounded loop.  Non-transient errnos (ENOSPC,
    EDQUOT, EROFS, ENOENT, EISDIR) re-raise IMMEDIATELY: the caller's
    typed degrade/classification path owns those.  Non-OSError
    exceptions propagate untouched.  ``on_retry`` (optional thunk) runs
    once per retry — adopters bump their own counters through it; the
    shared ``recovery_io_retries`` registry counter and a
    ``io_retry.<site>`` timing bump always fire."""
    import time as _time
    last: OSError | None = None
    for i in range(max(int(attempts), 1)):
        if i:
            from ..utils import timing
            from ..utils.logging import log
            _IO_RETRIES.inc()
            timing.bump(f"io_retry.{site}")
            if on_retry is not None:
                on_retry()
            log.warning("%s: transient OSError (%s); retry %d/%d after "
                        "%.3fs backoff", site, last, i, attempts - 1,
                        base_delay_s * (2 ** (i - 1)))
            _time.sleep(base_delay_s * (2 ** (i - 1)))
        try:
            return fn()
        except OSError as e:
            if e.errno in _NON_TRANSIENT_ERRNOS:
                raise
            last = e
    raise last


# ---------------------------------------------------------------------------
# the rank-coherent retry ladder
# ---------------------------------------------------------------------------

#: bounded deterministic escalation per agreed fault code: device/predicted
#: OOM retries the streaming fallback at growing chunk counts; a capacity
#: overflow takes exactly one cap-halving step (pieces are ~1/n_chunks
#: sized, so 8 chunks halves the 4-chunk default's piece cap); a DISK-TIER
#: corruption (Code.SerializationError from a ``disk.*`` site — a spill
#: page failed its sha check, so that owner's data exists nowhere else)
#: takes exactly one recompute of the stage at the base streaming
#: configuration — corruption degrades to recompute, never a wrong answer;
#: an INTEGRITY fault (Code.IntegrityFault — a conservation law or armed
#: content fingerprint caught data in flight being mutated) mirrors the
#: disk-corruption rung exactly: ONE recompute of the stage at the base
#: streaming configuration, then a typed abort on repeat
RETRY_RUNGS = {Code.OutOfMemory: (4, 16), Code.CapacityError: (8,),
               Code.SerializationError: (4,),
               Code.IntegrityFault: (4,)}

_tls = threading.local()


def _resumable(exc, label: str):
    """The ladder's FINAL rung (docs/robustness.md "Durable checkpoints
    & resume"): when durable checkpointing is armed
    (``CYLON_TPU_CKPT_DIR``) and the fault is one no in-process rung can
    cure — a real :class:`DeviceOOMError` (HBM may be poisoned) or an
    exhausted compiler-crash ladder — flush the checkpoint session and
    convert into a typed :class:`ResumableAbort` carrying the resume
    token, so a supervisor can relaunch with ``CYLON_TPU_RESUME=1`` and
    fast-forward past every committed piece.  Anything else (or with
    checkpointing unarmed) returns the input unchanged."""
    from . import checkpoint
    if not checkpoint.enabled():
        return exc
    if not (isinstance(exc, DeviceOOMError) or is_compiler_crash(exc)):
        return exc
    token = checkpoint.flush_for_abort(label)
    kind = getattr(exc, "kind", "compiler_crash")
    _record(label, kind, "resumable_abort")
    ra = ResumableAbort(
        f"{label}: unrecoverable {kind} fault with durable checkpoints "
        f"armed — committed piece state flushed; rerun the same workload "
        f"in a FRESH process with CYLON_TPU_RESUME=1 to fast-forward past "
        f"committed pieces (resume token: {token})", token=token)
    ra.__cause__ = exc
    return ra


def _attempt(fn, label: str = ""):
    """(result, fault) — non-fault exceptions propagate (a compiler
    crash that exhausted its pad ladder takes the FINAL checkpoint rung
    on the way out when one is armed)."""
    try:
        return fn(), None
    except Exception as e:  # noqa: BLE001 — classify filters
        fault = classify(e)
        if fault is None:
            exc = _resumable(e, label)
            if exc is e:
                raise
            raise exc
        return None, fault


def run_with_recovery(primary, can_fallback: bool, fallback, label: str,
                      env=None):
    """``primary()`` under the consensus retry ladder: classify any fault,
    agree on ONE status code across ranks, and either return, retry
    ``fallback(n_chunks)`` on the deterministic rung schedule
    (:data:`RETRY_RUNGS`), or raise the typed fault — identically on every
    rank.  ``env`` (a CylonEnv) supplies the mesh for the consensus
    all-reduce; without it (or single-process) consensus is local.

    Nested invocations (a fallback re-entering a guarded operator) never
    re-escalate: the outer ladder owns the rung schedule, so the total
    number of retries stays bounded.

    Protocol cost, stated plainly: in a MULTIPROCESS session every
    guarded operator call ends in one tiny pmax + host pull even on the
    happy path — that pull drains previously dispatched device work, so
    cross-operator dispatch overlap (deferred counts) is traded for the
    guarantee that a rank-local fault on any peer is seen by every rank
    before anyone commits to a result.  Single-controller sessions (the
    benched configurations) skip consensus entirely and keep full
    overlap."""
    mesh = getattr(env, "mesh", None)
    multi = mesh is not None and jax.process_count() > 1
    nested = getattr(_tls, "depth", 0) > 0

    def agree(fault):
        """(agreed Code, rank-coherent fault|None): consensus over the
        wire encoding, so ranks agree on the fault TYPE, not just the
        rung — a rank whose local fault differs from (or lacks) the
        agreed one adopts a synthesized fault of the agreed class
        (classify() passes typed faults through, keeping ENCLOSING
        ladders and type-dispatching callers coherent too).  The wire is
        session-namespaced (_ns_consensus): one serving session's ladder
        can never adopt a fault a peer rank voted from ANOTHER session's
        ladder."""
        wire = _wire_code(fault)
        agreed_w = _ns_consensus(mesh, wire, 1024, label) if multi else wire
        if agreed_w == 0:
            return Code.OK, None
        if fault is None or _wire_code(fault) != agreed_w:
            fault = _fault_from_wire(
                agreed_w, f"peer rank fault during {label} "
                          f"(consensus {_unwire(agreed_w).name})")
        return _unwire(agreed_w), fault

    result, fault = _attempt(primary, label)
    agreed, fault = agree(fault)
    if agreed == Code.OK:
        return result
    kind = getattr(fault, "kind", "fault")

    # ---- spill rung: free resident bytes, retry the SAME configuration --
    # A predicted fault fired BEFORE any allocation (HBM clean), so if the
    # host spill tier can free resident bytes, the cheapest recovery is to
    # evict and re-run at the same chunk count — no completed device work
    # is discarded (exec/memory, docs/robustness.md).  Rank-coherent by
    # construction: the fault TYPE is post-consensus (the wire encoding
    # separates predicted from device OOM), and spill_for_retry's eviction
    # set/order is a pure function of the rank-uniform ledger.  Chunk
    # escalation below remains the backstop when spilling is insufficient
    # (or there is nothing to spill).
    if not nested and isinstance(fault, PredictedResourceExhausted):
        from . import memory
        # the TAKE-THE-RUNG decision is agreed, not balance-gated: a
        # straggling GC could leave spillable bytes visible on one rank
        # only, and a rank retrying while its peers escalate is the
        # desync this module exists to prevent.  (The gate itself runs
        # on every rank: fault type and nesting depth are uniform.)
        local_can = config.SPILL_ENABLED and memory.spillable_bytes() > 0
        do_spill = spill_consensus(mesh, local_can) if multi else local_can
        if do_spill:
            # eviction goes through the scheduler facade (TS109): the
            # serving tier is the one sanctioned admission/eviction
            # mediator, so even the ladder's rung stays attributable
            from . import scheduler
            scheduler.spill_retry()
            from ..utils.logging import log as _log
            _record(label, kind, "spill_retry")
            _log.warning("%s %s fault; spill rung: resident state evicted "
                         "to host, retrying at the same configuration",
                         label, kind)
            _tls.depth = getattr(_tls, "depth", 0) + 1
            try:
                result, fault = _attempt(primary, label)
            finally:
                _tls.depth -= 1
            agreed, fault = agree(fault)
            if agreed == Code.OK:
                return result
            kind = getattr(fault, "kind", kind)

    rungs = RETRY_RUNGS.get(agreed, ())
    if not rungs or not can_fallback or nested:
        _record(label, kind, "abort")
        raise _resumable(fault, label)

    from ..utils.logging import log
    last = fault
    _tls.depth = getattr(_tls, "depth", 0) + 1
    try:
        for nc in rungs:
            _record(label, kind, f"retry_chunks_{nc}")
            log.warning("%s %s fault (%s); rank-coherent retry via "
                        "streaming fallback with %d chunks", label, kind,
                        type(last).__name__, nc)
            result, fault = _attempt(lambda: fallback(nc), label)
            agreed, fault = agree(fault)
            if agreed == Code.OK:
                return result
            last, kind = fault, getattr(fault, "kind", kind)
            if agreed not in RETRY_RUNGS:
                break
    finally:
        _tls.depth -= 1
    _record(label, kind, "abort")
    raise _resumable(last, label)


# ---------------------------------------------------------------------------
# trace-safety declaration (cylon_tpu.analysis.registry): the consensus
# program is ONE unconditional pmax — the jaxpr pass verifies exactly that
# (a conditional consensus would be the deadlock it exists to prevent).
# ---------------------------------------------------------------------------

def _trace_consensus(mesh):
    w = int(mesh.devices.size)
    fn = _unwrap(_consensus_fn(mesh, w))
    return jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((w,), np.int32))


from ..analysis.registry import declare_builder, unwrap as _unwrap  # noqa: E402

declare_builder(f"{__name__}._consensus_fn", _trace_consensus,
                collectives={"pmax"}, tags=("recovery",))
