"""Pipelined (chunked, comm/compute-overlapped) execution — the TPU-first
re-think of the reference's streaming operator DAG (cpp/src/cylon/ops/,
SURVEY.md §2 C9)."""

from ..relational.piece import PackedPiece, PieceSource  # noqa: F401
from .pipeline import (GroupBySink, chunk_table,  # noqa: F401
                       pipelined_join, pipelined_scan_join,
                       pipelined_set_op)
from . import checkpoint  # noqa: F401  — durable checkpoint/resume rung
from . import memory  # noqa: F401  — HBM budget ledger + host spill tier
from . import preempt  # noqa: F401  — SIGTERM preemption-grace drain
from . import recovery  # noqa: F401  — rank-coherent failure recovery
from . import scheduler  # noqa: F401  — multi-tenant serving tier
from .scheduler import QueryScheduler  # noqa: F401
from .session import QuerySession  # noqa: F401
