"""Multi-tenant serving tier: the admission-controlled concurrent query
scheduler — many sessions, one mesh.

The reference ships a push-based streaming op DAG with RoundRobin /
Priority / ForkJoin executors and intra-process logical-rank task
parallelism (SURVEY C9 ``ops/execution/execution.hpp:43-110``, C11
``ArrowTaskAllToAll``) — many in-flight operators sharing one worker
set.  Our :mod:`cylon_tpu.exec.pipeline` is that DAG for a SINGLE
query; this module is the serving layer above it, multiplexing many
concurrent queries (a TPC-H mix is the reference workload) over the
substrate PRs 3–6 built:

* **Admission control = the HBM ledger** (:mod:`cylon_tpu.exec.memory`,
  PR 4).  Every submitted query carries a pack-time footprint estimate;
  a session starts only when the running sessions' declared footprints
  plus its own fit the mesh-wide budget (realized overruns are handled
  at allocation time by the ledger's own consensus'd admission path).
  Under pressure the scheduler evicts COLD tenants'
  spillable registrations first — deterministic LRU over the shared
  ledger, the eviction COUNT agreed over the PR 3 consensus wire
  (:func:`cylon_tpu.exec.recovery.count_consensus`, the same transport
  as the ``Code.SpillRequired`` vote) so every rank of a multiprocess
  session admits and evicts identically.  A session whose footprint
  still cannot fit WAITS (counted: ``admission_waits``); when nothing is
  running at all, admission degrades to serial execution (the oldest
  pending session is force-admitted) rather than deadlocking.

* **Cooperative interleave at piece-loop boundaries.**  Each admitted
  session runs on its own daemon thread, but a single BATON serializes
  device dispatch: exactly one session runs between interleave points
  (:func:`maybe_yield` — called by the pipelined range loop per piece,
  the chunked set-op loop per chunk, and every hash shuffle), so each
  query sees the single-controller engine semantics every operator was
  built under, while the PR 6 overlap scheduler keeps the device busy
  ACROSS tenants: piece r of tenant A is still executing (async
  dispatch) while tenant B's next piece is being enqueued.

* **Pluggable policy**: ``fifo`` (arrival order, run-to-completion),
  ``priority`` (highest priority first, arrival order within), ``fair``
  (weighted fair share — the runnable session with the smallest
  ``attributed dispatch seconds / weight``, from the per-session
  :class:`~cylon_tpu.utils.timing.AttributionScope`, runs next; equal
  weights degenerate to round-robin).  In multiprocess sessions the
  pick is agreed over the consensus wire (max ordinal), so wall-clock
  skew between ranks cannot fork the schedule.

* **Shared plan cache**: :func:`cylon_tpu.utils.cache.program_cache`
  lives on the mesh, so tenants running the same plan shapes pay each
  compile once — no per-tenant program duplication (asserted in
  tests/test_scheduler.py).

* **Per-session recovery isolation** (:mod:`cylon_tpu.exec.recovery`):
  the session thread is tagged (``set_session``), so recovery events
  carry the tenant, fault injection targets tenants (``@session``
  grammar), consensus codes ride a session namespace (a rank voting
  from another tenant's ladder surfaces as a typed desync, never as a
  silently adopted foreign fault), checkpoint stage sequences are
  per-session, and the ladder's escalation depth is thread-local — one
  tenant's retry ladder or ``ResumableAbort`` cannot poison another's.

**TS109 — scheduler-mediated admission.**  This module (and the ledger
itself) is the ONE sanctioned caller of the ledger's admission/eviction
entry points (``ensure_headroom`` / ``try_free`` / ``spill_for_retry``
/ ``evict_n`` / ``evict_until``).  Operators route allocations through
:func:`admit_allocation`, guards through :func:`free_pressure`, the
retry ladder through :func:`spill_retry` — so per-tenant footprints,
admission waits and cross-tenant evictions stay attributed in one
place.  A direct ledger call anywhere else is a lint finding
(docs/trace_safety.md).

Happy path contract: with no scheduler active, :func:`maybe_yield` is
one module-global load, and the facades add one thread-local read over
the raw ledger calls — single-query workloads are unchanged.

See docs/serving.md for the full admission contract, fairness
semantics, interleave points and isolation rules.
"""

from __future__ import annotations

import threading
import time

from .. import config
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..status import InvalidError, ResumableAbort
from .session import DONE, FAILED, PENDING, RUNNING, QuerySession

#: the active scheduler — at most one per process; read by maybe_yield
#: on every interleave point, so the no-scheduler fast path is one load
_ACTIVE: "QueryScheduler | None" = None

_tls = threading.local()   # .session: the QuerySession on this thread


def current_session() -> QuerySession | None:
    """The serving session running on THIS thread, or None."""
    return getattr(_tls, "session", None)


def maybe_yield() -> None:
    """Cooperative interleave point — piece-loop boundaries call this.

    Outside a scheduler (or on a non-session thread) it is a no-op.  On
    a session thread it hands the baton back to the scheduler, which
    picks the next session per policy; the call returns when this
    session is granted its next slice.  Async device work this session
    already dispatched keeps executing while it waits — that is the
    cross-tenant overlap the serving tier exists for."""
    sched = _ACTIVE
    if sched is None:
        return
    sess = current_session()
    if sess is None or sess.state != RUNNING:
        return
    sched._yield_turn(sess)


# ---------------------------------------------------------------------------
# the sanctioned admission/eviction facades (lint rule TS109)
# ---------------------------------------------------------------------------

def admit_allocation(env, need: int, scratch: int = 0,
                     site: str = "spill.evict", reuse: int = 0) -> None:
    """Admission for a new resident allocation of ``need`` bytes — the
    operator-facing entry (PieceSource pack admission).  Attributes the
    bytes to the current serving session, then routes to the ledger's
    consensus-coherent admission path
    (:func:`cylon_tpu.exec.memory.ensure_headroom`): under budget
    pressure, cold tenants' spillable registrations evict first,
    identically on every rank."""
    from . import memory
    sess = current_session()
    if sess is not None:
        sess.bytes_admitted += int(need)
    memory.ensure_headroom(env, need, scratch=scratch, site=site,
                           reuse=reuse)


def free_pressure(need: int) -> int:
    """Best-effort eviction of ``need`` bytes of headroom at a guard
    call site (the exchange receive-budget guard).  Returns bytes freed;
    0 when the ledger is already under budget or in multiprocess
    sessions (where eviction is taken exclusively on the consensus'd
    admission path)."""
    from . import memory
    if memory.over_budget(int(need)):
        return memory.try_free(int(need))
    return 0


def spill_retry() -> int:
    """The retry ladder's spill rung, scheduler-mediated: evict every
    spillable resident registration (all tenants — the rung is a
    last-resort pressure release and spill round-trips are bit-exact),
    returning bytes freed."""
    from . import memory
    return memory.spill_for_retry()


def estimate_footprint(*tables, factor: float = 2.0) -> int:
    """Pack-time HBM footprint estimate for a query over ``tables``
    (Tables or DataFrames): resident column bytes (data + validity),
    scaled by ``factor`` for packed lane matrices + piece scratch.  An
    ESTIMATE by design — admission gates on it, execution gates on the
    ledger's exact accounting."""
    total = 0
    for t in tables:
        t = getattr(t, "_table", t)
        for c in t.columns.values():
            total += int(c.data.nbytes)
            if c.validity is not None:
                total += int(c.validity.nbytes)
    return int(total * float(factor))


# ---------------------------------------------------------------------------
# scheduling policies
# ---------------------------------------------------------------------------

def _fifo_key(s: QuerySession):
    return s.ordinal


def _priority_key(s: QuerySession):
    return (-s.priority, s.ordinal)


def _fair_key(s: QuerySession):
    # primary clock: attributed dispatch seconds (utils/timing scope);
    # sessions whose work never enters a timed region tie at 0 there, so
    # granted-slice wall time breaks the tie before arrival order does
    return (s.attributed_s() / s.weight, s.service_s / s.weight,
            s.ordinal)


POLICIES = {"fifo": _fifo_key, "priority": _priority_key,
            "fair": _fair_key}


# ---------------------------------------------------------------------------
# the scheduler
# ---------------------------------------------------------------------------

class QueryScheduler:
    """Admission-controlled concurrent query scheduler over one mesh.

    Usage::

        sched = QueryScheduler(env, policy="fair")
        a = sched.submit("tenant_a", qa, footprint_bytes=fa)
        b = sched.submit("tenant_b", qb, footprint_bytes=fb, weight=2.0)
        sched.run()                      # interleaves until all done
        a.result, a.summary()

    ``budget_bytes`` overrides the ledger budget for ADMISSION decisions
    only (the ledger's own allocation-time budget stays
    ``CYLON_TPU_HBM_BUDGET``/platform-detected); ``max_concurrency``
    caps simultaneously admitted sessions independently of memory.
    ``run`` drives the baton loop on the calling thread and returns the
    session list; failed sessions carry their exception in ``.error``
    (pass ``raise_errors=True`` to re-raise the first one)."""

    def __init__(self, env, policy: str = "fair",
                 budget_bytes: int | None = None,
                 max_concurrency: int | None = None):
        if policy not in POLICIES:
            raise InvalidError(
                f"unknown scheduling policy {policy!r}; one of "
                f"{sorted(POLICIES)}")
        self.env = env
        self.policy = policy
        self._key = POLICIES[policy]
        self.budget_bytes = budget_bytes
        self.max_concurrency = max_concurrency
        self.sessions: list[QuerySession] = []
        self._control = threading.Event()
        self._abort = False
        self._forced_admissions = 0
        self._scheduler_evictions = 0
        self._preempt_drained = 0

    # -- submission --------------------------------------------------------
    def submit(self, name: str, fn, *, footprint_bytes: int = 0,
               priority: int = 0, weight: float = 1.0,
               tenant: str | None = None,
               kind: str = "query") -> QuerySession:
        """Queue one query.  ``fn`` is a zero-arg callable executed on
        the session's thread under the baton; its return value lands in
        ``session.result``.  ``footprint_bytes`` is the pack-time HBM
        estimate admission gates on (:func:`estimate_footprint`).

        ``kind="stream"`` marks a STREAMING session — a long-lived
        ingest loop (:mod:`cylon_tpu.stream`) whose interleave points
        are its own micro-batch appends, watermark votes and window
        closes rather than piece-loop boundaries; admission, policies
        and isolation treat it exactly like a query tenant, so
        continuous ingest coexists with the TPC-H mix on one mesh
        (docs/streaming.md)."""
        if any(s.name == name for s in self.sessions):
            raise InvalidError(f"duplicate session name {name!r}")
        sess = QuerySession(name, fn, len(self.sessions),
                            footprint_bytes=footprint_bytes,
                            priority=priority, weight=weight, tenant=tenant,
                            kind=kind)
        self.sessions.append(sess)
        return sess

    # -- the baton loop ----------------------------------------------------
    def run(self, raise_errors: bool = False) -> list[QuerySession]:
        global _ACTIVE
        if _ACTIVE is not None:
            raise InvalidError(
                "a QueryScheduler is already serving this process")
        if not self.sessions:
            return []
        self._abort = False   # run() is re-enterable: a completed run's
        #                       abort latch must not fail later submits
        _ACTIVE = self
        try:
            self._loop()
        finally:
            # abort protocol: parked sessions wake, see _abort and RAISE
            # at their yield point (-> FAILED, thread exits) — they must
            # never free-run concurrently without the baton, which would
            # break the single-controller semantics every operator
            # assumes.  _ACTIVE stays set until the threads drain.
            self._abort = True
            for s in self.sessions:
                if s.state == RUNNING:
                    s._grant.set()     # release any thread still parked
            for s in self.sessions:
                if s._thread is not None:
                    s._thread.join(timeout=60.0)
            _ACTIVE = None
        if raise_errors:
            for s in self.sessions:
                if s.error is not None:
                    raise s.error
        return list(self.sessions)

    def _loop(self) -> None:
        while True:
            # periodic metrics snapshot for the GKE deploy
            # (CYLON_TPU_METRICS_JSON) — one list load when unarmed
            _metrics.maybe_write_snapshot()
            if self._draining():
                # preemption grace (exec/preempt): a SIGTERM arrived
                # with checkpointing armed — drain the whole box.  No
                # new admissions; PENDING sessions fail typed with the
                # resume token (they never started, so a resume simply
                # recomputes them); RUNNING sessions keep getting slices
                # and exit via their own checkpoint-boundary drains —
                # each tenant commits its current stage and raises
                # ResumableAbort, so a multi-tenant box preempts as
                # cleanly as a single query (docs/serving.md).
                self._drain_pending()
            else:
                self._admit_pending()
            running = [s for s in self.sessions if s.state == RUNNING]
            if not running:
                if any(s.state == PENDING for s in self.sessions):
                    # nothing running AND the head cannot fit even after
                    # eviction: degrade to serial execution rather than
                    # starve (docs/serving.md admission contract)
                    self._force_admit()
                    continue
                return
            self._grant_slice(self._pick(running))

    # -- preemption-grace drain --------------------------------------------
    def _draining(self) -> bool:
        """Preemption check gating NEW admissions.  In a multiprocess
        session the decision rides the same rank-coherent vote as the
        sessions' own boundary drains (``recovery.drain_consensus``) —
        a rank-local read would let the SIGTERM'd rank fail a pending
        session while its peers admit and start it, leaving them alone
        in that session's first collective.  Every rank's scheduler
        loop reaches this poll at the same iteration (the pick
        consensus already requires lockstep loops), and the vote is
        armed-only: grace budget + checkpointing, same as the piece
        boundaries."""
        from . import checkpoint, preempt
        if not (preempt.armed() and checkpoint.enabled()):
            return False
        if self._multi():
            from . import recovery
            return recovery.drain_consensus(self.env.mesh,
                                            preempt.requested())
        return preempt.requested()

    def _drain_pending(self) -> None:
        from . import checkpoint, recovery
        for s in self.sessions:
            if s.state != PENDING:
                continue
            token = checkpoint.flush_for_abort(f"sched.{s.name}")
            recovery._record(f"sched.{s.name}", "preempt", "drain_pending")
            s.state = FAILED
            s.error = ResumableAbort(
                f"preemption grace drain: session {s.name} was queued but "
                "never admitted — nothing committed, a rerun with "
                f"CYLON_TPU_RESUME=1 recomputes it (resume token: {token})",
                token=token)
            s.finished_s = time.perf_counter()
            self._preempt_drained += 1
            _metrics.counter("sched_preempt_drained").inc()

    # -- admission ---------------------------------------------------------
    def _budget(self) -> int:
        from . import memory
        if self.budget_bytes is not None:
            return int(self.budget_bytes)
        return memory.budget_bytes()

    def _fits(self, sess: QuerySession) -> bool:
        """Admission predicate: the candidate's DECLARED footprint on
        top of the running sessions' declared footprints must fit the
        budget.  Declared, not realized: admission happens BEFORE a
        query packs anything (the ledger balance alone would admit
        everyone up front), and realized pressure from estimates that
        were wrong is already handled at allocation time by the
        ledger's own admission path (``ensure_headroom`` evicts/spills
        with consensus) — gating here on the process-global balance
        would also leak unrelated residents into every decision."""
        b = self._budget()
        if b <= 0:
            return True
        committed = sum(s.footprint_bytes for s in self.sessions
                        if s.state == RUNNING)
        return committed + sess.footprint_bytes <= b

    def _multi(self) -> bool:
        import jax
        return (getattr(self.env, "mesh", None) is not None
                and jax.process_count() > 1)

    def _evict_for(self, sess: QuerySession) -> None:
        """Clear REALIZED residue for an admission: evict cold tenants'
        spillable registrations down to the budget before the admitted
        session allocates anything — deterministic LRU over the shared
        ledger, count agreed across ranks (the Code.SpillRequired
        family's wire) so every rank evicts the same owners in the same
        order."""
        from . import memory, recovery
        if not config.SPILL_ENABLED:
            return
        b = self._budget()
        if b <= 0:
            return
        want = memory.ledger().evict_count_for(sess.footprint_bytes, b)
        if self._multi():
            want = recovery.count_consensus(self.env.mesh, want)
        if want <= 0:
            return
        evicted = memory.ledger().evict_n(want)
        if evicted:
            self._scheduler_evictions += len(evicted)
            _metrics.counter("sched_evictions").inc(len(evicted))
            from ..utils.logging import log
            log.info("scheduler: evicted %s to admit session %s "
                     "(footprint %d B)", evicted, sess.name,
                     sess.footprint_bytes)

    def _admit_pending(self) -> None:
        while True:
            pend = [s for s in self.sessions if s.state == PENDING]
            if not pend:
                return
            running = [s for s in self.sessions if s.state == RUNNING]
            if (self.max_concurrency is not None
                    and len(running) >= self.max_concurrency):
                self._note_wait(pend)
                return
            cand = min(pend, key=self._key)
            if not self._fits(cand):
                # head-of-line admission (no overtaking): deterministic
                # and starvation-free — smaller later queries never
                # leapfrog a waiting tenant
                self._note_wait([cand])
                return
            # the declared footprint fits; clear REALIZED residue first
            # — cold tenants' spillable registrations (or estimates
            # that ran over) evict down to make room before the new
            # session allocates anything
            self._evict_for(cand)
            self._start(cand)

    def _note_wait(self, sessions) -> None:
        now = time.perf_counter()
        for s in sessions:
            if s._wait_mark is None:
                s._wait_mark = now
                s.admission_waits += 1

    def _force_admit(self) -> None:
        pend = [s for s in self.sessions if s.state == PENDING]
        cand = min(pend, key=self._key)
        self._forced_admissions += 1
        _metrics.counter("sched_forced_admissions").inc()
        from ..utils.logging import log
        log.warning("scheduler: nothing running and session %s "
                    "(footprint %d B) cannot fit the budget — force-"
                    "admitting; execution degrades to the ledger's own "
                    "spill tier", cand.name, cand.footprint_bytes)
        self._start(cand)

    def _start(self, sess: QuerySession) -> None:
        now = time.perf_counter()
        if sess._wait_mark is not None:
            sess.admission_wait_s += now - sess._wait_mark
            sess._wait_mark = None
        sess.state = RUNNING
        sess.started_s = now
        t = threading.Thread(target=self._session_body, args=(sess,),
                             name=f"cylon-session-{sess.name}", daemon=True)
        sess._thread = t
        t.start()

    # -- baton -------------------------------------------------------------
    def _session_body(self, sess: QuerySession) -> None:
        from ..utils import timing
        from . import recovery
        _tls.session = sess
        recovery.set_session(sess.name, sess.ordinal)
        sess._grant.wait()
        sess._grant.clear()
        sess._slice_t0 = time.perf_counter()
        try:
            if self._abort:
                # the scheduler aborted before this session's first
                # slice: fail it rather than free-run without the baton
                # (the same abort protocol _yield_turn enforces)
                from ..status import ExecutionError
                raise ExecutionError(
                    f"serving scheduler aborted before session "
                    f"{sess.name} ran")
            with timing.attribution_scope(sess.name) as scope:
                sess.timing = scope
                sess.result = sess.fn()
            sess.state = DONE
        except BaseException as e:  # noqa: BLE001 — isolated per session
            sess.error = e
            sess.state = FAILED
        finally:
            sess.service_s += time.perf_counter() - sess._slice_t0
            sess.slices += 1
            sess.finished_s = time.perf_counter()
            recovery.set_session(None, None)
            _tls.session = None
            self._control.set()

    def _yield_turn(self, sess: QuerySession) -> None:
        """Session side of the baton (runs on the session thread).  On
        scheduler abort the session FAILS at its yield point instead of
        free-running without the baton — concurrent unsupervised
        sessions would violate the single-controller semantics the
        engine assumes."""
        from ..status import ExecutionError
        if self._abort:
            raise ExecutionError(
                f"serving scheduler aborted while session {sess.name} "
                "was in flight")
        t_park = time.perf_counter()
        sess.service_s += t_park - sess._slice_t0
        sess.slices += 1
        self._control.set()
        sess._grant.wait()
        sess._grant.clear()
        sess._slice_t0 = time.perf_counter()
        # time parked at the baton is co-tenants' work, not this
        # session's: regions spanning this yield must not absorb it
        # (utils/timing scope exclusion — the no-bleed invariant)
        from ..utils import timing
        timing.exclude_from_scope(sess._slice_t0 - t_park)
        # baton handoff on the trace timeline: the park span (session-
        # tagged via the active attribution scope) shows exactly where a
        # tenant waited while its async device work kept running
        _trace.complete("sched.park", t_park, session=sess.name)
        if self._abort:
            raise ExecutionError(
                f"serving scheduler aborted while session {sess.name} "
                "was parked at a yield point")

    def _pick(self, running: list[QuerySession]) -> QuerySession:
        sess = min(running, key=self._key)
        if self._multi():
            # policy inputs like fair-share clocks are wall-time and NOT
            # rank-uniform: agree the pick (max ordinal wins) so every
            # rank grants the identical session — the serving analog of
            # the ladder's code consensus
            from . import recovery
            from ..status import RankDesyncError
            agreed = recovery.count_consensus(self.env.mesh, sess.ordinal)
            for s in running:
                if s.ordinal == agreed:
                    return s
            # a pick this rank cannot honor means session STATES have
            # already diverged across ranks; granting a local fallback
            # would dispatch different tenants' collectives per rank —
            # surface the divergence typed, at the point it is detected
            raise RankDesyncError(
                f"scheduler pick consensus chose session ordinal "
                f"{agreed}, which is not running on this rank "
                f"(running: {[s.ordinal for s in running]}) — session "
                "states diverged across ranks", site="scheduler.pick")
        return sess

    def _grant_slice(self, sess: QuerySession) -> None:
        self._control.clear()
        _trace.instant("sched.grant", session=sess.name,
                       policy=self.policy)
        sess._grant.set()
        while not self._control.wait(timeout=60.0):
            t = sess._thread
            if t is None or not t.is_alive():
                if sess.state == RUNNING:   # died without signaling
                    sess.state = FAILED
                    sess.error = RuntimeError(
                        f"session {sess.name} thread died mid-slice")
                return

    # -- reporting ---------------------------------------------------------
    def stats(self) -> dict:
        """Serving-tier counters for bench JSON detail (per-session
        detail rides each session's ``summary()``)."""
        from . import memory
        mem = memory.stats()
        return {
            "policy": self.policy,
            "sessions": len(self.sessions),
            "stream_sessions": sum(1 for s in self.sessions
                                   if s.kind == "stream"),
            "completed": sum(1 for s in self.sessions if s.state == DONE),
            "failed": sum(1 for s in self.sessions if s.state == FAILED),
            "admission_waits": sum(s.admission_waits
                                   for s in self.sessions),
            "admission_wait_s": round(sum(s.admission_wait_s
                                          for s in self.sessions), 4),
            "forced_admissions": self._forced_admissions,
            "scheduler_evictions": self._scheduler_evictions,
            "preempt_drained": self._preempt_drained,
            "resumable_aborts": sum(1 for s in self.sessions
                                    if isinstance(s.error, ResumableAbort)),
            "cross_session_evictions": mem["cross_session_evictions"],
            "spill_events": mem["spill_events"],
            "slices": sum(s.slices for s in self.sessions),
        }
