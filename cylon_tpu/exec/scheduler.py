"""Multi-tenant serving tier: the admission-controlled concurrent query
scheduler — many sessions, one mesh.

The reference ships a push-based streaming op DAG with RoundRobin /
Priority / ForkJoin executors and intra-process logical-rank task
parallelism (SURVEY C9 ``ops/execution/execution.hpp:43-110``, C11
``ArrowTaskAllToAll``) — many in-flight operators sharing one worker
set.  Our :mod:`cylon_tpu.exec.pipeline` is that DAG for a SINGLE
query; this module is the serving layer above it, multiplexing many
concurrent queries (a TPC-H mix is the reference workload) over the
substrate PRs 3–6 built:

* **Admission control = the HBM ledger** (:mod:`cylon_tpu.exec.memory`,
  PR 4).  Every submitted query carries a pack-time footprint estimate;
  a session starts only when the running sessions' declared footprints
  plus its own fit the mesh-wide budget (realized overruns are handled
  at allocation time by the ledger's own consensus'd admission path).
  Under pressure the scheduler evicts COLD tenants'
  spillable registrations first — deterministic LRU over the shared
  ledger, the eviction COUNT agreed over the PR 3 consensus wire
  (:func:`cylon_tpu.exec.recovery.count_consensus`, the same transport
  as the ``Code.SpillRequired`` vote) so every rank of a multiprocess
  session admits and evicts identically.  A session whose footprint
  still cannot fit WAITS (counted: ``admission_waits``); when nothing is
  running at all, admission degrades to serial execution (the oldest
  pending session is force-admitted) rather than deadlocking.

* **Cooperative interleave at piece-loop boundaries.**  Each admitted
  session runs on its own daemon thread, but a single BATON serializes
  device dispatch: exactly one session runs between interleave points
  (:func:`maybe_yield` — called by the pipelined range loop per piece,
  the chunked set-op loop per chunk, and every hash shuffle), so each
  query sees the single-controller engine semantics every operator was
  built under, while the PR 6 overlap scheduler keeps the device busy
  ACROSS tenants: piece r of tenant A is still executing (async
  dispatch) while tenant B's next piece is being enqueued.

* **Pluggable policy**: ``fifo`` (arrival order, run-to-completion),
  ``priority`` (highest priority first, arrival order within), ``fair``
  (weighted fair share — the runnable session with the smallest
  ``attributed dispatch seconds / weight``, from the per-session
  :class:`~cylon_tpu.utils.timing.AttributionScope`, runs next; equal
  weights degenerate to round-robin).  In multiprocess sessions the
  pick is agreed over the consensus wire (max ordinal), so wall-clock
  skew between ranks cannot fork the schedule.

* **Shared plan cache**: :func:`cylon_tpu.utils.cache.program_cache`
  lives on the mesh, so tenants running the same plan shapes pay each
  compile once — no per-tenant program duplication (asserted in
  tests/test_scheduler.py).

* **Per-session recovery isolation** (:mod:`cylon_tpu.exec.recovery`):
  the session thread is tagged (``set_session``), so recovery events
  carry the tenant, fault injection targets tenants (``@session``
  grammar), consensus codes ride a session namespace (a rank voting
  from another tenant's ladder surfaces as a typed desync, never as a
  silently adopted foreign fault), checkpoint stage sequences are
  per-session, and the ladder's escalation depth is thread-local — one
  tenant's retry ladder or ``ResumableAbort`` cannot poison another's.

**TS109 — scheduler-mediated admission.**  This module (and the ledger
itself) is the ONE sanctioned caller of the ledger's admission/eviction
entry points (``ensure_headroom`` / ``try_free`` / ``spill_for_retry``
/ ``evict_n`` / ``evict_until``).  Operators route allocations through
:func:`admit_allocation`, guards through :func:`free_pressure`, the
retry ladder through :func:`spill_retry` — so per-tenant footprints,
admission waits and cross-tenant evictions stay attributed in one
place.  A direct ledger call anywhere else is a lint finding
(docs/trace_safety.md).

Happy path contract: with no scheduler active, :func:`maybe_yield` is
one module-global load, and the facades add one thread-local read over
the raw ledger calls — single-query workloads are unchanged.

See docs/serving.md for the full admission contract, fairness
semantics, interleave points and isolation rules.
"""

from __future__ import annotations

import threading
import time

from .. import config
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..status import InvalidError, ResumableAbort
from .session import DONE, FAILED, PENDING, RUNNING, QuerySession

#: the active scheduler — at most one per process; read by maybe_yield
#: on every interleave point, so the no-scheduler fast path is one load
_ACTIVE: "QueryScheduler | None" = None

_tls = threading.local()   # .session: the QuerySession on this thread


def current_session() -> QuerySession | None:
    """The serving session running on THIS thread, or None."""
    return getattr(_tls, "session", None)


def maybe_yield() -> None:
    """Cooperative interleave point — piece-loop boundaries call this.

    Outside a scheduler (or on a non-session thread) it is a no-op.  On
    a session thread it hands the baton back to the scheduler, which
    picks the next session per policy; the call returns when this
    session is granted its next slice.  Async device work this session
    already dispatched keeps executing while it waits — that is the
    cross-tenant overlap the serving tier exists for."""
    sched = _ACTIVE
    if sched is None:
        return
    sess = current_session()
    if sess is None or sess.state != RUNNING:
        return
    sched._yield_turn(sess)


# ---------------------------------------------------------------------------
# the sanctioned admission/eviction facades (lint rule TS109)
# ---------------------------------------------------------------------------

def admit_allocation(env, need: int, scratch: int = 0,
                     site: str = "spill.evict", reuse: int = 0) -> None:
    """Admission for a new resident allocation of ``need`` bytes — the
    operator-facing entry (PieceSource pack admission).  Attributes the
    bytes to the current serving session, then routes to the ledger's
    consensus-coherent admission path
    (:func:`cylon_tpu.exec.memory.ensure_headroom`): under budget
    pressure, cold tenants' spillable registrations evict first,
    identically on every rank."""
    from . import memory
    sess = current_session()
    if sess is not None:
        sess.bytes_admitted += int(need)
    memory.ensure_headroom(env, need, scratch=scratch, site=site,
                           reuse=reuse)


def free_pressure(need: int) -> int:
    """Best-effort eviction of ``need`` bytes of headroom at a guard
    call site (the exchange receive-budget guard).  Returns bytes freed;
    0 when the ledger is already under budget or in multiprocess
    sessions (where eviction is taken exclusively on the consensus'd
    admission path)."""
    from . import memory
    if memory.over_budget(int(need)):
        return memory.try_free(int(need))
    return 0


def spill_retry() -> int:
    """The retry ladder's spill rung, scheduler-mediated: evict every
    spillable resident registration (all tenants — the rung is a
    last-resort pressure release and spill round-trips are bit-exact),
    returning bytes freed."""
    from . import memory
    return memory.spill_for_retry()


# ---------------------------------------------------------------------------
# admission estimates from ANALYZE history (docs/serving.md)
# ---------------------------------------------------------------------------

#: shape family -> max observed peak-ledger bytes, recorded by
#: obs.plan.explain_analyze(family=...) ANALYZE runs.  Admission uses
#: min(declared, observed_peak x safety_factor) for sessions submitted
#: with a shape_family, so a conservative declared footprint no longer
#: serializes tenants that demonstrably co-fit.
_FAMILY_PEAKS: dict[str, int] = {}


def note_family_peak(family: str, peak_bytes: int) -> None:
    """Record an observed peak-ledger-bytes sample for a shape family
    (max-update; called by ``explain_analyze(family=...)``)."""
    prev = _FAMILY_PEAKS.get(family, 0)
    _FAMILY_PEAKS[family] = max(prev, int(peak_bytes))


def observed_peak(family: str | None) -> int | None:
    """The recorded peak for a shape family, or None when unknown."""
    if family is None:
        return None
    return _FAMILY_PEAKS.get(family)


def reset_family_history() -> None:
    _FAMILY_PEAKS.clear()


def estimate_footprint(*tables, factor: float = 2.0) -> int:
    """Pack-time HBM footprint estimate for a query over ``tables``
    (Tables or DataFrames): resident column bytes (data + validity),
    scaled by ``factor`` for packed lane matrices + piece scratch.  An
    ESTIMATE by design — admission gates on it, execution gates on the
    ledger's exact accounting."""
    total = 0
    for t in tables:
        t = getattr(t, "_table", t)
        for c in t.columns.values():
            total += int(c.data.nbytes)
            if c.validity is not None:
                total += int(c.validity.nbytes)
    return int(total * float(factor))


# ---------------------------------------------------------------------------
# scheduling policies
# ---------------------------------------------------------------------------

def _fifo_key(s: QuerySession):
    return s.ordinal


def _priority_key(s: QuerySession):
    return (-s.priority, s.ordinal)


def _fair_key(s: QuerySession):
    # primary clock: attributed dispatch seconds (utils/timing scope);
    # sessions whose work never enters a timed region tie at 0 there, so
    # granted-slice wall time breaks the tie before arrival order does
    return (s.attributed_s() / s.weight, s.service_s / s.weight,
            s.ordinal)


POLICIES = {"fifo": _fifo_key, "priority": _priority_key,
            "fair": _fair_key}


# ---------------------------------------------------------------------------
# the scheduler
# ---------------------------------------------------------------------------

class QueryScheduler:
    """Admission-controlled concurrent query scheduler over one mesh.

    Usage::

        sched = QueryScheduler(env, policy="fair")
        a = sched.submit("tenant_a", qa, footprint_bytes=fa)
        b = sched.submit("tenant_b", qb, footprint_bytes=fb, weight=2.0)
        sched.run()                      # interleaves until all done
        a.result, a.summary()

    ``budget_bytes`` overrides the ledger budget for ADMISSION decisions
    only (the ledger's own allocation-time budget stays
    ``CYLON_TPU_HBM_BUDGET``/platform-detected); ``max_concurrency``
    caps simultaneously admitted sessions independently of memory.
    ``run`` drives the baton loop on the calling thread and returns the
    session list; failed sessions carry their exception in ``.error``
    (pass ``raise_errors=True`` to re-raise the first one)."""

    #: policies under which a higher-ranked arrival may preemptively
    #: drain a running tenant (docs/serving.md, "Preemption")
    PREEMPTIVE_POLICIES = ("priority", "fair")

    def __init__(self, env, policy: str = "fair",
                 budget_bytes: int | None = None,
                 max_concurrency: int | None = None,
                 admission_timeout_s: float | None = None,
                 requeue_capacity: int | None = None,
                 history_safety_factor: float = 1.5,
                 fleet=None):
        if policy not in POLICIES:
            raise InvalidError(
                f"unknown scheduling policy {policy!r}; one of "
                f"{sorted(POLICIES)}")
        self.env = env
        self.policy = policy
        self._key = POLICIES[policy]
        self.budget_bytes = budget_bytes
        self.max_concurrency = max_concurrency
        #: admission deadline (seconds); falls back to the
        #: CYLON_TPU_ADMISSION_TIMEOUT_S env knob when None
        self.admission_timeout_s = admission_timeout_s
        #: max preempt-requeues per run; beyond it a drained tenant
        #: stays failed TYPED (RequeueOverflowError).  None = unbounded.
        self.requeue_capacity = requeue_capacity
        #: multiplier on the ANALYZE-observed family peak when clamping
        #: declared admission footprints (satellite: estimates from
        #: history)
        self.history_safety_factor = float(history_safety_factor)
        #: optional exec.fleet.ResizeController polled each loop turn
        self._fleet = fleet
        self.sessions: list[QuerySession] = []
        self._control = threading.Event()
        self._abort = False
        self._forced_admissions = 0
        self._scheduler_evictions = 0
        self._preempt_drained = 0
        self._preemptions = 0
        self._requeues = 0
        self._requeue_overflows = 0
        self._admission_timeouts = 0
        self._fleet_drains = 0
        self._fleet_drain = False
        #: set by a fleet drain: the agreed new world size the caller
        #: should relaunch at (with CYLON_TPU_RESUME=1)
        self.resize_target: int | None = None

    # -- submission --------------------------------------------------------
    def submit(self, name: str, fn, *, footprint_bytes: int = 0,
               priority: int = 0, weight: float = 1.0,
               tenant: str | None = None,
               kind: str = "query", preempt_budget: int = 2,
               shape_family: str | None = None) -> QuerySession:
        """Queue one query.  ``fn`` is a zero-arg callable executed on
        the session's thread under the baton; its return value lands in
        ``session.result``.  ``footprint_bytes`` is the pack-time HBM
        estimate admission gates on (:func:`estimate_footprint`).

        ``kind="stream"`` marks a STREAMING session — a long-lived
        ingest loop (:mod:`cylon_tpu.stream`) whose interleave points
        are its own micro-batch appends, watermark votes and window
        closes rather than piece-loop boundaries; admission, policies
        and isolation treat it exactly like a query tenant, so
        continuous ingest coexists with the TPC-H mix on one mesh
        (docs/streaming.md)."""
        if any(s.name == name for s in self.sessions):
            raise InvalidError(f"duplicate session name {name!r}")
        sess = QuerySession(name, fn, len(self.sessions),
                            footprint_bytes=footprint_bytes,
                            priority=priority, weight=weight, tenant=tenant,
                            kind=kind, preempt_budget=preempt_budget,
                            shape_family=shape_family)
        self.sessions.append(sess)
        return sess

    # -- the baton loop ----------------------------------------------------
    def run(self, raise_errors: bool = False) -> list[QuerySession]:
        global _ACTIVE
        if _ACTIVE is not None:
            raise InvalidError(
                "a QueryScheduler is already serving this process")
        if not self.sessions:
            return []
        self._abort = False   # run() is re-enterable: a completed run's
        #                       abort latch must not fail later submits
        _ACTIVE = self
        try:
            self._loop()
        finally:
            # abort protocol: parked sessions wake, see _abort and RAISE
            # at their yield point (-> FAILED, thread exits) — they must
            # never free-run concurrently without the baton, which would
            # break the single-controller semantics every operator
            # assumes.  _ACTIVE stays set until the threads drain.
            self._abort = True
            for s in self.sessions:
                if s.state == RUNNING:
                    s._grant.set()     # release any thread still parked
            for s in self.sessions:
                if s._thread is not None:
                    s._thread.join(timeout=60.0)
            _ACTIVE = None
        # per-tenant outcome accounting: one sched_outcome_* counter
        # tick per finished session per lifetime (re-enterable run()s
        # must not double-count), so "zero failed tenants" is a
        # checkable counter (docs/serving.md)
        for s in self.sessions:
            if s.state in (DONE, FAILED) and not s._outcome_counted:
                s._outcome_counted = True
                _metrics.counter(f"sched_outcome_{s.outcome()}").inc()
        if raise_errors:
            for s in self.sessions:
                if s.error is not None:
                    raise s.error
        return list(self.sessions)

    def _loop(self) -> None:
        while True:
            # periodic metrics snapshot for the GKE deploy
            # (CYLON_TPU_METRICS_JSON) — one list load when unarmed
            _metrics.maybe_write_snapshot()
            if self._draining():
                # preemption grace (exec/preempt): a SIGTERM arrived
                # with checkpointing armed — drain the whole box.  No
                # new admissions; PENDING sessions fail typed with the
                # resume token (they never started, so a resume simply
                # recomputes them); RUNNING sessions keep getting slices
                # and exit via their own checkpoint-boundary drains —
                # each tenant commits its current stage and raises
                # ResumableAbort, so a multi-tenant box preempts as
                # cleanly as a single query (docs/serving.md).
                self._drain_pending()
            elif self._fleet_drain:
                # elastic resize in flight (exec/fleet): same protocol
                # as the grace drain — no new admissions, pending fail
                # typed-resumable, running sessions drain at their own
                # boundaries; the caller relaunches at resize_target
                self._drain_pending()
            else:
                self._requeue_preempted()
                self._admit_pending()
                if self._fleet is not None and not self._fleet_drain:
                    self._fleet.maybe_resize(self)
            running = [s for s in self.sessions if s.state == RUNNING]
            if not running:
                if any(s.state == PENDING for s in self.sessions):
                    # nothing running AND the head cannot fit even after
                    # eviction: degrade to serial execution rather than
                    # starve (docs/serving.md admission contract)
                    self._force_admit()
                    continue
                return
            self._grant_slice(self._pick(running))

    # -- preemption-grace drain --------------------------------------------
    def _draining(self) -> bool:
        """Preemption check gating NEW admissions.  In a multiprocess
        session the decision rides the same rank-coherent vote as the
        sessions' own boundary drains (``recovery.drain_consensus``) —
        a rank-local read would let the SIGTERM'd rank fail a pending
        session while its peers admit and start it, leaving them alone
        in that session's first collective.  Every rank's scheduler
        loop reaches this poll at the same iteration (the pick
        consensus already requires lockstep loops), and the vote is
        armed-only: grace budget + checkpointing, same as the piece
        boundaries."""
        from . import checkpoint, preempt
        if not (preempt.armed() and checkpoint.enabled()):
            return False
        if self._multi():
            from . import recovery
            return recovery.drain_consensus(self.env.mesh,
                                            preempt.requested())
        return preempt.requested()

    def _drain_pending(self) -> None:
        from . import checkpoint, recovery
        for s in self.sessions:
            if s.state != PENDING:
                continue
            token = checkpoint.flush_for_abort(f"sched.{s.name}")
            recovery._record(f"sched.{s.name}", "preempt", "drain_pending")
            s.state = FAILED
            s.error = ResumableAbort(
                f"preemption grace drain: session {s.name} was queued but "
                "never admitted — nothing committed, a rerun with "
                f"CYLON_TPU_RESUME=1 recomputes it (resume token: {token})",
                token=token)
            s.finished_s = time.perf_counter()
            self._preempt_drained += 1
            _metrics.counter("sched_preempt_drained").inc()

    # -- admission ---------------------------------------------------------
    def _budget(self) -> int:
        from . import memory
        if self.budget_bytes is not None:
            return int(self.budget_bytes)
        return memory.budget_bytes()

    def _admission_footprint(self, sess: QuerySession) -> int:
        """The footprint admission charges a session: the declared
        pack-time estimate, clamped by ANALYZE history when the
        session's shape family has a recorded peak-ledger observation —
        ``min(declared, observed_peak x safety_factor)`` — so a
        conservative declared maximum no longer serializes tenants that
        demonstrably co-fit (docs/serving.md, "Admission estimates from
        history").  History values are recorded by
        ``obs.plan.explain_analyze(family=...)`` and are rank-uniform
        (every rank ran the same ANALYZE), so the clamp cannot fork
        admission across ranks."""
        peak = observed_peak(sess.shape_family)
        if peak is None:
            return sess.footprint_bytes
        return min(sess.footprint_bytes,
                   int(peak * self.history_safety_factor))

    def _fits(self, sess: QuerySession) -> bool:
        """Admission predicate: the candidate's DECLARED footprint on
        top of the running sessions' declared footprints must fit the
        budget.  Declared, not realized: admission happens BEFORE a
        query packs anything (the ledger balance alone would admit
        everyone up front), and realized pressure from estimates that
        were wrong is already handled at allocation time by the
        ledger's own admission path (``ensure_headroom`` evicts/spills
        with consensus) — gating here on the process-global balance
        would also leak unrelated residents into every decision.
        Declared values are history-clamped per shape family
        (:meth:`_admission_footprint`)."""
        b = self._budget()
        if b <= 0:
            return True
        committed = sum(self._admission_footprint(s) for s in self.sessions
                        if s.state == RUNNING)
        return committed + self._admission_footprint(sess) <= b

    def _multi(self) -> bool:
        import jax
        return (getattr(self.env, "mesh", None) is not None
                and jax.process_count() > 1)

    def _evict_for(self, sess: QuerySession) -> None:
        """Clear REALIZED residue for an admission: evict cold tenants'
        spillable registrations down to the budget before the admitted
        session allocates anything — deterministic LRU over the shared
        ledger, count agreed across ranks (the Code.SpillRequired
        family's wire) so every rank evicts the same owners in the same
        order."""
        from . import memory, recovery
        if not config.SPILL_ENABLED:
            return
        b = self._budget()
        if b <= 0:
            return
        want = memory.ledger().evict_count_for(
            self._admission_footprint(sess), b)
        if self._multi():
            want = recovery.count_consensus(self.env.mesh, want)
        if want <= 0:
            return
        evicted = memory.ledger().evict_n(want)
        if evicted:
            self._scheduler_evictions += len(evicted)
            _metrics.counter("sched_evictions").inc(len(evicted))
            from ..utils.logging import log
            log.info("scheduler: evicted %s to admit session %s "
                     "(footprint %d B)", evicted, sess.name,
                     sess.footprint_bytes)

    def _admit_pending(self) -> None:
        self._expire_admissions()
        while True:
            pend = [s for s in self.sessions if s.state == PENDING]
            if not pend:
                return
            running = [s for s in self.sessions if s.state == RUNNING]
            cand = min(pend, key=self._key)
            if self._multi() and any(s.requeues for s in pend):
                # a requeued tenant's fair-share clocks are wall time
                # and NOT rank-uniform, so once one is queued the head-
                # of-line pick itself must be agreed (same wire as the
                # running pick) — never-ran pendings tie at 0 and need
                # no vote
                from . import recovery
                from ..status import RankDesyncError
                agreed = recovery.count_consensus(self.env.mesh,
                                                  cand.ordinal)
                cand = next((s for s in pend if s.ordinal == agreed), None)
                if cand is None:
                    raise RankDesyncError(
                        f"admission pick consensus chose ordinal {agreed},"
                        " which is not pending on this rank — session "
                        "states diverged", site="scheduler.admit")
            if (self.max_concurrency is not None
                    and len(running) >= self.max_concurrency):
                self._maybe_preempt(cand, running)
                self._note_wait(pend)
                return
            if not self._fits(cand):
                # head-of-line admission (no overtaking): deterministic
                # and starvation-free — smaller later queries never
                # leapfrog a waiting tenant.  A higher-ranked candidate
                # may instead preemptively DRAIN the lowest-ranked
                # running tenant at its next checkpoint boundary
                self._maybe_preempt(cand, running)
                self._note_wait([cand])
                return
            # the declared footprint fits; clear REALIZED residue first
            # — cold tenants' spillable registrations (or estimates
            # that ran over) evict down to make room before the new
            # session allocates anything
            self._evict_for(cand)
            self._start(cand)

    # -- admission deadline ------------------------------------------------
    def _admission_timeout(self) -> float | None:
        """Effective admission deadline: constructor knob first, then
        ``CYLON_TPU_ADMISSION_TIMEOUT_S``.  None / non-positive =>
        unbounded (the pre-PR-18 behavior)."""
        t = self.admission_timeout_s
        if t is None:
            import os
            raw = os.environ.get("CYLON_TPU_ADMISSION_TIMEOUT_S")
            if not raw:
                return None
            try:
                t = float(raw)
            except ValueError:
                return None
        return t if t > 0 else None

    def _expire_admissions(self) -> None:
        """Fail pending sessions whose admission wait exceeded the
        deadline — typed (:class:`AdmissionTimeoutError`), never a
        hang.  In multiprocess sessions wall clocks diverge, so the
        expiry DECISION is agreed over the count wire: the vote is
        entered whenever a deadline is configured and someone is
        waiting (both rank-uniform facts), and the max expired ordinal
        wins — one session per loop turn, the loop converges on the
        rest."""
        t = self._admission_timeout()
        if t is None:
            return
        waiting = [s for s in self.sessions
                   if s.state == PENDING and s._wait_mark is not None]
        if not waiting:
            return
        now = time.perf_counter()
        expired = [s for s in waiting if now - s._wait_mark > t]
        if self._multi():
            from . import recovery
            want = max((s.ordinal + 1 for s in expired), default=0)
            agreed = recovery.count_consensus(self.env.mesh, want)
            expired = [s for s in waiting if s.ordinal + 1 == agreed]
        for s in expired:
            waited = now - s._wait_mark
            s.admission_wait_s += waited
            s._wait_mark = None
            s.state = FAILED
            from ..status import AdmissionTimeoutError
            s.error = AdmissionTimeoutError(
                f"session {s.name} exceeded the admission deadline "
                f"({t:g}s) after waiting {waited:.3f}s at head of line "
                "— failing typed instead of waiting unboundedly "
                "(CYLON_TPU_ADMISSION_TIMEOUT_S / admission_timeout_s)",
                session=s.name, waited_s=waited)
            s.finished_s = time.perf_counter()
            self._admission_timeouts += 1
            _metrics.counter("sched_admission_timeouts").inc()
            from ..utils.logging import log
            log.warning("scheduler: admission deadline (%gs) expired for "
                        "session %s after %.3fs", t, s.name, waited)

    # -- preemptive scheduling (docs/serving.md) ---------------------------
    def _pick_victim(self, cand: QuerySession,
                     running: list[QuerySession]) -> QuerySession | None:
        """Rank-local victim choice for a preemptive drain: the LOWEST-
        ranked (max policy key) running query session that (a) is not
        already draining, (b) is strictly outranked by the candidate,
        (c) has preemption budget left, and (d) passes the no-progress
        guard — a tenant that committed zero new pieces since its last
        preemption is temporarily unpreemptable (otherwise a storm of
        arrivals could starve it forever)."""
        victims = [
            s for s in running
            if s.kind == "query" and s._drain_mode is None
            and self._key(cand) < self._key(s)
            and s.preemptions < s.preempt_budget
            and (s.preemptions == 0
                 or s.pieces_committed > s._progress_mark)
        ]
        if not victims:
            return None
        return max(victims, key=self._key)

    def _maybe_preempt(self, cand: QuerySession,
                       running: list[QuerySession]) -> bool:
        """Preemption decision for a blocked higher-ranked candidate.
        Armed-only: preemptive policies + durable checkpointing (the
        drain rides checkpoint boundaries; without it there is nothing
        to resume).  The decision is agreed over the session-namespaced
        consensus wire (max victim ordinal + 1 wins; 0 = no victim)
        BEFORE the victim is flagged, so every rank drains the same
        tenant — the vote short-circuits to the local choice in
        single-controller runs."""
        if self.policy not in self.PREEMPTIVE_POLICIES:
            return False
        from . import checkpoint
        if not checkpoint.enabled():
            return False
        from . import recovery
        from ..status import RankDesyncError
        victim = self._pick_victim(cand, running)
        want = 0 if victim is None else victim.ordinal + 1
        agreed = recovery.preempt_consensus(
            self.env.mesh if self._multi() else None, want)
        if not agreed:
            return False
        victim = next((s for s in running if s.ordinal == agreed - 1),
                      None)
        if victim is None:
            raise RankDesyncError(
                f"preempt consensus chose session ordinal {agreed - 1}, "
                "which is not running on this rank — session states "
                "diverged across ranks", site="sched.preempt")
        self._begin_preempt_drain(victim, cand)
        return True

    def _begin_preempt_drain(self, victim: QuerySession,
                             cand: QuerySession) -> None:
        """Flag the agreed victim for a checkpoint-boundary drain: its
        next ``checkpoint.drain_requested`` poll commits the current
        stage and raises ResumableAbort; the requeue pass then turns
        that into a fresh PENDING entry that fast-forwards on
        re-grant."""
        victim._drain_mode = "preempt"
        victim._progress_mark = victim.pieces_committed
        self._preemptions += 1
        _metrics.counter("sched_preemptions").inc()
        _trace.instant("sched.preempt", session=victim.name,
                       by=cand.name, policy=self.policy)
        from ..utils.logging import log
        log.info("scheduler: preempting session %s at its next "
                 "checkpoint boundary to admit %s (policy=%s)",
                 victim.name, cand.name, self.policy)

    def _requeue_preempted(self) -> None:
        """Turn completed preempt drains back into PENDING sessions.
        The drained tenant's committed pieces survive in its session-
        namespaced checkpoint stages; ``_resume_pending`` makes its
        next fn run resume in-process (checkpoint.resume_requested), so
        the re-granted run fast-forwards the committed prefix
        bit-identically.  Requeue capacity overflow is TYPED
        (RequeueOverflowError, resume token on ``__cause__``)."""
        for s in self.sessions:
            if s._drain_mode is None:
                continue
            if s.state == DONE:
                # flagged but finished before reaching a boundary —
                # nothing to requeue (sessions without checkpoint
                # stages never poll the drain; preemption is
                # best-effort for them by design)
                s._drain_mode = None
                continue
            if s.state != FAILED:
                continue   # still draining
            if s._drain_mode == "fleet":
                continue   # stays failed-resumable for the relaunch
            if not isinstance(s.error, ResumableAbort):
                s._drain_mode = None   # real failure mid-drain: keep it
                continue
            if (self.requeue_capacity is not None
                    and self._requeues >= self.requeue_capacity):
                from ..status import RequeueOverflowError
                err = RequeueOverflowError(
                    f"session {s.name} drained resumably but the "
                    f"requeue capacity ({self.requeue_capacity}) is "
                    "exhausted — relaunch with CYLON_TPU_RESUME=1 to "
                    "resume it", session=s.name)
                err.__cause__ = s.error
                s.error = err
                s._drain_mode = None
                self._requeue_overflows += 1
                _metrics.counter("sched_requeue_overflows").inc()
                continue
            from . import checkpoint
            checkpoint.reset_session_stages(s.name)
            s._drain_mode = None
            s.preemptions += 1
            s.requeues += 1
            s._progress_mark = s.pieces_committed
            s._resume_pending = True
            s.state = PENDING
            s.error = None
            s.finished_s = None
            s._thread = None
            s._grant = threading.Event()
            self._requeues += 1
            _metrics.counter("sched_requeues").inc()
            _trace.instant("sched.requeue", session=s.name)

    # -- elastic fleet drain (exec/fleet) ----------------------------------
    def _begin_fleet_drain(self, target_world: int, reason: str) -> None:
        """All-or-nothing elastic drain: every running tenant drains at
        its next checkpoint boundary (same flag the preempt path uses,
        but WITHOUT requeue — the resumes happen in the relaunched
        process at the new world), pending tenants fail
        typed-resumable, and the caller exits RESUMABLE_EXIT with
        ``resize_target`` set."""
        if self._fleet_drain:
            return
        self._fleet_drain = True
        self.resize_target = int(target_world)
        self._fleet_drains += 1
        _metrics.counter("sched_fleet_drains").inc()
        for s in self.sessions:
            if s.state == RUNNING and s._drain_mode is None:
                s._drain_mode = "fleet"
        _trace.instant("sched.fleet_drain", target_world=target_world,
                       reason=reason)
        from ..utils.logging import log
        log.warning("scheduler: elastic fleet drain engaged (%s) — "
                    "draining all tenants at their boundaries, relaunch "
                    "at world=%d with CYLON_TPU_RESUME=1",
                    reason, target_world)

    def _note_wait(self, sessions) -> None:
        now = time.perf_counter()
        for s in sessions:
            if s._wait_mark is None:
                s._wait_mark = now
                s.admission_waits += 1

    def _force_admit(self) -> None:
        pend = [s for s in self.sessions if s.state == PENDING]
        cand = min(pend, key=self._key)
        self._forced_admissions += 1
        _metrics.counter("sched_forced_admissions").inc()
        # force-degrade-to-serial is a distinct serving condition from a
        # plain forced admission start: count it under its own name and
        # close the candidate's open wait period HERE — _start would
        # also close it, but a force-serial grant that raced the wait
        # bookkeeping used to leave the period open (stale
        # admission_wait_s) when the candidate was force-admitted on
        # the same loop turn it was first noted waiting
        _metrics.counter("sched_admission_force_serial").inc()
        now = time.perf_counter()
        if cand._wait_mark is not None:
            cand.admission_wait_s += now - cand._wait_mark
            cand._wait_mark = None
        from ..utils.logging import log
        log.warning("scheduler: nothing running and session %s "
                    "(footprint %d B) cannot fit the budget — force-"
                    "admitting; execution degrades to the ledger's own "
                    "spill tier", cand.name, cand.footprint_bytes)
        self._start(cand)

    def _start(self, sess: QuerySession) -> None:
        now = time.perf_counter()
        if sess._wait_mark is not None:
            sess.admission_wait_s += now - sess._wait_mark
            sess._wait_mark = None
        sess.state = RUNNING
        sess.started_s = now
        t = threading.Thread(target=self._session_body, args=(sess,),
                             name=f"cylon-session-{sess.name}", daemon=True)
        sess._thread = t
        t.start()

    # -- baton -------------------------------------------------------------
    def _session_body(self, sess: QuerySession) -> None:
        from ..utils import timing
        from . import recovery
        _tls.session = sess
        recovery.set_session(sess.name, sess.ordinal)
        sess._grant.wait()
        sess._grant.clear()
        sess._slice_t0 = time.perf_counter()
        try:
            if self._abort:
                # the scheduler aborted before this session's first
                # slice: fail it rather than free-run without the baton
                # (the same abort protocol _yield_turn enforces)
                from ..status import ExecutionError
                raise ExecutionError(
                    f"serving scheduler aborted before session "
                    f"{sess.name} ran")
            with timing.attribution_scope(sess.name) as scope:
                sess.timing = scope
                sess.result = sess.fn()
            sess.state = DONE
        except BaseException as e:  # noqa: BLE001 — isolated per session
            sess.error = e
            sess.state = FAILED
        finally:
            sess.service_s += time.perf_counter() - sess._slice_t0
            sess.slices += 1
            sess.finished_s = time.perf_counter()
            recovery.set_session(None, None)
            _tls.session = None
            self._control.set()

    def _yield_turn(self, sess: QuerySession) -> None:
        """Session side of the baton (runs on the session thread).  On
        scheduler abort the session FAILS at its yield point instead of
        free-running without the baton — concurrent unsupervised
        sessions would violate the single-controller semantics the
        engine assumes."""
        from ..status import ExecutionError
        if self._abort:
            raise ExecutionError(
                f"serving scheduler aborted while session {sess.name} "
                "was in flight")
        t_park = time.perf_counter()
        sess.service_s += t_park - sess._slice_t0
        sess.slices += 1
        self._control.set()
        sess._grant.wait()
        sess._grant.clear()
        sess._slice_t0 = time.perf_counter()
        # time parked at the baton is co-tenants' work, not this
        # session's: regions spanning this yield must not absorb it
        # (utils/timing scope exclusion — the no-bleed invariant)
        from ..utils import timing
        timing.exclude_from_scope(sess._slice_t0 - t_park)
        # baton handoff on the trace timeline: the park span (session-
        # tagged via the active attribution scope) shows exactly where a
        # tenant waited while its async device work kept running
        _trace.complete("sched.park", t_park, session=sess.name)
        if self._abort:
            raise ExecutionError(
                f"serving scheduler aborted while session {sess.name} "
                "was parked at a yield point")

    def _pick(self, running: list[QuerySession]) -> QuerySession:
        sess = min(running, key=self._key)
        if self._multi():
            # policy inputs like fair-share clocks are wall-time and NOT
            # rank-uniform: agree the pick (max ordinal wins) so every
            # rank grants the identical session — the serving analog of
            # the ladder's code consensus
            from . import recovery
            from ..status import RankDesyncError
            agreed = recovery.count_consensus(self.env.mesh, sess.ordinal)
            for s in running:
                if s.ordinal == agreed:
                    return s
            # a pick this rank cannot honor means session STATES have
            # already diverged across ranks; granting a local fallback
            # would dispatch different tenants' collectives per rank —
            # surface the divergence typed, at the point it is detected
            raise RankDesyncError(
                f"scheduler pick consensus chose session ordinal "
                f"{agreed}, which is not running on this rank "
                f"(running: {[s.ordinal for s in running]}) — session "
                "states diverged across ranks", site="scheduler.pick")
        return sess

    def _grant_slice(self, sess: QuerySession) -> None:
        self._control.clear()
        _trace.instant("sched.grant", session=sess.name,
                       policy=self.policy)
        sess._grant.set()
        while not self._control.wait(timeout=60.0):
            t = sess._thread
            if t is None or not t.is_alive():
                if sess.state == RUNNING:   # died without signaling
                    sess.state = FAILED
                    sess.error = RuntimeError(
                        f"session {sess.name} thread died mid-slice")
                return

    # -- reporting ---------------------------------------------------------
    def stats(self) -> dict:
        """Serving-tier counters for bench JSON detail (per-session
        detail rides each session's ``summary()``)."""
        from . import compiler, memory
        mem = memory.stats()
        comp = compiler.stats()
        outcomes: dict[str, int] = {}
        for s in self.sessions:
            if s.state in (DONE, FAILED):
                o = s.outcome()
                outcomes[o] = outcomes.get(o, 0) + 1
        return {
            "policy": self.policy,
            "sessions": len(self.sessions),
            "stream_sessions": sum(1 for s in self.sessions
                                   if s.kind == "stream"),
            "completed": sum(1 for s in self.sessions if s.state == DONE),
            "failed": sum(1 for s in self.sessions if s.state == FAILED),
            "admission_waits": sum(s.admission_waits
                                   for s in self.sessions),
            "admission_wait_s": round(sum(s.admission_wait_s
                                          for s in self.sessions), 4),
            "forced_admissions": self._forced_admissions,
            "admission_force_serial": self._forced_admissions,
            "admission_timeouts": self._admission_timeouts,
            "scheduler_evictions": self._scheduler_evictions,
            "preempt_drained": self._preempt_drained,
            "preemptions": self._preemptions,
            "requeues": self._requeues,
            "requeue_overflows": self._requeue_overflows,
            "fleet_drains": self._fleet_drains,
            "resize_target": self.resize_target,
            "outcomes": outcomes,
            "resumable_aborts": sum(1 for s in self.sessions
                                    if isinstance(s.error, ResumableAbort)),
            "cross_session_evictions": mem["cross_session_evictions"],
            "spill_events": mem["spill_events"],
            "slices": sum(s.slices for s in self.sessions),
            # the compile-lifecycle block: a serving summary always says
            # whether tenant admission churned the executable population
            # (flat programs_live under shape families is the multi-
            # tenant compile-cost contract, docs/serving.md)
            "compile": {k: comp[k] for k in
                        ("programs_live", "cache_hits", "cache_misses",
                         "cache_evictions", "compile_seconds")},
        }
