"""IO: CSV / Parquet / JSON readers and writers, single and distributed.

TPU-native equivalent of the reference's IO layer (cpp/src/cylon/io/
arrow_io.cpp FromCSV/WriteCSV/FromParquet, table.cpp:239,318,1637,1696) and
PyCylon's distributed readers (python/pycylon/pycylon/frame.py
distributed_io.py:44 ``read_csv_dist`` — file lists divided among ranks,
:146 ``read_parquet_dist`` — row-group balancing, :344 write_*_dist).

Single-controller translation: the controller reads (optionally in parallel
threads, like the reference's ReadCSVThread table.cpp:1167-1210) and
distributes rows onto the mesh; distributed writes emit one file per shard
exactly like the per-rank writers of the reference.
"""

from __future__ import annotations

import glob as _glob
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Sequence

import numpy as np

from ..core.table import Table
from ..ctx.context import CylonEnv
from ..status import CylonIOError


def _expand(paths) -> list[str]:
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out = []
    for p in paths:
        p = os.fspath(p)
        matches = sorted(_glob.glob(p)) if any(ch in p for ch in "*?[") else [p]
        out.extend(matches)
    if not out:
        raise CylonIOError(f"no files match {paths!r}")
    return out


def _read_many(files: list[str], read_one, parallel: bool = True):
    """Threaded multi-file read (reference ReadCSVThread, table.cpp:1167)."""
    import pandas as pd
    if len(files) == 1:
        return read_one(files[0])
    if parallel:
        with ThreadPoolExecutor(max_workers=min(8, len(files))) as ex:
            dfs = list(ex.map(read_one, files))
    else:
        dfs = [read_one(f) for f in files]
    return pd.concat(dfs, ignore_index=True)


def read_csv(paths, env: CylonEnv | None = None, **kwargs) -> Table:
    import pandas as pd
    files = _expand(paths)
    df = _read_many(files, lambda f: pd.read_csv(f, **kwargs))
    return Table.from_pandas(df, env)


def read_parquet(paths, env: CylonEnv | None = None, **kwargs) -> Table:
    import pandas as pd
    files = _expand(paths)
    df = _read_many(files, lambda f: pd.read_parquet(f, **kwargs))
    return Table.from_pandas(df, env)


def read_json(paths, env: CylonEnv | None = None, **kwargs) -> Table:
    import pandas as pd
    files = _expand(paths)
    kwargs.setdefault("lines", str(files[0]).endswith(".jsonl"))
    df = _read_many(files, lambda f: pd.read_json(f, **kwargs))
    return Table.from_pandas(df, env)


# -- writers ----------------------------------------------------------------

def write_csv(table: Table, path, **kwargs) -> None:
    kwargs.setdefault("index", False)
    table.to_pandas().to_csv(path, **kwargs)


def write_parquet(table: Table, path, **kwargs) -> None:
    kwargs.setdefault("index", False)
    table.to_pandas().to_parquet(path, **kwargs)


def write_json(table: Table, path, **kwargs) -> None:
    kwargs.setdefault("orient", "records")
    kwargs.setdefault("lines", True)
    table.to_pandas().to_json(path, **kwargs)


def _shard_frames(table: Table):
    """Yield (rank, pandas frame of that shard's valid prefix)."""
    from ..relational import slice_table
    off = 0
    for i, n in enumerate(table.valid_counts):
        yield i, slice_table(table, off, int(n)).to_pandas()
        off += int(n)


def _dist_path(path: str, rank: int) -> str:
    root, ext = os.path.splitext(os.fspath(path))
    return f"{root}_{rank}{ext}"


def write_csv_dist(table: Table, path, **kwargs) -> list[str]:
    """One CSV per shard, ``{path}_{rank}.csv`` (reference write_*_dist,
    distributed_io.py:275-383 writes one file per rank)."""
    kwargs.setdefault("index", False)
    out = []
    for rank, df in _shard_frames(table):
        p = _dist_path(path, rank)
        df.to_csv(p, **kwargs)
        out.append(p)
    return out


def write_parquet_dist(table: Table, path, **kwargs) -> list[str]:
    kwargs.setdefault("index", False)
    out = []
    for rank, df in _shard_frames(table):
        p = _dist_path(path, rank)
        df.to_parquet(p, **kwargs)
        out.append(p)
    return out


# -- distributed readers (file-division semantics) --------------------------

def read_csv_dist(paths, env: CylonEnv, **kwargs) -> Table:
    """Divide the file list among ranks, each rank's files forming its
    partition (reference distributed_io.py:10-44).  The controller reads all
    files but assigns rows to shards following the same file->rank division,
    so resulting partition boundaries match the reference exactly."""
    import pandas as pd
    files = _expand(paths)
    w = env.world_size
    per_rank: list[list[str]] = [[] for _ in range(w)]
    for i, f in enumerate(files):
        per_rank[i % w].append(f)
    frames = []
    counts = []
    for fl in per_rank:
        if fl:
            df = _read_many(fl, lambda f: pd.read_csv(f, **kwargs))
        else:
            df = None
        frames.append(df)
        counts.append(0 if df is None else len(df))
    non_empty = [f for f in frames if f is not None]
    if not non_empty:
        raise CylonIOError("no data read")
    allf = pd.concat(non_empty, ignore_index=True)
    t = Table.from_pandas(allf, env)
    from ..relational import repartition
    return repartition(t, tuple(counts))


def read_parquet_dist(paths, env: CylonEnv, **kwargs) -> Table:
    """Row-group-balanced parquet read (reference distributed_io.py:146):
    row groups are assigned round-robin to ranks by size."""
    import pandas as pd
    import pyarrow.parquet as pq
    files = _expand(paths)
    w = env.world_size
    # collect (file, row_group, n_rows) units
    units = []
    for f in files:
        meta = pq.ParquetFile(f)
        for g in range(meta.num_row_groups):
            units.append((f, g, meta.metadata.row_group(g).num_rows))
    # greedy balance: biggest first onto least-loaded rank
    units.sort(key=lambda u: -u[2])
    loads = [0] * w
    assign: list[list[tuple]] = [[] for _ in range(w)]
    for u in units:
        r = int(np.argmin(loads))
        assign[r].append(u)
        loads[r] += u[2]
    frames, counts = [], []
    for r in range(w):
        if assign[r]:
            parts = [pq.ParquetFile(f).read_row_group(g).to_pandas()
                     for f, g, _ in assign[r]]
            df = pd.concat(parts, ignore_index=True)
        else:
            df = None
        frames.append(df)
        counts.append(0 if df is None else len(df))
    non_empty = [f for f in frames if f is not None]
    if not non_empty:
        raise CylonIOError("no data read")
    allf = pd.concat(non_empty, ignore_index=True)
    t = Table.from_pandas(allf, env)
    from ..relational import repartition
    return repartition(t, tuple(counts))
