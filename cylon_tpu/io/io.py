"""IO: CSV / Parquet / JSON readers and writers, single and distributed.

TPU-native equivalent of the reference's IO layer (cpp/src/cylon/io/
arrow_io.cpp FromCSV/WriteCSV/FromParquet, table.cpp:239,318,1637,1696) and
PyCylon's distributed readers (python/pycylon/pycylon/frame.py
distributed_io.py:44 ``read_csv_dist`` — file lists divided among ranks,
:146 ``read_parquet_dist`` — row-group balancing, :344 write_*_dist).

Single-controller translation: the controller reads (optionally in parallel
threads, like the reference's ReadCSVThread table.cpp:1167-1210) and
distributes rows onto the mesh; distributed writes emit one file per shard
exactly like the per-rank writers of the reference.
"""

from __future__ import annotations

import glob as _glob
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Sequence

import numpy as np

from ..core.table import Table
from ..ctx.context import CylonEnv
from ..status import CylonIOError, CylonTypeError


def _expand(paths) -> list[str]:
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out = []
    for p in paths:
        p = os.fspath(p)
        matches = sorted(_glob.glob(p)) if any(ch in p for ch in "*?[") else [p]
        out.extend(matches)
    if not out:
        raise CylonIOError(f"no files match {paths!r}")
    return out


def _read_many(files: list[str], read_one, parallel: bool = True):
    """Threaded multi-file read (reference ReadCSVThread, table.cpp:1167)."""
    import pandas as pd
    if len(files) == 1:
        return read_one(files[0])
    if parallel:
        with ThreadPoolExecutor(max_workers=min(8, len(files))) as ex:
            dfs = list(ex.map(read_one, files))
    else:
        dfs = [read_one(f) for f in files]
    return pd.concat(dfs, ignore_index=True)


def _concat_arrow(tables):
    import pyarrow as pa
    if len(tables) == 1:
        return tables[0]
    return pa.concat_tables(tables, promote_options="default")


def _read_many_arrow(files: list[str], read_one, parallel: bool = True):
    """Threaded multi-file Arrow read (reference ReadCSVThread,
    table.cpp:1167)."""
    if len(files) == 1:
        return read_one(files[0])
    if parallel:
        with ThreadPoolExecutor(max_workers=min(8, len(files))) as ex:
            ats = list(ex.map(read_one, files))
    else:
        ats = [read_one(f) for f in files]
    return _concat_arrow(ats)


def read_csv(paths, env: CylonEnv | None = None, **kwargs) -> Table:
    """Arrow-native CSV read (reference io/arrow_io.cpp FromCSV) — the
    column buffers go straight to host arrays, no pandas object round trip.
    Passing pandas-specific kwargs falls back to the pandas reader."""
    files = _expand(paths)
    if kwargs:
        import pandas as pd
        df = _read_many(files, lambda f: pd.read_csv(f, **kwargs))
        return Table.from_pandas(df, env)
    from pyarrow import csv as pacsv
    at = _read_many_arrow(files, lambda f: pacsv.read_csv(f))
    try:
        return Table.from_arrow(at, env)
    except CylonTypeError:
        # unsupported arrow column type: convert the ALREADY-READ table
        # (no second disk pass)
        return Table.from_pandas(at.to_pandas(), env)


def read_parquet(paths, env: CylonEnv | None = None, **kwargs) -> Table:
    files = _expand(paths)
    if kwargs:
        import pandas as pd
        df = _read_many(files, lambda f: pd.read_parquet(f, **kwargs))
        return Table.from_pandas(df, env)
    import pyarrow.parquet as pq
    at = _read_many_arrow(files, lambda f: pq.read_table(f))
    try:
        return Table.from_arrow(at, env)
    except CylonTypeError:
        return Table.from_pandas(at.to_pandas(), env)


def read_json(paths, env: CylonEnv | None = None, **kwargs) -> Table:
    files = _expand(paths)
    if not kwargs:
        # pyarrow's reader only speaks newline-delimited JSON; fall back to
        # pandas for array-of-objects files
        try:
            from pyarrow import json as pajson
            at = _read_many_arrow(files, lambda f: pajson.read_json(f))
            return Table.from_arrow(at, env)
        except Exception:  # noqa: BLE001 — e.g. pyarrow.ArrowInvalid
            pass
    import pandas as pd
    kwargs.setdefault("lines", str(files[0]).endswith(".jsonl"))
    df = _read_many(files, lambda f: pd.read_json(f, **kwargs))
    return Table.from_pandas(df, env)


# -- writers ----------------------------------------------------------------

def write_csv(table: Table, path, **kwargs) -> None:
    kwargs.setdefault("index", False)
    table.to_pandas().to_csv(path, **kwargs)


def write_parquet(table: Table, path, **kwargs) -> None:
    kwargs.setdefault("index", False)
    table.to_pandas().to_parquet(path, **kwargs)


def write_json(table: Table, path, **kwargs) -> None:
    kwargs.setdefault("orient", "records")
    kwargs.setdefault("lines", True)
    table.to_pandas().to_json(path, **kwargs)


def _shard_frames(table: Table):
    """Yield (rank, pandas frame of that shard's valid prefix), STREAMING:
    one shard resident on the host at a time, pulled straight from each
    column's per-shard device buffer (``addressable_shards``) in one
    batched fetch — no whole-table materialization, no device compute
    (the reference writes strictly per rank, distributed_io.py:344).
    Under multi-controller execution only this process's shards yield."""
    import jax
    import pandas as pd
    from ..core.column import Column
    from ..utils.host import host_arrays
    cols = dict(table.columns)
    cap = max(table.capacity, 1)
    # the ranks THIS process writes come from the mesh (single source of
    # truth for single- and multi-controller), not from any column's shard
    # layout — columns may be host numpy or replicated
    me = jax.process_index()
    mesh_devs = list(np.ravel(table.env.mesh.devices))
    ranks = [i for i, d in enumerate(mesh_devs)
             if getattr(d, "process_index", 0) == me]

    def getter(arr):
        """rank -> that rank's row block, without pulling other ranks."""
        if arr is None:
            return lambda i: None
        if isinstance(arr, np.ndarray):
            return lambda i: arr[i * cap:(i + 1) * cap]
        shards, whole = {}, None
        for s in arr.addressable_shards:
            st = s.index[0].start if s.index else None
            if s.data.shape[0] == arr.shape[0]:
                whole = s.data          # replicated / single-shard world
            else:
                shards[int(st) // cap] = s.data
        if shards:
            return lambda i: shards[i]
        return lambda i: whole[i * cap:(i + 1) * cap]

    getters = [(n, c, getter(c.data), getter(c.validity))
               for n, c in cols.items()]
    for i in ranks:
        n_live = int(table.valid_counts[i])
        flat = []
        for _, _, gd, gv in getters:
            flat.append(gd(i))
            flat.append(gv(i))
        # documented device→host PULL boundary (docs/trace_safety.md):
        # one batched sanctioned fetch through the utils/host funnel —
        # permitted under the tracecheck transfer guard
        pulled = host_arrays(flat)
        data = {}
        for j, (name, c, _, _) in enumerate(getters):
            d = np.asarray(pulled[2 * j])[:n_live]
            v = pulled[2 * j + 1]
            v = np.asarray(v)[:n_live] if v is not None else None
            data[name] = Column(d, c.type, v, c.dictionary).to_numpy(n_live)
        yield i, pd.DataFrame(data)


def _dist_path(path: str, rank: int) -> str:
    root, ext = os.path.splitext(os.fspath(path))
    return f"{root}_{rank}{ext}"


def write_csv_dist(table: Table, path, **kwargs) -> list[str]:
    """One CSV per shard, ``{path}_{rank}.csv`` (reference write_*_dist,
    distributed_io.py:275-383 writes one file per rank)."""
    kwargs.setdefault("index", False)
    out = []
    for rank, df in _shard_frames(table):
        p = _dist_path(path, rank)
        df.to_csv(p, **kwargs)
        out.append(p)
    return out


def write_parquet_dist(table: Table, path, **kwargs) -> list[str]:
    kwargs.setdefault("index", False)
    out = []
    for rank, df in _shard_frames(table):
        p = _dist_path(path, rank)
        df.to_parquet(p, **kwargs)
        out.append(p)
    return out


def write_json_dist(table: Table, path, **kwargs) -> list[str]:
    """One JSON file per shard (reference distributed_io.py:275-383 writes
    csv/json/parquet per rank)."""
    kwargs.setdefault("orient", "records")
    kwargs.setdefault("lines", True)
    out = []
    for rank, df in _shard_frames(table):
        p = _dist_path(path, rank)
        df.to_json(p, **kwargs)
        out.append(p)
    return out


# -- distributed readers (file-division semantics) --------------------------

def read_csv_dist(paths, env: CylonEnv, **kwargs) -> Table:
    """Divide the file list among ranks, each rank's files forming its
    partition (reference distributed_io.py:10-44).  The controller reads all
    files but assigns rows to shards following the same file->rank division,
    so resulting partition boundaries match the reference exactly."""
    files = _expand(paths)
    w = env.world_size
    per_rank: list[list[str]] = [[] for _ in range(w)]
    for i, f in enumerate(files):
        per_rank[i % w].append(f)
    if kwargs:  # pandas-specific options: per-rank pandas reads
        import pandas as pd
        read_one = lambda fl: _read_many(fl, lambda f: pd.read_csv(f, **kwargs))
        parts = [(read_one(fl) if fl else None) for fl in per_rank]
        counts = [0 if p is None else len(p) for p in parts]
        live = [p for p in parts if p is not None]
        if not live:
            raise CylonIOError("no data read")
        t = Table.from_pandas(pd.concat(live, ignore_index=True), env)
    else:
        from pyarrow import csv as pacsv
        parts, counts = [], []
        for fl in per_rank:
            if fl:
                at = _read_many_arrow(fl, lambda f: pacsv.read_csv(f))
                parts.append(at)
                counts.append(at.num_rows)
            else:
                counts.append(0)
        if not parts:
            raise CylonIOError("no data read")
        t = Table.from_arrow(_concat_arrow(parts), env)
    from ..relational import repartition
    return repartition(t, tuple(counts))


def _row_group_units(files: list[str]) -> list[tuple]:
    """(file, row_group, n_rows) units in file/row-group order — the
    shared scan geometry of the balanced read and the streaming scan."""
    import pyarrow.parquet as pq
    units = []
    for f in files:
        meta = pq.ParquetFile(f)
        for g in range(meta.num_row_groups):
            units.append((f, g, meta.metadata.row_group(g).num_rows))
    return units


class ParquetScanSource:
    """Streaming row-group scan — the scan-pushdown producer
    (reference read→partition→operate stack, distributed_io.py:146
    re-thought for out-of-core inputs): iterating yields one
    device-distributed :class:`Table` per BATCH of consecutive row
    groups, so the input side of a query holds at most one batch's rows
    at a time and the full table never enters the HBM ledger at full
    size.  PieceSource-compatible in the incremental-producer sense:
    ``column_names`` / ``total_rows`` describe the stream up front, and
    the pipelined consumers (:func:`cylon_tpu.exec.pipeline.
    pipelined_scan_join`, a GroupBySink fed per batch) absorb each
    piece and release it — the same consume-and-release contract a
    PackedPiece window has.

    ``batch_rows`` bounds a batch's row count (a single row group larger
    than it still forms its own batch — row groups are the atomic read
    unit).  ``columns`` projects the read at the parquet layer (column
    pushdown: unselected columns never leave the file; batches — and
    :attr:`column_names` — follow the REQUESTED column order).

    Single-controller translation (same as :func:`read_csv_dist`): the
    controller reads each batch's row groups and distributes the rows
    onto the mesh.  In a multi-controller session every process
    currently reads every row group — per-rank unit assignment (the
    balanced split :func:`read_parquet_dist` already computes) plus the
    per-batch shuffle the consumer performs anyway is the designated
    follow-up for scale-out scans."""

    def __init__(self, paths, env: CylonEnv, batch_rows: int = 1 << 20,
                 columns: Sequence | None = None):
        self.env = env
        self.files = _expand(paths)
        self.batch_rows = max(int(batch_rows), 1)
        self.columns = list(columns) if columns is not None else None
        self._units = _row_group_units(self.files)
        self.total_rows = int(sum(u[2] for u in self._units))
        self._names: list[str] | None = None
        #: one ParquetFile handle per path for the scan's lifetime — a
        #: per-row-group re-open would re-parse the footer every batch,
        #: one storage round trip each on the NFS/object-store backends
        #: this tier targets
        self._handles: dict = {}

    def _file(self, path: str):
        pf = self._handles.get(path)
        if pf is None:
            import pyarrow.parquet as pq
            pf = self._handles[path] = pq.ParquetFile(path)
        return pf

    @property
    def column_names(self) -> list[str]:
        """The stream's schema IN BATCH ORDER: a ``columns=`` projection
        yields batches in the REQUESTED order (pyarrow honors it), so
        the advertised names must match it — a file-schema-ordered
        answer would silently transpose same-dtype columns for a
        positionally-aligning consumer."""
        if self._names is None:
            schema = self._file(self.files[0]).schema_arrow
            if self.columns is None:
                self._names = list(schema.names)
            else:
                self._names = [n for n in self.columns
                               if n in schema.names]
        return self._names

    def batches(self):
        """(file, row_group, n_rows) unit lists, one per batch, in
        file/row-group order (deterministic: a rerun of the scan feeds
        consumers the identical piece sequence)."""
        out, rows = [], 0
        for u in self._units:
            if out and rows + u[2] > self.batch_rows:
                yield out
                out, rows = [], 0
            out.append(u)
            rows += u[2]
        if out:
            yield out

    def __iter__(self):
        for batch in self.batches():
            ats = [self._file(f).read_row_group(g, columns=self.columns)
                   for f, g, _ in batch]
            yield Table.from_arrow(_concat_arrow(ats), self.env)


def scan_parquet_dist(paths, env: CylonEnv, batch_rows: int = 1 << 20,
                      columns=None) -> ParquetScanSource:
    """The streaming (scan-pushdown) mode of :func:`read_parquet_dist`:
    returns a :class:`ParquetScanSource` whose iteration yields
    batch-sized distributed Tables instead of materializing the whole
    input — feed it to ``exec.pipeline.pipelined_scan_join`` or absorb
    its batches into a GroupBySink for out-of-core inputs."""
    return ParquetScanSource(paths, env, batch_rows=batch_rows,
                             columns=columns)


def read_parquet_dist(paths, env: CylonEnv, batch_rows: int | None = None,
                      **kwargs):
    """Row-group-balanced parquet read (reference distributed_io.py:146):
    row groups are assigned round-robin to ranks by size.  Passing
    ``batch_rows`` switches to the STREAMING scan mode instead — the
    returned :class:`ParquetScanSource` yields batch Tables for the
    pipelined consumers and never materializes the full table
    (docs/robustness.md "Disk tier & scan pushdown")."""
    import pyarrow.parquet as pq
    if batch_rows is not None:
        if kwargs:
            raise CylonIOError(
                "streaming parquet scan (batch_rows=) does not take "
                "pandas reader kwargs — project with columns= on "
                "scan_parquet_dist instead")
        return scan_parquet_dist(paths, env, batch_rows=batch_rows)
    files = _expand(paths)
    w = env.world_size
    units = _row_group_units(files)
    # greedy balance: biggest first onto least-loaded rank
    units.sort(key=lambda u: -u[2])
    loads = [0] * w
    assign: list[list[tuple]] = [[] for _ in range(w)]
    for u in units:
        r = int(np.argmin(loads))
        assign[r].append(u)
        loads[r] += u[2]
    # one handle per file for the whole read (same footer-reparse
    # avoidance as the streaming scan's handle cache)
    handles = {f: pq.ParquetFile(f) for f in files}
    parts, counts = [], []
    for r in range(w):
        if assign[r]:
            ats = [handles[f].read_row_group(g) for f, g, _ in assign[r]]
            parts.append(_concat_arrow(ats))
            counts.append(parts[-1].num_rows)
        else:
            counts.append(0)
    if not parts:
        raise CylonIOError("no data read")
    t = Table.from_arrow(_concat_arrow(parts), env)
    from ..relational import repartition
    return repartition(t, tuple(counts))
