"""IO subsystem (reference cpp/src/cylon/io + pycylon distributed_io)."""

from .io import (read_csv, read_csv_dist, read_json, read_parquet,  # noqa: F401
                 read_parquet_dist, write_csv, write_csv_dist, write_json,
                 write_json_dist, write_parquet, write_parquet_dist)
