"""IO subsystem (reference cpp/src/cylon/io + pycylon distributed_io)."""

from .io import (ParquetScanSource, read_csv, read_csv_dist,  # noqa: F401
                 read_json, read_parquet, read_parquet_dist,
                 scan_parquet_dist, write_csv, write_csv_dist, write_json,
                 write_json_dist, write_parquet, write_parquet_dist)
