"""Table/column collectives: AllGather, Gather, Bcast, AllReduce.

TPU-native equivalent of the reference's communicator collective surface
(net/communicator.hpp:31-69: ``AllGather(Table)``, ``Gather(Table, root)``,
``Bcast(Table)``, ``AllReduce(Column|Scalar, op)``; exposed to Python in
pycylon net/comm_ops.pyx:34-126).  The reference drives these through the
two-phase size-exchange + Iallgatherv/Igatherv/Ibcast pattern over the table
serializer (net/ops/base_ops.hpp:32-175); here each is one ``shard_map``
program over XLA collectives riding ICI:

* ``allgather_table`` — every shard ends with ALL rows, in (source rank,
  source position) order: ``lax.all_gather`` per column + one scatter into
  the compacted layout.  The result is a *replicated* table expressed in
  the row-sharded representation: every shard's valid prefix is the full
  row set (so ``row_count`` is W x the input's — the same multiplication of
  state the reference's per-rank table copies imply).
* ``gather_table`` — all rows on shard ``root`` (order-preserving
  repartition with a concentrated destination vector).
* ``bcast_table`` — replicate shard ``root``'s rows to every shard.
* ``allreduce`` — elementwise reduction of each shard's (capacity-padded)
  row block; returns the replicated result as a host array.

Ops use these where the reference uses its communicator (e.g. distributed
sort splitter selection, skew-join build-side replication) — the collective
stays inside the compiled program, no controller round-trip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .. import config
from ..utils.cache import jit, program_cache
from ..core.column import Column
from ..core.table import Table
from ..ctx.context import ROW_AXIS
from ..status import InvalidError

shard_map = jax.shard_map

ROW = P(ROW_AXIS)
REP = P()


@program_cache()
def _allgather_fn(mesh: Mesh, w: int, cap: int, out_cap: int, ncols: int):
    def per_shard(vc, *cols):
        k = jnp.arange(w * cap, dtype=jnp.int32)
        s = k // cap
        p = k - s * cap
        csum = jnp.cumsum(vc)
        offs = jnp.concatenate([jnp.zeros(1, csum.dtype), csum[:-1]])
        valid = p < vc[s]
        fslot = jnp.where(valid, offs[s].astype(jnp.int32) + p,
                          jnp.int32(out_cap))
        outs = []
        for c in cols:
            g = jax.lax.all_gather(c, ROW_AXIS)            # (W, cap, ...)
            flat = g.reshape((w * cap,) + g.shape[2:])
            out = jnp.zeros((out_cap,) + g.shape[2:], c.dtype)
            outs.append(out.at[fslot].set(flat, mode="drop"))
        return tuple(outs)

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP,) + (ROW,) * ncols,
                             out_specs=(ROW,) * ncols))


@program_cache()
def _bcast_fn(mesh: Mesh, root: int, ncols: int):
    def per_shard(*cols):
        outs = []
        for c in cols:
            g = jax.lax.all_gather(c, ROW_AXIS)            # (W, cap, ...)
            outs.append(g[root])
        return tuple(outs)

    return jit(shard_map(per_shard, mesh=mesh, in_specs=(ROW,) * ncols,
                             out_specs=(ROW,) * ncols))


_REDUCERS = {"sum": jax.lax.psum, "min": jax.lax.pmin, "max": jax.lax.pmax}


def _identity_for(op: str, dtype):
    """Identity element per op — padding rows past a shard's valid prefix
    hold arbitrary (clip-gather) values and must not contaminate the
    reduction."""
    if op == "sum":
        return jnp.zeros((), dtype)
    big = (jnp.iinfo(dtype).max if jnp.issubdtype(dtype, jnp.integer)
           else jnp.asarray(jnp.inf, dtype))
    small = (jnp.iinfo(dtype).min if jnp.issubdtype(dtype, jnp.integer)
             else jnp.asarray(-jnp.inf, dtype))
    return jnp.asarray(big if op == "min" else small, dtype)


@program_cache()
def _allreduce_fn(mesh: Mesh, op: str, ncols: int):
    def per_shard(vc, *cols):
        my = jax.lax.axis_index(ROW_AXIS)
        outs = []
        for c in cols:
            # dtype pins the iota: a default arange is int64 under x64 —
            # a row-scale array at 2x the bytes just to build a mask
            mask = jnp.arange(c.shape[0], dtype=jnp.int32) < vc[my]
            ident = _identity_for(op, c.dtype)
            masked = jnp.where(mask, c, ident)
            outs.append(_REDUCERS[op](masked, ROW_AXIS))
        return tuple(outs)

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP,) + (ROW,) * ncols,
                             out_specs=(REP,) * ncols))


def _flat_cols(table: Table):
    from ..relational.repart import _flatten_for_exchange
    return _flatten_for_exchange(table)


def _rebuild(recipe, new_flat, valid_counts, env) -> Table:
    from ..relational.repart import _rebuild as repart_rebuild
    return repart_rebuild(recipe, new_flat, valid_counts, env)


def allgather_table(table: Table) -> Table:
    """Every shard receives every row (reference AllGather(Table),
    net/communicator.hpp:51).  Result: replicated content in the row-sharded
    layout — each shard's valid prefix is the full global row set in
    (source rank, source position) order."""
    env = table.env
    w = env.world_size
    if w == 1:
        return table
    total = int(table.valid_counts.sum())
    out_cap = config.pow2ceil(max(total, 1))
    flat, recipe = _flat_cols(table)
    fn = _allgather_fn(env.mesh, w, table.capacity, out_cap, len(flat))
    new = fn(np.asarray(table.valid_counts, np.int32), *flat)
    return _rebuild(recipe, new, np.full(w, total, np.int64), env)


def gather_table(table: Table, root: int = 0) -> Table:
    """All rows onto shard ``root``, order preserved (reference
    Gather(Table, root), net/communicator.hpp:45)."""
    from ..relational.repart import repartition
    env = table.env
    w = env.world_size
    if root < 0 or root >= w:
        raise InvalidError(f"root {root} out of range for world {w}")
    dest = [0] * w
    dest[root] = table.row_count
    return repartition(table, tuple(dest))


def bcast_table(table: Table, root: int = 0) -> Table:
    """Replicate shard ``root``'s rows to every shard (reference
    Bcast(Table), net/communicator.hpp:57 — the root's table goes out to
    all ranks).  Typically used after :func:`gather_table`."""
    env = table.env
    w = env.world_size
    if w == 1:
        return table
    if root < 0 or root >= w:
        raise InvalidError(f"root {root} out of range for world {w}")
    flat, recipe = _flat_cols(table)
    fn = _bcast_fn(env.mesh, root, len(flat))
    new = fn(*flat)
    n_root = int(table.valid_counts[root])
    return _rebuild(recipe, new, np.full(w, n_root, np.int64), env)


def allreduce(table_or_column, op: str = "sum", valid_counts=None):
    """Elementwise reduce each shard's row block across shards; returns the
    (replicated) result as a host numpy array (reference
    AllReduce(Column|Scalar, ReduceOp), net/communicator.hpp:63).  Accepts a
    Column or a raw row-sharded device array.

    ``valid_counts`` (per-shard live row counts) masks each shard's padding
    with the op's identity; omit it only for arrays with no padding (every
    slot live on every shard).  Positions live on no shard yield the
    identity element."""
    if op not in _REDUCERS:
        raise InvalidError(f"allreduce op must be one of {set(_REDUCERS)}")
    arr = (table_or_column.data if isinstance(table_or_column, Column)
           else table_or_column)
    mesh = arr.sharding.mesh  # recover the env mesh from the array
    w = mesh.devices.size
    cap = arr.shape[0] // w
    vc = (np.asarray(valid_counts, np.int32) if valid_counts is not None
          else np.full(w, cap, np.int32))
    (res,) = _allreduce_fn(mesh, op, 1)(vc, arr)
    return np.asarray(res)  # out_specs REP: replicated, locally addressable


# ---------------------------------------------------------------------------
# trace-safety declarations (cylon_tpu.analysis.registry): the jaxpr pass
# traces each builder abstractly and verifies its SPMD invariants — the
# declared collective set, collective unconditionality, no row-scale
# i32→i64 widening, zero host callbacks.  docs/trace_safety.md.
# ---------------------------------------------------------------------------

def _trace_allgather(mesh):
    w, cap, S = _decl_shapes(mesh)
    out_cap = 2 * cap
    fn = _unwrap(_allgather_fn(mesh, w, cap, out_cap, 2))
    return jax.make_jaxpr(fn)(S((w,), np.int32), S((w * cap,), np.int64),
                              S((w * cap,), np.float64))


def _trace_bcast(mesh):
    w, cap, S = _decl_shapes(mesh)
    fn = _unwrap(_bcast_fn(mesh, 0, 2))
    return jax.make_jaxpr(fn)(S((w * cap,), np.int64),
                              S((w * cap,), np.float64))


def _trace_allreduce(mesh):
    # one combined trace covers all three reducers so the declared set
    # {psum, pmin, pmax} is verified in a single walk
    w, cap, S = _decl_shapes(mesh)
    fns = [_unwrap(_allreduce_fn(mesh, op, 1)) for op in ("sum", "min", "max")]

    def all_ops(vc, col):
        return tuple(fn(vc, col) for fn in fns)

    return jax.make_jaxpr(all_ops)(S((w,), np.int32),
                                   S((w * cap,), np.float64))


from ..analysis.registry import (declare_builder, decl_shapes as _decl_shapes,  # noqa: E402
                                 unwrap as _unwrap)

declare_builder(f"{__name__}._allgather_fn", _trace_allgather,
                collectives={"all_gather"}, tags=("collectives",))
declare_builder(f"{__name__}._bcast_fn", _trace_bcast,
                collectives={"all_gather"}, tags=("collectives",))
declare_builder(f"{__name__}._allreduce_fn", _trace_allreduce,
                collectives={"psum", "pmin", "pmax"}, tags=("collectives",))
