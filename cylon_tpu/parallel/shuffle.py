"""The shuffle engine: padded ICI all-to-all under ``shard_map``.

TPU-native replacement for the reference's entire async messaging stack —
the generic ``AllToAll`` state machine (net/ops/all_to_all.hpp:78), the
Arrow-aware ``ArrowAllToAll`` buffer streamer (arrow/arrow_all_to_all.hpp:93),
the per-backend channels (net/mpi/mpi_channel.cpp Isend/Irecv 8-int headers,
ucx/gloo equivalents) and the table serializer (serialize/table_serialize.hpp).
~6k LoC of hand-rolled messaging collapse into one XLA collective; the
complexity moves into static-shape capacity planning (SURVEY.md §7 hard-part
1):

  phase A (device): rows → target ranks, per-(src,dst) count matrix
  host:             pick pow2 block capacity c and output capacity
  phase B (device): stable-sort rows by target → scatter into (W·c) send
                    blocks → ``lax.all_to_all`` over the mesh axis →
                    stable compaction of valid rows (order-preserving:
                    received order is (source rank, source position), the
                    same contract as the reference's order-preserving
                    all-to-all, table.cpp:182-190)

The count matrix doubles as the row-count sidecar the reference sends in its
buffer headers.  All collectives ride ICI (mesh axis) — no host round-trip of
table payloads; only the O(W²) count matrix crosses to the host.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .. import config
from ..obs import comm as _comm, metrics as _metrics, plan as _plan
from ..topo import model as _topo
from ..utils.cache import jit, program_cache
from ..ctx.context import ROW_AXIS
from ..ops import hashing

shard_map = jax.shard_map


# ---------------------------------------------------------------------------
# Phase A: target computation + count matrix
# ---------------------------------------------------------------------------

@program_cache()
def _hash_targets_fn(mesh: Mesh, w: int, nkeys: int, with_valids: bool):
    def per_shard(vc, *keys):
        cap = keys[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        mask = jnp.arange(cap) < vc[my]
        datas = list(keys[:nkeys])
        valids = list(keys[nkeys:]) if with_valids else None
        h = hashing.hash_rows(datas, valids)
        tgt = hashing.partition_targets(h, w)
        return jnp.where(mask, tgt, jnp.int32(w))

    nargs = nkeys * 2 if with_valids else nkeys
    specs = (P(),) + tuple(P(ROW_AXIS) for _ in range(nargs))
    return jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                             out_specs=P(ROW_AXIS)))


def hash_targets(mesh: Mesh, key_datas, key_valids, valid_counts: np.ndarray):
    """Global (W·cap,) int32 target-rank array; padding rows get target W
    (the trash destination dropped by the exchange)."""
    w = valid_counts.shape[0]
    with_valids = any(v is not None for v in key_valids)
    args = list(key_datas)
    if with_valids:
        cap_total = key_datas[0].shape[0]
        # numpy sidecars: jit places them per the shard_map specs on the
        # mesh; eager jnp.* would create on the default backend
        args += [v if v is not None else np.ones(cap_total, bool)
                 for v in key_valids]
    vc = np.asarray(valid_counts, np.int32)
    return _hash_targets_fn(mesh, w, len(key_datas), with_valids)(vc, *args)


@program_cache()
def _count_fn(mesh: Mesh, w: int):
    def per_shard(tgt):
        counts = jax.ops.segment_sum(
            jnp.ones(tgt.shape[0], jnp.int32), tgt, num_segments=w + 1)
        return counts[:w].reshape(1, w)

    return jit(shard_map(per_shard, mesh=mesh, in_specs=(P(ROW_AXIS),),
                             out_specs=P(ROW_AXIS)))


def count_targets(mesh: Mesh, tgt) -> np.ndarray:
    """(W, W) host count matrix: C[s, d] = rows rank s sends to rank d.
    The host pull is the exchange's first cross-rank synchronization
    point, so it runs under the exchange watchdog: a peer that never
    produces its counts surfaces as a typed RankDesyncError instead of an
    infinite block (exec/recovery, ``CYLON_TPU_WATCHDOG_S``)."""
    w = mesh.devices.size
    from ..exec.recovery import exchange_watchdog
    from ..utils.host import host_array
    counts_dev = _count_fn(mesh, w)(tgt)
    return exchange_watchdog("exchange.counts",
                             lambda: host_array(counts_dev))


@program_cache()
def _skew_targets_fn(mesh: Mesh, w: int, k_heavy: int, nkeys: int):
    """Targets for a skew-split probe side: heavy-HASH rows spread evenly
    over all ranks (round-robin by global position) instead of hashing —
    the build side's rows with the same hashes are replicated, so any rank
    can join them.  Multi-column and float keys work uniformly (hash_rows
    canonicalizes).  Reference analog: sampled heavy-key handling,
    SURVEY.md §7 hard-part 4."""

    def per_shard(vc, heavy_hashes, *args):
        datas = list(args[:nkeys])
        valids = list(args[nkeys:])
        cap = datas[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        mask = jnp.arange(cap) < vc[my]
        h = hashing.hash_rows(datas, valids)
        tgt = hashing.partition_targets(h, w)
        is_heavy = jnp.zeros(cap, bool)
        for j in range(k_heavy):
            is_heavy = is_heavy | (h == heavy_hashes[j])
        spread = ((my * cap + jnp.arange(cap, dtype=jnp.int32)) % w).astype(
            jnp.int32)
        tgt = jnp.where(is_heavy, spread, tgt)
        return jnp.where(mask, tgt, jnp.int32(w))

    specs = (P(), P()) + (P(ROW_AXIS),) * (2 * nkeys)
    return jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                             out_specs=P(ROW_AXIS)))


def skew_targets(mesh: Mesh, key_datas, key_valids,
                 valid_counts: np.ndarray, heavy_hashes: np.ndarray):
    """Per-row targets with heavy key hashes spread round-robin.
    ``key_valids`` entries must be real arrays (callers pass all-ones for
    non-nullable columns so null folding matches the detection pass)."""
    w = valid_counts.shape[0]
    vc = np.asarray(valid_counts, np.int32)
    fn = _skew_targets_fn(mesh, w, len(heavy_hashes), len(key_datas))
    hv = np.asarray(heavy_hashes, np.uint32)
    return fn(vc, hv, *key_datas, *key_valids)


@program_cache()
def _skew_split_targets_fn(mesh: Mesh, w: int, k: int, nkeys: int,
                           need_nf: tuple, narrow: tuple):
    """Targets for the adaptive skew-split probe side (the plan facade,
    relational/skew.py — lint rule TS115): light rows hash as usual;
    rows equal (in sort-OPERAND space) to one of the K heavy tuples are
    salted by their WITHIN-KEY arrival index STRIDED over the key's
    contiguous rank group — global row j of the key goes to member
    ``j mod fanout``.  The strided (round-robin) salt keeps every
    member's rows an order-preserving SUBSEQUENCE of the key's global
    (source rank, source position) order — the property the stitch's
    bit/order-equality contract stands on — while spreading EVERY
    source's heavy rows evenly over the whole group, so the exchange's
    per-(src,dst) cells stay uniform-sized and single-round (a
    contiguous-chunk salt would map each source's heavy block onto one
    or two members and quadruple the padded exchange's rounds;
    docs/skew.md).  Pure-local: the plan sidecars are replicated host
    arrays; no collective."""
    from ..ops import pack

    def per_shard(vc, srcoff, fan, start, *args):
        datas = list(args[:nkeys])
        valids = list(args[nkeys:2 * nkeys])
        tup = args[2 * nkeys:]
        cap = datas[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        mask = jnp.arange(cap) < vc[my]
        h = hashing.hash_rows(datas, valids)
        base = hashing.partition_targets(h, w)
        ko_t = pack.key_operands(list(tup[:nkeys]), list(tup[nkeys:]),
                                 need_null_flags=need_nf, narrow32=narrow)
        ko_r = pack.key_operands(datas, valids, need_null_flags=need_nf,
                                 narrow32=narrow)
        _gt, eq = pack.rows_cmp_splitters(ko_r, ko_t.ops)
        eq = eq & mask[:, None]
        heavy = jnp.any(eq, axis=1)
        kidx = jnp.argmax(eq, axis=1).astype(jnp.int32)
        # born-wide int64 (JX203): within-key indices are GLOBAL row
        # counts — a single heavy key can exceed int32 at target scale
        eqi = eq.astype(jnp.int64)
        loc = jnp.cumsum(eqi, axis=0) - eqi          # within-shard index
        loc_k = jnp.take_along_axis(loc, kidx[:, None], axis=1)[:, 0]
        j = srcoff[my, kidx] + loc_k
        # fan arrives born-wide int64 (K,) so the row-scale modulus never
        # widens an int32 lane (JX203)
        ordn = (j % fan[kidx]).astype(jnp.int32)
        tgt_h = (start[kidx] + ordn) % w
        tgt = jnp.where(heavy, tgt_h, base)
        return jnp.where(mask, tgt, jnp.int32(w))

    specs = (P(), P(), P(), P()) + (P(ROW_AXIS),) * (2 * nkeys) \
        + (P(),) * (2 * nkeys)
    return jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                             out_specs=P(ROW_AXIS)))


def skew_split_targets(mesh: Mesh, key_datas, key_valids,
                       valid_counts: np.ndarray, k: int, need_nf: tuple,
                       narrow: tuple, tuple_args: tuple,
                       src_off: np.ndarray, fanout: np.ndarray,
                       start: np.ndarray):
    """Per-row targets for a skew-split probe exchange — called ONLY by
    the plan facade (relational/skew.py, lint rule TS115), which owns
    every sidecar's derivation.  ``key_valids`` entries must be real
    arrays (all-ones for non-nullable columns)."""
    w = valid_counts.shape[0]
    vc = np.asarray(valid_counts, np.int32)
    fn = _skew_split_targets_fn(mesh, w, int(k), len(key_datas), need_nf,
                                narrow)
    return fn(vc, np.asarray(src_off, np.int64),
              np.asarray(fanout, np.int64),
              np.asarray(start, np.int32), *key_datas, *key_valids,
              *tuple_args)


# ---------------------------------------------------------------------------
# Phase B: padded exchange, multi-round + order-preserving placement
#
# Send-buffer memory is W·block per column.  Under key skew (an all-to-one
# distribution) counts.max() approaches the whole shard, which would inflate
# device memory by ~W× per column (round-1 VERDICT red flag).  The exchange
# therefore runs in R = ceil(max_count / block) rounds with ``block`` capped
# near the uniform-case size: round r moves the rows whose within-(src,dst)
# position is in [r·block, (r+1)·block), and the receiver scatters each
# round's rows STRAIGHT into their final (source-rank, source-position)
# slots — no end-of-exchange compaction or re-sort, and peak extra memory
# stays at W·block ≈ one shard's worth regardless of skew.
# ---------------------------------------------------------------------------

@program_cache()
def _prep_fn(mesh: Mesh, w: int):
    """Per shard: stable order rows by destination once; reused each round.
    Returns (tgt_s, perm, pos): sorted targets, source permutation, and the
    row's position within its (me -> dest) stream."""

    def per_shard(tgt, counts):
        cap = tgt.shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        idx = jnp.arange(cap, dtype=jnp.int32)
        tgt_s, perm = jax.lax.sort((tgt, idx), num_keys=1, is_stable=True)
        my_counts = counts[my]
        csum = jnp.cumsum(my_counts)
        offs = jnp.concatenate([jnp.zeros(1, csum.dtype), csum[:-1]])
        tgt_safe = jnp.clip(tgt_s, 0, w - 1)
        pos = idx - offs[tgt_safe].astype(jnp.int32)
        return tgt_s, perm, pos

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(P(ROW_AXIS), P()),
                             out_specs=(P(ROW_AXIS),) * 3))


@program_cache()
def _round_fn(mesh: Mesh, w: int, block: int, out_cap: int,
              rounds: int = 1):
    """The exchange round engine: select a round's position window,
    all-to-all, scatter received rows into their final output slots.

    ``rounds > 1`` (skewed counts: some (src,dst) stream exceeds the
    block) runs ALL rounds inside one compiled program via
    ``lax.fori_loop`` — one dispatch total instead of one per round (the
    round-3 verdict's multi-round host loop; the collective sits inside
    the loop body, which XLA supports under shard_map)."""

    def one_round(r, tgt_s, perm, pos, counts, outs, cols, my):
        lo = r * block
        sel = (tgt_s < w) & (pos >= lo) & (pos < lo + block)
        slot = jnp.where(sel, jnp.clip(tgt_s, 0, w - 1) * block + (pos - lo),
                         jnp.int32(w * block))
        # receiver: slot k = src*block + q holds src's row (lo + q); final
        # position = (rows from earlier sources) + lo + q
        recv_counts = counts[:, my]
        rcsum = jnp.cumsum(recv_counts)
        roffs = jnp.concatenate([jnp.zeros(1, rcsum.dtype), rcsum[:-1]])
        k = jnp.arange(w * block, dtype=jnp.int32)
        src = k // block
        q = k - src * block
        valid = (lo + q) < recv_counts[src]
        fslot = jnp.where(valid, roffs[src].astype(jnp.int32) + lo + q,
                          jnp.int32(out_cap))
        new_outs = []
        for out, col in zip(outs, cols):
            send = jnp.zeros((w * block,) + col.shape[1:], col.dtype)
            send = send.at[slot].set(col[perm], mode="drop")
            recv = jax.lax.all_to_all(send, ROW_AXIS, split_axis=0,
                                      concat_axis=0, tiled=True)
            new_outs.append(out.at[fslot].set(recv, mode="drop"))
        return tuple(new_outs)

    def per_shard(tgt_s, perm, pos, counts, outs, cols):
        my = jax.lax.axis_index(ROW_AXIS)
        if rounds == 1:
            return one_round(jnp.int32(0), tgt_s, perm, pos, counts, outs,
                             cols, my)
        return jax.lax.fori_loop(
            0, rounds,
            lambda r, o: one_round(jnp.int32(r), tgt_s, perm, pos, counts,
                                   o, cols, my),
            tuple(outs))

    def fn(tgt_s, perm, pos, counts, outs, cols):
        n = len(cols)
        specs_in = (P(ROW_AXIS),) * 3 + (P(),) \
            + ((P(ROW_AXIS),) * n,) + ((P(ROW_AXIS),) * n,)
        sm = shard_map(per_shard, mesh=mesh, in_specs=specs_in,
                       out_specs=(P(ROW_AXIS),) * n)
        return sm(tgt_s, perm, pos, counts, outs, cols)

    return jit(fn, donate_argnums=(4,))


@program_cache()
def _alloc_fn(mesh: Mesh, out_cap: int, dtype: str, extra_shape: tuple):
    def per_shard():
        return jnp.zeros((out_cap,) + extra_shape, jnp.dtype(dtype))

    return jit(shard_map(per_shard, mesh=mesh, in_specs=(),
                             out_specs=P(ROW_AXIS)))


def exchange_block_cap(total: int, w: int) -> int:
    """Per-(src,dst) block bound: ~2× the uniform-case stream size, floored
    so tiny tables stay single-round."""
    uniform = -(-int(total) // max(w * w, 1))
    return config.pow2ceil(max(2 * uniform, 8192))


def exchange(mesh: Mesh, tgt, counts: np.ndarray, cols: tuple,
             guard: bool = False, owner: str = "shuffle.recv"):
    """Run the (possibly multi-round) padded all-to-all for every array in
    ``cols`` (payload-agnostic: callers pre-pack laneable columns into one
    (cap, L) u32 lane matrix — relational/repart._flatten_for_exchange —
    so the per-round scatter/all_to_all/scatter chain runs once per ARRAY,
    and a whole table is typically one matrix + f64 side arrays).

    ``owner`` names the ledger registration of the guarded receive
    buffers — streaming ingest appends pass ``stream.recv`` so the
    serving tier's budget decisions can tell long-lived ingest state from
    transient query shuffles (cylon_tpu/stream, docs/streaming.md).

    Returns (new_cols tuple, new_valid_counts np (W,)).  Capacities are
    bucketed (config.pow2ceil) so the family of compiled programs stays
    small; rounds bound peak send-buffer memory under skew (note: the
    caller's packed matrix is a full-shard copy that lives for the whole
    exchange alongside the source table — the W·block bound applies to the
    per-round send/recv buffers).
    """
    w = counts.shape[0]
    max_c = int(counts.max()) if counts.size else 1
    total = int(counts.sum()) if counts.size else 1
    block = config.pow2ceil(min(max(max_c, 1), exchange_block_cap(total, w)))
    rounds = -(-max_c // block) if max_c else 1
    per_dest = counts.sum(axis=0)
    out_cap = config.pow2ceil(int(per_dest.max()) if per_dest.size else 1)

    # topology route (cylon_tpu/topo, docs/topology.md): on a
    # multi-slice fabric phase B goes hierarchical — a slice-local ICI
    # alignment hop, then ONE aggregated cross-slice DCN hop — bit- and
    # order-equal to the flat plan by the slice-major layout.  The
    # route choice is deterministic from the cached topology plan
    # (rank-uniform by construction), and on a single-slice topology
    # ``hier_plan`` is one cached lookup returning None: the flat path
    # below is byte-identical to the pre-topology engine — zero extra
    # collectives, zero host syncs (the chaos --multislice unarmed-leg
    # contract).
    hplan = _topo.hier_plan(mesh)
    hprep = None
    if hplan is not None:
        # derive the two-hop schedule (hop count matrices, blocks,
        # gateway capacity) ONCE per exchange — the guard sizing, tier
        # accounting and dispatch below all read this object
        from ..topo import exchange as _topo_exchange
        hprep = _topo_exchange.prepare(hplan, counts)

    # Receive-side memory guard (accelerators only; ``guard=True`` from
    # hash-shuffle callers): the multi-round protocol bounds SEND
    # buffers, but the receiving shard still materializes every row
    # routed to it (out_cap is per-DEST).  A catastrophic route (skew
    # the heavy-key split didn't model, e.g. hash clustering) is known
    # from the COUNT SIDECAR before any allocation — raising an
    # OOM-shaped error here FAILS FAST AND CLEAN instead of submitting a
    # doomed multi-GB alloc, which this rig never recovers from (a real
    # device OOM poisons the process, docs/DESIGN.md).  Receive
    # concentration is not curable downstream — the streaming pipeline
    # shuffles the same full tables — so the REMEDY is the heavy-key
    # split (on by default); this guard is the backstop for routes the
    # split didn't model.  CPU meshes skip it (host RAM is typically far
    # above any HBM-sized budget); sort/repartition exchanges are
    # unguarded likewise.
    on_accel = mesh.devices.flat[0].platform != "cpu" \
        or config.EXCHANGE_RECV_GUARD_CPU
    row_bytes = sum(int(np.dtype(c.dtype).itemsize)
                    * int(np.prod(c.shape[1:], dtype=np.int64))
                    for c in cols)
    if guard:
        # The raise/proceed decision is itself rank-coherent: every rank
        # evaluates its local predicate (deterministic from the replicated
        # count sidecar, OR a rank-selective injected fault) and any
        # consensus runs BEFORE phase B's first collective is dispatched —
        # "no rank-local control flow after a collective has been
        # entered" (docs/robustness.md).  A rank whose guard did not fire
        # still raises when any peer's did, so no rank ever enters the
        # exchange alone.  The consensus poll itself runs ONLY when the
        # predicate can differ from OK somewhere — over_budget is
        # rank-uniform (replicated counts) and `armed` is rank-uniform by
        # construction (recovery.probe) — so the un-injected happy path
        # adds no collective and no host sync to the exchange.
        from ..exec import recovery, scheduler
        if hplan is not None:
            # two-hop peak receive: the hop-1 gateway buffers (payload
            # + the int32 final-target sidecar lane) are still alive —
            # as hop 2's inputs — while the final buffers fill, so the
            # guard sizes against the SUM of the tiers (deterministic
            # host math on the replicated sidecar)
            need = _topo_exchange.recv_guard_bytes(hplan, hprep, out_cap,
                                                   row_bytes)
        else:
            need = out_cap * row_bytes
        # HBM-ledger consult (exec/memory): the predicted receive is an
        # allocation ON TOP of the resident balance the ledger tracks —
        # and unlike the static receive budget, ledger pressure is
        # CURABLE: cold spillable owners (packed piece sources — sink
        # partials and receive buffers are accounting-only) evict to
        # host BEFORE the allocation.  Routed through the serving tier's
        # facade (scheduler.free_pressure, lint rule TS109); still
        # single-controller only (the underlying try_free no-ops in
        # multiprocess sessions, where eviction is taken exclusively on
        # the consensus'd admission path), and the raise/consensus
        # predicate below stays EXACTLY the replicated count-sidecar
        # one: a ledger balance read is rank-uniform only up to GC
        # release timing, so gating the consensus poll on it would risk
        # the very desync this guard exists to prevent.
        scheduler.free_pressure(need)
        over_budget = bool(
            on_accel
            and need > config.EXCHANGE_RECV_BUDGET_BYTES)
        kind, armed = recovery.probe("shuffle.recv_guard")
        local_fault = over_budget or kind is not None
        if ((over_budget or armed)
                and recovery.guard_consensus(mesh, local_fault)):
            from ..status import PredictedResourceExhausted
            if kind is not None and kind != "predicted":
                # rank-selective simulation of a non-guard fault at this
                # site (e.g. device_oom): raise the REQUESTED kind; peer
                # ranks raise the predicted shape below and the ladder's
                # code consensus re-aligns the branches
                raise recovery.make_fault(kind, "shuffle.recv_guard")
            hop1 = ("" if hplan is None else
                    f" (two-hop route: {out_cap} final rows + "
                    f"{hprep.cap1} gateway rows incl. the target "
                    "sidecar — both tiers live at once)")
            raise PredictedResourceExhausted(
                f"RESOURCE_EXHAUSTED (predicted): exchange receive "
                f"allocation {need} B at {row_bytes} B/row{hop1} exceeds "
                f"CYLON_TPU_EXCHANGE_RECV_BUDGET "
                f"({config.EXCHANGE_RECV_BUDGET_BYTES} B); one destination "
                "shard would materialize the bulk of the table",
                site="shuffle.recv_guard")

    # always-on exchange totals (host arithmetic on the already-pulled
    # count sidecar — no device work, no sync): the registry counters
    # the armed comm matrix's row/column sums must reconcile against
    # (obs/comm, docs/observability.md).  The counters record the
    # LOGICAL exchange — each row delivered once — whichever route
    # carried it, so flat and hierarchical runs of the same workload
    # stay comparable; the tier counters below say which interconnect
    # the journey used.
    _metrics.counter("exchange_rows_total").inc(total)
    _metrics.counter("exchange_bytes_total").inc(total * row_bytes)
    _metrics.counter("exchange_count").inc()
    route = "two_hop" if hplan is not None else "flat"
    topo_t = _topo.topology(mesh)
    tiers = None
    if topo_t.n_slices > 1:
        # always-on per-tier counters on MULTI-SLICE topologies only
        # (host numpy on the replicated sidecar; single-slice rigs skip
        # on one cached field load): payload rows/bytes split by which
        # tier the row's journey crosses, plus the PADDED wire volume
        # and (src, dst, round) message count each tier's links carry —
        # the DCN message count is the two-hop route's exactly-1/R
        # acceptance instrument (docs/topology.md, bench --slices).
        from ..topo import exchange as _topo_exchange
        ici_rows, dcn_rows = _topo.tier_split(counts, topo_t)
        traffic = _topo_exchange.tier_traffic(
            topo_t, counts, row_bytes, route, prep=hprep,
            flat_block_rounds=(block, rounds) if hplan is None else None)
        _metrics.counter("exchange_ici_rows_total").inc(ici_rows)
        _metrics.counter("exchange_dcn_rows_total").inc(dcn_rows)
        _metrics.counter("exchange_ici_bytes_total").inc(
            ici_rows * row_bytes)
        _metrics.counter("exchange_dcn_bytes_total").inc(
            dcn_rows * row_bytes)
        _metrics.counter("exchange_ici_wire_bytes_total").inc(
            traffic["wire_ici"])
        _metrics.counter("exchange_dcn_wire_bytes_total").inc(
            traffic["wire_dcn"])
        _metrics.counter("exchange_ici_messages_total").inc(
            traffic["msgs_ici"])
        _metrics.counter("exchange_dcn_messages_total").inc(
            traffic["msgs_dcn"])
        tiers = {"slice_ids": topo_t.slice_ids(), "route": route,
                 **traffic}
    if _comm.armed() or _plan.active():
        # per-(src,dst) matrix + plan-node attribution (armed runs /
        # active EXPLAIN ANALYZE only — the happy path skips on two
        # cached loads)
        _plan.record_exchange(counts, row_bytes, site=owner, tiers=tiers)
    if hplan is not None:
        # the voted hierarchical route (cylon_tpu/topo/exchange): the
        # plan hash is consensus-adopted BEFORE the first hierarchical
        # collective (one set lookup after the first exchange), then
        # phase B runs as slice-local ICI alignment + one aggregated
        # cross-slice DCN hop — bit- and order-equal to the flat branch
        # below (docs/topology.md)
        _topo.ensure_adopted(mesh, hplan)
        outs, _pd = _topo_exchange.two_hop(mesh, hplan, tgt, counts,
                                           tuple(cols), out_cap,
                                           prep=hprep)
    else:
        if rounds > 1:
            # countable path marker (tests/test_fuzz.py regime tier):
            # the multi-round protocol actually engaged
            from ..utils import timing
            timing.bump("exchange.multiround")
        counts_i = np.asarray(counts, np.int32)
        tgt_s, perm, pos = _prep_fn(mesh, w)(tgt, counts_i)
        outs = tuple(_alloc_fn(mesh, out_cap, str(c.dtype), c.shape[1:])()
                     for c in cols)
        # all rounds run in ONE compiled program (fori_loop if rounds>1)
        fn = _round_fn(mesh, w, block, out_cap, max(rounds, 1))
        outs = fn(tgt_s, perm, pos, counts_i, outs, tuple(cols))
    # integrity audit tier (exec/integrity, docs/robustness.md): the
    # corruption drill first (so the audit below is what catches it),
    # then the always-on conservation laws — pure host math on the
    # already-pulled sidecar, zero device work — then, ARMED only
    # (CYLON_TPU_AUDIT=1), fingerprint conservation across the route:
    # the XOR content fingerprint of the valid input rows must equal
    # the delivered outputs', whichever route carried them
    from ..exec import integrity as _integrity, recovery as _recovery
    if _recovery.maybe_inject("exchange.corrupt",
                              intercept=("corrupt",)) == "corrupt":
        _recovery._record("exchange.corrupt", "corrupt", "flipped")
        outs = _integrity.flip_one(mesh, outs, per_dest)
    _integrity.conserve_exchange(counts, per_dest, total, row_bytes,
                                 site=owner)
    if _integrity.armed():
        _integrity.verify_exchange(mesh, tgt, cols, outs, per_dest,
                                   site=owner)
    if guard:
        # HBM-ledger accounting of the receive allocation (exec/memory):
        # one registration PER buffer, each anchored to its own array, so
        # the balance tracks exactly the buffers still alive (the lane
        # matrix usually dies at rebuild; f64 side arrays live on as the
        # table's columns).  Non-spillable — an exchange output has no
        # cheap re-entry path.
        from ..exec import memory
        for arr in outs:
            memory.register(owner, (arr,), anchor=arr)
    return outs, per_dest.astype(np.int64)


# ---------------------------------------------------------------------------
# trace-safety declarations (cylon_tpu.analysis.registry) — the jaxpr pass
# verifies the exchange engine's SPMD invariants.  The high-value check is
# _round_fn: its all_to_all must stay UNCONDITIONAL — the multi-round path
# runs it under a static-trip-count fori_loop (lowered to scan, identical
# on every rank: allowed), never under cond/while (rank-divergent
# participation deadlocks the mesh).  docs/trace_safety.md.
# ---------------------------------------------------------------------------

def _trace_round(mesh):
    w, cap, S = _decl_shapes(mesh)
    block, out_cap, rounds = cap // 4, 2 * cap, 3
    fn = _unwrap(_round_fn(mesh, w, block, out_cap, rounds))
    one = _unwrap(_round_fn(mesh, w, cap, out_cap, 1))
    i32 = np.int32

    def both(tgt_s, perm, pos, counts, outs, cols):
        # single-round and scan-wrapped multi-round paths in one walk
        a = one(tgt_s, perm, pos, counts, outs, cols)
        b = fn(tgt_s, perm, pos, counts, outs, cols)
        return a, b

    args = (S((w * cap,), i32), S((w * cap,), i32), S((w * cap,), i32),
            S((w, w), i32), (S((w * out_cap,), np.int64),),
            (S((w * cap,), np.int64),))
    return jax.make_jaxpr(both)(*args)


def _trace_hash_targets(mesh):
    w, cap, S = _decl_shapes(mesh)
    fn = _unwrap(_hash_targets_fn(mesh, w, 1, True))
    return jax.make_jaxpr(fn)(S((w,), np.int32), S((w * cap,), np.int64),
                              S((w * cap,), np.bool_))


def _trace_count(mesh):
    w, cap, S = _decl_shapes(mesh)
    fn = _unwrap(_count_fn(mesh, w))
    return jax.make_jaxpr(fn)(S((w * cap,), np.int32))


def _trace_skew_targets(mesh):
    w, cap, S = _decl_shapes(mesh)
    fn = _unwrap(_skew_targets_fn(mesh, w, 2, 1))
    return jax.make_jaxpr(fn)(S((w,), np.int32), S((2,), np.uint32),
                              S((w * cap,), np.int64),
                              S((w * cap,), np.bool_))


def _trace_skew_split_targets(mesh):
    w, cap, S = _decl_shapes(mesh)
    fn = _unwrap(_skew_split_targets_fn(mesh, w, 2, 1, (True,), (False,)))
    return jax.make_jaxpr(fn)(S((w,), np.int32), S((w, 2), np.int64),
                              S((2,), np.int64), S((2,), np.int32),
                              S((w * cap,), np.int64),
                              S((w * cap,), np.bool_),
                              S((2,), np.int64), S((2,), np.bool_))


def _trace_prep(mesh):
    w, cap, S = _decl_shapes(mesh)
    fn = _unwrap(_prep_fn(mesh, w))
    return jax.make_jaxpr(fn)(S((w * cap,), np.int32), S((w, w), np.int32))


from ..analysis.registry import (declare_builder, decl_shapes as _decl_shapes,  # noqa: E402
                                 unwrap as _unwrap)

declare_builder(f"{__name__}._round_fn", _trace_round,
                collectives={"all_to_all"}, tags=("shuffle",))
declare_builder(f"{__name__}._hash_targets_fn", _trace_hash_targets,
                tags=("shuffle",))
declare_builder(f"{__name__}._count_fn", _trace_count, tags=("shuffle",))
declare_builder(f"{__name__}._skew_targets_fn", _trace_skew_targets,
                tags=("shuffle", "skew"))
declare_builder(f"{__name__}._skew_split_targets_fn",
                _trace_skew_split_targets, tags=("shuffle", "skew"))
declare_builder(f"{__name__}._prep_fn", _trace_prep, tags=("shuffle",))
