"""The shuffle engine: padded ICI all-to-all under ``shard_map``.

TPU-native replacement for the reference's entire async messaging stack —
the generic ``AllToAll`` state machine (net/ops/all_to_all.hpp:78), the
Arrow-aware ``ArrowAllToAll`` buffer streamer (arrow/arrow_all_to_all.hpp:93),
the per-backend channels (net/mpi/mpi_channel.cpp Isend/Irecv 8-int headers,
ucx/gloo equivalents) and the table serializer (serialize/table_serialize.hpp).
~6k LoC of hand-rolled messaging collapse into one XLA collective; the
complexity moves into static-shape capacity planning (SURVEY.md §7 hard-part
1):

  phase A (device): rows → target ranks, per-(src,dst) count matrix
  host:             pick pow2 block capacity c and output capacity
  phase B (device): stable-sort rows by target → scatter into (W·c) send
                    blocks → ``lax.all_to_all`` over the mesh axis →
                    stable compaction of valid rows (order-preserving:
                    received order is (source rank, source position), the
                    same contract as the reference's order-preserving
                    all-to-all, table.cpp:182-190)

The count matrix doubles as the row-count sidecar the reference sends in its
buffer headers.  All collectives ride ICI (mesh axis) — no host round-trip of
table payloads; only the O(W²) count matrix crosses to the host.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .. import config
from ..ctx.context import ROW_AXIS
from ..ops import hashing

shard_map = jax.shard_map


# ---------------------------------------------------------------------------
# Phase A: target computation + count matrix
# ---------------------------------------------------------------------------

@lru_cache(maxsize=None)
def _hash_targets_fn(mesh: Mesh, w: int, nkeys: int, with_valids: bool):
    def per_shard(vc, *keys):
        cap = keys[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        mask = jnp.arange(cap) < vc[my]
        datas = list(keys[:nkeys])
        valids = list(keys[nkeys:]) if with_valids else None
        h = hashing.hash_rows(datas, valids)
        tgt = hashing.partition_targets(h, w)
        return jnp.where(mask, tgt, jnp.int32(w))

    nargs = nkeys * 2 if with_valids else nkeys
    specs = (P(),) + tuple(P(ROW_AXIS) for _ in range(nargs))
    return jax.jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                             out_specs=P(ROW_AXIS)))


def hash_targets(mesh: Mesh, key_datas, key_valids, valid_counts: np.ndarray):
    """Global (W·cap,) int32 target-rank array; padding rows get target W
    (the trash destination dropped by the exchange)."""
    w = valid_counts.shape[0]
    with_valids = any(v is not None for v in key_valids)
    args = list(key_datas)
    if with_valids:
        cap_total = key_datas[0].shape[0]
        # numpy sidecars: jit places them per the shard_map specs on the
        # mesh; eager jnp.* would create on the default backend
        args += [v if v is not None else np.ones(cap_total, bool)
                 for v in key_valids]
    vc = np.asarray(valid_counts, np.int32)
    return _hash_targets_fn(mesh, w, len(key_datas), with_valids)(vc, *args)


@lru_cache(maxsize=None)
def _count_fn(mesh: Mesh, w: int):
    def per_shard(tgt):
        counts = jax.ops.segment_sum(
            jnp.ones(tgt.shape[0], jnp.int32), tgt, num_segments=w + 1)
        return counts[:w].reshape(1, w)

    return jax.jit(shard_map(per_shard, mesh=mesh, in_specs=(P(ROW_AXIS),),
                             out_specs=P(ROW_AXIS)))


def count_targets(mesh: Mesh, tgt) -> np.ndarray:
    """(W, W) host count matrix: C[s, d] = rows rank s sends to rank d."""
    w = mesh.devices.size
    return np.asarray(_count_fn(mesh, w)(tgt))


# ---------------------------------------------------------------------------
# Phase B: padded exchange + order-preserving compaction
# ---------------------------------------------------------------------------

@lru_cache(maxsize=None)
def _exchange_fn(mesh: Mesh, w: int, block: int, out_cap: int):
    def per_shard(tgt, counts, *cols):
        cap = tgt.shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        idx = jnp.arange(cap, dtype=jnp.int32)
        # stable group rows by destination (preserves source order per dest)
        tgt_s, perm = jax.lax.sort((tgt, idx), num_keys=1, is_stable=True)
        my_counts = counts[my]  # (w,)
        csum = jnp.cumsum(my_counts)
        offs = jnp.concatenate([jnp.zeros(1, csum.dtype), csum[:-1]])
        # position within destination block
        tgt_safe = jnp.clip(tgt_s, 0, w - 1)
        pos = idx - offs[tgt_safe].astype(jnp.int32)
        slot = tgt_safe * block + pos
        slot = jnp.where(tgt_s >= w, jnp.int32(w * block), slot)  # drop padding
        recv_block_valid = counts[:, my]  # rows each source sends me
        outs = []
        for col in cols:
            send = jnp.zeros((w * block,) + col.shape[1:], col.dtype)
            send = send.at[slot].set(col[perm], mode="drop")
            recv = jax.lax.all_to_all(send, ROW_AXIS, split_axis=0,
                                      concat_axis=0, tiled=True)
            outs.append(recv)
        # compact: slot k (= src*block + pos) valid iff pos < C[src, my].
        # Sort-free: output position = exclusive prefix sum of validity; one
        # scatter builds the take map.  Slots past the shard's valid count
        # keep the init value 0 (any in-bounds slot) — the valid_counts
        # sidecar masks those rows everywhere downstream.
        k = jnp.arange(w * block, dtype=jnp.int32)
        src = k // block
        kpos = k - src * block
        valid = kpos < recv_block_valid[src]
        vi = valid.astype(jnp.int32)
        cpos = (jnp.cumsum(vi) - vi).astype(jnp.int32)
        scat = jnp.where(valid, cpos, jnp.int32(out_cap))
        take = jnp.zeros(out_cap, jnp.int32).at[scat].set(k, mode="drop")
        final = [recv[take] for recv in outs]
        return tuple(final)

    def fn(tgt, counts, cols):
        ncols = len(cols)
        specs_in = (P(ROW_AXIS), P()) + tuple(P(ROW_AXIS) for _ in range(ncols))
        specs_out = tuple(P(ROW_AXIS) for _ in range(ncols))
        sm = shard_map(lambda t, c, *cs: per_shard(t, c, *cs), mesh=mesh,
                       in_specs=specs_in, out_specs=specs_out)
        return sm(tgt, counts, *cols)

    return jax.jit(fn, static_argnames=())


def exchange(mesh: Mesh, tgt, counts: np.ndarray, cols: tuple):
    """Run the padded all-to-all for every column array in ``cols``.

    Returns (new_cols tuple, new_valid_counts np (W,)).  Capacities are
    pow2-bucketed so the family of compiled programs stays small.
    """
    w = counts.shape[0]
    block = config.pow2ceil(int(counts.max()) if counts.size else 1)
    per_dest = counts.sum(axis=0)
    out_cap = config.pow2ceil(int(per_dest.max()) if per_dest.size else 1)
    fn = _exchange_fn(mesh, w, block, out_cap)
    new_cols = fn(tgt, np.asarray(counts, np.int32), tuple(cols))
    return new_cols, per_dest.astype(np.int64)
