"""cylon_tpu: a TPU-native distributed DataFrame framework.

A ground-up JAX/XLA re-design of the capabilities of Cylon (reference:
mstaylor/cylon, surveyed in SURVEY.md): Arrow/pandas-interoperable columnar
tables resident in device HBM, relational operators (join, groupby-aggregate,
distributed sample sort, set ops, unique, repartition/slice) as jit-compiled
vector kernels, and the MPI/UCX/Gloo shuffle layer replaced by SPMD mesh
collectives over ICI/DCN.

User contract preserved from the reference (frame.py:2063 dispatch rule):

    from cylon_tpu import DataFrame, CylonEnv, TPUConfig
    env = CylonEnv(config=TPUConfig())
    df = df1.merge(df2, on="key", env=env)   # distributed
    df = df1.merge(df2, on="key")            # local
"""

from . import config  # noqa: F401  (applies x64 policy at import)
from . import obs  # noqa: F401  (observability: metrics/trace/rank report)
obs.trace.autoarm()     # CYLON_TPU_TRACE=path arms the flight recorder
obs.metrics.autoarm()   # CYLON_TPU_METRICS_JSON=path: end-of-run snapshot
from .ctx.context import (CPUMeshConfig, CylonEnv, LocalConfig,  # noqa: F401
                          TPUConfig)
from .core.column import Column  # noqa: F401
from .core.dtypes import LogicalType  # noqa: F401
from .core.table import Table  # noqa: F401
from .frame import DataFrame, GroupByDataFrame, concat, read_pandas  # noqa: F401
from .series import Series  # noqa: F401
from .status import Code, CylonError, Status  # noqa: F401

__version__ = "0.1.0"
