"""StreamTable: the append-only distributed ingest table.

One micro-batch append is: interleave (the serving tier's streaming
yield point) → injector probe (``stream.append``) → host batch → device
Table → hash shuffle on the stream key (the SAME exchange engine every
relational operator uses; receive buffers ledger-labelled
``stream.recv``) → scheduler-mediated admission (TS109) → chunk
accumulation + subscriber notification.  The accumulated chunks are
ordinary Tables — ``snapshot()`` is their concatenation, and the
dispatch-on-demand property the pipelined ops rely on
(:func:`~cylon_tpu.exec.pipeline.chunk_table`) holds per append: no
chunk is sliced or copied until a consumer reads it.
"""

from __future__ import annotations

import numpy as np

from ..core.table import Table
from ..relational.repart import concat_tables, shuffle_table
from ..status import InvalidError


def _as_table(batch, env) -> Table:
    """Accept a pandas DataFrame, a dict of numpy arrays, a
    cylon DataFrame or a Table as one micro-batch."""
    if isinstance(batch, Table):
        return batch
    inner = getattr(batch, "_table", None)
    if isinstance(inner, Table):
        return inner
    if isinstance(batch, dict):
        return Table.from_pydict(batch, env)
    return Table.from_pandas(batch, env)


def _table_nbytes(table: Table) -> int:
    total = 0
    for c in table.columns.values():
        total += int(c.data.nbytes)
        if c.validity is not None:
            total += int(c.validity.nbytes)
    return total


class StreamTable:
    """Append-only distributed table fed by micro-batches.

    Usage::

        st = StreamTable(env, key="k", name="orders")
        view = IncrementalView(st, "k", [("v", "sum"), ("v", "mean")])
        st.append(batch_df)          # shuffled, admitted, absorbed
        view.read()                  # consistent snapshot, ingest live

    ``key``: the hash-shuffle column(s) — equal keys land on the same
    shard on arrival, so every downstream groupby/join starts
    co-located.  Appends register their bytes with the HBM ledger under
    ``<name>.chunk`` owners (anchored to the chunk tables, so GC drains
    the balance) and run admission through the scheduler facade; under
    budget pressure cold tenants (or cold stream windows) evict first.
    """

    def __init__(self, env, key, name: str = "stream"):
        self.env = env
        self.key = [key] if isinstance(key, str) else list(key)
        self.name = str(name)
        self.chunks: list[Table] = []
        self._regs: list = []
        self._subscribers: list = []
        self.rows_appended = 0
        self.bytes_appended = 0
        self.batches_appended = 0

    def subscribe(self, consumer) -> None:
        """``consumer(batch_table)`` is called with every appended
        (post-shuffle) batch — how an :class:`~cylon_tpu.stream.view.
        IncrementalView` rides the ingest path."""
        self._subscribers.append(consumer)

    def append(self, batch) -> Table:
        """Ingest one micro-batch; returns the shuffled device-resident
        batch Table (the unit subscribers absorbed)."""
        from ..exec import memory, recovery, scheduler
        from ..obs import plan as _plan
        from ..utils import timing
        # the streaming session's interleave point: one append per baton
        # slice, so continuous ingest coexists with the query tenant mix
        scheduler.maybe_yield()
        recovery.maybe_inject("stream.append")
        with _plan.node("stream.append", stream=self.name,
                        keys=tuple(self.key)) as pn, \
                timing.region("stream.append"):
            tbl = _as_table(batch, self.env)
            if self.env.world_size > 1:
                tbl = shuffle_table(tbl, self.key, owner="stream.recv")
            nbytes = _table_nbytes(tbl)
            if pn:
                pn.set(rows_in=tbl.row_count, rows_out=tbl.row_count,
                       batch=self.batches_appended)
            # scheduler-mediated admission (TS109): ingest state counts
            # against the mesh budget like any tenant's resident state
            scheduler.admit_allocation(self.env, nbytes)
            self._regs.append(
                memory.register_table(f"{self.name}.chunk", tbl))
            self.chunks.append(tbl)
        self.rows_appended += int(tbl.row_count)
        self.bytes_appended += nbytes
        self.batches_appended += 1
        timing.bump("stream.batch_appended")
        for consumer in self._subscribers:
            consumer(tbl)
        return tbl

    def snapshot(self) -> Table:
        """All rows appended so far as one Table (per-shard order =
        append order — the batch-recompute oracle's input)."""
        if not self.chunks:
            raise InvalidError(f"stream {self.name!r} has no batches")
        return concat_tables(self.chunks) if len(self.chunks) > 1 \
            else self.chunks[0]

    def release(self) -> None:
        """Drop the accumulated chunks and drain their ledger balance."""
        from ..exec import memory
        for reg in self._regs:
            memory.release(reg)
        self._regs = []
        self.chunks = []

    def stats(self) -> dict:
        return {"name": self.name, "batches": self.batches_appended,
                "rows": self.rows_appended,
                "bytes": self.bytes_appended,
                "valid_counts": (np.asarray(self.chunks[-1].valid_counts)
                                 .tolist() if self.chunks else [])}

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"StreamTable({self.name!r}, batches="
                f"{self.batches_appended}, rows={self.rows_appended})")
