"""Event-time tumbling windows with consensus watermarks + spill-tier
eviction of closed state.

The Dataflow/Flink-style contract (PAPERS.md): rows carry an event time,
window ``w`` covers ``[w·window, (w+1)·window)``, and a WATERMARK —
``max event time seen − allowed lateness`` — decides when a window's
contents are complete.  Distributed, the watermark is rank-local (each
rank advances it from its own shards' event times, monotone by
construction), so window CLOSE is a collective decision: every rank
votes its closable-window count and the agreed MINIMUM closes
(:func:`cylon_tpu.exec.recovery.watermark_consensus` — the pmax wire
complemented, session-namespaced, registered with the jaxpr gate), so
every rank finalizes the same window at the same step.  A rank-local
close would emit and evict different state per rank — the same desync a
rank-local retry causes.

Closed windows take the as-of/broadcast join path: buffered probe rows
join the CURRENT build-side snapshot (a slowly-changing small dimension
table — the existing broadcast-join route replicates it, so the
pre-shuffled probe rows never move again), the result is emitted, and
the buffered state retires through the spill tier — device → host →
released (:func:`cylon_tpu.exec.memory.evict_release`, the
window-lifetime eviction class).  While open, window buffers are
ordinary SPILLABLE ledger registrations: a cold window under budget
pressure evicts to host like any cold tenant's packed source and
re-enters bit-exactly at close.

Late rows (event time in an already-closed window) follow the
configured policy: ``drop`` (counted) or ``clamp`` (land in the oldest
still-open window).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..utils.cache import jit, program_cache
from ..core.column import Column
from ..core.table import Table
from ..ctx.context import ROW_AXIS
from ..relational.common import REP, ROW
from ..relational.join import join_tables
from ..relational.repart import concat_tables, shuffle_table
from ..status import InvalidError
from ..utils.host import host_array
from .table import _as_table, _table_nbytes

shard_map = jax.shard_map

#: event-time sentinel for empty shards (min/max fold identities)
_T_MAX = np.int64(2**62)
_T_MIN = np.int64(-(2**62))


@program_cache()
def _event_bounds_fn(mesh: Mesh, cap: int):
    """Per-shard (min, max) event time over the live prefix — the
    append path's device-side watermark input: the post-shuffle resident
    time column is the authoritative copy, and in a multiprocess session
    each rank reads only its addressable shards, which is exactly the
    rank-local watermark the consensus min-vote reconciles.  Pure-local
    program (no collective) — jaxpr-gate registered."""

    def per_shard(vc, t):
        my = jax.lax.axis_index(ROW_AXIS)
        n = vc[my]
        mask = jnp.arange(cap, dtype=jnp.int32) < n
        lo = jnp.min(jnp.where(mask, t, jnp.int64(_T_MAX))).reshape(1)
        hi = jnp.max(jnp.where(mask, t, jnp.int64(_T_MIN))).reshape(1)
        return lo, hi

    return jit(shard_map(per_shard, mesh=mesh, in_specs=(REP, ROW),
                             out_specs=(ROW, ROW)))


def event_bounds(table: Table, time_col: str) -> tuple[int, int]:
    """(min, max) event time over a table's live rows (this process's
    addressable shards), or (T_MAX, T_MIN) identities when empty."""
    col = table.column(time_col)
    vc = np.asarray(table.valid_counts, np.int32)
    lo, hi = _event_bounds_fn(table.env.mesh, max(table.capacity, 1))(
        vc, col.data)
    lo = host_array(lo)
    hi = host_array(hi)
    return int(lo.min()), int(hi.max())


class _WindowBuffer:
    """One appended micro-batch's buffered rows for one open window:
    the COLUMN ARRAYS live only inside a spillable window-lifetime
    ledger registration (plus a host-side rebuild recipe), so an
    eviction — under budget pressure while open, or the close
    lifecycle's device→host→released retirement — genuinely drops the
    device references."""

    __slots__ = ("env", "reg", "_names", "_types", "_dicts", "_bounds",
                 "_has_valid", "_valid_counts", "rows")

    def __init__(self, table: Table, env, owner: str):
        from ..exec import memory
        self.env = env
        arrays, self._names, self._types = [], [], []
        self._dicts, self._bounds, self._has_valid = [], [], []
        for name, c in table.columns.items():
            self._names.append(name)
            self._types.append(c.type)
            self._dicts.append(c.dictionary)
            self._bounds.append(c.bounds)
            self._has_valid.append(c.validity is not None)
            arrays.append(c.data)
            if c.validity is not None:
                arrays.append(c.validity)
        self._valid_counts = np.asarray(table.valid_counts, np.int64)
        self.rows = int(table.row_count)
        self.reg = memory.register_window(
            owner, arrays,
            sharding=env.sharding() if env.world_size > 1 else None)

    def table(self) -> Table:
        """Rebuild the buffered rows as a Table — re-uploading through
        the spill tier first when budget pressure evicted this window
        while it was open (bit-exact round trip)."""
        from ..exec import memory
        memory.touch(self.reg)
        arrays = memory.device_arrays(self.reg)
        if arrays is None:
            arrays = memory.readmit(self.reg)
        it = iter(arrays)
        cols = {}
        for i, name in enumerate(self._names):
            data = next(it)
            valid = next(it) if self._has_valid[i] else None
            cols[name] = Column(data, self._types[i], valid,
                                self._dicts[i], bounds=self._bounds[i])
        return Table(cols, self.env, self._valid_counts)


class TumblingWindowJoin:
    """Windowed + as-of join of an event-time stream against a
    slowly-changing small build side.

    Usage::

        wj = TumblingWindowJoin(env, key="k", time_col="t", window=100,
                                build=dims, build_on="k", lateness=50)
        wj.append(batch)          # buffered per window, watermark advances
        closed = wj.watermark()   # consensus vote; closes ready windows
        wj.closed                 # [(window_id, joined Table), ...]
        wj.pop_closed()           # drain emitted results (+ their ledger)

    ``window``: tumbling width in event-time units; ``lateness``:
    allowed out-of-orderness subtracted from the max event time seen;
    ``late_policy``: ``"drop"`` (late rows counted and discarded) or
    ``"clamp"`` (late rows land in the oldest still-open window).
    ``emit``: optional callback ``emit(window_id, table)`` per close.
    ``set_build`` swaps the build side (as-of: a window joins the build
    version current at ITS close)."""

    def __init__(self, env, key, time_col: str, window: int, build,
                 build_on, *, lateness: int = 0,
                 late_policy: str = "drop", name: str = "wjoin",
                 how: str = "inner", origin: int = 0, emit=None):
        if late_policy not in ("drop", "clamp"):
            raise InvalidError(
                f"late_policy {late_policy!r} must be 'drop' or 'clamp'")
        if int(window) <= 0:
            raise InvalidError("window width must be positive")
        self.env = env
        self.key = [key] if isinstance(key, str) else list(key)
        self.time_col = str(time_col)
        self.window = int(window)
        #: event-time origin — window ordinals are counted from here, so
        #: absolute timestamps (epoch nanoseconds) stay inside the
        #: consensus wire's 2^20 window-ordinal width
        self.origin = int(origin)
        self.build_on = [build_on] if isinstance(build_on, str) \
            else list(build_on)
        self.lateness = int(lateness)
        self.late_policy = late_policy
        self.name = str(name)
        self.how = how
        self.emit = emit
        self.build = _as_table(build, env)
        #: open window id -> list[_WindowBuffer]
        self._open: dict[int, list[_WindowBuffer]] = {}
        #: windows [0, _closed_through) are closed — the agreed count
        self._closed_through = 0
        self._local_wm = int(_T_MIN)   # monotone per-rank watermark
        self.closed: list[tuple[int, Table]] = []
        self._closed_regs: list = []   # ledger entries of emitted results
        self.windows_closed = 0
        self.late_dropped = 0
        self.late_clamped = 0
        self.rows_buffered = 0

    # -- build side (as-of) ------------------------------------------------
    def set_build(self, build) -> None:
        """Swap the slowly-changing build side; windows closed after
        this join the new version (as-of-close semantics)."""
        self.build = _as_table(build, self.env)

    # -- ingest ------------------------------------------------------------
    def append(self, batch) -> None:
        """Buffer one micro-batch into its event-time windows.  Host
        rows split per window id, each sub-batch hash-shuffles on the
        join key (arrival co-location like StreamTable), is admitted
        through the scheduler facade and registers as a spillable
        window-lifetime allocation; the device-resident time column then
        advances this rank's watermark."""
        from ..exec import recovery, scheduler
        from ..utils import timing
        scheduler.maybe_yield()
        recovery.maybe_inject("stream.append")
        cols = self._host_columns(batch)
        times = np.asarray(cols[self.time_col], np.int64)
        if times.size == 0:
            return
        wid = (times - self.origin) // self.window
        if (wid < 0).any():
            # pre-origin events are invalid input, NOT late rows: no
            # window before the origin ever existed (or closed), so
            # silently applying the late policy would discard data the
            # contract never covered — fail loud instead
            raise InvalidError(
                f"{self.name}: {int((wid < 0).sum())} event(s) before "
                f"the stream origin {self.origin} — window ordinals are "
                "counted from `origin`; construct the join with an "
                "origin at or below the earliest event time")
        late = wid < self._closed_through
        if late.any():
            if self.late_policy == "drop":
                self.late_dropped += int(late.sum())
                keep = ~late
                cols = {k: np.asarray(v)[keep] for k, v in cols.items()}
                wid = wid[keep]
            else:   # clamp: land in the oldest still-open window
                self.late_clamped += int(late.sum())
                wid = np.maximum(wid, self._closed_through)
        if wid.size == 0:
            return
        with timing.region("stream.window_append"):
            for w in np.unique(wid):
                sel = wid == w
                sub = {k: np.asarray(v)[sel] for k, v in cols.items()}
                tbl = Table.from_pydict(sub, self.env)
                if self.env.world_size > 1:
                    tbl = shuffle_table(tbl, self.key, owner="stream.recv")
                scheduler.admit_allocation(self.env, _table_nbytes(tbl))
                _lo, hi = event_bounds(tbl, self.time_col)
                buf = _WindowBuffer(tbl, self.env,
                                    f"{self.name}.w{int(w)}")
                del tbl     # the registration is the only device ref
                self._open.setdefault(int(w), []).append(buf)
                self.rows_buffered += buf.rows
                # monotone per-rank advance from the authoritative
                # (post-shuffle, device-resident) time column
                self._local_wm = max(self._local_wm,
                                     int(hi) - self.lateness)

    def _host_columns(self, batch) -> dict:
        if isinstance(batch, dict):
            return dict(batch)
        pdf = batch.to_pandas() if hasattr(batch, "to_pandas") else batch
        return {str(k): pdf[k].to_numpy() for k in pdf.columns}

    # -- watermark + close -------------------------------------------------
    def local_watermark(self) -> int:
        return self._local_wm

    def closable_count(self) -> int:
        """This rank's vote: how many windows [0, n) its local watermark
        has passed (window w closes when wm >= origin + (w+1)·window)."""
        rel = self._local_wm - self.origin
        if self._local_wm == int(_T_MIN) or rel < 0:
            return self._closed_through
        return max(int(rel) // self.window, self._closed_through)

    def watermark(self) -> int:
        """Agree the watermark across ranks and close every ready
        window.  Returns the agreed closable-window count (the agreed
        watermark is ``origin + count · window``).  Every rank closes the
        identical windows in the identical order — the min-vote holds
        the close back to the slowest rank's watermark.

        The wire carries the DELTA of newly-closable windows, not the
        cumulative ordinal: ``_closed_through`` advances only by agreed
        amounts, so it is identical on every rank and the cumulative
        count reconstructs exactly — while a forever-running stream (or
        a stream whose first batch sits billions of windows past the
        origin, e.g. epoch timestamps with the default origin) never
        outgrows the consensus wire's 2^20 width.  A jump wider than
        the wire votes in saturating rounds: the loop repeats exactly
        while the AGREED delta saturates the clamp — a rank-uniform
        value, so every rank takes the identical number of voting
        rounds.  Windows nothing was buffered into are skipped in
        O(open windows) — an idle stream closing a large time range
        records nothing."""
        from ..exec import recovery, scheduler
        scheduler.maybe_yield()
        recovery.maybe_inject("stream.watermark")
        mesh = getattr(self.env, "mesh", None) \
            if self.env.world_size > 1 else None
        wire_max = (1 << 20) - 1
        while True:
            delta = min(self.closable_count() - self._closed_through,
                        wire_max)
            agreed_delta = recovery.watermark_consensus(mesh, delta)
            agreed = self._closed_through + agreed_delta
            for wid in sorted(w for w in self._open if w < agreed):
                self._close(wid)
            self._closed_through = agreed
            if agreed_delta < wire_max:
                return agreed

    def _close(self, wid: int) -> None:
        """Finalize one window: concat its buffered rows, join the
        CURRENT build side (broadcast route for a small build — the
        probe rows never move again), emit, then retire the buffers
        through the spill tier (device → host → released — the ledger
        balance drains by the window's full byte count)."""
        from ..exec import memory
        from ..obs import plan as _plan
        from ..utils import timing
        bufs = self._open.pop(wid)
        with _plan.node("stream.window_close", stream=self.name,
                        window=int(wid), how=self.how) as pn, \
                timing.region("stream.window_close"):
            parts = [b.table() for b in bufs]
            probe = concat_tables(parts) if len(parts) > 1 else parts[0]
            if pn:
                pn.set(rows_in=probe.row_count)
            out = join_tables(probe, self.build, self.key, self.build_on,
                              how=self.how, allow_defer=False)
            if pn:
                pn.set(rows_out=out.row_count)
            del probe, parts
            for b in bufs:
                memory.evict_release(b.reg)
        # the emitted result is itself long-lived resident state while
        # it sits in `closed` — accounted (anchored to the table, so
        # pop_closed()/GC drains the balance), never ledger-invisible
        self._closed_regs.append(
            memory.register_table(f"{self.name}.closed", out))
        self.closed.append((wid, out))
        self.windows_closed += 1
        timing.bump("stream.window_closed")
        if self.emit is not None:
            self.emit(wid, out)

    def pop_closed(self) -> list[tuple[int, Table]]:
        """Drain the emitted results (and their ledger registrations) —
        the long-running consumer's hand-off point: a stream that closes
        windows forever must pop (or consume via ``emit=`` and pop) so
        retained results do not accumulate."""
        from ..exec import memory
        out, self.closed = self.closed, []
        for reg in self._closed_regs:
            memory.release(reg)
        self._closed_regs = []
        return out

    def stats(self) -> dict:
        return {"name": self.name, "windows_closed": self.windows_closed,
                "open_windows": len(self._open),
                "closed_through": self._closed_through,
                "late_dropped": self.late_dropped,
                "late_clamped": self.late_clamped,
                "rows_buffered": self.rows_buffered,
                "local_watermark": self._local_wm}


# ---------------------------------------------------------------------------
# trace-safety declarations (cylon_tpu.analysis.registry): the event-bounds
# program is pure-local (each rank reads only its shards — the watermark's
# rank-local half); the watermark VOTE rides the already-verified one-pmax
# consensus program, declared here under its stream alias so the gate
# covers the streaming use.  docs/trace_safety.md.
# ---------------------------------------------------------------------------

def _trace_event_bounds(mesh):
    w, cap, S = _decl_shapes(mesh)
    fn = _unwrap(_event_bounds_fn(mesh, cap))
    return jax.make_jaxpr(fn)(S((w,), np.int32), S((w * cap,), np.int64))


def _trace_watermark_consensus(mesh):
    from ..exec.recovery import _consensus_fn
    w = int(mesh.devices.size)
    fn = _unwrap(_consensus_fn(mesh, w))
    return jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((w,), np.int32))


from ..analysis.registry import (declare_builder,  # noqa: E402
                                 decl_shapes as _decl_shapes,
                                 unwrap as _unwrap)

declare_builder(f"{__name__}._event_bounds_fn", _trace_event_bounds,
                tags=("stream",))
declare_builder("cylon_tpu.exec.recovery._consensus_fn[stream.watermark]",
                _trace_watermark_consensus, collectives={"pmax"},
                tags=("stream", "recovery"))
