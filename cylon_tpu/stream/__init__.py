"""Streaming ingest subsystem: append-only tables, incremental views,
and event-time windowed joins with watermark eviction.

The reference ships a push-based streaming op stack — the
``StreamingSplitKernel`` (SURVEY C5) feeding the op DAG — precisely so
relational operators can serve continuously arriving data, not just
one-shot batches.  Everything this package layers on already exists in
the engine: :class:`~cylon_tpu.exec.pipeline.GroupBySink` maintains
streaming partial aggregates (including var/std),
:func:`~cylon_tpu.exec.pipeline.chunk_table` is dispatch-on-demand, the
serving scheduler (PR 7) interleaves long-lived sessions, the HBM
ledger (PR 4) accounts and spills resident state, and the PR 3
consensus wire agrees rank-divergent decisions.  This package turns
those internals into a PUBLIC continuously-served workload:

* :class:`~cylon_tpu.stream.table.StreamTable` — an append-only
  distributed table: each micro-batch is hash-shuffled on arrival
  through the existing exchange engine (``parallel/shuffle.py``, receive
  buffers ledger-labelled ``stream.recv``), admitted through the
  scheduler facade (TS109) and accumulated as dispatch-on-demand chunks;

* :class:`~cylon_tpu.stream.view.IncrementalView` — an incrementally
  maintained groupby-aggregate: every appended batch is absorbed into a
  long-lived ``GroupBySink`` and ``read()`` finalizes a consistent
  snapshot WITHOUT disturbing the partials — bit-equal to a from-scratch
  batch groupby over all rows seen so far whenever the partial sums are
  exact (docs/streaming.md "exactness contract"); with
  ``CYLON_TPU_CKPT_DIR`` armed each absorbed partial commits durably and
  a killed ingest resumes by fast-forwarding committed batches;

* :class:`~cylon_tpu.stream.window.TumblingWindowJoin` — event-time
  tumbling windows with a monotone per-rank watermark agreed over the
  consensus wire (min-vote,
  :func:`cylon_tpu.exec.recovery.watermark_consensus`) so every rank
  closes the same window at the same step; closed windows join against a
  slowly-changing small build side (the existing broadcast-join route —
  as-of semantics: the build version current at close) and their
  buffered state retires through the spill tier: device → host →
  released (:func:`cylon_tpu.exec.memory.evict_release`).

Benchmark: ``scripts/bench_streaming.py`` — sustained rows/s, p50/p99
append-to-visible staleness, watermark lag, window closes/evictions and
a bit-equal verdict vs batch recompute.  Contracts: docs/streaming.md.
"""

from .table import StreamTable  # noqa: F401
from .view import IncrementalView  # noqa: F401
from .window import TumblingWindowJoin  # noqa: F401
