"""IncrementalView: an incrementally-maintained groupby-aggregate.

Each appended micro-batch is absorbed into a long-lived
:class:`~cylon_tpu.exec.pipeline.GroupBySink` — one partial aggregate
per batch, HBM-ledger-accounted — and ``read()`` finalizes a consistent
snapshot through the sink's non-destructive ``snapshot()`` path
(:func:`cylon_tpu.relational.groupby.combine_sink_partials`) without
disturbing the partials, so ingestion continues underneath.  The
snapshot is bit-equal to a from-scratch batch groupby over every row
seen so far whenever the partial sums are exact (integer payloads /
integer-valued f64 — docs/streaming.md "exactness contract").

Durability (``CYLON_TPU_CKPT_DIR``): every absorbed partial is a
completed piece of a checkpoint stage — saved through the spill-tier
page transport and committed under the two-phase rank-coherent manifest
exactly like a pipelined join's pieces (exec/checkpoint).  A process
killed mid-ingest resumes (``CYLON_TPU_RESUME=1``) by restoring the
committed partials bit-identically and FAST-FORWARDING that many
appends: the replayed batches are counted, not recomputed, and the
final ``read()`` is bit-equal to the uninterrupted run
(scripts/chaos_soak.py ``--stream``).
"""

from __future__ import annotations

from ..exec.pipeline import GroupBySink
from ..core.table import Table


class IncrementalView:
    """A continuously-maintained groupby-aggregate over a stream.

    Usage::

        st = StreamTable(env, key="k")
        view = IncrementalView(st, "k", [("v", "sum"), ("v", "var")])
        st.append(batch); st.append(batch2)
        snap = view.read()        # Table; ingest keeps going

    ``source``: a :class:`~cylon_tpu.stream.table.StreamTable` to
    subscribe to (absorbs every append), or None to drive
    :meth:`absorb` manually.  Aggregation ops are the sink's
    decomposable set: sum/count/min/max/mean/var/std.
    """

    _SEQ = [0]  # deterministic default-name counter (resume-stable)

    def __init__(self, source, by, aggs, ddof: int = 1,
                 name: str | None = None, env=None,
                 compact_every: int = 32):
        self.env = env if env is not None else source.env
        self.by = [by] if isinstance(by, str) else list(by)
        self.aggs = list(aggs)
        self.ddof = int(ddof)
        #: fold the sink's partials into one every N absorbed batches
        #: (GroupBySink.compact) — bounded state and O(groups) reads for
        #: unbounded streams; semantics-preserving (bit-equal under the
        #: exactness contract).  0 disables.
        self.compact_every = int(compact_every)
        if name is None:
            name = f"view{self._SEQ[0]}"
            self._SEQ[0] += 1
        self.name = str(name)
        self.sink = GroupBySink(self.by, self.aggs, ddof=self.ddof)
        self.batches_absorbed = 0
        self.rows_absorbed = 0
        self._skip = 0          # resume fast-forward: batches already
        #                         covered by restored partials
        self._ffwd = 0          # restored-prefix length (resume audit)
        self._attach_checkpoint()
        if source is not None:
            source.subscribe(self.absorb)

    # -- durability --------------------------------------------------------
    def _attach_checkpoint(self) -> None:
        """Arm durable checkpointing when ``CYLON_TPU_CKPT_DIR`` is set:
        the view is ONE long-lived stage (plan token over the view's
        static plan — name, keys, agg specs, ddof; the world rides the
        LAYOUT half of the split token), each absorbed partial a
        committed piece.  On resume the committed prefix is restored
        bit-identically, the fast-forward count min-agreed across ranks
        (a rank whose page failed verification degrades the whole
        session coherently), and that many future appends are
        fast-forwarded instead of re-absorbed.

        Unlike a pipelined join's pieces, a view's piece identity (the
        batch ordinal in the stream) is WORLD-INVARIANT and its content
        is mergeable, so a resume at a different topology adopts the
        committed PREFIX: each foreign partial's pages are stitched and
        re-blocked onto the live mesh (`Stage.load_foreign_pieces`) and
        adopted via ``restore_partial`` — the sink's
        ``combine_sink_partials`` read path merges re-distributed
        partials exactly like same-world ones, which is why no row-order
        preservation is needed here.  The adopted prefix re-commits in
        the new layout so the next resume is plain."""
        from ..exec import checkpoint as ckpt
        from ..exec import recovery
        from ..status import CheckpointCorruptError, DataIntegrityError
        if not ckpt.enabled():
            return
        base = ckpt.plan_token(
            "stream_view", self.name, tuple(self.by),
            tuple((c, op) for c, op, *_ in self.aggs), self.ddof)
        token = ckpt.plan_token(base, int(self.env.world_size))
        stage = ckpt.open_stage(self.env, f"stream_view.{self.name}", token,
                                base_token=base)
        if ckpt.resume_requested():
            restored: list = []
            foreign = stage.foreign is not None
            if stage.resuming:
                while stage.has_piece(len(restored)):
                    try:
                        restored.append(stage.load_piece(len(restored)))
                    except (CheckpointCorruptError,
                            DataIntegrityError) as e:
                        # a manifest-fingerprint miss (armed audit)
                        # degrades exactly like page corruption:
                        # recompute, never adopt
                        ckpt.corrupt_fallback(stage, len(restored), e)
                        break
            elif foreign:
                try:
                    # prefix_ok: a corrupt batch k trims the adoption to
                    # the verified 0..k-1 prefix instead of discarding
                    # the stream's whole committed history
                    restored = stage.load_foreign_pieces(prefix_ok=True)
                except (CheckpointCorruptError, DataIntegrityError) as e:
                    ckpt.corrupt_fallback(stage, len(restored), e)
                    restored = []
            n = recovery.ckpt_resume_consensus(
                getattr(self.env, "mesh", None), len(restored))
            if foreign:
                restored = restored[:n]
                if restored:
                    ckpt.note_reshard(n)
                    stage.begin_rewrite()
                    for i, part in enumerate(restored):
                        stage.save_piece(i, part)
            elif len(restored) > n:
                ckpt.unrestore(len(restored) - n)
            for part in restored[:n]:
                self.sink.restore_partial(part)
            self._skip = self._ffwd = len(restored[:n])
        self.sink.attach_checkpoint(stage)

    @property
    def fast_forwarded(self) -> int:
        """Appends covered by restored checkpoint partials (resume)."""
        return self._ffwd

    # -- ingest ------------------------------------------------------------
    def absorb(self, batch: Table) -> None:
        """Absorb one (post-shuffle) micro-batch into the sink.  During
        a resume fast-forward the first ``_skip`` replayed batches are
        counted but NOT re-absorbed — the restored partials already hold
        their state bit-identically."""
        self.batches_absorbed += 1
        self.rows_absorbed += int(batch.row_count)
        if self._skip > 0:
            self._skip -= 1
            return
        from ..exec import integrity
        if integrity.armed():
            # armed audit (exec/integrity): vote the absorbed batch's
            # order-invariant fingerprint rank-coherently BEFORE it is
            # folded into the long-lived partials — a rank that ingested
            # different bytes surfaces typed here, not as a silently
            # diverged snapshot later
            integrity.audit_table(batch, site="stream.absorb",
                                  phase="stream_absorb")
        self.sink.absorb(batch)
        if (self.compact_every
                and len(self.sink._parts) >= self.compact_every):
            self.sink.compact()
        from ..exec import checkpoint as ckpt
        if self.sink._ckpt is not None and ckpt.drain_requested(self.env):
            # preemption grace: the batch just absorbed is committed —
            # this append boundary is the planned exit (exec/preempt);
            # the resumed ingest fast-forwards the committed batches,
            # re-sharding them if the world changed
            self.sink.flush_pending()
            ckpt.drain_abort(f"stream_view.{self.name}")

    def read(self) -> Table:
        """A consistent finalized snapshot over every batch absorbed so
        far.  Non-destructive: the sink's partials stay adopted and
        subsequent appends keep absorbing (the append-to-visible
        staleness the streaming bench measures is exactly the latency of
        one absorb + one read)."""
        return self.sink.snapshot()

    def finalize(self) -> Table:
        """Terminal read: drains the sink (ledger balance released)."""
        return self.sink.finalize()

    def stats(self) -> dict:
        return {"name": self.name, "batches": self.batches_absorbed,
                "rows": self.rows_absorbed,
                "fast_forwarded": self._ffwd,
                "partials": len(self.sink._parts)}
