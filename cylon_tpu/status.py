"""Status / error model.

TPU-native equivalent of the reference's C++ ``Status``/``Code`` pair
(reference: cpp/src/cylon/status.hpp:65, cpp/src/cylon/code.hpp:19).  The
reference threads a ``Status{code, msg}`` through every call; in Python the
idiomatic carrier is an exception hierarchy, but we keep the same code
vocabulary so bindings and tests can assert on error categories.
"""

from __future__ import annotations

import enum


class Code(enum.IntEnum):
    """Error codes mirroring reference cpp/src/cylon/code.hpp:19-40."""

    OK = 0
    OutOfMemory = 1
    KeyError = 2
    TypeError = 3
    Invalid = 4
    IOError = 5
    CapacityError = 6
    IndexError = 7
    UnknownError = 9
    NotImplemented = 10
    SerializationError = 11
    RError = 12
    #: spill-tier consensus vote (exec/memory): a rank under memory
    #: pressure requests a COLLECTIVE eviction; rides the same pmax wire
    #: as the fault codes (docs/robustness.md, "why eviction is
    #: collective").  Not an error class — never raised.
    SpillRequired = 46
    #: durable-checkpoint two-phase commit vote (exec/checkpoint): every
    #: rank has STAGED its manifest and votes this code with the staged
    #: epoch riding the same pmax wire, so a manifest is committed on
    #: every rank at the identical epoch or on none.  Not an error class
    #: — never raised.
    CkptCommit = 47
    #: preemption-grace drain vote (exec/preempt + exec/checkpoint): a
    #: rank that received SIGTERM with the grace budget armed requests a
    #: COLLECTIVE drain at the next checkpoint boundary, so every rank
    #: commits the same prefix and raises the same typed ResumableAbort
    #: instead of one rank draining while its peers enter the next
    #: collective alone.  Not an error class — never raised.
    PreemptDrain = 48
    #: skew-plan adoption vote (exec/recovery.skew_plan_consensus +
    #: relational/skew.py): every rank has computed the adaptive
    #: skew-split plan (heavy-key set, rank groups, salted fan-out) from
    #: the allgathered sample and votes this code with two 20-bit slices
    #: of the plan hash riding the pmax wire, so the recovery ladder,
    #: checkpoints and elastic resume all see ONE plan — a rank whose
    #: hash diverges raises typed instead of entering the split
    #: exchange's collectives alone.  Not an error class — never raised.
    SkewPlan = 49
    #: topology-plan adoption vote (exec/recovery.topo_plan_consensus +
    #: cylon_tpu/topo): every rank has derived the multi-slice topology
    #: plan (slice map, route choice, gateway scheme) from the same
    #: device attributes / CYLON_TPU_SLICES declaration and votes this
    #: code with two 20-bit slices of the canonical plan hash riding the
    #: pmax wire BEFORE the first hierarchical collective, so recovery
    #: ladders, checkpoints and elastic resume all adopt ONE topology —
    #: a rank whose slice map diverges raises typed instead of entering
    #: a two-hop exchange its peers route differently.  Not an error
    #: class — never raised.
    TopoPlan = 50
    #: data-integrity audit fault (exec/integrity + exec/recovery): a
    #: conservation law or an armed content fingerprint failed — bytes
    #: in flight were lost, duplicated or mutated.  Raised as
    #: :class:`DataIntegrityError` and retried ONCE by the ladder's
    #: recompute rung (mirroring the disk-corruption rung: corruption
    #: degrades to recompute, never to a wrong answer); the fingerprint
    #: verdict itself rides the double-polarity plan-hash wire with this
    #: code so every rank agrees on the failing site before anyone
    #: raises.  Must stay < 64: the wire packs ``code*4+sub`` under the
    #: ladder's 1024 base and ``code << 20`` under the checkpoint
    #: namespace base.
    IntegrityFault = 51
    CodeGenError = 40
    ExpressionValidationError = 41
    ExecutionError = 42
    AlreadyExists = 45


class CylonError(Exception):
    """Base error carrying a :class:`Code`."""

    code: Code = Code.UnknownError

    def __init__(self, msg: str = "", code: Code | None = None):
        super().__init__(msg)
        if code is not None:
            self.code = code

    @property
    def msg(self) -> str:
        return str(self)


class InvalidError(CylonError):
    code = Code.Invalid


# ---------------------------------------------------------------------------
# Fault taxonomy (docs/robustness.md).  Every recoverable capacity/comms
# failure in the engine is one of these four types; the consensus retry
# ladder (cylon_tpu.exec.recovery) dispatches on them, and string-matching
# XLA messages outside recovery.py is a lint finding (TS105).  Each class
# carries a short ``kind`` tag used by recovery-event logs and the
# fault-injection grammar.
# ---------------------------------------------------------------------------

class PredictedResourceExhausted(CylonError, MemoryError):
    """A capacity guard fired BEFORE any device allocation (e.g. the
    exchange receive-budget guard, parallel/shuffle.py): HBM is NOT
    poisoned, so an in-process retry at a degraded configuration is safe.
    Subclasses MemoryError and keeps ``RESOURCE_EXHAUSTED (predicted)`` in
    the message so pre-taxonomy callers keep classifying it as OOM."""

    code = Code.OutOfMemory
    kind = "predicted"

    def __init__(self, msg: str = "", site: str | None = None):
        super().__init__(msg)
        self.site = site


class DeviceOOMError(CylonError):
    """A real XLA/PJRT RESOURCE_EXHAUSTED surfaced by the runtime: device
    memory was actually exhausted (and on some rigs the process's HBM is
    poisoned).  Foreign runtime errors are wrapped into this type by
    ``cylon_tpu.exec.recovery.classify`` (the one sanctioned
    string-matching site); the original exception rides ``__cause__``."""

    code = Code.OutOfMemory
    kind = "device_oom"

    def __init__(self, msg: str = "", site: str | None = None):
        super().__init__(msg)
        self.site = site


class CapacityOverflowError(CylonError):
    """A pow2-bucketed static capacity (piece cap, output cap) was
    exceeded by the actual row counts — the planned shape family cannot
    hold the data; the remedy is a deterministic re-plan at a smaller
    piece size (cap halving), not a memory retry."""

    code = Code.CapacityError
    kind = "capacity"

    def __init__(self, msg: str = "", site: str | None = None):
        super().__init__(msg)
        self.site = site


class RankDesyncError(CylonError):
    """Ranks stopped advancing together: a peer hung in (or never
    entered) a collective, detected by the exchange watchdog, or a
    consensus poll disagreed structurally.  Carries the site and the
    last-known timing phase for postmortems."""

    code = Code.ExecutionError
    kind = "desync"

    def __init__(self, msg: str = "", site: str | None = None,
                 phase: str | None = None):
        super().__init__(msg)
        self.site = site
        self.phase = phase


class DataIntegrityError(CylonError):
    """The integrity audit tier (exec/integrity) caught data in flight
    being lost, duplicated or mutated: a conservation law over the
    exchange count sidecar failed (always-on, pure host math), or an
    armed order-invariant content fingerprint stopped matching across a
    stage boundary (``CYLON_TPU_AUDIT=1``).  Carries the facade ``site``
    (``exchange.conserve``, ``audit.verify``, ``ckpt.audit`` ...) and
    the dataflow ``phase`` (``post_exchange``, ``post_stitch``,
    ``stream_absorb``, ``resume``).  A fault type: the consensus ladder
    recomputes the affected stage ONCE (the silent-corruption analogue
    of the disk-corruption rung), then aborts typed on repeat — never a
    wrong answer, never an unbounded retry loop."""

    code = Code.IntegrityFault
    kind = "integrity"

    def __init__(self, msg: str = "", site: str | None = None,
                 phase: str | None = None):
        super().__init__(msg)
        self.site = site
        self.phase = phase


#: the recovery-fault types, in one tuple for isinstance dispatch
FAULT_TYPES = (PredictedResourceExhausted, DeviceOOMError,
               CapacityOverflowError, RankDesyncError,
               DataIntegrityError)


class NumericOverflowError(CylonError):
    """An armed-audit accumulator check (ops/groupby finalize under
    ``CYLON_TPU_AUDIT=1``) found an int64 sum/count at the saturation
    rail: the combine tree wrapped (or is one combine away from
    wrapping), so the aggregate would be silently wrong.  NOT a fault
    type — no retry rung can un-wrap modular arithmetic, so the
    contract is abort-not-wrong: classified typed, surfaced to the
    caller, never retried."""

    code = Code.ExecutionError
    kind = "overflow"

    def __init__(self, msg: str = "", site: str | None = None,
                 column: str | None = None):
        super().__init__(msg)
        self.site = site
        self.column = column


class ResumableAbort(CylonError):
    """The retry ladder's FINAL rung (exec/recovery + exec/checkpoint):
    an unrecoverable fault (real device OOM on an HBM-poisoning rig, an
    exhausted compiler-crash ladder) arrived while durable checkpointing
    was armed — committed piece state has been flushed, and a FRESH
    process launched with ``CYLON_TPU_RESUME=1`` fast-forwards past the
    committed pieces bit-identically instead of recomputing.  ``token``
    is the resume token (the checkpoint directory); the original fault
    rides ``__cause__``.  Terminal by design: never retried in-process
    (the whole point is that in-process retries are doomed here)."""

    code = Code.ExecutionError
    kind = "resumable"

    def __init__(self, msg: str = "", token: str | None = None):
        super().__init__(msg)
        self.token = token


class AdmissionTimeoutError(CylonError):
    """A pending serving session exceeded the admission deadline
    (``CYLON_TPU_ADMISSION_TIMEOUT_S`` or the scheduler's
    ``admission_timeout_s``) while waiting at the head of line: the
    tenant is failed TYPED instead of waiting unboundedly behind a
    long-running co-tenant (docs/serving.md, "Admission deadline").
    Rank-coherent under multi-controller runs — the expiry decision
    rides the count-consensus wire, so every rank fails the same
    session."""

    code = Code.ExecutionError
    kind = "admission_timeout"

    def __init__(self, msg: str = "", session: str | None = None,
                 waited_s: float | None = None):
        super().__init__(msg)
        self.session = session
        self.waited_s = waited_s


class RequeueOverflowError(CylonError):
    """A preempted tenant drained resumably but the scheduler's requeue
    capacity was already exhausted: the tenant stays failed TYPED with
    its resume token preserved on ``__cause__`` (the original
    :class:`ResumableAbort`), so an operator can relaunch it with
    ``CYLON_TPU_RESUME=1`` instead of silently losing the work
    (docs/serving.md, "Preemption & elastic serving")."""

    code = Code.CapacityError
    kind = "requeue_overflow"

    def __init__(self, msg: str = "", session: str | None = None):
        super().__init__(msg)
        self.session = session


class CompileQuarantinedError(CapacityOverflowError):
    """A compile signature is QUARANTINED: the compile-intent journal
    (exec/compiler) shows a predecessor process died mid-compile on this
    exact (builder, shape-signature) pair, so re-lowering it would walk
    straight back into the compiler crash.  Subclasses
    :class:`CapacityOverflowError` deliberately — the recovery ladder's
    ``Code.CapacityError`` rung re-plans at a halved piece cap, which
    changes the operand shapes and therefore the signature, sidestepping
    the quarantined program instead of re-crashing
    (docs/robustness.md, "Compile lifecycle")."""

    kind = "quarantined"

    def __init__(self, msg: str = "", site: str | None = None,
                 signature: str | None = None):
        super().__init__(msg, site=site)
        self.signature = signature


class CompileTimeoutError(CylonError):
    """A ``.lower()``/``.compile()`` exceeded the compile watchdog budget
    (``CYLON_TPU_COMPILE_TIMEOUT_S``): the build thread is hung inside
    XLA, so the caller surfaces TYPED instead of wedging the whole rank
    (and, in multi-controller runs, desyncing its peers).  Same worker
    thread + bounded ``join`` pattern as the exchange watchdog
    (exec/recovery.exchange_watchdog), but typed for the compile axis so
    serving can count / alert on slow-compile tenants separately from
    collective desyncs."""

    code = Code.ExecutionError
    kind = "compile_timeout"

    def __init__(self, msg: str = "", site: str | None = None,
                 signature: str | None = None):
        super().__init__(msg)
        self.site = site
        self.signature = signature


class CheckpointCorruptError(CylonError):
    """A checkpoint page or manifest failed its content-hash check (or
    an injected ``corrupt`` fault simulated that) on the resume path:
    the stage's remaining pieces are recomputed instead of restored —
    corruption degrades resume to recompute, never to a wrong answer."""

    code = Code.SerializationError
    kind = "corrupt"

    def __init__(self, msg: str = "", site: str | None = None):
        super().__init__(msg)
        self.site = site


class CylonTypeError(CylonError):
    code = Code.TypeError


class CylonKeyError(CylonError):
    code = Code.KeyError


class CylonIndexError(CylonError):
    code = Code.IndexError


class CylonIOError(CylonError):
    code = Code.IOError


class NotImplementedCylonError(CylonError):
    code = Code.NotImplemented


class ExecutionError(CylonError):
    code = Code.ExecutionError


class Status:
    """Value-style status for APIs that prefer returns over raises.

    Mirrors reference ``cylon::Status`` (status.hpp:65): ``is_ok()``,
    ``get_code()``, ``get_msg()``.
    """

    __slots__ = ("code", "msg")

    def __init__(self, code: Code = Code.OK, msg: str = ""):
        self.code = Code(code)
        self.msg = msg

    @staticmethod
    def OK() -> "Status":
        return Status(Code.OK)

    def is_ok(self) -> bool:
        return self.code == Code.OK

    def get_code(self) -> Code:
        return self.code

    def get_msg(self) -> str:
        return self.msg

    def raise_if_failed(self) -> None:
        if not self.is_ok():
            raise CylonError(self.msg, self.code)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Status({self.code.name}, {self.msg!r})"
