"""Status / error model.

TPU-native equivalent of the reference's C++ ``Status``/``Code`` pair
(reference: cpp/src/cylon/status.hpp:65, cpp/src/cylon/code.hpp:19).  The
reference threads a ``Status{code, msg}`` through every call; in Python the
idiomatic carrier is an exception hierarchy, but we keep the same code
vocabulary so bindings and tests can assert on error categories.
"""

from __future__ import annotations

import enum


class Code(enum.IntEnum):
    """Error codes mirroring reference cpp/src/cylon/code.hpp:19-40."""

    OK = 0
    OutOfMemory = 1
    KeyError = 2
    TypeError = 3
    Invalid = 4
    IOError = 5
    CapacityError = 6
    IndexError = 7
    UnknownError = 9
    NotImplemented = 10
    SerializationError = 11
    RError = 12
    CodeGenError = 40
    ExpressionValidationError = 41
    ExecutionError = 42
    AlreadyExists = 45


class CylonError(Exception):
    """Base error carrying a :class:`Code`."""

    code: Code = Code.UnknownError

    def __init__(self, msg: str = "", code: Code | None = None):
        super().__init__(msg)
        if code is not None:
            self.code = code

    @property
    def msg(self) -> str:
        return str(self)


class InvalidError(CylonError):
    code = Code.Invalid


class CylonTypeError(CylonError):
    code = Code.TypeError


class CylonKeyError(CylonError):
    code = Code.KeyError


class CylonIndexError(CylonError):
    code = Code.IndexError


class CylonIOError(CylonError):
    code = Code.IOError


class NotImplementedCylonError(CylonError):
    code = Code.NotImplemented


class ExecutionError(CylonError):
    code = Code.ExecutionError


class Status:
    """Value-style status for APIs that prefer returns over raises.

    Mirrors reference ``cylon::Status`` (status.hpp:65): ``is_ok()``,
    ``get_code()``, ``get_msg()``.
    """

    __slots__ = ("code", "msg")

    def __init__(self, code: Code = Code.OK, msg: str = ""):
        self.code = Code(code)
        self.msg = msg

    @staticmethod
    def OK() -> "Status":
        return Status(Code.OK)

    def is_ok(self) -> bool:
        return self.code == Code.OK

    def get_code(self) -> Code:
        return self.code

    def get_msg(self) -> str:
        return self.msg

    def raise_if_failed(self) -> None:
        if not self.is_ok():
            raise CylonError(self.msg, self.code)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Status({self.code.name}, {self.msg!r})"
