"""Indexing subsystem (reference cpp/src/cylon/indexing/)."""

from .indexer import ILocIndexer, LocIndexer, RANGE_INDEX  # noqa: F401
