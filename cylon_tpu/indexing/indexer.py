"""Indexing subsystem: row-label index + loc/iloc indexers.

TPU-native equivalent of the reference's indexing layer
(cpp/src/cylon/indexing/index.hpp:36 IndexingType RANGE/LINEAR/HASH...,
indexer.hpp:76 ``ArrowLocIndexer`` / :123 ``ArrowILocIndexer`` with pandas
loc/iloc semantics; table.hpp:164-169 Set/Get/ResetArrowIndex).

The reference attaches hash/linear index structures to the table for O(1)
label lookup; on TPU a label lookup is a vectorized compare/filter over the
(sharded) index column — no side structure beats a fused VPU scan, so
``IndexingType`` collapses to "which column is the index" plus a RANGE
default.  loc slices use the reference's contract: both endpoints inclusive.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any

import jax.numpy as jnp
import numpy as np

from ..core.dtypes import LogicalType
from ..relational import filter_table, slice_table
from ..status import CylonIndexError, CylonKeyError

if TYPE_CHECKING:  # pragma: no cover
    from ..frame import DataFrame

RANGE_INDEX = "__range__"


def _label_mask(col, labels) -> Any:
    """Device bool mask: row's index value in ``labels``."""
    if col.type == LogicalType.STRING:
        from ..core.column import HashedStrings
        d = col.dictionary
        if isinstance(d, HashedStrings):
            # label equality on hashed codes: hash the labels (equality is
            # an op the hashed path supports; order-based slicing is not)
            codes = d.hash_values(list(labels))
            return jnp.isin(col.data, np.asarray(codes, np.int64))
        codes = []
        for lb in labels:
            pos = int(np.searchsorted(d, lb))
            if pos < len(d) and d[pos] == lb:
                codes.append(pos)
        if not codes:
            return jnp.zeros_like(col.data, dtype=bool)
        return jnp.isin(col.data, np.asarray(codes, col.data.dtype))
    arr = np.asarray(labels).astype(np.dtype(col.data.dtype))
    return jnp.isin(col.data, arr)


class LocIndexer:
    """df.loc[labels] / df.loc[lo:hi] (inclusive) / df.loc[labels, cols]
    (reference ArrowLocIndexer modes, indexer.hpp:76)."""

    def __init__(self, df: "DataFrame"):
        self._df = df

    def __getitem__(self, key):
        df = self._df
        multi = isinstance(df._index, tuple)
        cols = None
        if isinstance(key, tuple) and len(key) == 2 and not multi:
            key, cols = key
        if multi and isinstance(key, tuple) and len(key) == 2 \
                and not self._is_label_tuple(key):
            # (row_key, cols) disambiguation: a 2-tuple whose parts are not
            # plausible level values is the pandas (rows, columns) form
            key, cols = key
        name = df._index
        if name is None or name == RANGE_INDEX:
            out = self._range_loc(key)
        elif multi:
            out = self._label_loc_multi(key, list(name))
        else:
            out = self._label_loc(key, name)
        if cols is not None:
            cols = [cols] if isinstance(cols, str) else list(cols)
            keep = df._index_cols() + cols
            out = out._wrap(out._table.project(
                [c for c in out._table.column_names if c in set(keep)]))
            out._index = df._index
            out._index_drop = df._index_drop
        return out

    def _is_label_tuple(self, key) -> bool:
        """Heuristic for multi-index ``loc[(a, b)]`` vs ``loc[rows, cols]``:
        a label tuple has only level-value parts (scalars, strings,
        timestamps, any non-container object) — the (rows, cols) form has
        a container/slice/Series part."""
        if not isinstance(key, tuple):
            return False
        nlev = len(self._df._index_cols())
        if len(key) > nlev:
            return False
        from ..series import Series
        return not any(isinstance(p, (list, tuple, slice, np.ndarray,
                                      Series)) for p in key)

    def _range_loc(self, key):
        df = self._df
        if isinstance(key, slice):
            lo = 0 if key.start is None else int(key.start)
            hi = len(df) - 1 if key.stop is None else int(key.stop)
            return df[lo:hi + 1]  # loc slices are inclusive
        if np.isscalar(key):
            return df[int(key):int(key) + 1]
        labels = list(key)
        # positional filter over the implicit range index
        return df.iloc[labels]

    def _label_loc(self, key, name: str):
        df = self._df
        col = df._table.column(name)
        if isinstance(key, slice):
            # inclusive label range: value >= start & value <= stop
            s = df._col_series(name)
            mask = None
            if key.start is not None:
                mask = (s >= key.start)
            if key.stop is not None:
                m2 = (s <= key.stop)
                mask = m2 if mask is None else (mask & m2)
            if mask is None:
                return df
            from ..relational.common import valid_flag
            out = df._wrap(filter_table(df._table, valid_flag(mask.column)))
            out._index = df._index
            out._index_drop = df._index_drop
            return out
        labels = [key] if np.isscalar(key) or isinstance(key, str) else list(key)
        # pandas raises when ANY requested label is absent, not only when all
        # are: check membership against the index column's values
        values = df._col_series(name).to_numpy()
        try:  # dtype-matched isin takes numpy's sort-based path; the object
            labels_arr = np.asarray(labels, dtype=values.dtype)
        except (TypeError, ValueError):  # fallback compares elementwise
            labels_arr = np.asarray(labels, dtype=object)
        present = np.isin(labels_arr, values)
        if not present.all():
            missing = [lb for lb, ok in zip(labels, present) if not ok]
            raise CylonKeyError(f"labels {missing!r} not found in index")
        mask = _label_mask(col, labels)
        out = df._wrap(filter_table(df._table, mask))
        out._index = df._index
        out._index_drop = df._index_drop
        return out


    # -- multi-index (reference index.hpp:36 types over indexer.hpp:76) ----

    def _multi_eq_mask(self, labels: tuple, names: list):
        """Conjunction of level equalities for a (possibly partial) label
        tuple — leading levels only, like pandas partial indexing."""
        df = self._df
        mask = None
        for lv, lb in zip(names, labels):
            m = df._col_series(lv) == lb
            mask = m if mask is None else (mask & m)
        return mask

    def _lex_bound_mask(self, bound: tuple, names: list, is_start: bool):
        """Lexicographic >= start / <= stop over the index levels (loc
        slice endpoints inclusive, reference contract).  ``bound`` may
        cover a prefix of the levels; rows equal on the prefix count as
        inside the bound."""
        df = self._df
        mask = None          # built innermost-out
        for lv, b in reversed(list(zip(names, bound))):
            s = df._col_series(lv)
            strict = (s > b) if is_start else (s < b)
            if mask is None:
                mask = strict | (s == b)
            else:
                mask = strict | ((s == b) & mask)
        return mask

    def _label_loc_multi(self, key, names: list):
        df = self._df
        if isinstance(key, slice):
            def as_tuple(x):
                if x is None:
                    return None
                return x if isinstance(x, tuple) else (x,)
            lo, hi = as_tuple(key.start), as_tuple(key.stop)
            mask = None
            if lo is not None:
                mask = self._lex_bound_mask(lo, names, True)
            if hi is not None:
                m2 = self._lex_bound_mask(hi, names, False)
                mask = m2 if mask is None else (mask & m2)
            if mask is None:
                return df
            out = df._wrap(filter_table(df._table, _series_flag(mask)))
        elif isinstance(key, list):
            labels = [k if isinstance(k, tuple) else (k,) for k in key]
            masks = [self._multi_eq_mask(lb, names) for lb in labels]
            # presence checks must ignore PADDING rows (their contents are
            # unspecified — post-concat padding can hold stale values that
            # fake a hit); ONE host sync covers every label
            vc = df._table.valid_counts
            cap = max(df._table.capacity, 1)
            live = np.concatenate(
                [np.arange(cap) < int(vc[s]) for s in range(len(vc))])
            hits = np.asarray(jnp.stack(
                [jnp.sum(_series_flag(m) & live) for m in masks]))
            for lb, h in zip(labels, hits):
                if int(h) == 0:
                    raise CylonKeyError(f"label {lb!r} not found in index")
            mask = masks[0]
            for m in masks[1:]:
                mask = mask | m
            out = df._wrap(filter_table(df._table, _series_flag(mask)))
        else:
            labels = key if isinstance(key, tuple) else (key,)
            if len(labels) > len(names):
                raise CylonKeyError(
                    f"label tuple {labels!r} longer than the "
                    f"{len(names)}-level index")
            mask = self._multi_eq_mask(labels, names)
            out = df._wrap(filter_table(df._table, _series_flag(mask)))
            if len(out) == 0:
                raise CylonKeyError(f"label {key!r} not found in index")
        out._index = df._index
        out._index_drop = df._index_drop
        return out


def _series_flag(mask):
    from ..relational.common import valid_flag
    return valid_flag(mask.column)


class ILocIndexer:
    """df.iloc[pos] — global positional selection (reference
    ArrowILocIndexer, indexer.hpp:123)."""

    def __init__(self, df: "DataFrame"):
        self._df = df

    def __getitem__(self, key):
        cols = None
        if isinstance(key, tuple) and len(key) == 2:
            key, cols = key
        df = self._df
        n = len(df)
        if isinstance(key, slice):
            start, stop, step = key.indices(n)
            if step != 1:
                raise CylonIndexError("iloc step not supported")
            out = df._wrap(slice_table(df._table, start, stop - start))
        elif np.isscalar(key):
            i = int(key)
            if i < 0:
                i += n
            if not (0 <= i < n):
                raise CylonIndexError(f"position {key} out of range [0,{n})")
            out = df._wrap(slice_table(df._table, i, 1))
        else:
            # positional list: pandas order/duplicate semantics — rows come
            # back in the REQUESTED order, duplicates repeated.  Device work
            # slices contiguous runs of the sorted unique positions (not one
            # launch per position); the k selected rows are then reordered
            # host-side and re-ingested.
            pos = [int(p) + (n if int(p) < 0 else 0) for p in key]
            if any(not 0 <= p < n for p in pos):
                raise CylonIndexError(f"positions out of range [0,{n})")
            if not pos:
                out = df[0:0]
            else:
                from ..relational import concat_tables
                uniq = sorted(set(pos))
                runs = []
                lo = prev = uniq[0]
                for p in uniq[1:]:
                    if p == prev + 1:
                        prev = p
                        continue
                    runs.append((lo, prev - lo + 1))
                    lo = prev = p
                runs.append((lo, prev - lo + 1))
                parts = [slice_table(df._table, o, ln) for o, ln in runs]
                picked = parts[0] if len(parts) == 1 else concat_tables(parts)
                order = {p: i for i, p in enumerate(uniq)}
                sel = np.asarray([order[p] for p in pos], np.int64)
                # dtype-faithful host reorder (a pandas round-trip would
                # stringify nullable int/bool/datetime columns)
                from ..core.column import Column
                from ..core.table import Table
                w = picked.env.world_size
                cap = picked.capacity
                gpos = np.concatenate(
                    [np.arange(i * cap, i * cap + int(picked.valid_counts[i]))
                     for i in range(w)]) if cap else np.zeros(0, np.int64)
                host_cols = {}
                for cn, c in picked.columns.items():
                    data = np.asarray(c.data)[gpos][sel]
                    v = (np.asarray(c.validity)[gpos][sel]
                         if c.validity is not None else None)
                    host_cols[cn] = Column(data, c.type, v, c.dictionary)
                out = df._wrap(Table.from_host_columns(host_cols, df.env))
        out._index = df._index
        out._index_drop = df._index_drop
        if cols is not None:
            cols = [cols] if isinstance(cols, str) else list(cols)
            out = out._wrap(out._table.project(cols))
            out._index = None
        return out
