"""Indexing subsystem: row-label index + loc/iloc indexers.

TPU-native equivalent of the reference's indexing layer
(cpp/src/cylon/indexing/index.hpp:36 IndexingType RANGE/LINEAR/HASH...,
indexer.hpp:76 ``ArrowLocIndexer`` / :123 ``ArrowILocIndexer`` with pandas
loc/iloc semantics; table.hpp:164-169 Set/Get/ResetArrowIndex).

The reference attaches hash/linear index structures to the table for O(1)
label lookup; on TPU a label lookup is a vectorized compare/filter over the
(sharded) index column — no side structure beats a fused VPU scan, so
``IndexingType`` collapses to "which column is the index" plus a RANGE
default.  loc slices use the reference's contract: both endpoints inclusive.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any

import jax.numpy as jnp
import numpy as np

from ..core.dtypes import LogicalType
from ..relational import filter_table, slice_table
from ..status import CylonIndexError, CylonKeyError

if TYPE_CHECKING:  # pragma: no cover
    from ..frame import DataFrame

RANGE_INDEX = "__range__"


def _label_mask(col, labels) -> Any:
    """Device bool mask: row's index value in ``labels``."""
    if col.type == LogicalType.STRING:
        codes = []
        d = col.dictionary
        for lb in labels:
            pos = int(np.searchsorted(d, lb))
            if pos < len(d) and d[pos] == lb:
                codes.append(pos)
        if not codes:
            return jnp.zeros(col.data.shape[0], bool)
        return jnp.isin(col.data, jnp.asarray(codes, col.data.dtype))
    arr = jnp.asarray(np.asarray(labels).astype(np.dtype(col.data.dtype)))
    return jnp.isin(col.data, arr)


class LocIndexer:
    """df.loc[labels] / df.loc[lo:hi] (inclusive) / df.loc[labels, cols]
    (reference ArrowLocIndexer modes, indexer.hpp:76)."""

    def __init__(self, df: "DataFrame"):
        self._df = df

    def __getitem__(self, key):
        cols = None
        if isinstance(key, tuple) and len(key) == 2:
            key, cols = key
        df = self._df
        name = df._index
        if name is None or name == RANGE_INDEX:
            out = self._range_loc(key)
        else:
            out = self._label_loc(key, name)
        if cols is not None:
            cols = [cols] if isinstance(cols, str) else list(cols)
            keep = ([df._index] if df._index not in (None, RANGE_INDEX) else []
                    ) + cols
            out = out._wrap(out._table.project(
                [c for c in out.columns if c in set(keep)]))
            out._index = df._index
        return out

    def _range_loc(self, key):
        df = self._df
        if isinstance(key, slice):
            lo = 0 if key.start is None else int(key.start)
            hi = len(df) - 1 if key.stop is None else int(key.stop)
            return df[lo:hi + 1]  # loc slices are inclusive
        if np.isscalar(key):
            return df[int(key):int(key) + 1]
        labels = list(key)
        # positional filter over the implicit range index
        return df.iloc[labels]

    def _label_loc(self, key, name: str):
        df = self._df
        col = df._table.column(name)
        if isinstance(key, slice):
            # inclusive label range: value >= start & value <= stop
            s = df[name]
            mask = None
            if key.start is not None:
                mask = (s >= key.start)
            if key.stop is not None:
                m2 = (s <= key.stop)
                mask = m2 if mask is None else (mask & m2)
            if mask is None:
                return df
            out = df._wrap(filter_table(df._table, mask.column.data))
            out._index = df._index
            return out
        labels = [key] if np.isscalar(key) or isinstance(key, str) else list(key)
        mask = _label_mask(col, labels)
        out = df._wrap(filter_table(df._table, mask))
        if out._table.row_count == 0:
            raise CylonKeyError(f"labels {labels!r} not found in index")
        out._index = df._index
        return out


class ILocIndexer:
    """df.iloc[pos] — global positional selection (reference
    ArrowILocIndexer, indexer.hpp:123)."""

    def __init__(self, df: "DataFrame"):
        self._df = df

    def __getitem__(self, key):
        cols = None
        if isinstance(key, tuple) and len(key) == 2:
            key, cols = key
        df = self._df
        n = len(df)
        if isinstance(key, slice):
            start, stop, step = key.indices(n)
            if step != 1:
                raise CylonIndexError("iloc step not supported")
            out = df._wrap(slice_table(df._table, start, stop - start))
        elif np.isscalar(key):
            i = int(key)
            if i < 0:
                i += n
            if not (0 <= i < n):
                raise CylonIndexError(f"position {key} out of range [0,{n})")
            out = df._wrap(slice_table(df._table, i, 1))
        else:
            # positional list: filter on global position
            pos = sorted(int(p) + (n if p < 0 else 0) for p in key)
            if pos and not (0 <= pos[0] and pos[-1] < n):
                raise CylonIndexError(f"positions out of range [0,{n})")
            from ..relational import concat_tables
            parts = [slice_table(df._table, p, 1) for p in pos]
            out = df._wrap(concat_tables(parts)) if parts else df[0:0]
        out._index = df._index
        if cols is not None:
            cols = [cols] if isinstance(cols, str) else list(cols)
            out = out._wrap(out._table.project(cols))
            out._index = None
        return out
