"""TPC-H subset: data generator + a 21-query suite on the DataFrame API
(Q1 Q3 Q4 Q5 Q6 Q7 Q8 Q9 Q10 Q11 Q12 Q13 Q14 Q15 Q16 Q17 Q18 Q19 Q20
Q21 Q22).

The reference validated its relational engine on TPC-xBB / TPC-H-style
workloads (docs/docs/release/cylon_release_0.4.0.md; BASELINE.md config 4:
SF10 Q3/Q5 on 8 ranks).  This module provides:

* :func:`generate_tables` — a numpy dbgen-alike for the eight tables the
  suite touches (customer, orders, lineitem, supplier, nation, region,
  part, partsupp) with the standard cardinalities
  (150K/1.5M/~6M/10K/25/5/200K/800K rows x SF) and the value distributions the queries are sensitive to
  (mktsegment 5-way uniform, order dates uniform over 1992-1998, discount
  0-0.10, one region in 5, closed p_type/brand/container vocabularies);
* ``q1``..``q19`` — the queries written against the public DataFrame API
  (filter -> merge -> arithmetic -> groupby -> sort -> head), exactly how
  a user would port them — together they cover join+conditional-agg
  (Q14), groupby-HAVING semi-join (Q18), disjunctive multi-attribute
  filters (Q19), the round-5 NOT-EXISTS family on true SEMI/ANTI joins
  (Q16 Q21 Q22), — round 7, for the serving tier's mixed-traffic
  plan shapes — scalar-subquery HAVING (Q11), an aggregate view with a
  scalar-max equi-select (Q15) and a correlated-avg subquery (Q17), and
  — round 9, alongside the streaming ingest tier — Q20's nested
  IN-subqueries over streaming-friendly partsupp semantics, — round
  12, the query profiler's acceptance workload — Q13's customer
  count-distribution (LEFT join + two-level groupby, its EXPLAIN
  ANALYZE plan recorded in the bench detail), and — round 13, alongside
  the out-of-core disk tier — Q9's product-type profit: six tables,
  five joins (one two-key), the suite's widest join working set and the
  disk tier's natural TPC-H exerciser, and — round 14, alongside the
  adaptive skew-split join route — Q7's volume shipping: lineitem ⋈
  supplier/customer ⋈ nation×2 on a 25-value nation key, where EVERY
  key is a heavy hitter and the naturally skew-shaped Q18 (lineitem
  groupby-HAVING + 3-way join) gets its EXPLAIN ANALYZE plan recorded
  in the bench detail beside Q13's, and — round 15, alongside the
  multi-slice topology tier — Q8's national market share: seven tables
  chained through six shuffle-backed joins, the suite's widest
  cross-slice working set, its EXPLAIN ANALYZE plan recorded in the
  bench detail as the two-hop route's query-level audit
  (docs/topology.md);
* ``q*_pandas`` — the pandas oracles;
* :func:`bench_tpch` — the ``bench.py --tpch`` entry.

Dates are datetime64[ns] columns; scalar date predicates compare against
integer nanoseconds (``_ts``) since epoch.
"""

from __future__ import annotations

import time

import numpy as np
import pandas as pd

SEGMENTS = np.asarray(["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD",
                       "MACHINERY"])
REGIONS = np.asarray(["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"])
NATIONS = np.asarray(
    ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
     "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ", "JAPAN",
     "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU", "CHINA", "ROMANIA",
     "SAUDI ARABIA", "VIETNAM", "RUSSIA", "UNITED KINGDOM", "UNITED STATES"])
#: n_nationkey -> n_regionkey per the TPC-H spec nation table
NATION_REGION = np.asarray([0, 1, 1, 1, 4, 0, 3, 3, 2, 2, 4, 4, 2, 4, 0, 0,
                            0, 1, 2, 3, 4, 2, 3, 3, 1])
PRIORITIES = np.asarray(["1-URGENT", "2-HIGH", "3-MEDIUM",
                         "4-NOT SPECIFIED", "5-LOW"])
SHIPMODES = np.asarray(["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP",
                        "TRUCK"])
SHIPINSTRUCT = np.asarray(["COLLECT COD", "DELIVER IN PERSON", "NONE",
                           "TAKE BACK RETURN"])
PTYPES = np.asarray(["PROMO ANODIZED", "PROMO BURNISHED", "PROMO PLATED",
                     "STANDARD PLATED", "ECONOMY BRUSHED",
                     "MEDIUM POLISHED"])
PROMO_TYPES = tuple(t for t in PTYPES if t.startswith("PROMO"))
BRANDS = np.asarray([f"Brand#{i}{j}" for i in range(1, 6)
                     for j in range(1, 6)])
CONTAINERS = np.asarray([f"{s} {c}" for s in ("SM", "MED", "LG", "JUMBO",
                                              "WRAP")
                         for c in ("CASE", "BOX", "BAG", "JAR", "PKG",
                                   "PACK", "CAN", "DRUM")])
#: closed p_name vocabulary (Q20's ``p_name LIKE 'forest%'`` becomes an
#: exact-value IN over the forest-prefixed entries — the engine has no
#: device-side substring, same documented simplification as Q22's phone
#: prefix)
PNAME_ADJ = ("almond", "antique", "azure", "forest", "frosted", "lavender")
PNAME_NOUN = ("beige", "blush", "cream", "linen", "misty")
PNAMES = np.asarray([f"{a} {n}" for a in PNAME_ADJ for n in PNAME_NOUN])


def _ts(date: str) -> int:
    return int(pd.Timestamp(date).value)


def generate_pandas(scale: float = 0.01, seed: int = 0) -> dict:
    """Host-side table generation (pandas dict) at TPC-H scale ``scale``."""
    rng = np.random.default_rng(seed)
    n_cust = max(int(150_000 * scale), 10)
    n_ord = max(int(1_500_000 * scale), 40)
    n_supp = max(int(10_000 * scale), 5)
    lines_per_order = rng.integers(1, 8, n_ord)
    n_line = int(lines_per_order.sum())

    day = 24 * 3600 * 1_000_000_000
    d0 = _ts("1992-01-01")
    span = (_ts("1998-08-02") - d0) // day

    customer = pd.DataFrame({
        "c_custkey": np.arange(n_cust, dtype=np.int64),
        "c_name": np.char.add("Customer#",
                              np.arange(n_cust).astype(np.str_)),
        "c_mktsegment": SEGMENTS[rng.integers(0, len(SEGMENTS), n_cust)],
        "c_nationkey": rng.integers(0, 25, n_cust).astype(np.int64),
        "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_cust), 2),
    })
    orders = pd.DataFrame({
        "o_orderkey": np.arange(n_ord, dtype=np.int64),
        "o_custkey": rng.integers(0, n_cust, n_ord).astype(np.int64),
        "o_orderdate": (d0 + rng.integers(0, span, n_ord) * day
                        ).astype("datetime64[ns]"),
        "o_shippriority": np.zeros(n_ord, dtype=np.int64),
        "o_orderpriority": PRIORITIES[rng.integers(0, len(PRIORITIES),
                                                   n_ord)],
    })
    l_orderkey = np.repeat(orders["o_orderkey"].to_numpy(), lines_per_order)
    ship_delay = rng.integers(1, 122, n_line) * day
    shipdate = (np.repeat(orders["o_orderdate"].to_numpy(),
                          lines_per_order).astype(np.int64)
                + ship_delay).astype("datetime64[ns]")
    commitdate = (shipdate.astype(np.int64)
                  + rng.integers(-30, 61, n_line) * day
                  ).astype("datetime64[ns]")
    receiptdate = (shipdate.astype(np.int64)
                   + rng.integers(1, 31, n_line) * day
                   ).astype("datetime64[ns]")
    # returnflag/linestatus per the spec's date rules: lines shipped after
    # the dataset's currentdate-ish cutoff are still Open/None, earlier
    # lines are Fulfilled and split A/R
    cutoff = np.datetime64("1995-06-17")
    open_line = shipdate > cutoff
    ar = rng.integers(0, 2, n_line)
    lineitem = pd.DataFrame({
        "l_orderkey": l_orderkey.astype(np.int64),
        "l_suppkey": rng.integers(0, n_supp, n_line).astype(np.int64),
        "l_quantity": rng.integers(1, 51, n_line).astype(np.int64),
        "l_extendedprice": np.round(rng.uniform(900.0, 105_000.0, n_line), 2),
        "l_discount": np.round(rng.integers(0, 11, n_line) * 0.01, 2),
        "l_tax": np.round(rng.integers(0, 9, n_line) * 0.01, 2),
        "l_returnflag": np.where(open_line, "N", np.where(ar == 0, "A", "R")),
        "l_linestatus": np.where(open_line, "O", "F"),
        "l_shipdate": shipdate,
        "l_commitdate": commitdate,
        "l_receiptdate": receiptdate,
        "l_shipmode": SHIPMODES[rng.integers(0, len(SHIPMODES), n_line)],
    })
    supplier = pd.DataFrame({
        "s_suppkey": np.arange(n_supp, dtype=np.int64),
        "s_nationkey": rng.integers(0, 25, n_supp).astype(np.int64),
    })
    # part + the Q14/Q18/Q19 columns draw from an INDEPENDENT stream so the
    # original six tables stay byte-identical across versions (recorded
    # results / regression baselines do not shift)
    rng2 = np.random.default_rng(seed + 104729)
    n_part = max(int(200_000 * scale), 8)
    part = pd.DataFrame({
        "p_partkey": np.arange(n_part, dtype=np.int64),
        "p_type": PTYPES[rng2.integers(0, len(PTYPES), n_part)],
        "p_brand": BRANDS[rng2.integers(0, len(BRANDS), n_part)],
        "p_container": CONTAINERS[rng2.integers(0, len(CONTAINERS), n_part)],
        "p_size": rng2.integers(1, 51, n_part).astype(np.int64),
    })
    lineitem["l_partkey"] = rng2.integers(0, n_part, n_line).astype(np.int64)
    lineitem["l_shipinstruct"] = SHIPINSTRUCT[
        rng2.integers(0, len(SHIPINSTRUCT), n_line)]
    orders["o_totalprice"] = np.round(rng2.uniform(1_000.0, 500_000.0,
                                                   n_ord), 2)
    nation = pd.DataFrame({
        "n_nationkey": np.arange(25, dtype=np.int64),
        "n_name": NATIONS,
        "n_regionkey": NATION_REGION.astype(np.int64),
    })
    region = pd.DataFrame({
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": REGIONS,
    })
    # Q16/Q21/Q22 additions (round 5) draw from a THIRD independent stream
    # so every earlier table/column stays byte-identical (same regression-
    # baseline rule as the rng2 block above)
    rng3 = np.random.default_rng(seed + 7919)
    ps_partkey = np.repeat(part["p_partkey"].to_numpy(), 4)  # spec: 4/part
    partsupp = pd.DataFrame({
        "ps_partkey": ps_partkey.astype(np.int64),
        "ps_suppkey": rng3.integers(0, n_supp,
                                    len(ps_partkey)).astype(np.int64),
        "ps_availqty": rng3.integers(1, 10_000,
                                     len(ps_partkey)).astype(np.int64),
    })
    supplier["s_name"] = np.char.add("Supplier#",
                                     np.arange(n_supp).astype(np.str_))
    supplier["s_comment"] = np.where(rng3.random(n_supp) < 0.02,
                                     "Customer Complaints", "ok")
    # orderstatus: F when every line shipped by the cutoff, O when none,
    # else P — derived from the open_line flags per order (spec semantics)
    open_per_order = np.zeros(n_ord, np.int64)
    np.add.at(open_per_order, l_orderkey, open_line.astype(np.int64))
    orders["o_orderstatus"] = np.where(
        open_per_order == 0, "F",
        np.where(open_per_order == lines_per_order, "O", "P"))
    # Q22 uses substring(c_phone,1,2); phones here are generated with the
    # spec's countrycode+10 prefix AND the prefix is carried as its own
    # int column (the engine has no device-side substring — documented
    # simplification, the pandas oracle mirrors it)
    cntry = customer["c_nationkey"].to_numpy() + 10
    customer["c_phone"] = np.char.add(
        np.char.add(cntry.astype(np.str_), "-555-"),
        np.arange(n_cust).astype(np.str_))
    customer["c_cntrycode"] = cntry.astype(np.int64)
    # Q11 addition (round 7) draws from a FOURTH independent stream so
    # every earlier table/column stays byte-identical (same regression-
    # baseline rule as the rng2/rng3 blocks above)
    rng4 = np.random.default_rng(seed + 15485863)
    partsupp["ps_supplycost"] = np.round(
        rng4.uniform(1.0, 1000.0, len(ps_partkey)), 2)
    # Q20 addition (round 9) draws from a FIFTH independent stream so
    # every earlier table/column stays byte-identical (same regression-
    # baseline rule as the rng2/rng3/rng4 blocks above)
    rng5 = np.random.default_rng(seed + 32452843)
    part["p_name"] = PNAMES[rng5.integers(0, len(PNAMES), n_part)]
    # Q13 addition (round 12, the profiler's acceptance workload) draws
    # from a SIXTH independent stream, same regression-baseline rule.
    # o_comment is a closed two-value vocabulary: the spec's
    # `NOT LIKE '%special%requests%'` becomes an exact != over the
    # "special requests" entries (~5% of orders) — the same documented
    # substring simplification as Q22's phone prefix and Q20's p_name.
    rng6 = np.random.default_rng(seed + 86028121)
    orders["o_comment"] = np.where(rng6.random(n_ord) < 0.05,
                                   "special requests", "ok")
    # Q9 addition (round 13, the out-of-core tier's wide-join exerciser):
    # extract(year FROM o_orderdate) rides a DERIVED int column — no new
    # RNG draws, so every earlier table/column stays byte-identical (the
    # engine has no device-side date-part extraction; the same documented
    # simplification as Q22's phone-prefix column)
    orders["o_orderyear"] = orders["o_orderdate"].dt.year.astype(np.int64)
    # Q7 addition (round 14, the adaptive skew-split route's nation-key
    # exerciser): extract(year FROM l_shipdate) rides a DERIVED int
    # column — no new RNG draws, every earlier table/column stays
    # byte-identical (the same regression-baseline rule and the same
    # documented date-part simplification as Q9's o_orderyear)
    lineitem["l_shipyear"] = lineitem["l_shipdate"].dt.year.astype(np.int64)
    return {"customer": customer, "orders": orders, "lineitem": lineitem,
            "supplier": supplier, "nation": nation, "region": region,
            "part": part, "partsupp": partsupp}


def generate_tables(scale: float = 0.01, env=None, seed: int = 0) -> dict:
    """Device-resident DataFrames for all six tables."""
    from .frame import DataFrame
    pdfs = generate_pandas(scale, seed)
    return {k: DataFrame(v, env=env) for k, v in pdfs.items()}


# ---------------------------------------------------------------------------
# Q1 — pricing summary report
# ---------------------------------------------------------------------------

def q1(dfs: dict, env=None, date: str = "1998-09-02"):
    """SELECT l_returnflag, l_linestatus, sum(l_quantity), sum(price),
    sum(price*(1-disc)), sum(price*(1-disc)*(1+tax)), avg(qty), avg(price),
    avg(disc), count(*) FROM lineitem WHERE l_shipdate <= :date GROUP BY
    l_returnflag, l_linestatus ORDER BY l_returnflag, l_linestatus."""
    line = dfs["lineitem"]
    l = line[line["l_shipdate"] <= _ts(date)]
    l["disc_price"] = l["l_extendedprice"] * (1.0 - l["l_discount"])
    l["charge"] = l["disc_price"] * (1.0 + l["l_tax"])
    g = (l.groupby(["l_returnflag", "l_linestatus"], env=env)
         .agg([("l_quantity", "sum"), ("l_extendedprice", "sum"),
               ("disc_price", "sum"), ("charge", "sum"),
               ("l_quantity", "mean"), ("l_extendedprice", "mean"),
               ("l_discount", "mean"), ("l_orderkey", "count")]))
    return g.sort_values(["l_returnflag", "l_linestatus"], env=env)


def q1_pandas(pdfs: dict, date: str = "1998-09-02") -> pd.DataFrame:
    l = pdfs["lineitem"]
    l = l[l.l_shipdate <= pd.Timestamp(date)].copy()
    l["disc_price"] = l.l_extendedprice * (1.0 - l.l_discount)
    l["charge"] = l.disc_price * (1.0 + l.l_tax)
    g = (l.groupby(["l_returnflag", "l_linestatus"], as_index=False)
         .agg(l_quantity_sum=("l_quantity", "sum"),
              l_extendedprice_sum=("l_extendedprice", "sum"),
              disc_price_sum=("disc_price", "sum"),
              charge_sum=("charge", "sum"),
              l_quantity_mean=("l_quantity", "mean"),
              l_extendedprice_mean=("l_extendedprice", "mean"),
              l_discount_mean=("l_discount", "mean"),
              l_orderkey_count=("l_orderkey", "count")))
    return g.sort_values(["l_returnflag", "l_linestatus"]) \
        .reset_index(drop=True)


# ---------------------------------------------------------------------------
# Q6 — revenue-change forecast
# ---------------------------------------------------------------------------

def q6(dfs: dict, env=None, date_lo: str = "1994-01-01",
       date_hi: str = "1995-01-01", discount: float = 0.06,
       quantity: int = 24):
    """SELECT sum(l_extendedprice*l_discount) AS revenue FROM lineitem
    WHERE l_shipdate >= :lo AND l_shipdate < :hi AND l_discount BETWEEN
    :d - 0.01 AND :d + 0.01 AND l_quantity < :q (the filter widens the
    BETWEEN bounds by 0.001 — float tolerance for the 0.01-grid discount
    values, matching the oracle)."""
    l = dfs["lineitem"]
    sel = ((l["l_shipdate"] >= _ts(date_lo)) & (l["l_shipdate"] < _ts(date_hi))
           & (l["l_discount"] >= discount - 0.011)
           & (l["l_discount"] <= discount + 0.011)
           & (l["l_quantity"] < quantity))
    f = l[sel]
    rev = f["l_extendedprice"] * f["l_discount"]
    return float(rev.sum())


def q6_pandas(pdfs: dict, date_lo: str = "1994-01-01",
              date_hi: str = "1995-01-01", discount: float = 0.06,
              quantity: int = 24) -> float:
    l = pdfs["lineitem"]
    sel = ((l.l_shipdate >= pd.Timestamp(date_lo))
           & (l.l_shipdate < pd.Timestamp(date_hi))
           & (l.l_discount >= discount - 0.011)
           & (l.l_discount <= discount + 0.011)
           & (l.l_quantity < quantity))
    f = l[sel]
    return float((f.l_extendedprice * f.l_discount).sum())


# ---------------------------------------------------------------------------
# Q3 — shipping priority
# ---------------------------------------------------------------------------

def q3(dfs: dict, env=None, segment: str = "BUILDING",
       date: str = "1995-03-15"):
    """SELECT l_orderkey, sum(l_extendedprice*(1-l_discount)) AS revenue,
    o_orderdate, o_shippriority FROM customer, orders, lineitem WHERE
    c_mktsegment = :segment AND c_custkey = o_custkey AND l_orderkey =
    o_orderkey AND o_orderdate < :date AND l_shipdate > :date GROUP BY
    l_orderkey, o_orderdate, o_shippriority ORDER BY revenue DESC,
    o_orderdate LIMIT 10."""
    cust = dfs["customer"]
    orders = dfs["orders"]
    line = dfs["lineitem"]
    t = _ts(date)

    c = cust[cust["c_mktsegment"] == segment]
    o = orders[orders["o_orderdate"] < t]
    l = line[line["l_shipdate"] > t]

    co = c.merge(o, left_on="c_custkey", right_on="o_custkey", env=env)
    col = co.merge(l, left_on="o_orderkey", right_on="l_orderkey", env=env)
    col["revenue"] = col["l_extendedprice"] * (1.0 - col["l_discount"])
    g = (col.groupby(["l_orderkey", "o_orderdate", "o_shippriority"],
                     env=env)[["revenue"]].sum())
    out = g.sort_values(["revenue", "o_orderdate"],
                        ascending=[False, True], env=env).head(10)
    return out[["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]]


def q3_pandas(pdfs: dict, segment: str = "BUILDING",
              date: str = "1995-03-15") -> pd.DataFrame:
    t = pd.Timestamp(date)
    c = pdfs["customer"]
    c = c[c.c_mktsegment == segment]
    o = pdfs["orders"]
    o = o[o.o_orderdate < t]
    l = pdfs["lineitem"]
    l = l[l.l_shipdate > t]
    j = c.merge(o, left_on="c_custkey", right_on="o_custkey") \
         .merge(l, left_on="o_orderkey", right_on="l_orderkey")
    j["revenue"] = j.l_extendedprice * (1.0 - j.l_discount)
    g = (j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"],
                   as_index=False)["revenue"].sum())
    g = g.sort_values(["revenue", "o_orderdate"],
                      ascending=[False, True]).head(10)
    return g[["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]] \
        .reset_index(drop=True)


# ---------------------------------------------------------------------------
# Q5 — local supplier volume
# ---------------------------------------------------------------------------

def q5(dfs: dict, env=None, region: str = "ASIA",
       date_lo: str = "1994-01-01", date_hi: str = "1995-01-01"):
    """SELECT n_name, sum(l_extendedprice*(1-l_discount)) AS revenue FROM
    customer, orders, lineitem, supplier, nation, region WHERE c_custkey =
    o_custkey AND l_orderkey = o_orderkey AND l_suppkey = s_suppkey AND
    c_nationkey = s_nationkey AND s_nationkey = n_nationkey AND n_regionkey
    = r_regionkey AND r_name = :region AND o_orderdate >= :lo AND
    o_orderdate < :hi GROUP BY n_name ORDER BY revenue DESC."""
    lo, hi = _ts(date_lo), _ts(date_hi)
    reg = dfs["region"]
    reg = reg[reg["r_name"] == region]
    nat = dfs["nation"].merge(reg, left_on="n_regionkey",
                              right_on="r_regionkey", env=env)
    sup = dfs["supplier"].merge(nat, left_on="s_nationkey",
                                right_on="n_nationkey", env=env)
    o = dfs["orders"]
    o = o[(o["o_orderdate"] >= lo) & (o["o_orderdate"] < hi)]
    co = dfs["customer"].merge(o, left_on="c_custkey", right_on="o_custkey",
                               env=env)
    col = co.merge(dfs["lineitem"], left_on="o_orderkey",
                   right_on="l_orderkey", env=env)
    # l_suppkey = s_suppkey AND c_nationkey = s_nationkey (two-column key)
    j = col.merge(sup, left_on=["l_suppkey", "c_nationkey"],
                  right_on=["s_suppkey", "s_nationkey"], env=env)
    j["revenue"] = j["l_extendedprice"] * (1.0 - j["l_discount"])
    g = j.groupby(["n_name"], env=env)[["revenue"]].sum()
    return g.sort_values("revenue", ascending=False,
                         env=env)[["n_name", "revenue"]]


def q5_pandas(pdfs: dict, region: str = "ASIA", date_lo: str = "1994-01-01",
              date_hi: str = "1995-01-01") -> pd.DataFrame:
    lo, hi = pd.Timestamp(date_lo), pd.Timestamp(date_hi)
    reg = pdfs["region"]
    reg = reg[reg.r_name == region]
    nat = pdfs["nation"].merge(reg, left_on="n_regionkey",
                               right_on="r_regionkey")
    sup = pdfs["supplier"].merge(nat, left_on="s_nationkey",
                                 right_on="n_nationkey")
    o = pdfs["orders"]
    o = o[(o.o_orderdate >= lo) & (o.o_orderdate < hi)]
    j = (pdfs["customer"].merge(o, left_on="c_custkey", right_on="o_custkey")
         .merge(pdfs["lineitem"], left_on="o_orderkey",
                right_on="l_orderkey")
         .merge(sup, left_on=["l_suppkey", "c_nationkey"],
                right_on=["s_suppkey", "s_nationkey"]))
    j["revenue"] = j.l_extendedprice * (1.0 - j.l_discount)
    g = j.groupby("n_name", as_index=False)["revenue"].sum()
    return g.sort_values("revenue", ascending=False)[
        ["n_name", "revenue"]].reset_index(drop=True)


# ---------------------------------------------------------------------------
# Q4 — order priority checking (EXISTS semi-join)
# ---------------------------------------------------------------------------

def q4(dfs: dict, env=None, date_lo: str = "1993-07-01",
       date_hi: str = "1993-10-01"):
    """SELECT o_orderpriority, count(*) AS order_count FROM orders WHERE
    o_orderdate >= :lo AND o_orderdate < :hi AND EXISTS (SELECT * FROM
    lineitem WHERE l_orderkey = o_orderkey AND l_commitdate <
    l_receiptdate) GROUP BY o_orderpriority ORDER BY o_orderpriority.
    The EXISTS is a semi-join: dedupe the qualifying lineitem order keys,
    then inner-merge (reference pattern: DistributedUnique + join)."""
    o = dfs["orders"]
    o = o[(o["o_orderdate"] >= _ts(date_lo))
          & (o["o_orderdate"] < _ts(date_hi))]
    l = dfs["lineitem"]
    l = l[l["l_commitdate"] < l["l_receiptdate"]]
    lk = l[["l_orderkey"]].drop_duplicates(env=env)
    j = o.merge(lk, left_on="o_orderkey", right_on="l_orderkey", env=env)
    g = (j.groupby(["o_orderpriority"], env=env)
         .agg([("o_orderkey", "count")]))
    out = g.sort_values("o_orderpriority", env=env)
    return out.rename({"o_orderkey_count": "order_count"})


def q4_pandas(pdfs: dict, date_lo: str = "1993-07-01",
              date_hi: str = "1993-10-01") -> pd.DataFrame:
    o = pdfs["orders"]
    o = o[(o.o_orderdate >= pd.Timestamp(date_lo))
          & (o.o_orderdate < pd.Timestamp(date_hi))]
    l = pdfs["lineitem"]
    lk = l[l.l_commitdate < l.l_receiptdate][["l_orderkey"]] \
        .drop_duplicates()
    j = o.merge(lk, left_on="o_orderkey", right_on="l_orderkey")
    g = (j.groupby("o_orderpriority", as_index=False)
         .agg(order_count=("o_orderkey", "count")))
    return g.sort_values("o_orderpriority").reset_index(drop=True)


# ---------------------------------------------------------------------------
# Q10 — returned item reporting
# ---------------------------------------------------------------------------

def q10(dfs: dict, env=None, date_lo: str = "1993-10-01",
        date_hi: str = "1994-01-01", limit: int = 20):
    """SELECT c_custkey, c_name, sum(l_extendedprice*(1-l_discount)) AS
    revenue, c_acctbal, n_name FROM customer, orders, lineitem, nation
    WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey AND
    o_orderdate >= :lo AND o_orderdate < :hi AND l_returnflag = 'R' AND
    c_nationkey = n_nationkey GROUP BY c_custkey, c_name, c_acctbal,
    n_name ORDER BY revenue DESC LIMIT 20."""
    o = dfs["orders"]
    o = o[(o["o_orderdate"] >= _ts(date_lo))
          & (o["o_orderdate"] < _ts(date_hi))]
    l = dfs["lineitem"]
    l = l[l["l_returnflag"] == "R"]
    co = dfs["customer"].merge(o, left_on="c_custkey", right_on="o_custkey",
                               env=env)
    col = co.merge(l, left_on="o_orderkey", right_on="l_orderkey", env=env)
    j = col.merge(dfs["nation"], left_on="c_nationkey",
                  right_on="n_nationkey", env=env)
    j["revenue"] = j["l_extendedprice"] * (1.0 - j["l_discount"])
    g = (j.groupby(["c_custkey", "c_name", "c_acctbal", "n_name"],
                   env=env)[["revenue"]].sum())
    out = g.sort_values(["revenue", "c_custkey"], ascending=[False, True],
                        env=env).head(limit)
    return out[["c_custkey", "c_name", "revenue", "c_acctbal", "n_name"]]


def q10_pandas(pdfs: dict, date_lo: str = "1993-10-01",
               date_hi: str = "1994-01-01", limit: int = 20) -> pd.DataFrame:
    o = pdfs["orders"]
    o = o[(o.o_orderdate >= pd.Timestamp(date_lo))
          & (o.o_orderdate < pd.Timestamp(date_hi))]
    l = pdfs["lineitem"]
    l = l[l.l_returnflag == "R"]
    j = (pdfs["customer"]
         .merge(o, left_on="c_custkey", right_on="o_custkey")
         .merge(l, left_on="o_orderkey", right_on="l_orderkey")
         .merge(pdfs["nation"], left_on="c_nationkey",
                right_on="n_nationkey"))
    j["revenue"] = j.l_extendedprice * (1.0 - j.l_discount)
    g = (j.groupby(["c_custkey", "c_name", "c_acctbal", "n_name"],
                   as_index=False)["revenue"].sum())
    g = g.sort_values(["revenue", "c_custkey"],
                      ascending=[False, True]).head(limit)
    return g[["c_custkey", "c_name", "revenue", "c_acctbal", "n_name"]] \
        .reset_index(drop=True)


# ---------------------------------------------------------------------------
# Q12 — shipping modes and order priority
# ---------------------------------------------------------------------------

def q12(dfs: dict, env=None, mode1: str = "MAIL", mode2: str = "SHIP",
        date_lo: str = "1994-01-01", date_hi: str = "1995-01-01"):
    """SELECT l_shipmode, sum(high_line_count), sum(low_line_count) FROM
    orders, lineitem WHERE o_orderkey = l_orderkey AND l_shipmode IN
    (:m1, :m2) AND l_commitdate < l_receiptdate AND l_shipdate <
    l_commitdate AND l_receiptdate >= :lo AND l_receiptdate < :hi GROUP BY
    l_shipmode ORDER BY l_shipmode; high = priority in (1-URGENT, 2-HIGH)."""
    l = dfs["lineitem"]
    sel = (_isin(l["l_shipmode"], [mode1, mode2])
           & (l["l_commitdate"] < l["l_receiptdate"])
           & (l["l_shipdate"] < l["l_commitdate"])
           & (l["l_receiptdate"] >= _ts(date_lo))
           & (l["l_receiptdate"] < _ts(date_hi)))
    lf = l[sel]
    j = lf.merge(dfs["orders"], left_on="l_orderkey", right_on="o_orderkey",
                 env=env)
    high = ((j["o_orderpriority"] == "1-URGENT")
            | (j["o_orderpriority"] == "2-HIGH"))
    j["high_line"] = high.astype("int64")
    j["low_line"] = (~high).astype("int64")
    g = (j.groupby(["l_shipmode"], env=env)
         .agg([("high_line", "sum"), ("low_line", "sum")]))
    out = g.sort_values("l_shipmode", env=env)
    return out.rename({"high_line_sum": "high_line_count",
                       "low_line_sum": "low_line_count"})


def q12_pandas(pdfs: dict, mode1: str = "MAIL", mode2: str = "SHIP",
               date_lo: str = "1994-01-01",
               date_hi: str = "1995-01-01") -> pd.DataFrame:
    l = pdfs["lineitem"]
    lf = l[(l.l_shipmode.isin([mode1, mode2]))
           & (l.l_commitdate < l.l_receiptdate)
           & (l.l_shipdate < l.l_commitdate)
           & (l.l_receiptdate >= pd.Timestamp(date_lo))
           & (l.l_receiptdate < pd.Timestamp(date_hi))]
    j = lf.merge(pdfs["orders"], left_on="l_orderkey",
                 right_on="o_orderkey")
    high = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    g = (j.assign(high_line=high.astype(np.int64),
                  low_line=(~high).astype(np.int64))
         .groupby("l_shipmode", as_index=False)
         .agg(high_line_count=("high_line", "sum"),
              low_line_count=("low_line", "sum")))
    return g.sort_values("l_shipmode").reset_index(drop=True)


# ---------------------------------------------------------------------------
# Q13 — customer distribution (LEFT join + two-level groupby)
# ---------------------------------------------------------------------------

def q13(dfs: dict, env=None, word: str = "special requests"):
    """SELECT c_count, count(*) AS custdist FROM (SELECT c_custkey,
    count(o_orderkey) AS c_count FROM customer LEFT OUTER JOIN orders ON
    c_custkey = o_custkey AND o_comment NOT LIKE '%special%requests%'
    GROUP BY c_custkey) GROUP BY c_count ORDER BY custdist DESC, c_count
    DESC.  The comment filter applies to the RIGHT side before the left
    join (filtering after would drop the no-order customers the query
    counts); o_comment is a closed vocabulary so NOT LIKE is an exact !=
    (documented generator simplification).  count(o_orderkey) counts
    NON-NULL keys only, so customers whose every order was filtered (or
    who never ordered) land in the c_count = 0 bucket — the left join's
    null extension is exactly what the count distribution measures.
    This is the profiler's acceptance workload: its EXPLAIN ANALYZE plan
    is recorded in the tpch bench JSON detail (docs/observability.md)."""
    o = dfs["orders"]
    o = o[o["o_comment"] != word][["o_custkey", "o_orderkey"]]
    j = dfs["customer"][["c_custkey"]].merge(
        o, how="left", left_on="c_custkey", right_on="o_custkey", env=env)
    per_cust = (j.groupby(["c_custkey"], env=env)
                .agg([("o_orderkey", "count")])
                .rename({"o_orderkey_count": "c_count"}))
    dist = (per_cust.groupby(["c_count"], env=env)
            .agg([("c_custkey", "count")])
            .rename({"c_custkey_count": "custdist"}))
    out = dist.sort_values(["custdist", "c_count"],
                           ascending=[False, False], env=env)
    return out[["c_count", "custdist"]]


def q13_pandas(pdfs: dict, word: str = "special requests") -> pd.DataFrame:
    o = pdfs["orders"]
    o = o[o.o_comment != word][["o_custkey", "o_orderkey"]]
    j = pdfs["customer"][["c_custkey"]].merge(
        o, how="left", left_on="c_custkey", right_on="o_custkey")
    per_cust = (j.groupby("c_custkey", as_index=False)
                .agg(c_count=("o_orderkey", "count")))
    dist = (per_cust.groupby("c_count", as_index=False)
            .agg(custdist=("c_custkey", "count")))
    return (dist.sort_values(["custdist", "c_count"],
                             ascending=[False, False])
            .reset_index(drop=True)[["c_count", "custdist"]])


# ---------------------------------------------------------------------------
# Q14 — promotion effect (join + conditional aggregate)
# ---------------------------------------------------------------------------

def q14(dfs: dict, env=None, date_lo: str = "1995-09-01",
        date_hi: str = "1995-10-01") -> float:
    """SELECT 100 * sum(case when p_type like 'PROMO%' then
    l_extendedprice*(1-l_discount) else 0 end) / sum(l_extendedprice*
    (1-l_discount)) FROM lineitem, part WHERE l_partkey = p_partkey AND
    l_shipdate >= :lo AND l_shipdate < :hi.  The LIKE prefix match is an
    isin over the generator's closed p_type vocabulary (PROMO_TYPES)."""
    l = dfs["lineitem"]
    l = l[(l["l_shipdate"] >= _ts(date_lo)) & (l["l_shipdate"] < _ts(date_hi))]
    j = l.merge(dfs["part"], left_on="l_partkey", right_on="p_partkey",
                env=env)
    rev = j["l_extendedprice"] * (1.0 - j["l_discount"])
    promo = _isin(j["p_type"], list(PROMO_TYPES))
    promo_rev = (promo.astype("float64") * rev).sum()
    total = rev.sum()
    return float(100.0 * promo_rev / total) if total else 0.0


def q14_pandas(pdfs: dict, date_lo: str = "1995-09-01",
               date_hi: str = "1995-10-01") -> float:
    l = pdfs["lineitem"]
    l = l[(l.l_shipdate >= pd.Timestamp(date_lo))
          & (l.l_shipdate < pd.Timestamp(date_hi))]
    j = l.merge(pdfs["part"], left_on="l_partkey", right_on="p_partkey")
    rev = j.l_extendedprice * (1.0 - j.l_discount)
    promo = j.p_type.str.startswith("PROMO")
    total = float(rev.sum())
    return float(100.0 * (rev * promo).sum() / total) if total else 0.0


# ---------------------------------------------------------------------------
# Q18 — large volume customer (groupby-HAVING semi-join)
# ---------------------------------------------------------------------------

def q18(dfs: dict, env=None, quantity: int = 300, limit: int = 100):
    """SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
    sum(l_quantity) FROM customer, orders, lineitem WHERE o_orderkey IN
    (SELECT l_orderkey FROM lineitem GROUP BY l_orderkey HAVING
    sum(l_quantity) > :q) AND c_custkey = o_custkey AND o_orderkey =
    l_orderkey GROUP BY c_name, c_custkey, o_orderkey, o_orderdate,
    o_totalprice ORDER BY o_totalprice DESC, o_orderdate LIMIT 100.
    The HAVING subquery is a groupby + filter + semi-join (reference
    pattern: DistributedHashGroupBy then DistributedJoin)."""
    l = dfs["lineitem"]
    big = l.groupby(["l_orderkey"], env=env).agg([("l_quantity", "sum")])
    big = big[big["l_quantity_sum"] > float(quantity)][["l_orderkey"]]
    o = dfs["orders"].merge(big, left_on="o_orderkey", right_on="l_orderkey",
                            env=env)
    co = dfs["customer"].merge(o, left_on="c_custkey", right_on="o_custkey",
                               env=env)
    j = co.merge(l, left_on="o_orderkey", right_on="l_orderkey", env=env)
    g = (j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                    "o_totalprice"], env=env)
         .agg([("l_quantity", "sum")]))
    out = g.sort_values(["o_totalprice", "o_orderdate"],
                        ascending=[False, True], env=env).head(limit)
    return out[["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                "o_totalprice", "l_quantity_sum"]]


def q18_pandas(pdfs: dict, quantity: int = 300,
               limit: int = 100) -> pd.DataFrame:
    l = pdfs["lineitem"]
    big = l.groupby("l_orderkey", as_index=False)["l_quantity"].sum()
    big = big[big.l_quantity > quantity][["l_orderkey"]]
    o = pdfs["orders"].merge(big, left_on="o_orderkey",
                             right_on="l_orderkey")
    j = (pdfs["customer"].merge(o, left_on="c_custkey", right_on="o_custkey")
         .merge(l, left_on="o_orderkey", right_on="l_orderkey"))
    g = (j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                    "o_totalprice"], as_index=False)
         .agg(l_quantity_sum=("l_quantity", "sum")))
    g = g.sort_values(["o_totalprice", "o_orderdate"],
                      ascending=[False, True]).head(limit)
    return g[["c_name", "c_custkey", "o_orderkey", "o_orderdate",
              "o_totalprice", "l_quantity_sum"]].reset_index(drop=True)


# ---------------------------------------------------------------------------
# Q19 — discounted revenue (disjunctive multi-attribute filters)
# ---------------------------------------------------------------------------

def _isin(series, values):
    out = series == values[0]
    for v in values[1:]:
        out = out | (series == v)
    return out


def q19(dfs: dict, env=None, brand1: str = "Brand#12",
        brand2: str = "Brand#23", brand3: str = "Brand#34",
        q1_: int = 1, q2_: int = 10, q3_: int = 20) -> float:
    """SELECT sum(l_extendedprice*(1-l_discount)) FROM lineitem, part WHERE
    three disjunctive (brand, container-set, quantity-range, size-range)
    branches AND l_shipmode IN (AIR, REG AIR) AND l_shipinstruct =
    'DELIVER IN PERSON' — the classic disjunctive-predicate stressor: one
    join, then one boolean tree over five columns."""
    l = dfs["lineitem"]
    l = l[_isin(l["l_shipmode"], ["AIR", "REG AIR"])
          & (l["l_shipinstruct"] == "DELIVER IN PERSON")]
    j = l.merge(dfs["part"], left_on="l_partkey", right_on="p_partkey",
                env=env)
    qty, size = j["l_quantity"], j["p_size"]
    b1 = ((j["p_brand"] == brand1)
          & _isin(j["p_container"], ["SM CASE", "SM BOX", "SM PACK",
                                     "SM PKG"])
          & (qty >= q1_) & (qty <= q1_ + 10) & (size >= 1) & (size <= 5))
    b2 = ((j["p_brand"] == brand2)
          & _isin(j["p_container"], ["MED BAG", "MED BOX", "MED PKG",
                                     "MED PACK"])
          & (qty >= q2_) & (qty <= q2_ + 10) & (size >= 1) & (size <= 10))
    b3 = ((j["p_brand"] == brand3)
          & _isin(j["p_container"], ["LG CASE", "LG BOX", "LG PACK",
                                     "LG PKG"])
          & (qty >= q3_) & (qty <= q3_ + 10) & (size >= 1) & (size <= 15))
    f = j[b1 | b2 | b3]
    rev = f["l_extendedprice"] * (1.0 - f["l_discount"])
    return float(rev.sum())


def q19_pandas(pdfs: dict, brand1: str = "Brand#12", brand2: str = "Brand#23",
               brand3: str = "Brand#34", q1_: int = 1, q2_: int = 10,
               q3_: int = 20) -> float:
    l = pdfs["lineitem"]
    l = l[l.l_shipmode.isin(["AIR", "REG AIR"])
          & (l.l_shipinstruct == "DELIVER IN PERSON")]
    j = l.merge(pdfs["part"], left_on="l_partkey", right_on="p_partkey")
    b1 = ((j.p_brand == brand1)
          & j.p_container.isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
          & j.l_quantity.between(q1_, q1_ + 10)
          & j.p_size.between(1, 5))
    b2 = ((j.p_brand == brand2)
          & j.p_container.isin(["MED BAG", "MED BOX", "MED PKG", "MED PACK"])
          & j.l_quantity.between(q2_, q2_ + 10)
          & j.p_size.between(1, 10))
    b3 = ((j.p_brand == brand3)
          & j.p_container.isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
          & j.l_quantity.between(q3_, q3_ + 10)
          & j.p_size.between(1, 15))
    f = j[b1 | b2 | b3]
    return float((f.l_extendedprice * (1.0 - f.l_discount)).sum())


# ---------------------------------------------------------------------------
# Q16 — parts/supplier relationship (ANTI join vs complained suppliers)
# ---------------------------------------------------------------------------

def q16(dfs: dict, env=None, brand: str = "Brand#45",
        sizes=(49, 14, 23, 45, 19, 3, 36, 9)):
    """SELECT p_brand, p_type, p_size, count(DISTINCT ps_suppkey) AS
    supplier_cnt FROM partsupp, part WHERE p_partkey = ps_partkey AND
    p_brand <> :brand AND p_type NOT LIKE 'PROMO%' AND p_size IN :sizes
    AND ps_suppkey NOT IN (SELECT s_suppkey FROM supplier WHERE s_comment
    LIKE '%Customer%Complaints%') GROUP BY p_brand, p_type, p_size ORDER
    BY supplier_cnt DESC, p_brand, p_type, p_size.  The NOT IN is an ANTI
    join; NOT LIKE maps to the generator's type vocabulary."""
    p = dfs["part"]
    p = p[(p["p_brand"] != brand)
          & ~_isin(p["p_type"], list(PROMO_TYPES))
          & _isin(p["p_size"], list(sizes))]
    ps = dfs["partsupp"].merge(p, left_on="ps_partkey",
                               right_on="p_partkey", env=env)
    s = dfs["supplier"]
    bad = s[s["s_comment"] == "Customer Complaints"]
    ps = ps.merge(bad[["s_suppkey"]], how="anti", left_on="ps_suppkey",
                  right_on="s_suppkey", env=env)
    g = (ps.groupby(["p_brand", "p_type", "p_size"], env=env)
         .agg([("ps_suppkey", "nunique")]))
    g = g.rename({"ps_suppkey_nunique": "supplier_cnt"})
    return g.sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                         ascending=[False, True, True, True], env=env)


def q16_pandas(pdfs: dict, brand: str = "Brand#45",
               sizes=(49, 14, 23, 45, 19, 3, 36, 9)) -> pd.DataFrame:
    p = pdfs["part"]
    p = p[(p.p_brand != brand) & ~p.p_type.isin(list(PROMO_TYPES))
          & p.p_size.isin(list(sizes))]
    ps = pdfs["partsupp"].merge(p, left_on="ps_partkey",
                                right_on="p_partkey")
    bad = set(pdfs["supplier"][pdfs["supplier"].s_comment ==
                               "Customer Complaints"].s_suppkey)
    ps = ps[~ps.ps_suppkey.isin(bad)]
    g = (ps.groupby(["p_brand", "p_type", "p_size"], as_index=False)
         .agg(supplier_cnt=("ps_suppkey", "nunique")))
    return g.sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                         ascending=[False, True, True, True]) \
        .reset_index(drop=True)


# ---------------------------------------------------------------------------
# Q21 — suppliers who kept orders waiting (SEMI + SEMI-on-condition)
# ---------------------------------------------------------------------------

def q21(dfs: dict, env=None, nation: str = "SAUDI ARABIA",
        limit: int = 100):
    """SELECT s_name, count(*) AS numwait FROM supplier, lineitem l1,
    orders, nation WHERE s_suppkey = l1.l_suppkey AND o_orderkey =
    l1.l_orderkey AND o_orderstatus = 'F' AND l1.l_receiptdate >
    l1.l_commitdate AND EXISTS (l2: same order, other supplier) AND NOT
    EXISTS (l3: same order, other supplier, late) AND s_nationkey =
    n_nationkey AND n_name = :nation GROUP BY s_name ORDER BY numwait
    DESC, s_name LIMIT 100.

    The correlated EXISTS pair decomposes into per-order supplier
    statistics + SEMI joins: an order qualifies for l1's supplier iff it
    has >= 2 distinct suppliers overall and EXACTLY ONE distinct late
    supplier (l1's own)."""
    l = dfs["lineitem"]
    late = l[l["l_receiptdate"] > l["l_commitdate"]]
    o = dfs["orders"]
    of = o[o["o_orderstatus"] == "F"][["o_orderkey"]]
    # per-order distinct-supplier counts (all lines / late lines)
    nsupp = (l.groupby(["l_orderkey"], env=env)
             .agg([("l_suppkey", "nunique")]))
    multi = nsupp[nsupp["l_suppkey_nunique"] >= 2][["l_orderkey"]]
    nlate = (late.groupby(["l_orderkey"], env=env)
             .agg([("l_suppkey", "nunique")]))
    onelate = nlate[nlate["l_suppkey_nunique"] == 1][["l_orderkey"]]
    l1 = late.merge(of, left_on="l_orderkey", right_on="o_orderkey",
                    env=env)
    l1 = l1.merge(multi, how="semi", on="l_orderkey", env=env)
    l1 = l1.merge(onelate, how="semi", on="l_orderkey", env=env)
    s = dfs["supplier"].merge(dfs["nation"], left_on="s_nationkey",
                              right_on="n_nationkey", env=env)
    s = s[s["n_name"] == nation][["s_suppkey", "s_name"]]
    j = l1.merge(s, left_on="l_suppkey", right_on="s_suppkey", env=env)
    g = (j.groupby(["s_name"], env=env).agg([("l_orderkey", "count")])
         .rename({"l_orderkey_count": "numwait"}))
    return g.sort_values(["numwait", "s_name"],
                         ascending=[False, True], env=env).head(limit)


def q21_pandas(pdfs: dict, nation: str = "SAUDI ARABIA",
               limit: int = 100) -> pd.DataFrame:
    l = pdfs["lineitem"]
    late = l[l.l_receiptdate > l.l_commitdate]
    of = pdfs["orders"][pdfs["orders"].o_orderstatus == "F"][["o_orderkey"]]
    nsupp = l.groupby("l_orderkey")["l_suppkey"].nunique()
    multi = set(nsupp[nsupp >= 2].index)
    nlate = late.groupby("l_orderkey")["l_suppkey"].nunique()
    onelate = set(nlate[nlate == 1].index)
    l1 = late.merge(of, left_on="l_orderkey", right_on="o_orderkey")
    l1 = l1[l1.l_orderkey.isin(multi) & l1.l_orderkey.isin(onelate)]
    s = pdfs["supplier"].merge(pdfs["nation"], left_on="s_nationkey",
                               right_on="n_nationkey")
    s = s[s.n_name == nation][["s_suppkey", "s_name"]]
    j = l1.merge(s, left_on="l_suppkey", right_on="s_suppkey")
    g = (j.groupby("s_name", as_index=False)
         .agg(numwait=("l_orderkey", "count")))
    return g.sort_values(["numwait", "s_name"],
                         ascending=[False, True]).head(limit) \
        .reset_index(drop=True)


# ---------------------------------------------------------------------------
# Q9 — product type profit (the suite's WIDEST join working set)
# ---------------------------------------------------------------------------

def q9(dfs: dict, env=None, name_part: str = "misty"):
    """SELECT nation, o_year, sum(amount) AS sum_profit FROM (SELECT
    n_name AS nation, extract(year FROM o_orderdate) AS o_year,
    l_extendedprice*(1-l_discount) - ps_supplycost*l_quantity AS amount
    FROM part, supplier, lineitem, partsupp, orders, nation WHERE
    s_suppkey = l_suppkey AND ps_suppkey = l_suppkey AND ps_partkey =
    l_partkey AND p_partkey = l_partkey AND o_orderkey = l_orderkey AND
    s_nationkey = n_nationkey AND p_name LIKE '%:part%') GROUP BY
    nation, o_year ORDER BY nation, o_year DESC.

    Six tables, five joins — including the two-key
    (l_suppkey, l_partkey) ⋈ (ps_suppkey, ps_partkey) edge — over the
    largest fact table: the suite's widest join working set and the
    natural out-of-core exerciser (the disk tier's TPC-H acceptance
    query, docs/robustness.md "Disk tier & scan pushdown").  LIKE rides
    the closed p_name vocabulary as exact-value equality and
    extract(year) rides the generator's derived ``o_orderyear`` int
    column (documented simplifications; the pandas oracle uses real
    ``str.contains`` / ``dt.year``)."""
    p = dfs["part"]
    names = [v for v in PNAMES.tolist() if name_part in v]
    p = p[_isin(p["p_name"], names)][["p_partkey"]]
    j = dfs["lineitem"].merge(p, left_on="l_partkey", right_on="p_partkey",
                              env=env)
    ps = dfs["partsupp"][["ps_partkey", "ps_suppkey", "ps_supplycost"]]
    j = j.merge(ps, left_on=["l_partkey", "l_suppkey"],
                right_on=["ps_partkey", "ps_suppkey"], env=env)
    j = j.merge(dfs["supplier"][["s_suppkey", "s_nationkey"]],
                left_on="l_suppkey", right_on="s_suppkey", env=env)
    j = j.merge(dfs["orders"][["o_orderkey", "o_orderyear"]],
                left_on="l_orderkey", right_on="o_orderkey", env=env)
    j = j.merge(dfs["nation"][["n_nationkey", "n_name"]],
                left_on="s_nationkey", right_on="n_nationkey", env=env)
    j["amount"] = (j["l_extendedprice"] * (1.0 - j["l_discount"])
                   - j["ps_supplycost"] * j["l_quantity"].astype("float64"))
    g = (j.groupby(["n_name", "o_orderyear"], env=env)[["amount"]].sum()
         .rename({"amount": "sum_profit"}))
    out = g.sort_values(["n_name", "o_orderyear"],
                        ascending=[True, False], env=env)
    return out[["n_name", "o_orderyear", "sum_profit"]]


def q9_pandas(pdfs: dict, name_part: str = "misty") -> pd.DataFrame:
    p = pdfs["part"]
    p = p[p.p_name.str.contains(name_part)][["p_partkey"]]
    j = (pdfs["lineitem"]
         .merge(p, left_on="l_partkey", right_on="p_partkey")
         .merge(pdfs["partsupp"][["ps_partkey", "ps_suppkey",
                                  "ps_supplycost"]],
                left_on=["l_partkey", "l_suppkey"],
                right_on=["ps_partkey", "ps_suppkey"])
         .merge(pdfs["supplier"][["s_suppkey", "s_nationkey"]],
                left_on="l_suppkey", right_on="s_suppkey")
         .merge(pdfs["orders"][["o_orderkey", "o_orderdate"]],
                left_on="l_orderkey", right_on="o_orderkey")
         .merge(pdfs["nation"][["n_nationkey", "n_name"]],
                left_on="s_nationkey", right_on="n_nationkey"))
    j["o_orderyear"] = j.o_orderdate.dt.year.astype(np.int64)
    j["amount"] = (j.l_extendedprice * (1.0 - j.l_discount)
                   - j.ps_supplycost * j.l_quantity.astype(np.float64))
    g = (j.groupby(["n_name", "o_orderyear"], as_index=False)
         .agg(sum_profit=("amount", "sum")))
    return g.sort_values(["n_name", "o_orderyear"],
                         ascending=[True, False]).reset_index(drop=True)


# ---------------------------------------------------------------------------
# Q7 — volume shipping (nation-key joins: every key is a heavy hitter)
# ---------------------------------------------------------------------------

def q7(dfs: dict, env=None, nation1: str = "FRANCE",
       nation2: str = "GERMANY"):
    """SELECT supp_nation, cust_nation, l_year, sum(volume) AS revenue
    FROM (SELECT n1.n_name AS supp_nation, n2.n_name AS cust_nation,
    extract(year FROM l_shipdate) AS l_year, l_extendedprice *
    (1 - l_discount) AS volume FROM supplier, lineitem, orders, customer,
    nation n1, nation n2 WHERE s_suppkey = l_suppkey AND o_orderkey =
    l_orderkey AND c_custkey = o_custkey AND s_nationkey = n1.n_nationkey
    AND c_nationkey = n2.n_nationkey AND ((n1.n_name = :n1 AND n2.n_name
    = :n2) OR (n1.n_name = :n2 AND n2.n_name = :n1)) AND l_shipdate
    BETWEEN date '1995-01-01' AND date '1996-12-31') shipping GROUP BY
    supp_nation, cust_nation, l_year ORDER BY supp_nation, cust_nation,
    l_year.

    Round 14, the adaptive skew-split route's TPC-H exerciser
    (docs/skew.md): the supplier→nation and customer→nation joins run on
    a 25-value key — EVERY key is a heavy hitter under plain hash
    partitioning, the distribution shape the split + duplicate-broadcast
    route exists for.  The symmetric nation-pair disjunction collapses
    to ``s_nationkey != c_nationkey`` once both ends are restricted to
    the two nations; extract(year) rides the generator's derived
    ``l_shipyear`` int column (documented simplification; the pandas
    oracle uses real ``dt.year``)."""
    n = dfs["nation"][["n_nationkey", "n_name"]]
    n = n[_isin(n["n_name"], [nation1, nation2])]
    s = dfs["supplier"][["s_suppkey", "s_nationkey"]].merge(
        n, left_on="s_nationkey", right_on="n_nationkey", env=env)
    s = s.rename({"n_name": "supp_nation"})[
        ["s_suppkey", "s_nationkey", "supp_nation"]]
    c = dfs["customer"][["c_custkey", "c_nationkey"]].merge(
        n, left_on="c_nationkey", right_on="n_nationkey", env=env)
    c = c.rename({"n_name": "cust_nation"})[
        ["c_custkey", "c_nationkey", "cust_nation"]]
    l = dfs["lineitem"]
    l = l[(l["l_shipdate"] >= _ts("1995-01-01"))
          & (l["l_shipdate"] <= _ts("1996-12-31"))]
    l["volume"] = l["l_extendedprice"] * (1.0 - l["l_discount"])
    l = l[["l_orderkey", "l_suppkey", "l_shipyear", "volume"]]
    j = l.merge(s, left_on="l_suppkey", right_on="s_suppkey", env=env)
    j = j.merge(dfs["orders"][["o_orderkey", "o_custkey"]],
                left_on="l_orderkey", right_on="o_orderkey", env=env)
    j = j.merge(c, left_on="o_custkey", right_on="c_custkey", env=env)
    j = j[j["s_nationkey"] != j["c_nationkey"]]
    g = (j.groupby(["supp_nation", "cust_nation", "l_shipyear"], env=env)
         [["volume"]].sum().rename({"volume": "revenue"}))
    out = g.sort_values(["supp_nation", "cust_nation", "l_shipyear"],
                        env=env)
    return out[["supp_nation", "cust_nation", "l_shipyear", "revenue"]]


def q7_pandas(pdfs: dict, nation1: str = "FRANCE",
              nation2: str = "GERMANY") -> pd.DataFrame:
    n = pdfs["nation"][["n_nationkey", "n_name"]]
    n = n[n.n_name.isin([nation1, nation2])]
    s = pdfs["supplier"].merge(n, left_on="s_nationkey",
                               right_on="n_nationkey")
    s = s.rename(columns={"n_name": "supp_nation"})[
        ["s_suppkey", "s_nationkey", "supp_nation"]]
    c = pdfs["customer"].merge(n, left_on="c_nationkey",
                               right_on="n_nationkey")
    c = c.rename(columns={"n_name": "cust_nation"})[
        ["c_custkey", "c_nationkey", "cust_nation"]]
    l = pdfs["lineitem"]
    l = l[(l.l_shipdate >= pd.Timestamp("1995-01-01"))
          & (l.l_shipdate <= pd.Timestamp("1996-12-31"))].copy()
    l["volume"] = l.l_extendedprice * (1.0 - l.l_discount)
    l["l_shipyear"] = l.l_shipdate.dt.year.astype(np.int64)
    j = l.merge(s, left_on="l_suppkey", right_on="s_suppkey")
    j = j.merge(pdfs["orders"][["o_orderkey", "o_custkey"]],
                left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(c, left_on="o_custkey", right_on="c_custkey")
    j = j[j.s_nationkey != j.c_nationkey]
    g = (j.groupby(["supp_nation", "cust_nation", "l_shipyear"],
                   as_index=False).agg(revenue=("volume", "sum")))
    g = g.sort_values(["supp_nation", "cust_nation",
                       "l_shipyear"]).reset_index(drop=True)
    return g[["supp_nation", "cust_nation", "l_shipyear", "revenue"]]


# ---------------------------------------------------------------------------
# Q8 — national market share (the suite's widest join: 7 tables + region)
# ---------------------------------------------------------------------------

def q8(dfs: dict, env=None, nation: str = "BRAZIL",
       region: str = "AMERICA", ptype: str = "STANDARD PLATED"):
    """SELECT o_year, sum(case when nation = :nation then volume else 0
    end) / sum(volume) AS mkt_share FROM (SELECT extract(year FROM
    o_orderdate) AS o_year, l_extendedprice * (1 - l_discount) AS
    volume, n2.n_name AS nation FROM part, supplier, lineitem, orders,
    customer, nation n1, nation n2, region WHERE p_partkey = l_partkey
    AND s_suppkey = l_suppkey AND l_orderkey = o_orderkey AND o_custkey
    = c_custkey AND c_nationkey = n1.n_nationkey AND n1.n_regionkey =
    r_regionkey AND r_name = :region AND s_nationkey = n2.n_nationkey
    AND o_orderdate BETWEEN date '1995-01-01' AND date '1996-12-31' AND
    p_type = :ptype) all_nations GROUP BY o_year ORDER BY o_year.

    Round 15, the multi-slice topology tier's TPC-H exerciser
    (docs/topology.md): seven tables (part, supplier, lineitem, orders,
    customer, nation ×2, region) chained through SIX shuffle-backed
    joins — the widest cross-slice working set in the suite, every hop
    of which must stay bit-equal whichever route (flat vs two-hop)
    carries its exchanges.  ``extract(year)`` rides the generator's
    derived ``o_orderyear`` int column and ``p_type = :ptype`` the
    closed vocabulary (the same documented simplifications as Q9/Q7);
    the conditional numerator is the Q14 flag-times-value pattern."""
    p = dfs["part"][["p_partkey", "p_type"]]
    p = p[p["p_type"] == ptype]
    o = dfs["orders"][["o_orderkey", "o_custkey", "o_orderdate",
                       "o_orderyear"]]
    o = o[(o["o_orderdate"] >= _ts("1995-01-01"))
          & (o["o_orderdate"] <= _ts("1996-12-31"))]
    reg = dfs["region"]
    reg = reg[reg["r_name"] == region]
    n1 = dfs["nation"][["n_nationkey", "n_regionkey"]].merge(
        reg, left_on="n_regionkey", right_on="r_regionkey", env=env)
    c = dfs["customer"][["c_custkey", "c_nationkey"]].merge(
        n1, left_on="c_nationkey", right_on="n_nationkey", env=env)
    n2 = dfs["nation"][["n_nationkey", "n_name"]]
    s = dfs["supplier"][["s_suppkey", "s_nationkey"]].merge(
        n2, left_on="s_nationkey", right_on="n_nationkey", env=env)
    l = dfs["lineitem"][["l_orderkey", "l_partkey", "l_suppkey",
                         "l_extendedprice", "l_discount"]]
    j = l.merge(p, left_on="l_partkey", right_on="p_partkey", env=env)
    j = j.merge(o, left_on="l_orderkey", right_on="o_orderkey", env=env)
    j = j.merge(c, left_on="o_custkey", right_on="c_custkey", env=env)
    j = j.merge(s, left_on="l_suppkey", right_on="s_suppkey", env=env)
    j["volume"] = j["l_extendedprice"] * (1.0 - j["l_discount"])
    is_nation = j["n_name"] == nation
    j["nation_volume"] = is_nation.astype("float64") * j["volume"]
    g = (j.groupby(["o_orderyear"], env=env)
         [["volume", "nation_volume"]].sum())
    g["mkt_share"] = g["nation_volume"] / g["volume"]
    out = g.sort_values("o_orderyear", env=env)
    return out[["o_orderyear", "mkt_share"]]


def q8_pandas(pdfs: dict, nation: str = "BRAZIL",
              region: str = "AMERICA",
              ptype: str = "STANDARD PLATED") -> pd.DataFrame:
    p = pdfs["part"][["p_partkey", "p_type"]]
    p = p[p.p_type == ptype]
    o = pdfs["orders"][["o_orderkey", "o_custkey", "o_orderdate",
                        "o_orderyear"]]
    o = o[(o.o_orderdate >= pd.Timestamp("1995-01-01"))
          & (o.o_orderdate <= pd.Timestamp("1996-12-31"))]
    reg = pdfs["region"]
    reg = reg[reg.r_name == region]
    n1 = pdfs["nation"][["n_nationkey", "n_regionkey"]].merge(
        reg, left_on="n_regionkey", right_on="r_regionkey")
    c = pdfs["customer"][["c_custkey", "c_nationkey"]].merge(
        n1, left_on="c_nationkey", right_on="n_nationkey")
    s = pdfs["supplier"][["s_suppkey", "s_nationkey"]].merge(
        pdfs["nation"][["n_nationkey", "n_name"]],
        left_on="s_nationkey", right_on="n_nationkey")
    l = pdfs["lineitem"][["l_orderkey", "l_partkey", "l_suppkey",
                          "l_extendedprice", "l_discount"]]
    j = (l.merge(p, left_on="l_partkey", right_on="p_partkey")
         .merge(o, left_on="l_orderkey", right_on="o_orderkey")
         .merge(c, left_on="o_custkey", right_on="c_custkey")
         .merge(s, left_on="l_suppkey", right_on="s_suppkey"))
    j = j.copy()
    j["volume"] = j.l_extendedprice * (1.0 - j.l_discount)
    j["nation_volume"] = (j.n_name == nation).astype(np.float64) \
        * j["volume"]
    g = (j.groupby("o_orderyear", as_index=False)
         .agg(volume=("volume", "sum"),
              nation_volume=("nation_volume", "sum")))
    g["mkt_share"] = g.nation_volume / g.volume
    return (g.sort_values("o_orderyear").reset_index(drop=True)
            [["o_orderyear", "mkt_share"]])


# ---------------------------------------------------------------------------
# Q22 — global sales opportunity (ANTI join vs orders)
# ---------------------------------------------------------------------------

def q22(dfs: dict, env=None, codes=(13, 31, 23, 29, 30, 18, 17)):
    """SELECT cntrycode, count(*) AS numcust, sum(c_acctbal) AS totacctbal
    FROM customer WHERE cntrycode IN :codes AND c_acctbal > (SELECT
    avg(c_acctbal) FROM customer WHERE c_acctbal > 0 AND cntrycode IN
    :codes) AND NOT EXISTS (SELECT * FROM orders WHERE o_custkey =
    c_custkey) GROUP BY cntrycode ORDER BY cntrycode.  cntrycode =
    substring(c_phone,1,2) rides the generator's c_cntrycode int column
    (no device-side substring); the NOT EXISTS is an ANTI join."""
    c = dfs["customer"]
    c = c[_isin(c["c_cntrycode"], list(codes))]
    pos = c[c["c_acctbal"] > 0.0]
    avg_bal = float(pos["c_acctbal"].mean())
    c = c[c["c_acctbal"] > avg_bal]
    c = c.merge(dfs["orders"][["o_custkey"]], how="anti",
                left_on="c_custkey", right_on="o_custkey", env=env)
    g = (c.groupby(["c_cntrycode"], env=env)
         .agg([("c_custkey", "count"), ("c_acctbal", "sum")])
         .rename({"c_custkey_count": "numcust",
                  "c_acctbal_sum": "totacctbal"}))
    return g.sort_values("c_cntrycode", env=env)


def q22_pandas(pdfs: dict,
               codes=(13, 31, 23, 29, 30, 18, 17)) -> pd.DataFrame:
    c = pdfs["customer"]
    c = c[c.c_cntrycode.isin(list(codes))]
    avg_bal = float(c[c.c_acctbal > 0.0].c_acctbal.mean())
    c = c[c.c_acctbal > avg_bal]
    c = c[~c.c_custkey.isin(set(pdfs["orders"].o_custkey))]
    g = (c.groupby("c_cntrycode", as_index=False)
         .agg(numcust=("c_custkey", "count"),
              totacctbal=("c_acctbal", "sum")))
    return g.sort_values("c_cntrycode").reset_index(drop=True)


# ---------------------------------------------------------------------------
# Q11 — important stock identification (scalar-subquery HAVING)
# ---------------------------------------------------------------------------

def q11(dfs: dict, env=None, nation: str = "GERMANY",
        fraction: float = 0.0001):
    """SELECT ps_partkey, sum(ps_supplycost*ps_availqty) AS value FROM
    partsupp, supplier, nation WHERE ps_suppkey = s_suppkey AND
    s_nationkey = n_nationkey AND n_name = :nation GROUP BY ps_partkey
    HAVING value > :fraction * (SELECT sum(...) same predicate) ORDER BY
    value DESC.  The scalar subquery is the filtered aggregate's own
    total — computed once, threaded through as a host scalar."""
    n = dfs["nation"]
    n = n[n["n_name"] == nation]
    s = dfs["supplier"].merge(n, left_on="s_nationkey",
                              right_on="n_nationkey", env=env)
    ps = dfs["partsupp"].merge(s[["s_suppkey"]], left_on="ps_suppkey",
                               right_on="s_suppkey", env=env)
    ps["value"] = ps["ps_supplycost"] * ps["ps_availqty"].astype("float64")
    g = ps.groupby(["ps_partkey"], env=env)[["value"]].sum()
    total = float(g["value"].sum())
    out = g[g["value"] > fraction * total]
    return out.sort_values(["value", "ps_partkey"],
                           ascending=[False, True],
                           env=env)[["ps_partkey", "value"]]


def q11_pandas(pdfs: dict, nation: str = "GERMANY",
               fraction: float = 0.0001) -> pd.DataFrame:
    n = pdfs["nation"]
    n = n[n.n_name == nation]
    s = pdfs["supplier"].merge(n, left_on="s_nationkey",
                               right_on="n_nationkey")
    ps = pdfs["partsupp"].merge(s[["s_suppkey"]], left_on="ps_suppkey",
                                right_on="s_suppkey")
    ps = ps.assign(value=ps.ps_supplycost * ps.ps_availqty.astype(np.float64))
    g = ps.groupby("ps_partkey", as_index=False)["value"].sum()
    total = float(g.value.sum())
    g = g[g.value > fraction * total]
    return g.sort_values(["value", "ps_partkey"],
                         ascending=[False, True])[
        ["ps_partkey", "value"]].reset_index(drop=True)


# ---------------------------------------------------------------------------
# Q15 — top supplier (aggregate view + scalar max equi-select)
# ---------------------------------------------------------------------------

def q15(dfs: dict, env=None, date_lo: str = "1996-01-01",
        date_hi: str = "1996-04-01"):
    """WITH revenue AS (SELECT l_suppkey AS supplier_no,
    sum(l_extendedprice*(1-l_discount)) AS total_revenue FROM lineitem
    WHERE l_shipdate >= :lo AND l_shipdate < :hi GROUP BY l_suppkey)
    SELECT s_suppkey, s_name, total_revenue FROM supplier, revenue WHERE
    s_suppkey = supplier_no AND total_revenue = (SELECT max(...) FROM
    revenue) ORDER BY s_suppkey.  The equi-select compares the view's
    own values against its own max — exact by construction."""
    l = dfs["lineitem"]
    l = l[(l["l_shipdate"] >= _ts(date_lo))
          & (l["l_shipdate"] < _ts(date_hi))]
    l["total_revenue"] = l["l_extendedprice"] * (1.0 - l["l_discount"])
    rev = l.groupby(["l_suppkey"], env=env)[["total_revenue"]].sum()
    top = float(rev["total_revenue"].max())
    best = rev[rev["total_revenue"] >= top]
    j = dfs["supplier"].merge(best, left_on="s_suppkey",
                              right_on="l_suppkey", env=env)
    return j.sort_values("s_suppkey", env=env)[
        ["s_suppkey", "s_name", "total_revenue"]]


def q15_pandas(pdfs: dict, date_lo: str = "1996-01-01",
               date_hi: str = "1996-04-01") -> pd.DataFrame:
    l = pdfs["lineitem"]
    l = l[(l.l_shipdate >= pd.Timestamp(date_lo))
          & (l.l_shipdate < pd.Timestamp(date_hi))].copy()
    l["total_revenue"] = l.l_extendedprice * (1.0 - l.l_discount)
    rev = l.groupby("l_suppkey", as_index=False)["total_revenue"].sum()
    top = float(rev.total_revenue.max())
    best = rev[rev.total_revenue >= top]
    j = pdfs["supplier"].merge(best, left_on="s_suppkey",
                               right_on="l_suppkey")
    return j.sort_values("s_suppkey")[
        ["s_suppkey", "s_name", "total_revenue"]].reset_index(drop=True)


# ---------------------------------------------------------------------------
# Q17 — small-quantity-order revenue (correlated avg subquery)
# ---------------------------------------------------------------------------

def q17(dfs: dict, env=None, brand: str = "Brand#23",
        container: str = "MED BOX") -> float:
    """SELECT sum(l_extendedprice) / 7.0 AS avg_yearly FROM lineitem,
    part WHERE p_partkey = l_partkey AND p_brand = :brand AND
    p_container = :container AND l_quantity < (SELECT 0.2*avg(l_quantity)
    FROM lineitem WHERE l_partkey = p_partkey).  The correlated avg
    decomposes into a per-part groupby mean joined back onto the lines
    (reference pattern: DistributedHashGroupBy then DistributedJoin)."""
    p = dfs["part"]
    p = p[(p["p_brand"] == brand)
          & (p["p_container"] == container)][["p_partkey"]]
    j = dfs["lineitem"].merge(p, left_on="l_partkey", right_on="p_partkey",
                              env=env)
    avg = j.groupby(["l_partkey"], env=env).agg([("l_quantity", "mean")])
    j2 = j.merge(avg, on="l_partkey", env=env)
    f = j2[j2["l_quantity"].astype("float64")
           < j2["l_quantity_mean"] * 0.2]
    return float(f["l_extendedprice"].sum()) / 7.0


def q17_pandas(pdfs: dict, brand: str = "Brand#23",
               container: str = "MED BOX") -> float:
    p = pdfs["part"]
    p = p[(p.p_brand == brand)
          & (p.p_container == container)][["p_partkey"]]
    j = pdfs["lineitem"].merge(p, left_on="l_partkey",
                               right_on="p_partkey")
    avg = (j.groupby("l_partkey", as_index=False)
           .agg(l_quantity_mean=("l_quantity", "mean")))
    j2 = j.merge(avg, on="l_partkey")
    f = j2[j2.l_quantity.astype(np.float64) < j2.l_quantity_mean * 0.2]
    return float(f.l_extendedprice.sum()) / 7.0


# ---------------------------------------------------------------------------
# Q20 — potential part promotion (nested IN-subqueries over partsupp)
# ---------------------------------------------------------------------------

def q20(dfs: dict, env=None, name_prefix: str = "forest",
        nation: str = "CANADA", date_lo: str = "1994-01-01",
        date_hi: str = "1995-01-01"):
    """SELECT s_name FROM supplier, nation WHERE s_suppkey IN (SELECT
    ps_suppkey FROM partsupp WHERE ps_partkey IN (SELECT p_partkey FROM
    part WHERE p_name LIKE :prefix%) AND ps_availqty > (SELECT
    0.5*sum(l_quantity) FROM lineitem WHERE l_partkey = ps_partkey AND
    l_suppkey = ps_suppkey AND l_shipdate IN [:lo, :hi))) AND
    s_nationkey = n_nationkey AND n_name = :nation ORDER BY s_name.

    The streaming-friendly partsupp semantics: the correlated half-sum
    subquery decomposes into a two-key groupby over the date-filtered
    lineitem joined back onto partsupp (an empty inner sum is NULL in
    SQL — comparison false — which the inner join reproduces), the
    nested INs become a filter + two SEMI joins, and LIKE 'forest%'
    rides the closed p_name vocabulary as exact-value equality
    (documented simplification, same as Q22's phone prefix; the pandas
    oracle uses a real str.startswith)."""
    p = dfs["part"]
    forest = [v for v in PNAMES.tolist() if v.startswith(name_prefix)]
    p = p[_isin(p["p_name"], forest)][["p_partkey"]]
    l = dfs["lineitem"]
    l = l[(l["l_shipdate"] >= _ts(date_lo))
          & (l["l_shipdate"] < _ts(date_hi))]
    half = (l.groupby(["l_partkey", "l_suppkey"], env=env)
            .agg([("l_quantity", "sum")]))
    ps = dfs["partsupp"].merge(p, how="semi", left_on="ps_partkey",
                               right_on="p_partkey", env=env)
    j = ps.merge(half, left_on=["ps_partkey", "ps_suppkey"],
                 right_on=["l_partkey", "l_suppkey"], env=env)
    f = j[j["ps_availqty"].astype("float64")
          > 0.5 * j["l_quantity_sum"].astype("float64")]
    s = dfs["supplier"].merge(f[["ps_suppkey"]], how="semi",
                              left_on="s_suppkey", right_on="ps_suppkey",
                              env=env)
    n = dfs["nation"]
    n = n[n["n_name"] == nation]
    out = s.merge(n, left_on="s_nationkey", right_on="n_nationkey",
                  env=env)
    return out.sort_values("s_name", env=env)[["s_name"]]


def q20_pandas(pdfs: dict, name_prefix: str = "forest",
               nation: str = "CANADA", date_lo: str = "1994-01-01",
               date_hi: str = "1995-01-01") -> pd.DataFrame:
    p = pdfs["part"]
    pk = set(p[p.p_name.str.startswith(name_prefix)].p_partkey)
    l = pdfs["lineitem"]
    l = l[(l.l_shipdate >= pd.Timestamp(date_lo))
          & (l.l_shipdate < pd.Timestamp(date_hi))]
    half = (l.groupby(["l_partkey", "l_suppkey"], as_index=False)
            .agg(l_quantity_sum=("l_quantity", "sum")))
    ps = pdfs["partsupp"]
    ps = ps[ps.ps_partkey.isin(pk)]
    j = ps.merge(half, left_on=["ps_partkey", "ps_suppkey"],
                 right_on=["l_partkey", "l_suppkey"])
    sk = set(j[j.ps_availqty.astype(np.float64)
               > 0.5 * j.l_quantity_sum.astype(np.float64)].ps_suppkey)
    s = pdfs["supplier"]
    s = s[s.s_suppkey.isin(sk)]
    n = pdfs["nation"]
    s = s.merge(n[n.n_name == nation], left_on="s_nationkey",
                right_on="n_nationkey")
    return s.sort_values("s_name")[["s_name"]].reset_index(drop=True)


# ---------------------------------------------------------------------------
# bench entry (bench.py --tpch)
# ---------------------------------------------------------------------------

# CX suppressed: the bench driver's halving loop is the single-process
# top-of-stack entry, outside the SPMD region — when armed, the
# run_with_recovery ladder has already consensus'd the fault before it
# propagates here, so the rank-local classify/retry below never races a
# peer mid-collective.
def bench_tpch(scale: float = 1.0, iters: int = 3) -> dict:  # tracecheck: off[CX401,CX404]
    """Runs the full query suite at ``scale``; on device OOM the scale halves
    (the whole-working-set analog of bench.py's rows halving: TPC-H keeps
    every base table plus query intermediates resident, so past the HBM
    ceiling no operator-level chunking can save a single chip — the
    deploy story for SF10+ is a pod slice, deploy/README.md)."""
    import jax

    from cylon_tpu.exec import checkpoint, recovery, scheduler
    from cylon_tpu.status import Code, PredictedResourceExhausted
    # the detail block reports THIS bench invocation's recoveries only
    # (including failed-attempt events from the halving loop below)
    recovery.reset_events()
    checkpoint.reset_stats()
    spilled_scales: set = set()
    while True:
        try:
            return _bench_tpch_once(scale, iters)
        except Exception as e:  # noqa: BLE001
            # classify() is the taxonomy boundary — it also shims foreign
            # exceptions that carry the XLA OOM message shape (ADVICE r5)
            fault = recovery.classify(e)
            if fault is None or fault.code != Code.OutOfMemory \
                    or scale <= 0.02:
                raise
            predicted = isinstance(fault, PredictedResourceExhausted)
            if predicted and scale not in spilled_scales \
                    and scheduler.spill_retry() > 0:
                # prefer the SPILL rung over in-process scale-halving:
                # a predicted guard fired pre-allocation (HBM clean), so
                # evicting resident state to host and retrying at the
                # SAME scale keeps the benchmark's configuration intact
                # (docs/robustness.md rung ordering); one spill attempt
                # per scale — a re-fault then falls through to halving
                spilled_scales.add(scale)
                print(f"# TPC-H predicted OOM; spilled resident state, "
                      f"retrying at SF{scale:g}", flush=True)
                import gc
                gc.collect()
                continue
            if jax.devices()[0].platform != "cpu" and not predicted:
                # measured (round 5): a REAL device OOM on the axon TPU
                # rig POISONS the process — the leaked HBM never returns
                # and every later allocation fails, so in-process retries
                # are doomed.  A PREDICTED guard error is different: it
                # fired before any allocation, HBM is untouched, and the
                # in-process scale-halving retry below is safe.  (With
                # durable checkpointing armed the ladder's FINAL rung
                # already converted this into a ResumableAbort carrying
                # the resume token — classify() passes it through above
                # — so this bare-abort advice is the UNARMED path only.)
                resume_hint = (
                    "; set CYLON_TPU_CKPT_DIR to make the fresh-process "
                    "rerun fast-forward past completed pieces "
                    "(CYLON_TPU_RESUME=1, docs/robustness.md)"
                    if not checkpoint.enabled() else "")
                raise RuntimeError(
                    f"TPC-H SF{scale:g} exceeded device memory and "
                    "this rig does not recover HBM after an OOM in the "
                    "same process; rerun at a smaller --scale in a FRESH "
                    "process, or use scripts/bench_tpch_q3q5.py "
                    "(column-projected ingest) for large scales"
                    + resume_hint) from e
            scale = scale / 2
            print(f"# TPC-H {fault.kind} OOM; retrying at SF{scale:g}",
                  flush=True)
            # the failed attempt's tables/intermediates sit in REFERENCE
            # CYCLES (DeferredTable thunks close over their tables): the
            # retry must not inherit their device buffers
            import gc
            gc.collect()


def _bench_tpch_once(scale: float, iters: int) -> dict:
    import jax
    import cylon_tpu as ct
    from cylon_tpu.ctx.context import CPUMeshConfig, TPUConfig

    devs = jax.devices()
    on_accel = devs[0].platform != "cpu"
    env = ct.CylonEnv(config=TPUConfig() if on_accel else CPUMeshConfig())
    dfs = generate_tables(scale=scale, env=env)

    def run_query(fn):
        import gc

        def step():
            out = fn(dfs, env=env)
            if hasattr(out, "to_pandas"):
                out.to_pandas()  # materialize to host = completion barrier
            return out
        step()  # warmup/compile
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            step()
            ts.append(time.perf_counter() - t0)
        # drop this query's intermediates (incl. cyclic DeferredTable
        # state) before the next query allocates — at SF10 the base
        # tables alone hold ~half of HBM
        gc.collect()
        return min(ts)

    queries = {"q1": q1, "q3": q3, "q4": q4, "q5": q5, "q6": q6,
               "q7": q7, "q8": q8, "q9": q9, "q10": q10, "q11": q11,
               "q12": q12,
               "q13": q13, "q14": q14, "q15": q15, "q16": q16,
               "q17": q17, "q18": q18, "q19": q19, "q20": q20,
               "q21": q21, "q22": q22}
    times = {name: run_query(fn) for name, fn in queries.items()}
    # the profiler's acceptance workload (docs/observability.md): one
    # extra ANALYZE-profiled Q13 run whose plan tree — per-node
    # rows/bytes/seconds with the phase-table reconciliation block —
    # rides the bench JSON detail; round 14 adds the naturally
    # skew-shaped Q18 beside it, so the skew route's decision (or its
    # absence) on a real query is auditable from the same JSON
    # (docs/skew.md)
    from cylon_tpu import obs
    q13_plan = obs.explain_analyze(lambda: q13(dfs, env=env).to_pandas())
    q18_plan = obs.explain_analyze(
        lambda: q18(dfs, env=env, quantity=150).to_pandas())
    # round 15 adds Q8 beside them — the seven-table national market
    # share, the suite's widest cross-slice working set: its plan tree
    # carries every join's exchange totals (and, with the comm matrix
    # armed on a multi-slice topology, the ICI/DCN tier split) so the
    # two-hop route's effect on a real query is auditable from the
    # same JSON (docs/topology.md)
    q8_plan = obs.explain_analyze(lambda: q8(dfs, env=env).to_pandas())
    return {
        "metric": f"TPC-H SF{scale:g} {'+'.join(q.upper() for q in queries)}"
                  " wall time",
        "value": round(sum(times.values()), 4),
        "unit": "seconds",
        "vs_baseline": 0.0,
        "detail": {"world": env.world_size, "platform": devs[0].platform,
                   "scale": scale,
                   # was this number achieved on the happy path or after
                   # in-run degradation (docs/robustness.md)?
                   "recovery_events": _recovery_events(),
                   # resident vs host-spilled vs OUT-OF-CORE state
                   # (exec/memory): disk_events/bytes_to_disk > 0 means
                   # the number rode the disk tier
                   **{k: v for k, v in _spill_stats().items() if k in
                      ("spill_events", "bytes_spilled",
                       "peak_ledger_bytes", "disk_events",
                       "bytes_to_disk", "bytes_from_disk")},
                   # durable checkpoint traffic (exec/checkpoint): did
                   # this number include checkpoint writes, and did a
                   # resumed run fast-forward instead of recomputing?
                   # resume_world_mismatch alongside
                   # resume_resharded_pieces says whether a topology
                   # change resharded or threw the checkpoint away
                   **{k: v for k, v in _ckpt_stats().items() if k in
                      ("checkpoint_events", "bytes_checkpointed",
                       "resume_fast_forwarded_pieces",
                       "resume_resharded_pieces", "resume_world_mismatch")},
                   # EXPLAIN ANALYZE of Q13 (obs/plan): the plan tree
                   # with per-node seconds + the reconcile block — and
                   # of the skew-shaped Q18, whose join nodes carry the
                   # skew route decision when a plan armed (docs/skew.md)
                   "q13_plan": q13_plan.to_dict(),
                   "q18_plan": q18_plan.to_dict(),
                   "q8_plan": q8_plan.to_dict(),
                   **{f"{n}_s": round(t, 4) for n, t in times.items()}},
    }


def _recovery_events() -> list:
    from cylon_tpu.exec import recovery
    return recovery.drain_events()


def _spill_stats() -> dict:
    from cylon_tpu.exec import memory
    return memory.stats()


def _ckpt_stats() -> dict:
    from cylon_tpu.exec import checkpoint
    return checkpoint.stats()
