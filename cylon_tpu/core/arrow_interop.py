"""Zero-pandas Arrow interop: pyarrow Table/Array <-> host Columns.

TPU-native equivalent of the reference's Arrow data plane boundary
(``Table::FromArrowTable/ToArrowTable``, table.hpp:61-82, io/arrow_io.cpp).
The round-1 ingest funneled every Arrow table through ``to_pandas()`` — an
object-dtype round trip that dominates at scale and loses dtype fidelity
(VERDICT item 5).  Here each Arrow column's buffers convert directly:

* numeric/bool/temporal: ``fill_null`` + ``to_numpy`` on the combined chunk
  (keeps the physical dtype; no object arrays), validity from
  ``is_valid()``;
* timestamps/date32/duration: cast to ns-resolution int64 views;
* strings (utf8 / large_utf8 / dictionary): ``dictionary_encode`` then
  re-coded onto a SORTED value table so code order == lexical order (the
  invariant every sort/join on codes relies on, core/column.py).

The device transfer itself stays ``jax.device_put`` of the resulting host
arrays (core/table.py placement), so no backend is touched here.
"""

from __future__ import annotations

import numpy as np

from ..status import CylonTypeError
from .column import Column
from .dtypes import LogicalType, from_numpy_dtype, physical_np_dtype


def _sorted_dictionary(indices: np.ndarray, values: np.ndarray):
    """Re-code onto a sorted unique dictionary (code order == lexical)."""
    uniq, remap = np.unique(values, return_inverse=True)
    codes = remap.astype(np.int32)[np.clip(indices, 0, len(values) - 1)] \
        if len(values) else indices.astype(np.int32)
    return codes, uniq


def column_from_arrow(arr) -> Column:
    """pyarrow Array/ChunkedArray -> host Column (no pandas round trip)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    t = arr.type

    validity = None
    if arr.null_count:
        validity = np.asarray(arr.is_valid())

    if pa.types.is_dictionary(t):
        inner = arr.cast(t.value_type) if not pa.types.is_string(t.value_type) \
            else None
        if inner is not None:  # dictionary of non-strings: decode plainly
            return column_from_arrow(inner)
        idx = np.asarray(arr.indices.fill_null(0))
        vals = np.asarray(arr.dictionary, dtype=object)
        vals = np.asarray([v if isinstance(v, str) else str(v)
                           for v in vals], dtype=object)
        codes, uniq = _sorted_dictionary(idx, vals)
        return Column(codes, LogicalType.STRING, validity, uniq)

    if pa.types.is_string(t) or pa.types.is_large_string(t):
        enc = pc.dictionary_encode(arr.fill_null(""))
        idx = np.asarray(enc.indices.fill_null(0))
        vals = np.asarray(enc.dictionary, dtype=object)
        codes, uniq = _sorted_dictionary(idx, vals)
        return Column(codes, LogicalType.STRING, validity, uniq)

    if pa.types.is_timestamp(t) or pa.types.is_date(t):
        arr = arr.cast(pa.timestamp("ns"))
        data = np.asarray(arr.fill_null(0).cast(pa.int64()))
        return Column(data, LogicalType.DATE64, validity)
    if pa.types.is_duration(t):
        arr = arr.cast(pa.duration("ns"))
        data = np.asarray(arr.fill_null(0).cast(pa.int64()))
        return Column(data, LogicalType.TIMEDELTA, validity)

    if pa.types.is_boolean(t):
        data = np.asarray(arr.fill_null(False))
        return Column(data, LogicalType.BOOL, validity)

    if pa.types.is_null(t):
        # arrow 'null' (e.g. an all-empty CSV column) -> all-null float64,
        # matching what the pandas reader produced
        n = len(arr)
        return Column(np.zeros(n, np.float64), LogicalType.FLOAT64,
                      np.zeros(n, bool))

    if pa.types.is_decimal(t):
        if pa.types.is_decimal128(t) and t.precision <= 18:
            # exact scaled-int64 (TPC-H money semantics; reference:
            # decimal128 comparators, arrow_comparator.cpp).  The unscaled
            # integer IS decimal128's two's-complement storage; for p<=18
            # it lives in the low 64-bit limb (hi limb = sign extension),
            # so the buffer view is exact and vectorized.
            from .column import DecimalScale
            raw = np.frombuffer(arr.buffers()[1], np.int64)
            data = raw.reshape(-1, 2)[arr.offset:arr.offset + len(arr),
                                      0].copy()
            if validity is not None:
                data[~validity] = 0   # null slots hold undefined storage
            bounds = ((int(data.min()), int(data.max()))
                      if data.size else None)
            return Column(data, LogicalType.DECIMAL, validity,
                          DecimalScale(t.precision, t.scale), bounds=bounds)
        # p > 18 or decimal256: documented lossy float64 fallback
        arr = arr.cast(pa.float64())
        t = arr.type

    if pa.types.is_list(t) or pa.types.is_large_list(t) \
            or pa.types.is_fixed_size_list(t):
        # host passthrough (no device layout for variable-length payloads;
        # reference joins list<float32> locally, join_test.cpp:124 — here
        # the values ride host-side and the CODES ride the device)
        from .column import PassthroughValues
        vals = np.asarray(arr.to_pylist(), dtype=object)
        codes = np.arange(len(vals), dtype=np.int32)
        return Column(codes, LogicalType.LIST, validity,
                      PassthroughValues(vals),
                      bounds=(0, max(len(vals) - 1, 0)))

    if pa.types.is_integer(t) or pa.types.is_floating(t):
        filled = arr.fill_null(0) if arr.null_count else arr
        data = np.asarray(filled)
        lt = from_numpy_dtype(data.dtype)
        data = data.astype(physical_np_dtype(lt), copy=False)
        bounds = None
        if data.dtype.kind in ("i", "u") and data.size:
            bounds = (int(data.min()), int(data.max()))
        return Column(data, lt, validity, bounds=bounds)

    raise CylonTypeError(f"unsupported arrow type {t}")


def table_from_arrow(at, env=None):
    """pyarrow.Table -> device Table (reference Table::FromArrowTable)."""
    from .table import Table
    cols = {name: column_from_arrow(at.column(name))
            for name in at.column_names}
    return Table.from_host_columns(cols, env)


def table_to_arrow(table):
    """Device Table -> pyarrow.Table with faithful types (reference
    Table::ToArrowTable)."""
    import pyarrow as pa
    arrays, names = [], []
    hosts = table.host_columns()
    for name, c in table.columns.items():
        data, valid = hosts[name]
        mask = ~valid if valid is not None else None
        if c.type == LogicalType.STRING:
            from .column import HashedStrings
            if isinstance(c.dictionary, HashedStrings):
                arr = pa.array(c.dictionary.take(data), type=pa.string(),
                               mask=mask)
            else:
                idx = pa.array(data.astype(np.int32), mask=mask)
                arr = pa.DictionaryArray.from_arrays(
                    idx, pa.array(c.dictionary.astype(object)))
                # faithful schema: sources are typically plain utf8, and
                # our dictionary-encoding is an internal representation
                # choice
                arr = arr.dictionary_decode()
        elif c.type == LogicalType.DATE64:
            arr = pa.array(data, type=pa.timestamp("ns"), mask=mask)
        elif c.type == LogicalType.TIMEDELTA:
            arr = pa.array(data, type=pa.duration("ns"), mask=mask)
        elif c.type == LogicalType.DECIMAL:
            sc = c.dictionary
            # precision must cover the scale: a tight ingested precision
            # (digit count of the max unscaled int) can be smaller than
            # the scale — e.g. [0.01, 0.02] -> (1, 2) — and Arrow rejects
            # decimal128(1, 2)
            arr = pa.array(sc.to_decimal(data),
                           type=pa.decimal128(
                               max(sc.precision, sc.scale, 1),
                               sc.scale), mask=mask)
        elif c.type == LogicalType.LIST:
            arr = pa.array(list(c.dictionary.take(data)), mask=mask)
        else:
            arr = pa.array(data, mask=mask)
        arrays.append(arr)
        names.append(name)
    return pa.Table.from_arrays(arrays, names=names)
