"""Device-resident Column.

TPU-native equivalent of the reference ``cylon::Column`` (cpp/src/cylon/
column.hpp:27, wrapping ``arrow::Array``).  Physical layout follows the GCylon
pattern (accelerator-resident, cpp/src/gcylon/gtable.hpp): a fixed-width
device array + an optional boolean validity array (bool array instead of the
Arrow bitmap — TPU vectors have no cheap bit addressing, and XLA fuses mask
ops for free).  Variable-width strings are dictionary-encoded: int32 codes on
device, the value table host-side (the reference likewise flattens non-fixed
keys to binary before hashing, util/flatten_array.cpp).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..status import CylonTypeError, InvalidError
from .dtypes import LogicalType, from_numpy_dtype, physical_np_dtype


class HashedStrings:
    """High-cardinality string 'dictionary': device codes are stable 64-bit
    value hashes (int64 bit-pattern) instead of sorted-dictionary indices.

    Rides the existing ``Column.dictionary`` slot so every column rebuild
    site propagates it untouched.  Semantics vs a real dictionary:

    * EQUALITY on codes is (probabilistically) value equality — joins,
      groupbys, set ops, unique and ==/!= filters are exact up to 64-bit
      hash collisions (birthday bound: ~3e-20·n² chance of any collision —
      ~0.3% at 100M distinct values); the reference compares flattened
      binary exactly (util/flatten_array.cpp), this path trades that for
      never building an n-entry dictionary.
    * ORDER of codes is NOT value order: lexical sorts, range compares and
      min/max on such columns raise (the caller sees a clear error, never
      a wrong answer).
    * decode goes through a lazily built hash->value map over the source
      values (only paid if the strings are actually materialized).

    Construction cost is one stable 64-bit hash per row
    (:func:`cylon_tpu.native.hash_strings` — native murmur64a when the
    toolchain is present).
    """

    __slots__ = ("_hashes", "_values", "_sorted")

    def __init__(self, hashes: np.ndarray, values: np.ndarray):
        self._hashes = hashes      # uint64, aligned with _values
        self._values = values      # object array of source strings
        self._sorted = None

    def _lookup(self):
        if self._sorted is None:
            order = np.argsort(self._hashes)
            hs = self._hashes[order]
            vs = self._values[order]
            keep = np.concatenate([[True], hs[1:] != hs[:-1]])
            self._sorted = (hs[keep], vs[keep])
        return self._sorted

    def take(self, codes: np.ndarray) -> np.ndarray:
        """Decode int64-bit-pattern codes to their string values."""
        hs, vs = self._lookup()
        u = np.asarray(codes).astype(np.int64).view(np.uint64)
        idx = np.clip(np.searchsorted(hs, u), 0, max(len(hs) - 1, 0))
        if len(hs) == 0:
            return np.asarray([""] * len(u), dtype=object)
        return vs[idx]

    def hash_values(self, values) -> np.ndarray:
        """int64-bit-pattern codes for new values (filter literals,
        dictionary-side re-encoding in joins)."""
        from .. import native
        return native.hash_strings(np.asarray(values, dtype=object)) \
            .view(np.int64)

    def merged_with(self, other: "HashedStrings") -> "HashedStrings":
        return HashedStrings(
            np.concatenate([self._hashes, other._hashes]),
            np.concatenate([self._values, other._values]))

    def __len__(self):  # distinct-count queries on the lookup
        return len(self._lookup()[0])


def hashed_codes(values: np.ndarray):
    """(codes int64, HashedStrings) for a host string/object array."""
    from .. import native
    hashes = native.hash_strings(np.asarray(values, dtype=object))
    return hashes.view(np.int64), HashedStrings(hashes, values)


class DecimalScale:
    """DECIMAL column metadata (rides the ``Column.dictionary`` slot like
    HashedStrings, so every column rebuild site propagates it untouched):
    device data is the UNSCALED int64 (value · 10^scale) — exact TPC-H
    money semantics for precision <= 18 (reference: Arrow decimal128
    comparators, arrow_comparator.cpp).  Equality/order on the scaled ints
    equals decimal equality/order at a COMMON scale, so joins, groupbys,
    sorts and filters all work on the physical column."""

    __slots__ = ("precision", "scale")

    def __init__(self, precision: int, scale: int):
        if precision > 18:
            raise CylonTypeError(
                f"decimal precision {precision} > 18 does not fit int64")
        self.precision = int(precision)
        self.scale = int(scale)

    def __eq__(self, other):
        return (isinstance(other, DecimalScale)
                and other.precision == self.precision
                and other.scale == self.scale)

    def __hash__(self):
        return hash((DecimalScale, self.precision, self.scale))

    def __repr__(self):  # pragma: no cover
        return f"DecimalScale({self.precision}, {self.scale})"

    def to_decimal(self, data: np.ndarray) -> np.ndarray:
        import decimal
        return np.asarray(
            [decimal.Decimal(int(v)).scaleb(-self.scale) for v in data],
            dtype=object)


class PassthroughValues:
    """Host-side passthrough 'dictionary' for values with no TPU device
    layout (variable-length lists): device data is int32 row codes into a
    host object array.  Carried through joins/filters/exchanges by the
    same code gathers strings use; NOT usable as a key (codes are row
    ids, not value-equal — key sites raise CylonTypeError)."""

    __slots__ = ("values",)

    def __init__(self, values: np.ndarray):
        self.values = np.asarray(values, dtype=object)

    def take(self, codes: np.ndarray) -> np.ndarray:
        n = len(self.values)
        if n == 0:
            return np.asarray([None] * len(codes), dtype=object)
        return self.values[np.clip(codes, 0, n - 1)]

    def __len__(self):
        return len(self.values)


class Column:
    __slots__ = ("data", "validity", "type", "dictionary", "bounds")

    def __init__(self, data, type: LogicalType, validity=None,
                 dictionary: Optional[np.ndarray] = None,
                 bounds: Optional[tuple] = None):
        self.data = data
        self.type = type
        self.validity = validity  # bool array, True = valid; None = all valid
        self.dictionary = dictionary  # host np.ndarray for STRING codes
        #: host-known (lo, hi) value bounds for integer columns, or None.
        #: Conservative: any subset/permutation of the values keeps them
        #: valid; ops that create new values must drop them.  Consulted by
        #: sort-operand packing: int64 keys within int32 range sort as ONE
        #: native operand (ops/pack.py narrow32).
        self.bounds = bounds
        if type == LogicalType.STRING and dictionary is None:
            raise InvalidError("STRING column requires a dictionary")
        if type == LogicalType.DECIMAL and not isinstance(dictionary,
                                                          DecimalScale):
            raise InvalidError("DECIMAL column requires a DecimalScale")
        if type == LogicalType.LIST and not isinstance(dictionary,
                                                       PassthroughValues):
            raise InvalidError("LIST column requires PassthroughValues")

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_numpy(arr: np.ndarray, type: LogicalType | None = None) -> "Column":
        """Build a HOST column from a host array (data stays numpy — no
        device/backend is touched; ``Table`` factories place columns onto the
        env's devices explicitly, so ingestion never initializes the default
        backend).  Encodes strings/objects; NaN stays a float payload,
        matching pandas semantics."""
        arr = np.asarray(arr)
        if arr.dtype.kind in ("U", "S", "O"):
            return Column._encode_strings(arr)
        lt = type or from_numpy_dtype(arr.dtype)
        phys = physical_np_dtype(lt)
        if arr.dtype.kind == "M":
            # normalize any pandas resolution (s/ms/us) to ns before bitview
            arr = arr.astype("datetime64[ns]").astype("int64", copy=False)
        elif arr.dtype.kind == "m":
            arr = arr.astype("timedelta64[ns]").astype("int64", copy=False)
        arr = arr.astype(phys, copy=False)
        bounds = None
        if arr.dtype.kind in ("i", "u") and arr.size:
            bounds = (int(arr.min()), int(arr.max()))
        return Column(arr, lt, bounds=bounds)

    @staticmethod
    def _decimal_from_objects(arr: np.ndarray, mask: np.ndarray) -> "Column":
        """Object array of decimal.Decimal -> scaled-int64 DECIMAL column
        (exact for precision <= 18; reference: decimal128 comparators)."""
        import decimal
        vals = [v for v, m in zip(arr, mask) if not m]
        try:
            # TypeError also covers non-finite Decimals (NaN/Infinity),
            # whose as_tuple().exponent is a str
            scale = max((-v.as_tuple().exponent for v in vals), default=0)
        except (AttributeError, TypeError) as e:
            raise CylonTypeError(
                "mixed or non-finite decimal column; cast uniformly "
                "before ingest") from e
        scale = max(scale, 0)
        data = np.zeros(len(arr), np.int64)
        for i, (v, m) in enumerate(zip(arr, mask)):
            if not m:
                try:
                    data[i] = int(decimal.Decimal(v).scaleb(scale))
                except (decimal.InvalidOperation, TypeError,
                        ValueError) as e:
                    raise CylonTypeError(
                        "mixed decimal column; cast uniformly before "
                        "ingest") from e
        validity = ~mask if mask.any() else None
        bounds = ((int(data.min()), int(data.max())) if len(data) else None)
        # tight precision (actual digit count): leaves headroom for later
        # 10^Δ rescales against finer-scaled partners (the 18 cap is the
        # int64 representation's, not each column's)
        max_abs = int(np.abs(data).max()) if len(data) else 0
        prec = max(len(str(max_abs)), 1)
        return Column(data, LogicalType.DECIMAL, validity,
                      DecimalScale(prec, scale), bounds=bounds)

    @staticmethod
    def _list_passthrough(arr: np.ndarray, mask: np.ndarray) -> "Column":
        """Object array of lists -> host passthrough column (carried
        through joins by code gathers; not usable as a key)."""
        codes = np.arange(len(arr), dtype=np.int32)
        validity = ~mask if mask.any() else None
        return Column(codes, LogicalType.LIST, validity,
                      PassthroughValues(arr),
                      bounds=(0, max(len(arr) - 1, 0)))

    @staticmethod
    def _encode_strings(arr: np.ndarray) -> "Column":
        if arr.dtype.kind == "S":  # binary: decode, don't repr-mangle
            arr = np.char.decode(arr, "utf-8")
        if arr.dtype == object:
            # pd.isna covers None, float NaN, pd.NA and NaT — a hand-rolled
            # None/NaN check silently stringifies pd.NA (pandas StringDtype
            # nulls) into the literal "<NA>".  pd.isna on a cell holding a
            # LIST returns an array — probe for nested values first.
            import pandas as pd
            import decimal

            def null_scalar(v):
                # list cells make pd.isna return an ARRAY — guard them
                if isinstance(v, (list, np.ndarray)):
                    return False
                return bool(pd.isna(v))   # None, NaN, pd.NA, NaT

            probe = next((v for v in arr if not null_scalar(v)), None)
            if isinstance(probe, (list, np.ndarray)):
                mask = np.asarray([null_scalar(v) for v in arr], bool)
                return Column._list_passthrough(arr, mask)
            mask = np.asarray(pd.isna(arr), bool)
            if isinstance(probe, decimal.Decimal):
                return Column._decimal_from_objects(arr, mask)
        else:
            mask = np.zeros(len(arr), bool)
        safe = np.where(mask, "", arr.astype(object)) if mask.any() else arr

        import decimal

        def as_str(v):
            # documented rejection (SURVEY C6: the reference's comparators
            # span every Arrow type incl. lists, join_test.cpp:124): struct
            # values have no TPU device layout OR passthrough mode here —
            # refuse loudly instead of silently stringifying a wrong
            # answer.  (Lists take the passthrough path above; decimals
            # the scaled-int64 path.)
            if isinstance(v, (list, tuple, dict, np.ndarray)):
                raise CylonTypeError(
                    "struct/mixed nested columns are not supported on the "
                    "TPU device layout; explode or serialize them before "
                    "ingest")
            if isinstance(v, decimal.Decimal):
                raise CylonTypeError(
                    "mixed decimal/str column; cast uniformly before "
                    "ingest")
            if isinstance(v, (bytes, np.bytes_)):
                return v.decode("utf-8", "replace")
            return str(v)

        if safe.dtype.kind == "U":
            values = safe.astype(object)
        elif all(isinstance(v, str) for v in safe[:64]):
            # object arrays from pandas are usually already str (np.str_
            # included) — probe a prefix, stringify only the exceptions
            values = np.asarray(
                [v if isinstance(v, str) else as_str(v) for v in safe],
                dtype=object)
        else:
            values = np.asarray([as_str(v) for v in safe], dtype=object)
        validity = ~mask if mask.any() else None
        # crossover heuristic: a sampled distinct-ratio estimate decides
        # between the sorted dictionary (order-isomorphic codes — lexical
        # sorts/compares work) and the hashed-codes path (HashedStrings:
        # no n-entry dictionary is ever built; equality-only semantics).
        # Reference analog: flatten-then-hash of non-fixed keys
        # (util/flatten_array.cpp + util/murmur3.cpp).
        from .. import config
        n = len(values)
        # x64 opt-out downcasts 8-byte transfers: 32-bit hash equality
        # would collide at birthday rates, so the crossover requires x64
        if n >= config.STRING_HASH_MIN_ROWS and config.X64_ENABLED:
            samp = values[::max(n // 65536, 1)][:65536]
            if len(np.unique(samp)) >= config.STRING_HASH_RATIO * len(samp):
                codes, lookup = hashed_codes(values)
                return Column(codes, LogicalType.STRING, validity, lookup)
        # sorted dictionary so code order == lexical order: sorts/joins on
        # codes are exact on the decoded values.  pd.factorize(sort=True)
        # is the C-speed np.unique(return_inverse) (several x faster on
        # object arrays — the ingest hot loop at TPC-H scale).
        import pandas as pd
        codes, uniques = pd.factorize(values, sort=True)
        dictionary = np.asarray(uniques, dtype=object)
        return Column(codes.astype(np.int32), LogicalType.STRING, validity,
                      dictionary)

    # -- properties --------------------------------------------------------
    def __len__(self) -> int:
        return int(self.data.shape[0])

    @property
    def has_nulls(self) -> bool:
        return self.validity is not None

    def with_data(self, data, validity="__same__") -> "Column":
        v = self.validity if validity == "__same__" else validity
        return Column(data, self.type, v, self.dictionary)

    # -- materialization ---------------------------------------------------
    def to_numpy(self, n: int | None = None) -> np.ndarray:
        """Decode to a host array of length n (valid prefix)."""
        data = np.asarray(self.data)[: n if n is not None else len(self)]
        valid = (np.asarray(self.validity)[: len(data)]
                 if self.validity is not None else None)
        if self.type == LogicalType.DECIMAL:
            out = self.dictionary.to_decimal(data)
            if valid is not None:
                out[~valid] = None
            return out
        if self.type == LogicalType.LIST:
            out = np.asarray(self.dictionary.take(data), dtype=object)
            if valid is not None:
                out = out.copy()
                out[~valid] = None
            return out
        if self.type == LogicalType.STRING:
            if isinstance(self.dictionary, HashedStrings):
                out = self.dictionary.take(data)
            else:
                out = self.dictionary[
                    np.clip(data, 0, len(self.dictionary) - 1)]
            out = np.asarray(out).astype(object)
            if valid is not None:
                out[~valid] = None
            return out
        if self.type == LogicalType.DATE64:
            out = data.astype("datetime64[ns]")
        elif self.type == LogicalType.TIMEDELTA:
            out = data.astype("timedelta64[ns]")
        else:
            out = data.astype(np.dtype(self.type.value), copy=False)
        if valid is not None:
            if out.dtype.kind == "f":
                out = out.copy()
                out[~valid] = np.nan
            else:
                out = out.astype(object)
                out[~valid] = None
        return out

    def cast(self, lt: LogicalType) -> "Column":
        if self.type == LogicalType.STRING or lt == LogicalType.STRING:
            raise CylonTypeError("cast to/from string not supported on device")
        phys = physical_np_dtype(lt)
        keep = (self.bounds is not None and phys.kind in ("i", "u")
                and np.can_cast(np.min_scalar_type(self.bounds[0]), phys)
                and np.can_cast(np.min_scalar_type(self.bounds[1]), phys))
        return Column(self.data.astype(phys), lt, self.validity,
                      bounds=self.bounds if keep else None)
