"""Device-resident Column.

TPU-native equivalent of the reference ``cylon::Column`` (cpp/src/cylon/
column.hpp:27, wrapping ``arrow::Array``).  Physical layout follows the GCylon
pattern (accelerator-resident, cpp/src/gcylon/gtable.hpp): a fixed-width
device array + an optional boolean validity array (bool array instead of the
Arrow bitmap — TPU vectors have no cheap bit addressing, and XLA fuses mask
ops for free).  Variable-width strings are dictionary-encoded: int32 codes on
device, the value table host-side (the reference likewise flattens non-fixed
keys to binary before hashing, util/flatten_array.cpp).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..status import CylonTypeError, InvalidError
from .dtypes import LogicalType, from_numpy_dtype, physical_np_dtype


class Column:
    __slots__ = ("data", "validity", "type", "dictionary", "bounds")

    def __init__(self, data, type: LogicalType, validity=None,
                 dictionary: Optional[np.ndarray] = None,
                 bounds: Optional[tuple] = None):
        self.data = data
        self.type = type
        self.validity = validity  # bool array, True = valid; None = all valid
        self.dictionary = dictionary  # host np.ndarray for STRING codes
        #: host-known (lo, hi) value bounds for integer columns, or None.
        #: Conservative: any subset/permutation of the values keeps them
        #: valid; ops that create new values must drop them.  Consulted by
        #: sort-operand packing: int64 keys within int32 range sort as ONE
        #: native operand (ops/pack.py narrow32).
        self.bounds = bounds
        if type == LogicalType.STRING and dictionary is None:
            raise InvalidError("STRING column requires a dictionary")

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_numpy(arr: np.ndarray, type: LogicalType | None = None) -> "Column":
        """Build a HOST column from a host array (data stays numpy — no
        device/backend is touched; ``Table`` factories place columns onto the
        env's devices explicitly, so ingestion never initializes the default
        backend).  Encodes strings/objects; NaN stays a float payload,
        matching pandas semantics."""
        arr = np.asarray(arr)
        if arr.dtype.kind in ("U", "S", "O"):
            return Column._encode_strings(arr)
        lt = type or from_numpy_dtype(arr.dtype)
        phys = physical_np_dtype(lt)
        if arr.dtype.kind == "M":
            # normalize any pandas resolution (s/ms/us) to ns before bitview
            arr = arr.astype("datetime64[ns]").astype("int64", copy=False)
        elif arr.dtype.kind == "m":
            arr = arr.astype("timedelta64[ns]").astype("int64", copy=False)
        arr = arr.astype(phys, copy=False)
        bounds = None
        if arr.dtype.kind in ("i", "u") and arr.size:
            bounds = (int(arr.min()), int(arr.max()))
        return Column(arr, lt, bounds=bounds)

    @staticmethod
    def _encode_strings(arr: np.ndarray) -> "Column":
        if arr.dtype.kind == "S":  # binary: decode, don't repr-mangle
            arr = np.char.decode(arr, "utf-8")
        if arr.dtype == object:
            # pd.isna covers None, float NaN, pd.NA and NaT — a hand-rolled
            # None/NaN check silently stringifies pd.NA (pandas StringDtype
            # nulls) into the literal "<NA>"
            import pandas as pd
            mask = np.asarray(pd.isna(arr), bool)
        else:
            mask = np.zeros(len(arr), bool)
        safe = np.where(mask, "", arr.astype(object)) if mask.any() else arr

        def as_str(v):
            if isinstance(v, (bytes, np.bytes_)):
                return v.decode("utf-8", "replace")
            return str(v)

        values = np.asarray([as_str(v) for v in safe], dtype=object)
        # np.unique returns a *sorted* dictionary so code order == lexical
        # order: sorts/joins on codes are exact on the decoded values.
        dictionary, codes = np.unique(values, return_inverse=True)
        validity = ~mask if mask.any() else None
        return Column(codes.astype(np.int32), LogicalType.STRING, validity,
                      dictionary)

    # -- properties --------------------------------------------------------
    def __len__(self) -> int:
        return int(self.data.shape[0])

    @property
    def has_nulls(self) -> bool:
        return self.validity is not None

    def with_data(self, data, validity="__same__") -> "Column":
        v = self.validity if validity == "__same__" else validity
        return Column(data, self.type, v, self.dictionary)

    # -- materialization ---------------------------------------------------
    def to_numpy(self, n: int | None = None) -> np.ndarray:
        """Decode to a host array of length n (valid prefix)."""
        data = np.asarray(self.data)[: n if n is not None else len(self)]
        valid = (np.asarray(self.validity)[: len(data)]
                 if self.validity is not None else None)
        if self.type == LogicalType.STRING:
            out = self.dictionary[np.clip(data, 0, len(self.dictionary) - 1)]
            out = out.astype(object)
            if valid is not None:
                out[~valid] = None
            return out
        if self.type == LogicalType.DATE64:
            out = data.astype("datetime64[ns]")
        elif self.type == LogicalType.TIMEDELTA:
            out = data.astype("timedelta64[ns]")
        else:
            out = data.astype(np.dtype(self.type.value), copy=False)
        if valid is not None:
            if out.dtype.kind == "f":
                out = out.copy()
                out[~valid] = np.nan
            else:
                out = out.astype(object)
                out[~valid] = None
        return out

    def cast(self, lt: LogicalType) -> "Column":
        if self.type == LogicalType.STRING or lt == LogicalType.STRING:
            raise CylonTypeError("cast to/from string not supported on device")
        phys = physical_np_dtype(lt)
        keep = (self.bounds is not None and phys.kind in ("i", "u")
                and np.can_cast(np.min_scalar_type(self.bounds[0]), phys)
                and np.can_cast(np.min_scalar_type(self.bounds[1]), phys))
        return Column(self.data.astype(phys), lt, self.validity,
                      bounds=self.bounds if keep else None)
