"""Device-resident Table.

TPU-native equivalent of ``cylon::Table`` (reference cpp/src/cylon/table.hpp:46
— a ``shared_ptr<arrow::Table>`` + context) in the GCylon accelerator-resident
style (cpp/src/gcylon/gtable.hpp: data stays in device memory, the host only
orchestrates).  Layout:

* every column is a global ``jax.Array`` of identical length ``W * cap``,
  row-sharded over the env mesh (``P(ROW_AXIS)``);
* shard ``i`` holds ``valid_counts[i] <= cap`` real rows as a prefix, the rest
  is padding — XLA collectives are static-shape, so capacity-padding + a
  row-count sidecar replaces the reference's variable-size Arrow buffer
  serializer (serialize/table_serialize.hpp:23, SURVEY.md §5.8);
* global row order == concatenation of shard valid prefixes in rank order
  (the same contract the reference's order-preserving all-to-all maintains,
  table.cpp:182-190).

A local table is the world-size-1 special case: one shard, zero padding.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Sequence

import jax
import numpy as np

from ..ctx.context import CylonEnv, LocalConfig
from ..status import CylonKeyError, InvalidError
from .column import Column
from .dtypes import Field, LogicalType

_default_env: CylonEnv | None = None


def default_env() -> CylonEnv:
    global _default_env
    if _default_env is None:
        _default_env = CylonEnv(LocalConfig())
    return _default_env


class Table:
    # __weakref__: the HBM ledger (exec/memory.register_table) anchors
    # byte registrations to table lifetime via weakref.finalize
    __slots__ = ("_cols", "_env", "_valid", "grouped_by", "__weakref__")

    def __init__(self, cols: Mapping[str, Column], env: CylonEnv | None,
                 valid_counts: np.ndarray | None = None):
        self._cols: dict[str, Column] = dict(cols)
        self._env = env or default_env()
        #: names of key columns this table is known to be GROUPED by: equal
        #: keys are contiguous within each shard and co-located across
        #: shards.  Set by ops that establish the property (join output,
        #: global sort, groupby output); every other constructor path leaves
        #: it None.  Lets groupby skip its shuffle + rank sort.
        self.grouped_by: tuple | None = None
        n = None
        for c in self._cols.values():
            if n is None:
                n = len(c)
            elif len(c) != n:
                raise InvalidError("column length mismatch")
        n = n or 0
        w = self._env.world_size
        if valid_counts is None:
            if n % w:
                raise InvalidError(f"rows {n} not divisible by world {w}")
            valid_counts = np.full(w, n // w, dtype=np.int64)
        self._valid = np.asarray(valid_counts, dtype=np.int64)
        if self._valid.shape != (w,):
            raise InvalidError("valid_counts must have one entry per rank")

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_pydict(data: Mapping[str, np.ndarray], env: CylonEnv | None = None) -> "Table":
        env = env or default_env()
        cols = {k: Column.from_numpy(np.asarray(v)) for k, v in data.items()}
        return _ingest(cols, env)

    @staticmethod
    def from_pandas(df, env: CylonEnv | None = None) -> "Table":
        env = env or default_env()
        cols = {str(k): _column_from_series(df[k]) for k in df.columns}
        return _ingest(cols, env)

    @staticmethod
    def from_arrow(at, env: CylonEnv | None = None) -> "Table":
        """From a pyarrow.Table via direct buffer conversion — no pandas
        object round trip (reference Table::FromArrowTable, table.hpp:61;
        conversion rules in core/arrow_interop.py)."""
        from .arrow_interop import table_from_arrow
        return table_from_arrow(at, env)

    @staticmethod
    def from_numpy(names: Sequence[str], arrays: Sequence[np.ndarray],
                   env: CylonEnv | None = None) -> "Table":
        return Table.from_pydict(dict(zip(names, arrays)), env)

    @staticmethod
    def from_host_columns(cols: Mapping[str, Column],
                          env: CylonEnv | None = None) -> "Table":
        """Place already-typed HOST columns (numpy data/validity, logical
        type and dictionary preserved) onto the env — the dtype-faithful
        ingest path (no pandas object round-trip)."""
        env = env or default_env()
        return _ingest(dict(cols), env)

    # -- schema ------------------------------------------------------------
    @property
    def env(self) -> CylonEnv:
        return self._env

    @property
    def column_names(self) -> list[str]:
        return list(self._cols)

    @property
    def columns(self) -> dict[str, Column]:
        return self._cols

    @property
    def column_count(self) -> int:
        return len(self._cols)

    @property
    def row_count(self) -> int:
        """Global (world-wide) valid row count."""
        return int(self._valid.sum())

    @property
    def valid_counts(self) -> np.ndarray:
        return self._valid

    @property
    def capacity(self) -> int:
        """Per-shard padded capacity."""
        if not self._cols:
            return 0
        return len(next(iter(self._cols.values()))) // self._env.world_size

    @property
    def schema(self) -> list[Field]:
        return [Field(k, c.type, c.has_nulls) for k, c in self._cols.items()]

    def column(self, name: str) -> Column:
        try:
            return self._cols[name]
        except KeyError:
            raise CylonKeyError(f"no column {name!r}; have {self.column_names}")

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    # -- projections (host-side metadata ops, zero device work) ------------
    def project(self, names: Iterable[str]) -> "Table":
        return Table({n: self.column(n) for n in names}, self._env, self._valid)

    def drop(self, names: Iterable[str]) -> "Table":
        drop = set(names)
        return Table({k: v for k, v in self._cols.items() if k not in drop},
                     self._env, self._valid)

    def rename(self, mapping: Mapping[str, str]) -> "Table":
        return Table({mapping.get(k, k): v for k, v in self._cols.items()},
                     self._env, self._valid)

    def with_columns(self, extra: Mapping[str, Column]) -> "Table":
        cols = dict(self._cols)
        cols.update(extra)
        return Table(cols, self._env, self._valid)

    # -- materialization ---------------------------------------------------
    def _concat_live(self, host, valid):
        w = self._env.world_size
        cap = self.capacity
        sl = [slice(i * cap, i * cap + int(self._valid[i])) for i in range(w)]
        data = np.concatenate([host[s] for s in sl]) if sl else host[:0]
        vcat = (np.concatenate([valid[s] for s in sl])
                if valid is not None else None)
        return data, vcat

    def host_column(self, name: str):
        """(data, validity) host arrays of one column's live rows in global
        order (shard valid prefixes concatenated) — multi-host aware.  For
        whole-table materialization use :meth:`host_columns` (ONE batched
        device fetch instead of per-column round-trips)."""
        from ..utils.host import host_arrays
        c = self.column(name)
        host, valid = host_arrays([c.data, c.validity])
        return self._concat_live(host, valid)

    def host_columns(self):
        """{name: (data, validity)} live-row host arrays for every column
        in ONE batched device fetch (the axon tunnel charges ~100 ms per
        sequential first fetch; utils.host.host_arrays overlaps them)."""
        from ..utils.host import host_arrays
        flat = []
        for c in self._cols.values():
            flat.append(c.data)
            flat.append(c.validity)
        pulled = host_arrays(flat)
        return {k: self._concat_live(pulled[2 * i], pulled[2 * i + 1])
                for i, k in enumerate(self._cols)}

    def to_pandas(self):
        import pandas as pd
        out = {}
        hosts = self.host_columns()
        for k, c in self._cols.items():
            data, vcat = hosts[k]
            out[k] = Column(data, c.type, vcat, c.dictionary).to_numpy(len(data))
        return pd.DataFrame(out)

    def to_arrow(self):
        from .arrow_interop import table_to_arrow
        return table_to_arrow(self)

    def to_pylist(self) -> list[dict]:
        return self.to_pandas().to_dict("records")

    def __repr__(self) -> str:  # pragma: no cover
        return (f"Table(rows={self.row_count}, cols={self.column_names}, "
                f"world={self._env.world_size}, cap={self.capacity})")


class DeferredTable(Table):
    """A Table whose columns materialize lazily on first data access.

    The TPU analog of the reference's streaming operator DAG
    (cpp/src/cylon/ops/, SURVEY §2 C9): an upstream operator (join) may
    hand its *pre-materialization state* to a compatible downstream
    consumer (groupby pushdown, relational/fused.py) without ever paying
    for the intermediate table; any other access runs the deferred
    materialization transparently.

    Schema queries (``column_names``/``schema``/``capacity``/counts)
    answer from stored metadata so DataFrame-level bookkeeping does not
    force materialization; ``column()``/``columns`` do."""

    __slots__ = ("_thunk", "_cap", "_meta", "op_state", "_counts_thunk")

    def __init__(self, env, valid_counts, capacity: int | None, thunk,
                 meta, op_state=None, counts_thunk=None):
        """``meta`` = (names, types, dicts, has_nulls) tuples parallel to
        the eventual columns; ``thunk()`` -> dict[str, Column]; ``op_state``
        is consumed by fused downstream operators (cleared on
        materialization).

        ``counts_thunk`` (with ``valid_counts=None``): the per-shard output
        counts are still on device — the producer dispatched its count
        phase but did NOT pull the result, so the NEXT operator's dispatch
        can be enqueued before this one's host sync (the pipelined piece
        loop's one-deep software pipeline).  First access of
        ``valid_counts``/``row_count``/``capacity`` pulls; a fused consumer
        that drains ``op_state`` never does."""
        self._thunk = None
        self._counts_thunk = None
        if valid_counts is None:
            if counts_thunk is None:
                raise InvalidError("DeferredTable needs valid_counts or "
                                   "counts_thunk")
            valid_counts = np.zeros(
                (env or default_env()).world_size, np.int64)
        super().__init__({}, env, valid_counts)
        self._counts_thunk = counts_thunk
        self._cap = None if capacity is None else int(capacity)
        self._meta = meta
        self._thunk = thunk
        self.op_state = op_state

    # _valid shadows the Table slot: reads pull the pending device counts
    @property
    def _valid(self):
        if self._counts_thunk is not None:
            th, self._counts_thunk = self._counts_thunk, None
            Table._valid.__set__(self, np.asarray(th(), np.int64))
        return Table._valid.__get__(self)

    @_valid.setter
    def _valid(self, v):
        self._counts_thunk = None
        Table._valid.__set__(self, v)

    # _cols shadows the Table slot: reads trigger materialization
    @property
    def _cols(self):
        if self._thunk is not None:
            thunk, self._thunk = self._thunk, None
            # drop the fused-consumer state BEFORE materializing: it pins
            # N-length device buffers the thunk never reads, and peak HBM
            # during the expansion is the binding constraint
            self.op_state = None
            out = thunk()
            if isinstance(out, Table):
                # OOM-fallback protocol: the thunk re-ran the whole
                # operator down a streaming path and produced a fresh
                # Table — adopt its layout (per-shard counts/capacity may
                # differ from the deferred prediction; global rows match)
                Table._cols.__set__(self, dict(out.columns))
                self._valid = out.valid_counts
                self._cap = out.capacity
                self.grouped_by = out.grouped_by
            else:
                Table._cols.__set__(self, dict(out))
        return Table._cols.__get__(self)

    @_cols.setter
    def _cols(self, v):
        Table._cols.__set__(self, v)

    @property
    def materialized(self) -> bool:
        return self._thunk is None

    # -- schema without materialization ------------------------------------
    @property
    def column_names(self) -> list[str]:
        return list(self._meta[0])

    @property
    def column_count(self) -> int:
        return len(self._meta[0])

    @property
    def capacity(self) -> int:
        if self._cap is None:
            # capacity prediction pending on the device counts (lazy-count
            # deferred join): pull and bucket exactly like the producer
            # would have
            from .. import config
            counts = self._valid
            self._cap = config.pow2ceil(int(counts.max())
                                        if counts.size else 1)
        return self._cap

    @property
    def schema(self) -> list[Field]:
        return [Field(n, t, hn) for n, t, hn in
                zip(self._meta[0], self._meta[1], self._meta[3])]

    def __contains__(self, name: str) -> bool:
        return name in self._meta[0]


def _column_from_series(s) -> Column:
    """pandas Series -> HOST Column, nullable-extension-dtype aware: masked
    numeric/boolean dtypes (Int64/Float64/boolean, with .numpy_dtype) keep
    their numeric payload + a validity mask instead of collapsing to an
    object array of pd.NA (which would stringify); everything else takes
    the plain to_numpy path (object/str columns dictionary-encode with a
    pd.isna mask in Column._encode_strings)."""
    import pandas as pd
    npdt = getattr(s.dtype, "numpy_dtype", None)
    if npdt is not None and npdt.kind in ("i", "u", "f", "b"):
        mask = np.asarray(s.isna(), bool)
        if mask.any():
            vals = s.to_numpy(dtype=npdt, na_value=0)
            col = Column.from_numpy(vals)
            return Column(col.data, col.type, ~mask, col.dictionary,
                          bounds=col.bounds)
        return Column.from_numpy(s.to_numpy(dtype=npdt))
    return Column.from_numpy(s.to_numpy())


def _put(host: np.ndarray, sharding):
    """Place a host array under a sharding.  device_put in single-controller
    mode; in multi-controller (jax.distributed) mode each process holds the
    same full host copy and materializes only its addressable shards
    (SPMD ingest — the reference's per-rank partition reads).

    This is the documented host→device UPLOAD boundary (trace-safety,
    docs/trace_safety.md): device_put/make_array_from_callback are
    explicit transfers, permitted under every transfer-guard level the
    test rig uses; the matching device→host boundary is the
    utils/host.py pull funnel."""
    import jax as _jax
    if _jax.process_count() > 1:
        return _jax.make_array_from_callback(host.shape, sharding,
                                             lambda idx: host[idx])
    return _jax.device_put(host, sharding)


def _place_local(cols: dict[str, Column], env: CylonEnv) -> dict[str, Column]:
    """Place host-built columns onto the env's (single) device — only the
    env's devices are ever touched, never the process default backend (the
    round-1 multichip dryrun died on exactly that leak)."""
    sharding = env.sharding()
    out = {}
    for k, c in cols.items():
        data = _put(np.asarray(c.data), sharding)
        v = (_put(np.asarray(c.validity), sharding)
             if c.validity is not None else None)
        out[k] = Column(data, c.type, v, c.dictionary, bounds=c.bounds)
    return out


def _ingest(cols: dict[str, Column], env: CylonEnv) -> Table:
    """Ingest dispatch — the shape-family canonicalization gate
    (exec/compiler.family_cap, docs/robustness.md "Compile lifecycle").

    Single-controller tables historically placed EXACT shapes
    (``_place_local``), so every distinct tenant row count compiled its
    own program family — compile cost O(tenants).  With shape families
    armed (the default) a world-1 ingest whose row count is not already
    its own family representative routes through :func:`_distribute`,
    which pow2-pads the capacity with a masked validity tail — exactly
    what multi-rank ingest always did — so near-miss row counts share
    one compiled program per plan shape, bit- and order-equal.
    ``CYLON_TPU_SHAPE_FAMILIES=0`` (and already-canonical or empty
    ingests) keep the zero-copy exact placement."""
    if env.world_size == 1:
        from ..exec.compiler import family_cap
        n = len(next(iter(cols.values()))) if cols else 0
        if family_cap(n) == n:
            return Table(_place_local(cols, env), env)
    return _distribute(cols, env)


def _distribute(cols: dict[str, Column], env: CylonEnv) -> Table:
    """Split host-built columns into W contiguous row blocks, pad each to the
    common capacity, and place them sharded on the mesh.  This is the
    single-controller analog of per-rank partition ingestion (reference:
    each rank reads its own partition, docs/docs/arch.md:42-47)."""
    from .. import config
    n = len(next(iter(cols.values()))) if cols else 0
    w = env.world_size
    chunk = -(-n // w)  # contiguous rows per rank (last ranks may get fewer)
    # pow2-bucketed capacity: bounds the family of compiled shapes across
    # ingests of varying row counts (config.POW2_CAPACITIES)
    cap = config.pow2ceil(chunk)
    # the canonicalization decision is a pure function of (rows, world) —
    # rank-uniform, no vote — recorded on the active plan node (no-op
    # without a profile) so EXPLAIN output shows the family bucket
    from ..obs.plan import annotate
    annotate(shape_family=int(cap), ingest_rows=int(n))
    valid = np.asarray([max(0, min(chunk, n - i * chunk)) for i in range(w)],
                       np.int64)
    sharding = env.sharding()
    out = {}
    for k, c in cols.items():
        host = np.asarray(c.data)
        padded = np.zeros((w * cap,) + host.shape[1:], host.dtype)
        vhost = np.asarray(c.validity) if c.validity is not None else None
        vpad = np.zeros(w * cap, bool) if vhost is not None else None
        for i in range(w):
            m = int(valid[i])
            if m:
                padded[i * cap: i * cap + m] = host[i * chunk: i * chunk + m]
                if vpad is not None:
                    vpad[i * cap: i * cap + m] = vhost[i * chunk: i * chunk + m]
        data = _put(padded, sharding)
        v = _put(vpad, sharding) if vpad is not None else None
        # padding rows are zeros — covered by widening bounds to include 0
        b = c.bounds
        if b is not None:
            b = (min(b[0], 0), max(b[1], 0))
        out[k] = Column(data, c.type, v, c.dictionary, bounds=b)
    return Table(out, env, valid)
