"""Logical type system.

TPU-native equivalent of the reference's ``cylon::DataType`` id layer
(reference: cpp/src/cylon/data_types.hpp, 225 LoC) which mirrors Arrow types.
Here a :class:`LogicalType` names the user-visible type while the physical
representation is always a fixed-width device array (strings/binary are
dictionary-encoded to int32 codes with a host-side value table — the reference
itself flattens variable-width keys to binary for hashing,
util/flatten_array.cpp).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np

from .. import config
from ..status import CylonTypeError


class LogicalType(enum.Enum):
    BOOL = "bool"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    STRING = "string"          # dictionary-encoded, codes int32
    DATE64 = "datetime64[ns]"  # physical int64 nanoseconds
    TIMEDELTA = "timedelta64[ns]"
    DECIMAL = "decimal"        # physical int64, scaled by DecimalScale
    LIST = "list"              # host passthrough: int32 codes into values


_NUMERIC_NP = {
    LogicalType.BOOL: np.bool_,
    LogicalType.INT8: np.int8,
    LogicalType.INT16: np.int16,
    LogicalType.INT32: np.int32,
    LogicalType.INT64: np.int64,
    LogicalType.UINT8: np.uint8,
    LogicalType.UINT16: np.uint16,
    LogicalType.UINT32: np.uint32,
    LogicalType.UINT64: np.uint64,
    LogicalType.FLOAT32: np.float32,
    LogicalType.FLOAT64: np.float64,
}

_FLOATS = (LogicalType.FLOAT32, LogicalType.FLOAT64)


def physical_np_dtype(lt: LogicalType) -> np.dtype:
    """The numpy dtype of the device representation of ``lt``."""
    if lt in (LogicalType.STRING, LogicalType.LIST):
        return np.dtype(np.int32)
    if lt in (LogicalType.DATE64, LogicalType.TIMEDELTA,
              LogicalType.DECIMAL):
        return np.dtype(np.int64)
    d = np.dtype(_NUMERIC_NP[lt])
    if not config.X64_ENABLED and d.itemsize == 8:
        # x64 disabled: degrade 64-bit to 32-bit device storage.
        return np.dtype(d.kind + "4")
    return d


def from_numpy_dtype(dt: np.dtype) -> LogicalType:
    dt = np.dtype(dt)
    if dt.kind == "M":
        return LogicalType.DATE64
    if dt.kind == "m":
        return LogicalType.TIMEDELTA
    if dt.kind in ("U", "S", "O"):
        return LogicalType.STRING
    try:
        return LogicalType(dt.name)
    except ValueError as e:
        raise CylonTypeError(f"unsupported dtype {dt}") from e


def is_floating(lt: LogicalType) -> bool:
    return lt in _FLOATS


def is_integer(lt: LogicalType) -> bool:
    return lt.value.startswith(("int", "uint"))


def is_numeric(lt: LogicalType) -> bool:
    return lt in _NUMERIC_NP and lt != LogicalType.BOOL


@dataclass(frozen=True)
class Field:
    """Column schema entry: name + logical type + nullability."""

    name: str
    type: LogicalType
    nullable: bool = False
