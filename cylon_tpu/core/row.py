"""Row & Scalar — reference ``cylon::Row`` (row.hpp, used by
``Table::Select``, table.cpp:892) and ``cylon::Scalar`` (scalar.hpp,
wrapping ``arrow::Scalar``).

In the device-resident model a Row is a host-side *view* of one global row
(gathered lazily on first access — row access is an inherently host-facing
operation), and a Scalar wraps one typed value with its logical type, as
produced by column reductions and consumed by comparisons/fills.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..status import CylonKeyError, InvalidError
from .dtypes import LogicalType


class Scalar:
    """One typed value (reference scalar.hpp).  ``value`` is a python/numpy
    scalar or None (null)."""

    __slots__ = ("value", "type")

    def __init__(self, value: Any, type: LogicalType):
        self.value = value
        self.type = type

    @property
    def is_null(self) -> bool:
        return self.value is None or (
            isinstance(self.value, float) and np.isnan(self.value))

    def __repr__(self) -> str:  # pragma: no cover
        return f"Scalar({self.value!r}, {self.type.value})"

    def __eq__(self, other) -> bool:
        o = other.value if isinstance(other, Scalar) else other
        if self.is_null:
            return o is None
        return bool(self.value == o)

    def __hash__(self):
        return hash((self.value, self.type))


class Row:
    """One global row of a DataFrame/Table (reference row.hpp).  Values are
    gathered to the host on first access and cached."""

    __slots__ = ("_df", "_i", "_values")

    def __init__(self, df, i: int):
        n = len(df)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise InvalidError(f"row {i} out of range for {n} rows")
        self._df = df
        self._i = i
        self._values: dict | None = None

    def _load(self) -> dict:
        if self._values is None:
            # one-row global slice -> host (a row access is host-facing);
            # restricted to VISIBLE columns so a drop=True index column
            # stays hidden here exactly as it is on the frame
            from ..relational.repart import slice_table
            one = slice_table(self._df.table, self._i, 1).to_pandas()
            rec = one.to_dict("records")[0] if len(one) else {}
            vis = list(self._df.columns)
            self._values = {k: (None if isinstance(rec[k], float)
                                and np.isnan(rec[k]) else rec[k])
                            for k in vis if k in rec}
        return self._values

    @property
    def columns(self) -> list[str]:
        return self._df.columns

    def __getitem__(self, name: str):
        vals = self._load()
        if name not in vals:
            raise CylonKeyError(f"no column {name!r}")
        return vals[name]

    def scalar(self, name: str) -> Scalar:
        col = self._df.table.column(name)
        return Scalar(self[name], col.type)

    def to_dict(self) -> dict:
        return dict(self._load())

    def __iter__(self):
        vals = self._load()
        return iter(vals.values())

    def __repr__(self) -> str:  # pragma: no cover
        return f"Row({self._i}, {self._load()!r})"
