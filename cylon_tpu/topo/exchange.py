"""The hierarchical two-hop exchange engine (docs/topology.md).

Runs one logical hash/range exchange — identical inputs and outputs to
the flat engine in :mod:`cylon_tpu.parallel.shuffle` — as two grouped
collectives on a two-tier fabric:

* **hop 1 (ICI)**: a slice-local all-to-all (``lax.all_to_all`` with
  ``axis_index_groups`` = the slice blocks) routes every row to its
  destination's *gateway-local bucket*: the in-slice rank whose local
  index matches the final destination's (:func:`model.gateway_of`).
  The row's final target rides along as one int32 sidecar lane.
* **hop 2 (DCN)**: a cross-slice all-to-all between same-local ranks
  (groups = the local-index columns) delivers each (src-slice,
  dst-slice) payload in ONE aggregated message per local index —
  O(rows) bytes over DCN once, instead of the flat plan's
  O(rows × peers) small padded messages: each rank's DCN partner count
  drops from ``(S-1)·R`` to ``S-1`` (cross-slice message count exactly
  1/R of the flat plan's — the acceptance instrument,
  :func:`tier_traffic`), and the padded cross-slice wire volume drops
  toward 1/R wherever the count matrix is concentrated
  (order-preserving repartition/sort bands, low-cardinality keys) —
  cross-slice payload itself is route-invariant, as it must be.

**Order preservation** (the bit/order-equality contract): with the
slice-major layout, hop 1's receive order at gateway ``(s, j)`` is
(local source ``i`` ascending, source position ascending); restricted
to rows bound for one final rank ``(D, j)`` that order survives hop 2's
stable per-target sort, and hop 2's receive order at ``(D, j)`` is
(source slice ``s`` ascending, hop-1 position ascending) — composing to
exactly (global source rank ``s·R + i``, source position), the flat
exchange's contract (table.cpp:182-190 in the reference; proof sketch
in docs/topology.md).  No position sidecar, no final re-sort: the
composition is order-preserving by construction.

Both hops' count matrices are pure host arithmetic on the ALREADY
PULLED global count sidecar (:func:`hop_counts`) — the two-hop route
adds zero host syncs and zero device pulls over the flat plan.

This module is part of the ``cylon_tpu/topo`` plan facade (lint rule
TS116): callers route through :func:`two_hop` with a plan the facade
voted; the gateway math and hop programs are not callable decisions
elsewhere.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .. import config
from ..ctx.context import ROW_AXIS
from ..utils.cache import jit, program_cache

shard_map = jax.shard_map


# ---------------------------------------------------------------------------
# host math: per-hop count matrices from the global sidecar
# ---------------------------------------------------------------------------

def hop_counts(counts: np.ndarray, n_slices: int) -> tuple:
    """(C1, C2): the two hops' (W, W) count matrices from the logical
    exchange's global count matrix ``C`` — pure host numpy, no device
    work (part of the TS116 facade: the gateway assignment is encoded
    here and nowhere else).

    ``C1[(s,i), (s,j)] = Σ_D C[(s,i), (D,j)]`` — source ``(s,i)``'s rows
    bound for ANY rank with local index ``j`` go to the in-slice
    gateway ``(s,j)``; every C1 cell is slice-local (ICI).

    ``C2[(s,j), (D,j)] = Σ_i C[(s,i), (D,j)]`` — gateway ``(s,j)``
    forwards slice ``s``'s aggregated payload for ``(D,j)``; every C2
    cell connects same-local ranks (diagonal ``D = s`` stays ICI, the
    rest crosses DCN exactly once).

    Row sums of C1 = C's row sums, column sums of C2 = C's column sums,
    and C1's column sums = C2's row sums — the conservation identities
    tests/test_topo.py asserts."""
    c = np.asarray(counts, np.int64)
    w = c.shape[0]
    s_, r_ = int(n_slices), w // int(n_slices)
    c4 = c.reshape(s_, r_, s_, r_)           # [s, i, D, j]
    c1 = np.zeros((w, w), np.int64)
    c2 = np.zeros((w, w), np.int64)
    m1 = c4.sum(axis=2)                      # [s, i, j]
    m2 = c4.sum(axis=1)                      # [s, D, j]
    for s in range(s_):
        c1[s * r_:(s + 1) * r_, s * r_:(s + 1) * r_] = m1[s]
        for d in range(s_):
            c2[s * r_ + np.arange(r_), d * r_ + np.arange(r_)] = m2[s, d]
    return c1, c2


def hop_block(counts_hop: np.ndarray, total: int, w: int,
              group: int) -> tuple[int, int]:
    """(block, rounds) for one grouped hop — the flat engine's sizing
    rule with the per-rank cell count ``w·group`` replacing ``w²``:
    block ≈ 2× the uniform stream, floored for tiny tables, and rounds
    bound peak send memory at ``group·block`` under skew."""
    max_c = int(counts_hop.max()) if counts_hop.size else 1
    uniform = -(-int(total) // max(w * group, 1))
    cap = config.pow2ceil(max(2 * uniform, 8192))
    block = config.pow2ceil(min(max(max_c, 1), cap))
    rounds = -(-max_c // block) if max_c else 1
    return block, max(rounds, 1)


# ---------------------------------------------------------------------------
# device programs
# ---------------------------------------------------------------------------

@program_cache()
def _hop1_targets_fn(mesh: Mesh, w: int, n_slices: int):
    """Final target → hop-1 gateway target (pure-local): destination
    ``d``'s rows bucket on the in-slice rank ``my_slice·R + d % R``;
    the trash destination ``w`` passes through."""
    r_ = w // n_slices

    def per_shard(tgt):
        my = jax.lax.axis_index(ROW_AXIS)
        base = (my // r_) * r_
        g = base + jnp.clip(tgt, 0, w - 1) % r_
        return jnp.where(tgt < w, g.astype(jnp.int32), jnp.int32(w))

    return jit(shard_map(per_shard, mesh=mesh, in_specs=(P(ROW_AXIS),),
                             out_specs=P(ROW_AXIS)))


@program_cache()
def _hop2_targets_fn(mesh: Mesh, w: int, cap: int):
    """Hop-2 targets from the hop-1-delivered final-target sidecar:
    live rows keep their carried target, receive-buffer padding (zeros)
    masks to the trash destination via the hop-1 valid counts."""

    def per_shard(vc, tgt):
        my = jax.lax.axis_index(ROW_AXIS)
        mask = jnp.arange(cap, dtype=jnp.int32) < vc[my]
        return jnp.where(mask, jnp.clip(tgt, 0, w - 1), jnp.int32(w))

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(P(), P(ROW_AXIS)),
                             out_specs=P(ROW_AXIS)))


@program_cache()
def _tier_round_fn(mesh: Mesh, w: int, n_slices: int, hop: int,
                   block: int, out_cap: int, rounds: int = 1):
    """The grouped exchange round engine — the flat ``_round_fn`` with
    the all-to-all restricted to a tier's groups:

    * ``hop == 1`` (ICI): groups are the slice blocks
      ``[sR .. sR+R)``; a target's slot index within my group is its
      local index ``tgt % R`` (targets are in-slice by construction of
      :func:`_hop1_targets_fn`).
    * ``hop == 2`` (DCN): groups are the local-index columns
      ``[j, R+j, ...]``; a target's slot index is its slice ``tgt // R``.

    Send buffers are ``G·block`` rows (G = group size) — the grouped
    collective moves G·block per rank per round instead of the flat
    engine's W·block, which is where the ~1/R cross-slice wire
    reduction comes from.  Receive placement is the flat engine's:
    slot ``k = src_in_group·block + q`` holds group-source
    ``src_in_group``'s row ``lo + q``, scattered straight to final
    position (rows from earlier group sources) + lo + q — group order
    is ascending global rank for both tiers, so the receive order
    composes to the flat contract.  Multi-round runs under one
    static-trip fori_loop exactly like the flat engine (the collective
    stays unconditional — the JX201 invariant)."""
    r_ = w // n_slices
    g = r_ if hop == 1 else n_slices
    if hop == 1:
        groups = [[s * r_ + i for i in range(r_)] for s in range(n_slices)]
    else:
        groups = [[s * r_ + j for s in range(n_slices)] for j in range(r_)]

    def one_round(r, tgt_s, perm, pos, counts, outs, cols, my):
        lo = r * block
        tgt_c = jnp.clip(tgt_s, 0, w - 1)
        gidx = (tgt_c % r_) if hop == 1 else (tgt_c // r_)
        sel = (tgt_s < w) & (pos >= lo) & (pos < lo + block)
        slot = jnp.where(sel, gidx * block + (pos - lo),
                         jnp.int32(g * block))
        # receiver: slot k = src_in_group*block + q; the group's sources
        # ascend in GLOBAL rank order for both tiers, so earlier-source
        # offsets reproduce the flat engine's placement
        if hop == 1:
            src_ranks = (my // r_) * r_ + jnp.arange(g, dtype=jnp.int32)
        else:
            src_ranks = jnp.arange(g, dtype=jnp.int32) * r_ + (my % r_)
        recv_g = counts[src_ranks, my]
        rcsum = jnp.cumsum(recv_g)
        roffs = jnp.concatenate([jnp.zeros(1, rcsum.dtype), rcsum[:-1]])
        k = jnp.arange(g * block, dtype=jnp.int32)
        sg = k // block
        q = k - sg * block
        valid = (lo + q) < recv_g[sg]
        fslot = jnp.where(valid, roffs[sg].astype(jnp.int32) + lo + q,
                          jnp.int32(out_cap))
        new_outs = []
        for out, col in zip(outs, cols):
            send = jnp.zeros((g * block,) + col.shape[1:], col.dtype)
            send = send.at[slot].set(col[perm], mode="drop")
            recv = jax.lax.all_to_all(send, ROW_AXIS, split_axis=0,
                                      concat_axis=0, tiled=True,
                                      axis_index_groups=groups)
            new_outs.append(out.at[fslot].set(recv, mode="drop"))
        return tuple(new_outs)

    def per_shard(tgt_s, perm, pos, counts, outs, cols):
        my = jax.lax.axis_index(ROW_AXIS)
        if rounds == 1:
            return one_round(jnp.int32(0), tgt_s, perm, pos, counts, outs,
                             cols, my)
        return jax.lax.fori_loop(
            0, rounds,
            lambda r, o: one_round(jnp.int32(r), tgt_s, perm, pos, counts,
                                   o, cols, my),
            tuple(outs))

    def fn(tgt_s, perm, pos, counts, outs, cols):
        n = len(cols)
        specs_in = (P(ROW_AXIS),) * 3 + (P(),) \
            + ((P(ROW_AXIS),) * n,) + ((P(ROW_AXIS),) * n,)
        sm = shard_map(per_shard, mesh=mesh, in_specs=specs_in,
                       out_specs=(P(ROW_AXIS),) * n)
        return sm(tgt_s, perm, pos, counts, outs, cols)

    return jit(fn, donate_argnums=(4,))


# ---------------------------------------------------------------------------
# the orchestrator
# ---------------------------------------------------------------------------

def two_hop(mesh: Mesh, plan, tgt, counts: np.ndarray, cols: tuple,
            out_cap: int, prep: HopPrep | None = None):
    """Run one logical exchange through the two-tier route per the
    VOTED plan: hop-1 slice-local alignment (final target riding as a
    sidecar lane), hop-2 aggregated cross-slice delivery.  Inputs and
    outputs match the flat engine's phase B exactly — ``(outs tuple,
    per-dest valid counts)`` with identical values, order and (pow2)
    capacities, which is what makes the route transparent to every
    operator riding ``shuffle_table`` (docs/topology.md).

    ``counts`` is the logical (W, W) sidecar the caller already pulled;
    both hop matrices derive from it on the host (:func:`hop_counts`) —
    no extra pulls, no extra syncs."""
    from ..parallel import shuffle as shf
    from ..utils import timing

    w = counts.shape[0]
    s_ = plan.n_slices
    p = prep if prep is not None else HopPrep(plan, counts)
    c1, c2 = p.c1, p.c2
    block1, rounds1, block2, rounds2 = (p.block1, p.rounds1, p.block2,
                                        p.rounds2)
    cap1 = p.cap1

    timing.bump("exchange.two_hop")
    if rounds1 > 1 or rounds2 > 1:
        timing.bump("exchange.multiround")

    # hop 1: slice-local alignment over ICI, final target as sidecar
    tgt1 = _hop1_targets_fn(mesh, w, s_)(tgt)
    c1_i = np.asarray(c1, np.int32)
    tgt1_s, perm1, pos1 = shf._prep_fn(mesh, w)(tgt1, c1_i)
    cols1 = tuple(cols) + (tgt,)
    outs1 = tuple(shf._alloc_fn(mesh, cap1, str(c.dtype), c.shape[1:])()
                  for c in cols1)
    outs1 = _tier_round_fn(mesh, w, s_, 1, block1, cap1,
                           max(rounds1, 1))(tgt1_s, perm1, pos1, c1_i,
                                            outs1, cols1)

    # hop 2: aggregated cross-slice delivery over DCN
    vc1 = np.asarray(p.per_gw, np.int32)
    tgt2 = _hop2_targets_fn(mesh, w, cap1)(vc1, outs1[-1])
    c2_i = np.asarray(c2, np.int32)
    tgt2_s, perm2, pos2 = shf._prep_fn(mesh, w)(tgt2, c2_i)
    outs = tuple(shf._alloc_fn(mesh, out_cap, str(c.dtype), c.shape[1:])()
                 for c in cols)
    outs = _tier_round_fn(mesh, w, s_, 2, block2, out_cap,
                          max(rounds2, 1))(tgt2_s, perm2, pos2, c2_i,
                                           outs, outs1[:-1])
    return outs, counts.sum(axis=0).astype(np.int64)


class HopPrep:
    """One logical exchange's derived two-hop schedule — both hop count
    matrices, their block/round sizing and the gateway capacity —
    computed ONCE per exchange (``hop_counts`` is O(W²) host numpy with
    per-slice Python loops, and a guarded multi-slice exchange would
    otherwise derive it three times: guard, tier counters, dispatch)."""

    __slots__ = ("c1", "c2", "block1", "rounds1", "block2", "rounds2",
                 "per_gw", "cap1")

    def __init__(self, plan, counts: np.ndarray):
        w = counts.shape[0]
        total = int(counts.sum()) if counts.size else 0
        self.c1, self.c2 = hop_counts(counts, plan.n_slices)
        self.block1, self.rounds1 = hop_block(self.c1, total, w,
                                              plan.ranks_per_slice)
        self.block2, self.rounds2 = hop_block(self.c2, total, w,
                                              plan.n_slices)
        #: per-gateway received rows (hop-1 column sums) — also hop 2's
        #: valid-count sidecar
        self.per_gw = self.c1.sum(axis=0)
        #: hop-1 gateway receive capacity (pow2): a gateway buckets its
        #: whole slice's traffic for one local index
        self.cap1 = config.pow2ceil(int(self.per_gw.max())
                                    if self.per_gw.size else 1)
        # always-on conservation laws over the derived hop matrices
        # (exec/integrity — the audit facade owns the typed raise):
        # host math on arrays this constructor just built, zero device
        # work, checked ONCE per exchange at derivation time
        from ..exec import integrity as _integrity
        _integrity.conserve_hops(counts, self.c1, self.c2)


def prepare(plan, counts: np.ndarray) -> HopPrep:
    """Derive the two-hop schedule for one exchange (see
    :class:`HopPrep`) — the caller threads it through the guard sizing,
    the tier accounting and :func:`two_hop`."""
    return HopPrep(plan, counts)


def recv_guard_bytes(plan, prep: HopPrep, out_cap: int,
                     row_bytes: int) -> int:
    """The hierarchical route's peak RECEIVE allocation in BYTES, for
    the flat engine's pre-allocation guard: the hop-1 gateway buffers
    (payload + the 4-byte int32 final-target sidecar lane) are still
    alive — as hop 2's inputs — while the final ``out_cap`` buffers are
    allocated and filled, so the peak is the SUM of the tiers, not
    their max (parallel/shuffle.exchange)."""
    return prep.cap1 * (int(row_bytes) + 4) + out_cap * int(row_bytes)


def tier_traffic(plan, counts: np.ndarray, row_bytes: int, route: str,
                 prep: HopPrep | None = None,
                 flat_block_rounds: tuple | None = None) -> dict:
    """Per-tier link traffic of one logical exchange — the PADDED wire
    volume and the (src, dst, round) MESSAGE count each tier's
    interconnect actually carries, per route (docs/topology.md "What
    the two-hop route buys").

    Stated plainly: cross-slice PAYLOAD is route-invariant — every row
    bound for a remote slice crosses DCN exactly once whichever route
    carries it — so the two-hop win is (a) the DCN **message count**,
    W·(S−1) aggregated transfers per round instead of the flat plan's
    W·(W−R) small ones — exactly 1/R, each rank keeping S−1 DCN
    partners instead of (S−1)·R (the α-term of the α·messages +
    β·bytes cost model, which is what "O(rows × peers) small messages"
    costs on a real fabric) — and (b) the padded **wire bytes** in
    concentrated-count regimes (order-preserving repartition/sort
    bands, low-cardinality keys), where the flat plan pads every one of
    its W−R cross-slice cells per rank to the global block while the
    aggregated hop-2 cells stay near their payload.

    ``route == "flat"``: the one-hop engine's W² cells at its block
    (``flat_block_rounds`` takes the (block, rounds) the flat engine
    already computed instead of re-deriving them); hierarchical: hop 1
    (all ICI) + hop 2 (diagonal ICI, rest DCN) at the ``prep``
    schedule's group blocks."""
    w = counts.shape[0]
    s_, r_ = plan.n_slices, plan.ranks_per_slice
    total = int(counts.sum()) if counts.size else 0
    rb = int(row_bytes)
    if route == "flat":
        if flat_block_rounds is not None:
            block, rounds = flat_block_rounds
        else:
            from ..parallel.shuffle import exchange_block_cap
            max_c = int(counts.max()) if counts.size else 1
            block = config.pow2ceil(min(max(max_c, 1),
                                        exchange_block_cap(total, w)))
            rounds = -(-max_c // block) if max_c else 1
        rounds = max(int(rounds), 1)
        return {"wire_ici": w * r_ * block * rounds * rb,
                "wire_dcn": w * (w - r_) * block * rounds * rb,
                "msgs_ici": w * r_ * rounds,
                "msgs_dcn": w * (w - r_) * rounds}
    p = prep if prep is not None else HopPrep(plan, counts)
    return {"wire_ici": (w * r_ * p.block1 * p.rounds1
                         + w * 1 * p.block2 * p.rounds2) * rb,  # h2 diag
            "wire_dcn": w * (s_ - 1) * p.block2 * p.rounds2 * rb,
            "msgs_ici": w * r_ * p.rounds1 + w * 1 * p.rounds2,
            "msgs_dcn": w * (s_ - 1) * p.rounds2}


# ---------------------------------------------------------------------------
# trace-safety declarations (cylon_tpu.analysis.registry) — the jaxpr
# pass verifies the two-hop engine's SPMD invariants: the grouped
# all_to_all must stay UNCONDITIONAL (multi-round runs under a
# static-trip fori_loop → scan, identical on every rank: allowed; never
# cond/while — rank-divergent group participation deadlocks both
# tiers), and the target/sidecar programs are pure-local.
# docs/trace_safety.md.
# ---------------------------------------------------------------------------

def _trace_tier_round(mesh):
    w, cap, S = _decl_shapes(mesh)
    n_slices = 2 if w % 2 == 0 and w >= 4 else 1
    if n_slices == 1:   # degenerate rig: nothing hierarchical to trace
        return jax.make_jaxpr(lambda x: x)(S((w,), np.int32))
    block, out_cap = cap // 4, 2 * cap
    i32 = np.int32
    hop1 = _unwrap(_tier_round_fn(mesh, w, n_slices, 1, block, out_cap, 3))
    hop2 = _unwrap(_tier_round_fn(mesh, w, n_slices, 2, block, out_cap, 1))

    def both(tgt_s, perm, pos, counts, outs, cols):
        a = hop1(tgt_s, perm, pos, counts, outs, cols)
        b = hop2(tgt_s, perm, pos, counts, outs, cols)
        return a, b

    args = (S((w * cap,), i32), S((w * cap,), i32), S((w * cap,), i32),
            S((w, w), i32), (S((w * out_cap,), np.int64),),
            (S((w * cap,), np.int64),))
    return jax.make_jaxpr(both)(*args)


def _trace_hop1_targets(mesh):
    w, cap, S = _decl_shapes(mesh)
    n_slices = 2 if w % 2 == 0 and w >= 4 else 1
    if n_slices == 1:
        return jax.make_jaxpr(lambda x: x)(S((w,), np.int32))
    fn = _unwrap(_hop1_targets_fn(mesh, w, n_slices))
    return jax.make_jaxpr(fn)(S((w * cap,), np.int32))


def _trace_hop2_targets(mesh):
    w, cap, S = _decl_shapes(mesh)
    fn = _unwrap(_hop2_targets_fn(mesh, w, cap))
    return jax.make_jaxpr(fn)(S((w,), np.int32), S((w * cap,), np.int32))


from ..analysis.registry import (declare_builder, decl_shapes as _decl_shapes,  # noqa: E402
                                 unwrap as _unwrap)

declare_builder(f"{__name__}._tier_round_fn", _trace_tier_round,
                collectives={"all_to_all"}, tags=("shuffle", "topo"))
declare_builder(f"{__name__}._hop1_targets_fn", _trace_hop1_targets,
                tags=("shuffle", "topo"))
declare_builder(f"{__name__}._hop2_targets_fn", _trace_hop2_targets,
                tags=("shuffle", "topo"))
