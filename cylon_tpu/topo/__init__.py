"""Multi-slice topology tier: the hierarchical two-hop shuffle over
ICI + DCN (ROADMAP item 5, docs/topology.md).

* :mod:`cylon_tpu.topo.model` — the plan facade (lint rule TS116):
  slice discovery (jax device attributes, ``CYLON_TPU_SLICES``
  simulation knob), the slice-major tier model, gateway assignment,
  and the consensus-voted :class:`~cylon_tpu.topo.model.TopologyPlan`.
* :mod:`cylon_tpu.topo.exchange` — the two-hop exchange engine
  (slice-local ICI alignment, one aggregated cross-slice DCN hop),
  bit- and order-equal to the flat plan by construction.

Import-light by design: :mod:`ctx.context` imports the model for
slice-major device ordering, and the exchange engine (which imports
the parallel transport) loads lazily from
``parallel/shuffle.exchange``'s hierarchical route.
"""

from .model import (Topology, TopologyPlan, declared_slices,  # noqa: F401
                    ensure_adopted, gateway_of, hier_plan, last_plan,
                    slice_major_order, tier_split, topology)
