"""Multi-slice topology model — THE one place slice maps, gateway
assignment and topology-plan construction happen (lint rule TS116,
docs/topology.md).

Everything below ROADMAP item 5 assumed one slice where all-to-all is
uniform.  A real TPU fleet is a two-tier fabric: chips within a slice
talk over ICI, slices talk over DCN ("DCN between pods via jax's
multi-slice runtime", SURVEY §5.8) — inter-slice ≠ intra-slice, the
same asymmetry the reference built its entire net layer around.  This
module is the plan facade for that fabric:

1. **Discovery** — slices come from jax device attributes
   (``slice_index`` on a real multi-slice fleet) or from the
   ``CYLON_TPU_SLICES=<n>`` declaration (contiguous slice-major blocks
   over the visible devices — the CPU-grid simulation knob the tests
   and chaos schedules use today).  Non-uniform or non-dividing slice
   shapes degrade to a single-slice topology (flat route), never an
   error: topology is an optimization, not a correctness input.

2. **Slice-major layout** — rank ``r`` lives in slice ``r // R`` at
   local index ``r % R`` (``R`` ranks per slice).  Slice-major is what
   keeps ``repart``'s order-preserving index math valid under the
   two-hop exchange: both hops' receive orders compose to exactly the
   flat exchange's (source rank, source position) order
   (docs/topology.md, "Order preservation").

3. **Gateway scheme** — the two-hop route's hop 1 sends a row destined
   for global rank ``d`` to the slice-LOCAL rank ``d % R`` (the
   destination's *gateway-local bucket*): after hop 1, every row of
   slice ``s`` bound for any ``(D, j)`` sits on ``(s, j)``, so hop 2 is
   one aggregated cross-slice exchange per (src-slice, dst-slice) pair
   — O(rows) over DCN once, instead of O(rows × peers) small padded
   messages (:func:`gateway_of`).

4. **Plan + vote** — the route choice (flat vs hierarchical, slice map,
   gateway scheme) is a canonical :class:`TopologyPlan` whose sha256
   hash is voted over the PR 3 consensus wire
   (:func:`cylon_tpu.exec.recovery.topo_plan_consensus`,
   ``Code.TopoPlan``) BEFORE the first hierarchical collective — so
   recovery ladders, checkpoints and elastic resume (slice loss →
   PR 9 re-shard onto the surviving world) all adopt ONE topology.

The single-slice / unarmed path is one cached lookup per exchange:
zero collectives, zero votes, zero host syncs (asserted in
tests/test_topo.py and the chaos ``--multislice`` unarmed leg).
"""

from __future__ import annotations

import hashlib
import os

import numpy as np

from .. import config

__all__ = ["Topology", "TopologyPlan", "topology", "hier_plan",
           "ensure_adopted", "last_plan", "tier_split", "gateway_of",
           "slice_major_order", "declared_slices"]

#: env declaration, read ONCE at first topology() (None = unread): the
#: lookup sits on the per-exchange hot path, so it must stay a list
#: load, not an environ lookup — tests re-slicing mid-process call
#: :func:`_reslice` (the obs/comm._rearm pattern)
_DECLARED: list = [None]

#: plan identities already voted this process: (mesh ident, plan hash).
#: Advances identically on every rank of an SPMD session (the first
#: hierarchical exchange is reached at the same program point), so the
#: vote-once gate is rank-uniform by construction.
_ADOPTED: set = set()

#: the most recently voted plan (bench --slices detail and the chaos
#: --multislice same-plan-after-recovery assertions read it)
_LAST: list = [None]


def declared_slices() -> int | None:
    """The ``CYLON_TPU_SLICES`` declaration (cached), or None."""
    d = _DECLARED[0]
    if d is None:
        raw = os.environ.get("CYLON_TPU_SLICES", "")
        try:
            d = int(raw) if raw else 0
        except ValueError:
            d = 0
        _DECLARED[0] = d
    return d if d > 0 else None


def _reslice() -> None:
    """Re-read ``CYLON_TPU_SLICES`` on the next topology() (tests; env
    changed mid-process).  Also forgets voted plans — a re-sliced mesh
    is a NEW topology and must re-vote."""
    _DECLARED[0] = None
    _ADOPTED.clear()
    _LAST[0] = None
    _CACHE.clear()


class Topology:
    """The tier model of one mesh: ``world`` ranks in ``n_slices``
    uniform slices of ``ranks_per_slice``, slice-major."""

    __slots__ = ("world", "n_slices", "ranks_per_slice", "source")

    def __init__(self, world: int, n_slices: int, source: str):
        self.world = int(world)
        self.n_slices = int(n_slices)
        self.ranks_per_slice = self.world // max(self.n_slices, 1)
        self.source = source      # "env" | "device" | "single"

    def slice_of(self, rank: int) -> int:
        return int(rank) // self.ranks_per_slice

    def slice_ids(self) -> np.ndarray:
        """(W,) int32 per-rank slice ids — the tier key obs/comm splits
        the cumulative matrices on."""
        return (np.arange(self.world, dtype=np.int32)
                // self.ranks_per_slice)

    def cross_mask(self) -> np.ndarray:
        """(W, W) bool: cell (s, d) crosses slices — the DCN tier."""
        sid = self.slice_ids()
        return sid[:, None] != sid[None, :]

    def __repr__(self) -> str:  # pragma: no cover
        return (f"Topology(world={self.world}, slices={self.n_slices}x"
                f"{self.ranks_per_slice}, source={self.source})")


def _device_slices(devices) -> list | None:
    """Per-device slice ids from jax device attributes (real multi-slice
    fleets carry ``slice_index``), or None when absent/uniform."""
    ids = []
    for d in devices:
        s = getattr(d, "slice_index", None)
        if s is None:
            return None
        ids.append(int(s))
    return ids if len(set(ids)) > 1 else None


def slice_major_order(devices) -> list:
    """Reorder a device list slice-major (stable within a slice) so the
    mesh's rank numbering satisfies ``rank // R == slice`` — the layout
    premise of the two-hop exchange's order-preservation proof and of
    ``repart``'s global index math (docs/topology.md).  Devices without
    slice attributes (CPU grids, single-slice fleets) come back
    untouched: the ``CYLON_TPU_SLICES`` declaration partitions the
    existing order contiguously, which is already slice-major."""
    ids = _device_slices(devices)
    if ids is None:
        return list(devices)
    order = sorted(range(len(devices)), key=lambda i: (ids[i], i))
    return [devices[i] for i in order]


#: (mesh device ids, declared, armed?) -> Topology/TopologyPlan: tiny
#: host objects (a few ints each) keyed on stable hashables — the
#: per-exchange hot-path lookup.  Bounded in practice by the handful of
#: meshes a process ever builds (utils/cache's MESH_TABLE_LIMIT rationale
#: does not apply: nothing here pins executables or device memory).
_CACHE: dict = {}


def _mesh_ident(mesh) -> tuple:
    return tuple(d.id for d in mesh.devices.flat)


def _topology_for(mesh, declared: int | None) -> Topology:
    key = ("topo", _mesh_ident(mesh), declared)
    hit = _CACHE.get(key)
    if hit is not None:
        return hit
    t = _CACHE[key] = _build_topology(mesh, declared)
    return t


def _build_topology(mesh, declared: int | None) -> Topology:
    w = int(mesh.devices.size)
    if declared is not None:
        if 2 <= declared <= w and w % declared == 0:
            return Topology(w, declared, "env")
        return Topology(w, 1, "single")
    ids = _device_slices(list(mesh.devices.flat))
    if ids is None:
        return Topology(w, 1, "single")
    n = len(set(ids))
    per = [ids.count(s) for s in sorted(set(ids))]
    # uniform slice-major only: anything else degrades to single-slice
    # (flat route) — topology is an optimization, never a correctness
    # input, and a ragged fleet's exchange must still be exact
    if len(set(per)) != 1 or ids != sorted(ids):
        return Topology(w, 1, "single")
    return Topology(w, n, "device")


def topology(mesh) -> Topology:
    """The (cached) tier model of ``mesh`` — one dict lookup on the
    per-exchange hot path after the first call."""
    return _topology_for(mesh, declared_slices())


def gateway_of(dest: int, src_slice: int, ranks_per_slice: int) -> int:
    """Hop-1 gateway: the slice-LOCAL rank of ``src_slice`` that buckets
    rows destined for global rank ``dest`` — the destination's local
    index, so hop 2 is a pure cross-slice exchange between same-local
    ranks (the "gateway-local bucket" of docs/topology.md)."""
    return src_slice * ranks_per_slice + (dest % ranks_per_slice)


class TopologyPlan:
    """The voted route choice for one mesh: tier map + gateway scheme +
    flat/hierarchical decision, with a canonical hash covering every
    field that shapes the collective sequence."""

    __slots__ = ("world", "n_slices", "ranks_per_slice", "route",
                 "gateway", "source", "_hash")

    def __init__(self, topo: Topology, route: str):
        self.world = topo.world
        self.n_slices = topo.n_slices
        self.ranks_per_slice = topo.ranks_per_slice
        self.route = route                 # "hierarchical" | "flat"
        self.gateway = "local-index"       # the one implemented scheme
        self.source = topo.source
        self._hash = None

    def plan_hash(self) -> int:
        """Canonical 64-bit plan identity: every collective-shaping
        field feeds a sha256.  Deterministic given the device attributes
        / env declaration, so a recovery-ladder retry (or a crashed
        rerun) re-votes the identical hash — the chaos ``--multislice``
        contract."""
        if self._hash is None:
            h = hashlib.sha256()
            h.update(repr((self.world, self.n_slices,
                           self.ranks_per_slice, self.route,
                           self.gateway)).encode())
            self._hash = int.from_bytes(h.digest()[:8], "big")
        return self._hash

    def summary(self) -> dict:
        """The JSON-friendly decision record (bench detail, EXPLAIN)."""
        return {"route": self.route,
                "n_slices": int(self.n_slices),
                "ranks_per_slice": int(self.ranks_per_slice),
                "gateway": self.gateway,
                "source": self.source,
                "plan_hash": format(self.plan_hash(), "016x")}


def _plan_for(mesh, declared: int | None, armed: bool) -> TopologyPlan:
    key = ("plan", _mesh_ident(mesh), declared, armed)
    hit = _CACHE.get(key)
    if hit is not None:
        return hit
    topo = _topology_for(mesh, declared)
    hier = (armed and topo.n_slices >= 2 and topo.ranks_per_slice >= 2)
    plan = _CACHE[key] = TopologyPlan(topo,
                                      "hierarchical" if hier else "flat")
    return plan


def hier_plan(mesh) -> TopologyPlan | None:
    """The mesh's voted-route plan when it is hierarchical, else None —
    the per-exchange route switch (parallel/shuffle.exchange).  Cached:
    one dict lookup on the hot path.  ``ranks_per_slice == 1`` (every
    rank its own slice) routes flat: hop 2 would be the full-axis
    exchange and hop 1 pure overhead."""
    plan = _plan_for(mesh, declared_slices(), config.TOPO_SHUFFLE)
    return plan if plan.route == "hierarchical" else None


def ensure_adopted(mesh, plan: TopologyPlan) -> None:
    """Vote the plan's canonical hash over the consensus wire
    (``Code.TopoPlan``) exactly once per (mesh, plan) — called by the
    exchange engine BEFORE its first hierarchical collective.  A rank
    whose slice map diverged raises typed here instead of entering a
    two-hop exchange its peers route differently.  After the first
    adoption this is one set lookup."""
    ident = (_mesh_ident(mesh), plan.plan_hash())
    if ident in _ADOPTED:
        return
    from ..exec.recovery import topo_plan_consensus
    from ..obs import metrics as _metrics
    topo_plan_consensus(mesh, plan.plan_hash())
    _ADOPTED.add(ident)
    _LAST[0] = plan
    _metrics.counter("topo_plans_voted").inc()


def last_plan() -> TopologyPlan | None:
    """The most recently voted :class:`TopologyPlan` (None while every
    exchange has routed flat)."""
    return _LAST[0]


def tier_split(counts: np.ndarray, topo: Topology) -> tuple[int, int]:
    """(ici_rows, dcn_rows) of one exchange's logical count matrix under
    ``topo`` — pure host numpy on the replicated sidecar.  Same-slice
    cells are ICI; cross-slice cells cross DCN exactly once whichever
    route carried them (the two-hop route changes the WIRE volume and
    message count, never which rows must cross — docs/topology.md)."""
    c = np.asarray(counts, np.int64)
    if topo.n_slices <= 1:
        return int(c.sum()), 0
    cross = topo.cross_mask()
    dcn = int(c[cross].sum())
    return int(c.sum()) - dcn, dcn
