// Native string hashing for the high-cardinality string-key path.
//
// TPU-native equivalent of the reference's row-hash machinery for
// non-fixed-width keys (cpp/src/cylon/util/murmur3.cpp + the multi-column
// flattener util/flatten_array.cpp): variable-length UTF-8 values are
// flattened host-side into (data buffer, offsets) — exactly Arrow's string
// layout, so pyarrow buffers feed this zero-copy — and each value maps to
// a stable 64-bit hash used as its device-side code.  Joins/groupbys/set
// ops compare the codes (two u32 lanes on device); raw values stay host
// side and materialize through a hash->value lookup.
//
// Hash: MurmurHash64A (Austin Appleby's public-domain algorithm) with a
// fixed seed — stable across processes, which multi-controller execution
// requires (every process must code identical strings identically).
//
// Build: g++ -O3 -shared -fPIC strhash.cpp -o _strhash.so   (see loader in
// cylon_tpu/native/__init__.py; falls back to pandas' stable hash_array
// when no toolchain is present).

#include <cstdint>
#include <cstddef>

namespace {

inline uint64_t murmur64a(const void* key, int len, uint64_t seed) {
  const uint64_t m = 0xc6a4a7935bd1e995ULL;
  const int r = 47;
  uint64_t h = seed ^ (static_cast<uint64_t>(len) * m);

  const uint8_t* data = static_cast<const uint8_t*>(key);
  const uint8_t* end = data + (len & ~7);

  while (data != end) {
    uint64_t k;
    __builtin_memcpy(&k, data, 8);
    data += 8;
    k *= m;
    k ^= k >> r;
    k *= m;
    h ^= k;
    h *= m;
  }

  switch (len & 7) {
    case 7: h ^= static_cast<uint64_t>(data[6]) << 48; [[fallthrough]];
    case 6: h ^= static_cast<uint64_t>(data[5]) << 40; [[fallthrough]];
    case 5: h ^= static_cast<uint64_t>(data[4]) << 32; [[fallthrough]];
    case 4: h ^= static_cast<uint64_t>(data[3]) << 24; [[fallthrough]];
    case 3: h ^= static_cast<uint64_t>(data[2]) << 16; [[fallthrough]];
    case 2: h ^= static_cast<uint64_t>(data[1]) << 8;  [[fallthrough]];
    case 1: h ^= static_cast<uint64_t>(data[0]);
            h *= m;
  }

  h ^= h >> r;
  h *= m;
  h ^= h >> r;
  return h;
}

constexpr uint64_t kSeed = 0x43594c4f4e545055ULL;  // "CYLONTPU"

}  // namespace

extern "C" {

// Hash n UTF-8 values laid out Arrow-style: value i occupies
// data[offsets[i] .. offsets[i+1]).  offsets has n+1 entries (int64 —
// pyarrow large_string).  out receives n uint64 hashes.
void cylon_hash_strings(const uint8_t* data, const int64_t* offsets,
                        int64_t n, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    const int64_t lo = offsets[i];
    const int64_t hi = offsets[i + 1];
    out[i] = murmur64a(data + lo, static_cast<int>(hi - lo), kSeed);
  }
}

// Order lanes for lexical string sort (the type-dispatched string sort
// slot, reference arrow_kernels.hpp:53 IndexSortKernel<StringArray>):
// value i's first 4*n_lanes bytes packed BIG-ENDIAN into n_lanes uint32
// (missing bytes = 0, which sorts short strings before their
// extensions — bytewise UTF-8 order, matching Arrow's binary compare).
// out is row-major (n, n_lanes).  The lanes are VALUE-STABLE: any process
// holding the same value computes the same lanes, so multi-controller
// range partitioning agrees without exchanging dictionaries.
void cylon_prefix_lanes(const uint8_t* data, const int64_t* offsets,
                        int64_t n, int64_t n_lanes, uint32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    const int64_t lo = offsets[i];
    const int64_t len = offsets[i + 1] - lo;
    const uint8_t* p = data + lo;
    for (int64_t l = 0; l < n_lanes; ++l) {
      uint32_t v = 0;
      const int64_t base = 4 * l;
      for (int64_t b = 0; b < 4; ++b) {
        v <<= 8;
        if (base + b < len) v |= p[base + b];
      }
      out[i * n_lanes + l] = v;
    }
  }
}

// Longest common prefix (bytes) over ADJACENT pairs of n values taken in
// ``order`` — for values in sorted order this equals the global max LCP
// over all DISTINCT pairs, i.e. how many prefix bytes separate every
// distinct value.  Returns max LCP; identical adjacent values are skipped
// (callers pass unique values).
int64_t cylon_max_adjacent_lcp(const uint8_t* data, const int64_t* offsets,
                               const int64_t* order, int64_t n) {
  int64_t best = 0;
  for (int64_t i = 0; i + 1 < n; ++i) {
    const int64_t a = order[i], b = order[i + 1];
    const uint8_t* pa = data + offsets[a];
    const uint8_t* pb = data + offsets[b];
    const int64_t la = offsets[a + 1] - offsets[a];
    const int64_t lb = offsets[b + 1] - offsets[b];
    const int64_t lim = la < lb ? la : lb;
    int64_t k = 0;
    while (k < lim && pa[k] == pb[k]) ++k;
    if (k == lim && la == lb) continue;  // equal values: no separation need
    if (k > best) best = k;
  }
  return best;
}

}  // extern "C"
