// Native string hashing for the high-cardinality string-key path.
//
// TPU-native equivalent of the reference's row-hash machinery for
// non-fixed-width keys (cpp/src/cylon/util/murmur3.cpp + the multi-column
// flattener util/flatten_array.cpp): variable-length UTF-8 values are
// flattened host-side into (data buffer, offsets) — exactly Arrow's string
// layout, so pyarrow buffers feed this zero-copy — and each value maps to
// a stable 64-bit hash used as its device-side code.  Joins/groupbys/set
// ops compare the codes (two u32 lanes on device); raw values stay host
// side and materialize through a hash->value lookup.
//
// Hash: MurmurHash64A (Austin Appleby's public-domain algorithm) with a
// fixed seed — stable across processes, which multi-controller execution
// requires (every process must code identical strings identically).
//
// Build: g++ -O3 -shared -fPIC strhash.cpp -o _strhash.so   (see loader in
// cylon_tpu/native/__init__.py; falls back to pandas' stable hash_array
// when no toolchain is present).

#include <cstdint>
#include <cstddef>

namespace {

inline uint64_t murmur64a(const void* key, int len, uint64_t seed) {
  const uint64_t m = 0xc6a4a7935bd1e995ULL;
  const int r = 47;
  uint64_t h = seed ^ (static_cast<uint64_t>(len) * m);

  const uint8_t* data = static_cast<const uint8_t*>(key);
  const uint8_t* end = data + (len & ~7);

  while (data != end) {
    uint64_t k;
    __builtin_memcpy(&k, data, 8);
    data += 8;
    k *= m;
    k ^= k >> r;
    k *= m;
    h ^= k;
    h *= m;
  }

  switch (len & 7) {
    case 7: h ^= static_cast<uint64_t>(data[6]) << 48; [[fallthrough]];
    case 6: h ^= static_cast<uint64_t>(data[5]) << 40; [[fallthrough]];
    case 5: h ^= static_cast<uint64_t>(data[4]) << 32; [[fallthrough]];
    case 4: h ^= static_cast<uint64_t>(data[3]) << 24; [[fallthrough]];
    case 3: h ^= static_cast<uint64_t>(data[2]) << 16; [[fallthrough]];
    case 2: h ^= static_cast<uint64_t>(data[1]) << 8;  [[fallthrough]];
    case 1: h ^= static_cast<uint64_t>(data[0]);
            h *= m;
  }

  h ^= h >> r;
  h *= m;
  h ^= h >> r;
  return h;
}

constexpr uint64_t kSeed = 0x43594c4f4e545055ULL;  // "CYLONTPU"

}  // namespace

extern "C" {

// Hash n UTF-8 values laid out Arrow-style: value i occupies
// data[offsets[i] .. offsets[i+1]).  offsets has n+1 entries (int64 —
// pyarrow large_string).  out receives n uint64 hashes.
void cylon_hash_strings(const uint8_t* data, const int64_t* offsets,
                        int64_t n, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    const int64_t lo = offsets[i];
    const int64_t hi = offsets[i + 1];
    out[i] = murmur64a(data + lo, static_cast<int>(hi - lo), kSeed);
  }
}

}  // extern "C"
