"""Native (C++) runtime helpers.

The compute path is JAX/XLA; host-side hot loops that the reference
implements in C++ (murmur3 row hashing, util/murmur3.cpp; the non-fixed-
width key flattener, util/flatten_array.cpp) get native equivalents here,
compiled on demand with the system toolchain and loaded through ctypes —
no pybind11 dependency.

Current components:

* ``strhash`` — MurmurHash64A over Arrow string buffers (strhash.cpp),
  the encode-time hot loop of the high-cardinality string-key path
  (:meth:`cylon_tpu.core.column.Column._encode_strings`).  Falls back to
  pandas' stable SipHash (``pd.util.hash_array``) when no C++ toolchain
  is available.  The chosen implementation is fixed per process at first
  use; both are process-stable, so multi-controller runs code identical
  strings identically as long as all processes resolve the same
  implementation (same image → same toolchain).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB = None           # ctypes CDLL once built/loaded
_LIB_TRIED = False


def _build_and_load():
    """Compile strhash.cpp to a shared object (cached beside the source
    when writable, else in a temp dir) and load it."""
    src = os.path.join(_HERE, "strhash.cpp")
    so = os.path.join(_HERE, "_strhash.so")
    if not os.path.exists(so) or \
            os.path.getmtime(so) < os.path.getmtime(src):
        # compile to a temp file, then atomically os.replace into place: a
        # failed/interrupted g++ must never leave a fresh-mtime partial .so
        # (it would silently disable the native hash forever after — and
        # worse, differently per process in multi-controller runs)
        build_dir = _HERE if os.access(_HERE, os.W_OK) \
            else tempfile.mkdtemp(prefix="cylon_tpu_")
        # per-process tmp name: concurrent first-use builds (multi-rank
        # launch) must not clobber each other mid-write — a truncated .so
        # would silently drop one rank to the fallback hash and diverge
        # string codes across ranks
        tmp = os.path.join(build_dir, f"_strhash.tmp.{os.getpid()}.so")
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", src,
             "-o", tmp],
            check=True, capture_output=True)
        final = os.path.join(build_dir, "_strhash.so")
        os.replace(tmp, final)
        so = final
    lib = ctypes.CDLL(so)
    lib.cylon_hash_strings.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
    lib.cylon_hash_strings.restype = None
    lib.cylon_prefix_lanes.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p]
    lib.cylon_prefix_lanes.restype = None
    lib.cylon_max_adjacent_lcp.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
    lib.cylon_max_adjacent_lcp.restype = ctypes.c_int64
    return lib


def native_available() -> bool:
    global _LIB, _LIB_TRIED
    if not _LIB_TRIED:
        _LIB_TRIED = True
        try:
            _LIB = _build_and_load()
        except Exception:  # noqa: BLE001 — no toolchain / sandboxed fs
            _LIB = None
    return _LIB is not None


def hash_strings(values: np.ndarray) -> np.ndarray:
    """Stable 64-bit hash per UTF-8 string value (object/str array in,
    uint64 out).  Native murmur64a over Arrow string buffers when the
    toolchain is available; pandas' stable hash otherwise."""
    if native_available():
        import pyarrow as pa
        arr = pa.array(values, type=pa.large_string())
        if arr.null_count:
            arr = arr.fill_null("")
        bufs = arr.buffers()  # [validity, offsets(int64), data]
        offsets = np.frombuffer(bufs[1], dtype=np.int64,
                                count=len(arr) + 1, offset=8 * arr.offset)
        data = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] is not None \
            else np.zeros(1, np.uint8)
        out = np.empty(len(arr), np.uint64)
        _LIB.cylon_hash_strings(
            data.ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int64(len(arr)),
            out.ctypes.data_as(ctypes.c_void_p))
        return out
    import pandas as pd
    return pd.util.hash_array(np.asarray(values, dtype=object))


def _arrow_bufs(values: np.ndarray):
    """(data uint8 np, offsets int64 np, n) for an object/str array in
    Arrow large_string layout (nulls become empty strings — callers mask
    them separately)."""
    import pyarrow as pa
    arr = pa.array(values, type=pa.large_string())
    if arr.null_count:
        arr = arr.fill_null("")
    bufs = arr.buffers()
    offsets = np.frombuffer(bufs[1], dtype=np.int64,
                            count=len(arr) + 1, offset=8 * arr.offset)
    data = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] is not None \
        else np.zeros(1, np.uint8)
    return data, offsets, len(arr)


def prefix_lanes(values: np.ndarray, n_lanes: int) -> np.ndarray:
    """Big-endian u32 order lanes of each value's first ``4*n_lanes``
    UTF-8 bytes — (n, n_lanes) uint32; lane order == bytewise (Arrow
    binary) order.  Value-stable across processes."""
    if native_available():
        data, offsets, n = _arrow_bufs(values)
        out = np.empty((n, n_lanes), np.uint32)
        _LIB.cylon_prefix_lanes(
            data.ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int64(n), ctypes.c_int64(n_lanes),
            out.ctypes.data_as(ctypes.c_void_p))
        return out
    out = np.zeros((len(values), n_lanes), np.uint32)
    for i, v in enumerate(values):
        b = ("" if v is None else str(v)).encode("utf-8")[:4 * n_lanes]
        b = b + b"\0" * (-len(b) % 4)
        if b:
            lanes = np.frombuffer(b, dtype=">u4")
            out[i, :len(lanes)] = lanes
    return out


def max_adjacent_lcp(values_in_order: np.ndarray) -> int:
    """Longest common prefix in BYTES over adjacent pairs (callers pass
    sorted unique values, making this the global distinct-pair max)."""
    if native_available():
        data, offsets, n = _arrow_bufs(values_in_order)
        order = np.arange(n, dtype=np.int64)
        return int(_LIB.cylon_max_adjacent_lcp(
            data.ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.c_void_p),
            order.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int64(n)))
    best = 0
    enc = [("" if v is None else str(v)).encode("utf-8")
           for v in values_in_order]
    for a, b in zip(enc, enc[1:]):
        lim = min(len(a), len(b))
        k = 0
        while k < lim and a[k] == b[k]:
            k += 1
        if k == lim and len(a) == len(b):
            continue
        best = max(best, k)
    return best


def utf8_lengths(values: np.ndarray) -> np.ndarray:
    """Byte length of each value's UTF-8 encoding (int64)."""
    _, offsets, _n = _arrow_bufs(values)
    return np.diff(offsets)
