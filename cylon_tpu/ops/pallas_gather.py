"""Pallas windowed row gather — the groupby prefix-diff's hot op.

The grouped-reduce machinery (ops/groupby.grouped_reduce) ends in ONE
``mat[starts]`` gather of a (seg_cap, L) u32 lane matrix at SORTED row
indices.  XLA:TPU lowers that gather to a per-row dynamic-slice loop at a
flat ~21-24 ns/row regardless of row width (measured v5e, 32M rows of a
64M x 8 u32 matrix: 750 ms — the single dominant stage of the fused
join+groupby at bench shape; separate 1-D gathers are 10x worse, scatter
and sort-compaction 6-8x worse).

But ``starts`` is sorted and DENSE (one start per group; at bench shape
~45% of all rows are gathered), so each tile of TILE consecutive output
rows reads from a bounded source window.  That turns the gather into:

  per output tile j:  DMA  mat.T[:, ws_j : ws_j+W]  (HBM -> VMEM, async,
                      double-buffered across the sequential grid)
                      byte-split window (4L x W) @ onehot^T (TILE x W)
                      on the MXU -> (4L, TILE), recombined by sublane
                      slices into the (L, TILE) output block

with the u32 lanes split into four exact-in-bf16 u8 sub-lanes for the
matmul and recombined after.  Selection-by-matmul replaces XLA's per-row
loop with dense MXU/VPU work (~10x at bench shape).

Mosaic landmines this shape navigates (v5e libtpu 2026-07, found
empirically — each violation produced wrong VALUES or failed compiles):
- the source matrix must be TRANSPOSED (L, M) so the dynamic DMA slice
  rides the minor 128-tiled dim; an (M, L<128) input gets lane-padded to
  (M, 128) in HBM (18x memory) and its slices can't align to tiling;
- window starts must be 128-aligned AND hinted via ``pl.multiple_of``
  (arithmetic inside the slice expression fails to legalize);
- index-map literals must be wrapped in jnp.int32 under x64 (i64 block
  indices fail func.func legalization);
- the accumulator must be LANE-MAJOR (4L, TILE): lane-dim slices of a
  (TILE, 4L) result at offset 16 silently zero values < 128 (a Mosaic
  lane-rotation bug); sublane slices are exact.

Skew safety: a tile whose index span exceeds W cannot be served from its
window.  The wrapper computes the span check on device and wraps fast and
plain paths in ``lax.cond`` — degenerate densities (a few huge groups)
fall back to the XLA gather at RUNTIME with no host round-trip.  (Low
densities also mean a small seg_cap, where the plain gather is cheap —
callers only route here when the predicted density clears
:data:`MIN_DENSITY`.)

Reference slot: the type-dispatched aggregation kernels this feeds replace
cpp/src/cylon/groupby/hash_groupby.cpp:340 (single-pass combine) — the
gather is the TPU-native analog of its group-id indexed writes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: output rows per grid step
TILE = 256
#: don't attempt the windowed path below this measured density (average
#: tile spans approach MAX_WINDOW and the margin collapses)
MIN_DENSITY = 0.10
MIN_WINDOW, MAX_WINDOW = 1024, 4096


def pick_window(density_est: float) -> int:
    """Static window size for a compile-time density estimate: cover the
    average span TILE/density with ~1.8x margin, clamped to pow2 bounds."""
    from .. import config
    want = int(TILE / max(density_est, 1e-6) * 1.8)
    return max(MIN_WINDOW, min(MAX_WINDOW, config.pow2ceil(want)))


def _kernel(ws_ref, idx_ref, mat_ref, out_ref, win_ref, wb_ref, sem_ref,
            *, window: int, n_lanes: int):
    j = pl.program_id(0)
    nt = pl.num_programs(0)
    L = n_lanes

    def dma(slot, t):
        # int32 everywhere: x64 mode would promote python-int indices to
        # i64, which tpu.memref_slice rejects
        slot = jnp.asarray(slot, jnp.int32)
        start = pl.multiple_of(ws_ref[t], 128)
        return pltpu.make_async_copy(
            mat_ref.at[:, pl.ds(start, window)],
            win_ref.at[slot], sem_ref.at[slot])

    @pl.when(j == 0)
    def _():
        dma(0, jnp.int32(0)).start()

    @pl.when(j + 1 < nt)
    def _():
        dma(jax.lax.rem(j + 1, jnp.int32(2)), j + 1).start()

    slot = jax.lax.rem(j, jnp.int32(2))
    dma(slot, j).wait()

    # u32 -> four u8 planes, exact in bf16 (no direct u32->float cast in
    # Mosaic: hop through i32/f32); assembled in a scratch so one 4L-row
    # matmul serves all planes
    w32 = win_ref[slot]                                    # (L, window)
    for k in range(4):
        wb_ref[pl.ds(k * L, L), :] = ((w32 >> jnp.uint32(8 * k))
                                      & jnp.uint32(0xFF)) \
            .astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)

    # idx block is (1, 8, TILE//8); a lane-crossing reshape to (TILE,) is
    # unsupported in Mosaic, so build the one-hot in (8, TILE//8, W)
    # geometry and merge only the LEADING dims (minor dim intact)
    lidx = idx_ref[0] - ws_ref[j]                          # (8, TILE//8)
    iota = jax.lax.broadcasted_iota(jnp.int32,
                                    (8, TILE // 8, window), 2)
    oh = (iota == lidx[:, :, None]).astype(jnp.bfloat16)
    oh = oh.reshape(TILE, window)
    # (4L, W) x (TILE, W) contracting W -> LANE-MAJOR (4L, TILE)
    accT = jax.lax.dot_general(wb_ref[...], oh, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)
    u = accT.astype(jnp.int32).astype(jnp.uint32)
    out_ref[...] = (u[0:L] | u[L:2 * L] << jnp.uint32(8)
                    | u[2 * L:3 * L] << jnp.uint32(16)
                    | u[3 * L:4 * L] << jnp.uint32(24))


def _pallas_take(mat_t, idx2, ws, window: int, interpret: bool):
    # idx arrives as (G, 8, TILE//8): a (1, 8, TILE//8) block satisfies the
    # TPU (8, 128)-tiling rule (last dim equals the array's)
    G = idx2.shape[0]
    tile = idx2.shape[1] * idx2.shape[2]
    L, M = mat_t.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(G,),
        in_specs=[
            pl.BlockSpec((1, 8, tile // 8),
                         lambda j, ws_ref: (j, jnp.int32(0), jnp.int32(0))),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((L, tile),
                               lambda j, ws_ref: (jnp.int32(0), j)),
        scratch_shapes=[
            pltpu.VMEM((2, L, window), jnp.uint32),
            pltpu.VMEM((4 * L, window), jnp.bfloat16),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    # under shard_map (check_vma) the output must declare which mesh axes
    # it varies over — the union of the inputs'.  jax < 0.5 has no vma
    # concept on ShapeDtypeStruct (check_rep validates differently there).
    try:
        vma = frozenset()
        for a in (ws, idx2, mat_t):
            vma = vma | getattr(a.aval, "vma", frozenset())
        out_shape = jax.ShapeDtypeStruct((L, G * tile), jnp.uint32, vma=vma)
    except TypeError:
        out_shape = jax.ShapeDtypeStruct((L, G * tile), jnp.uint32)
    return pl.pallas_call(
        partial(_kernel, window=window, n_lanes=L),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(ws, idx2, mat_t)


def supported(n_rows: int, seg_cap: int, n_lanes: int, window: int) -> bool:
    """Static eligibility of the windowed path for a gather of ``seg_cap``
    sorted indices into an (n_rows, n_lanes) u32 matrix."""
    return (seg_cap % TILE == 0 and seg_cap >= TILE
            and n_rows >= window and n_lanes >= 1)


def windowed_take_t(mat_t, idx, window: int, interpret: bool | None = None):
    """``mat_t[:, idx]`` for SORTED int32 ``idx`` into a LANE-MAJOR (L, M)
    u32 ``mat_t``.  Returns ``(out, ok)``: out is (L, S) — row l holds
    lane l at every index — and ok is a scalar bool.

    The matrix must arrive lane-major: an XLA transpose of an (M, L)
    matrix at bench shape costs ~700 ms on v5e (per-element, like its
    gathers) — callers stack lanes as ROWS instead, which is free.

    When a tile's index span exceeds the window (skewed group sizes), the
    overflowing rows come out as ZEROS and ``ok`` is False — the caller
    must discard the result and redispatch a no-window program.  No
    in-graph fallback: wrapping both paths in ``lax.cond`` forces an XLA
    relayout of the 2 GB operand (~690 ms measured, erasing the win), so
    the mispredict round-trip lives at the host dispatch layer like the
    seg-cap mispredict it already handles.  Caller must ensure
    :func:`supported`.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    L, M = mat_t.shape
    S = idx.shape[0]
    G = S // TILE
    idx = idx.astype(jnp.int32)
    # pad BOTH dims to the DMA tiling: lanes to a sublane multiple (8)
    # and the row count to a lane-tile multiple (128).  The row pad is
    # load-bearing for the tail: with M % 128 != 0, the 128-floored
    # window-start clamp excludes the last rows — exactly where the
    # sentinel index (= n_live) every empty group slot points at lives.
    L8 = -(-L // 8) * 8
    M128 = -(-M // 128) * 128
    if L8 != L or M128 != M:
        mat_t = jnp.pad(mat_t, ((0, L8 - L), (0, M128 - M)))
    heads = idx[::TILE]
    # window starts 128-aligned (the minor-dim DMA slice must match the
    # HBM tiling); clamp so every window stays in-bounds
    ws = jnp.minimum((heads // 128) * 128, jnp.int32(M128 - window))
    lasts = idx[TILE - 1::TILE]
    ok = jnp.all(lasts - ws < window)
    idx2 = idx.reshape(G, 8, TILE // 8)
    out = _pallas_take(mat_t, idx2, ws, window, interpret)[:L]
    return out, ok
