"""Key canonicalization & dense-rank packing.

TPU-native replacement for the reference's row comparators / hashers
(cpp/src/cylon/arrow/arrow_comparator.hpp:59 ``ArrayIndexComparator``, :196
``TableRowIndexHash``, :238/270 dual-table variants) and the multi-column
flattener (util/flatten_array.cpp).  The reference compares rows via per-type
virtual comparators and pointer-chasing hash maps; on TPU we instead

1. canonicalize every key column into **sort operands** (``KeyOps``) such
   that ``jax.lax.sort``'s multi-operand lexicographic order implements the
   requested row order (ascending/descending, nulls first/last), and
2. replace "row equality/hash" with a **dense rank**: jointly sort the key
   tuples and assign consecutive group ids.  Two tables get comparable ids by
   ranking their concatenation (the dual-table comparator analog).

No 64-bit bitcasts anywhere — XLA's TPU x64 emulation does not implement
``bitcast-convert`` on u64, so descending order uses arithmetic transforms
(``~x`` for ints — total, overflow-free — and ``-x`` for floats) and float
equality is handled by NaN/zero canonicalization plus float-aware compare
helpers instead of the classic IEEE bit-flip trick.

Every downstream op (join, groupby, set ops, unique) then works on a single
int32 id column — the moral equivalent of the reference flattening multi-col
keys to one binary column before hashing.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

NULL_FIRST = 0
NULL_LAST = 2


class KeyOps(NamedTuple):
    """Lexicographic sort operands + per-operand kind ('i' int-like,
    'f' float — needs NaN-aware equality)."""

    ops: tuple
    kinds: tuple

    @property
    def n(self):
        return self.ops[0].shape[0]


def _canon_float(x: jax.Array) -> jax.Array:
    """Canonicalize float payloads for *equality*: -0.0 → +0.0 and all NaNs
    → one positive quiet NaN (so sort is deterministic and NaNs group)."""
    x = jnp.where(x == 0, jnp.zeros_like(x), x)
    return jnp.where(jnp.isnan(x), jnp.full_like(x, jnp.nan), x)


def _sort_value(x: jax.Array, descending: bool) -> tuple[jax.Array, str]:
    dt = x.dtype
    if dt == jnp.bool_:
        v = x.astype(jnp.int32)
        return (-v if descending else v), "i"
    if jnp.issubdtype(dt, jnp.integer):
        # ~x = -x-1: strictly decreasing, total, no overflow (INT_MIN→INT_MAX)
        return (~x if descending else x), "i"
    if jnp.issubdtype(dt, jnp.floating):
        v = -x if descending else x
        # positive canonical NaN sorts after all numbers in XLA's total order
        v = _canon_float(v)
        return v, "f"
    raise TypeError(f"unsortable dtype {dt}")


def key_operands(datas, validities=None, row_mask=None, descendings=None,
                 nulls_position: int = NULL_LAST, pad_key: int = 4) -> KeyOps:
    """Build the lexicographic sort-operand list for a key tuple.

    For each key column: a (null-flag, value) operand pair — valid rows get
    flag 1, nulls get 0 (first) or 2 (last), matching pandas ``na_position``
    independently of ascending/descending.  A leading row-liveness operand is
    added when ``row_mask`` is given; padding rows sort last with flag
    ``pad_key`` (use distinct pad keys per table so padding never matches
    across tables in a dense rank).
    """
    ops, kinds = [], []
    n = datas[0].shape[0]
    if row_mask is not None:
        ops.append(jnp.where(row_mask, jnp.int32(0), jnp.int32(pad_key)))
        kinds.append("i")
    for i, d in enumerate(datas):
        desc = bool(descendings[i]) if descendings is not None else False
        val, kind = _sort_value(d, desc)
        v = validities[i] if validities is not None else None
        if v is None:
            nf = jnp.zeros(n, jnp.int32)
        else:
            nf = jnp.where(v, jnp.int32(1), jnp.int32(nulls_position))
            val = jnp.where(v, val, jnp.zeros_like(val))
        ops.append(nf)
        kinds.append("i")
        ops.append(val)
        kinds.append(kind)
    return KeyOps(tuple(ops), tuple(kinds))


def concat_keyops(a: KeyOps, b: KeyOps) -> KeyOps:
    assert a.kinds == b.kinds
    return KeyOps(tuple(jnp.concatenate([x, y]) for x, y in zip(a.ops, b.ops)),
                  a.kinds)


# -- float-aware elementwise comparisons (post-canonicalization) ------------

def op_neq(a, b, kind: str):
    if kind == "f":
        return (a != b) & ~(jnp.isnan(a) & jnp.isnan(b))
    return a != b


def op_gt(a, b, kind: str):
    if kind == "f":
        return (a > b) | (jnp.isnan(a) & ~jnp.isnan(b))
    return a > b


def op_eq(a, b, kind: str):
    if kind == "f":
        return (a == b) | (jnp.isnan(a) & jnp.isnan(b))
    return a == b


def neighbor_flags(sorted_ops, kinds):
    """int32 flags: row i != row i-1 under the key tuple (row 0 → 0)."""
    n = sorted_ops[0].shape[0]
    neq = jnp.zeros(n, jnp.int32)
    for op, kind in zip(sorted_ops, kinds):
        d = op_neq(op[1:], op[:-1], kind).astype(jnp.int32)
        neq = neq | jnp.concatenate([jnp.zeros(1, jnp.int32), d])
    return neq


def dense_rank(keyops: KeyOps):
    """Rank rows by their key tuple: returns ``(gids, n_groups)`` where
    ``gids[i]`` is the 0-based dense rank of row i's key (ids ordered like
    the keys — an order-preserving perfect hash over this batch)."""
    n = keyops.n
    idx = jnp.arange(n, dtype=jnp.int32)
    sorted_all = jax.lax.sort(keyops.ops + (idx,), num_keys=len(keyops.ops),
                              is_stable=True)
    sidx = sorted_all[-1]
    gid_sorted = jnp.cumsum(neighbor_flags(sorted_all[:-1], keyops.kinds))
    gids = jnp.zeros(n, jnp.int32).at[sidx].set(gid_sorted.astype(jnp.int32))
    n_groups = (jnp.where(n > 0, gid_sorted[-1] + 1, 0).astype(jnp.int32)
                if n > 0 else jnp.int32(0))
    return gids, n_groups


def dense_rank_two(l: KeyOps, r: KeyOps):
    """Comparable dense ranks across two tables (dual-table comparator
    analog, arrow_comparator.hpp:238): rank the concatenation, split back."""
    n = l.n
    gids, n_groups = dense_rank(concat_keyops(l, r))
    return gids[:n], gids[n:], n_groups


def rows_gt_splitters(keyops: KeyOps, splitter_ops: tuple):
    """(n, S) bool: row i's key tuple strictly greater than splitter j's.
    Used by sample-sort range partitioning (reference table.cpp:564-609
    split-point binary search).  ``splitter_ops`` parallel ``keyops.ops``
    with shape (S,) each."""
    n = keyops.n
    s = splitter_ops[0].shape[0]
    gt = jnp.zeros((n, s), bool)
    eq = jnp.ones((n, s), bool)
    for op, sop, kind in zip(keyops.ops, splitter_ops, keyops.kinds):
        a = op[:, None]
        b = sop[None, :]
        gt = gt | (eq & op_gt(a, b, kind))
        eq = eq & op_eq(a, b, kind)
    return gt
