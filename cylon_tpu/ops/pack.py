"""Key canonicalization & dense-rank packing.

TPU-native replacement for the reference's row comparators / hashers
(cpp/src/cylon/arrow/arrow_comparator.hpp:59 ``ArrayIndexComparator``, :196
``TableRowIndexHash``, :238/270 dual-table variants) and the multi-column
flattener (util/flatten_array.cpp).  The reference compares rows via per-type
virtual comparators and pointer-chasing hash maps; on TPU we instead

1. canonicalize every key column into **sort operands** (``KeyOps``) such
   that ``jax.lax.sort``'s multi-operand lexicographic order implements the
   requested row order (ascending/descending, nulls first/last), and
2. replace "row equality/hash" with a **dense rank**: jointly sort the key
   tuples and assign consecutive group ids.  Two tables get comparable ids by
   ranking their concatenation (the dual-table comparator analog).

**u32 lane packing (the TPU fast path).**  TPU has no native 64-bit integer
compare — an int64 ``lax.sort`` operand runs through XLA's x64 emulation and
dominates every relational op.  So every sort operand is packed into native
32-bit lanes before it reaches ``lax.sort``:

* int64/uint64 → (hi int32/uint32, lo uint32) operand pair — lexicographic
  order over the pair equals the 64-bit numeric order;
* int8/16/32, bool → one int32 operand;
* float32 → one uint32 operand via the IEEE total-order bit flip
  (sign bit set → flip all bits, else set sign bit) after NaN/-0.0
  canonicalization, so plain unsigned compares implement float order and
  bit equality implements float equality (NaN == NaN);
* float64 → kept as one f64 operand (kind 'f'): XLA TPU does not implement
  u64 bitcast-convert and the (2,)-u32 bitcast half-order is platform
  ambiguous, so f64 keys stay on the (slower) emulated-compare path.

Descending order uses arithmetic transforms before packing (``~x`` for ints
— total, overflow-free — and ``-x`` for floats).  Null flags are emitted
only for columns that can actually hold nulls (callers coordinate the
static operand structure across tables with ``need_null_flags``).

Every downstream op (join, groupby, set ops, unique) then works on a single
int32 id column — the moral equivalent of the reference flattening multi-col
keys to one binary column before hashing.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

NULL_FIRST = 0
NULL_LAST = 2


def sort_operand_nbytes(dtypes, need_nf, narrow, rows: int,
                        row_mask: bool = True) -> int:
    """Host-side static size of the operand set :func:`key_operands`
    materializes for ``rows`` rows — the per-piece sort scratch a join
    over this key structure will hold resident while it runs.  Mirrors
    the packing rules above (liveness flag + per-column null flag + one
    or two native value lanes; f64 stays a single 8-byte operand).

    This is the "registration at pack time" half of the HBM ledger
    (:mod:`cylon_tpu.exec.memory`): piece working-set sizing consults it
    so admission of a new packed source accounts for the transient
    operands its consumer will add on top of the resident matrices."""
    per_row = 4 if row_mask else 0
    for dt, nf, nw in zip(dtypes, need_nf, narrow):
        if nf:
            per_row += 4
        d = np.dtype(dt)
        if d.kind == "f" and d.itemsize == 8:
            per_row += 8          # f64 keys stay one emulated-compare operand
        elif d.itemsize == 8 and d.kind in ("i", "u") and not nw:
            per_row += 8          # (hi, lo) native lane pair
        else:
            per_row += 4          # one native 32-bit operand
    return per_row * int(rows)


class KeyOps(NamedTuple):
    """Lexicographic sort operands + per-operand kind ('i' int-like,
    'f' float — needs NaN-aware equality)."""

    ops: tuple
    kinds: tuple

    @property
    def n(self):
        return self.ops[0].shape[0]


def _canon_float(x: jax.Array) -> jax.Array:
    """Canonicalize float payloads for *equality*: -0.0 → +0.0 and all NaNs
    → one positive quiet NaN (so sort is deterministic and NaNs group)."""
    x = jnp.where(x == 0, jnp.zeros_like(x), x)
    return jnp.where(jnp.isnan(x), jnp.full_like(x, jnp.nan), x)


def _sort_value(x: jax.Array, descending: bool,
                narrow: bool = False) -> list[tuple[jax.Array, str]]:
    """Pack one key column into native-lane sort operands: a list of
    (operand, kind) pairs whose lexicographic order equals the column's
    requested order (see module docstring for the packing rules).

    ``narrow=True`` asserts (host-known ``Column.bounds``) that a 64-bit
    integer column's values fit in int32 — it then sorts as ONE native
    operand instead of a (hi, lo) pair."""
    dt = x.dtype
    if dt == jnp.bool_:
        v = x.astype(jnp.int32)
        return [((-v if descending else v), "i")]
    if jnp.issubdtype(dt, jnp.integer):
        if narrow and x.dtype.itemsize == 8:
            x = x.astype(jnp.int32)
        # ~x = -x-1: strictly decreasing, total, no overflow (INT_MIN→INT_MAX)
        if descending:
            x = ~x
        if x.dtype.itemsize <= 4:
            if x.dtype == jnp.uint32:
                return [(x, "i")]
            return [(x.astype(jnp.int32), "i")]
        # 64-bit: split into (hi, lo) native lanes.  Arithmetic >>32 keeps
        # the sign in hi (signed) / zero-extends (unsigned); lo compares
        # unsigned either way.
        signed = jnp.issubdtype(x.dtype, jnp.signedinteger)
        hi = (x >> 32).astype(jnp.int32 if signed else jnp.uint32)
        lo = (x & jnp.asarray(0xFFFFFFFF, x.dtype)).astype(jnp.uint32)
        return [(hi, "i"), (lo, "i")]
    if jnp.issubdtype(dt, jnp.floating):
        v = -x if descending else x
        # positive canonical NaN sorts after all numbers in XLA's total order
        v = _canon_float(v)
        if dt == jnp.float32:
            u = jax.lax.bitcast_convert_type(v, jnp.uint32)
            flip = jnp.where(u >> 31 != 0, jnp.uint32(0xFFFFFFFF),
                             jnp.uint32(0x80000000))
            return [(u ^ flip, "i")]
        return [(v, "f")]
    raise TypeError(f"unsortable dtype {dt}")


def key_operands(datas, validities=None, row_mask=None, descendings=None,
                 nulls_position: int = NULL_LAST, pad_key: int = 4,
                 need_null_flags=None, narrow32=None) -> KeyOps:
    """Build the lexicographic sort-operand list for a key tuple.

    For each nullable key column: a null-flag operand then the packed value
    operand(s) — valid rows get flag 1, nulls get 0 (first) or 2 (last),
    matching pandas ``na_position`` independently of ascending/descending.
    A leading row-liveness operand is added when ``row_mask`` is given;
    padding rows sort last with flag ``pad_key`` (use distinct pad keys per
    table so padding never matches across tables in a dense rank).

    ``need_null_flags`` (tuple of bool per column) forces/suppresses the
    null-flag operand statically — callers ranking TWO tables together must
    pass the same tuple on both sides (operand structures must match even
    when only one side is nullable).  Default: emit iff the column has a
    validity mask.
    """
    ops, kinds = [], []
    n = datas[0].shape[0]
    if row_mask is not None:
        ops.append(jnp.where(row_mask, jnp.int32(0), jnp.int32(pad_key)))
        kinds.append("i")
    for i, d in enumerate(datas):
        desc = bool(descendings[i]) if descendings is not None else False
        v = validities[i] if validities is not None else None
        need_nf = (v is not None) if need_null_flags is None \
            else bool(need_null_flags[i])
        if need_nf:
            if v is None:
                nf = jnp.ones(n, jnp.int32)
            else:
                nf = jnp.where(v, jnp.int32(1), jnp.int32(nulls_position))
                d = jnp.where(v, d, jnp.zeros_like(d))
            ops.append(nf)
            kinds.append("i")
        nrw = bool(narrow32[i]) if narrow32 is not None else False
        for val, kind in _sort_value(d, desc, narrow=nrw):
            ops.append(val)
            kinds.append(kind)
    return KeyOps(tuple(ops), tuple(kinds))


def key_operand_kinds(dtypes, need_null_flags, narrow32) -> tuple:
    """Static operand KIND tuple that :func:`key_operands` (with a
    ``row_mask``, ascending keys) produces for this key structure —
    liveness flag, then per column an optional null flag plus the value
    operand kind(s).  This is :func:`_sort_value`'s packing rules in
    dtype space only (no arrays built): keep the two in lockstep — the
    Pallas probe's eligibility gate and exec/pipeline's static operand
    counts both read this."""
    kinds = ["i"]
    for dt, nf, nrw in zip(dtypes, need_null_flags, narrow32):
        if nf:
            kinds.append("i")
        d = np.dtype(dt)
        if d.kind == "b":
            kinds.append("i")
        elif d.kind in "iu":
            # wide 64-bit values split into a native (hi, lo) lane pair
            kinds.extend(("i",) if (d.itemsize <= 4 or nrw) else ("i", "i"))
        elif d.kind == "f":
            # f32 sorts via the order-preserving uint32 bitcast ('i');
            # f64 keeps native NaN-aware float compares ('f')
            kinds.append("i" if d.itemsize <= 4 else "f")
        else:
            raise TypeError(f"unsortable dtype {dt}")
    return tuple(kinds)


def concat_keyops(a: KeyOps, b: KeyOps) -> KeyOps:
    assert a.kinds == b.kinds
    return KeyOps(tuple(jnp.concatenate([x, y]) for x, y in zip(a.ops, b.ops)),
                  a.kinds)


# -- float-aware elementwise comparisons (post-canonicalization) ------------

def op_neq(a, b, kind: str):
    if kind == "f":
        return (a != b) & ~(jnp.isnan(a) & jnp.isnan(b))
    return a != b


def op_gt(a, b, kind: str):
    if kind == "f":
        return (a > b) | (jnp.isnan(a) & ~jnp.isnan(b))
    return a > b


def op_eq(a, b, kind: str):
    if kind == "f":
        return (a == b) | (jnp.isnan(a) & jnp.isnan(b))
    return a == b


def neighbor_flags(sorted_ops, kinds):
    """int32 flags: row i != row i-1 under the key tuple (row 0 → 0)."""
    n = sorted_ops[0].shape[0]
    neq = jnp.zeros(n, jnp.int32)
    for op, kind in zip(sorted_ops, kinds):
        d = op_neq(op[1:], op[:-1], kind).astype(jnp.int32)
        neq = neq | jnp.concatenate([jnp.zeros(1, jnp.int32), d])
    return neq


def dense_rank(keyops: KeyOps):
    """Rank rows by their key tuple: returns ``(gids, n_groups)`` where
    ``gids[i]`` is the 0-based dense rank of row i's key (ids ordered like
    the keys — an order-preserving perfect hash over this batch)."""
    n = keyops.n
    idx = jnp.arange(n, dtype=jnp.int32)
    sorted_all = jax.lax.sort(keyops.ops + (idx,), num_keys=len(keyops.ops),
                              is_stable=True)
    sidx = sorted_all[-1]
    gid_sorted = jnp.cumsum(neighbor_flags(sorted_all[:-1], keyops.kinds))
    gids = jnp.zeros(n, jnp.int32).at[sidx].set(gid_sorted.astype(jnp.int32))
    n_groups = (jnp.where(n > 0, gid_sorted[-1] + 1, 0).astype(jnp.int32)
                if n > 0 else jnp.int32(0))
    return gids, n_groups


def row_neq_prev(datas, validities=None, narrow32=None):
    """(n,) bool: row i's key tuple differs from row i-1's (row 0 -> False).
    Null-aware (null == null, null != value) and float-total (NaN == NaN,
    -0.0 == 0.0) — the same equality the dense rank implements, but computed
    directly on adjacent rows of an already-grouped table (no sort).
    ``narrow32[i]`` (host-known bounds fit int32) compares a 64-bit integer
    column in native int32 (x64-emulated i64 compares cost 2-4x)."""
    n = datas[0].shape[0]
    neq = jnp.zeros(max(n - 1, 0), bool)
    for i, d in enumerate(datas):
        if jnp.issubdtype(d.dtype, jnp.floating):
            d = _canon_float(d)
            kind = "f"
        else:
            if narrow32 is not None and bool(narrow32[i]) \
                    and d.dtype.itemsize == 8:
                d = d.astype(jnp.int32)
            kind = "i"
        dn = op_neq(d[1:], d[:-1], kind)
        v = validities[i] if validities is not None else None
        if v is not None:
            dn = (v[1:] != v[:-1]) | (dn & v[1:] & v[:-1])
        neq = neq | dn
    return jnp.concatenate([jnp.zeros(min(n, 1), bool), neq])


def grouped_gids(datas, validities, mask, narrow32=None):
    """Dense group ids for an already-grouped (equal keys contiguous) shard:
    boundary flags + prefix sum — no sort.  Returns (gids, n_groups, first)
    with masked (padding) rows excluded from the id space (caller routes
    them); ``first`` marks each group's first live row."""
    n = datas[0].shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    first0 = pos == 0
    bnd = (row_neq_prev(datas, validities, narrow32) | first0) & mask
    gid = jnp.cumsum(bnd.astype(jnp.int32)).astype(jnp.int32) - 1
    n_groups = jnp.max(jnp.where(mask, gid, -1)) + 1
    return jnp.where(mask, gid, n), n_groups.astype(jnp.int32), bnd


def _rows_cmp_splitters(keyops: KeyOps, splitter_ops: tuple):
    n = keyops.n
    s = splitter_ops[0].shape[0]
    gt = jnp.zeros((n, s), bool)
    eq = jnp.ones((n, s), bool)
    for op, sop, kind in zip(keyops.ops, splitter_ops, keyops.kinds):
        a = op[:, None]
        b = sop[None, :]
        gt = gt | (eq & op_gt(a, b, kind))
        eq = eq & op_eq(a, b, kind)
    return gt, eq


def rows_cmp_splitters(keyops: KeyOps, splitter_ops: tuple):
    """(gt, eq) (n, S) bool pairs: row i's key tuple strictly greater
    than / exactly equal to splitter j's under the operand total order —
    the comparison primitive of the skew-split plan facade
    (relational/skew.py): heavy-key membership (eq) and key-rank
    corrections (gt ≡ "splitter sorts before row") both run in OPERAND
    space, so they agree bit-for-bit with the join sort's own key order
    (float canonicalization, null flags, narrow lanes and all)."""
    return _rows_cmp_splitters(keyops, splitter_ops)


def rows_gt_splitters(keyops: KeyOps, splitter_ops: tuple):
    """(n, S) bool: row i's key tuple strictly greater than splitter j's.
    Used by sample-sort range partitioning (reference table.cpp:564-609
    split-point binary search).  ``splitter_ops`` parallel ``keyops.ops``
    with shape (S,) each."""
    gt, _ = _rows_cmp_splitters(keyops, splitter_ops)
    return gt


def rows_ge_splitters(keyops: KeyOps, splitter_ops: tuple):
    """(n, S) bool: row i's key tuple >= splitter j's under the same total
    order as :func:`rows_gt_splitters`.  Used by the range-partitioned
    pipeline (exec/pipeline.py): splitters are key-GROUP STARTS of the
    sorted build side, so a probe key equal to splitter j belongs to the
    range j opens — assignment must be >=, not >."""
    gt, eq = _rows_cmp_splitters(keyops, splitter_ops)
    return gt | eq
