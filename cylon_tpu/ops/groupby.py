"""Segment-reduction groupby kernels.

TPU-native replacement for the reference's groupby engines
(cpp/src/cylon/groupby/hash_groupby.cpp CRTP agg kernels,
cpp/src/cylon/mapreduce/mapreduce.hpp:79 ``MapReduceKernel`` with its
CombineLocally → shuffle intermediates → ReduceShuffledResults → Finalize
flow, and compute/aggregate_kernels.hpp:43 ``AggregationOpId``).

Design: group identity comes from a dense rank (:mod:`.pack`) instead of a
hash map; every aggregation is then a ``jax.ops.segment_*`` — an XLA scatter
that fuses and vectorizes.  The MapReduce decomposition is preserved exactly
because it is what makes distributed groupby cheap: each op declares
*intermediate* columns that are themselves segment-reducible (MEAN →
{sum,count}, VAR/STD → {sum,sumsq,count}), so the distributed path is
local-combine → hash-shuffle intermediates → combine → finalize
(reference groupby/groupby.cpp:33 ``DistributedHashGroupBy``).

Masked (padding) rows are routed to one extra trash segment which is sliced
off — never out-of-bounds scatters.

Supported ops (AggregationOpId parity): sum, count, min, max, mean, var,
std, nunique, quantile/median (+ first/last index helpers).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

#: ops whose intermediates are plain segment reductions (associative —
#: eligible for local pre-combine before the shuffle, groupby.cpp:76-81)
ASSOCIATIVE = {"sum", "count", "min", "max", "mean", "var", "std",
               "sumsq"}
#: ops that must see raw (shuffled) values
NON_ASSOCIATIVE = {"nunique", "quantile", "median"}


def _int_dtype():
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


def _route(gids, num_segments, mask):
    """(effective gids, total segments): masked rows → trash segment."""
    if mask is None:
        return gids, num_segments
    return jnp.where(mask, gids, jnp.int32(num_segments)), num_segments + 1


#: below this segment count a dense one-hot masked reduction replaces the
#: scatter: XLA's scatter-add serializes on colliding indices (~72 ns/row
#: measured on v5e at any small segment count, vs ~9-36 ns/row for the
#: dense broadcast-compare-reduce, which the VPU vectorizes across segment
#: lanes; crossover ~8-16k segments)
_DENSE_SEG_MAX = 4096


def _ident(kind: str, dt):
    if kind == "min":
        if jnp.issubdtype(dt, jnp.floating):
            return jnp.asarray(jnp.inf, dt)
        if dt == jnp.bool_:
            return jnp.asarray(True)
        return jnp.asarray(jnp.iinfo(dt).max, dt)
    if kind == "max":
        if jnp.issubdtype(dt, jnp.floating):
            return jnp.asarray(-jnp.inf, dt)
        if dt == jnp.bool_:
            return jnp.asarray(False)
        return jnp.asarray(jnp.iinfo(dt).min, dt)
    return jnp.asarray(0, dt)  # sum


def _seg_apply(kind: str, values, g, ns: int, out_len: int):
    """Segment reduce over ROUTED gids ``g`` (trash segment included in
    ``ns``), returning the first ``out_len`` segments.  Dense one-hot
    reduction below :data:`_DENSE_SEG_MAX`, scatter otherwise — both yield
    the reduction identity for empty segments."""
    if ns <= _DENSE_SEG_MAX:
        eq = g[:, None] == jnp.arange(out_len, dtype=g.dtype)[None, :]
        src = jnp.where(eq, values[:, None], _ident(kind, values.dtype))
        red = {"sum": jnp.sum, "min": jnp.min, "max": jnp.max}[kind]
        return red(src, axis=0)
    fn = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
          "max": jax.ops.segment_max}[kind]
    return fn(values, g, num_segments=ns)[:out_len]


def seg_sum(values, gids, num_segments, mask=None):
    g, ns = _route(gids, num_segments, mask)
    return _seg_apply("sum", values, g, ns, num_segments)


def seg_count(values, gids, num_segments, mask=None):
    g, ns = _route(gids, num_segments, mask)
    ones = jnp.ones(gids.shape[0], _int_dtype())
    return _seg_apply("sum", ones, g, ns, num_segments)


def seg_min(values, gids, num_segments, mask=None):
    g, ns = _route(gids, num_segments, mask)
    return _seg_apply("min", values, g, ns, num_segments)


def seg_max(values, gids, num_segments, mask=None):
    g, ns = _route(gids, num_segments, mask)
    return _seg_apply("max", values, g, ns, num_segments)


def _ftype(values):
    # accumulate in float64 whenever available: float32 sums over large
    # groups / large-magnitude ints lose precision visibly (and var via
    # E[x^2]-mean^2 compounds it with cancellation)
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


# ---------------------------------------------------------------------------
# Grouped-run reductions (fast path for inputs with contiguous equal keys)
#
# Scatter-add segment reductions dominate groupby runtime on TPU for large
# segment counts.  When the input is already grouped (join/sort output), a
# per-group sum is a difference of the value prefix sum at the run bounds:
# one cumsum + one stacked gather replaces each scatter pass.  Integer
# prefix diffs are exact; float inputs accumulate in float64.
# ---------------------------------------------------------------------------

def grouped_starts(gids, first, mask, n_live, seg_cap: int):
    """First live row position of each group id, for grouped input (each
    group one contiguous run in the live prefix).  Slots past the last
    group hold ``n_live`` — making them both the empty-group sentinel and
    the "next start" of the final group, so every run extent is a
    consecutive diff of this one array.  ONE scatter."""
    n = gids.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    scat = jnp.where(first & mask, gids, jnp.int32(seg_cap))
    return jnp.full(seg_cap, n_live, jnp.int32).at[scat].set(pos,
                                                             mode="drop")


_GROUPED_NEEDS = {"sum": ("sum",), "count": ("count",),
                  "sumsq": ("sumsq",),
                  "mean": ("sum", "count"),
                  "var": ("sum", "sumsq", "count"),
                  "std": ("sum", "sumsq", "count")}


def _u32(x):
    return jax.lax.bitcast_convert_type(x, jnp.uint32)


def grouped_reduce(ops, values_list, vmasks, starts, n_live, key_datas,
                   key_valids, seg_cap: int, key_narrow=None,
                   value_narrow=None, pad_lanes: int = 0,
                   gather_parts: int = 1, use_window: int = 0):
    """Grouped-input fast path, fully batched: per-group sums for the
    cumsum-able ops (sum/count/mean/var/std) AND the representative-key
    gather share ONE u32 lane-matrix gather (plus one f64 side gather when
    float accumulators are present — f64 cannot lane-split on TPU).

    For contiguous runs, group g's sum over x is PS[starts[g+1]] -
    PS[starts[g]], with PS the zero-padded exclusive prefix of x and
    starts[n_groups..] = n_live — so a single (seg_cap, L) gather of the
    stacked prefix lanes at ``starts`` + a consecutive diff replaces every
    per-column reduction pass (gathers are the dominant groupby cost on
    TPU, ~15 ns/row; splitting an i64 prefix into (hi, lo) u32 lanes is
    elementwise ~1 ns/row).  Key columns and their validity ride the same
    gather as passthrough lanes; ``key_narrow[i]`` (host-known bounds fit
    int32) rides a 64-bit key as ONE lane; ``value_narrow[i]`` (host-proven
    n·max|v| fits int32 — a BOOLEAN so compiled-fn caches key on it, not on
    raw data bounds) narrows the i-th op's integer SUM prefix to one lane.

    ``use_window`` (a window size, 0 = off) routes the u32 matrix gather
    through the Pallas windowed kernel (ops/pallas_gather) — ~6x the XLA
    gather at bench density.  Returns (inter dicts per op, key_out tuple,
    kval_out tuple, win_ok) — win_ok is a scalar bool that is False when
    a windowed tile's index span overflowed (results are then garbage and
    the DISPATCH layer must re-run with use_window=0)."""
    from . import lanes as lanes_mod
    n = key_datas[0].shape[0]

    # entries: (kind, slot, name) with kind prefix|key|kval; each appends
    # its u32 lanes (or f64 side columns) plus a reconstruction recipe
    u32_cols: list = []    # (n+1,) u32 arrays
    f64_cols: list = []    # (n+1,) f64 arrays (side channel)
    recipes: list = []     # (kind, slot, name, space, lane_ids, meta)

    acc_i = _int_dtype()   # int64, or int32 under the CYLON_TPU_X64=0 opt-out

    def prefix_lanes(src, islot, name):
        if jnp.issubdtype(src.dtype, jnp.floating):
            ps = jnp.concatenate([jnp.zeros(1, src.dtype), jnp.cumsum(src)])
            if src.dtype == jnp.float32 and not jax.config.jax_enable_x64:
                u32_cols.append(_u32(ps))
                recipes.append(("prefix", islot, name, "u32",
                                (len(u32_cols) - 1,), "f32"))
            else:
                f64_cols.append(ps.astype(jnp.float64))
                recipes.append(("prefix", islot, name, "f64",
                                (len(f64_cols) - 1,), None))
            return
        ps = jnp.concatenate([jnp.zeros(1, acc_i),
                              jnp.cumsum(src.astype(acc_i))])
        narrow = name == "count" or (
            name == "sum" and value_narrow is not None
            and bool(value_narrow[islot]))
        narrow = narrow or np.dtype(ps.dtype).itemsize == 4
        ls = lanes_mod._to_lanes(ps, narrow)   # 1 lane narrow, else (hi, lo)
        u32_cols.extend(ls)
        recipes.append(("prefix", islot, name, "u32",
                        tuple(range(len(u32_cols) - len(ls),
                                    len(u32_cols))),
                        ("int32" if np.dtype(ps.dtype).itemsize == 4
                         else "int64", narrow)))

    def pass_lanes(src, kind, kslot):
        """Passthrough (gathered at start, no diff): key data / validity.
        Lane split/reconstruct delegates to lanes._to_lanes/_from_lanes
        (one fork of the per-dtype packing rules, not two); recipe meta =
        (dtype name, narrow flag) for the reconstruction."""
        ext = jnp.concatenate([src, src[-1:]])
        dt = np.dtype(ext.dtype)
        if dt == np.float64:
            f64_cols.append(ext)
            recipes.append((kind, kslot, None, "f64",
                            (len(f64_cols) - 1,), ("float64", False)))
            return
        nrw = key_narrow is not None and kind == "key" \
            and bool(key_narrow[kslot]) and dt.itemsize == 8 \
            and dt.kind in ("i", "u")
        if np.issubdtype(dt, np.floating) and dt != np.float32:
            ext = ext.astype(jnp.float32)  # f16 widens; recon casts back
        ls = lanes_mod._to_lanes(ext, nrw)
        u32_cols.extend(ls)
        recipes.append((kind, kslot, None, "u32",
                        tuple(range(len(u32_cols) - len(ls),
                                    len(u32_cols))), (dt.name, nrw)))

    for i, op in enumerate(ops):
        vm = vmasks[i] if vmasks[i] is not None else jnp.ones(n, bool)
        v = values_list[i]
        f = v.astype(_ftype(v)) if (op in ("mean", "var", "std", "sumsq")
                                    or jnp.issubdtype(v.dtype, jnp.floating)) \
            else v
        for name in _GROUPED_NEEDS[op]:
            if name == "count":
                src = vm.astype(jnp.int32)
            elif name == "sum":
                src = jnp.where(vm, f, jnp.zeros_like(f))
            else:
                src = jnp.where(vm, f * f, jnp.zeros_like(f))
            prefix_lanes(src, i, name)
    for ki, (d, v) in enumerate(zip(key_datas, key_valids)):
        pass_lanes(d, "key", ki)
        if v is not None:
            pass_lanes(v, "kval", ki)

    def gather_pair(cols):
        mat = jnp.stack(cols, axis=1)                  # (n+1, L)
        g = mat[starts]                                # THE gather
        # "next start" of slot seg_cap-1 is n_live (PS there = full total)
        tailv = mat[jnp.minimum(n_live, n)][None, :]
        g_next = jnp.concatenate([g[1:], tailv], axis=0)
        return g, g_next

    def gather_pair_multi(cols):
        """gather_pair split into ``gather_parts`` narrower matrix
        gathers, columns re-concatenated in order — another shape-shifting
        variant for the XLA:TPU compiler-crash ladder (specific full-width
        combinations crash; the narrower parts compile)."""
        parts = min(gather_parts, len(cols))
        if parts <= 1:
            return gather_pair(cols)
        bounds = np.linspace(0, len(cols), parts + 1).astype(int)
        gs, gns = [], []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            if hi > lo:
                g, gn = gather_pair(list(cols[lo:hi]))
                gs.append(g)
                gns.append(gn)
        return (jnp.concatenate(gs, axis=1),
                jnp.concatenate(gns, axis=1))

    win_ok = jnp.ones((), bool)
    windowed = False
    if use_window and u32_cols:
        from . import pallas_gather as pg
        windowed = pg.supported(n + 1, seg_cap, len(u32_cols), use_window)
    if pad_lanes and not windowed:
        # XLA:TPU compiler landmine: specific (u32, f64) gather-lane width
        # combinations SIGSEGV tpu_compile_helper (v5e libtpu 2026-07; e.g.
        # 7xu32+6xf64 crashes while 8xu32+6xf64 compiles).  Callers retry a
        # crashed compile with pad_lanes>0 dummy lanes to shift the width.
        u32_cols = u32_cols + [jnp.zeros(n + 1, jnp.uint32)] * pad_lanes
    g_u = gn_u = g_f = gn_f = None
    if windowed:
        # lane-major stack (a post-hoc transpose would cost ~700 ms; the
        # axis-0 stack is a plain concat); f64 side columns keep the XLA
        # gather below
        mat_t = jnp.stack(u32_cols, axis=0)
        g_u, win_ok = pg.windowed_take_t(mat_t, starts, use_window)
        tail = jax.lax.dynamic_slice(
            mat_t, (jnp.int32(0), jnp.minimum(n_live, jnp.int32(n))),
            (len(u32_cols), 1))
        gn_u = jnp.concatenate([g_u[:, 1:], tail], axis=1)
    elif u32_cols:
        g_u, gn_u = gather_pair_multi(u32_cols)
    if f64_cols:
        g_f, gn_f = gather_pair_multi(f64_cols)

    def ucol(li, at_next: bool):
        src = gn_u if at_next else g_u
        return src[li] if windowed else src[:, li]

    def prefix_recon(lane_ids, meta, at_next: bool):
        """Gathered prefix lanes -> accumulator value (i32/i64/f32/f64)."""
        if meta is None:  # f64 side channel
            return (gn_f if at_next else g_f)[:, lane_ids[0]]
        if meta == "f32":
            return jax.lax.bitcast_convert_type(ucol(lane_ids[0], at_next),
                                                jnp.float32)
        dt_name, nrw = meta
        return lanes_mod._from_lanes([ucol(li, at_next) for li in lane_ids],
                                     dt_name, nrw)

    inters = [dict() for _ in ops]
    key_out = [None] * len(key_datas)
    kval_out = [None] * len(key_datas)
    for kind, slot, name, space, lane_ids, meta in recipes:
        if kind == "prefix":
            d = prefix_recon(lane_ids, meta, True) \
                - prefix_recon(lane_ids, meta, False)
            if name == "count":
                d = d.astype(_int_dtype())
            inters[slot][name] = d
        else:
            dt_name, nrw = meta
            if space == "f64":
                v = g_f[:, lane_ids[0]]
            else:
                v = lanes_mod._from_lanes([ucol(li, False)
                                           for li in lane_ids],
                                          dt_name, nrw)
            if kind == "key":
                key_out[slot] = v
            else:  # validity lanes are always planned as bool
                kval_out[slot] = v
    return inters, tuple(key_out), tuple(kval_out), win_ok


#: ops whose grouped-input fast path avoids scatter reductions entirely
CUMSUMMABLE = {"sum", "count", "mean", "var", "std", "sumsq"}


# ---------------------------------------------------------------------------
# MapReduce decomposition (reference mapreduce.hpp:56-76 six-stage flow)
# ---------------------------------------------------------------------------

def combine_locally(op: str, values, gids, num_segments, mask=None):
    """Stage 1: per-group intermediates on local rows.  Returns a dict of
    named intermediate arrays, each of length num_segments and each further
    reducible by :func:`reduce_intermediates`."""
    if op == "sum":
        return {"sum": seg_sum(values, gids, num_segments, mask)}
    if op == "count":
        return {"count": seg_count(values, gids, num_segments, mask)}
    if op == "min":
        return {"min": seg_min(values, gids, num_segments, mask),
                "count": seg_count(values, gids, num_segments, mask)}
    if op == "max":
        return {"max": seg_max(values, gids, num_segments, mask),
                "count": seg_count(values, gids, num_segments, mask)}
    if op == "mean":
        f = values.astype(_ftype(values))
        return {"sum": seg_sum(f, gids, num_segments, mask),
                "count": seg_count(values, gids, num_segments, mask)}
    if op in ("var", "std"):
        f = values.astype(_ftype(values))
        return {"sum": seg_sum(f, gids, num_segments, mask),
                "sumsq": seg_sum(f * f, gids, num_segments, mask),
                "count": seg_count(values, gids, num_segments, mask)}
    if op == "sumsq":
        f = values.astype(_ftype(values))
        return {"sumsq": seg_sum(f * f, gids, num_segments, mask)}
    raise ValueError(f"op {op} has no associative decomposition")


_REDUCERS = {"sum": seg_sum, "sumsq": seg_sum, "count": seg_sum,
             "min": seg_min, "max": seg_max}


def reduce_intermediates(inter: dict, gids, num_segments, mask=None):
    """Stage 4: combine shuffled intermediates keyed by new group ids.
    min/max of empty pre-groups carry sentinel values; their count=0 keeps
    them out of the final validity."""
    return {k: _REDUCERS[k](v, gids, num_segments, mask)
            for k, v in inter.items()}


def finalize(op: str, inter: dict, ddof: int = 1):
    """Stage 5: intermediates → (result_values, result_validity|None)."""
    cnt = inter.get("count")
    if op == "sum":
        return inter["sum"], None
    if op == "sumsq":
        return inter["sumsq"], None
    if op == "count":
        return inter["count"], None
    if op == "min":
        return inter["min"], (cnt > 0) if cnt is not None else None
    if op == "max":
        return inter["max"], (cnt > 0) if cnt is not None else None
    if op == "mean":
        c = jnp.maximum(cnt, 1).astype(inter["sum"].dtype)
        return inter["sum"] / c, cnt > 0
    if op in ("var", "std"):
        c = jnp.maximum(cnt, 1).astype(inter["sum"].dtype)
        mean = inter["sum"] / c
        var = jnp.maximum(inter["sumsq"] / c - mean * mean, 0.0)
        denom = jnp.maximum(cnt - ddof, 1).astype(var.dtype)
        var = var * (c / denom)
        ok = cnt > ddof
        return (jnp.sqrt(var) if op == "std" else var), ok
    raise ValueError(f"unknown associative op {op}")


# ---------------------------------------------------------------------------
# Non-associative ops on raw (possibly shuffled) values
# ---------------------------------------------------------------------------

def nunique(value_keyops, gids, num_segments, mask=None):
    """Distinct count per group: sort (gid, value...) tuples, count boundary
    transitions per segment.  ``value_keyops`` is a
    :class:`~cylon_tpu.ops.pack.KeyOps` over the value column; pass a mask to
    exclude padding/null rows (pandas nunique drops nulls)."""
    from .pack import neighbor_flags
    g, ns = _route(gids, num_segments, mask)
    keys = (g,) + value_keyops.ops
    kinds = ("i",) + value_keyops.kinds
    srt = jax.lax.sort(keys, num_keys=len(keys), is_stable=False)
    gs = srt[0]
    first = jnp.concatenate([jnp.ones(1, jnp.int32),
                             jnp.zeros(gs.shape[0] - 1, jnp.int32)]) \
        if gs.shape[0] else jnp.zeros(0, jnp.int32)
    neq = neighbor_flags(srt, kinds) | first
    return _seg_apply("sum", neq, gs, ns, num_segments)


def quantile(values, gids, num_segments, q: float, mask=None):
    """Per-group quantile with linear interpolation.  Sorts (gid, value) then
    indexes each group's sorted run via count prefix sums."""
    f = values.astype(_ftype(values))
    g, ns = _route(gids, num_segments, mask)
    v = f if mask is None else jnp.where(mask, f, jnp.inf)
    g_s, v_s = jax.lax.sort((g, v), num_keys=2, is_stable=False)
    cnt_all = _seg_apply("sum", jnp.ones_like(g, dtype=_int_dtype()), g,
                         ns, ns)
    offs_all = jnp.concatenate(
        [jnp.zeros(1, cnt_all.dtype), jnp.cumsum(cnt_all)[:-1]])
    cnt, offs = cnt_all[:num_segments], offs_all[:num_segments]
    posf = jnp.asarray(q, f.dtype) * jnp.maximum(cnt - 1, 0).astype(f.dtype)
    lo = jnp.floor(posf).astype(cnt.dtype)
    hi = jnp.ceil(posf).astype(cnt.dtype)
    frac = posf - lo.astype(f.dtype)
    n = v_s.shape[0]
    take = lambda i: v_s[jnp.clip(offs + i, 0, max(n - 1, 0)).astype(jnp.int32)]
    vlo, vhi = take(lo), take(hi)
    return vlo + (vhi - vlo) * frac, cnt > 0


def group_first_index(gids, num_segments, mask=None):
    """Representative (first) source-row index per group — used to gather the
    key columns of the groupby result."""
    n = gids.shape[0]
    g, ns = _route(gids, num_segments, mask)
    idx = jnp.arange(n, dtype=jnp.int32)
    return _seg_apply("min", idx, g, ns, num_segments)


def np_result_dtype(op: str, src: np.dtype) -> np.dtype:
    if op in ("count", "nunique"):
        return np.dtype(np.int64)
    if op in ("mean", "var", "std", "sumsq", "quantile", "median"):
        # float32 in -> float32 out (pandas parity); everything else f64.
        # Accumulation happens in _ftype regardless; this is the result cast.
        return (np.dtype(np.float32) if src == np.dtype(np.float32)
                else np.dtype(np.float64))
    return np.dtype(src)


# ---------------------------------------------------------------------------
# armed-audit saturation guard (the integrity tier's abort-not-wrong
# satellite — docs/robustness.md "Integrity audit tier")
# ---------------------------------------------------------------------------

#: int64 accumulators past this magnitude count as saturated: 2**62
#: leaves headroom for ONE more combine doubling, so the guard fires
#: while the value is still meaningful — both pre-wrap (a huge positive
#: one step from wrapping) and post-wrap (the wrapped negative) land
#: outside the rail.  The ±rail form also avoids the int64 abs(INT64_MIN)
#: trap (abs of the minimum is itself negative).
SATURATION_RAIL = 1 << 62


def guard_saturation(op: str, data, *, column=None,
                     site: str = "groupby.finalize") -> None:
    """Armed-audit overflow guard (``CYLON_TPU_AUDIT=1``): int64
    ``sum``/``count`` accumulators wrap silently in XLA — a saturated
    aggregate is a WRONG answer, not an error.  Called at the host
    assembly boundary (concrete result columns, never inside a traced
    builder); raises a typed
    :class:`~cylon_tpu.status.NumericOverflowError` so the run aborts
    instead of publishing the wrap.  Unarmed: one env-cached load."""
    from ..exec import integrity
    if not integrity.armed():
        return
    if op not in ("sum", "count"):
        return
    if np.dtype(getattr(data, "dtype", "f8")) != np.dtype(np.int64):
        return
    if not getattr(data, "size", 0):
        return
    hi, lo = int(jnp.max(data)), int(jnp.min(data))
    if hi > SATURATION_RAIL or lo < -SATURATION_RAIL:
        from ..status import NumericOverflowError
        raise NumericOverflowError(
            f"groupby {op} accumulator saturated int64 (|value| > 2**62; "
            f"max={hi}, min={lo}): the aggregate has wrapped or is one "
            "combine away from wrapping — aborting instead of returning "
            "a silently wrong answer", site=site, column=column)
