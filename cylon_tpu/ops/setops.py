"""Set-semantic kernels: unique / union / intersect / subtract.

TPU-native replacement for the reference's row-set operators
(cpp/src/cylon/table.cpp ``Union`` :925, ``Subtract`` :997, ``Intersect``
:1051, ``Unique`` :1306) which build ska::bytell hash sets of row indices over
``TableRowIndexHash/EqualTo`` comparators.  Hash sets don't map to XLA; the
dense-rank (:mod:`.pack`) turns "row set membership" into integer segment
logic:

* rows of both tables are dense-ranked together → group id == row value;
* per-group presence flags (``in_a``/``in_b``) come from segment ORs;
* the surviving representative row per group is a segment-min of row index;
* compaction to the output is a stable sort by flag (static capacity).

All kernels are two-phase (count → materialize) like :mod:`.join`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..utils.cache import jit


def _first_index_per_group(gids, idx, num_segments_cap):
    return jax.ops.segment_min(idx, gids, num_segments=num_segments_cap)


@partial(jit, static_argnames=("keep",))
def unique_flags(gids, mask=None, keep: str = "first"):
    """Flag the kept occurrence of each distinct row (reference Unique
    :1306 keep-first/last).  gids: dense rank per row; masked rows never
    flagged."""
    n = gids.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    cap = n + 1
    g = gids if mask is None else jnp.where(mask, gids, jnp.int32(n))
    if keep == "last":
        rep = jax.ops.segment_max(idx, g, num_segments=cap)
    else:
        rep = jax.ops.segment_min(idx, g, num_segments=cap)
    flag = rep[g] == idx
    if mask is not None:
        flag = flag & mask
    return flag


@partial(jit, static_argnames=("op",))
def set_op_flags(gids_cat, side_is_b, op: str, mask=None):
    """Flags over the concatenated rows of A then B selecting the output rows
    of a set operation (distinct semantics, matching the reference):

    * union:     first occurrence of each group (A preferred — A rows come
                 first in the concat, segment_min picks them)
    * intersect: first A-occurrence of groups present in both
    * subtract:  first A-occurrence of groups absent from B
    """
    n = gids_cat.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    cap = n + 1
    g = gids_cat if mask is None else jnp.where(mask, gids_cat, jnp.int32(n))
    a_row = (~side_is_b) if mask is None else ((~side_is_b) & mask)
    b_row = side_is_b if mask is None else (side_is_b & mask)
    in_b = jax.ops.segment_max(b_row.astype(jnp.int32), g, num_segments=cap)
    # first A row of each group (n when group has no A row)
    first_a = jax.ops.segment_min(jnp.where(a_row, idx, jnp.int32(n)), g,
                                  num_segments=cap)
    if op == "union":
        first_any = jax.ops.segment_min(idx, g, num_segments=cap)
        flag = (first_any[g] == idx)
        if mask is not None:
            flag = flag & mask
        return flag
    if op == "intersect":
        flag = (first_a[g] == idx) & (in_b[g] > 0)
    elif op == "subtract":
        flag = (first_a[g] == idx) & (in_b[g] == 0)
    else:
        raise ValueError(f"unknown set op {op}")
    return flag & a_row
