"""Local join kernel: single-sort merge + segmented-scan geometry.

TPU-native replacement for the reference's local join layer
(cpp/src/cylon/join/join.cpp:60 ``JoinTables`` dispatch, sort_join.cpp:66
``do_sorted_join``, hash_join.cpp:22-85).  The reference's default algorithm
is SORT (join_config.hpp:37); a pointer-chasing hash build/probe doesn't map
to XLA, so the sort path is *the* design here (SURVEY.md §7 hard-part 2),
engineered around the measured v5e cost model: ``lax.sort`` is cheap
(~7 ns/row), random gathers are expensive (~20 ns/row/lane), segment
reductions with large segment counts are expensive — prefix scans are cheap.

  1. ``join_sort_state``: ONE stable sort of the concatenated (left ++
     right) packed key tuples (u32 lanes, :mod:`.pack`).  Stability makes
     left rows precede right rows within every equal-key run, so the sorted
     order itself encodes the merge.
  2. ``join_carry``: per-position geometry from *segmented scans* only
     (``associative_scan`` — no segment reductions, no group-space gather):
     reverse segmented counts give every left row its group's right-count
     and the position where its matches start; forward counts give right
     rows their left-count (for right/outer emission).
  3. ``join_take``: output expansion — a scatter + ``cummax`` reconstructs
     "which emitting row owns output slot k" (offsets are strictly
     increasing over emitting rows), then ONE stacked (out, 4) meta gather +
     ONE 1-D gather produce the (l_take, r_take) index pairs.

Output size is data-dependent; callers run phase 1 (sort + carry + exact
count), pick a static pow2 capacity, then phase 2 — with the carry arrays
passed between the two compiled programs as device residents so the sort
and scans run once.

INNER / LEFT / RIGHT / FULL_OUTER all supported (join_config.hpp:25);
"right" emits from the right side over the same sorted state (left rows
lead every group, so right-row matches start at the group start).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .pack import KeyOps, concat_keyops, neighbor_flags


class JoinCarry(NamedTuple):
    """Per-sorted-position state carried from count to materialize phase.
    All (n_l + n_r,) int32 device arrays."""
    offs: jax.Array    # exclusive prefix sum of eff (output offset)
    eff: jax.Array     # output rows this position emits
    cnt: jax.Array     # match count of the position's group (other side)
    mstart: jax.Array  # sorted position where this row's matches start
    idx_s: jax.Array   # concat-row index at this sorted position
    un: jax.Array      # outer only: 1 = unmatched right row (else zeros)


def join_sort_state(ko_l: KeyOps, ko_r: KeyOps, payloads: tuple = ()):
    """THE sort: stable lexicographic sort of the concatenated key tuples.

    Returns ``(bnd, idx_s, sorted_payloads)`` — bnd/idx_s (n_l + n_r,)
    int32.  ``idx_s[p]`` is the concat-row index occupying sorted position
    p (values < n_l are left rows); ``bnd[p]`` = 1 iff position p starts a
    new key group (p=0 -> 0).  Stability ⇒ within a group, left rows come
    first, each side in source order.

    ``payloads``: optional (n_l+n_r,) arrays carried through the sort —
    moving data as sort payload costs ~2 ns/row/operand vs ~20 ns/row for
    a later gather, so callers ride small column sets along.
    """
    cat = concat_keyops(ko_l, ko_r)
    n = cat.n
    idx = jnp.arange(n, dtype=jnp.int32)
    sorted_all = jax.lax.sort(cat.ops + (idx,) + tuple(payloads),
                              num_keys=len(cat.ops), is_stable=True)
    nk = len(cat.ops)
    idx_s = sorted_all[nk]
    bnd = neighbor_flags(sorted_all[:nk], cat.kinds)
    return bnd, idx_s, tuple(sorted_all[nk + 1:])


def join_carry(bnd, idx_s, live_cat, n_l: int, how: str) -> tuple:
    """Phase-1 geometry: returns ``(total, JoinCarry)`` with ``total`` the
    exact output row count (device scalar int32).

    Segmented counts come from plain prefix sums + ONE stacked monotone
    gather at the group end/start positions — NOT ``associative_scan``,
    whose XLA:TPU compile time explodes superlinearly with array size
    (~200 s at 2M rows, measured)."""
    n = bnd.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    side = idx_s >= n_l
    live = live_cat[idx_s]
    lefts = ((~side) & live).astype(jnp.int32)
    rights = (side & live).astype(jnp.int32)
    first = bnd.astype(bool) | (pos == 0)

    s_l = jnp.cumsum(lefts).astype(jnp.int32)    # inclusive prefix counts
    s_r = jnp.cumsum(rights).astype(jnp.int32)

    emit_right = how == "right"
    keep_unmatched = how in ("left", "right", "outer")
    need_fwd = emit_right or how == "outer"

    if need_fwd:
        # lefts in the whole group, via the group-start prefix state
        start = jax.lax.cummax(jnp.where(first, pos, 0))
        at_start = jnp.stack([s_l, lefts], 1)[start]       # monotone gather

    if emit_right:
        # group left-count = S_l[end] - S_l[start-1]; for a right row p all
        # group lefts precede it, so S_l[p] already includes them all
        cnt = (s_l - (at_start[:, 0] - at_start[:, 1])).astype(jnp.int32)
        mstart = start
        emits = side & live
    else:
        # group END position = next boundary - 1 (reverse min of marks)
        ebnd = jnp.concatenate([first[1:], jnp.ones(1, bool)])
        end = jax.lax.cummin(jnp.where(ebnd, pos, jnp.int32(n)), reverse=True)
        at_end = jnp.stack([s_l, s_r], 1)[end]             # monotone gather
        t_l = at_end[:, 0] - (s_l - lefts)   # lefts in [p .. end]
        t_r = at_end[:, 1] - (s_r - rights)  # rights in [p .. end]
        cnt = t_r
        mstart = pos + t_l                   # first right position of group
        emits = (~side) & live

    eff = jnp.where(emits,
                    jnp.maximum(cnt, 1) if keep_unmatched else cnt,
                    0).astype(jnp.int32)
    csum = jnp.cumsum(eff)
    offs = (csum - eff).astype(jnp.int32)
    total = (csum[-1] if n > 0 else jnp.int32(0)).astype(jnp.int32)

    if how == "outer":
        grp_l = (s_l - (at_start[:, 0] - at_start[:, 1])).astype(jnp.int32)
        un = (side & live & (grp_l == 0)).astype(jnp.int32)
        total = total + jnp.sum(un)
    else:
        un = jnp.zeros(n, jnp.int32)
    return total, JoinCarry(offs, eff, cnt, mstart, idx_s, un)


def join_take(carry: JoinCarry, n_l: int, how: str, out_cap: int):
    """Phase-2 materialization: (l_take, r_take, total) — row index pairs of
    the join result (l_take indexes left rows 0..n_l-1, r_take right rows
    0..n_r-1), -1 marking the null side of unmatched outer rows.  ``out_cap``
    must be >= phase 1's total; slots past ``total`` hold (-1, -1)."""
    offs, eff, cnt, mstart, idx_s, un = carry
    n = offs.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    total_main = (offs[-1] + eff[-1] if n > 0 else jnp.int32(0)).astype(
        jnp.int32)

    scat = jnp.where(eff > 0, offs, jnp.int32(out_cap))
    p0 = jnp.zeros(out_cap, jnp.int32).at[scat].max(pos, mode="drop")
    p_of_k = jax.lax.cummax(p0)

    meta = jnp.stack([offs, cnt, mstart, idx_s], axis=1)[p_of_k]  # (out, 4)
    k = jnp.arange(out_cap, dtype=jnp.int32)
    rel = k - meta[:, 0]
    matched = rel < meta[:, 1]
    mpos = jnp.clip(meta[:, 2] + rel, 0, max(n - 1, 0))
    m_idx = idx_s[mpos]
    valid = k < total_main
    if how == "right":
        r_take = jnp.where(valid, meta[:, 3] - n_l, jnp.int32(-1))
        l_take = jnp.where(valid & matched, m_idx, jnp.int32(-1))
    else:
        l_take = jnp.where(valid, meta[:, 3], jnp.int32(-1))
        r_take = jnp.where(valid & matched, m_idx - n_l, jnp.int32(-1))

    total = total_main
    if how == "outer":
        unpos = (jnp.cumsum(un) - un).astype(jnp.int32)
        slot = jnp.where(un > 0, total_main + unpos, jnp.int32(out_cap))
        r_take = r_take.at[slot].set(idx_s - n_l, mode="drop")
        total = total_main + jnp.sum(un).astype(jnp.int32)
    return l_take, r_take, total, mpos
