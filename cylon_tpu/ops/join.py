"""Local join kernel: single-sort merge + segmented-scan geometry.

TPU-native replacement for the reference's local join layer
(cpp/src/cylon/join/join.cpp:60 ``JoinTables`` dispatch, sort_join.cpp:66
``do_sorted_join``, hash_join.cpp:22-85).  The reference's default algorithm
is SORT (join_config.hpp:37); a pointer-chasing hash build/probe doesn't map
to XLA, so the sort path is *the* design here (SURVEY.md §7 hard-part 2),
engineered around the measured v5e cost model: ``lax.sort`` is cheap
(~7 ns/row), random gathers are expensive (~20 ns/row/lane), segment
reductions with large segment counts are expensive — prefix scans are cheap.

  1. ``join_sort_state``: ONE stable sort of the concatenated (left ++
     right) packed key tuples (u32 lanes, :mod:`.pack`).  Stability makes
     left rows precede right rows within every equal-key run, so the sorted
     order itself encodes the merge.
  2. ``join_carry``: per-position geometry from *segmented scans* only
     (``associative_scan`` — no segment reductions, no group-space gather):
     reverse segmented counts give every left row its group's right-count
     and the position where its matches start; forward counts give right
     rows their left-count (for right/outer emission).
  3. ``join_take``: output expansion — a scatter + ``cummax`` reconstructs
     "which emitting row owns output slot k" (offsets are strictly
     increasing over emitting rows), then ONE stacked (out, 4) meta gather +
     ONE 1-D gather produce the (l_take, r_take) index pairs.

Output size is data-dependent; callers run phase 1 (sort + carry + exact
count), pick a static pow2 capacity, then phase 2 — with the carry arrays
passed between the two compiled programs as device residents so the sort
and scans run once.

INNER / LEFT / RIGHT / FULL_OUTER all supported (join_config.hpp:25);
"right" emits from the right side over the same sorted state (left rows
lead every group, so right-row matches start at the group start).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .pack import KeyOps, concat_keyops, neighbor_flags


class JoinCarry(NamedTuple):
    """Per-sorted-position state carried from count to materialize phase.
    All (n_l + n_r,) int32 device arrays."""
    offs: jax.Array    # exclusive prefix sum of eff (output offset)
    eff: jax.Array     # output rows this position emits
    cnt: jax.Array     # match count of the position's group (other side)
    mstart: jax.Array  # sorted position where this row's matches start
    idx_s: jax.Array   # concat-row index at this sorted position
    un: jax.Array      # outer only: 1 = unmatched right row (else zeros)


def join_sort_state(ko_l: KeyOps, ko_r: KeyOps, payloads: tuple = ()):
    """THE sort: stable lexicographic sort of the concatenated key tuples.

    Returns ``(bnd, idx_s, sorted_payloads)`` — bnd/idx_s (n_l + n_r,)
    int32.  ``idx_s[p]`` is the concat-row index occupying sorted position
    p (values < n_l are left rows); ``bnd[p]`` = 1 iff position p starts a
    new key group (p=0 -> 0).  Stability ⇒ within a group, left rows come
    first, each side in source order.

    ``payloads``: optional (n_l+n_r,) arrays carried through the sort —
    moving data as sort payload costs ~2 ns/row/operand vs ~20 ns/row for
    a later gather, so callers ride small column sets along.
    """
    cat = concat_keyops(ko_l, ko_r)
    n = cat.n
    idx = jnp.arange(n, dtype=jnp.int32)
    sorted_all = jax.lax.sort(cat.ops + (idx,) + tuple(payloads),
                              num_keys=len(cat.ops), is_stable=True)
    nk = len(cat.ops)
    idx_s = sorted_all[nk]
    bnd = neighbor_flags(sorted_all[:nk], cat.kinds)
    return bnd, idx_s, tuple(sorted_all[nk + 1:])


def join_carry(bnd, idx_s, live_cat, n_l: int, how: str) -> tuple:
    """Phase-1 geometry: returns ``(total, JoinCarry)`` with ``total`` the
    exact output row count (device scalar int32).

    Segmented counts come from prefix sums + monotone-broadcast scans ONLY
    (cummax forward, reverse cummin backward over the non-decreasing
    prefixes) — no gathers at all (~15 ns/row each, measured, vs ~1 ns/row
    for a scan) and NOT ``associative_scan``, whose XLA:TPU compile time
    explodes superlinearly with array size (~200 s at 2M rows, measured).

    ``live_cat=None`` asserts every concat row is live (host-known
    ``valid_counts == capacity`` — the common case for exact-bucket tables):
    it skips the ~15 ns/row ``live_cat[idx_s]`` gather entirely."""
    n = bnd.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    side = idx_s >= n_l
    if live_cat is None:
        lefts = (~side).astype(jnp.int32)
        rights = side.astype(jnp.int32)
    else:
        live = live_cat[idx_s]
        lefts = ((~side) & live).astype(jnp.int32)
        rights = (side & live).astype(jnp.int32)
    first = bnd.astype(bool) | (pos == 0)

    s_l = jnp.cumsum(lefts).astype(jnp.int32)    # inclusive prefix counts
    s_r = jnp.cumsum(rights).astype(jnp.int32)

    emit_right = how == "right"
    keep_unmatched = how in ("left", "right", "outer")
    need_fwd = emit_right or how == "outer"

    if need_fwd:
        # S_l exclusive at the group start, broadcast forward: s_l - lefts is
        # non-decreasing, so a cummax of its masked group-start values holds
        # each position's own-group start state
        b_l = jax.lax.cummax(jnp.where(first, s_l - lefts, jnp.int32(0)))

    if emit_right:
        # group left-count = S_l[p] - S_l[group start - 1]; for a right row
        # all group lefts precede it (stability), so s_l[p] includes them all
        cnt = (s_l - b_l).astype(jnp.int32)
        mstart = jax.lax.cummax(jnp.where(first, pos, jnp.int32(0)))
        emits = rights != 0
    else:
        # S_l/S_r at the group END, broadcast backward: the prefixes are
        # non-decreasing, so reverse-cummin of their masked group-end values
        # gives each position its own group's end state
        ebnd = jnp.concatenate([first[1:], jnp.ones(1, bool)])
        imax = jnp.int32(2**31 - 1)
        e_l = jax.lax.cummin(jnp.where(ebnd, s_l, imax), reverse=True)
        e_r = jax.lax.cummin(jnp.where(ebnd, s_r, imax), reverse=True)
        t_l = e_l - (s_l - lefts)            # lefts in [p .. end]
        cnt = e_r - (s_r - rights)           # rights in [p .. end]
        mstart = pos + t_l                   # first right position of group
        emits = lefts != 0

    eff = jnp.where(emits,
                    jnp.maximum(cnt, 1) if keep_unmatched else cnt,
                    0).astype(jnp.int32)
    csum = jnp.cumsum(eff)
    offs = (csum - eff).astype(jnp.int32)
    total = (csum[-1] if n > 0 else jnp.int32(0)).astype(jnp.int32)

    if how == "outer":
        grp_l = (s_l - b_l).astype(jnp.int32)
        un = ((rights != 0) & (grp_l == 0)).astype(jnp.int32)
        total = total + jnp.sum(un)
    else:
        un = jnp.zeros(n, jnp.int32)
    return total, JoinCarry(offs, eff, cnt, mstart, idx_s, un)


class JoinTake(NamedTuple):
    """Phase-2 expansion state, all (out_cap,) arrays over output slots.

    ``valid`` covers the MAIN emission only (slot < total excluding outer
    joins' appended unmatched-right rows, which occupy [main, total) with
    valid=False but a real ``r_take``) — outer-join callers must use the
    take arrays, not ``valid``, to mask real rows.  The carry_* fast paths
    that do rely on ``valid`` are restricted to inner/left joins, where
    valid exactly means "real output row"."""
    total: jax.Array      # scalar int32: exact output rows
    valid: jax.Array      # bool: slot holds a main-emission output row
    matched: jax.Array    # bool: slot's match-side row exists
    mpos: jax.Array       # int32: sorted position of the match-side row
    l_take: object        # left row index or -1; None if suppressed
    r_take: object        # right row index or -1; None if suppressed
    extra: tuple          # carried emit-side u32 lanes at the owning row


def join_take(carry: JoinCarry, n_l: int, how: str, out_cap: int,
              extra: tuple = (), carry_emit: bool = False,
              carry_match: bool = False, emit_idx: bool = False,
              match_idx: bool = False) -> JoinTake:
    """Phase-2 materialization over ``out_cap`` static output slots
    (``out_cap`` >= phase 1's total; slots past ``total`` are invalid).

    Output slot k is owned by the "emitting" sorted row (left rows for
    inner/left/outer, right rows for right joins) whose offs/eff interval
    contains k; ownership is reconstructed with one scatter (offs strictly
    increase over emitting rows, so plain ``set`` — no combiner needed) and
    a ``cummax`` fill.  ONE stacked (out, M) gather at the owner position
    then provides the slot's geometry AND any ``extra`` u32 lanes the
    caller rode through the phase-1 sort (the emit side's packed output
    columns — ``carry_emit``).

    Static specialization knobs (and the measured ~15 ns/slot gathers they
    remove):
      * ``carry_emit``: emit-side values arrive via ``extra`` → the owner's
        concat-row index (idx_s) drops out of the meta stack and the
        emit-side take array is None (no emit-side lane-matrix gather in
        the caller).
      * ``carry_match``: match-side values ride sorted payload lanes the
        caller gathers at ``mpos`` → the dependent ``idx_s[mpos]`` gather
        is skipped and the match-side take array is None.
      * ``how == "inner"``: every emitted slot is a real match, so
        ``matched == valid`` and the per-group match count drops out of the
        meta stack entirely.
      * ``emit_idx``/``match_idx`` (carry-LITE, f64 columns): laneable
        columns ride the sort but f64 cannot (TPU bitcast/sort-payload
        SIGSEGV), so the corresponding take array is kept alongside the
        carried lanes — the caller gathers just the f64 side columns by
        index.
    """
    offs, eff, cnt, mstart, idx_s, un = carry
    n = offs.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    total_main = (offs[-1] + eff[-1] if n > 0 else jnp.int32(0)).astype(
        jnp.int32)

    # emitting rows have strictly increasing offs -> distinct slots: set,
    # not max (measured ~8.8 vs ~12 ns/update); unscattered slots keep 0 and
    # the cummax fill assigns them their predecessor's owner
    scat = jnp.where(eff > 0, offs, jnp.int32(out_cap))
    p0 = jnp.zeros(out_cap, jnp.int32).at[scat].set(pos, mode="drop")
    p_of_k = jax.lax.cummax(p0)

    need_cnt = how != "inner"
    need_own_idx = (not carry_emit) or emit_idx
    meta_cols = [offs, mstart]
    if need_cnt:
        meta_cols.append(cnt)
    if need_own_idx:
        meta_cols.append(idx_s)
    for e in extra:
        meta_cols.append(jax.lax.bitcast_convert_type(e, jnp.int32))
    meta = jnp.stack(meta_cols, axis=1)[p_of_k]    # THE (out, M) gather
    k = jnp.arange(out_cap, dtype=jnp.int32)
    rel = k - meta[:, 0]
    valid = k < total_main
    matched = valid if how == "inner" else valid & (rel < meta[:, 2])
    mpos = jnp.clip(meta[:, 1] + rel, 0, max(n - 1, 0))
    ci = 2 + int(need_cnt)
    own_idx = meta[:, ci] if need_own_idx else None
    extra_out = tuple(
        jax.lax.bitcast_convert_type(meta[:, ci + int(need_own_idx) + j],
                                     jnp.uint32)
        for j in range(len(extra)))
    m_idx = None if (carry_match and not match_idx) else idx_s[mpos]

    l_take = r_take = None
    if how == "right":
        if need_own_idx:
            r_take = jnp.where(valid, own_idx - n_l, jnp.int32(-1))
        if m_idx is not None:
            l_take = jnp.where(matched, m_idx, jnp.int32(-1))
    else:
        if need_own_idx:
            l_take = jnp.where(valid, own_idx, jnp.int32(-1))
        if m_idx is not None:
            r_take = jnp.where(matched, m_idx - n_l, jnp.int32(-1))

    total = total_main
    if how == "outer":
        unpos = (jnp.cumsum(un) - un).astype(jnp.int32)
        slot = jnp.where(un > 0, total_main + unpos, jnp.int32(out_cap))
        r_take = r_take.at[slot].set(idx_s - n_l, mode="drop")
        total = total_main + jnp.sum(un, dtype=jnp.int32)
    return JoinTake(total, valid, matched, mpos, l_take, r_take, extra_out)
