"""Local join kernel: sort-merge on dense key ids.

TPU-native replacement for the reference's local join layer
(cpp/src/cylon/join/join.cpp:60 ``JoinTables`` dispatch, sort_join.cpp:66
``do_sorted_join``, hash_join.cpp:22-85).  The reference's default algorithm
is SORT (join_config.hpp:37); a pointer-chasing hash build/probe doesn't map
to XLA, so the sort path is *the* design here (SURVEY.md §7 hard-part 2):

    sort right ids → searchsorted(left ids) match ranges →
    prefix-sum offsets → one vectorized gather expansion.

Inputs are int32 **dense ranks** from :mod:`cylon_tpu.ops.pack` (multi-column
/ string / null-aware keys all collapse to one id column first), so a single
int comparison implements full row equality.  Output size is data-dependent;
callers run the ``*_count`` phase, pick a static capacity (pow2-bucketed),
then the ``*_indices`` phase — the two-phase static-shape pattern that
replaces the reference's dynamically-growing Arrow builders.

INNER / LEFT / RIGHT / FULL_OUTER all supported (join_config.hpp:25).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# numpy scalars, not jnp: a module-level jnp constant would eagerly
# initialize the default backend at import time (round-1 dryrun crash)
SENT_L = np.int32(1 << 30)
SENT_R = np.int32((1 << 30) + 1)


def _effective_ids(l_ids, r_ids, l_mask, r_mask):
    le = l_ids if l_mask is None else jnp.where(l_mask, l_ids, SENT_L)
    re_ = r_ids if r_mask is None else jnp.where(r_mask, r_ids, SENT_R)
    return le, re_


def _bounds(sorted_ids, query):
    lo = jnp.searchsorted(sorted_ids, query, side="left", method="sort")
    hi = jnp.searchsorted(sorted_ids, query, side="right", method="sort")
    return lo.astype(jnp.int32), hi.astype(jnp.int32)


def _sort_ids(ids):
    idx = jnp.arange(ids.shape[0], dtype=jnp.int32)
    s, perm = jax.lax.sort((ids, idx), num_keys=1, is_stable=True)
    return s, perm


def _counts(le, re_, l_mask, how_left: bool):
    rs, _ = _sort_ids(re_)
    lo, hi = _bounds(rs, le)
    counts = hi - lo
    out = jnp.maximum(counts, 1) if how_left else counts
    if l_mask is not None:
        out = jnp.where(l_mask, out, 0)
    return counts, out


def _unmatched_right(le, re_, r_mask):
    ls, _ = _sort_ids(le)
    lo, hi = _bounds(ls, re_)
    un = lo == hi
    if r_mask is not None:
        un = un & r_mask
    return un


@partial(jax.jit, static_argnames=("how",))
def join_count(l_ids, r_ids, how: str, l_mask=None, r_mask=None):
    """Exact output row count (device scalar) for the given join type."""
    if how == "right":
        return join_count(r_ids, l_ids, "left", r_mask, l_mask)
    le, re_ = _effective_ids(l_ids, r_ids, l_mask, r_mask)
    _, eff = _counts(le, re_, l_mask, how_left=how in ("left", "outer"))
    total = jnp.sum(eff)
    if how == "outer":
        total = total + jnp.sum(_unmatched_right(le, re_, r_mask))
    return total.astype(jnp.int32)


def _expand(counts, eff_counts, lo, perm_r, out_cap: int):
    n = counts.shape[0]
    csum = jnp.cumsum(eff_counts)
    offs = jnp.concatenate([jnp.zeros(1, csum.dtype), csum[:-1]])
    total = jnp.where(n > 0, csum[-1], 0)
    k = jnp.arange(out_cap, dtype=jnp.int32)
    li = (jnp.searchsorted(offs, k, side="right", method="sort") - 1).astype(jnp.int32)
    li = jnp.clip(li, 0, max(n - 1, 0))
    rel = k - offs[li].astype(jnp.int32)
    matched = rel < counts[li]
    rpos = jnp.where(matched, lo[li] + rel, 0)
    r_take = jnp.where(matched, perm_r[rpos], -1)
    valid = k < total
    l_take = jnp.where(valid, li, -1)
    r_take = jnp.where(valid, r_take, -1)
    return l_take, r_take, total.astype(jnp.int32)


@partial(jax.jit, static_argnames=("how", "out_cap"))
def join_indices(l_ids, r_ids, how: str, out_cap: int, l_mask=None, r_mask=None):
    """Materialize (l_take, r_take, total): row index pairs of the join
    result, -1 marking the null side of unmatched outer rows.  ``out_cap``
    must be >= the count from :func:`join_count`; slots past ``total`` hold
    (-1, -1)."""
    if how == "right":
        r_take, l_take, total = join_indices(
            r_ids, l_ids, "left", out_cap, r_mask, l_mask)
        return l_take, r_take, total
    le, re_ = _effective_ids(l_ids, r_ids, l_mask, r_mask)
    rs, perm_r = _sort_ids(re_)
    lo, hi = _bounds(rs, le)
    counts = hi - lo
    eff = jnp.maximum(counts, 1) if how in ("left", "outer") else counts
    if l_mask is not None:
        eff = jnp.where(l_mask, eff, 0)
    l_take, r_take, total = _expand(counts, eff, lo, perm_r, out_cap)
    if how == "outer":
        un = _unmatched_right(le, re_, r_mask)  # (m,)
        m = un.shape[0]
        ridx = jnp.arange(m, dtype=jnp.int32)
        # compact unmatched right rows preserving order: first n_un of ``src``
        order = jnp.where(un, ridx, jnp.int32(m))
        _, src = jax.lax.sort((order, ridx), num_keys=1, is_stable=True)
        n_un = jnp.sum(un).astype(jnp.int32)
        pos = total + jnp.arange(m, dtype=jnp.int32)
        pos = jnp.where(jnp.arange(m) < n_un, pos, jnp.int32(out_cap))
        l_take = l_take.at[pos].set(jnp.int32(-1), mode="drop")
        r_take = r_take.at[pos].set(src, mode="drop")
        total = total + n_un
    return l_take, r_take, total
