"""Device-side row hashing for partitioning.

TPU-native replacement for the reference's murmur3 row hash
(cpp/src/cylon/util/murmur3.cpp + arrow/arrow_partition_kernels.hpp:55
``HashPartitionKernel`` with composable ``UpdateHash``).  The reference hashes
on the host CPU per row with per-type C++ templates; here hashing is a fused
elementwise pipeline on the VPU.

The pipeline is **pure uint32**: 64-bit values are split into two u32 lanes
arithmetically (TPU x64 emulation lacks u64 bitcasts, and u32 ops are native
VPU width — 2× the lanes of emulated u64).  Equal keys always produce equal
hashes (the only correctness requirement for routing); distribution quality
comes from murmur3's fmix32 finalizer between lanes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_GOLD = 0x9E3779B9


def _mix32(z: jax.Array) -> jax.Array:
    z = (z ^ (z >> 16)) * jnp.uint32(0x85EBCA6B)
    z = (z ^ (z >> 13)) * jnp.uint32(0xC2B2AE35)
    return z ^ (z >> 16)


def _u32_lanes(x: jax.Array) -> list[jax.Array]:
    """Split any numeric column into one or two u32 lanes, equal-preserving.

    Floats are canonicalized (-0.0→+0.0, NaN→one NaN) then bitcast; float64
    is *downcast to float32* for hashing only — equal f64 values still map to
    equal lanes (routing stays correct; only bucket collision odds change).
    64-bit ints split via shift/mask arithmetic, no bitcast.
    """
    dt = x.dtype
    if dt == jnp.bool_:
        return [x.astype(jnp.uint32)]
    if jnp.issubdtype(dt, jnp.floating):
        x = jnp.where(x == 0, jnp.zeros_like(x), x)
        x = jnp.where(jnp.isnan(x), jnp.full_like(x, jnp.nan), x)
        if dt.itemsize == 8:
            x = x.astype(jnp.float32)
        elif dt.itemsize < 4:
            x = x.astype(jnp.float32)
        return [jax.lax.bitcast_convert_type(x, jnp.uint32)]
    if jnp.issubdtype(dt, jnp.integer):
        if dt.itemsize == 8:
            lo = (x & jnp.array(0xFFFFFFFF, dt)).astype(jnp.uint32)
            hi = ((x >> 32) & jnp.array(0xFFFFFFFF, dt)).astype(jnp.uint32)
            return [lo, hi]
        if jnp.issubdtype(dt, jnp.signedinteger):
            return [x.astype(jnp.int32).astype(jnp.uint32)]
        return [x.astype(jnp.uint32)]
    raise TypeError(f"unhashable dtype {dt}")


def hash_rows(datas, validities=None, seed: int = _GOLD) -> jax.Array:
    """Combined avalanche hash (u32) of each row's key tuple; nulls hash to a
    fixed lane so null==null (the reference's comparators likewise treat
    nulls as equal)."""
    h = jnp.full(datas[0].shape[0], jnp.uint32(seed))
    gold = jnp.uint32(_GOLD)
    for i, d in enumerate(datas):
        lanes = _u32_lanes(d)
        v = validities[i] if validities is not None else None
        for lane in lanes:
            if v is not None:
                lane = jnp.where(v, lane, jnp.uint32(0xDEADBEEF))
            h = _mix32(h ^ (lane + gold + (h << jnp.uint32(6))
                            + (h >> jnp.uint32(2))))
    return h


def partition_targets(h: jax.Array, world: int) -> jax.Array:
    """Row → destination rank in [0, world)."""
    if (world & (world - 1)) == 0:
        return (h & jnp.uint32(world - 1)).astype(jnp.int32)
    return (h % jnp.uint32(world)).astype(jnp.int32)


def partition_of(h: int, world: int) -> int:
    """Host-side mirror of :func:`partition_targets` for ONE u32 hash —
    the skew-plan facade (relational/skew.py) derives each heavy key's
    HOME rank from its sampled device hash with exactly the routing
    math, so the split plan's rank groups anchor where plain hashing
    would have sent the key.  Keep the two in lockstep."""
    h = int(h) & 0xFFFFFFFF
    if (world & (world - 1)) == 0:
        return h & (world - 1)
    return h % world
