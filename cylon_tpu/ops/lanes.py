"""u32 lane-matrix packing: move many columns in ONE gather/collective.

TPU cost model (measured on v5e): a random row gather of an (n, L) matrix
costs ~(1 + 0.2·(L-1))× a 1-D gather — far cheaper than L separate 1-D
gathers.  So whenever an operator must move whole rows by index (join/filter
materialization, shuffle exchange), the table's columns are first bitcast
into one (n, L) uint32 lane matrix, moved in one pass, and unpacked after.

This is the TPU analog of the reference's row-wise serializer: Arrow buffer
triplets per column (serialize/table_serialize.hpp:23-59) become u32 lanes —
  * int64/uint64/datetime64 → 2 lanes (hi, lo via shifts — no 64-bit
    bitcasts: XLA's TPU x64 rewriter does not implement them)
  * int32/uint32/float32/int16/int8/string-codes → 1 lane (bitcast/widen)
  * bool → 1 lane (0/1)
  * validity masks → bit-packed, 32 columns per lane
  * float64 → NOT laneable (its bit split would need a 64-bit bitcast);
    planned as a side column and moved as a raw f64 array — XLA's gather
    handles f64 under x64 fine, it's only bitcast that is missing.
Bitcasts are bit-exact roundtrips on the same device, so no ordering or
canonicalization concerns apply (unlike sort-operand packing in pack.py).

The static :class:`LaneSpec` travels with compiled programs (hashable), the
matrix with the data.  :func:`gather_columns` is the one-stop row-move:
one (n, L) matrix gather for every laneable column + validity, plus one
1-D gather per f64 column.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class ColLanes(NamedTuple):
    """Static description of one column's slot in the lane matrix."""
    dtype: str       # numpy dtype name of the column data
    lanes: tuple     # lane indices (1 or 2 entries; 64-bit = (hi, lo));
                     # empty tuple = non-laneable (f64 side column)
    valid_bit: int   # bit position in the validity lane block, or -1
    narrow: bool = False  # 64-bit int whose host-known bounds fit int32:
                          # packed as ONE sign-extending lane instead of two


class LaneSpec(NamedTuple):
    cols: tuple          # tuple[ColLanes]
    n_lanes: int         # total lanes incl. validity lanes
    valid_lane0: int     # first validity lane index (== n_lanes if none)


def plan_lanes(dtypes, has_valid, narrow=None) -> LaneSpec:
    """Build the static lane layout for columns of ``dtypes`` (numpy dtype
    names) where ``has_valid[i]`` marks nullable columns.  float64 columns
    get no lanes (side-channel); their validity still rides the matrix.
    ``narrow[i]`` (host-known ``Column.bounds`` fit int32) packs a 64-bit
    integer column as ONE lane — every pass that moves the matrix gets
    proportionally cheaper."""
    cols = []
    lane = 0
    vbit = 0
    for i, (dt, hv) in enumerate(zip(dtypes, has_valid)):
        ndt = np.dtype(dt)
        nrw = bool(narrow[i]) if narrow is not None else False
        nrw = nrw and ndt.itemsize == 8 and ndt.kind in ("i", "u")
        if ndt.itemsize == 8 and np.issubdtype(ndt, np.floating):
            lanes = ()
        else:
            width = 1 if (ndt.itemsize < 8 or nrw) else 2
            lanes = tuple(range(lane, lane + width))
            lane += width
        cols.append(ColLanes(dt, lanes, vbit if hv else -1, nrw))
        if hv:
            vbit += 1
    valid_lane0 = lane
    n_valid_lanes = (vbit + 31) // 32
    return LaneSpec(tuple(cols), lane + n_valid_lanes, valid_lane0)


def _to_lanes(x, narrow: bool = False):
    """Column data array -> list of u32 lane arrays (hi, lo for 64-bit
    ints; f64 never reaches here — it is planned laneless)."""
    dt = x.dtype
    if dt == jnp.bool_:
        return [x.astype(jnp.uint32)]
    if dt.itemsize == 8:
        if narrow:  # host-known bounds fit int32: one sign-carrying lane
            return [jax.lax.bitcast_convert_type(x.astype(jnp.int32),
                                                 jnp.uint32)]
        xi = x.astype(jnp.int64) if dt != jnp.uint64 else x
        hi = (xi >> 32).astype(jnp.uint32)
        lo = (xi & jnp.asarray(0xFFFFFFFF, xi.dtype)).astype(jnp.uint32)
        return [hi, lo]
    if jnp.issubdtype(dt, jnp.floating):  # f32 (f16 widened by caller)
        return [jax.lax.bitcast_convert_type(x.astype(jnp.float32),
                                             jnp.uint32)]
    if jnp.issubdtype(dt, jnp.signedinteger):
        return [jax.lax.bitcast_convert_type(x.astype(jnp.int32),
                                             jnp.uint32)]
    return [x.astype(jnp.uint32)]


def _from_lanes(lanes, dtype: str, narrow: bool = False):
    dt = np.dtype(dtype)
    jdt = jnp.dtype(dt)
    if dt == np.bool_:
        return lanes[0] != 0
    if dt.itemsize == 8:
        if narrow:
            return jax.lax.bitcast_convert_type(
                lanes[0], jnp.int32).astype(jdt)
        hi, lo = lanes
        x = (jax.lax.bitcast_convert_type(hi, jnp.int32).astype(jnp.int64)
             << 32) | lo.astype(jnp.int64)
        return x.astype(jdt)
    if np.issubdtype(dt, np.floating):
        return jax.lax.bitcast_convert_type(lanes[0], jnp.float32).astype(jdt)
    if np.issubdtype(dt, np.signedinteger):
        return jax.lax.bitcast_convert_type(lanes[0], jnp.int32).astype(jdt)
    return lanes[0].astype(jdt)


def pack_lanes(spec: LaneSpec, datas, valids):
    """(n, spec.n_lanes) uint32 lane matrix from parallel column arrays
    (laneless f64 columns contribute only their validity bit).
    ``valids[i]`` may be None for columns planned with valid_bit == -1."""
    n = datas[0].shape[0]
    lanes = [None] * spec.n_lanes
    n_valid_lanes = spec.n_lanes - spec.valid_lane0
    vlanes = [jnp.zeros(n, jnp.uint32) for _ in range(n_valid_lanes)]
    for col, d, v in zip(spec.cols, datas, valids):
        if col.lanes:
            for li, arr in zip(col.lanes, _to_lanes(d, col.narrow)):
                lanes[li] = arr
        if col.valid_bit >= 0:
            vb = jnp.ones(n, jnp.uint32) if v is None else v.astype(jnp.uint32)
            slot = col.valid_bit // 32
            vlanes[slot] = vlanes[slot] | (vb << jnp.uint32(col.valid_bit % 32))
    for i, vl in enumerate(vlanes):
        lanes[spec.valid_lane0 + i] = vl
    return jnp.stack(lanes, axis=1)


def unpack_lanes(spec: LaneSpec, mat):
    """Inverse of :func:`pack_lanes`: (datas, valids) tuples — laneless
    (f64) columns yield None data (moved separately); valids entries are
    None for columns planned without validity."""
    datas, valids = [], []
    for col in spec.cols:
        if col.lanes:
            datas.append(_from_lanes([mat[:, li] for li in col.lanes],
                                     col.dtype, col.narrow))
        else:
            datas.append(None)
        if col.valid_bit >= 0:
            vl = mat[:, spec.valid_lane0 + col.valid_bit // 32]
            valids.append(((vl >> jnp.uint32(col.valid_bit % 32)) & 1) != 0)
        else:
            valids.append(None)
    return tuple(datas), tuple(valids)


def slice_lanes(spec: LaneSpec, mat, start, window: int):
    """Contiguous window ``[start, start+window)`` of the lane matrix as a
    dynamic slice (no gather).  The caller guarantees the matrix is padded
    so the window never clamps (see exec/pipeline piece sources)."""
    return jax.lax.dynamic_slice(mat, (start, jnp.int32(0)),
                                 (window, spec.n_lanes))


def unpack_column(spec: LaneSpec, mat, i: int):
    """Lazily unpack ONE column ``i`` from the lane matrix: ``(data,
    valid)``, either None when the column is laneless (f64 side channel) /
    planned without validity.  The point versus :func:`unpack_lanes`: a
    consumer that reads only the key columns of a packed piece touches
    only their lanes — every other column's unpack never enters the
    program (XLA sees no use of those lanes)."""
    col = spec.cols[i]
    d = _from_lanes([mat[:, li] for li in col.lanes], col.dtype,
                    col.narrow) if col.lanes else None
    v = None
    if col.valid_bit >= 0:
        vl = mat[:, spec.valid_lane0 + col.valid_bit // 32]
        v = ((vl >> jnp.uint32(col.valid_bit % 32)) & 1) != 0
    return d, v


def gather_laneless(spec: LaneSpec, datas, take) -> dict:
    """{col_index: gathered data} for ONLY the laneless (f64) columns of
    ``spec`` — one batched (n, K) f64 matrix gather.  Used by the join's
    carry-LITE path: laneable columns ride the sort, f64 columns gather
    by take index."""
    idxs = [i for i, c in enumerate(spec.cols) if not c.lanes]
    if not idxs:
        return {}
    n = datas[idxs[0]].shape[0]
    sel = jnp.clip(take, 0, max(n - 1, 0))
    if len(idxs) == 1:
        return {idxs[0]: datas[idxs[0]][sel]}
    fmat = jnp.stack([datas[i] for i in idxs], axis=1)[sel]
    return {i: fmat[:, j] for j, i in enumerate(idxs)}


def gather_columns(spec: LaneSpec, datas, valids, take):
    """Move whole rows by index: ONE (n, L) matrix gather for every laneable
    column + validity bits, plus ONE (n, K) f64 matrix gather batching all
    laneless (f64) columns (measured v5e: ~6 ns/row/col at K=5 vs ~16 for
    separate 1-D gathers).  ``take`` entries < 0 select row 0 (callers mask
    via validity).  Returns (datas, valids) aligned with the input order."""
    if not spec.cols:
        return (), ()
    n = datas[0].shape[0]
    sel = jnp.clip(take, 0, max(n - 1, 0))
    if spec.n_lanes:
        mat = pack_lanes(spec, datas, valids)
        out_d, out_v = unpack_lanes(spec, mat[sel])
        out_d, out_v = list(out_d), list(out_v)
    else:
        out_d = [None] * len(spec.cols)
        out_v = [None] * len(spec.cols)
    for i, d in gather_laneless(spec, datas, take).items():
        out_d[i] = d
    return tuple(out_d), tuple(out_v)
