"""Multi-key sort + gather kernels.

TPU-native replacement for the reference's type-dispatched sort kernels
(cpp/src/cylon/arrow/arrow_kernels.hpp:53 ``IndexSortKernel``, :121
``SortIndicesMultiColumns``, util/sort.hpp introsort).  The reference emits a
per-type C++ comparator sort on the host; here ``jax.lax.sort`` is already a
multi-operand lexicographic bitonic sort on the VPU — multi-column ascending/
descending/nulls-first/last all become key-operand transforms built by
:func:`cylon_tpu.ops.pack.key_operands`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..utils.cache import jit


def sort_permutation(keyops) -> jax.Array:
    """Stable argsort of rows under a :class:`~cylon_tpu.ops.pack.KeyOps`
    lexicographic operand list."""
    n = keyops.n
    idx = jnp.arange(n, dtype=jnp.int32)
    out = jax.lax.sort(keyops.ops + (idx,), num_keys=len(keyops.ops),
                       is_stable=True)
    return out[-1]


def take_data(data: jax.Array, idx: jax.Array) -> jax.Array:
    """Gather rows; idx must be in-bounds (a permutation/selection)."""
    return data[idx]


def take_with_nulls(data: jax.Array, validity, idx: jax.Array):
    """Gather rows where idx == -1 yields a null (outer-join null side).
    Returns (data, validity) with validity None when provably all-valid."""
    n = data.shape[0]
    safe = jnp.clip(idx, 0, max(n - 1, 0))
    g = data[safe]
    v = idx >= 0
    if validity is not None:
        v = v & validity[safe]
    return g, v


@partial(jit, static_argnames=("out_cap",))
def compact_by_flag(flag: jax.Array, out_cap: int):
    """Indices of rows with flag set, in original row order, padded to
    ``out_cap`` with -1; plus the true count.  The static-shape analog of the
    reference's growing Arrow index builders.  Sort-free: output positions
    are the exclusive prefix sum of the flags, materialized by one scatter."""
    n = flag.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    fi = flag.astype(jnp.int32)
    pos = (jnp.cumsum(fi) - fi).astype(jnp.int32)
    total = jnp.sum(fi, dtype=jnp.int32)
    scat = jnp.where(flag, pos, jnp.int32(out_cap))
    out = jnp.full(out_cap, -1, jnp.int32).at[scat].set(idx, mode="drop")
    return out, total
