"""Pallas splitter-probe kernel — the pipelined join's phase-1 probe.

The range-partitioned pipeline (exec/pipeline.py) assigns every probe
row its key-range id by counting how many of the build side's key-group
splitters compare ``<=`` the row's key tuple (``_probe_targets_fn`` —
SURVEY §7 hard-part 2 names "pallas hash-probe" as exactly this later
optimization).  The XLA path materializes the full ``(rows, splitters)``
lexicographic comparison matrix (:func:`cylon_tpu.ops.pack.
rows_ge_splitters`): at 125M rows x R splitters x K operands that is an
O(n*R*K) HBM-resident boolean intermediate, and ``pipe.targets`` was
~1.2 s of the 12.75 s BENCH_r05 iteration.

This kernel streams the probe rows through VMEM in (8, 128) tiles with
the splitter operands resident in SMEM (scalar prefetch — splitters are
R-1 <= a few dozen scalars per operand), accumulating the ge-count
in-register: no comparison matrix ever touches HBM, and the row operands
are read exactly once.  Same structure as :mod:`cylon_tpu.ops.
pallas_gather` (the proven MXU-kernel route in this repo): interpreter
fallback on CPU rigs, ``ShapeDtypeStruct(vma=)`` shim for jax >= 0.5,
registered with the trace-safety jaxpr gate through its consumer
(``exec/pipeline._probe_targets_fn[pallas]``).

Bit-equality contract: the kernel implements the IDENTICAL lexicographic
``>=`` algebra as ``rows_ge_splitters`` over int-kind operands (uint32
operands are rebased to int32 through the order-preserving
``x ^ 0x8000_0000`` bijection, which preserves both ``>`` and ``==`` —
so the counts are equal bit-for-bit, asserted for all four join hows in
tests/test_pipeline.py).  Float64 key operands (kind 'f', NaN-aware
compares) are NOT eligible — callers gate on :func:`supported` and keep
the XLA path.

One Mosaic note beyond the pallas_gather landmine list: pallas_call has
no shard_map replication rule on jax < 0.5, so the consumer's shard_map
must pass ``check_rep=False`` when this kernel is in the program (the
program is still pure-local — the jaxpr gate asserts no collective).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: probe rows per grid step — one (8, 128) int32 tile
TILE = 1024

#: unroll ceiling: splitter loops are statically unrolled S x K compares
#: per tile; past this the XLA matrix path is the better program anyway
MAX_SPLITTERS = 128


def supported(cap: int, n_split: int, kinds: tuple) -> bool:
    """Static eligibility for a per-shard probe of ``cap`` rows against
    ``n_split`` splitters whose operand kinds are ``kinds`` (from
    :class:`cylon_tpu.ops.pack.KeyOps`): int-kind operands only (float
    'f' operands need NaN-aware compares), tile-aligned capacity, and a
    bounded unroll."""
    return (cap % TILE == 0 and cap >= TILE
            and 1 <= int(n_split) <= MAX_SPLITTERS
            and all(k == "i" for k in kinds))


def _kernel(*refs, n_split: int, n_ops: int):
    # refs: n_ops splitter SMEM refs, n_ops row-tile refs, out ref
    sops = refs[:n_ops]
    rows = [refs[n_ops + i][0] for i in range(n_ops)]     # (8, TILE//8)
    out_ref = refs[2 * n_ops]
    cnt = jnp.zeros(rows[0].shape, jnp.int32)
    for j in range(n_split):
        gt = jnp.zeros(rows[0].shape, jnp.bool_)
        eq = jnp.ones(rows[0].shape, jnp.bool_)
        for i in range(n_ops):
            s = sops[i][j]                                # SMEM scalar
            gt = gt | (eq & (rows[i] > s))
            eq = eq & (rows[i] == s)
        cnt = cnt + (gt | eq).astype(jnp.int32)
    out_ref[0] = cnt


def _as_i32(x):
    """Order-preserving int32 rebase of an int-kind operand: uint32 maps
    through ``x ^ 0x8000_0000`` (a monotone bijection onto int32 order —
    ``>`` and ``==`` outcomes are unchanged, so ge-counts stay bit-equal
    to the native unsigned compare); int32 passes through."""
    if x.dtype == jnp.uint32:
        return jax.lax.bitcast_convert_type(
            x ^ jnp.uint32(0x80000000), jnp.int32)
    return x.astype(jnp.int32)


def count_ge_splitters(ops: tuple, sops: tuple,
                       interpret: bool | None = None):
    """(cap,) int32: per row, how many splitter tuples compare ``<=`` the
    row's operand tuple under the lexicographic total order — exactly
    ``jnp.sum(rows_ge_splitters(ko, sops), axis=1, dtype=int32)``.

    ``ops``: K parallel (cap,) int-kind key operands of one shard;
    ``sops``: K parallel (S,) splitter operands.  Caller must ensure
    :func:`supported`.  Runs in interpreter mode off-TPU (CPU test rigs
    exercise the identical kernel logic)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n_ops = len(ops)
    n_split = int(sops[0].shape[0])
    cap = ops[0].shape[0]
    G = cap // TILE
    blocks = tuple(_as_i32(o).reshape(G, 8, TILE // 8) for o in ops)
    scalars = tuple(_as_i32(s) for s in sops)
    # index-map literals wrapped in jnp.int32: i64 block indices fail
    # func.func legalization under x64 (see ops/pallas_gather.py)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_ops,
        grid=(G,),
        in_specs=[pl.BlockSpec((1, 8, TILE // 8),
                               lambda j, *_: (j, jnp.int32(0), jnp.int32(0)))
                  for _ in range(n_ops)],
        out_specs=pl.BlockSpec((1, 8, TILE // 8),
                               lambda j, *_: (j, jnp.int32(0),
                                              jnp.int32(0))),
    )
    # under shard_map (check_vma, jax >= 0.5) the output must declare the
    # mesh axes it varies over — the union of the inputs'.  jax < 0.5 has
    # no vma concept on ShapeDtypeStruct (its check_rep has no pallas
    # rule at all — consumers pass check_rep=False).
    try:
        vma = frozenset()
        for a in (*scalars, *blocks):
            vma = vma | getattr(a.aval, "vma", frozenset())
        out_shape = jax.ShapeDtypeStruct((G, 8, TILE // 8), jnp.int32,
                                         vma=vma)
    except TypeError:
        out_shape = jax.ShapeDtypeStruct((G, 8, TILE // 8), jnp.int32)
    out = pl.pallas_call(
        partial(_kernel, n_split=n_split, n_ops=n_ops),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(*scalars, *blocks)
    return out.reshape(cap)
