"""Misra-Gries top-K frequency sketch — the heavy-hitter profiler's core.

The plan profiler (obs/plan.py) needs "which keys are hot and how hot"
from a bounded sample of join/groupby/sort keys without materializing a
full frequency table.  Misra-Gries is the classic deterministic answer:
``k`` tracked counters over a stream of ``n`` (weighted) updates
guarantee, for every tracked value,

    true_count - n/(k+1)  <=  estimate  <=  true_count

and every value whose true count exceeds ``n/(k+1)`` IS tracked — no
genuinely heavy key can be missed (asserted against exact counts in
tests/test_explain.py).  The flow-join-style adaptive skew handling in
the literature (PAPERS.md) starts from exactly this estimate.

Host-side and numpy-only (updates pre-aggregate through ``np.unique``,
so a 4096-row sample is one vectorized pass plus O(distinct) dict work);
nothing here imports jax and nothing here runs unless the profiler is
armed — the zero-overhead-unarmed contract lives in the callers.
"""

from __future__ import annotations

import numpy as np

__all__ = ["MisraGries"]


class MisraGries:
    """Weighted Misra-Gries sketch with ``k`` counters.

    ``update(values[, weights])`` absorbs a batch; ``items()`` returns
    ``[(value, est_count)]`` sorted heaviest-first; ``error_bound``
    is the worst-case undercount of any estimate (total decremented
    weight — at most ``n / (k + 1)``)."""

    __slots__ = ("k", "n", "counters", "_dec")

    def __init__(self, k: int = 16):
        if k < 1:
            from ..status import InvalidError
            raise InvalidError(f"MisraGries needs k >= 1, got {k}")
        self.k = int(k)
        self.n = 0.0          # total absorbed weight
        self.counters: dict = {}
        self._dec = 0.0       # total weight removed by decrements

    def update(self, values, weights=None) -> None:
        """Absorb a batch of values (numpy array of a hashable dtype),
        each optionally carrying a weight (default 1.0 — the profiler
        passes per-shard sample weights so unequal shards pool fairly)."""
        values = np.asarray(values)
        if values.size == 0:
            return
        if weights is None:
            uniq, cnt = np.unique(values, return_counts=True)
            pairs = zip(uniq.tolist(), cnt.tolist())
        else:
            weights = np.asarray(weights, np.float64)
            uniq, inv = np.unique(values, return_inverse=True)
            wsum = np.zeros(len(uniq), np.float64)
            np.add.at(wsum, inv, weights)
            pairs = zip(uniq.tolist(), wsum.tolist())
        for v, c in pairs:
            self._add(v, float(c))

    def _add(self, v, c: float) -> None:
        self.n += c
        cur = self.counters.get(v)
        if cur is not None:
            self.counters[v] = cur + c
            return
        if len(self.counters) < self.k:
            self.counters[v] = c
            return
        # weighted decrement: drop min(smallest counter, c) from every
        # counter AND from c; zeroed counters vacate slots the remainder
        # of c may claim — the per-item MG semantics, batched.  Each
        # round removes d from k counters plus d of the incoming weight,
        # so the summed d (tracked in _dec) stays <= n/(k+1).
        while c > 0:
            d = min(min(self.counters.values()), c)
            self._dec += d
            for key in list(self.counters):
                nv = self.counters[key] - d
                if nv <= 0:
                    del self.counters[key]
                else:
                    self.counters[key] = nv
            c -= d
            if c > 0 and len(self.counters) < self.k:
                self.counters[v] = c
                return

    @property
    def error_bound(self) -> float:
        """Worst-case undercount of any estimate: the total decremented
        weight (itself bounded by n / (k + 1))."""
        return min(self._dec, self.n / (self.k + 1))

    def items(self) -> list[tuple]:
        """``[(value, est_count)]``, heaviest first."""
        return sorted(self.counters.items(), key=lambda kv: -kv[1])

    def shares(self) -> list[tuple]:
        """``[(value, est_share, err_share)]`` heaviest first —
        ``est_share`` is the estimated fraction of the absorbed weight,
        ``err_share`` the worst-case undercount as a fraction."""
        if self.n <= 0:
            return []
        err = self.error_bound / self.n
        return [(v, c / self.n, err) for v, c in self.items()]
