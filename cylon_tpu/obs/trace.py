"""Trace timeline / flight recorder — a bounded ring of span + instant
events, exportable as Chrome-trace (Perfetto) JSON.

What lands in the ring once armed (``CYLON_TPU_TRACE=path`` or
:func:`arm`):

* every ``utils/timing`` region as a complete span ("X") — the recorder
  installs itself as timing's trace sink, so the pipelined join's phase
  regions, the checkpoint/spill regions and the stream regions all
  appear without their modules knowing about this file;
* every ``timing.bump``/``add_bytes`` as an instant ("i") — recovery
  events, consensus outcomes, window closes;
* per-piece lifecycle from exec/pipeline: a dispatch span per piece and
  an ASYNC span ("b"/"e", one per piece index) covering dispatch →
  consume-settle, which is how piece r+1's dispatch visibly overlaps
  piece r's consume on the Perfetto timeline;
* serving baton handoffs from exec/scheduler (grant instants, park
  spans), tagged with the session so per-tenant filtering works.

Every event records the active :func:`~cylon_tpu.utils.timing.
attribution_scope` tag, so a multi-tenant trace separates per session.

**Postmortem breadcrumb.**  On a preemption-grace drain, a final-rung
``ResumableAbort`` flush (exec/checkpoint.flush_for_abort) or an
injected hard kill (exec/recovery.hard_kill), the last-N events dump to
``TRACE_POSTMORTEM.json`` alongside the checkpoint manifests —
superseding the single ``last_region()`` string as the crash
breadcrumb.

**Overhead contract.**  Unarmed: timing pays one extra list load per
region; nothing else runs, nothing allocates, no file is touched
(asserted in tests/test_obs.py).  Armed: events are tuples in a
preallocated ring (capacity ``CYLON_TPU_TRACE_EVENTS``, default 65536);
export happens once, at :func:`export`/process exit.

A hung or failing trace write surfaces TYPED through the fault
injector's ``obs.export`` site (exec/recovery) — never a silent loss.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time

__all__ = ["arm", "disarm", "armed", "recorder", "instant", "complete",
           "async_begin", "async_end", "export", "postmortem", "autoarm"]

#: the active recorder — one module-global load on every armed() check
_REC: list = [None]


def armed() -> bool:
    return _REC[0] is not None


def recorder() -> "TraceRecorder | None":
    return _REC[0]


class TraceRecorder:
    """Bounded ring buffer of trace events.

    Events are tuples ``(ts_us, dur_us, ph, name, tid, session, args)``
    — ``dur_us`` is None for instants, ``ph`` a Chrome-trace phase
    ("X" complete, "i" instant, "b"/"e" async begin/end), ``args`` a
    small dict or None.  Timestamps are microseconds relative to the
    recorder's arming (perf_counter based — monotonic per process)."""

    __slots__ = ("capacity", "path", "t0", "_buf", "_n", "_lock",
                 "_tids", "_exported")

    def __init__(self, capacity: int = 65536, path: str | None = None):
        self.capacity = max(int(capacity), 8)
        self.path = path
        self.t0 = time.perf_counter()
        self._buf: list = [None] * self.capacity
        self._n = 0
        self._lock = threading.Lock()
        self._tids: dict[int, tuple[int, str]] = {}
        self._exported = False

    # -- recording ---------------------------------------------------------
    def _now_us(self) -> int:
        return int((time.perf_counter() - self.t0) * 1e6)

    def _tid(self) -> int:
        ident = threading.get_ident()
        ent = self._tids.get(ident)
        if ent is None:
            with self._lock:
                ent = self._tids.setdefault(
                    ident, (len(self._tids),
                            threading.current_thread().name))
        return ent[0]

    def _session(self):
        from ..utils import timing
        sc = timing._scope()
        return sc.tag if sc is not None and sc.tag else None

    def _push(self, ev: tuple) -> None:
        with self._lock:
            self._buf[self._n % self.capacity] = ev
            self._n += 1

    def span(self, name: str, t0_s: float, dur_s: float,
             args: dict | None = None) -> None:
        """One complete span — timing's region sink calls this with the
        region's own perf_counter start/duration."""
        ts = int((t0_s - self.t0) * 1e6)
        self._push((ts, max(int(dur_s * 1e6), 1), "X", name, self._tid(),
                    self._session(), args))

    def instant(self, name: str, args: dict | None = None) -> None:
        self._push((self._now_us(), None, "i", name, self._tid(),
                    self._session(), args))

    def async_begin(self, name: str, aid: int,
                    args: dict | None = None) -> None:
        self._push((self._now_us(), None, "b", name, self._tid(),
                    self._session(), dict(args or (), id=int(aid))))

    def async_end(self, name: str, aid: int) -> None:
        self._push((self._now_us(), None, "e", name, self._tid(),
                    self._session(), {"id": int(aid)}))

    # -- reading -----------------------------------------------------------
    def events(self, last: int | None = None) -> list[tuple]:
        """Recorded events oldest-first (ring order preserved across
        wrap); ``last`` trims to the newest N."""
        with self._lock:
            if self._n <= self.capacity:
                out = [e for e in self._buf[:self._n]]
            else:
                cut = self._n % self.capacity
                out = self._buf[cut:] + self._buf[:cut]
        return out if last is None else out[-int(last):]

    @property
    def dropped(self) -> int:
        """Events that fell off the ring (flight-recorder semantics)."""
        return max(self._n - self.capacity, 0)

    # -- export ------------------------------------------------------------
    def _pid(self) -> int:
        try:
            import jax
            return int(jax.process_index())
        except Exception:  # noqa: BLE001 — no backend: single process
            return 0

    def chrome_trace(self) -> dict:
        """The Chrome-trace/Perfetto JSON object for the current ring."""
        pid = self._pid()
        events = []
        for ident, (tid, tname) in sorted(self._tids.items(),
                                          key=lambda kv: kv[1][0]):
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": tname}})
        for ts, dur, ph, name, tid, sess, args in self.events():
            ev: dict = {"name": name, "ph": ph, "ts": ts,
                        "pid": pid, "tid": tid}
            if ph == "X":
                ev["dur"] = dur
            elif ph == "i":
                ev["s"] = "t"
            elif ph in ("b", "e"):
                ev["cat"] = "piece"
                ev["id"] = (args or {}).get("id", 0)
            a = dict(args) if args else {}
            if sess is not None:
                a["session"] = sess
            if a:
                ev["args"] = a
            events.append(ev)
        # stable, ts-sorted stream (metadata first at ts implicit 0)
        events.sort(key=lambda e: e.get("ts", -1))
        return {"traceEvents": events,
                "displayTimeUnit": "ms",
                "otherData": {"dropped_events": self.dropped,
                              "recorded_events": self._n}}


def arm(path: str | None = None, capacity: int | None = None
        ) -> TraceRecorder:
    """Arm the flight recorder (idempotent — re-arming with the same
    path returns the live recorder) and install it as utils/timing's
    trace sink so regions/bumps start landing."""
    rec = _REC[0]
    if rec is not None:
        if path is not None:
            rec.path = path
        return rec
    if capacity is None:
        capacity = int(os.environ.get("CYLON_TPU_TRACE_EVENTS", "65536"))
    rec = TraceRecorder(capacity=capacity, path=path)
    _REC[0] = rec
    from ..utils import timing
    timing._TRACE[0] = rec
    return rec


def disarm() -> None:
    _REC[0] = None
    from ..utils import timing
    timing._TRACE[0] = None


def autoarm() -> None:
    """Arm from ``CYLON_TPU_TRACE=path`` (called at package import):
    registers an atexit export so bench/CI subprocess runs emit their
    timeline without any explicit call.  No env var: nothing happens."""
    path = os.environ.get("CYLON_TPU_TRACE")
    if not path or armed():
        return
    arm(path=path)
    atexit.register(_atexit_export)


def _atexit_export() -> None:
    rec = _REC[0]
    if rec is not None and rec.path and not rec._exported:
        try:
            export()
        except Exception:  # noqa: BLE001 — exit path: never raise
            pass


# -- module-level conveniences (no-ops unarmed: one list load) -------------

def instant(name: str, **args) -> None:
    rec = _REC[0]
    if rec is not None:
        rec.instant(name, args or None)


def complete(name: str, t0_s: float, **args) -> None:
    """Record a span begun at perf_counter() time ``t0_s``, ending now."""
    rec = _REC[0]
    if rec is not None:
        rec.span(name, t0_s, time.perf_counter() - t0_s, args or None)


def async_begin(name: str, aid: int, **args) -> None:
    rec = _REC[0]
    if rec is not None:
        rec.async_begin(name, aid, args or None)


def async_end(name: str, aid: int) -> None:
    rec = _REC[0]
    if rec is not None:
        rec.async_end(name, aid)


# -- export + postmortem ----------------------------------------------------

def export(path: str | None = None) -> str | None:
    """Write the Chrome-trace JSON to ``path`` (default: the armed
    path).  Returns the path written, or None when unarmed/pathless.
    The write is an injection site (``obs.export``): a simulated hung
    or corrupt write surfaces TYPED (exec/recovery), and a real OSError
    is wrapped into :class:`~cylon_tpu.status.ExecutionError` — a trace
    the operator asked for must never vanish silently."""
    rec = _REC[0]
    if rec is None:
        return None
    path = path or rec.path
    if not path:
        return None
    from ..exec import recovery
    recovery.maybe_inject("obs.export")

    def _write() -> None:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(rec.chrome_trace(), f)
        os.replace(tmp, path)

    try:
        # ride the recovery tier's bounded transient-OSError backoff
        # (exec/recovery.retry_io) before the typed wrap below: a
        # sidecar racing the rename costs a retry, not the trace
        recovery.retry_io(_write, "obs.export")
    except OSError as e:
        from ..status import ExecutionError
        raise ExecutionError(
            f"trace export to {path!r} failed: {e}") from e
    rec._exported = True
    return path


#: newest events carried in a postmortem dump
POSTMORTEM_EVENTS = 256


def postmortem(reason: str, dir_path: str | None = None,
               n: int = POSTMORTEM_EVENTS) -> str | None:
    """Dump the last-``n`` events (+ the last-region breadcrumb and the
    serving session, when tagged) to ``TRACE_POSTMORTEM.json`` in
    ``dir_path`` — default: the checkpoint root when armed, else the
    trace path's directory.  Best-effort by design (it runs on abort
    paths); returns the path written or None.  Unarmed: nothing."""
    rec = _REC[0]
    if rec is None:
        return None
    if dir_path is None:
        from ..exec import checkpoint
        dir_path = checkpoint.ckpt_dir()
        if dir_path is None and rec.path:
            dir_path = os.path.dirname(os.path.abspath(rec.path))
    if not dir_path:
        return None
    from ..utils import timing
    payload = {
        "reason": reason,
        "pid": os.getpid(),
        "last_region": timing.last_region(),
        "session": rec._session(),
        "dropped_events": rec.dropped,
        "events": [
            {"ts_us": ts, "dur_us": dur, "ph": ph, "name": name,
             "tid": tid, "session": sess, "args": args}
            for ts, dur, ph, name, tid, sess, args in rec.events(last=n)],
    }
    # the checkpoint root is SHARED storage in multihost deploys
    # (deploy/gke): non-zero ranks suffix the filename so concurrent
    # dumps never clobber rank 0's breadcrumb
    r = rec._pid()
    fname = ("TRACE_POSTMORTEM.json" if r == 0
             else f"TRACE_POSTMORTEM.rank{r}.json")
    try:
        os.makedirs(dir_path, exist_ok=True)
        path = os.path.join(dir_path, fname)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        return path
    except OSError:
        return None
