"""Per-rank phase aggregation — the min/median/max skew report.

ROADMAP item 2 (skew-proof joins) needs per-rank imbalance VISIBILITY
before any heavy-hitter mechanism can be judged: a mesh bounded by its
hottest chip shows up here as one rank's ``pipe.piece_join`` seconds
towering over the median.  This module gathers every rank's phase table
(utils/timing.snapshot) at END OF RUN and reduces it to, per phase::

    {"min_s": ..., "median_s": ..., "max_s": ..., "skew": max/median}

**Arming contract** (same as the checkpoint tier): unarmed —
``CYLON_TPU_RANK_REPORT`` unset and no :func:`arm` call — the report
never runs: zero extra collectives, zero host syncs, zero allocations
on the happy path (bench.py consults :func:`armed` before calling).
Armed, the gather is ONE ``process_allgather`` of a packed float64
vector over an agreed phase-name set (name agreement verified by crc —
a rank whose phase table diverged structurally surfaces as a typed
:class:`~cylon_tpu.status.RankDesyncError`, never a silently misaligned
report).  Single-process sessions (including multi-chip
single-controller meshes, where every device is driven by one host
loop and there is no per-rank host table to diverge) reduce over one
rank without touching the network.
"""

from __future__ import annotations

import os
import zlib

__all__ = ["arm", "armed", "report"]

_ARMED: list = [False]


def arm(on: bool = True) -> None:
    _ARMED[0] = bool(on)


def armed() -> bool:
    return _ARMED[0] or os.environ.get("CYLON_TPU_RANK_REPORT") == "1"


def _local_phases() -> dict[str, float]:
    from ..utils import timing
    return {k: float(v["s"]) for k, v in timing.snapshot().items()}


def report() -> dict:
    """Build the skew report NOW (the caller decides end-of-run).  The
    gather rides the PROCESS group (``multihost_utils`` over every
    rank of the jax.distributed world — per-rank phase tables are
    per-process host state, so there is no narrower mesh to scope to);
    the caller is responsible for honoring :func:`armed` so unarmed
    runs stay collective-free."""
    import numpy as np

    local = _local_phases()
    names = sorted(local)
    vec = np.asarray([local[n] for n in names], np.float64)

    import jax
    nproc = jax.process_count()
    if nproc > 1:
        from jax.experimental import multihost_utils
        from ..status import RankDesyncError
        crc = np.float64(zlib.crc32("|".join(names).encode()))
        wire = np.concatenate([[crc], vec])
        gathered = np.asarray(
            multihost_utils.process_allgather(wire)).reshape(nproc, -1)
        if len({float(r[0]) for r in gathered}) != 1:
            raise RankDesyncError(
                "per-rank phase report: phase-name sets differ across "
                "ranks — the ranks timed different programs",
                site="obs.rank_report")
        table = gathered[:, 1:]
    else:
        table = vec.reshape(1, -1)

    phases = {}
    for i, n in enumerate(names):
        col = table[:, i]
        med = float(np.median(col))
        phases[n] = {
            "min_s": round(float(col.min()), 4),
            "median_s": round(med, 4),
            "max_s": round(float(col.max()), 4),
            "skew": round(float(col.max()) / med, 3) if med > 0 else None,
        }
    return {"ranks": int(table.shape[0]), "phases": phases}
