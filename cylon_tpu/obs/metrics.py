"""Typed metrics registry — ONE facade over every counter in the engine.

Before this module, telemetry was scattered: four module-private
``_STATS`` dicts (exec/memory, exec/checkpoint, exec/scheduler,
exec/recovery), a phase table in utils/timing, and four bench scripts
each hand-rolling the collection.  The registry unifies them behind
typed :class:`Counter`/:class:`Gauge`/:class:`Histogram` objects with

* a **Prometheus text exposition** writer (:func:`prometheus_text`) for
  the GKE deploy's scrape endpoint,
* periodic **JSON snapshots** (``CYLON_TPU_METRICS_JSON=path`` +
  ``CYLON_TPU_METRICS_INTERVAL_S``, polled from the serving scheduler's
  baton loop — :func:`maybe_write_snapshot`),
* the shared bench-detail collector (:func:`bench_detail`) the bench
  scripts previously each hand-rolled, and
* **migration shims**: :func:`group` returns a dict-like view whose
  items are registry counters, so the exec modules' ``_STATS[k] += 1``
  call sites (and their public ``stats()`` functions) keep working
  verbatim while the values live here; :func:`namespace` is the
  dynamic-key analog for utils/timing's byte/event attribution.

Overhead contract: a counter bump is one dict-free attribute add; the
snapshot poll is one module-global load when unarmed (the same contract
as the checkpoint tier); nothing here imports jax.  Module-level
mutable counter dicts anywhere else in the package are a lint finding
(TS112, docs/trace_safety.md).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections.abc import MutableMapping

__all__ = [
    "Counter", "Gauge", "Histogram", "counter", "gauge", "histogram",
    "group", "namespace", "register_collector", "snapshot",
    "prometheus_text", "write_prometheus", "maybe_write_snapshot",
    "write_snapshot", "bench_detail", "reset",
]


class Counter:
    """Monotonic event count (resettable for bench iterations)."""

    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):  # noqa: A002
        self.name = name
        self.help = help
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def set(self, v) -> None:
        """Back-compat for the ``_STATS[k] = 0`` reset idiom (the
        migration shim's __setitem__); new code should use inc/reset."""
        self.value = v

    def reset(self) -> None:
        self.value = 0


class Gauge:
    """Point-in-time value; ``fn`` makes it computed-on-read (e.g. the
    HBM ledger balance), so exposition always reads fresh."""

    __slots__ = ("name", "help", "fn", "_value")

    def __init__(self, name: str, help: str = "", fn=None):  # noqa: A002
        self.name = name
        self.help = help
        self.fn = fn
        self._value = 0

    def set(self, v) -> None:
        self._value = v

    @property
    def value(self):
        if self.fn is not None:
            try:
                return self.fn()
            except Exception:  # noqa: BLE001 — exposition must not raise
                return self._value
        return self._value

    def reset(self) -> None:
        self._value = 0


#: default histogram buckets: latency seconds, ~1ms → ~17min exponential
DEFAULT_BUCKETS = tuple(0.001 * (2 ** i) for i in range(21))

#: raw samples retained per histogram for exact quantiles; past the cap
#: percentile() falls back to bucket interpolation (documented in
#: docs/observability.md — serving benches stay far below it)
SAMPLE_CAP = 65536


class Histogram:
    """Streaming latency histogram with EXACT quantiles at bench scale.

    Bucket counts serve the Prometheus exposition; the raw samples (kept
    up to :data:`SAMPLE_CAP`) serve :meth:`percentile`, which is
    bit-consistent with ``np.percentile`` over the same observations —
    the serving bench's acceptance criterion (its previous sorted-list
    quantiles are exactly this computation).  Past the cap, quantiles
    degrade to linear interpolation inside the containing bucket (and
    :attr:`truncated` reads True so a report can say so)."""

    __slots__ = ("name", "help", "buckets", "bucket_counts", "count",
                 "sum", "_samples", "truncated")

    def __init__(self, name: str, help: str = "",  # noqa: A002
                 buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        self.bucket_counts = [0] * (len(self.buckets) + 1)
        self.count = 0
        self.sum = 0.0
        self._samples: list[float] = []
        self.truncated = False

    def observe(self, x) -> None:
        x = float(x)
        self.count += 1
        self.sum += x
        import bisect
        self.bucket_counts[bisect.bisect_left(self.buckets, x)] += 1
        if len(self._samples) < SAMPLE_CAP:
            self._samples.append(x)
        else:
            self.truncated = True

    def percentile(self, p: float):
        """Quantile at percent ``p`` in [0, 100] — ``np.percentile``
        (linear interpolation) over the retained samples.

        Edge contract (regression-tested in tests/test_obs.py): ``p``
        outside [0, 100] raises typed
        :class:`~cylon_tpu.status.InvalidError`; an EMPTY histogram
        returns ``nan`` (not None — a report can carry it through
        arithmetic and JSON without type forks); a FULLY-truncated one
        (samples observed but none retained, ``SAMPLE_CAP`` exhausted
        before the first observation) returns ``nan`` too — bucket
        interpolation with zero retained samples would fabricate a
        quantile from the bucket grid alone.  Partial truncation keeps
        the documented bucket-interpolation fallback."""
        p = float(p)
        if not 0.0 <= p <= 100.0:
            from ..status import InvalidError
            raise InvalidError(
                f"percentile {p!r} outside [0, 100] on {self.name!r}")
        if not self._samples:
            return float("nan")
        if not self.truncated:
            import numpy as np
            return float(np.percentile(
                np.asarray(self._samples, float), p))
        return self._bucket_percentile(p)

    def _bucket_percentile(self, p: float) -> float:
        target = (p / 100.0) * max(self.count - 1, 0)
        seen = 0
        lo = 0.0
        for i, n in enumerate(self.bucket_counts):
            hi = self.buckets[i] if i < len(self.buckets) else lo * 2 or 1.0
            if n and seen + n > target:
                frac = (target - seen) / n
                return lo + frac * (hi - lo)
            seen += n
            lo = hi
        return lo

    def attainment(self, target) -> float | None:
        """Fraction of observations at or under ``target`` — SLO
        attainment for the serving tier's per-tenant report."""
        if self.count == 0:
            return None
        t = float(target)
        if not self.truncated:
            return sum(1 for x in self._samples if x <= t) / self.count
        under = 0
        for i, n in enumerate(self.bucket_counts):
            if i < len(self.buckets) and self.buckets[i] <= t:
                under += n
        return under / self.count

    @property
    def value(self):
        # the exposition/JSON-snapshot view: NaN quantiles (empty or
        # fully-truncated histogram — the percentile() edge contract)
        # export as None/null, which strict JSON parsers accept where a
        # literal NaN token would be rejected
        def _j(x):
            return None if x != x else x
        return {"count": self.count, "sum": round(self.sum, 6),
                "p50": _j(self.percentile(50)),
                "p99": _j(self.percentile(99))}

    def reset(self) -> None:
        self.bucket_counts = [0] * (len(self.buckets) + 1)
        self.count = 0
        self.sum = 0.0
        self._samples = []
        self.truncated = False


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_METRICS: dict[str, object] = {}
_COLLECTORS: list = []   # callables -> {section: payload} (timing phases)


def _get_or_make(name: str, cls, **kw):
    m = _METRICS.get(name)
    if m is None:
        with _LOCK:
            m = _METRICS.get(name)
            if m is None:
                m = cls(name, **kw)
                _METRICS[name] = m
    if not isinstance(m, cls):
        from ..status import InvalidError
        raise InvalidError(
            f"metric {name!r} already registered as "
            f"{type(m).__name__}, requested {cls.__name__}")
    return m


def counter(name: str, help: str = "") -> Counter:  # noqa: A002
    return _get_or_make(name, Counter, help=help)


def gauge(name: str, help: str = "", fn=None) -> Gauge:  # noqa: A002
    g = _get_or_make(name, Gauge, help=help)
    if fn is not None:
        g.fn = fn
    return g


def histogram(name: str, help: str = "",  # noqa: A002
              buckets=DEFAULT_BUCKETS) -> Histogram:
    return _get_or_make(name, Histogram, help=help, buckets=buckets)


def register_collector(fn) -> None:
    """Register a callable returning ``{section: payload}`` merged into
    :func:`snapshot` — utils/timing contributes its phase table this way
    without the registry importing it."""
    if fn not in _COLLECTORS:
        _COLLECTORS.append(fn)


def reset(prefix: str = "") -> None:
    """Zero every metric (optionally only names under ``prefix``).
    Registrations survive — handles stay valid, like the exec modules'
    ``reset_stats`` contract."""
    with _LOCK:
        items = list(_METRICS.items())
    for name, m in items:
        if name.startswith(prefix):
            m.reset()


# ---------------------------------------------------------------------------
# migration shims: dict-like views backed by registry counters
# ---------------------------------------------------------------------------

class CounterGroup(MutableMapping):
    """Fixed-key dict-like view over counters ``<prefix>_<key>`` — the
    exec modules' ``_STATS`` tables migrate onto the registry by
    rebinding ``_STATS = metrics.group("ckpt", (...))``: every
    ``_STATS[k] += 1`` site, ``dict(_STATS)`` shim and ``for k in
    _STATS`` reset keeps working verbatim while the values live in (and
    export from) the registry."""

    __slots__ = ("_keys", "_counters")

    def __init__(self, prefix: str, keys):
        self._keys = tuple(keys)
        self._counters = {k: counter(f"{prefix}_{k}") for k in self._keys}

    def __getitem__(self, k):
        return self._counters[k].value

    def __setitem__(self, k, v):
        self._counters[k].set(v)

    def __delitem__(self, k):
        raise TypeError("CounterGroup keys are fixed")

    def __iter__(self):
        return iter(self._keys)

    def __len__(self):
        return len(self._keys)


def group(prefix: str, keys) -> CounterGroup:
    return CounterGroup(prefix, keys)


class Namespace(MutableMapping):
    """Dynamic-key dict-like view over counters ``<prefix>_<key>`` —
    utils/timing's byte attribution (``add_bytes``) migrates onto the
    registry through this: keys appear on first write, ``clear()``
    zeroes (registrations survive)."""

    __slots__ = ("_prefix", "_local")

    def __init__(self, prefix: str):
        self._prefix = prefix
        self._local: dict[str, Counter] = {}

    def _c(self, k) -> Counter:
        c = self._local.get(k)
        if c is None:
            c = self._local[k] = counter(f"{self._prefix}_{k}")
        return c

    def __getitem__(self, k):
        if k not in self._local:
            raise KeyError(k)
        return self._local[k].value

    def get(self, k, default=None):
        c = self._local.get(k)
        return default if c is None else c.value

    def __setitem__(self, k, v):
        self._c(k).set(v)

    def __delitem__(self, k):
        self._local.pop(k).reset()

    def __iter__(self):
        return iter(self._local)

    def __len__(self):
        return len(self._local)

    def clear(self) -> None:
        for c in self._local.values():
            c.reset()
        self._local.clear()


def namespace(prefix: str) -> Namespace:
    return Namespace(prefix)


# ---------------------------------------------------------------------------
# exposition: Prometheus text + JSON snapshots
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    n = _NAME_RE.sub("_", name)
    return n if not n[:1].isdigit() else "_" + n


def prometheus_text(prefix: str = "cylon_tpu") -> str:
    """The registry in Prometheus text exposition format (counters,
    gauges, histograms with ``_bucket``/``_sum``/``_count`` series) —
    the GKE deploy serves this from a sidecar file or debug endpoint."""
    out = []
    with _LOCK:   # registrations are concurrent (serving threads)
        items = sorted(_METRICS.items())
    for name, m in items:
        pn = f"{prefix}_{_prom_name(name)}"
        if isinstance(m, Counter):
            out.append(f"# TYPE {pn} counter")
            out.append(f"{pn} {m.value}")
        elif isinstance(m, Gauge):
            out.append(f"# TYPE {pn} gauge")
            out.append(f"{pn} {m.value}")
        elif isinstance(m, Histogram):
            out.append(f"# TYPE {pn} histogram")
            acc = 0
            for i, b in enumerate(m.buckets):
                acc += m.bucket_counts[i]
                out.append(f'{pn}_bucket{{le="{b:g}"}} {acc}')
            out.append(f'{pn}_bucket{{le="+Inf"}} {m.count}')
            out.append(f"{pn}_sum {m.sum:g}")
            out.append(f"{pn}_count {m.count}")
    return "\n".join(out) + "\n"


def write_prometheus(path: str, prefix: str = "cylon_tpu") -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(prometheus_text(prefix))
    os.replace(tmp, path)


def snapshot() -> dict:
    """Every metric's current value as one JSON-able dict, plus any
    registered collector sections (utils/timing's phase table)."""
    with _LOCK:   # registrations are concurrent (serving threads)
        items = sorted(_METRICS.items())
    out = {name: m.value for name, m in items}
    for fn in _COLLECTORS:
        try:
            out.update(fn())
        except Exception:  # noqa: BLE001 — a broken collector must not
            pass           # take the snapshot down
    return out


def write_snapshot(path: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"ts": time.time(), "metrics": snapshot()}, f)
    os.replace(tmp, path)


#: [armed_path or "" (= checked, off) or None (= env unread), next_due]
_SNAP: list = [None, 0.0]


def maybe_write_snapshot() -> bool:
    """Periodic JSON snapshot poll (``CYLON_TPU_METRICS_JSON=path``,
    interval ``CYLON_TPU_METRICS_INTERVAL_S``, default 30 s) — called
    from the serving scheduler's baton loop.  Unarmed: one list load
    after the first env read (the happy-path contract)."""
    path = _SNAP[0]
    if path is None:
        path = _SNAP[0] = os.environ.get("CYLON_TPU_METRICS_JSON", "")
    if not path:
        return False
    now = time.monotonic()
    if now < _SNAP[1]:
        return False
    _SNAP[1] = now + float(
        os.environ.get("CYLON_TPU_METRICS_INTERVAL_S", "30"))
    try:
        # the periodic write rides the recovery tier's bounded
        # transient-OSError backoff (a scrape sidecar racing the rename,
        # a briefly-full tmpfs): one flaky write no longer drops a whole
        # interval's telemetry.  Non-transient errnos re-raise
        # immediately into the warn-once fallback below.
        from ..exec.recovery import retry_io
        retry_io(lambda: write_snapshot(path), "obs.snapshot")
    except OSError as e:
        if not _SNAP_WARNED[0]:
            # warn ONCE: the operator armed this path and would
            # otherwise get zero telemetry with zero diagnostics (the
            # same silent-loss mode obs.export surfaces typed for
            # traces); later failures stay quiet — the poll runs in
            # hot loops
            _SNAP_WARNED[0] = True
            from ..utils.logging import log
            log.warning("obs: metrics snapshot to %r failed: %s "
                        "(CYLON_TPU_METRICS_JSON armed but unwritable; "
                        "further failures are silent)", path, e)
        return False
    return True


_SNAP_WARNED = [False]


def _rearm_snapshots() -> None:
    """Re-read the env on the next poll (tests; env changed mid-run)."""
    _SNAP[0] = None
    _SNAP[1] = 0.0
    _SNAP_WARNED[0] = False


_AUTOARMED = [False]


def autoarm() -> None:
    """With ``CYLON_TPU_METRICS_JSON`` set, register an atexit final
    snapshot (called at package import): entrypoints that never reach a
    periodic poll site — the serving scheduler's baton loop, the
    pipelined piece loop — still emit the end-of-run snapshot the
    scrape sidecar reads.  No env var: nothing happens."""
    if _AUTOARMED[0] or not os.environ.get("CYLON_TPU_METRICS_JSON"):
        return
    _AUTOARMED[0] = True
    import atexit

    def _final_snapshot() -> None:
        path = os.environ.get("CYLON_TPU_METRICS_JSON")
        if path:
            try:
                write_snapshot(path)
            except OSError:
                pass   # exit path: never raise
    atexit.register(_final_snapshot)


# ---------------------------------------------------------------------------
# the shared bench-detail collector
# ---------------------------------------------------------------------------

#: bench.py's spill-counter selection (exec/memory.stats keys) — the
#: disk-tier pair (``disk_events``/``bytes_to_disk``) rides along so a
#: bench number always says whether it was achieved HBM-resident,
#: host-spilled, or out-of-core (docs/robustness.md "Disk tier & scan
#: pushdown")
BENCH_SPILL_KEYS = ("spill_events", "bytes_spilled", "peak_ledger_bytes",
                    "donated_bytes_reused", "disk_events", "bytes_to_disk")
#: the durable-checkpoint counters every bench JSON carries
BENCH_CKPT_KEYS = ("checkpoint_events", "bytes_checkpointed",
                   "resume_fast_forwarded_pieces", "resume_resharded_pieces",
                   "resume_world_mismatch")
#: the compile-lifecycle counters (exec/compiler.stats) every bench JSON
#: carries — a bench number always says how many executables were live,
#: how much wall-clock went to XLA, and whether the run re-used or
#: rebuilt its program family (docs/robustness.md "Compile lifecycle")
BENCH_COMPILE_KEYS = ("programs_live", "cache_hits", "cache_misses",
                      "cache_evictions", "compile_seconds")
#: the data-integrity audit counters (exec/integrity.stats) every bench
#: JSON carries — a bench number always says whether the audit tier was
#: armed (nonzero fingerprint checks ⇒ its ≤10% overhead is included in
#: the measurement) and whether it fired (docs/robustness.md "Integrity
#: audit tier")
BENCH_AUDIT_KEYS = ("conservation_checks", "fingerprint_checks",
                    "violations")


def bench_detail(*, spill_keys=BENCH_SPILL_KEYS, ckpt_keys=BENCH_CKPT_KEYS,
                 compile_keys=BENCH_COMPILE_KEYS,
                 audit_keys=BENCH_AUDIT_KEYS,
                 events: str | None = "drain", plan=None) -> dict:
    """The counter block every bench script previously hand-rolled:
    recovery events (``events="drain"`` empties the log like bench.py
    always did; ``"keep"`` reads without draining; ``None`` omits),
    the selected spill-tier counters (exec/memory.stats) and the
    selected checkpoint counters (exec/checkpoint.stats).  Key names
    are exactly the stats() keys — the bench JSONs' schema is asserted
    stable in tests/test_obs.py.

    ``plan``: a :class:`~cylon_tpu.obs.plan.QueryPlan` (or an already
    rendered dict) adds a ``plan`` section — the EXPLAIN/ANALYZE tree
    the bench drivers emit alongside the phase table (absent by
    default, so unprofiled schemas are unchanged)."""
    from ..exec import checkpoint, compiler, memory, recovery
    out: dict = {}
    if events == "drain":
        out["recovery_events"] = recovery.drain_events()
    elif events == "keep":
        out["recovery_events"] = recovery.recovery_events()
    mem = memory.stats()
    out.update({k: mem[k] for k in spill_keys})
    ck = checkpoint.stats()
    out.update({k: ck[k] for k in ckpt_keys})
    if compile_keys:
        comp = compiler.stats()
        out["compile"] = {k: comp[k] for k in compile_keys}
    if audit_keys:
        from ..exec import integrity
        au = integrity.stats()
        out["audit"] = {k: au[k] for k in audit_keys}
    if plan is not None:
        out["plan"] = plan.to_dict() if hasattr(plan, "to_dict") else plan
    return out
