"""Shuffle communication matrix — per-(src,dst) rows and bytes.

The exchange engine's count sidecar (parallel/shuffle.exchange) already
knows exactly which rank pair carried which rows: ``counts[s, d]`` is
the number of rows rank ``s`` sent to rank ``d``, replicated to every
process by the count-matrix pull the exchange needs anyway.  This module
turns that free information into the operator-facing N×N view ROADMAP
item 5 (topology-aware shuffle) will be judged against: armed
(``CYLON_TPU_COMM_MATRIX=1`` or :func:`arm` — same contract as
``CYLON_TPU_RANK_REPORT``), every exchange accumulates its count matrix
(rows and bytes) host-side, and :func:`report` reduces them to one
cumulative matrix whose row sums are per-source sent totals, column sums
per-destination received totals, and whose grand totals must equal the
always-on registry counters ``exchange_rows_total`` /
``exchange_bytes_total`` (asserted in tests/test_explain.py and
cross-checked byte-identical across ranks in tests/multihost_driver.py).

Unarmed and with no plan profile active, :func:`record` is never called
— the exchange guards on ``armed()`` (one env-cached list load): zero
extra collectives, zero host syncs, zero allocations.  Recording itself
is pure host numpy over the already-pulled sidecar — arming adds no
device work either; the one collective lives in :func:`report`'s
OPTIONAL cross-rank verification, at the explicit call site.
"""

from __future__ import annotations

import os

import numpy as np

__all__ = ["arm", "armed", "record", "reset", "report", "matrix"]

_ARMED: list = [False]

#: env arming, read ONCE at first check (None = unread): armed() sits on
#: the per-exchange hot path, so it must stay a list load, not an
#: environ lookup — arm at launch via the env var, or at runtime via
#: :func:`arm`; a mid-process env change needs :func:`_rearm` (tests)
_ENV_ARMED: list = [None]

#: cumulative state: [world, rows (W,W) int64, bytes (W,W) int64,
#: n_exchanges] — None until the first record
_STATE: list = [None]

#: per-exchange log (site, rows_total, bytes_total), newest last, bounded
_LOG: list = []
_LOG_CAP = 256


def arm(on: bool = True) -> None:
    _ARMED[0] = bool(on)


def armed() -> bool:
    if _ARMED[0]:
        return True
    e = _ENV_ARMED[0]
    if e is None:
        e = _ENV_ARMED[0] = \
            os.environ.get("CYLON_TPU_COMM_MATRIX") == "1"
    return e


def _rearm() -> None:
    """Re-read the env on the next armed() check (tests; env changed
    mid-run) — the metrics._rearm_snapshots pattern."""
    _ENV_ARMED[0] = None


def reset() -> None:
    _STATE[0] = None
    del _LOG[:]


def record(counts, row_bytes: int, site: str = "exchange") -> None:
    """Accumulate one exchange's (W, W) count sidecar into the
    cumulative matrices + the bounded per-exchange log.  Called (via
    ``obs.plan.record_exchange``) only when :func:`armed`; pure host
    work on the replicated sidecar — the plan profiler computes its
    node totals from the same sidecar independently, so an unarmed
    profile never touches this module's state."""
    counts = np.asarray(counts, np.int64)
    w = counts.shape[0]
    bmat = counts * int(row_bytes)
    st = _STATE[0]
    if st is None or st[0] != w:
        # world change (new mesh mid-process): restart the accumulation
        # — matrices of different shapes cannot legally sum
        st = _STATE[0] = [w, np.zeros((w, w), np.int64),
                          np.zeros((w, w), np.int64), 0]
    st[1] += counts
    st[2] += bmat
    st[3] += 1
    _LOG.append({"site": site, "rows": int(counts.sum()),
                 "bytes": int(bmat.sum()), "row_bytes": int(row_bytes)})
    if len(_LOG) > _LOG_CAP:
        del _LOG[:len(_LOG) - _LOG_CAP]


def matrix() -> tuple | None:
    """The cumulative (rows, bytes) matrices, or None before the first
    recorded exchange."""
    st = _STATE[0]
    if st is None:
        return None
    return st[1], st[2]


def report(verify_across_ranks: bool = True) -> dict | None:
    """The cumulative communication matrix with row/column sums, or None
    when nothing was recorded.  In a multiprocess session (armed runs
    only — the caller honors :func:`armed`) the matrix is allgathered
    and must be BYTE-IDENTICAL on every rank: each process accumulated
    the same replicated count sidecars, so any divergence means the
    ranks ran different exchanges — a typed
    :class:`~cylon_tpu.status.RankDesyncError`, never a silently
    per-rank report (the obs/rank_report contract)."""
    st = _STATE[0]
    if st is None:
        return None
    w, rows, bts, n = st[0], st[1], st[2], st[3]

    import jax
    nproc = jax.process_count()
    if verify_across_ranks and nproc > 1:
        from jax.experimental import multihost_utils
        from ..status import RankDesyncError
        wire = np.concatenate([[np.int64(n)], rows.ravel(), bts.ravel()])
        gathered = np.asarray(
            multihost_utils.process_allgather(wire)).reshape(nproc, -1)
        for r in range(1, nproc):
            if not np.array_equal(gathered[0], gathered[r]):
                raise RankDesyncError(
                    "comm matrix: ranks accumulated different exchange "
                    "sidecars — the ranks ran different shuffles",
                    site="obs.comm")

    return {
        "world": w,
        "exchanges": n,
        "rows": rows.tolist(),
        "bytes": bts.tolist(),
        "row_sums_bytes": bts.sum(axis=1).tolist(),   # per-src sent
        "col_sums_bytes": bts.sum(axis=0).tolist(),   # per-dst received
        "total_rows": int(rows.sum()),
        "total_bytes": int(bts.sum()),
        "recent": list(_LOG[-16:]),
    }
