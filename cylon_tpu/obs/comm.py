"""Shuffle communication matrix — per-(src,dst) rows and bytes.

The exchange engine's count sidecar (parallel/shuffle.exchange) already
knows exactly which rank pair carried which rows: ``counts[s, d]`` is
the number of rows rank ``s`` sent to rank ``d``, replicated to every
process by the count-matrix pull the exchange needs anyway.  This module
turns that free information into the operator-facing N×N view ROADMAP
item 5 (topology-aware shuffle) will be judged against: armed
(``CYLON_TPU_COMM_MATRIX=1`` or :func:`arm` — same contract as
``CYLON_TPU_RANK_REPORT``), every exchange accumulates its count matrix
(rows and bytes) host-side, and :func:`report` reduces them to one
cumulative matrix whose row sums are per-source sent totals, column sums
per-destination received totals, and whose grand totals must equal the
always-on registry counters ``exchange_rows_total`` /
``exchange_bytes_total`` (asserted in tests/test_explain.py and
cross-checked byte-identical across ranks in tests/multihost_driver.py).
On a multi-slice topology (cylon_tpu/topo, docs/topology.md) the
cumulative matrices additionally split by TIER — same-slice cells are
ICI, cross-slice cells DCN, ici+dcn grand totals still equal the
registry counters — alongside each tier's padded wire volume, the
two-hop route's acceptance instrument.

Unarmed and with no plan profile active, :func:`record` is never called
— the exchange guards on ``armed()`` (one env-cached list load): zero
extra collectives, zero host syncs, zero allocations.  Recording itself
is pure host numpy over the already-pulled sidecar — arming adds no
device work either; the one collective lives in :func:`report`'s
OPTIONAL cross-rank verification, at the explicit call site.
"""

from __future__ import annotations

import os

import numpy as np

__all__ = ["arm", "armed", "record", "reset", "report", "matrix"]

_ARMED: list = [False]

#: env arming, read ONCE at first check (None = unread): armed() sits on
#: the per-exchange hot path, so it must stay a list load, not an
#: environ lookup — arm at launch via the env var, or at runtime via
#: :func:`arm`; a mid-process env change needs :func:`_rearm` (tests)
_ENV_ARMED: list = [None]

#: cumulative state: [world, rows (W,W) int64, bytes (W,W) int64,
#: n_exchanges, slice_ids (W,) int32 or None, tier_traffic dict
#: (wire_ici/wire_dcn/msgs_ici/msgs_dcn), route_counts dict] — None
#: until the first record.  The tier fields (cylon_tpu/topo,
#: docs/topology.md) stay None/zero on single-slice topologies, and
#: :func:`report` splits the cumulative matrices by the slice map when
#: one was recorded.
_STATE: list = [None]

#: per-exchange log (site, rows_total, bytes_total), newest last, bounded
_LOG: list = []
_LOG_CAP = 256


def arm(on: bool = True) -> None:
    _ARMED[0] = bool(on)


def armed() -> bool:
    if _ARMED[0]:
        return True
    e = _ENV_ARMED[0]
    if e is None:
        e = _ENV_ARMED[0] = \
            os.environ.get("CYLON_TPU_COMM_MATRIX") == "1"
    return e


def _rearm() -> None:
    """Re-read the env on the next armed() check (tests; env changed
    mid-run) — the metrics._rearm_snapshots pattern."""
    _ENV_ARMED[0] = None


def reset() -> None:
    _STATE[0] = None
    del _LOG[:]


def record(counts, row_bytes: int, site: str = "exchange",
           tiers: dict | None = None) -> None:
    """Accumulate one exchange's (W, W) count sidecar into the
    cumulative matrices + the bounded per-exchange log.  Called (via
    ``obs.plan.record_exchange``) only when :func:`armed`; pure host
    work on the replicated sidecar — the plan profiler computes its
    node totals from the same sidecar independently, so an unarmed
    profile never touches this module's state.

    ``tiers`` (multi-slice topologies, cylon_tpu/topo): the engine's
    tier attribution — ``slice_ids`` (the per-rank slice map the report
    splits the matrices on), ``route`` ("flat"/"two_hop"), the PADDED
    per-tier wire volumes ``wire_ici``/``wire_dcn`` and the per-tier
    message counts ``msgs_ici``/``msgs_dcn`` this exchange put on each
    interconnect (the count matrix records payload rows; padding and
    per-message overhead are where the flat plan's small-message cost
    lives — docs/topology.md)."""
    counts = np.asarray(counts, np.int64)
    w = counts.shape[0]
    bmat = counts * int(row_bytes)
    sids = None if tiers is None \
        else np.asarray(tiers["slice_ids"], np.int32)
    st = _STATE[0]
    topo_changed = st is not None and (
        (sids is None) != (st[4] is None)
        or (sids is not None and not np.array_equal(st[4], sids)))
    if st is None or st[0] != w or topo_changed:
        # world OR topology change (new mesh / re-sliced fabric
        # mid-process, in EITHER direction — tiered↔tier-less included):
        # restart the accumulation — matrices of different shapes or
        # tier maps cannot legally sum, and a tier split computed over
        # traffic recorded under another (or no) slice map would
        # misattribute every pre-change exchange
        st = _STATE[0] = [w, np.zeros((w, w), np.int64),
                          np.zeros((w, w), np.int64), 0, sids,
                          {"wire_ici": 0, "wire_dcn": 0,
                           "msgs_ici": 0, "msgs_dcn": 0}, {}]
    st[1] += counts
    st[2] += bmat
    st[3] += 1
    ent = {"site": site, "rows": int(counts.sum()),
           "bytes": int(bmat.sum()), "row_bytes": int(row_bytes)}
    if tiers is not None:
        for k in st[5]:
            st[5][k] += int(tiers[k])
        route = tiers["route"]
        st[6][route] = st[6].get(route, 0) + 1
        ent["route"] = route
    _LOG.append(ent)
    if len(_LOG) > _LOG_CAP:
        del _LOG[:len(_LOG) - _LOG_CAP]


def matrix() -> tuple | None:
    """The cumulative (rows, bytes) matrices, or None before the first
    recorded exchange."""
    st = _STATE[0]
    if st is None:
        return None
    return st[1], st[2]


def report(verify_across_ranks: bool = True) -> dict | None:
    """The cumulative communication matrix with row/column sums, or None
    when nothing was recorded.  In a multiprocess session (armed runs
    only — the caller honors :func:`armed`) the matrix is allgathered
    and must be BYTE-IDENTICAL on every rank: each process accumulated
    the same replicated count sidecars, so any divergence means the
    ranks ran different exchanges — a typed
    :class:`~cylon_tpu.status.RankDesyncError`, never a silently
    per-rank report (the obs/rank_report contract)."""
    st = _STATE[0]
    if st is None:
        return None
    w, rows, bts, n = st[0], st[1], st[2], st[3]
    sids, traffic, routes = st[4], st[5], st[6]

    import jax
    nproc = jax.process_count()
    if verify_across_ranks and nproc > 1:
        from jax.experimental import multihost_utils
        from ..status import RankDesyncError
        tier_wire = ([np.int64(traffic[k]) for k in sorted(traffic)]
                     + (sids.astype(np.int64).tolist()
                        if sids is not None else []))
        wire = np.concatenate([[np.int64(n)], rows.ravel(), bts.ravel(),
                               np.asarray(tier_wire, np.int64)])
        gathered = np.asarray(
            multihost_utils.process_allgather(wire)).reshape(nproc, -1)
        for r in range(1, nproc):
            if not np.array_equal(gathered[0], gathered[r]):
                raise RankDesyncError(
                    "comm matrix: ranks accumulated different exchange "
                    "sidecars — the ranks ran different shuffles",
                    site="obs.comm")

    out = {
        "world": w,
        "exchanges": n,
        "rows": rows.tolist(),
        "bytes": bts.tolist(),
        "row_sums_bytes": bts.sum(axis=1).tolist(),   # per-src sent
        "col_sums_bytes": bts.sum(axis=0).tolist(),   # per-dst received
        "total_rows": int(rows.sum()),
        "total_bytes": int(bts.sum()),
        "recent": list(_LOG[-16:]),
    }
    if sids is not None:
        # tier split (cylon_tpu/topo, docs/topology.md): the cumulative
        # matrices masked by the slice map.  ICI + DCN grand totals
        # equal the matrix totals above — which reconcile with the
        # always-on registry counters — while the wire/message fields
        # carry each tier's PADDED link volume and (src, dst, round)
        # transfer count: the DCN message count is the two-hop route's
        # exactly-1/R acceptance instrument (cross-slice payload itself
        # is route-invariant — each remote row crosses DCN once either
        # way).
        cross = sids[:, None] != sids[None, :]
        out["tiers"] = {
            "n_slices": int(len(np.unique(sids))),
            "ici_rows_matrix": np.where(cross, 0, rows).tolist(),
            "dcn_rows_matrix": np.where(cross, rows, 0).tolist(),
            "ici_rows": int(rows[~cross].sum()),
            "dcn_rows": int(rows[cross].sum()),
            "ici_bytes": int(bts[~cross].sum()),
            "dcn_bytes": int(bts[cross].sum()),
            "ici_wire_bytes": int(traffic["wire_ici"]),
            "dcn_wire_bytes": int(traffic["wire_dcn"]),
            "ici_messages": int(traffic["msgs_ici"]),
            "dcn_messages": int(traffic["msgs_dcn"]),
            "routes": dict(routes),
        }
    return out
