"""Unified observability subsystem: metrics registry, trace timeline /
flight recorder, and per-rank skew reporting.

Three cooperating pieces (docs/observability.md):

* :mod:`cylon_tpu.obs.metrics` — typed counters/gauges/histograms
  behind one facade; the exec modules' former ``_STATS`` dicts and
  utils/timing's byte/event attribution live here now, with a
  Prometheus text writer and periodic JSON snapshots for the GKE
  deploy, plus the shared :func:`~cylon_tpu.obs.metrics.bench_detail`
  collector the bench scripts report through.
* :mod:`cylon_tpu.obs.trace` — a bounded ring of span/instant events
  (``CYLON_TPU_TRACE=path``) exported as Chrome-trace/Perfetto JSON,
  with a last-N postmortem dump on drains, final-rung aborts and
  injected kills.
* :mod:`cylon_tpu.obs.rank_report` — an explicitly-armed end-of-run
  allgather of each rank's phase table, reduced to a min/median/max
  skew report (``CYLON_TPU_RANK_REPORT=1``).
* :mod:`cylon_tpu.obs.plan` — the query profiler: every distributed
  operator pushes a typed plan node; :func:`~cylon_tpu.obs.plan.
  explain` returns the static tree, :func:`~cylon_tpu.obs.plan.
  explain_analyze` attaches per-node rows/bytes/seconds (reconciling
  with the global phase table) and heavy-hitter key profiles
  (:mod:`cylon_tpu.obs.sketch`, Misra-Gries).
* :mod:`cylon_tpu.obs.comm` — the shuffle communication matrix:
  per-(src,dst) rows/bytes accumulated from the exchange's count
  sidecar (``CYLON_TPU_COMM_MATRIX=1``), row/column sums reconciling
  with the always-on exchange byte counters.

Overhead contract: with nothing armed, the whole subsystem costs one
extra list load per timed region and one per scheduler loop — zero
collectives, zero host syncs, zero filesystem writes (asserted in
tests/test_obs.py).  Module-level ad-hoc counter dicts outside this
package are a lint finding (TS112, docs/trace_safety.md).
"""

from . import comm, metrics, plan, rank_report, sketch, trace  # noqa: F401
from .metrics import (bench_detail, counter, gauge,  # noqa: F401
                      histogram, maybe_write_snapshot, prometheus_text,
                      snapshot, write_prometheus, write_snapshot)
from .plan import explain, explain_analyze  # noqa: F401
