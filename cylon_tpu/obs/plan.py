"""Query plan profiler — EXPLAIN / EXPLAIN ANALYZE for the engine.

Every distributed operator entry point (relational/join, groupby, sort,
setops, repart, exec/pipeline, stream/) pushes a typed :class:`PlanNode`
onto a QUERY-SCOPED context while it runs — operator name, keys, the
route it chose (broadcast vs hash vs skew-split vs pipelined), chunk
counts, piece caps, spill/donation flags.  With no profile active the
whole facade is one thread-local load per operator call: no node, no
allocation, no timing, no device work (the PR 10 overhead contract,
asserted in tests/test_explain.py).

:func:`explain` runs a query and returns the static tree;
:func:`explain_analyze` additionally attaches measurements per node:

* **seconds** — a node-scoped ``utils/timing`` attribution scope (the
  same mechanism as the serving tier's per-session scopes, PR 7): each
  node's scope is innermost while the node runs, so node phase tables
  are SELF times (exclusive of children) by construction, and their
  per-region sums reconcile with the process-global phase table — the
  invariant ``QueryPlan.reconcile`` checks and tests assert.  The
  ``.block`` suffix convention (``timing.sync_region``) splits each
  node into dispatch vs block seconds.
* **rows in/out** — from the host-known valid-count sidecars (no sync).
* **bytes/rows exchanged** — recorded by ``parallel/shuffle.exchange``
  into the innermost node; with the comm matrix armed
  (``CYLON_TPU_COMM_MATRIX=1``, obs/comm) the per-(src,dst) matrix
  accumulates alongside.
* **events** — spill/recovery/checkpoint counter deltas over the node's
  window (inclusive of children; the registry counters are global).
* **heavy hitters** — a Misra-Gries top-K sketch (obs/sketch) over
  sampled key values, piggybacking on the sort-splitter sampling
  machinery (``relational/common.sample_keys``, an evenly-spaced
  per-shard device sample like ``relational/sort._sample_fn``), with an
  estimated max-rank share — the ROADMAP item 2 detection baseline.

The ONLY sanctioned way to create plan nodes is this module's
:func:`node` context manager (plus :func:`annotate` for attributes
discovered mid-operator).  A direct ``push_node``/``pop_node`` call in
``relational/``, ``exec/`` or ``stream/`` is lint rule **TS113**
(docs/trace_safety.md): an unbalanced push leaves every later query's
tree reparented under a dead node.
"""

from __future__ import annotations

import contextlib
import threading

__all__ = ["PlanNode", "QueryPlan", "node", "annotate", "active",
           "current", "explain", "explain_analyze", "record_exchange",
           "profile_keys", "key_profile", "render_tree"]

#: default Misra-Gries capacity for per-node key profiles
SKETCH_K = 16

_TLS = threading.local()


def _profile():
    return getattr(_TLS, "profile", None)


def active() -> bool:
    """A query profile is collecting on this thread (one TLS load)."""
    return getattr(_TLS, "profile", None) is not None


class PlanNode:
    """One operator invocation in a query's plan tree."""

    __slots__ = ("op", "attrs", "children", "rows_in", "rows_out",
                 "rows_exchanged", "bytes_exchanged", "exchanges",
                 "phases", "dispatch_s", "block_s", "seconds", "events",
                 "heavy", "_scope", "_scope_cm", "_ev0")

    def __init__(self, op: str, attrs: dict):
        self.op = op
        self.attrs = dict(attrs)
        self.children: list[PlanNode] = []
        self.rows_in = None
        self.rows_out = None
        self.rows_exchanged = 0
        self.bytes_exchanged = 0
        self.exchanges: list[dict] = []
        self.phases = None          # self-time region table (analyze)
        self.dispatch_s = None
        self.block_s = None
        self.seconds = None         # self seconds (exclusive of children)
        self.events = None          # counter deltas (inclusive window)
        self.heavy = None           # Misra-Gries key profile
        self._scope = None
        self._scope_cm = None
        self._ev0 = None

    def __bool__(self) -> bool:
        return True

    def set(self, **kw) -> None:
        """Set measured fields (``rows_in``/``rows_out``) or extend
        ``attrs`` — the operator-facing write API."""
        for k, v in kw.items():
            if k in ("rows_in", "rows_out"):
                setattr(self, k, int(v))
            else:
                self.attrs[k] = v

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    # -- reporting --------------------------------------------------------
    def total_seconds(self) -> float:
        """Inclusive seconds: self + children."""
        own = self.seconds or 0.0
        return own + sum(c.total_seconds() for c in self.children)

    def total_bytes_exchanged(self) -> int:
        return self.bytes_exchanged \
            + sum(c.total_bytes_exchanged() for c in self.children)

    def static_dict(self) -> dict:
        """The measurement-free tree — two runs of the same query must
        produce IDENTICAL static dicts (asserted in tests)."""
        return {"op": self.op,
                "attrs": {k: self.attrs[k] for k in sorted(self.attrs)},
                "children": [c.static_dict() for c in self.children]}

    def to_dict(self) -> dict:
        out = {"op": self.op,
               "attrs": {k: self.attrs[k] for k in sorted(self.attrs)}}
        if self.rows_in is not None:
            out["rows_in"] = self.rows_in
        if self.rows_out is not None:
            out["rows_out"] = self.rows_out
        if self.rows_exchanged:
            out["rows_exchanged"] = self.rows_exchanged
            out["bytes_exchanged"] = self.bytes_exchanged
        if self.seconds is not None:
            out["self_s"] = round(self.seconds, 6)
            out["dispatch_s"] = round(self.dispatch_s, 6)
            out["block_s"] = round(self.block_s, 6)
            out["total_s"] = round(self.total_seconds(), 6)
        if self.phases:
            out["phases"] = self.phases
        if self.events:
            out["events"] = self.events
        if self.heavy is not None:
            out["heavy_hitters"] = self.heavy
        out["children"] = [c.to_dict() for c in self.children]
        return out


class _NoopNode:
    """The unarmed stand-in: falsy, swallows every write."""

    __slots__ = ()

    def __bool__(self) -> bool:
        return False

    def set(self, **kw) -> None:
        pass

    def annotate(self, **attrs) -> None:
        pass


_NOOP = _NoopNode()


class QueryPlan:
    """The result of :func:`explain` / :func:`explain_analyze`."""

    def __init__(self, mode: str):
        self.mode = mode            # "explain" | "analyze"
        self.roots: list[PlanNode] = []
        self.result = None          # the profiled callable's return value
        self.global_phases: dict = {}
        self.comm: dict | None = None

    def static_dict(self) -> dict:
        return {"mode": "explain",
                "roots": [r.static_dict() for r in self.roots]}

    def to_dict(self) -> dict:
        out = {"mode": self.mode,
               "roots": [r.to_dict() for r in self.roots]}
        if self.mode == "analyze":
            out["global_phases"] = self.global_phases
            out["reconcile"] = self.reconcile()
        if self.comm is not None:
            out["comm_matrix"] = self.comm
        return out

    def render(self) -> str:
        return render_tree(self.to_dict())

    def reconcile(self) -> dict:
        """The analyze invariant: per-region seconds summed over every
        node's SELF table must equal the process-global phase table
        accumulated over the run (both tables saw the identical region
        durations; only the grouping differs, so equality holds to fp
        summation order).  Regions fired outside any node land in
        ``unattributed_s``."""
        per_name: dict = {}

        def walk(n: PlanNode):
            for k, v in (n.phases or {}).items():
                per_name[k] = per_name.get(k, 0.0) + v["s"]
            for c in n.children:
                walk(c)

        for r in self.roots:
            walk(r)
        node_s = sum(per_name.values())
        glob = {k: v["s"] for k, v in self.global_phases.items()}
        glob_s = sum(glob.values())
        return {"node_s": round(node_s, 6),
                "phase_s": round(glob_s, 6),
                "unattributed_s": round(glob_s - node_s, 6),
                "per_phase_node_s": {k: round(v, 6)
                                     for k, v in sorted(per_name.items())}}


# ---------------------------------------------------------------------------
# the context-manager facade (the ONLY sanctioned push/pop caller — TS113)
# ---------------------------------------------------------------------------

def push_node(op: str, attrs: dict, prof: QueryPlan) -> PlanNode:
    """INTERNAL — create a node, attach it to the current parent and make
    it current.  Only :func:`node` may call this (lint rule TS113): an
    unbalanced push corrupts every later query's tree."""
    n = PlanNode(op, attrs)
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    if stack:
        stack[-1].children.append(n)
    else:
        prof.roots.append(n)
    stack.append(n)
    return n


def pop_node(n: PlanNode) -> None:
    """INTERNAL — the balanced inverse of :func:`push_node` (TS113)."""
    stack = getattr(_TLS, "stack", None)
    if stack and stack[-1] is n:
        stack.pop()


def current() -> PlanNode | None:
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


def annotate(**attrs) -> None:
    """Merge attributes into the CURRENT node (route decisions made deep
    inside an operator, where the node handle is out of scope).  No-op
    without an active profile."""
    if getattr(_TLS, "profile", None) is None:
        return
    n = current()
    if n is not None:
        n.annotate(**attrs)


def _event_counters() -> tuple:
    from ..exec import recovery
    from . import metrics
    return (metrics.counter("memory_spill_events").value,
            metrics.counter("ckpt_checkpoint_events").value,
            len(recovery.recovery_events()))


class _NodeCtx:
    """The per-operator context manager: cheap no-op when no profile is
    active; otherwise push + (analyze mode) a node-scoped attribution
    scope whose table becomes the node's self-time phase breakdown."""

    __slots__ = ("_op", "_attrs", "_node", "_prof")

    def __init__(self, op: str, attrs: dict):
        self._op = op
        self._attrs = attrs
        self._node = None
        self._prof = None

    def __enter__(self):
        prof = getattr(_TLS, "profile", None)
        if prof is None:
            return _NOOP
        self._prof = prof
        n = self._node = push_node(self._op, self._attrs, prof)
        if prof.mode == "analyze":
            from ..utils import timing
            n._scope_cm = timing.attribution_scope(f"plan:{self._op}")
            n._scope = n._scope_cm.__enter__()
            n._ev0 = _event_counters()
        return n

    def __exit__(self, exc_type, exc, tb):
        n = self._node
        if n is None:
            return False
        if n._scope_cm is not None:
            n._scope_cm.__exit__(exc_type, exc, tb)
            sc, n._scope, n._scope_cm = n._scope, None, None
            from ..utils import timing
            n.phases = sc.snapshot()
            n.seconds = sc.total_seconds()
            dispatch, block = timing.split_snapshot(n.phases)
            n.dispatch_s = sum(dispatch.values())
            n.block_s = sum(block.values())
            ev1 = _event_counters()
            n.events = {k: max(b - a, 0) for k, (a, b) in zip(
                ("spill_events", "checkpoint_events", "recovery_events"),
                zip(n._ev0, ev1))}
            # a session (serving) scope enclosing the whole profile must
            # not lose this node's seconds to the shadowing node scope —
            # absorb each node's SELF table into it exactly once
            outer = getattr(self._prof, "_outer", None)
            if outer is not None:
                outer.absorb(sc)
        pop_node(n)
        return False


def node(op: str, **attrs) -> _NodeCtx:
    """Open a plan node for one operator invocation::

        with plan.node("join", how=how, on=tuple(left_on)) as pn:
            ...
            if pn:
                pn.set(rows_out=out.row_count)

    Yields the :class:`PlanNode` (truthy) with a profile active, or a
    falsy no-op stand-in otherwise — call sites guard their bookkeeping
    on ``if pn:`` so the unarmed path computes nothing."""
    return _NodeCtx(op, attrs)


# ---------------------------------------------------------------------------
# exchange + key-profile recording (called from the engine)
# ---------------------------------------------------------------------------

def record_exchange(counts, row_bytes: int, site: str = "exchange",
                    tiers: dict | None = None) -> None:
    """Attach one exchange's totals to the innermost plan node, and —
    ONLY with the comm matrix explicitly armed — accumulate its
    per-(src,dst) matrix.  Called by ``parallel/shuffle.exchange`` only
    when a profile is active or the comm matrix is armed (the caller
    guards, so the happy path never reaches here).  A profile alone must
    NOT touch the comm module's cumulative state: an unarmed
    explain/explain_analyze would otherwise leave exchanges behind that
    a later ARMED session's report() serves, breaking its
    totals-equal-the-exchange-counters invariant (and, cross-rank, its
    byte-identity check when ranks profiled different queries before
    arming — regression test in tests/test_explain.py).

    ``tiers`` (multi-slice topologies only, cylon_tpu/topo): the
    engine-computed tier attribution — per-rank slice ids, the route
    that carried the exchange, and each tier's padded wire volume — fed
    through to :func:`cylon_tpu.obs.comm.record`'s ICI/DCN split."""
    import numpy as np
    from . import comm
    rows = int(np.asarray(counts).sum())
    nbytes = rows * int(row_bytes)
    if comm.armed():
        comm.record(counts, row_bytes, site=site, tiers=tiers)
    n = current()
    if n is not None:
        n.rows_exchanged += rows
        n.bytes_exchanged += nbytes
        ent = {"site": site, "rows": rows, "bytes": nbytes}
        if tiers is not None:
            ent["route"] = tiers["route"]
        n.exchanges.append(ent)


def profile_keys(pn, table, key_names, k: int = SKETCH_K) -> None:
    """Sample ``table``'s key columns (the sort-splitter sampling path:
    evenly spaced per-shard positions, shard-weighted) and attach a
    Misra-Gries heavy-hitter profile to node ``pn``.  Analyze-mode
    operators call this with their (falsy-when-unarmed) node, so the
    unarmed path is one truthiness check."""
    if not pn:
        return
    prof = _profile()
    if prof is None or prof.mode != "analyze" \
            or not getattr(prof, "keys_enabled", True):
        return
    pn.heavy = key_profile(table, key_names, k=k)


def key_profile(table, key_names, k: int = SKETCH_K,
                m: int | None = None) -> dict | None:
    """Standalone heavy-hitter profile of ``table``'s key columns —
    ``bench.py --skew`` reports this for the Zipf key column.  Returns
    None for empty tables.  ``est_max_rank_share`` is the estimated
    fraction of rows the hottest rank would receive under plain hash
    partitioning: the top key's share plus a uniform spread of the
    rest — the imbalance ROADMAP item 2's splitter will be judged
    against.  ``est_rows_per_rank`` places each tracked key on its
    ACTUAL partition (``ops/hashing.partition_of`` over the sampled
    routing hash — the exact shuffle predicate) and spreads the
    untracked residue uniformly: the per-rank row histogram the CURRENT
    partitioner would produce, which is what ``scripts/explain.py``
    diffs against a split plan's balanced layout to answer "why this
    plan" (docs/skew.md)."""
    import numpy as np

    from .sketch import MisraGries
    from ..ops.hashing import partition_of
    from ..relational.common import sample_keys

    key_names = [key_names] if isinstance(key_names, str) else list(key_names)
    sampled = sample_keys(table, key_names, m=m, with_hashes=True)
    if sampled is None:
        return None
    values, weights, total_rows, hashes = sampled
    mg = MisraGries(k=k)
    mg.update(values, weights)
    w = table.env.world_size
    shares = mg.shares()
    heavy = [{"key": kv, "share": round(sh, 6), "err": round(err, 6)}
             for kv, sh, err in shares if sh > max(err, 1.0 / (2 * k))]
    top = shares[0][1] if shares else 0.0
    covered = min(sum(sh for _, sh, _ in shares), 1.0)
    # identity -> routing hash (first sampled occurrence); tracked keys
    # land on partition_of(hash), the residue spreads uniformly
    id2hash = {}
    for v, h in zip(values.tolist(), hashes.tolist()):
        id2hash.setdefault(v, int(h))
    per_rank = np.full(w, (1.0 - covered) / w * total_rows)
    for kv, sh, _err in shares:
        h = id2hash.get(kv)
        if h is None:           # decayed out of the sample window
            per_rank += sh * total_rows / w
        else:
            per_rank[partition_of(h, w)] += sh * total_rows
    return {
        "keys": key_names,
        "sampled": int(len(values)),
        "rows": int(total_rows),
        "k": k,
        "heavy": heavy,
        "max_key_share": round(top, 6),
        "est_max_rank_share": round(top + max(1.0 - covered, 0.0) / w, 6),
        "est_rows_per_rank": [int(round(x)) for x in per_rank],
    }


# ---------------------------------------------------------------------------
# explain / explain_analyze
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _query_profile(mode: str):
    if getattr(_TLS, "profile", None) is not None:
        from ..status import InvalidError
        raise InvalidError("a query profile is already active on this "
                           "thread — explain/explain_analyze do not nest")
    prof = QueryPlan(mode)
    if mode == "analyze":
        from ..utils import timing
        prof._outer = timing._scope()
    _TLS.profile = prof
    _TLS.stack = []
    try:
        yield prof
    finally:
        _TLS.profile = None
        _TLS.stack = []


def explain(fn, *args, **kwargs) -> QueryPlan:
    """Run ``fn(*args, **kwargs)`` with plan collection on: returns the
    STATIC tree (operators, keys, routes, chunking) — no timing scopes,
    no sampling, no counter reads.  The query still executes (plans are
    discovered by running, not parsed)."""
    with _query_profile("explain") as prof:
        prof.result = fn(*args, **kwargs)
    return prof


def explain_analyze(fn, *args, reset_timings: bool = True,
                    profile_keys: bool = True,
                    family: str | None = None, **kwargs) -> QueryPlan:
    """:func:`explain` plus measurements: arms ``config.BENCH_TIMINGS``
    for the duration (restoring the caller's flags), resets the global
    phase table (``reset_timings=False`` to accumulate instead), runs
    the query under per-node attribution scopes, and snapshots the
    global phase table for :meth:`QueryPlan.reconcile`.  With the comm
    matrix armed the report is attached as ``comm_matrix``.

    ``profile_keys=False`` skips the per-node heavy-hitter sampling —
    the one ANALYZE feature that adds device programs and mid-query
    host pulls of its own.  bench.py's profiled iteration uses this so
    its ``profiled_iter_s``/phase split stay comparable with
    pre-profiler rounds (the BENCH_rNN baselines) and the async-mode
    one-designated-block contract holds.

    ``family`` names the query's admission SHAPE FAMILY: after the run
    the observed peak-ledger bytes are recorded against it
    (:func:`cylon_tpu.exec.scheduler.note_family_peak`), and serving
    sessions submitted with the same ``shape_family`` are admitted at
    ``min(declared, observed_peak x safety_factor)`` — ANALYZE history
    replacing the conservative declared maximum (docs/serving.md)."""
    from .. import config
    from ..utils import timing
    from . import comm

    prev = config.BENCH_TIMINGS
    config.BENCH_TIMINGS = True
    if reset_timings:
        timing.reset()
    if comm.armed():
        comm.reset()
    try:
        with _query_profile("analyze") as prof:
            prof.keys_enabled = bool(profile_keys)
            prof.result = fn(*args, **kwargs)
            prof.global_phases = timing.snapshot()
    finally:
        config.BENCH_TIMINGS = prev
    if comm.armed():
        prof.comm = comm.report()
    if family is not None:
        from ..exec import memory, scheduler
        scheduler.note_family_peak(
            family, int(memory.stats()["peak_ledger_bytes"]))
    return prof


# ---------------------------------------------------------------------------
# rendering (shared with scripts/explain.py)
# ---------------------------------------------------------------------------

def _node_line(d: dict) -> str:
    bits = [d["op"]]
    attrs = d.get("attrs") or {}
    if attrs:
        bits.append("[" + " ".join(f"{k}={attrs[k]}"
                                   for k in sorted(attrs)) + "]")
    rio = []
    if "rows_in" in d:
        rio.append(f"rows={d['rows_in']}")
    if "rows_out" in d:
        rio.append(f"out={d['rows_out']}")
    if d.get("bytes_exchanged"):
        rio.append(f"xchg={d['bytes_exchanged']}B")
    if "total_s" in d:
        rio.append(f"self={d['self_s']:.4f}s total={d['total_s']:.4f}s "
                   f"(dispatch {d['dispatch_s']:.4f} / "
                   f"block {d['block_s']:.4f})")
    if rio:
        bits.append("(" + ", ".join(rio) + ")")
    hh = d.get("heavy_hitters")
    if hh and hh.get("heavy"):
        top = hh["heavy"][0]
        bits.append(f"hot[{top['key']}≈{top['share']:.1%}]")
    if hh and hh.get("est_rows_per_rank"):
        # the "why this plan" number (docs/skew.md): the hottest rank's
        # estimated row share under the CURRENT partitioner — what a
        # split plan's balanced layout is judged against
        per = hh["est_rows_per_rank"]
        tot = sum(per) or 1
        hot_r = max(range(len(per)), key=per.__getitem__)
        bits.append(f"rank_max[r{hot_r}≈{per[hot_r] / tot:.1%} of rows]")
    return " ".join(bits)


def render_tree(plan_dict: dict) -> str:
    """ASCII tree of a :meth:`QueryPlan.to_dict` payload (also consumed
    by scripts/explain.py on saved JSON)."""
    lines = [f"query plan ({plan_dict.get('mode', 'explain')})"]

    def walk(d, prefix, last):
        lines.append(prefix + ("└─ " if last else "├─ ") + _node_line(d))
        kids = d.get("children") or []
        for i, c in enumerate(kids):
            walk(c, prefix + ("   " if last else "│  "),
                 i == len(kids) - 1)

    roots = plan_dict.get("roots") or []
    for i, r in enumerate(roots):
        walk(r, "", i == len(roots) - 1)
    rec = plan_dict.get("reconcile")
    if rec:
        lines.append(f"phases: node {rec['node_s']}s / global "
                     f"{rec['phase_s']}s (unattributed "
                     f"{rec['unattributed_s']}s)")
    return "\n".join(lines)
