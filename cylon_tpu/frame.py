"""DataFrame: the pandas-like user API.

TPU-native equivalent of PyCylon's ``DataFrame`` veneer (reference
python/pycylon/pycylon/frame.py:187, GroupByDataFrame :122) preserving the
reference's dispatch contract (frame.py:2063-2076): every operator takes
``env: CylonEnv = None`` — ``None`` runs the op locally (serial world), an
env runs it distributed over that env's device mesh.  A DataFrame built
without an env lives on the default local device; passing ``env=`` to an op
(or the constructor) moves/keeps it on the mesh.

Column math and filters go through :class:`cylon_tpu.series.Series`
(reference compute.pyx engine).
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping, Sequence

import numpy as np

from .core.column import Column
from .core.table import Table, default_env
from .ctx.context import CylonEnv
from .relational import (concat_tables, equals, filter_table,
                         groupby_aggregate, head, join_tables, repartition,
                         set_operation, shuffle_table, slice_table,
                         sort_table, tail, unique_table)
from .series import Series
from .status import CylonKeyError, InvalidError

__all__ = ["DataFrame", "GroupByDataFrame", "concat", "read_pandas"]


def _check_join_algorithm(algorithm: str) -> None:
    """The reference's SORT|HASH join choice (join_config.hpp:25,37).  On
    TPU the single-sort merge dominates a hash build/probe at every
    build-side size (measured v5e: ≥15.5 ns/row per random probe gather vs
    ~3.5 ns/row sort operand + ~1.7/payload lane; see docs/DESIGN.md
    "HASH join option"), so "hash" warns and runs the sort path."""
    if algorithm == "sort":
        return
    if algorithm == "hash":
        import warnings
        warnings.warn(
            "algorithm='hash' is not implemented on TPU: a hash probe "
            "costs >=15.5 ns/row (random gather) vs ~3.5 ns/row for a "
            "sort operand, so the single-sort merge join is used instead "
            "(see docs/DESIGN.md)", UserWarning, stacklevel=3)
        return
    raise InvalidError(f"algorithm must be 'sort' or 'hash', got "
                       f"{algorithm!r}")


def _resolve_env(df_env: CylonEnv, env: CylonEnv | None) -> CylonEnv:
    return env if env is not None else df_env


class DataFrame:
    """Columnar distributed dataframe over a device mesh."""

    def __init__(self, data: Any = None, env: CylonEnv | None = None,
                 _table: Table | None = None):
        self._index: str | None = None  # label index column (C24 analog)
        self._index_drop: bool = True   # pandas set_index drop semantics
        if _table is not None:
            self._table = _table
            return
        if data is None:
            data = {}
        if isinstance(data, Table):
            self._table = data
        elif isinstance(data, DataFrame):
            self._table = data._table
        elif isinstance(data, Mapping):
            self._table = Table.from_pydict(
                {k: np.asarray(v) for k, v in data.items()}, env)
        elif isinstance(data, (list, tuple)):
            # list of columns (PyCylon accepts list-of-lists)
            cols = {f"{i}": np.asarray(c) for i, c in enumerate(data)}
            self._table = Table.from_pydict(cols, env)
        else:
            try:
                import pandas as pd
                if isinstance(data, pd.DataFrame):
                    self._table = Table.from_pandas(data, env)
                else:
                    raise TypeError
            except TypeError:
                raise InvalidError(f"cannot build DataFrame from {type(data)}")

    # -- construction helpers ---------------------------------------------
    @staticmethod
    def from_table(table: Table) -> "DataFrame":
        return DataFrame(_table=table)

    @property
    def table(self) -> Table:
        return self._table

    @property
    def env(self) -> CylonEnv:
        return self._table.env

    def _to_env(self, env: CylonEnv) -> "DataFrame":
        """Move this frame onto another env's mesh (host round-trip)."""
        if env is self._table.env:
            return self
        return DataFrame(self.to_pandas(), env=env)

    def _index_cols(self) -> list:
        """Index column names as a list: [] (range index), one name, or
        several (multi-index, reference index.hpp:36 over indexer.hpp:76)."""
        if self._index is None:
            return []
        if isinstance(self._index, tuple):
            return list(self._index)
        return [self._index]

    def _wrap(self, table: Table, keep_index: bool = False) -> "DataFrame":
        out = DataFrame(_table=table)
        idx = self._index_cols()
        if keep_index and idx and all(c in table.column_names for c in idx):
            out._index = self._index
            out._index_drop = self._index_drop
        return out

    def _hidden(self) -> set:
        """Columns present in the physical table but not user-visible (a
        dropped-into-index column)."""
        if self._index is not None and self._index_drop:
            return set(self._index_cols())
        return set()

    def _visible_table(self) -> Table:
        hid = self._hidden()
        return self._table.drop(hid) if hid else self._table

    # -- schema / introspection -------------------------------------------
    @property
    def columns(self) -> list[str]:
        hid = self._hidden()
        return [c for c in self._table.column_names if c not in hid]

    @property
    def shape(self) -> tuple[int, int]:
        return (self._table.row_count, len(self.columns))

    @property
    def dtypes(self) -> dict[str, str]:
        hid = self._hidden()
        return {f.name: f.type.value for f in self._table.schema
                if f.name not in hid}

    def __len__(self) -> int:
        return self._table.row_count

    def __contains__(self, name: str) -> bool:
        return name in self._table and name not in self._hidden()

    def __repr__(self) -> str:  # pragma: no cover
        n = len(self)
        show = self.to_pandas() if n <= 20 else head(self._table, 10).to_pandas()
        s = repr(show)
        if n > 20:
            s += f"\n... ({n} rows x {self._table.column_count} cols, " \
                 f"world={self.env.world_size})"
        return s

    # -- index (reference indexing subsystem, indexing/index.hpp) ----------
    @property
    def loc(self):
        from .indexing.indexer import LocIndexer
        return LocIndexer(self)

    @property
    def iloc(self):
        from .indexing.indexer import ILocIndexer
        return ILocIndexer(self)

    @property
    def index(self):
        if self._index is None:
            return np.arange(len(self))
        idx = self._index_cols()
        if len(idx) == 1:
            return self._col_series(idx[0]).to_numpy()
        import pandas as pd
        return pd.MultiIndex.from_arrays(
            [self._col_series(c).to_numpy() for c in idx], names=idx)

    def set_index(self, name, drop: bool = True) -> "DataFrame":
        """Use column ``name`` (or a LIST of columns — multi-index,
        reference index.hpp:36 / indexer.hpp:76) as the row-label index
        (reference Table::SetArrowIndex, table.hpp:164).  ``drop`` follows
        pandas: drop=True (default) removes the column(s) from the visible
        columns — they live on as the index (physically retained for loc)
        — while drop=False keeps them addressable as data columns too."""
        names = [name] if isinstance(name, str) else list(name)
        if not names:
            raise CylonKeyError("set_index needs at least one column")
        for n in names:
            if n not in self._table:
                raise CylonKeyError(f"no column {n!r}")
        out = DataFrame(_table=self._table)
        out._index = names[0] if len(names) == 1 else tuple(names)
        out._index_drop = bool(drop)
        return out

    def reset_index(self) -> "DataFrame":
        """Demote the index back to a regular column (pandas semantics —
        the physical column was retained, so this is metadata-only)."""
        out = DataFrame(_table=self._table)
        return out

    # -- materialization ---------------------------------------------------
    def to_pandas(self):
        df = self._table.to_pandas()
        idx = self._index_cols()
        if idx:
            df = df.set_index(idx if len(idx) > 1 else idx[0],
                              drop=self._index_drop)
            if not self._index_drop and len(idx) == 1:
                # pandas keeps the column AND names the index after it
                df.index.name = idx[0]
        return df

    def to_arrow(self):
        return self._table.to_arrow()

    def to_numpy(self) -> np.ndarray:
        return self.to_pandas().to_numpy()

    def to_dict(self) -> dict:
        return {k: v.tolist()
                for k, v in self.to_pandas().to_dict("list").items()}

    # -- column access / mutation -----------------------------------------
    def _col_series(self, name: str) -> "Series":
        """Internal column access that ignores index-hiding (used by the
        loc/iloc machinery, which must read the index column)."""
        return Series(name, self._table.column(name), self.env,
                      self._table.valid_counts)

    def __getitem__(self, key):
        if isinstance(key, str):
            if key in self._hidden():
                raise CylonKeyError(
                    f"{key!r} is the index (set_index drop=True)")
            col = self._table.column(key)
            return Series(key, col, self.env, self._table.valid_counts)
        if isinstance(key, (list, tuple)) and all(isinstance(k, str)
                                                  for k in key):
            return self._wrap(self._table.project(key))
        if isinstance(key, Series):
            if key.dtype.value != "bool":
                raise InvalidError("filter mask must be a bool series")
            from .relational.common import valid_flag
            return self._wrap(filter_table(self._table,
                                           valid_flag(key.column)),
                              keep_index=True)
        if isinstance(key, slice):
            start, stop, step = key.indices(len(self))
            if step != 1:
                raise InvalidError("slice step not supported")
            return self._wrap(slice_table(self._table, start, stop - start),
                              keep_index=True)
        raise CylonKeyError(f"cannot index DataFrame with {key!r}")

    def __setitem__(self, name: str, value):
        if not isinstance(name, str):
            raise CylonKeyError("column name must be a string")
        if isinstance(value, Series):
            # same capacity is not enough: a column from a differently-
            # partitioned frame would silently misalign rows across shards
            if (value.column.data.shape[0] != self._table.capacity *
                    self.env.world_size
                    or not np.array_equal(value.valid_counts,
                                          self._table.valid_counts)):
                raise InvalidError("series layout mismatch")
            col = value.column
        elif np.isscalar(value) or isinstance(value, (int, float, bool, str)):
            n = len(self)
            col = self._ingest_column(np.full(n, value))
        else:
            arr = np.asarray(value)
            if arr.shape[0] != len(self):
                raise InvalidError(
                    f"column length {arr.shape[0]} != rows {len(self)}")
            col = self._ingest_column(arr)
        self._table = self._table.with_columns({name: col})

    def _ingest_column(self, arr: np.ndarray) -> Column:
        """Host array -> column matching this table's shard layout."""
        tmp = Table.from_pydict({"__c": arr}, self.env)
        tmp = repartition(tmp, tuple(int(x) for x in self._table.valid_counts))
        from .relational.repart import repad_table
        tmp = repad_table(tmp, self._table.capacity)
        return tmp.column("__c")

    def drop(self, columns: Iterable[str]) -> "DataFrame":
        if isinstance(columns, str):
            columns = [columns]
        return self._wrap(self._table.drop(columns))

    def rename(self, columns: Mapping[str, str]) -> "DataFrame":
        return self._wrap(self._table.rename(columns))

    # -- relational operators (the reference's Table API surface) ----------
    def merge(self, right: "DataFrame", how: str = "inner", on=None,
              left_on=None, right_on=None, suffixes=("_x", "_y"),
              env: CylonEnv | None = None, algorithm: str = "sort") -> "DataFrame":
        """pandas.merge parity (reference frame.py:1852 + dispatch :2063).

        ``algorithm``: the reference offers SORT|HASH (join_config.hpp:25);
        on TPU every join runs the single-sort merge — a hash build/probe
        needs ≥1 random gather per probe row (~15.5 ns/row measured on
        v5e) while a sort operand costs ~3.5 ns/row, so the sort path
        dominates at every build-side size (docs/DESIGN.md).  Passing
        ``algorithm="hash"`` warns and uses sort."""
        _check_join_algorithm(algorithm)
        env = _resolve_env(self.env, env)
        lhs, rhs = self._to_env(env), right._to_env(env)
        if on is not None:
            left_on = right_on = on
        if left_on is None or right_on is None:
            common = [c for c in lhs.columns if c in set(rhs.columns)]
            if not common:
                raise InvalidError("no common columns to merge on")
            left_on = right_on = common
        t = join_tables(lhs._visible_table(), rhs._visible_table(),
                        left_on, right_on, how=how,
                        suffixes=suffixes, coalesce_keys=True)
        return self._wrap(t)

    def join(self, other: "DataFrame", how: str = "left", on=None,
             lsuffix: str = "l", rsuffix: str = "r",
             env: CylonEnv | None = None, algorithm: str = "sort") -> "DataFrame":
        """Key-based join with suffixed columns (reference frame.py:1723
        joins add suffixes to every overlapping column, keys kept apart).
        ``algorithm`` as in :meth:`merge`."""
        _check_join_algorithm(algorithm)
        env = _resolve_env(self.env, env)
        lhs, oth = self._to_env(env), other._to_env(env)
        if on is None:
            raise InvalidError("join requires on= key column(s)")
        on = [on] if isinstance(on, str) else list(on)
        t = join_tables(lhs._visible_table(), oth._visible_table(), on, on,
                        how=how, suffixes=(lsuffix, rsuffix),
                        coalesce_keys=False)
        return self._wrap(t)

    def sort_values(self, by, ascending=True, nulls_position: str = "last",
                    env: CylonEnv | None = None,
                    method: str = "initial") -> "DataFrame":
        """``method``: "initial" (sample-first) or "regular" (local-sort
        first, quantile-exact splitters) — the reference's two distributed
        sort strategies (SortOptions, table.cpp:761)."""
        env = _resolve_env(self.env, env)
        return self._wrap(sort_table(self._to_env(env)._table, by,
                                     ascending=ascending,
                                     nulls_position=nulls_position,
                                     method=method),
                          keep_index=True)

    def groupby(self, by, env: CylonEnv | None = None) -> "GroupByDataFrame":
        env = _resolve_env(self.env, env)
        by = [by] if isinstance(by, str) else list(by)
        return GroupByDataFrame(self._to_env(env), by)

    def drop_duplicates(self, subset=None, keep: str = "first",
                        env: CylonEnv | None = None) -> "DataFrame":
        env = _resolve_env(self.env, env)
        d = self._to_env(env)
        if subset is None:
            subset = d.columns  # visible columns only, pandas semantics
        return d._wrap(unique_table(d._table, subset, keep), keep_index=True)

    def union(self, other: "DataFrame", env: CylonEnv | None = None) -> "DataFrame":
        env = _resolve_env(self.env, env)
        return self._wrap(set_operation(self._to_env(env)._table,
                                        other._to_env(env)._table, "union"))

    def intersect(self, other: "DataFrame", env: CylonEnv | None = None) -> "DataFrame":
        env = _resolve_env(self.env, env)
        return self._wrap(set_operation(self._to_env(env)._table,
                                        other._to_env(env)._table, "intersect"))

    def subtract(self, other: "DataFrame", env: CylonEnv | None = None) -> "DataFrame":
        env = _resolve_env(self.env, env)
        return self._wrap(set_operation(self._to_env(env)._table,
                                        other._to_env(env)._table, "subtract"))

    def shuffle(self, on, env: CylonEnv | None = None) -> "DataFrame":
        env = _resolve_env(self.env, env)
        on = [on] if isinstance(on, str) else list(on)
        return self._wrap(shuffle_table(self._to_env(env)._table, on))

    def repartition(self, rows_per_partition=None,
                    env: CylonEnv | None = None) -> "DataFrame":
        env = _resolve_env(self.env, env)
        return self._wrap(repartition(self._to_env(env)._table,
                                      rows_per_partition))

    def head(self, n: int = 5) -> "DataFrame":
        return self._wrap(head(self._table, n), keep_index=True)

    def tail(self, n: int = 5) -> "DataFrame":
        return self._wrap(tail(self._table, n), keep_index=True)

    def to_csv(self, path, **kw) -> None:
        from .io import write_csv
        write_csv(self._table, path, **kw)

    def to_parquet(self, path, **kw) -> None:
        from .io import write_parquet
        write_parquet(self._table, path, **kw)

    def to_json(self, path, **kw) -> None:
        from .io import write_json
        write_json(self._table, path, **kw)

    def equals(self, other: "DataFrame", ordered: bool = True) -> bool:
        return equals(self._table, other._to_env(self.env)._table,
                      ordered=ordered)

    def isin(self, other: "DataFrame") -> bool:
        """Row-subset test: every row of self appears in other."""
        diff = set_operation(self._table, other._to_env(self.env)._table,
                             "subtract")
        return diff.row_count == 0

    # -- missing data (reference frame.py:187-2421 breadth; pandas parity) --
    def _rebuild_cols(self, newcols: dict) -> "DataFrame":
        """New table from per-column results, re-attaching a hidden index
        column so the label index survives (pandas keeps the index through
        elementwise ops)."""
        for h in self._hidden():
            newcols[h] = self._table.column(h)
        return self._wrap(Table(newcols, self._table.env,
                                self._table.valid_counts), keep_index=True)

    def isna(self) -> "DataFrame":
        """Boolean frame: True where a value is missing (null or NaN)."""
        return self._rebuild_cols(
            {c: self[c].isna().column for c in self.columns})

    def notna(self) -> "DataFrame":
        return self._rebuild_cols(
            {c: self[c].notna().column for c in self.columns})

    # pandas/pycylon aliases (reference data/table.pyx isnull/notnull)
    isnull = isna
    notnull = notna

    def add_prefix(self, prefix: str) -> "DataFrame":
        """Rename every visible column to ``prefix + name`` (reference
        data/table.pyx add_prefix)."""
        return self.rename({c: prefix + c for c in self.columns})

    def add_suffix(self, suffix: str) -> "DataFrame":
        return self.rename({c: c + suffix for c in self.columns})

    def where(self, cond: "DataFrame | Series", other=None) -> "DataFrame":
        """Keep values where ``cond`` holds; elsewhere ``other`` (null when
        ``other`` is None) over a bool frame or a single bool Series.

        Divergence from pandas (intentional, pycylon-style): a Series
        ``cond`` is applied ROW-WISE to every column (what pandas spells
        ``where(cond, axis=0)``); pandas' default would align the Series
        on column labels, which is never useful for a row-predicate."""
        from .relational.common import valid_flag
        cols = {}
        for name in self.columns:
            col = self._table.column(name)
            c_ser = cond[name] if isinstance(cond, DataFrame) else cond
            flag = valid_flag(c_ser.column)
            if other is None:
                v = flag if col.validity is None else (col.validity & flag)
                cols[name] = Column(col.data, col.type, v, col.dictionary)
            else:
                s = Series(name, col, self.env, self._table.valid_counts)
                filled = s._fill_where(~flag, other)
                cols[name] = filled.column
        return self._rebuild_cols(cols)

    def to_pydict(self) -> dict:
        """Materialize as {column: list} (reference data/table.pyx
        to_pydict)."""
        return {c: list(self[c].to_numpy()) for c in self.columns}

    def to_string(self) -> str:
        return self.to_pandas().to_string()

    def show(self, n: int = 10) -> None:
        """Print the first n rows (reference data/table.pyx show /
        Table::PrintToOStream, table.hpp:96)."""
        print(self.head(n).to_pandas().to_string())

    def dropna(self, how: str = "any", subset=None) -> "DataFrame":
        """Drop rows with missing values (any/all over ``subset``)."""
        from .status import InvalidError as _IE
        if how not in ("any", "all"):
            raise _IE("how must be 'any' or 'all'")
        cols = list(subset) if subset is not None else self.columns
        keep = None
        for c in cols:
            ok = self[c].notna()
            keep = ok if keep is None else (
                (keep & ok) if how == "any" else (keep | ok))
        if keep is None:
            return self
        from .relational.common import valid_flag
        return self._wrap(filter_table(self._table, valid_flag(keep.column)),
                          keep_index=True)

    def fillna(self, value) -> "DataFrame":
        """Replace missing values (nulls and float NaNs) with ``value``.
        Columns whose dtype cannot hold ``value`` (e.g. a string column vs a
        numeric fill) are left unchanged — a documented deviation from
        pandas' object-dtype mixing, which fixed-width device columns cannot
        represent."""
        from .status import CylonTypeError
        cols = {}
        for name, c in self._table.columns.items():
            if name in self._hidden() or (
                    c.validity is None
                    and not str(c.data.dtype).startswith("float")):
                cols[name] = c
                continue
            s = Series(name, c, self.env, self._table.valid_counts)
            try:
                cols[name] = s.fillna(value).column
            except CylonTypeError:
                cols[name] = c
        return self._wrap(Table(cols, self._table.env,
                                self._table.valid_counts), keep_index=True)

    # -- elementwise frame arithmetic (pandas operator parity) -------------
    def _colwise(self, fn) -> "DataFrame":
        return self._rebuild_cols({c: fn(self[c]).column
                                   for c in self.columns})

    def _frame_op(self, other, op_name: str) -> "DataFrame":
        if isinstance(other, DataFrame):
            if other.columns != self.columns:
                raise InvalidError("frame op requires identical columns")
            return self._colwise(
                lambda s: getattr(s, op_name)(other[s.name]))
        return self._colwise(lambda s: getattr(s, op_name)(other))

    def __add__(self, o):
        return self._frame_op(o, "__add__")

    def __sub__(self, o):
        return self._frame_op(o, "__sub__")

    def __mul__(self, o):
        return self._frame_op(o, "__mul__")

    def __truediv__(self, o):
        return self._frame_op(o, "__truediv__")

    def __neg__(self):
        return self._colwise(lambda s: -s)

    def __abs__(self):
        return self._colwise(abs)

    def abs(self) -> "DataFrame":
        return self._colwise(abs)

    # -- row-wise host iteration (reference Row, row.hpp; frame.py parity) --
    def applymap(self, func) -> "DataFrame":
        """Elementwise python function over the data columns — host round
        trip by necessity (arbitrary python is not jittable); index labels
        are untouched, pandas-compatible."""
        pdf = self.to_pandas()
        mapped = pdf.map(func)
        if self._index is None:
            return DataFrame(mapped, env=self.env)
        idx = self._index_cols()
        out = DataFrame(mapped.reset_index(names=idx), env=self.env)
        return out.set_index(idx, drop=self._index_drop)

    def iterrows(self):
        """Host-side row iteration, pandas-compatible (reference Row
        iteration, row.hpp via table.cpp:892 Select)."""
        return self.to_pandas().iterrows()

    def itertuples(self, index: bool = True, name: str = "Cylon"):
        return self.to_pandas().itertuples(index=index, name=name)

    def row(self, i: int):
        """One global row as a :class:`~cylon_tpu.core.row.Row`."""
        from .core.row import Row
        return Row(self, i)

    # -- reductions over all columns ---------------------------------------
    def _agg_all(self, op: str):
        from .status import CylonTypeError
        import pandas as pd
        out = {}
        for name in self.columns:
            s = self[name]
            try:
                out[name] = getattr(s, op)()
            except CylonTypeError:
                continue  # column type doesn't support this reduction
        return pd.Series(out)

    def sum(self):
        return self._agg_all("sum")

    def min(self):
        return self._agg_all("min")

    def max(self):
        return self._agg_all("max")

    def count(self):
        return self._agg_all("count")

    def mean(self):
        return self._agg_all("mean")


class GroupByDataFrame:
    """Deferred groupby (reference frame.py:122 GroupByDataFrame): terminal
    aggregation methods run the distributed two-phase engine."""

    def __init__(self, df: DataFrame, by: list[str]):
        self._df = df
        self._by = by
        self._value_cols = [c for c in df.columns if c not in set(by)]

    def __getitem__(self, cols) -> "GroupByDataFrame":
        cols = [cols] if isinstance(cols, str) else list(cols)
        for c in cols:
            if c not in self._df.columns:
                raise CylonKeyError(f"no column {c!r}")
        g = GroupByDataFrame(self._df, self._by)
        g._value_cols = cols
        return g

    def _run(self, aggs) -> DataFrame:
        t = groupby_aggregate(self._df._table, self._by, aggs)
        return DataFrame(_table=t)

    def _all(self, op: str) -> DataFrame:
        from .core.dtypes import LogicalType
        # types via schema, NOT column(): column access would materialize a
        # DeferredTable join result and forfeit the fused groupby pushdown
        types = {f.name: f.type for f in self._df._table.schema}
        aggs = []
        for c in self._value_cols:
            if types[c] == LogicalType.STRING and op not in (
                    "count", "nunique", "min", "max"):
                continue
            aggs.append((c, op))
        if not aggs:
            raise InvalidError(f"no columns support {op!r}")
        out = self._run(aggs)
        # pandas-style: result columns keep the value column name
        ren = {f"{c}_{op}": c for c, _ in aggs}
        return DataFrame(_table=out._table.rename(ren))

    def sum(self) -> DataFrame:
        return self._all("sum")

    def count(self) -> DataFrame:
        return self._all("count")

    def min(self) -> DataFrame:
        return self._all("min")

    def max(self) -> DataFrame:
        return self._all("max")

    def mean(self) -> DataFrame:
        return self._all("mean")

    def var(self) -> DataFrame:
        return self._all("var")

    def std(self) -> DataFrame:
        return self._all("std")

    def nunique(self) -> DataFrame:
        return self._all("nunique")

    def median(self) -> DataFrame:
        return self._all("median")

    def quantile(self, q: float = 0.5) -> DataFrame:
        aggs = [(c, "quantile", q) for c in self._value_cols]
        out = self._run(aggs)
        ren = {f"{c}_quantile_{q:g}": c for c in self._value_cols}
        ren.update({f"{c}_quantile": c for c in self._value_cols})
        ren = {k: v for k, v in ren.items() if k in out.columns}
        return DataFrame(_table=out._table.rename(ren))

    def agg(self, spec) -> DataFrame:
        """pandas .agg spellings: a single op name ('sum'), a list of op
        names applied to every value column, {'col': 'sum'|['sum','mean']},
        or an explicit [(col, op), ...] list (ops may repeat across
        columns)."""
        if isinstance(spec, str):
            return self._all(spec)
        aggs = []
        if isinstance(spec, Mapping):
            for col, ops in spec.items():
                ops = [ops] if isinstance(ops, str) else list(ops)
                for op in ops:
                    aggs.append((col, op))
        elif spec and all(isinstance(a, str) for a in spec):
            aggs = [(c, op) for c in self._value_cols for op in spec]
        else:
            aggs = [tuple(a) for a in spec]
        if not aggs:
            raise InvalidError("no aggregations specified")
        return self._run(aggs)


def concat(objs: Sequence[DataFrame], env: CylonEnv | None = None) -> "DataFrame":
    """Row-wise concat (reference frame.py:2295)."""
    if not objs:
        raise InvalidError("concat of nothing")
    env = _resolve_env(objs[0].env, env)
    tables = [o._to_env(env)._table for o in objs]
    return DataFrame(_table=concat_tables(tables))


def read_pandas(df, env: CylonEnv | None = None) -> DataFrame:
    return DataFrame(df, env=env)
