"""Pass 3 — the retrace / transfer sentinel (runtime hooks).

Retraces and host round-trips are invisible on CPU test rigs: a builder
whose cache key omits a shape-dependent static argument silently
recompiles per call (seconds-per-compile on a remote TPU), and a stray
``np.asarray`` inside an op turns a device-resident pipeline into a
host ping-pong.  This module makes both observable and budget-checkable:

* **compile attribution** — every program built through
  :func:`cylon_tpu.utils.cache.program_cache` is tagged
  (:func:`tag_program`) so that XLA compile events (``jax.monitoring``,
  ``/jax/core/compile/backend_compile_duration``) occurring during its
  calls are recorded against ``(builder, shape_signature)``;
* **retrace detection** — a second compile for the SAME (builder,
  signature) means the jit cache failed to hold (unstable key, donated
  buffer mismatch, weak-type flapping): rule RT301.  More distinct
  compiled programs for one builder than its declared budget
  (:mod:`cylon_tpu.analysis.registry`) is a shape-family explosion:
  rule RT302;
* **transfer ledger** — :func:`transfer_scope` counts sanctioned host
  pulls (the :mod:`cylon_tpu.utils.host` funnel calls
  :func:`note_transfer`) so tests can assert an op's device↔host budget:
  rule RT303.

Everything is off (near-zero overhead: one truthiness check per builder
call) until :func:`enable` — ``tests/conftest.py`` enables it under
``CYLON_TPU_TRACECHECK=1``.
"""

from __future__ import annotations

import contextlib
import threading
from collections import Counter
from dataclasses import dataclass, field

_lock = threading.Lock()

#: sentinel state — module-level singleton, None while disabled
_state = None

_local = threading.local()


@dataclass
class SentinelState:
    #: (builder, signature) -> number of program CALLS that triggered an
    #: XLA backend compile (a second one for the same signature = retrace)
    compiles: Counter = field(default_factory=Counter)
    #: builder -> number of distinct cache keys built (program_cache misses)
    builds: Counter = field(default_factory=Counter)
    #: builder -> number of cache hits (for cache-health reporting)
    hits: Counter = field(default_factory=Counter)
    #: compiles not attributable to any tagged builder
    untagged_compiles: int = 0
    listener_installed: bool = False


def enabled() -> bool:
    return _state is not None


def enable() -> "SentinelState":
    """Install the sentinel (idempotent).  Returns the live state."""
    global _state
    with _lock:
        if _state is None:
            _state = SentinelState()
        if not _state.listener_installed:
            import jax
            jax.monitoring.register_event_duration_secs_listener(_on_event)
            _state.listener_installed = True
    return _state


def reset() -> None:
    """Zero the counters (keeps the listener installed)."""
    if _state is not None:
        _state.compiles.clear()
        _state.builds.clear()
        _state.hits.clear()
        _state.untagged_compiles = 0


def state() -> "SentinelState | None":
    return _state


def _on_event(event: str, duration: float, **kwargs) -> None:
    # one logical program call can emit several backend_compile events
    # (main program + auxiliary reshard/convert programs); the sentinel
    # counts COMPILING CALLS, so the listener just raises a flag the call
    # wrapper collapses to one count per call
    st = _state
    if st is None or not event.startswith("/jax/core/compile/backend_compile"):
        return
    if getattr(_local, "builder", None) is None:
        with _lock:
            st.untagged_compiles += 1
    else:
        _local.call_compiled = True


def _signature(args, kwargs) -> tuple:
    """Cheap shape signature of a program call: (shape, dtype) leaves.
    Only computed while the sentinel is enabled."""
    sig = []

    def leaf(x):
        shp = getattr(x, "shape", None)
        if shp is not None:
            sig.append((tuple(shp), str(getattr(x, "dtype", ""))))
        elif isinstance(x, (tuple, list)):
            for e in x:
                leaf(e)

    for a in args:
        leaf(a)
    for a in kwargs.values():
        leaf(a)
    return tuple(sig)


def note_builder(name: str, key, miss: bool) -> None:
    """Called by program_cache on every lookup."""
    st = _state
    if st is None:
        return
    with _lock:
        (st.builds if miss else st.hits)[name] += 1


def note_transfer(kind: str, n: int = 1) -> None:
    """Called by the utils.host funnel on every sanctioned host pull."""
    ledger = getattr(_local, "ledger", None)
    if ledger is not None:
        ledger[kind] += n


def tag_program(name: str, program, key=()):
    """Wrap a built program so calls attribute compile events to ``name``.

    ``key`` is the builder's static cache key: two programs from one
    builder with different static args legitimately compile once EACH,
    so the retrace identity is (builder, static key, call-shape
    signature) — without the key, zero-arg programs (and same-shaped
    calls of sibling programs) would collapse and false-trip RT301.
    Transparent when the sentinel is disabled except for one attribute
    check; ``__wrapped__`` exposes the raw program for tracing.
    """

    def tagged(*args, **kwargs):
        st = _state
        if st is None:
            return program(*args, **kwargs)
        prev = getattr(_local, "builder", None)
        prev_flag = getattr(_local, "call_compiled", False)
        _local.builder = (name, key, _signature(args, kwargs))
        _local.call_compiled = False
        try:
            return program(*args, **kwargs)
        finally:
            if getattr(_local, "call_compiled", False):
                with _lock:
                    st.compiles[_local.builder] += 1
            _local.builder = prev
            _local.call_compiled = prev_flag

    tagged.__wrapped__ = program
    tagged.__name__ = f"tagged[{name}]"
    return tagged


@contextlib.contextmanager
def transfer_scope():
    """Count sanctioned host pulls made inside the scope.

    Yields a ``Counter``; the utils.host funnel increments it.  Nested
    scopes shadow outer ones (innermost wins — per-op budgets).
    """
    prev = getattr(_local, "ledger", None)
    ledger = Counter()
    _local.ledger = ledger
    try:
        yield ledger
    finally:
        _local.ledger = prev


def check_budgets(budgets: dict | None = None) -> list:
    """Evaluate sentinel counters against declared budgets.

    Returns a list of ``(rule, builder, message)`` violations:

    * RT301 — some (builder, signature) compiled more than once;
    * RT302 — a builder built more distinct programs than its budget
      (default from the registry; 64 when undeclared).
    """
    st = _state
    out = []
    if st is None:
        return out
    from . import registry
    decls = {d.builder: d for d in registry.all_declarations()}
    for tag, n in st.compiles.items():
        name, sig = tag[0], tag[1:]
        if n > 1:
            out.append(("RT301", name,
                        f"{name} compiled {n}x for one (static key, shape "
                        f"signature) {sig!r} — jit cache is not holding"))
    if budgets is None:
        budgets = {}
    for name, n in st.builds.items():
        decl = decls.get(name)
        budget = budgets.get(name,
                             decl.retrace_budget if decl is not None else 64)
        if n > budget:
            out.append(("RT302", name,
                        f"{name} built {n} distinct programs this session "
                        f"(budget {budget}) — shape-family explosion"))
    return out
