"""Per-op builder declarations for the jaxpr pass + sentinel budgets.

Each program-builder module (``relational/*.py``, ``parallel/*.py``)
declares its builders here: a :class:`BuilderDecl` names the builder,
states the SPMD invariants the jaxpr pass must verify (which collectives
the traced program is allowed/required to contain, whether int32→int64
widening is intentional, the host-callback budget) and the sentinel's
retrace budget.  ``trace(mesh)`` returns a ClosedJaxpr of the builder's
program over small abstract inputs — tracing only, nothing compiles.

Declarations are registered at module import; :func:`collect` imports
every builder module so a checker (CLI or the slow pytest) sees the full
set.  This module must stay import-light (no jax, no cylon_tpu.relational
imports at module scope) — builder modules import it at their bottom.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

#: modules whose import populates the registry (every program-builder
#: module that declares invariants)
BUILDER_MODULES = (
    "cylon_tpu.parallel.collectives",
    "cylon_tpu.parallel.shuffle",
    "cylon_tpu.topo.exchange",
    "cylon_tpu.relational.join",
    "cylon_tpu.relational.piece",
    "cylon_tpu.relational.sort",
    "cylon_tpu.relational.groupby",
    "cylon_tpu.relational.setops",
    "cylon_tpu.relational.repart",
    "cylon_tpu.exec.pipeline",
    "cylon_tpu.exec.recovery",
    "cylon_tpu.exec.integrity",
    "cylon_tpu.stream.window",
)

#: default bound on distinct compiled programs per builder per session
#: (RT302); pow2-bucketed capacities keep real families far below this
DEFAULT_RETRACE_BUDGET = 32

#: arrays at or above this many elements count as "row-scale" for the
#: JX203 widening check (sidecars — valid-count vectors, count matrices —
#: stay below it at the trace shapes the declarations use)
ROW_SCALE_ELEMS = 256


@dataclass(frozen=True)
class BuilderDecl:
    #: fully qualified builder name (module.func)
    builder: str
    #: trace(mesh) -> jax.core.ClosedJaxpr over abstract inputs
    trace: Callable
    #: collective primitives the program MUST contain (all of them,
    #: unconditionally) and may not exceed; frozenset() = pure-local
    #: program, any collective is a finding
    collectives: frozenset = frozenset()
    #: ops the op family tags itself with ("join", "sort", ...)
    tags: tuple = ()
    #: int32→int64 widening of row-scale arrays is intentional here
    allow_widen: bool = False
    #: host callbacks (pure/io/debug_callback) allowed in the program
    callback_budget: int = 0
    #: RT302: max distinct compiled programs per session
    retrace_budget: int = DEFAULT_RETRACE_BUDGET


def unwrap(fn):
    """Strip the retrace-sentinel tag wrapper off a built program so
    declarations trace the raw jit function (no sentinel noise)."""
    return getattr(fn, "__wrapped__", fn)


def decl_shapes(mesh, cap: int = 1024):
    """Shared trace-shape helper for declarations: ``(w, cap, S)`` with
    ``cap`` per-shard rows — large enough that row-scale arrays clear
    ROW_SCALE_ELEMS while (W,)/(W,W) sidecars stay below it."""
    import jax
    return int(mesh.devices.size), cap, jax.ShapeDtypeStruct


_DECLS: dict[str, BuilderDecl] = {}


def declare_builder(builder: str, trace: Callable, *,
                    collectives=frozenset(), tags=(), allow_widen=False,
                    callback_budget=0,
                    retrace_budget=DEFAULT_RETRACE_BUDGET) -> None:
    _DECLS[builder] = BuilderDecl(
        builder=builder, trace=trace, collectives=frozenset(collectives),
        tags=tuple(tags), allow_widen=allow_widen,
        callback_budget=callback_budget, retrace_budget=retrace_budget)


def all_declarations() -> list[BuilderDecl]:
    return list(_DECLS.values())


def get(builder: str) -> BuilderDecl | None:
    return _DECLS.get(builder)


def collect() -> list[BuilderDecl]:
    """Import every builder module (populating the registry) and return
    the declarations."""
    import importlib
    for mod in BUILDER_MODULES:
        importlib.import_module(mod)
    return all_declarations()
