"""cylon_tpu.analysis: trace-safety / SPMD-hazard static analyzer.

The correctness-tooling layer for the shard_map/XLA-collective operator
stack — the role sanitizers and MPI race detectors play in the C++
reference.  Four cooperating passes:

1. **AST lint** (:mod:`.ast_lint`, rules TS1xx) — source-level hazards
   over the whole package: host syncs and tracer control flow inside
   traced bodies, jit wrappers missing static_argnums, Mesh-pinning
   lru_cache builders;
2. **collective coherence** (:mod:`.coherence`, rules CX4xx) —
   interprocedural call-graph + taint/dominance pass: rank-local
   control flow between collectives, path-dependent collective
   sequences, plan-vote dominance (skew/topo/ckpt/drain), untyped
   post-collective raises;
3. **jaxpr verification** (:mod:`.jaxpr_check`, rules JX2xx) — each
   registered program builder (:mod:`.registry`) is traced abstractly
   and its jaxpr checked for SPMD invariants: collectives appear
   unconditionally (never under cond / data-dependent while), the
   collective set matches the declaration, no row-scale int32→int64
   widening, host callbacks within budget;
4. **runtime sentinel** (:mod:`.runtime`, rules RT3xx) — compile and
   host-transfer counters wired into test sessions
   (``CYLON_TPU_TRACECHECK=1``) that fail on budget overruns.

CLI: ``python scripts/check_trace_safety.py [--strict] [paths...]``.
Docs: ``docs/trace_safety.md`` (rule catalog + suppression syntax).
"""

from .rules import RULES, Finding  # noqa: F401
from .ast_lint import lint_file, lint_paths, lint_source  # noqa: F401
from .coherence import analyze_files, analyze_paths, analyze_source  # noqa: F401
from .registry import BuilderDecl, all_declarations, declare_builder  # noqa: F401
from . import runtime  # noqa: F401
