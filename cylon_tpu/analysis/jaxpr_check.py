"""Pass 2 — jaxpr-level SPMD invariant verification.

For each registered program builder (:mod:`cylon_tpu.analysis.registry`)
the checker traces the builder's program over small abstract inputs
(``jax.make_jaxpr`` — no compilation) and walks the jaxpr, recursing
through every sub-jaxpr (``pjit``, ``shard_map``, ``cond`` branches,
``while`` cond/body, ``scan``), to assert:

* **collective unconditionality** (JX201/JX202): a collective primitive
  (``all_gather``/``all_to_all``/``psum``/``ppermute``/…) under a
  ``cond``/``switch`` branch or a data-dependent ``while`` body executes
  on a rank-dependent subset of the mesh — the classic mismatched-
  participation deadlock, invisible on CPU.  ``scan`` (static trip count,
  identical on every rank — e.g. the multi-round exchange's
  ``fori_loop``) is explicitly allowed;
* **declared collective set** (JX205): the program contains exactly the
  collectives its declaration names — a builder that silently grew an
  ``all_gather`` (or lost its ``all_to_all``) changed its communication
  contract;
* **no unintended i32→i64 widening** (JX203): under x64 a stray Python
  int or default reduction accumulator (``jnp.sum(bool_mat)``,
  ``cumsum``) promotes a row-scale int32 array to int64 — 2x the bytes
  through every gather and collective.  The rule sees
  ``convert_element_type`` only: an int64 array *born* wide (a
  default-dtype ``iota``) has no convert and must be caught by pinning
  iota dtypes at the source (see the masks in collectives/repart);
* **host-callback budget** (JX204): ``pure_callback``/``io_callback``/
  ``debug_callback`` primitives are device→host round-trips inside the
  program; each builder budgets them (default zero).
"""

from __future__ import annotations

from .registry import ROW_SCALE_ELEMS, BuilderDecl
from .rules import Finding

#: cross-device communication primitives (normalized names).  NOT listed:
#: ``pbroadcast`` — shard_map's check_rep machinery inserts it to coerce
#: replication types; it moves no data and lowers to nothing device-side.
COLLECTIVE_PRIMS = {
    "all_gather", "all_to_all", "psum", "pmin", "pmax", "ppermute",
    "reduce_scatter",
}

#: primitives that are host round-trips
CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback"}

#: control primitives recorded in the walk context
_CONTROL = {"cond", "while", "scan", "pjit", "shard_map", "closed_call",
            "core_call", "custom_jvp_call", "custom_vjp_call", "remat",
            "checkpoint"}


def _norm(prim_name: str) -> str:
    """Normalize primitive spelling drift across jax versions
    (``psum2``/``psum_invariant`` → ``psum``, ``all_gather_invariant`` →
    ``all_gather``)."""
    name = prim_name
    if name.endswith("2"):
        name = name[:-1]
    if name.endswith("_invariant"):
        name = name[: -len("_invariant")]
    return name


def _sub_jaxprs(eqn):
    """Yield every (sub)jaxpr referenced by an eqn's params."""
    from jax.core import ClosedJaxpr, Jaxpr
    for val in eqn.params.values():
        if isinstance(val, (ClosedJaxpr, Jaxpr)):
            yield val
        elif isinstance(val, (tuple, list)):
            for item in val:
                if isinstance(item, (ClosedJaxpr, Jaxpr)):
                    yield item


def iter_eqns(jaxpr, ctx=()):
    """Depth-first walk yielding ``(eqn, ctx)`` where ``ctx`` is the tuple
    of enclosing control-primitive names (outermost first)."""
    from jax.core import ClosedJaxpr
    if isinstance(jaxpr, ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn, ctx
        name = eqn.primitive.name
        inner = ctx + ((name,) if name in _CONTROL else ("call",))
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, inner)


def check_jaxpr(closed_jaxpr, decl: BuilderDecl) -> list[Finding]:
    """Walk a traced builder program and return JX findings."""
    import numpy as np
    findings = []
    where = decl.builder
    found = set()
    n_callbacks = 0
    for eqn, ctx in iter_eqns(closed_jaxpr):
        name = _norm(eqn.primitive.name)
        if name in COLLECTIVE_PRIMS:
            found.add(name)
            if "cond" in ctx:
                findings.append(Finding(
                    "JX201", where, 0,
                    f"collective '{name}' under cond/switch "
                    f"(context {'/'.join(ctx)}) — rank-divergent branches "
                    "deadlock the mesh"))
            if "while" in ctx:
                findings.append(Finding(
                    "JX202", where, 0,
                    f"collective '{name}' under a data-dependent while "
                    f"(context {'/'.join(ctx)}) — trip counts can diverge "
                    "across ranks"))
        elif name in CALLBACK_PRIMS:
            n_callbacks += 1
        elif name == "convert_element_type" and not decl.allow_widen:
            new = eqn.params.get("new_dtype")
            aval = eqn.invars[0].aval
            src = getattr(aval, "dtype", None)
            if (src is not None and new is not None
                    and np.dtype(src) in (np.dtype(np.int32),
                                          np.dtype(np.uint32))
                    and np.dtype(new) in (np.dtype(np.int64),
                                          np.dtype(np.uint64))
                    and int(np.prod(aval.shape, dtype=np.int64))
                    >= ROW_SCALE_ELEMS):
                findings.append(Finding(
                    "JX203", where, 0,
                    f"row-scale {aval.shape} array widened "
                    f"{np.dtype(src).name}→{np.dtype(new).name} under x64 — "
                    "2x bytes through every downstream gather/collective"))
    if n_callbacks > decl.callback_budget:
        findings.append(Finding(
            "JX204", where, 0,
            f"{n_callbacks} host callback(s) in the program "
            f"(budget {decl.callback_budget})"))
    if found != decl.collectives:
        extra = sorted(found - decl.collectives)
        missing = sorted(decl.collectives - found)
        parts = []
        if extra:
            parts.append(f"undeclared collective(s) {extra}")
        if missing:
            parts.append(f"declared collective(s) {missing} absent")
        findings.append(Finding("JX205", where, 0, "; ".join(parts)))
    return findings


def verify_builder(decl: BuilderDecl, mesh) -> list[Finding]:
    """Trace one declared builder over ``mesh`` and check it."""
    try:
        traced = decl.trace(mesh)
    except Exception as e:  # noqa: BLE001 — a broken trace IS a finding
        return [Finding("JX205", decl.builder, 0,
                        f"builder trace failed: {type(e).__name__}: {e}")]
    return check_jaxpr(traced, decl)


def verify_all(mesh, decls=None) -> list[Finding]:
    from . import registry
    if decls is None:
        decls = registry.collect()
    findings = []
    for decl in decls:
        findings.extend(verify_builder(decl, mesh))
    return findings
